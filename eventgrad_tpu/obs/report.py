"""Run-report builder: history/JSONL in, one self-contained report out.

Consumes the per-epoch history records of `train(obs="block"|"epoch")` —
either the in-memory list or the JSONL stream `cli.py --log-file`
writes — and renders the derived series `obs.schema.REPORT_FIELDS`
documents: per-layer msgs-saved-% vs epoch, threshold/fire-rate heatmap
data, compact-wire capacity utilization (fired bytes vs C, deferral
rate), and the consensus-error trajectory. `tools/obs_report.py` is the
CLI wrapper; `artifacts/obs_report_cpu.json` is a committed example.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from eventgrad_tpu.obs.schema import OBS_SCHEMA_VERSION
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.utils.metrics import msgs_saved_pct_per_leaf


def load_history_jsonl(path: str) -> List[Dict[str, Any]]:
    """Epoch records (lines carrying "epoch") from a metrics JSONL stream;
    non-record lines (final summary, malformed tails from a crash) are
    skipped — a crash-truncated log still reports its completed epochs."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "epoch" in rec:
                out.append(rec)
    return out


def _obs_windows(history: List[Dict[str, Any]]):
    """(epoch, obs-dict) pairs in epoch order, plus the run meta carried
    by the first obs record."""
    windows, meta = [], {}
    for rec in history:
        obs = rec.get("obs")
        if not obs:
            continue
        if not meta and "meta" in obs:
            meta = obs["meta"]
        windows.append((rec["epoch"], obs))
    return windows, meta


def build_report(history: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One self-contained dict from a train() history (see module doc).
    Works on any history: sections whose inputs are absent (no obs
    telemetry, no compact wire, no consensus probe) come out None rather
    than failing, so the tool renders partial reports from legacy logs."""
    windows, meta = _obs_windows(history)
    n_ranks = int(meta.get("n_ranks", 1))
    n_nb = int(meta.get("n_neighbors", 1))
    wire = meta.get("wire")

    report: Dict[str, Any] = {
        "obs_schema": OBS_SCHEMA_VERSION,
        "algo": history[0].get("algo") if history else None,
        "epochs": [h["epoch"] for h in history],
        "meta": meta or None,
        "msgs_saved_pct": [h.get("msgs_saved_pct") for h in history],
        "sent_bytes_wire_real_per_step_per_chip": [
            h.get("sent_bytes_wire_real_per_step_per_chip")
            for h in history
        ],
        "loss": [h.get("loss") for h in history],
        "test_accuracy": [h.get("test_accuracy") for h in history],
    }

    # consensus-error trajectory (block-end probe; obs or chaos runs)
    cons = [
        (h["epoch"], h["consensus_err_max"], h["consensus_err_mean"])
        for h in history
        if "consensus_err_max" in h
    ]
    report["consensus_error"] = (
        {
            "epochs": [e for e, _, _ in cons],
            "max": [m for _, m, _ in cons],
            "mean": [m for _, _, m in cons],
        }
        if cons else None
    )

    if not windows:
        report.update(
            msgs_saved_pct_per_leaf=None, fire_rate_heatmap=None,
            thres_heatmap=None, silence_hist_total=None,
            capacity_utilization=None,
        )
        return report

    epochs_w = [e for e, _ in windows]
    per_leaf_saved, fire_rows, thres_rows, drift_rows = [], [], [], []
    hist_total: Optional[List[int]] = None
    for _, w in windows:
        steps = max(1, int(w["steps"]))
        fire = w.get("fire_count")
        if fire is not None:
            per_leaf_saved.append(msgs_saved_pct_per_leaf(
                fire, steps, n_nb, n_ranks
            ))
            fire_rows.append([f / (steps * n_ranks) for f in fire])
        thres_rows.append(w.get("thres_mean"))
        drift_rows.append(w.get("drift_mean"))
        sh = w.get("silence_hist")
        if sh is not None:
            hist_total = (
                [a + b for a, b in zip(hist_total, sh)]
                if hist_total else list(sh)
            )

    report["msgs_saved_pct_per_leaf"] = {
        "epochs": epochs_w,
        "leaves": meta.get("leaves"),
        "pct": per_leaf_saved,
    } if per_leaf_saved else None
    report["fire_rate_heatmap"] = {
        "epochs": epochs_w, "leaves": meta.get("leaves"),
        "rows": fire_rows,
    } if fire_rows else None
    report["thres_heatmap"] = {
        "epochs": epochs_w, "leaves": meta.get("leaves"),
        "rows": thres_rows, "drift_rows": drift_rows,
    } if any(r is not None for r in thres_rows) else None
    report["silence_hist_total"] = hist_total

    # compact-wire capacity utilization: fired bytes vs the static C.
    # Only COMPACT-ERA windows count — the dense warmup/autotune phase
    # fires everything through the unbudgeted wire (fired_elems up to
    # n_params > C), so folding it in would report a physically
    # impossible >100% utilization of a budget the gate never exceeded.
    caps = [h for h in history if h.get("compact_capacity")]
    if caps:
        cap = int(caps[-1]["compact_capacity"])
        compact_epochs = {h["epoch"] for h in caps}
        util_rows = []
        defer_total = fire_total = 0
        n_leaves = len(meta.get("leaves") or []) or 1
        for e, w in windows:
            if e not in compact_epochs:
                continue
            fe_mean = w.get("fired_elems_mean")
            if fe_mean is None:
                continue
            fired_leaves = (
                sum(w["fire_count"]) / (max(1, int(w["steps"])) * n_ranks)
                if w.get("fire_count") else n_leaves
            )
            util_rows.append({
                "epoch": e,
                "steps": int(w["steps"]),
                "utilization": fe_mean / cap,
                "fired_bytes_per_step_per_edge":
                    collectives.fired_wire_bytes_per_neighbor(
                        fe_mean, fired_leaves, wire
                    ),
            })
            defer_total += int(sum(w.get("defer_count") or [0]))
            fire_total += int(sum(w.get("fire_count") or [0]))
        proposed = defer_total + fire_total
        total_steps = sum(r["steps"] for r in util_rows)
        report["capacity_utilization"] = {
            "compact_capacity": cap,
            "capacity_bytes_per_edge":
                collectives.wire_real_bytes_per_neighbor(
                    cap, n_leaves, wire,
                    compact_capacity=cap, fire_bits=True,
                ),
            # steps-weighted mean over compact-era windows; per-pass
            # peaks are bounded by C by construction (capacity_gate), so
            # the mean + deferral rate carry the tuning signal
            "utilization_mean": (
                sum(r["utilization"] * r["steps"] for r in util_rows)
                / total_steps
                if total_steps else None
            ),
            # cumulative running max since init — INCLUDES the dense
            # warmup phase (a running max cannot be windowed); kept for
            # autotune forensics, not a utilization of C
            "fired_elems_peak_cumulative": max(
                (w.get("fired_elems_peak") or 0) for _, w in windows
            ),
            "deferral_rate": (defer_total / proposed) if proposed else 0.0,
            "per_window": util_rows,
        }
    else:
        report["capacity_utilization"] = None

    # bounded-async staleness surface (train(staleness=D >= 2),
    # docs/chaos.md "Bounded-async gossip & stragglers"): the per-edge
    # staleness gauge trajectory, the staleness histogram, and late
    # commits. D <= 1 runs emit all-zero counters — report None there
    # so legacy/lockstep reports stay unchanged.
    stale_rows = [
        (e, w["edge_staleness_per_step"], int(w.get("late_commit_count", 0)),
         w.get("staleness_hist"))
        for e, w in windows if "edge_staleness_per_step" in w
    ]
    if any(any(v > 0 for v in row) for _, row, _, _ in stale_rows):
        hist_tot = None
        for _, _, _, sh in stale_rows:
            if sh is not None:
                hist_tot = (
                    [a + b for a, b in zip(hist_tot, sh)] if hist_tot
                    else list(sh)
                )
        report["edge_staleness"] = {
            "epochs": [e for e, _, _, _ in stale_rows],
            "edges": meta.get("edges"),
            "rows": [row for _, row, _, _ in stale_rows],
            "late_commits": [lc for _, _, lc, _ in stale_rows],
            "staleness_hist_total": hist_tot,
            "staleness_bound": next(
                (h["staleness"] for h in reversed(history)
                 if h.get("staleness")), None
            ),
        }
    else:
        report["edge_staleness"] = None

    # message-lifecycle ledger (obs/ledger.py): fold the per-window
    # `message_ledger` blocks into a run-total per-edge disposition
    # table, keep the per-window timeline (rank+edge sums), and
    # aggregate the conservation auditor's verdicts
    led_rows = [
        (e, w["message_ledger"], w.get("ledger_audit"))
        for e, w in windows if "message_ledger" in w
    ]
    if led_rows:
        totals: Dict[str, List[int]] = {}
        for _, blk, _ in led_rows:
            for k, v in blk.items():
                if k == "in_flight":
                    continue  # gauge, not a windowable count
                totals[k] = (
                    [a + b for a, b in zip(totals[k], v)]
                    if k in totals else list(v)
                )
        audits = [a for _, _, a in led_rows if a]
        report["message_lifecycle"] = {
            "epochs": [e for e, _, _ in led_rows],
            "edges": meta.get("edges"),
            "totals": totals,
            "in_flight_final": led_rows[-1][1].get("in_flight"),
            "timeline": [
                {"epoch": e, **{k: sum(v) for k, v in blk.items()}}
                for e, blk, _ in led_rows
            ],
            "audit": {
                "windows": len(audits),
                "checks": sum(int(a.get("checks", 0)) for a in audits),
                "ok": all(a.get("ok", False) for a in audits),
                "violations": [
                    v for a in audits for v in a.get("violations", [])
                ][:8],
            } if audits else None,
        }
    else:
        report["message_lifecycle"] = None
    return report


def render_text(report: Dict[str, Any]) -> str:
    """Terse human summary of a report (the tool's stdout)."""
    lines = [
        f"obs report (schema v{report['obs_schema']}) — "
        f"algo={report.get('algo')}, {len(report.get('epochs') or [])} "
        "epoch records",
    ]
    pls = report.get("msgs_saved_pct_per_leaf")
    if pls and pls["pct"]:
        last = pls["pct"][-1]
        names = pls.get("leaves") or [str(i) for i in range(len(last))]
        worst = min(range(len(last)), key=lambda i: last[i])
        best = max(range(len(last)), key=lambda i: last[i])
        lines.append(
            f"per-leaf msgs saved (last window): best {names[best]} "
            f"{last[best]:.1f}%, worst {names[worst]} {last[worst]:.1f}%"
        )
    cap = report.get("capacity_utilization")
    if cap:
        util = cap.get("utilization_mean")
        util_s = f"{100 * util:.1f}%" if util is not None else "n/a"
        lines.append(
            f"compact wire: C={cap['compact_capacity']} elems, mean "
            f"utilization {util_s}, deferral "
            f"rate {100 * cap['deferral_rate']:.2f}%"
        )
    cons = report.get("consensus_error")
    if cons and cons["max"]:
        lines.append(
            f"consensus error: max {cons['max'][-1]:.3g} "
            f"(mean {cons['mean'][-1]:.3g}) at epoch {cons['epochs'][-1]}"
        )
    st = report.get("edge_staleness")
    if st and st["rows"]:
        last = st["rows"][-1]
        names = st.get("edges") or [str(i) for i in range(len(last))]
        worst = max(range(len(last)), key=lambda i: last[i])
        lines.append(
            f"bounded-async (D={st.get('staleness_bound')}): stalest "
            f"edge {names[worst]} at {last[worst]:.2f} passes (last "
            f"window), {sum(st['late_commits'])} late commits total"
        )
    ml = report.get("message_lifecycle")
    if ml and ml.get("totals"):
        totals = ml["totals"]
        rows = list(totals)
        n_edges = len(next(iter(totals.values())))
        names = ml.get("edges") or [str(i) for i in range(n_edges)]
        aud = ml.get("audit")
        aud_s = (
            f"audit {aud['checks']} checks "
            + ("OK" if aud["ok"] else
               f"FAILED ({len(aud['violations'])}+ violations)")
            if aud else "no audit"
        )
        lines.append(
            f"message lifecycle ({len(ml['epochs'])} windows, {aud_s}):"
        )
        width = max(len(n) for n in names) if names else 4
        lines.append(
            "  " + "edge".ljust(width) + "  "
            + "  ".join(f"{r:>10}" for r in rows)
        )
        for e in range(n_edges):
            lines.append(
                "  " + str(names[e]).ljust(width) + "  "
                + "  ".join(f"{totals[r][e]:>10d}" for r in rows)
            )
        infl = ml.get("in_flight_final")
        if infl and any(infl):
            lines.append(f"  in-flight at run end: {infl}")
        tl = ml.get("timeline") or []
        if len(tl) > 1:
            lines.append(
                "  timeline (fired/delivered/dropped/rejected per window): "
                + " ".join(
                    f"e{t['epoch']}:{t.get('fired', 0)}/"
                    f"{t.get('delivered', 0)}/{t.get('dropped', 0)}/"
                    f"{t.get('rejected', 0)}"
                    for t in tl
                )
            )
        if aud and not aud["ok"]:
            for v in aud["violations"][:4]:
                lines.append(
                    f"  VIOLATION {v['law']} rank={v['rank']} "
                    f"edge={v['edge']}: {v['lhs']} != {v['rhs']}"
                )
    return "\n".join(lines)
