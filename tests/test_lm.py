"""Language-model training end-to-end: the synthetic Markov-chain LM task,
hybrid-mesh batch layout, and the transformer family through train()/CLI.

Beyond-reference capability (the reference has no attention at all,
SURVEY.md §2.5); this locks in the launcher-level story: every parallel
family — dp-gossip x {sp, tp, pp, ep} — is reachable end-to-end from the
same flags that drive the reference's four algorithms.
"""

import json

import numpy as np
import pytest

from eventgrad_tpu.cli import main, parse_mesh
from eventgrad_tpu.data.datasets import synthetic_lm_dataset
from eventgrad_tpu.data.sharding import expand_to_mesh
from eventgrad_tpu.parallel.topology import Ring, Topology


def test_lm_dataset_deterministic_learnable_markov():
    x, y = synthetic_lm_dataset(128, 32, vocab=50, seed=3)
    assert x.shape == y.shape == (128, 32) and x.dtype == np.int32
    # targets are the next token
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
    x2, _ = synthetic_lm_dataset(128, 32, vocab=50, seed=3)
    np.testing.assert_array_equal(x, x2)
    xt, _ = synthetic_lm_dataset(128, 32, vocab=50, seed=3, split="test")
    assert not np.array_equal(x, xt)
    # peaked transitions: the most-likely successor of a token repeats far
    # more often than uniform chance would allow
    from collections import Counter

    follows = Counter(zip(x[:, :-1].ravel(), x[:, 1:].ravel()))
    top = follows.most_common(1)[0][1]
    assert top > 5 * (x.size / 50 / 50)


def test_expand_to_mesh_layouts():
    topo = Topology(axes=("dp", "sp"), shape=(2, 2), gossip_axes=("dp",))
    xb = np.arange(2 * 3 * 4 * 8).reshape(2, 3, 4, 8).astype(np.int32)
    yb = xb + 1
    xe, ye = expand_to_mesh(xb, yb, topo)
    assert xe.shape == (4, 3, 4, 4)
    # rank order row-major over (dp, sp): rank 1 = dp0/sp1 -> second chunk
    np.testing.assert_array_equal(xe[1], xb[0][..., 4:])
    np.testing.assert_array_equal(xe[2], xb[1][..., :4])
    np.testing.assert_array_equal(ye[3], yb[1][..., 4:])

    # sharded axis (tp): batches replicate, nothing is chunked
    topo_tp = Topology(
        axes=("dp", "tp"), shape=(2, 2), gossip_axes=("dp",), sharded_axes=("tp",)
    )
    xe, ye = expand_to_mesh(xb, yb, topo_tp)
    assert xe.shape == (4, 3, 4, 8)
    np.testing.assert_array_equal(xe[0], xe[1])
    np.testing.assert_array_equal(xe[2], xb[1])

    with pytest.raises(ValueError, match="not divisible"):
        expand_to_mesh(xb[..., :7], yb[..., :7], topo)


def test_parse_mesh_hybrid_specs():
    t = parse_mesh("dp:4,sp:2")
    assert t.axes == ("dp", "sp") and t.shape == (4, 2)
    assert t.gossip_axes == ("dp",) and t.sharded_axes == ()
    t = parse_mesh("dp:2,tp:2")
    assert t.sharded_axes == ("tp",)
    t = parse_mesh("tp:4")
    assert t.gossip_axes == () and t.sharded_axes == ("tp",)
    import argparse

    for bad in ("dp:2,dp:2", "dp:x", "blah:3", "dp:2,qq:2"):
        with pytest.raises(argparse.ArgumentTypeError):
            parse_mesh(bad)


LM_ARGS = [
    "--dataset", "synthetic-lm", "--seq-len", "32", "--vocab", "64",
    "--dim", "32", "--heads", "4", "--layers", "1", "--epochs", "2",
    "--batch-size", "4", "--n-synth", "64", "--lr", "0.1",
    "--warmup-passes", "2",
]


def _run(capsys, args):
    assert main(args) == 0
    return [json.loads(l) for l in capsys.readouterr().out.splitlines()]


def test_cli_transformer_ring_consensus_eval(capsys):
    recs = _run(capsys, ["--algo", "eventgrad", "--mesh", "ring:4",
                         "--model", "transformer"] + LM_ARGS)
    final = recs[-1]
    assert final["final"] and "accuracy" in final  # consensus eval ran
    assert recs[1]["loss"] < recs[0]["loss"]
    assert recs[-2]["msgs_saved_pct"] > 0


def test_cli_transformer_ring_attention_dp_sp(capsys):
    recs = _run(capsys, ["--algo", "eventgrad", "--mesh", "dp:2,sp:2",
                         "--model", "transformer", "--attn", "ring"] + LM_ARGS)
    assert recs[-1]["final"] and recs[-1]["consensus_eval"] is False
    assert recs[1]["loss"] < recs[0]["loss"]


def test_cli_transformer_tp_mesh_backend(capsys):
    recs = _run(capsys, ["--algo", "eventgrad", "--mesh", "dp:2,tp:2",
                         "--backend", "mesh", "--model", "transformer_tp"]
                + LM_ARGS)
    assert recs[1]["loss"] < recs[0]["loss"]


def test_cli_transformer_pp_and_moe(capsys):
    recs = _run(capsys, ["--algo", "dpsgd", "--mesh", "dp:2,pp:2",
                         "--model", "transformer_pp"]
                + LM_ARGS + ["--layers", "2"])
    assert recs[1]["loss"] < recs[0]["loss"]
    recs = _run(capsys, ["--algo", "sp_eventgrad", "--mesh", "dp:2,ep:2",
                         "--model", "transformer_moe", "--topk-percent", "25"]
                + LM_ARGS)
    assert recs[1]["loss"] < recs[0]["loss"]


def test_cli_lm_guards():
    with pytest.raises(SystemExit):  # ring attention needs an sp axis
        main(["--model", "transformer", "--attn", "ring",
              "--mesh", "ring:4"] + LM_ARGS)
    with pytest.raises(SystemExit):  # image model on LM data
        main(["--model", "cnn2", "--dataset", "synthetic-lm"])
    with pytest.raises(SystemExit):  # explicit image dataset on a transformer
        main(["--model", "transformer"] + LM_ARGS + ["--dataset", "mnist"])
    with pytest.raises(SystemExit):  # augment is an image transform
        main(["--model", "transformer", "--augment"] + LM_ARGS)
