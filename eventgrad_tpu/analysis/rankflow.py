"""Rank-isolation dataflow analysis over the vmap-lifted train step.

The decentralized-semantics guarantee EventGraD rests on: rank r's new
state depends on other ranks ONLY through the declared neighbor
exchange.  On the single-chip vmap lift every rank lives as one index
of a leading [n_ranks] axis, so the guarantee has a precise structural
form: every equation of the lifted jaxpr must treat that axis
POINTWISE, except the equations `lax.ppermute` lowers to — under vmap,
a gather over the rank axis whose indices are a CONSTANT permutation
(the neighbor shift).  This module is an abstract interpreter that
tracks, for every intermediate, which array axis (if any) carries the
rank coordinate, and reports

  * `exchanges` — the constant-permutation gathers found, each with its
    ring offset, per-neighbor lane shape, and dtype (the wire-truth
    inputs of analysis/audit.py);
  * `psums` — positional cross-rank reductions (`lax.psum`/`pmean`
    under vmap); legal only for configurations that declare them
    (allreduce, aux axes), never for ring gossip;
  * `violations` — every other equation that moves information across
    the rank axis (a data-dependent cross-rank gather, a slice or
    concatenate that cuts the axis, a reduction over it, a reshape that
    folds it away, an unknown primitive the rules cannot prove safe).

Soundness stance: UNKNOWN primitives are violations, not warnings — a
new op in the step must either be provably rank-pointwise (add a rule)
or be a declared exchange.  Known limitation: a reshape that merges the
rank axis with another dim (the vmap batching rule for convolutions
does this) reports as a violation; the audit matrix therefore runs on
the MLP geometry, where the step's exchange structure is identical and
no such merge occurs (docs/ANALYSIS.md).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

#: cap on constant values carried through the fold (the permutation
#: vectors are [n_ranks]; anything big is never needed for an index)
_MAX_CONST_ELEMS = 1 << 16


@dataclasses.dataclass(frozen=True)
class Abs:
    """Abstract value: `axis` is the array dim carrying the rank
    coordinate (None = rank-invariant — the value does not depend on
    any rank's inputs); `const` is the concrete value when statically
    known (index pipelines), else None."""

    axis: Optional[int] = None
    const: Optional[np.ndarray] = None


@dataclasses.dataclass
class Exchange:
    """One declared cross-rank move: a constant-permutation gather."""

    offset: int  #: signed ring offset (dst reads from dst+offset)
    lane_shape: Tuple[int, ...]  #: per-rank payload shape
    dtype: str
    path: Tuple[str, ...]

    @property
    def lane_elems(self) -> int:
        return int(math.prod(self.lane_shape)) if self.lane_shape else 1


@dataclasses.dataclass
class Finding:
    kind: str  #: "violation" | "psum"
    prim: str
    reason: str
    path: Tuple[str, ...]


@dataclasses.dataclass
class RankFlowReport:
    n_ranks: int
    exchanges: List[Exchange]
    psums: List[Finding]
    violations: List[Finding]

    def exchange_offsets(self) -> List[int]:
        return sorted({e.offset for e in self.exchanges})


# --- primitive rule tables --------------------------------------------------

#: pointwise primitives: every ranked operand shares the rank axis and
#: the output inherits it — no data moves across ranks
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "atan2",
    "max", "min", "and", "or", "xor", "not", "neg", "sign", "abs",
    "exp", "exp2", "log", "log1p", "expm1", "sqrt", "rsqrt", "cbrt",
    "tanh", "tan", "sin", "cos", "asin", "acos", "atan", "sinh", "cosh",
    "asinh", "acosh", "atanh", "logistic", "erf", "erfc", "erf_inv",
    "floor", "ceil", "round", "nextafter", "is_finite", "square",
    "gt", "lt", "ge", "le", "eq", "ne", "select_n", "clamp",
    "gt_to", "lt_to", "ge_to", "le_to", "eq_to", "ne_to",
    "convert_element_type", "stop_gradient", "add_any", "copy",
    "reduce_precision", "real", "imag", "conj",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "population_count", "clz",
})

#: prefix-preserving primitives: output keeps the leading dims of the
#: input (rank axis survives in place); trailing dims may change
_PREFIX = frozenset({
    "random_wrap", "random_unwrap", "random_split", "random_bits",
    "random_fold_in", "random_seed", "bitcast_convert_type",
})

_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_or", "reduce_and", "reduce_xor", "argmax", "argmin",
})

_CUM = frozenset({"cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp"})

_FOLD = {
    "add": np.add, "sub": np.subtract, "mul": np.multiply,
    "rem": np.mod, "max": np.maximum, "min": np.minimum,
    "lt": np.less, "le": np.less_equal, "gt": np.greater,
    "ge": np.greater_equal, "eq": np.equal, "ne": np.not_equal,
    "and": np.bitwise_and, "or": np.bitwise_or, "xor": np.bitwise_xor,
    "not": np.invert, "neg": np.negative, "abs": np.abs,
}

_COLLECTIVE_VIOLATIONS = frozenset({
    "all_gather", "all_to_all", "reduce_scatter", "pgather", "pbroadcast",
})


def _const_of(v) -> Optional[np.ndarray]:
    if v is None:
        return None
    try:
        arr = np.asarray(v)
    except Exception:
        return None
    if arr.size > _MAX_CONST_ELEMS or arr.dtype == object:
        return None
    return arr


class _Flow:
    def __init__(self, n_ranks: int):
        self.n = n_ranks
        self.exchanges: List[Exchange] = []
        self.psums: List[Finding] = []
        self.violations: List[Finding] = []

    # -- helpers ------------------------------------------------------------

    def _mark(self):
        """Snapshot of the findings lists (fixpoint re-runs and cond
        branches truncate back to a mark so one runtime execution is
        recorded exactly once)."""
        return len(self.exchanges), len(self.psums), len(self.violations)

    def _reset(self, mark):
        e, p, v = mark
        del self.exchanges[e:]
        del self.psums[p:]
        del self.violations[v:]

    def _take_since(self, mark):
        e, p, v = mark
        taken = (self.exchanges[e:], self.psums[p:], self.violations[v:])
        self._reset(mark)
        return taken

    def _bad(self, eqn, path, reason) -> Abs:
        self.violations.append(
            Finding("violation", eqn.primitive.name, reason, path)
        )
        return Abs(None, None)

    def _read(self, env, v) -> Abs:
        if isinstance(v, jax.core.Literal):
            return Abs(None, _const_of(v.val))
        return env.get(v, Abs(None, None))

    def _common_axis(self, eqn, path, abs_in) -> Tuple[Optional[int], bool]:
        axes = {a.axis for a in abs_in if a.axis is not None}
        if len(axes) > 1:
            self._bad(eqn, path, f"operands carry rank axes {sorted(axes)}")
            return None, False
        return (next(iter(axes)) if axes else None), True

    # -- entry point --------------------------------------------------------

    def run(self, closed, in_abs: Sequence[Abs], path=()) -> List[Abs]:
        jaxpr = closed.jaxpr
        env: Dict[Any, Abs] = {}
        for cv, cval in zip(jaxpr.constvars, closed.consts):
            env[cv] = Abs(None, _const_of(cval))
        if len(in_abs) != len(jaxpr.invars):
            raise ValueError(
                f"rankflow: {len(in_abs)} abstract inputs for "
                f"{len(jaxpr.invars)} invars"
            )
        for v, a in zip(jaxpr.invars, in_abs):
            env[v] = a
        self._run_eqns(jaxpr, env, path)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _run_jaxpr_open(self, jaxpr, consts_abs, in_abs, path) -> List[Abs]:
        """Bare Jaxpr whose constvars get abstract values (scan body)."""
        env: Dict[Any, Abs] = {}
        for cv, a in zip(jaxpr.constvars, consts_abs):
            env[cv] = a
        for v, a in zip(jaxpr.invars, in_abs):
            env[v] = a
        self._run_eqns(jaxpr, env, path)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _run_eqns(self, jaxpr, env, path):
        for eqn in jaxpr.eqns:
            abs_in = [self._read(env, v) for v in eqn.invars]
            abs_out = self._apply(eqn, abs_in, path)
            for v, a in zip(eqn.outvars, abs_out):
                env[v] = a

    # -- the per-primitive transfer function --------------------------------

    def _apply(self, eqn, abs_in: List[Abs], path) -> List[Abs]:
        prim = eqn.primitive.name
        n_out = len(eqn.outvars)
        p = eqn.params

        if prim in _ELEMENTWISE:
            d, ok = self._common_axis(eqn, path, abs_in)
            const = None
            if ok and all(a.const is not None for a in abs_in):
                fn = _FOLD.get(prim)
                if prim == "select_n" and len(abs_in) == 3:
                    const = _const_of(np.where(
                        abs_in[0].const.astype(bool),
                        abs_in[2].const, abs_in[1].const,
                    ))
                elif prim == "convert_element_type":
                    const = _const_of(
                        abs_in[0].const.astype(p["new_dtype"])
                    )
                elif prim in ("stop_gradient", "copy"):
                    const = abs_in[0].const
                elif fn is not None:
                    try:
                        const = _const_of(fn(*[a.const for a in abs_in]))
                    except Exception:
                        const = None
            return [Abs(d, const)] * n_out

        if prim in _PREFIX:
            a = abs_in[0]
            d = a.axis
            out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
            if d is not None and (
                len(out_shape) <= d or out_shape[d] != self.n
            ):
                return [self._bad(
                    eqn, path, f"{prim} drops the rank axis (dim {d})"
                )] * n_out
            return [Abs(d, None)] * n_out

        if prim == "broadcast_in_dim":
            a = abs_in[0]
            d = None if a.axis is None else int(p["broadcast_dimensions"][a.axis])
            const = None
            if a.const is not None:
                try:
                    shape = tuple(int(s) for s in p["shape"])
                    with_ones = [1] * len(shape)
                    for src, dst in enumerate(p["broadcast_dimensions"]):
                        with_ones[int(dst)] = a.const.shape[src]
                    const = _const_of(np.broadcast_to(
                        a.const.reshape(with_ones), shape
                    ))
                except Exception:
                    const = None
            return [Abs(d, const)]

        if prim == "reshape":
            a = abs_in[0]
            if p.get("dimensions") is not None and a.axis is not None:
                return [self._bad(
                    eqn, path, "reshape with permuted dimensions over a "
                    "rank-carrying value"
                )]
            in_shape = tuple(eqn.invars[0].aval.shape)
            out_shape = tuple(eqn.outvars[0].aval.shape)
            const = None
            if a.const is not None:
                try:
                    const = _const_of(a.const.reshape(out_shape))
                except Exception:
                    const = None
            if a.axis is None:
                return [Abs(None, const)]
            pre = math.prod(in_shape[: a.axis]) if a.axis else 1
            for d2 in range(len(out_shape)):
                if (
                    math.prod(out_shape[:d2]) == pre
                    and out_shape[d2] == self.n
                ):
                    return [Abs(d2, const)]
            return [self._bad(
                eqn, path,
                f"reshape {in_shape}->{out_shape} folds the rank axis "
                f"(dim {a.axis}) into another dim — rank blocks are no "
                "longer separable",
            )]

        if prim == "squeeze":
            a = abs_in[0]
            dims = tuple(int(x) for x in p["dimensions"])
            const = None
            if a.const is not None:
                try:
                    const = _const_of(np.squeeze(a.const, axis=dims))
                except Exception:
                    const = None
            if a.axis is None:
                return [Abs(None, const)]
            if a.axis in dims:
                return [self._bad(eqn, path, "squeeze removes the rank axis")]
            return [Abs(a.axis - sum(1 for x in dims if x < a.axis), const)]

        if prim == "transpose":
            a = abs_in[0]
            perm = tuple(int(x) for x in p["permutation"])
            d = None if a.axis is None else perm.index(a.axis)
            const = None
            if a.const is not None:
                try:
                    const = _const_of(np.transpose(a.const, perm))
                except Exception:
                    const = None
            return [Abs(d, const)]

        if prim == "slice":
            a = abs_in[0]
            const = None
            if a.const is not None:
                try:
                    idx = tuple(
                        slice(int(s), int(l), int(st))
                        for s, l, st in zip(
                            p["start_indices"], p["limit_indices"],
                            p["strides"] or [1] * len(p["start_indices"]),
                        )
                    )
                    const = _const_of(a.const[idx])
                except Exception:
                    const = None
            if a.axis is None:
                return [Abs(None, const)]
            d = a.axis
            strides = p["strides"] or [1] * len(p["start_indices"])
            if (
                int(p["start_indices"][d]) != 0
                or int(p["limit_indices"][d]) != self.n
                or int(strides[d]) != 1
            ):
                return [self._bad(
                    eqn, path,
                    "slice selects a subset of ranks (cross-rank read)",
                )]
            return [Abs(d, const)]

        if prim == "pad":
            a = abs_in[0]
            if a.axis is not None:
                cfg = p["padding_config"][a.axis]
                if tuple(int(x) for x in cfg) != (0, 0, 0):
                    return [self._bad(eqn, path, "pad alters the rank axis")]
            return [Abs(a.axis, None)]

        if prim == "concatenate":
            d, ok = self._common_axis(eqn, path, abs_in)
            if not ok:
                return [Abs(None, None)]
            if d is not None and int(p["dimension"]) == d:
                return [self._bad(
                    eqn, path,
                    "concatenate along the rank axis reassembles ranks "
                    "(cross-rank write)",
                )]
            return [Abs(d, None)]

        if prim == "iota":
            const = None
            shape = tuple(int(s) for s in p["shape"])
            if len(shape) == 1 and shape[0] <= _MAX_CONST_ELEMS:
                const = _const_of(
                    np.arange(shape[0]).astype(p["dtype"])
                )
            return [Abs(None, const)]

        if prim in _REDUCE:
            a = abs_in[0]
            axes = tuple(int(x) for x in p["axes"])
            if a.axis is not None and a.axis in axes:
                return [self._bad(
                    eqn, path,
                    f"{prim} reduces over the rank axis — cross-rank "
                    "information flow",
                )] * n_out
            d = (
                None if a.axis is None
                else a.axis - sum(1 for x in axes if x < a.axis)
            )
            return [Abs(d, None)] * n_out

        if prim in _CUM:
            a = abs_in[0]
            if a.axis is not None and int(p["axis"]) == a.axis:
                return [self._bad(
                    eqn, path, f"{prim} scans across the rank axis"
                )]
            return [Abs(a.axis, None)]

        if prim == "sort":
            d, ok = self._common_axis(eqn, path, abs_in)
            if ok and d is not None and int(p["dimension"]) == d:
                return [self._bad(eqn, path, "sort along the rank axis")] * n_out
            return [Abs(d, None)] * n_out

        if prim == "top_k":
            a = abs_in[0]
            ndim = len(eqn.invars[0].aval.shape)
            if a.axis is not None and a.axis == ndim - 1:
                return [self._bad(eqn, path, "top_k along the rank axis")] * n_out
            return [Abs(a.axis, None)] * n_out

        if prim == "rev":
            a = abs_in[0]
            if a.axis is not None and a.axis in tuple(
                int(x) for x in p["dimensions"]
            ):
                return [self._bad(
                    eqn, path, "rev reverses the rank axis (a cross-rank "
                    "permutation outside the declared exchange)",
                )]
            return [Abs(a.axis, None)]

        if prim == "gather":
            return [self._gather(eqn, abs_in, path)]

        if prim in ("scatter", "scatter-add", "scatter-mul", "scatter-min",
                    "scatter-max"):
            return [self._scatter(eqn, abs_in, path)]

        if prim == "dot_general":
            return [self._dot_general(eqn, abs_in, path)]

        if prim == "dynamic_slice":
            a = abs_in[0]
            if any(x.axis is not None for x in abs_in[1:]):
                return [self._bad(
                    eqn, path, "rank-dependent dynamic_slice start index"
                )]
            if a.axis is not None and int(p["slice_sizes"][a.axis]) != self.n:
                return [self._bad(
                    eqn, path, "dynamic_slice cuts the rank axis"
                )]
            return [Abs(a.axis, None)]

        if prim == "dynamic_update_slice":
            op, upd = abs_in[0], abs_in[1]
            if any(x.axis is not None for x in abs_in[2:]):
                return [self._bad(
                    eqn, path, "rank-dependent dynamic_update_slice index"
                )]
            d, ok = self._common_axis(eqn, path, [op, upd])
            if not ok:
                return [Abs(None, None)]
            if d is not None and tuple(eqn.invars[1].aval.shape)[d] != self.n:
                return [self._bad(
                    eqn, path, "dynamic_update_slice writes a subset of ranks"
                )]
            return [Abs(d, None)]

        if prim == "psum":
            a = abs_in[0]
            axes = tuple(x for x in p["axes"] if isinstance(x, int))
            if a.axis is not None and a.axis in axes:
                self.psums.append(Finding(
                    "psum", prim,
                    "positional psum over the rank axis (allreduce/pmean)",
                    path,
                ))
                d = None  # reduced away: result is rank-invariant
                return [Abs(d, None)] * n_out
            d = (
                None if a.axis is None
                else a.axis - sum(1 for x in axes if x < a.axis)
            )
            return [Abs(d, None)] * n_out

        if prim == "ppermute":
            # shard_map / pmap form: explicit named-axis permutation
            perm = tuple((int(s), int(d)) for s, d in p["perm"])
            offs = {(s - d) % self.n for s, d in perm}
            off = offs.pop() if len(offs) == 1 else None
            if off is None:
                return [self._bad(
                    eqn, path, "ppermute with a non-uniform permutation"
                )] * n_out
            for ov in eqn.outvars:
                self.exchanges.append(Exchange(
                    offset=off if off <= self.n // 2 else off - self.n,
                    lane_shape=tuple(ov.aval.shape),
                    dtype=str(ov.aval.dtype),
                    path=path,
                ))
            return [Abs(a.axis, None) for a in abs_in[:n_out]]

        if prim in _COLLECTIVE_VIOLATIONS:
            return [self._bad(
                eqn, path, f"{prim}: undeclared cross-rank collective"
            )] * n_out

        # --- nested jaxprs --------------------------------------------------

        if prim == "pjit":
            return self.run(
                p["jaxpr"], abs_in, path + (p.get("name") or "pjit",)
            )

        if prim in ("closed_call", "core_call", "call"):
            return self.run(p["call_jaxpr"], abs_in, path + (prim,))

        if prim in ("custom_jvp_call", "custom_vjp_call",
                    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"):
            sub = p.get("call_jaxpr") or p.get("fun_jaxpr")
            if sub is None:
                return [self._bad(
                    eqn, path, f"{prim} without an inspectable call_jaxpr"
                )] * n_out
            return self.run(sub, abs_in, path + (prim,))

        if prim in ("remat", "checkpoint", "remat2"):
            sub = p["jaxpr"]
            if isinstance(sub, jax.core.Jaxpr):
                return self._run_jaxpr_open(sub, [], abs_in, path + (prim,))
            return self.run(sub, abs_in, path + (prim,))

        if prim == "scan":
            return self._scan(eqn, abs_in, path)

        if prim == "while":
            return self._while(eqn, abs_in, path)

        if prim == "cond":
            return self._cond(eqn, abs_in, path)

        return [self._bad(
            eqn, path,
            f"primitive '{prim}' has no rank-flow rule — prove it "
            "rank-pointwise (add a rule in analysis/rankflow.py) or "
            "declare it as an exchange",
        )] * n_out

    # -- the interesting primitives -----------------------------------------

    def _gather(self, eqn, abs_in, path) -> Abs:
        op, idx = abs_in[0], abs_in[1]
        dn = eqn.params["dimension_numbers"]
        offset_dims = tuple(int(x) for x in dn.offset_dims)
        collapsed = tuple(int(x) for x in dn.collapsed_slice_dims)
        start_map = tuple(int(x) for x in dn.start_index_map)
        op_batch = tuple(int(x) for x in getattr(dn, "operand_batching_dims", ()))
        idx_batch = tuple(
            int(x) for x in getattr(dn, "start_indices_batching_dims", ())
        )
        slice_sizes = tuple(int(x) for x in eqn.params["slice_sizes"])
        idx_ndim = len(eqn.invars[1].aval.shape)
        out_ndim = len(eqn.outvars[0].aval.shape)
        # output dims not fed by slices come from the indices' non-vector
        # dims, in order (XLA gather semantics; the last indices dim is
        # the index vector)
        batch_positions = [q for q in range(out_ndim) if q not in offset_dims]
        idx_nonvec = list(range(idx_ndim - 1))

        def out_axis_from_idx(di):
            if di not in idx_nonvec:
                return None
            return batch_positions[idx_nonvec.index(di)]

        if op.axis is None:
            if idx.axis is None:
                return Abs(None, None)
            d_out = out_axis_from_idx(idx.axis)
            if d_out is None:
                return self._bad(
                    eqn, path,
                    "rank axis used as the gather index vector dim",
                )
            # per-rank selection from a rank-invariant table: no
            # cross-rank information flow
            return Abs(d_out, None)

        d = op.axis
        if d in op_batch:
            if idx.axis is None:
                # rank-invariant indices applied within each rank's
                # batch slice: out[r] = operand[r][idx] — pointwise
                di = idx_batch[op_batch.index(d)]
                return Abs(out_axis_from_idx(di), None)
            if idx.axis not in idx_batch:
                return self._bad(
                    eqn, path,
                    "batched gather whose indices carry the rank axis "
                    "outside a batching dim",
                )
            return Abs(out_axis_from_idx(idx.axis), None)

        if d in start_map:
            # data moves ACROSS the rank axis, driven by the indices:
            # legal only as a constant permutation (the ppermute lowering)
            if idx.axis is not None:
                return self._bad(
                    eqn, path,
                    "rank-indexed gather across the rank axis (a rank's "
                    "data chosen by another rank's value)",
                )
            perm = None
            if idx.const is not None:
                flat = np.asarray(idx.const).reshape(-1)
                if (
                    flat.size == self.n
                    and np.issubdtype(flat.dtype, np.integer)
                    and sorted(int(x) for x in flat) == list(range(self.n))
                ):
                    perm = [int(x) for x in flat]
            if perm is None:
                return self._bad(
                    eqn, path,
                    "gather across the rank axis whose indices are not a "
                    "static permutation — undeclared cross-rank data "
                    "movement",
                )
            offs = {(perm[r] - r) % self.n for r in range(self.n)}
            if len(offs) != 1:
                return self._bad(
                    eqn, path,
                    f"cross-rank gather permutation {perm} is not a "
                    "uniform ring shift",
                )
            off = offs.pop()
            out_shape = tuple(eqn.outvars[0].aval.shape)
            d_out = out_axis_from_idx(idx_nonvec[0]) if idx_nonvec else None
            if d_out is None:
                return self._bad(
                    eqn, path, "exchange gather with no output rank dim"
                )
            lane = tuple(
                s for q, s in enumerate(out_shape) if q != d_out
            )
            self.exchanges.append(Exchange(
                offset=off if off <= self.n // 2 else off - self.n,
                lane_shape=lane,
                dtype=str(eqn.outvars[0].aval.dtype),
                path=path,
            ))
            return Abs(d_out, None)

        if d in collapsed:
            return self._bad(
                eqn, path, "gather collapses the rank axis"
            )
        # rank dim passes through whole as a slice dim
        if slice_sizes[d] != self.n:
            return self._bad(
                eqn, path, "gather slices a subset of ranks"
            )
        surviving = [
            q for q in range(len(slice_sizes))
            if q not in collapsed and q not in op_batch
        ]
        return Abs(offset_dims[surviving.index(d)], None)

    def _scatter(self, eqn, abs_in, path) -> Abs:
        op, idx, upd = abs_in[0], abs_in[1], abs_in[2]
        dn = eqn.params["dimension_numbers"]
        op_batch = tuple(int(x) for x in getattr(dn, "operand_batching_dims", ()))
        idx_batch = tuple(
            int(x) for x in getattr(dn, "scatter_indices_batching_dims", ())
        )
        scatter_op_dims = tuple(
            int(x) for x in dn.scatter_dims_to_operand_dims
        )
        if op.axis is None and idx.axis is None and upd.axis is None:
            return Abs(None, None)
        if op.axis is not None and op.axis in scatter_op_dims:
            return self._bad(
                eqn, path,
                "scatter writes across the rank axis (cross-rank write)",
            )
        if op.axis is not None and op.axis in op_batch:
            if idx.axis is not None and idx.axis not in idx_batch:
                return self._bad(
                    eqn, path,
                    "batched scatter whose indices carry the rank axis "
                    "outside a batching dim",
                )
            return Abs(op.axis, None)
        if (
            op.axis is None
            and idx.axis is not None and idx.axis in idx_batch
            and op_batch
        ):
            # rank-invariant base (e.g. a zeros buffer) scattered with
            # per-rank batched indices/updates: each rank's slice only
            # receives that rank's updates — pointwise
            return Abs(op_batch[idx_batch.index(idx.axis)], None)
        if op.axis is not None and idx.axis is None and upd.axis is None:
            # rank-invariant updates written identically into every
            # rank's slice of a pass-through rank dim
            return Abs(op.axis, None)
        return self._bad(
            eqn, path, "scatter mixes ranked and unranked operands in a "
            "shape the rules cannot prove rank-pointwise",
        )

    def _dot_general(self, eqn, abs_in, path) -> Abs:
        lhs, rhs = abs_in[0], abs_in[1]
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lc, rc = tuple(int(x) for x in lc), tuple(int(x) for x in rc)
        lb, rb = tuple(int(x) for x in lb), tuple(int(x) for x in rb)
        lhs_ndim = len(eqn.invars[0].aval.shape)
        rhs_ndim = len(eqn.invars[1].aval.shape)
        lhs_free = [q for q in range(lhs_ndim) if q not in lc and q not in lb]
        rhs_free = [q for q in range(rhs_ndim) if q not in rc and q not in rb]

        def out_pos_lhs(d):
            if d in lb:
                return lb.index(d)
            return len(lb) + lhs_free.index(d)

        def out_pos_rhs(d):
            if d in rb:
                return rb.index(d)
            return len(lb) + len(lhs_free) + rhs_free.index(d)

        if lhs.axis is None and rhs.axis is None:
            return Abs(None, None)
        for a, contract in ((lhs, lc), (rhs, rc)):
            if a.axis is not None and a.axis in contract:
                return self._bad(
                    eqn, path,
                    "dot_general contracts over the rank axis — a "
                    "cross-rank reduction",
                )
        if lhs.axis is not None and rhs.axis is not None:
            if lhs.axis in lb and rhs.axis in rb and (
                lb.index(lhs.axis) == rb.index(rhs.axis)
            ):
                return Abs(lb.index(lhs.axis), None)
            return self._bad(
                eqn, path,
                "dot_general pairs two rank-carrying operands outside a "
                "shared batch dim — every rank sees every rank",
            )
        if lhs.axis is not None:
            return Abs(out_pos_lhs(lhs.axis), None)
        return Abs(out_pos_rhs(rhs.axis), None)

    # -- control flow --------------------------------------------------------

    def _scan(self, eqn, abs_in, path) -> List[Abs]:
        p = eqn.params
        nc, ncar = int(p["num_consts"]), int(p["num_carry"])
        consts, carries, xs = (
            abs_in[:nc], abs_in[nc:nc + ncar], abs_in[nc + ncar:],
        )
        xs_body = []
        for a, v in zip(xs, eqn.invars[nc + ncar:]):
            if a.axis == 0:
                return [self._bad(
                    eqn, path, "scan iterates OVER the rank axis — each "
                    "step would see one rank's data with carried state "
                    "across ranks",
                )] * len(eqn.outvars)
            xs_body.append(Abs(None if a.axis is None else a.axis - 1, None))
        carry_abs = list(carries)
        body = p["jaxpr"]  # ClosedJaxpr
        mark = self._mark()
        for _ in range(3):
            # each fixpoint re-run replaces (not appends to) the body's
            # findings: one scan body, one set of exchanges/violations
            self._reset(mark)
            outs = self.run(
                body, list(consts) + carry_abs + xs_body, path + ("scan",)
            )
            new_carry = [Abs(a.axis, None) for a in outs[:ncar]]
            if [a.axis for a in new_carry] == [a.axis for a in carry_abs]:
                break
            carry_abs = [
                Abs(o.axis if o.axis is not None else i.axis, None)
                for i, o in zip(carry_abs, new_carry)
            ]
        else:
            return [self._bad(
                eqn, path, "scan carry rank structure did not stabilize"
            )] * len(eqn.outvars)
        ys = [
            Abs(None if a.axis is None else a.axis + 1, None)
            for a in outs[ncar:]
        ]
        return [Abs(a.axis, None) for a in outs[:ncar]] + ys

    def _while(self, eqn, abs_in, path) -> List[Abs]:
        p = eqn.params
        cn, bn = int(p["cond_nconsts"]), int(p["body_nconsts"])
        cond_c, body_c = abs_in[:cn], abs_in[cn:cn + bn]
        carry = list(abs_in[cn + bn:])
        mark = self._mark()
        for _ in range(3):
            self._reset(mark)
            self.run(
                p["cond_jaxpr"], list(cond_c) + carry, path + ("while.cond",)
            )
            outs = self.run(
                p["body_jaxpr"], list(body_c) + carry, path + ("while.body",)
            )
            if [a.axis for a in outs] == [a.axis for a in carry]:
                break
            carry = [
                Abs(o.axis if o.axis is not None else i.axis, None)
                for i, o in zip(carry, outs)
            ]
        else:
            return [self._bad(
                eqn, path, "while carry rank structure did not stabilize"
            )] * len(eqn.outvars)
        return [Abs(a.axis, None) for a in carry]

    def _cond(self, eqn, abs_in, path) -> List[Abs]:
        pred, ops = abs_in[0], abs_in[1:]
        if pred.axis is not None:
            return [self._bad(
                eqn, path, "cond predicate carries the rank axis "
                "(rank-varying control flow)",
            )] * len(eqn.outvars)
        # at runtime exactly ONE branch executes: record each branch's
        # findings separately, keep every branch's violations/psums, but
        # count the exchange lanes once — and only if the branches agree
        # on them (branches shipping different wires is itself a
        # violation: the step's wire would be control-flow-dependent)
        per_branch, branch_finds = [], []
        for i, br in enumerate(eqn.params["branches"]):
            mark = self._mark()
            per_branch.append(self.run(br, list(ops), path + (f"cond.{i}",)))
            branch_finds.append(self._take_since(mark))
        for exchanges, psums, violations in branch_finds:
            self.psums.extend(psums)
            self.violations.extend(violations)
        sigs = [
            sorted((e.offset, e.lane_shape, e.dtype) for e in ex)
            for ex, _, _ in branch_finds
        ]
        self.exchanges.extend(branch_finds[0][0])
        if any(s != sigs[0] for s in sigs[1:]):
            self.violations.append(Finding(
                "violation", "cond",
                "cond branches ship different exchange lanes — the wire "
                "format would depend on control flow",
                path,
            ))
        outs = []
        for k in range(len(eqn.outvars)):
            axes = {b[k].axis for b in per_branch if b[k].axis is not None}
            if len(axes) > 1:
                outs.append(self._bad(
                    eqn, path,
                    f"cond branches disagree on output {k}'s rank axis",
                ))
            else:
                outs.append(Abs(next(iter(axes)) if axes else None, None))
        return outs


def analyze(
    closed_jaxpr: "jax.core.ClosedJaxpr",
    n_ranks: int,
    in_axes: Optional[Sequence[Optional[int]]] = None,
) -> RankFlowReport:
    """Run the rank-isolation dataflow over a lifted step's closed jaxpr.

    `in_axes` gives the rank-axis position per flat invar; by default
    every invar whose leading dim equals `n_ranks` is assumed stacked at
    axis 0 (the spmd vmap-lift layout) and everything else is
    rank-invariant."""
    if in_axes is None:
        in_axes = [
            0 if (tuple(v.aval.shape)[:1] == (n_ranks,)) else None
            for v in closed_jaxpr.jaxpr.invars
        ]
    flow = _Flow(n_ranks)
    flow.run(closed_jaxpr, [Abs(d, None) for d in in_axes])
    return RankFlowReport(
        n_ranks=n_ranks,
        exchanges=flow.exchanges,
        psums=flow.psums,
        violations=flow.violations,
    )
