"""Analytic cost model (obs/costmodel.py + obs/devicespec.py): FLOP
counts proven against closed-form oracles, phase attribution through the
egphase named scopes, bitwise neutrality of the annotations, roofline
arithmetic, and the state-layout detection that keeps the MFU numerator
from silently zeroing on arena states.

FLOP oracles
  * MLP (dot_general only): EXACT.  For a stack of Dense layers traced
    through jax.vjp(loss, params), layer 1 contributes 2 dots (forward,
    weight-grad — the INPUT grad of the first layer is never built) and
    every deeper layer 3 (forward, weight-grad, input-grad), each
    2·B·in·out FLOPs.  The model's dot_flops must equal that closed form
    to the FLOP.
  * conv (CNN2): within the DOCUMENTED bound.  The backward pass adds a
    data-grad and a filter-grad conv of roughly forward cost each, so
    total conv+dot FLOPs sit in [2x, 4x] the closed-form forward count —
    the bound docs/OBSERVABILITY.md states.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import CNN2, MLP
from eventgrad_tpu.obs import costmodel
from eventgrad_tpu.obs.devicespec import (
    GENERIC_CPU, DeviceSpec, device_spec, spec_for_kind,
)
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils.flops import step_layout_kwargs, train_step_flops

N_RANKS = 4
PER_RANK = 4
IN_SHAPE = (8, 8, 1)
CFG = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2,
                  max_silence=4)


def _setup(model, algo, in_shape=IN_SHAPE, arena=False, n=64):
    topo = Ring(N_RANKS)
    tx = optax.sgd(0.05)
    state = init_train_state(
        model, in_shape, tx, topo, algo, CFG, seed=0, arena=arena
    )
    x, y = synthetic_dataset(n, in_shape, seed=0)
    return topo, tx, state, x, y


# --- FLOP oracles -----------------------------------------------------------


def test_mlp_dot_flops_match_closed_form_exactly():
    model = MLP(hidden=16)
    topo, tx, state, x, y = _setup(model, "dpsgd")
    cm = costmodel.analyze_step(
        model, tx, topo, "dpsgd", CFG, x, y, PER_RANK, state
    )
    batch = N_RANKS * PER_RANK
    n_in = math.prod(IN_SHAPE)
    layers = [(n_in, 16), (16, 10)]
    # layer 1: forward + weight-grad (2 dots); deeper layers add the
    # input-grad dot (3) — each dot is 2*B*in*out FLOPs
    expected = sum(
        (2 if i == 0 else 3) * 2 * batch * fan_in * fan_out
        for i, (fan_in, fan_out) in enumerate(layers)
    )
    assert cm["dot_flops"] == expected
    assert cm["conv_flops"] == 0.0
    assert cm["flops_total"] > cm["dot_flops"]  # eltwise/reductions ride


def test_cnn_conv_flops_within_documented_bound():
    model = CNN2()
    in_shape = (28, 28, 1)
    topo, tx, state, x, y = _setup(model, "dpsgd", in_shape=in_shape)
    cm = costmodel.analyze_step(
        model, tx, topo, "dpsgd", CFG, x, y, PER_RANK, state
    )
    batch = N_RANKS * PER_RANK
    # CNN2 forward closed form (models/cnn.py): conv 3x3x1->10 VALID on
    # 28x28 -> 26x26; pool -> 13x13; conv 3x3x10->20 -> 11x11; pool ->
    # 5x5; dense 500->50->10
    fwd = (
        2 * batch * 26 * 26 * 10 * (3 * 3 * 1)
        + 2 * batch * 11 * 11 * 20 * (3 * 3 * 10)
        + 2 * batch * (500 * 50 + 50 * 10)
    )
    total = cm["conv_flops"] + cm["dot_flops"]
    # the documented training-step bound: backward adds a data-grad and
    # a filter-grad pass of ~forward cost each
    assert 2.0 * fwd <= total <= 4.0 * fwd, (total, fwd, total / fwd)
    assert cm["conv_flops"] > 0


def test_scan_bodies_multiply_by_length():
    def body(c, _):
        return c @ c, None

    def f(c):
        out, _ = jax.lax.scan(body, c, None, length=5)
        return out

    one = costmodel.analyze_jaxpr(
        jax.make_jaxpr(lambda c: c @ c)(jnp.ones((8, 8)))
    )
    scanned = costmodel.analyze_jaxpr(jax.make_jaxpr(f)(jnp.ones((8, 8))))
    assert scanned["dot_flops"] == 5 * one["dot_flops"]


# --- phase attribution ------------------------------------------------------


def test_phases_attributed_across_step():
    model = MLP(hidden=16)
    topo, tx, state, x, y = _setup(model, "eventgrad", arena=True)
    cm = costmodel.analyze_step(
        model, tx, topo, "eventgrad", CFG, x, y, PER_RANK, state
    )
    by = cm["by_phase"]
    # the backward pass lands in grad (vjp transposition keeps the
    # scope), and grad dominates the step
    assert by["grad"]["flops"] > 0.5 * cm["flops_total"]
    assert by["gate_pack"]["flops"] > 0  # trigger state machine
    assert by["exchange"]["hbm_bytes"] > 0  # wire assembly moves bytes
    assert by["commit_mix"]["flops"] > 0
    # the aggregate view reproduces the totals exactly
    assert sum(p["flops"] for p in by.values()) == cm["flops_total"]
    assert sum(p["hbm_bytes"] for p in by.values()) == cm["hbm_bytes_total"]


def test_bucketed_phases_carry_bucket_labels():
    model = MLP(hidden=16)
    topo, tx, state, x, y = _setup(model, "eventgrad", arena=True)
    # state layout must match the bucketed step it traces
    cm = costmodel.analyze_step(
        model, tx, topo, "eventgrad", CFG, x, y, PER_RANK,
        init_train_state(
            MLP(hidden=16), IN_SHAPE, tx, topo, "eventgrad", CFG,
            seed=0, arena=True, bucketed=2,
        ),
        arena=True, bucketed=2,
    )
    labels = set(cm["phases"])
    assert {"exchange.b0", "exchange.b1"} <= labels, labels
    assert any(l.startswith("commit_mix.b") for l in labels), labels
    # bucket labels fold into their base phase in the aggregate view
    ex = cm["by_phase"]["exchange"]
    assert ex["hbm_bytes"] == sum(
        cm["phases"][l]["hbm_bytes"]
        for l in labels if l.startswith("exchange")
    )


def test_annotations_are_bitwise_neutral():
    """obs='off'-style guarantee: the traced step with phase scopes
    disabled (the pre-PR program) trains bitwise identically to the
    annotated one."""
    model = MLP(hidden=16)
    topo, tx, state, x, y = _setup(model, "eventgrad", arena=True)
    xb = jnp.asarray(x[: N_RANKS * PER_RANK]).reshape(
        (N_RANKS, PER_RANK) + IN_SHAPE
    )
    yb = jnp.asarray(y[: N_RANKS * PER_RANK]).reshape((N_RANKS, PER_RANK))

    def _run():
        step = jax.jit(spmd(
            make_train_step(
                model, tx, topo, "eventgrad", event_cfg=CFG, arena=True
            ),
            topo,
        ))
        s, m = state, None
        for _ in range(3):
            s, m = step(s, (xb, yb))
        return s, m

    assert costmodel.annotations_enabled()
    s_on, m_on = _run()
    with costmodel.annotations_disabled():
        assert not costmodel.annotations_enabled()
        s_off, m_off = _run()
    assert costmodel.annotations_enabled()
    for a, b in zip(jax.tree.leaves(s_on), jax.tree.leaves(s_off)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m_on:
        np.testing.assert_array_equal(
            np.asarray(m_on[k]), np.asarray(m_off[k]), err_msg=k
        )
    # and with scopes off the program really carries no phase labels
    with costmodel.annotations_disabled():
        cm = costmodel.analyze_step(
            model, tx, topo, "eventgrad", CFG, x, y, PER_RANK, state
        )
    assert set(cm["phases"]) == {"other"}


# --- state-layout detection (the silent-0.0-FLOPs regression) ---------------


def test_step_layout_detection_and_nonzero_flops():
    model = MLP(hidden=16)
    topo = Ring(N_RANKS)
    tx = optax.sgd(0.05)
    x, y = synthetic_dataset(64, IN_SHAPE, seed=0)
    tree_state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0
    )
    arena_state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True
    )
    bucketed_state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True,
        bucketed=2,
    )
    carrier_state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True,
        resident_wire="int8",
    )
    bf16_state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True,
        resident_wire="bf16",
    )
    assert step_layout_kwargs(tree_state) == {}
    assert step_layout_kwargs(arena_state) == {"arena": True}
    assert step_layout_kwargs(bucketed_state) == {
        "arena": True, "bucketed": 2,
    }
    # carrier-resident states advertise their layout too, so the cost
    # model traces the program that actually ran (int8/bf16 buffer
    # reads, not a silently-retraced f32 twin)
    assert step_layout_kwargs(carrier_state) == {
        "arena": True, "carrier_resident": True, "wire": "int8",
    }
    assert step_layout_kwargs(bf16_state) == {
        "arena": True, "carrier_resident": True, "wire": "bf16",
    }
    # the regression this fixes: train() auto-enables the arena, and the
    # tree-step trace against that state used to be swallowed into a
    # silent 0.0 FLOPs (None MFU on chip)
    assert train_step_flops(
        model, tx, topo, "eventgrad", CFG, x, y, PER_RANK, arena_state
    ) > 0


def test_carrier_resident_bytes_below_f32_twin():
    """The cost model counts buffer reads at the STORED dtype: an int8
    carrier-resident config's analytic bytes/step sit strictly below
    its f32-resident twin's (same model, wire, trigger — only the
    residency differs), and roofline_frac moves with the bytes."""
    model = MLP(hidden=16)
    topo = Ring(N_RANKS)
    tx = optax.sgd(0.05)
    x, y = synthetic_dataset(64, IN_SHAPE, seed=0)
    f32_state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True
    )
    car_state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True,
        resident_wire="int8",
    )
    cm_f = costmodel.analyze_step(
        model, tx, topo, "eventgrad", CFG, x, y, PER_RANK, f32_state,
        wire="int8",
    )
    # carrier_resident=True rides in from step_layout_kwargs(car_state)
    cm_c = costmodel.analyze_step(
        model, tx, topo, "eventgrad", CFG, x, y, PER_RANK, car_state,
        wire="int8",
    )
    assert cm_c["hbm_bytes_total"] < cm_f["hbm_bytes_total"]
    # at the same step time, fewer bytes -> higher intensity -> higher
    # memory-bound ceiling -> roofline_frac strictly drops
    step_s = 0.01
    rl_f = costmodel.roofline(
        cm_f["flops_total"], cm_f["hbm_bytes_total"], step_s, GENERIC_CPU
    )
    rl_c = costmodel.roofline(
        cm_c["flops_total"], cm_c["hbm_bytes_total"], step_s, GENERIC_CPU
    )
    assert rl_f["roofline_bound"] == "memory"
    assert rl_c["roofline_frac"] < rl_f["roofline_frac"]


# --- roofline / device specs ------------------------------------------------


def test_roofline_verdicts_and_mfu():
    spec = DeviceSpec("t", peak_flops=100.0, peak_hbm_bytes_per_s=10.0)
    assert spec.ridge_intensity == 10.0
    # intensity 20 FLOP/B > ridge -> compute-bound; 200 FLOP over 2 s on
    # a 100 FLOP/s peak = MFU 1.0 at the ceiling
    r = costmodel.roofline(200.0, 10.0, 2.0, spec)
    assert r["roofline_bound"] == "compute"
    assert r["mfu"] == pytest.approx(1.0)
    assert r["roofline_frac"] == pytest.approx(1.0)
    # intensity 0.5 < ridge -> memory-bound; ceiling is bw-limited
    r = costmodel.roofline(5.0, 10.0, 1.0, spec)
    assert r["roofline_bound"] == "memory"
    assert r["achieved_bytes_per_s"] == pytest.approx(10.0)
    assert r["roofline_frac"] == pytest.approx(1.0)  # at the bw roof
    assert r["mfu"] == pytest.approx(0.05)
    # degenerate inputs answer None, not a crash
    r = costmodel.roofline(0.0, 0.0, 0.0, spec)
    assert r["mfu"] is None and r["roofline_bound"] is None


def test_device_specs():
    assert spec_for_kind("tpu", "TPU v5 lite").name == "tpu-v5e"
    assert spec_for_kind("tpu", "TPU v5 lite").peak_flops == 197e12
    assert spec_for_kind("tpu", "TPU v4").peak_flops == 275e12
    assert spec_for_kind("cpu", "cpu") is GENERIC_CPU
    assert spec_for_kind("tpu", "TPU v99 hyperlite") is GENERIC_CPU
    assert GENERIC_CPU.nominal
    if jax.default_backend() != "tpu":
        assert device_spec() is GENERIC_CPU
    # the one spec table: utils.flops reads its TPU peaks from here
    from eventgrad_tpu.utils.flops import PEAK_FLOPS_BY_KIND

    assert dict(PEAK_FLOPS_BY_KIND)["v5 lite"] == 197e12


# --- compiled-program facts -------------------------------------------------


def test_compile_timed_records_stage_spans():
    from eventgrad_tpu.obs import Registry

    reg = Registry()

    def f(a, b):
        return a @ b + 1.0

    args = (jnp.ones((8, 8)), jnp.ones((8, 8)))
    compiled, spans, memory = costmodel.compile_timed(
        f, *args, registry=reg, label="unit"
    )
    stages = (
        "compile_trace", "compile_lower", "compile_compile",
        "first_dispatch",
    )
    assert set(spans) == set(stages)
    assert all(v >= 0 for v in spans.values())
    names = [s.name for s in reg.spans]
    for stage in stages:
        assert stage in names
    assert all(
        s.cat == "compile" for s in reg.spans if s.name in stages
    )
    # memory analysis is backend-optional: None or a dict with the peak
    if memory is not None:
        assert "peak_bytes" in memory
