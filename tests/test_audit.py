"""The trace auditor (eventgrad_tpu/analysis/): walker units, the
rank-isolation dataflow, the clean full config matrix, and the seeded
oracle violations — every check proven able to fire.

Acceptance (ISSUE 9 + the ISSUE 12 full-geometry extension): zero
violations across the full configuration matrix — including the
PRODUCTION geometries (LeNetCifar / ResNet18 via the blocked-layout
conv rules, the transformer full+flash via the declared-kernel
registry) — the jaxpr-derived wire-byte count equal to the accounting
formula AND to the executed step's `sent_bytes_wire_real` metric
EXACTLY (masked and compact wires; in the metric's f32 carrier), and
each seeded violation class (rank coupling, byte-formula drift, host
sync, dtype promotion, extra ravel, conv rank-merge, unregistered
kernel, attention cross-rank gather) detected.  Heavy cells (ResNet18,
flash interpret) carry the `slow` mark; the fast conv smoke keeps the
rankflow conv rules in tier-1.  tools/audit.py commits the same story
as the schema-gated artifacts/audit_cpu.json.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

from _spmd import requires_shard_map
from jax import lax

from eventgrad_tpu.analysis import audit, kernels, rankflow, walker
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring


# --- walker units -----------------------------------------------------------


def test_walker_counts_through_nesting():
    """iter_eqns/count_primitives see inside pjit, scan, AND cond —
    an op one nesting level down counts exactly once."""

    def inner(x):
        return jnp.concatenate([x, x])

    def f(x):
        y = jax.jit(inner)(x)  # pjit sub-jaxpr

        def body(c, t):
            return c + jnp.sum(jnp.concatenate([t, t])), c

        c, _ = lax.scan(body, 0.0, jnp.zeros((2, 3)))  # scan sub-jaxpr
        z = lax.cond(
            c > 0,
            lambda v: jnp.concatenate([v, v]),
            lambda v: jnp.concatenate([v, -v]),
            x,
        )  # two cond branches
        return y, z

    jx = jax.make_jaxpr(f)(jnp.ones((3,)))
    assert walker.count_primitives(jx.jaxpr, "concatenate") == 4
    paths = {
        p for eqn, p in walker.iter_eqns(jx.jaxpr)
        if eqn.primitive.name == "concatenate"
    }
    assert any("scan" in p for p in paths)
    assert any("cond" in p for p in paths)
    census = walker.primitive_census(jx.jaxpr)
    assert census["concatenate"] == 4


def test_walker_counts_through_pallas():
    """The walker descends into pallas_call KERNEL bodies (the `jaxpr`
    param is a bare Jaxpr): primitives inside the kernel are visible to
    the same traversal the cost model and auditor share — an op moved
    into a Pallas body cannot silently drop out of any census."""
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.tanh(x_ref[...]) * 2.0 + jnp.sin(x_ref[...])

    def f(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(x)

    jx = jax.make_jaxpr(f)(jnp.ones((8, 128), jnp.float32))
    outer = [e.primitive.name for e in jx.jaxpr.eqns]
    assert "pallas_call" in outer
    assert "tanh" not in outer  # the body op is one level DOWN...
    assert walker.count_primitives(jx.jaxpr, "tanh") == 1  # ...and seen
    assert walker.count_primitives(jx.jaxpr, "sin") == 1
    paths = {
        p for eqn, p in walker.iter_eqns(jx.jaxpr)
        if eqn.primitive.name == "tanh"
    }
    assert any("pallas_call" in p for p in paths)
    census = walker.primitive_census(jx.jaxpr)
    assert census["tanh"] == 1 and census["sin"] == 1


def test_walker_full_ravel_counts_trailing_dim():
    def f(a, b):
        return jnp.concatenate([a, b], axis=-1), jnp.concatenate([a, a], -1)

    jx = jax.make_jaxpr(f)(jnp.ones((4, 6)), jnp.ones((4, 4)))
    assert walker.count_full_ravels(jx.jaxpr, 10) == 1
    assert walker.count_full_ravels(jx.jaxpr, 12) == 1
    assert walker.count_full_ravels(jx.jaxpr, 7) == 0


# --- rankflow units ---------------------------------------------------------


def _lift_jaxpr(fn, *args):
    topo = Ring(audit.N_RANKS)
    return jax.make_jaxpr(spmd(fn, topo))(*args), topo


def test_rankflow_clean_pointwise_program():
    x = jnp.ones((audit.N_RANKS, 8))
    jx, _ = _lift_jaxpr(lambda v: jnp.tanh(v) * 2 + jnp.sum(v), x)
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.violations == [] and rep.exchanges == [] and rep.psums == []


def test_rankflow_detects_ppermute_and_offset():
    def f(v):
        return lax.ppermute(
            v, "ring",
            [((r + 1) % audit.N_RANKS, r) for r in range(audit.N_RANKS)],
        )

    x = jnp.ones((audit.N_RANKS, 8))
    jx, _ = _lift_jaxpr(f, x)
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.violations == []
    assert rep.exchange_offsets() == [1]
    assert rep.exchanges[0].lane_shape == (8,)
    assert rep.exchanges[0].dtype == "float32"


def test_rankflow_flags_psum_and_cross_rank_reduce():
    x = jnp.ones((audit.N_RANKS, 8))
    jx, _ = _lift_jaxpr(lambda v: lax.pmean(v, "ring"), x)
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.psums and rep.violations == []

    # a positional reduction over the stacked rank axis OUTSIDE the
    # per-rank fn is a violation, not a psum
    def leak(state):
        return state + jnp.sum(state, axis=0, keepdims=True)

    jx2 = jax.make_jaxpr(leak)(x)
    rep2 = rankflow.analyze(jx2, audit.N_RANKS)
    assert rep2.violations
    assert "reduces over the rank axis" in rep2.violations[0].reason


def test_rankflow_tracks_through_scan_over_time():
    """A step scanned over TIME (rank axis in the carry, time leading
    the xs) audits clean — the dispatch-block shape of the train loop."""

    def step(v):
        got = lax.ppermute(
            v, "ring",
            [((r + 1) % audit.N_RANKS, r) for r in range(audit.N_RANKS)],
        )
        return (v + got) * 0.5

    topo = Ring(audit.N_RANKS)
    lifted = spmd(step, topo)

    def scanned(v0, ts):
        def body(c, _):
            return lifted(c), jnp.sum(c, axis=tuple(range(1, c.ndim)))

        return lax.scan(body, v0, ts)

    x = jnp.ones((audit.N_RANKS, 8))
    jx = jax.make_jaxpr(scanned)(x, jnp.arange(3.0))
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.violations == []
    assert rep.exchange_offsets() == [1]


def test_rankflow_counts_cond_and_scan_exchanges_once():
    """One runtime exchange is ONE recorded exchange: a ppermute inside
    both branches of a cond, or inside a scan whose carry needs a second
    fixpoint pass, must not double the derived wire bytes — and cond
    branches shipping DIFFERENT wires is itself a violation."""
    perm = [((r + 1) % audit.N_RANKS, r) for r in range(audit.N_RANKS)]
    topo = Ring(audit.N_RANKS)

    def shift(v):
        return lax.ppermute(v, "ring", perm)

    lifted = spmd(shift, topo)
    x = jnp.ones((audit.N_RANKS, 8))

    # a rank-invariant predicate keeps lax.cond a real cond primitive
    # (a rank-dependent one is batched into run-both+select by vmap, in
    # which case both exchanges genuinely execute and both count)
    def cond_prog(v, flag):
        return lax.cond(flag > 0, lifted, lifted, v)

    rep = rankflow.analyze(
        jax.make_jaxpr(cond_prog)(x, jnp.float32(1.0)), audit.N_RANKS
    )
    assert rep.violations == []
    assert len(rep.exchanges) == 1  # both branches agree: counted once

    # a scan whose carry starts rank-invariant (zeros built inline)
    # takes a second fixpoint pass; the body's exchange still counts once
    def scanned(v, ts):
        def body(c, _):
            return lifted(c + v), jnp.sum(c, axis=1)

        return lax.scan(body, jnp.zeros((audit.N_RANKS, 8)), ts)

    rep2 = rankflow.analyze(
        jax.make_jaxpr(scanned)(x, jnp.arange(2.0)), audit.N_RANKS
    )
    assert rep2.violations == []
    assert len(rep2.exchanges) == 1

    def asym_prog(v, flag):
        return lax.cond(flag > 0, lifted, lambda u: u * 1.0, v)

    rep3 = rankflow.analyze(
        jax.make_jaxpr(asym_prog)(x, jnp.float32(1.0)), audit.N_RANKS
    )
    assert any("different exchange lanes" in v.reason
               for v in rep3.violations)


def test_rankflow_flags_scan_over_ranks():
    def over_ranks(state):
        def body(c, row):
            return c + jnp.sum(row), c

        return lax.scan(body, 0.0, state)  # leading axis IS the rank axis

    jx = jax.make_jaxpr(over_ranks)(jnp.ones((audit.N_RANKS, 8)))
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert any("scan iterates OVER the rank axis" in v.reason
               for v in rep.violations)


# --- conv / window / blocked-layout rules (ISSUE 12) ------------------------


def _conv_ranked(x, w, fgc=None, dn=("NHWC", "HWIO", "NHWC")):
    return lax.conv_general_dilated(
        x, w, (1, 1), "VALID", dimension_numbers=dn,
        feature_group_count=fgc or 1,
    )


def test_rankflow_conv_vmap_batching_clean():
    """The full conv sandwich the vmap batching rule emits — rank-major
    feature merge, grouped conv with fgc *= n, split back — tracks
    clean through fwd AND bwd (the dW/dx transposed convs), with
    pooling's reduce_window/select_and_scatter_add along for the ride."""

    def per_rank(w, x):
        y = _conv_ranked(x, w)
        y = nn.max_pool(y, (2, 2), strides=(2, 2))
        return jnp.sum(y ** 2)

    w = jnp.zeros((audit.N_RANKS, 3, 3, 3, 6))
    x = jnp.zeros((audit.N_RANKS, 2, 8, 8, 3))
    jx = jax.make_jaxpr(
        jax.vmap(jax.grad(per_rank), axis_name="ring")
    )(w, x)
    rep = rankflow.analyze(jx, audit.N_RANKS)
    assert rep.violations == [], [
        (v.prim, v.reason) for v in rep.violations
    ]


def test_rankflow_conv_rank_merge_without_groups_flagged():
    """The rank-major merge is only legal UNDER group confinement: the
    same merged layout convolved with feature_group_count=1 contracts
    every rank's channels into every output channel — flagged at the
    conv, not laundered through the legal-looking reshape."""
    n = audit.N_RANKS

    def leak(x):  # stacked [n, B, H, W, C]
        merged = jnp.transpose(x, (1, 2, 3, 0, 4)).reshape(
            x.shape[1], x.shape[2], x.shape[3], n * x.shape[4]
        )
        kern = jnp.ones((3, 3, n * x.shape[4], 2), x.dtype)
        return jnp.sum(_conv_ranked(merged, kern))

    jx = jax.make_jaxpr(leak)(jnp.zeros((n, 2, 8, 8, 3)))
    rep = rankflow.analyze(jx, n)
    assert any(
        v.prim == "conv_general_dilated"
        and "feature groups" in v.reason
        for v in rep.violations
    ), [(v.prim, v.reason) for v in rep.violations]


def test_rankflow_reshape_merge_split_roundtrip():
    """A rank-major merge is tracked as a BLOCKED layout and a split
    recovers the pure axis; splitting the rank axis itself is flagged."""
    n = audit.N_RANKS
    x = jnp.ones((n, 3, 5))

    # merge [n,3,5] -> [n*3,5] (blocked) -> split back -> reduce: clean
    def roundtrip(v):
        merged = v.reshape(n * 3, 5)
        back = merged.reshape(n, 3, 5)
        return jnp.sum(back, axis=(1, 2))

    rep = rankflow.analyze(jax.make_jaxpr(roundtrip)(x), n)
    assert rep.violations == []

    # reducing over the MERGED dim crosses ranks: flagged
    def bad_reduce(v):
        return jnp.sum(v.reshape(n * 3, 5), axis=0)

    rep2 = rankflow.analyze(jax.make_jaxpr(bad_reduce)(x), n)
    assert any("rank axis" in v.reason for v in rep2.violations)

    # splitting the rank axis across dims ([n,...] -> [2, n//2, ...])
    def bad_split(v):
        return v.reshape(2, n // 2, 3, 5)

    rep3 = rankflow.analyze(jax.make_jaxpr(bad_split)(x), n)
    assert any("splits the rank axis" in v.reason for v in rep3.violations)


def test_rankflow_window_touching_rank_dim_flagged():
    """A pooling window that sweeps ACROSS the rank dim mixes ranks."""
    n = audit.N_RANKS

    def bad(v):  # stacked [n, 8]: window of 2 over the rank dim
        return lax.reduce_window(
            v, -jnp.inf, lax.max, (2, 1), (1, 1), "VALID"
        )

    rep = rankflow.analyze(jax.make_jaxpr(bad)(jnp.ones((n, 8))), n)
    assert any(
        "window touches the rank dim" in v.reason for v in rep.violations
    )


def test_rankflow_embed_scatter_window_case_clean():
    """The position-embedding-gradient scatter (rank-invariant indices,
    rank riding a window dim of a zeros base) is rank-pointwise — and
    the token-embedding batched gather/scatter too."""
    n = audit.N_RANKS

    def per_rank(table, pos_table, toks, g):
        emb = table[toks] + pos_table[jnp.arange(toks.shape[-1])]
        return jnp.sum(emb * g)

    tab = jnp.zeros((n, 16, 4))
    pos = jnp.zeros((n, 8, 4))
    toks = jnp.zeros((n, 3, 8), jnp.int32)
    g = jnp.zeros((n, 3, 8, 4))
    jx = jax.make_jaxpr(
        jax.vmap(jax.grad(per_rank, argnums=(0, 1)), axis_name="ring")
    )(tab, pos, toks, g)
    rep = rankflow.analyze(jx, n)
    assert rep.violations == [], [
        (v.prim, v.reason) for v in rep.violations
    ]


# --- the declared-kernel registry -------------------------------------------


def test_rankflow_registered_kernel_clean_unregistered_flagged():
    """A pallas_call passes ONLY under a declared signature: the flash
    kernel (registered) audits clean; the same call under an unknown
    kernel name is a violation; a registered kernel whose operand
    carries the rank axis at the wrong dim is a violation too."""
    from jax.experimental import pallas as pl

    n = audit.N_RANKS

    def _fwd_kernel(x_ref, o_ref):  # shadows the registered flash name
        o_ref[...] = x_ref[...] * 2.0

    def _rogue_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def call(kernel, v):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(v)

    x = jnp.ones((n, 8, 128))
    lifted_ok = jax.vmap(lambda v: call(_fwd_kernel, v), axis_name="ring")
    rep = rankflow.analyze(jax.make_jaxpr(lifted_ok)(x), n)
    assert rep.violations == [], [v.reason for v in rep.violations]

    lifted_bad = jax.vmap(lambda v: call(_rogue_kernel, v), axis_name="ring")
    rep2 = rankflow.analyze(jax.make_jaxpr(lifted_bad)(x), n)
    assert any(
        "unregistered pallas kernel '_rogue_kernel'" in v.reason
        for v in rep2.violations
    )

    # registered name, rank axis at the WRONG dim (not the lifted dim)
    def wrong_dim(v):  # rank axis declared at dim 1 via in_axes
        return pl.pallas_call(
            _fwd_kernel,
            out_shape=jax.ShapeDtypeStruct((8, n), jnp.float32),
            interpret=True,
        )(v)

    rep3 = rankflow.analyze(
        jax.make_jaxpr(wrong_dim)(jnp.ones((8, n))), n, in_axes=[1]
    )
    assert any(
        "declared signature lifts at dim" in v.reason
        for v in rep3.violations
    )


def test_kernel_registry_entries_match_sources():
    """Every registry entry names a real module, and the traced-name
    normalization strips vmap's `_batched` suffixes."""
    import os
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name, sig in kernels.REGISTRY.items():
        assert os.path.exists(os.path.join(repo, sig.module)), sig
        assert sig.reviewed, f"{name}: a registration must say WHY"
        with open(os.path.join(repo, sig.module)) as f:
            assert re.search(rf"def {re.escape(name)}\(", f.read()), (
                f"registered kernel {name} not defined in {sig.module}"
            )
    assert kernels.lookup("_fwd_kernel_batched") is not None
    assert kernels.lookup("_fwd_kernel_batched_batched") is not None
    assert kernels.lookup("_nope") is None
    assert kernels.base_name("_dq_kernel_batched") == "_dq_kernel"


# --- the fast tier-1 conv smoke ---------------------------------------------


def test_conv_audit_smoke():
    """ISSUE 12 tier-1 smoke: a tiny conv net (conv-pool-conv-dense at
    12x12) through the FULL lifted train-step audit machinery — so a
    rankflow conv-rule regression fails here in seconds, not only in
    the slow full-matrix tools/audit.py run."""
    import optax

    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.train.state import init_train_state
    from eventgrad_tpu.train.steps import make_train_step

    class TinyConv(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Conv(4, (3, 3), padding="VALID")(x)
            x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
            x = nn.Conv(8, (3, 3), padding="VALID")(x)
            x = nn.relu(x)
            x = x.reshape((x.shape[0], -1))
            return nn.log_softmax(nn.Dense(10)(x), axis=-1)

    topo = Ring(audit.N_RANKS)
    model = TinyConv()
    tx = optax.sgd(0.05)
    cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2)
    state = init_train_state(
        model, (12, 12, 1), tx, topo, "eventgrad", cfg, seed=0
    )
    step = make_train_step(model, tx, topo, "eventgrad", event_cfg=cfg)
    x = jnp.zeros((audit.N_RANKS, 2, 12, 12, 1))
    y = jnp.zeros((audit.N_RANKS, 2), jnp.int32)
    closed = jax.make_jaxpr(spmd(step, topo))(state, (x, y))
    rep = rankflow.analyze(closed, audit.N_RANKS)
    assert rep.violations == [], [
        (v.prim, v.reason) for v in rep.violations
    ]
    assert rep.exchange_offsets() == [-1, 1]
    # ... and the conv oracle fires (one conv cell + one oracle)
    detected, reason = audit.ORACLES["conv_rank_merge"]()
    assert detected, reason


# --- the clean matrix -------------------------------------------------------


@pytest.mark.parametrize("name", [
    pytest.param(c.name, marks=pytest.mark.slow) if c.heavy else c.name
    for c in audit.CONFIGS
])
def test_audit_matrix_config_clean(name):
    """Every cell: zero rank-isolation violations, declared offsets
    only, wire bytes derived == formula == executed metric EXACTLY,
    ravel budget, no callbacks, donation aliasing where checked.
    Heavy cells (ResNet18, flash interpret) ride the slow mark — the
    full matrix runs in tools/audit.py; the fast cells (incl. the
    LeNetCifar conv and full-attention transformer geometries) keep
    rankflow's production rules in tier-1."""
    r = audit.audit_config(audit.config_by_name(name), run_metric=True)
    assert r["violations"] == 0, r["violation_details"]
    assert r["undeclared_offsets"] == [] and r["missing_offsets"] == []
    assert r["wire_problems"] == []
    assert (
        r["wire_bytes_per_neighbor_derived"]
        == r["wire_bytes_per_neighbor_formula"]
    )
    assert r["metric_match"] is True, (
        r["wire_metric_total"], r["wire_bytes_per_neighbor_derived"]
    )
    assert r["ravel_ok"], (r["ravel_count"], r["ravel_budget"])
    assert r["callbacks"] == 0
    assert r["donation_ok"] in (None, True), r["donation_note"]
    assert audit.clean(r)


def test_integrity_checksum_is_a_declared_rider():
    """The integrity checksum ships one int32 per neighbor OUTSIDE the
    wire-byte formula — visible to the auditor, excluded by contract,
    and absent with integrity off."""
    on = audit.audit_config(
        audit.config_by_name("event_masked_f32_arena_integrity"),
        run_metric=False,
    )
    off = audit.audit_config(
        audit.config_by_name("event_masked_f32_arena_obs"),
        run_metric=False,
    )
    assert on["wire_rider_bytes_per_neighbor"] == 4.0
    assert off["wire_rider_bytes_per_neighbor"] == 0.0
    assert (
        on["wire_bytes_per_neighbor_derived"]
        == off["wire_bytes_per_neighbor_derived"]
    )


# --- the oracle legs --------------------------------------------------------


@pytest.mark.parametrize("name", sorted(audit.ORACLES))
def test_oracle_violation_detected(name):
    """Each seeded violation class is flagged — a check that cannot
    fire proves nothing."""
    detected, reason = audit.ORACLES[name]()
    assert detected, f"oracle {name} NOT detected: {reason}"


def test_oracles_leave_no_monkeypatch_behind():
    """The dtype/formula oracles sabotage collectives functions under
    try/finally; a clean config audited afterwards is still clean."""
    audit.ORACLES["wire_dtype_upcast"]()
    audit.ORACLES["byte_formula_drift"]()
    r = audit.audit_config(
        audit.config_by_name("event_masked_bf16_arena"), run_metric=True
    )
    assert audit.clean(r)


# --- the real-mesh lift -----------------------------------------------------


@requires_shard_map
def test_audit_shard_lift_clean():
    """Under the shard_map lift the per-rank collectives stay explicit:
    only ppermutes at the declared offsets (plus axis_index) appear in
    the traced program, and the hygiene checks hold."""
    if len(jax.devices()) < audit.N_RANKS:
        pytest.skip(f"needs {audit.N_RANKS} devices")
    r = audit.audit_shard_lift(audit.config_by_name("event_masked_f32_tree"))
    assert r["offsets_ok"], (r["exchange_offsets"], r["declared_offsets"])
    assert r["undeclared_collectives"] == []
    assert r["callbacks"] == 0


@requires_shard_map
@pytest.mark.parametrize("name", sorted(audit.MESH_ORACLES))
def test_mesh_oracle_violation_detected(name):
    """Each seeded MESH-lift sabotage (undeclared ppermute offset in
    the shard_map program) is detected by `shard_lift_report` — the
    real-mesh auditor can actually fire, not just pass clean cells."""
    if len(jax.devices()) < audit.N_RANKS:
        pytest.skip(f"needs {audit.N_RANKS} devices")
    detected, reason = audit.MESH_ORACLES[name]()
    assert detected, f"mesh oracle {name} NOT detected: {reason}"


@requires_shard_map
def test_audit_shard_lift_conv_clean():
    """The same real-mesh question at CONV geometry (ISSUE 12): the
    LeNetCifar cell's shard_map lift keeps its collectives declared —
    conv batching rewrites are a vmap artifact, so the mesh program
    must show nothing but the ring ppermutes."""
    if len(jax.devices()) < audit.N_RANKS:
        pytest.skip(f"needs {audit.N_RANKS} devices")
    r = audit.audit_shard_lift(
        audit.config_by_name("lenet_masked_f32_arena")
    )
    assert r["offsets_ok"], (r["exchange_offsets"], r["declared_offsets"])
    assert r["undeclared_collectives"] == []
    assert r["callbacks"] == 0
