// eventgrad-tpu native data pipeline.
//
// TPU-native replacement for the reference's C++ data layer: the OpenCV JPEG
// walker + label map of /root/reference/dcifar10/common/custom.hpp:26-122 and
// libtorch's MNIST reader (used at dmnist/cent/cent.cpp:53-56). On TPU the
// only host-side jobs are bulk IO, deterministic shard/shuffle planning, and
// contiguous batch assembly (pixels are augmented on-device); those are
// exactly what this library does, exposed as a C ABI consumed from Python via
// ctypes (no pybind11 in this image).
//
// Everything is deterministic: shuffling uses splitmix64 seeded by
// (seed, epoch), mirroring the reference's per-epoch reshuffle of its path
// list (custom.hpp:119-120) without the hidden global RNG.
//
// Build: `make -C native` (plain g++ -O3 -shared; no external deps).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

extern "C" {

// ---------------------------------------------------------------------------
// deterministic RNG (splitmix64) — stable across platforms, unlike std::mt19937
// usage patterns that depend on distribution implementations.
// ---------------------------------------------------------------------------
static inline uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// ---------------------------------------------------------------------------
// CIFAR-10 binary batches: each record is 1 label byte + 3072 CHW bytes.
// Returns number of samples written, or -1 on IO error.
// Output images are NHWC float32 in [0,1]; labels int32.
// ---------------------------------------------------------------------------
int64_t eg_load_cifar10_file(const char *path, float *images, int32_t *labels,
                             int64_t max_samples) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  const int64_t rec = 1 + 3 * 32 * 32;
  unsigned char buf[1 + 3 * 32 * 32];
  int64_t n = 0;
  const float inv = 1.0f / 255.0f;
  while (n < max_samples && fread(buf, 1, rec, f) == (size_t)rec) {
    labels[n] = (int32_t)buf[0];
    float *out = images + n * 32 * 32 * 3;
    // CHW uint8 -> HWC float
    for (int c = 0; c < 3; ++c) {
      const unsigned char *plane = buf + 1 + c * 32 * 32;
      for (int hw = 0; hw < 32 * 32; ++hw) {
        out[hw * 3 + c] = (float)plane[hw] * inv;
      }
    }
    ++n;
  }
  fclose(f);
  return n;
}

// ---------------------------------------------------------------------------
// MNIST idx files (big-endian headers).
// images path + labels path -> NHWC float32 (normalized if mean/std given).
// Returns sample count or -1.
// ---------------------------------------------------------------------------
static uint32_t be32(const unsigned char *p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

int64_t eg_load_mnist(const char *images_path, const char *labels_path,
                      float *images, int32_t *labels, int64_t max_samples,
                      float mean, float std) {
  FILE *fi = fopen(images_path, "rb");
  if (!fi) return -1;
  unsigned char hdr[16];
  if (fread(hdr, 1, 16, fi) != 16) { fclose(fi); return -1; }
  int64_t n = be32(hdr + 4), rows = be32(hdr + 8), cols = be32(hdr + 12);
  if (n > max_samples) n = max_samples;
  const int64_t px = rows * cols;
  unsigned char *row = new unsigned char[px];
  const float inv = 1.0f / 255.0f;
  const float s = (std > 0.0f) ? (1.0f / std) : 1.0f;
  for (int64_t i = 0; i < n; ++i) {
    if (fread(row, 1, px, fi) != (size_t)px) { n = i; break; }
    float *out = images + i * px;
    for (int64_t j = 0; j < px; ++j)
      out[j] = ((float)row[j] * inv - mean) * s;
  }
  delete[] row;
  fclose(fi);

  FILE *fl = fopen(labels_path, "rb");
  if (!fl) return -1;
  unsigned char lhdr[8];
  if (fread(lhdr, 1, 8, fl) != 8) { fclose(fl); return -1; }
  unsigned char *lab = new unsigned char[n];
  int64_t got = (int64_t)fread(lab, 1, n, fl);
  for (int64_t i = 0; i < got; ++i) labels[i] = (int32_t)lab[i];
  delete[] lab;
  fclose(fl);
  return (got < n) ? got : n;
}

// ---------------------------------------------------------------------------
// Distributed shard plan — the reference's samplers as one call
// (DistributedRandomSampler / DistributedSequentialSampler,
//  cent.cpp:59-60, decent.cpp:81-82): disjoint 1/N shards, optionally a
// global Fisher-Yates permutation reseeded per (seed, epoch).
// out_idx has space for n_ranks * (n / n_ranks) int64s.
// ---------------------------------------------------------------------------
void eg_shard_plan(int64_t n, int64_t n_ranks, uint64_t seed, uint64_t epoch,
                   int shuffle, int64_t *out_idx) {
  const int64_t per = n / n_ranks;
  const int64_t total = per * n_ranks;
  if (!shuffle) {
    for (int64_t i = 0; i < total; ++i) out_idx[i] = i;
    return;
  }
  int64_t *perm = new int64_t[n];
  for (int64_t i = 0; i < n; ++i) perm[i] = i;
  uint64_t st = seed * 0x9E3779B97F4A7C15ULL + epoch + 1;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)(splitmix64(st) % (uint64_t)(i + 1));
    int64_t t = perm[i]; perm[i] = perm[j]; perm[j] = t;
  }
  memcpy(out_idx, perm, total * sizeof(int64_t));
  delete[] perm;
}

// ---------------------------------------------------------------------------
// Batch assembly: gather rows of a contiguous [n, elem] float array into
// [count, elem] following idx — the contiguous-marshalling role the reference
// performs per-tensor with flatten+memcpy (dcifar10/event/event.cpp:292-297),
// applied host-side to sample batches before one device_put.
// ---------------------------------------------------------------------------
void eg_gather(const float *src, int64_t elem, const int64_t *idx,
               int64_t count, float *dst) {
  const size_t bytes = (size_t)elem * sizeof(float);
  for (int64_t i = 0; i < count; ++i)
    memcpy(dst + i * elem, src + idx[i] * elem, bytes);
}

void eg_gather_i32(const int32_t *src, const int64_t *idx, int64_t count,
                   int32_t *dst) {
  for (int64_t i = 0; i < count; ++i) dst[i] = src[idx[i]];
}

int eg_version(void) { return 1; }

}  // extern "C"
