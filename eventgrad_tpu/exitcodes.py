"""Process exit codes shared by the training CLI and the supervisor.

One import-bare module, because the codes are a cross-process CONTRACT:
the child picks one, the supervisor (`eventgrad_tpu.supervise`)
switches on it. Before this module, `INTEGRITY_ABORT_EXIT` lived in
`chaos/integrity.py` and was re-declared by value in `supervise.py`
with only an equality-pin test holding the two together — every new
code would have doubled that debt. Both now import from here
(integrity re-exports its name for compatibility). This file itself
imports nothing; reaching it through the package still runs
`eventgrad_tpu/__init__` like any `python -m eventgrad_tpu.*`
invocation always has.

The vocabulary:

  * 0                    — the run completed; the supervisor is done.
  * ``PREEMPTED_EXIT``   — GRACEFUL PREEMPTION (chaos/crashpoint.py):
    the child saw SIGTERM/SIGINT (or a scheduled ``preempt=`` clause),
    drained the dispatch pipeline at the next block boundary, joined the
    checkpoint writer, force-snapshotted, wrote a ``PREEMPTED`` marker,
    and exited on purpose. The supervisor relaunches IMMEDIATELY with
    ``--resume``: no restart-budget charge, no backoff — preemption is
    the dominant *healthy* exit on spot/preemptible capacity, and at
    most one dispatch block of work is at stake. 75 is sysexits.h
    EX_TEMPFAIL: "temporary failure, retry".
  * ``INTEGRITY_ABORT_EXIT`` — the divergence sentinel tripped beyond
    the rollback budget (chaos/integrity.py): a relaunch would restore
    the same last-known-good snapshot and replay the same divergence,
    so the supervisor gives up WITHOUT a restart.
  * ``CRASHPOINT_EXIT``  — an armed ``EG_CRASHPOINT`` site fired
    (chaos/crashpoint.py): the process killed itself mid-mutation on
    purpose, simulating a hard kill for the crash-consistency matrix
    (tools/crash_matrix.py). Distinct from any organic failure so the
    matrix can verify the kill landed at the armed site and nowhere
    else.
  * anything else nonzero — a crash; the supervisor restarts from the
    latest snapshot under its sliding budget + backoff.
"""

#: graceful preemption: the child drained, snapshotted, and exited on
#: purpose — relaunch immediately, charge nothing (EX_TEMPFAIL)
PREEMPTED_EXIT = 75

#: integrity engine gave up (divergence sentinel beyond max_rollbacks):
#: permanent — restarting would replay the same divergence
INTEGRITY_ABORT_EXIT = 77

#: an armed EG_CRASHPOINT site killed the process on purpose (the
#: crash-consistency matrix's seeded kill)
CRASHPOINT_EXIT = 83

#: name table for logs/docs (docs/chaos.md "Preemption & crash
#: consistency" mirrors it)
EXIT_CODE_NAMES = {
    PREEMPTED_EXIT: "PREEMPTED",
    INTEGRITY_ABORT_EXIT: "INTEGRITY_ABORT",
    CRASHPOINT_EXIT: "CRASHPOINT",
}
