"""Analytic per-step cost model: FLOPs + HBM bytes from the jaxpr, split
by phase, combined with measured step time into MFU and a roofline point.

The repo's only hardware-efficiency number used to be the XLA
`cost_analysis()` FLOP count bench.py computes on TPU — opaque
(backend-dependent, unavailable on the CPU tiers) and unattributable (one
scalar for the whole step). This module walks the traced step's jaxpr
with the SAME nested traversal the trace auditor uses
(`analysis/walker.sub_jaxprs` — one walker repo-wide, so the cost model
and the wire-byte audit read one program) and counts, per primitive:

  * FLOPs — `dot_general` and `conv_general_dilated` exactly from shapes
    (2·B·M·N·K; 2·out·C_in/g·prod(kernel)), elementwise arithmetic and
    reductions as one FLOP per operand/output element, pure data movement
    (gather/select/reshape/convert/compares) as zero.
  * HBM bytes — operand + result bytes of every equation: the NO-FUSION
    traffic ceiling. XLA fuses aggressively, so the true traffic is
    lower; the ceiling is stable across rounds (it depends only on the
    traced program), which is exactly what a regression ledger needs.
    `compiled_memory()` reports the backend's own peak-memory analysis
    next to it when available.

Phase attribution rides `jax.named_scope` annotations
(`phase_scope("grad"|"gate_pack"|"exchange"|"commit_mix")` — the hooks
live in train/steps.py; per-bucket scopes are "<phase>.b<k>" under the
bucketed gossip schedule). Scope names survive vmap lifting AND vjp
transposition in equation name stacks, so backward-pass work lands in
the phase whose forward region produced it. Unannotated equations count
under "other". Annotations are metadata only — the traced computation is
bitwise identical with them disabled (EG_PHASE_SCOPES=0 /
`annotations_disabled()`; regression-tested in tests/test_costmodel.py).

`roofline()` turns (FLOPs, bytes, measured step seconds) plus an
`obs.devicespec.DeviceSpec` into MFU, achieved bytes/s, arithmetic
intensity, and the compute/memory verdict. `compile_timed()` records the
trace/lower/compile/first-dispatch wall spans into an `obs.Registry`.

Scan bodies multiply their equation counts by the scan length; `while`
trip counts are unknowable statically — their bodies count ONCE and the
result carries `unbounded_loops` so a consumer can see the caveat.
"""

from __future__ import annotations

import contextlib
import math
import os
import re
from typing import Any, Dict, Optional

from eventgrad_tpu.obs.devicespec import DeviceSpec

# --- phase annotation hooks (train/steps.py wraps its regions) -------------

#: named-scope prefix the cost model recognizes; everything else in a
#: name stack (vmap/transpose wrappers, user scopes) is ignored
PHASE_PREFIX = "egphase."

#: the canonical step phases, in pipeline order (docs/OBSERVABILITY.md
#: "Reading the roofline"); "other" absorbs unannotated equations
PHASES = ("grad", "gate_pack", "exchange", "commit_mix", "other")

_PHASE_RE = re.compile(r"egphase\.([a-z_]+)(?:\.b(\d+))?")

_annotations_on = os.environ.get("EG_PHASE_SCOPES", "1") != "0"


def annotations_enabled() -> bool:
    return _annotations_on


@contextlib.contextmanager
def annotations_disabled():
    """Trace with phase scopes off — the pre-annotation program, for the
    bitwise-equivalence regression test."""
    global _annotations_on
    prev, _annotations_on = _annotations_on, False
    try:
        yield
    finally:
        _annotations_on = prev


def phase_scope(name: str):
    """`jax.named_scope(PHASE_PREFIX + name)` — or a no-op context when
    annotations are disabled. Purely trace-time metadata: never changes
    the computation, only the name stacks the cost model reads."""
    if not _annotations_on:
        return contextlib.nullcontext()
    import jax

    return jax.named_scope(PHASE_PREFIX + name)


def phase_of(eqn) -> str:
    """Full phase label of an equation ("grad", "exchange.b2", ... or
    "other") from its source-info name stack."""
    m = _PHASE_RE.search(str(eqn.source_info.name_stack))
    if not m:
        return "other"
    return m.group(1) if m.group(2) is None else f"{m.group(1)}.b{m.group(2)}"


# --- per-primitive FLOP rules ----------------------------------------------

#: one FLOP per OUTPUT element
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "neg", "abs", "sign",
    "pow", "integer_pow", "exp", "exp2", "expm1", "log", "log1p", "sqrt",
    "rsqrt", "cbrt", "tanh", "sin", "cos", "tan", "atan2", "erf", "erfc",
    "erf_inv", "logistic", "floor", "ceil", "round", "nextafter",
    "square",
})

#: one FLOP per INPUT element (tree reductions / scans over the operand)
_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "cumsum", "cumprod", "cummax",
    "cummin", "cumlogsumexp", "reduce_precision",
})

#: equations owning sub-jaxprs whose own operands must not be charged
#: (their bodies are walked instead — charging the boundary would double
#: count every byte the inner equations already account)
_CONTAINERS = frozenset({
    "pjit", "jit", "xla_call", "closed_call", "core_call", "remat",
    "remat2", "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "scan", "while",
    "cond", "custom_vjp_call_custom_transpose",
})


def _aval_bytes(v) -> float:
    aval = getattr(v, "aval", None)
    dtype = getattr(aval, "dtype", None)
    if aval is None or dtype is None:
        return 0.0
    return float(aval.size) * float(dtype.itemsize)


def _dot_flops(eqn) -> float:
    """2·B·M·N·K from the dot_general dimension numbers — exact."""
    (lhs_c, rhs_c), (lhs_b, rhs_b) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = math.prod(lhs[d] for d in lhs_b)
    contract = math.prod(lhs[d] for d in lhs_c)
    m = math.prod(
        d for i, d in enumerate(lhs) if i not in lhs_b and i not in lhs_c
    )
    n = math.prod(
        d for i, d in enumerate(rhs) if i not in rhs_b and i not in rhs_c
    )
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    """2 · out_elements · (C_in / feature_groups) · prod(kernel spatial) —
    exact for the conv as traced (forward convs AND the transposed convs
    the backward pass emits are each counted from their own shapes)."""
    dn = eqn.params["dimension_numbers"]
    rhs_spec = dn.rhs_spec  # (out_ch, in_ch/g, *spatial)
    rhs = eqn.invars[1].aval.shape
    out_elems = math.prod(eqn.outvars[0].aval.shape)
    in_ch_per_group = rhs[rhs_spec[1]]
    kernel_spatial = math.prod(rhs[d] for d in rhs_spec[2:])
    return 2.0 * out_elems * in_ch_per_group * kernel_spatial


def _eqn_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE:
        return float(eqn.outvars[0].aval.size)
    if name in _REDUCTIONS:
        return float(eqn.invars[0].aval.size)
    return 0.0


# --- the jaxpr walk --------------------------------------------------------


def analyze_jaxpr(jaxpr) -> Dict[str, Any]:
    """Cost model of a (Closed)Jaxpr: totals, per-phase split, and the
    dot/conv/elementwise decomposition the oracle tests pin.

    Returns
      flops_total / hbm_bytes_total      — whole-program analytic counts
      by_phase                           — {base phase: {flops, hbm_bytes}}
                                           (bucket scopes fold into their
                                           base phase here)
      phases                             — the full-label split, buckets
                                           separate ("exchange.b0", ...)
      dot_flops / conv_flops / eltwise_flops — per-rule totals
      n_eqns, unbounded_loops            — walk stats / while-loop caveat
    """
    from eventgrad_tpu.analysis import walker

    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr

    phases: Dict[str, Dict[str, float]] = {}
    out = {
        "flops_total": 0.0, "hbm_bytes_total": 0.0,
        "dot_flops": 0.0, "conv_flops": 0.0, "eltwise_flops": 0.0,
        "n_eqns": 0, "unbounded_loops": 0,
    }

    def _walk(jx, mult: float):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            subs = list(walker.sub_jaxprs(eqn))
            if subs:
                sub_mult = mult
                if name == "scan":
                    sub_mult = mult * float(eqn.params.get("length", 1))
                elif name == "while":
                    out["unbounded_loops"] += 1
                if name in _CONTAINERS or name in ("scan", "while", "cond"):
                    for sub in subs:
                        _walk(sub, sub_mult)
                    continue
                # unknown primitive carrying a jaxpr: walk it AND fall
                # through to charge its own boundary conservatively
                for sub in subs:
                    _walk(sub, sub_mult)
            flops = _eqn_flops(eqn) * mult
            in_bytes = sum(_aval_bytes(v) for v in eqn.invars)
            out_bytes = sum(_aval_bytes(v) for v in eqn.outvars)
            bytes_ = (in_bytes + out_bytes) * mult
            out["n_eqns"] += 1
            out["flops_total"] += flops
            out["hbm_bytes_total"] += bytes_
            if name == "dot_general":
                out["dot_flops"] += flops
            elif name == "conv_general_dilated":
                out["conv_flops"] += flops
            elif name in _ELEMENTWISE:
                out["eltwise_flops"] += flops
            label = phase_of(eqn)
            slot = phases.setdefault(label, {"flops": 0.0, "hbm_bytes": 0.0})
            slot["flops"] += flops
            slot["hbm_bytes"] += bytes_

    _walk(jaxpr, 1.0)

    by_phase = {p: {"flops": 0.0, "hbm_bytes": 0.0} for p in PHASES}
    for label, slot in phases.items():
        base = label.split(".")[0]
        tgt = by_phase.setdefault(base, {"flops": 0.0, "hbm_bytes": 0.0})
        tgt["flops"] += slot["flops"]
        tgt["hbm_bytes"] += slot["hbm_bytes"]
    out["phases"] = phases
    out["by_phase"] = by_phase
    return out


def analyze_step(model, tx, topo, algo, event_cfg, x, y, per_rank: int,
                 state, **step_kwargs) -> Dict[str, Any]:
    """Cost model of one full train step (all vmap-ranks) at this
    op-point — trace only, nothing compiles or executes. Mirrors
    `utils.flops.train_step_flops`'s construction exactly so the analytic
    numbers describe the same program the XLA cost analysis measures."""
    import jax
    import jax.numpy as jnp

    from eventgrad_tpu.parallel.spmd import spmd
    from eventgrad_tpu.train.steps import make_train_step
    from eventgrad_tpu.utils.flops import step_layout_kwargs

    # the traced step's buffer layout must match the state's (a tree
    # step cannot consume an arena state) — auto-detect unless the
    # caller pinned the layout explicitly
    for k, v in step_layout_kwargs(state).items():
        step_kwargs.setdefault(k, v)
    step = make_train_step(
        model, tx, topo, algo, event_cfg=event_cfg, **step_kwargs
    )
    xb = jnp.asarray(x[: topo.n_ranks * per_rank]).reshape(
        (topo.n_ranks, per_rank) + x.shape[1:]
    )
    yb = jnp.asarray(y[: topo.n_ranks * per_rank]).reshape(
        (topo.n_ranks, per_rank)
    )
    jaxpr = jax.make_jaxpr(spmd(step, topo))(state, (xb, yb))
    return analyze_jaxpr(jaxpr)


# --- roofline accounting ---------------------------------------------------


def roofline(flops: float, hbm_bytes: float, step_s: float,
             spec: DeviceSpec) -> Dict[str, Any]:
    """MFU + roofline position of `flops`/`hbm_bytes` of work observed to
    take `step_s` seconds on a device with `spec` peaks.

    `roofline_frac` is achieved FLOP/s over the roofline CEILING at this
    arithmetic intensity — min(peak_flops, intensity · peak_bw) — i.e.
    "how close to the attainable line", which is the honest utilization
    number for memory-bound kernels where MFU alone reads unfairly low.
    """
    if not (flops and step_s):
        return {
            "mfu": None, "achieved_flops_per_s": None,
            "achieved_bytes_per_s": None, "arithmetic_intensity": None,
            "ridge_intensity": spec.ridge_intensity,
            "roofline_bound": None, "roofline_frac": None,
            "device_spec": spec.name, "nominal_spec": spec.nominal,
            "peak_flops": spec.peak_flops,
            "peak_hbm_bytes_per_s": spec.peak_hbm_bytes_per_s,
        }
    achieved_f = flops / step_s
    achieved_b = (hbm_bytes / step_s) if hbm_bytes else None
    intensity = (flops / hbm_bytes) if hbm_bytes else None
    ridge = spec.ridge_intensity
    bound = None
    ceiling = spec.peak_flops
    if intensity is not None:
        bound = "compute" if intensity >= ridge else "memory"
        ceiling = min(spec.peak_flops, intensity * spec.peak_hbm_bytes_per_s)
    return {
        "mfu": achieved_f / spec.peak_flops,
        "achieved_flops_per_s": achieved_f,
        "achieved_bytes_per_s": achieved_b,
        "arithmetic_intensity": intensity,
        "ridge_intensity": ridge,
        "roofline_bound": bound,
        "roofline_frac": achieved_f / ceiling if ceiling else None,
        "device_spec": spec.name,
        "nominal_spec": spec.nominal,
        "peak_flops": spec.peak_flops,
        "peak_hbm_bytes_per_s": spec.peak_hbm_bytes_per_s,
    }


def record_block(cm: Dict[str, Any], rl: Dict[str, Any]) -> Dict[str, Any]:
    """The `costmodel` block bench.py and tools/tpu_flagship.py attach
    to their records — ONE definition (obs/schema.py PERF_FIELDS names
    the fields), so the two surfaces can never drift apart."""
    return {
        "flops_per_step": cm["flops_total"],
        "hbm_bytes_per_step": cm["hbm_bytes_total"],
        "flops_by_phase": {
            k: round(v["flops"]) for k, v in cm["by_phase"].items()
        },
        "hbm_bytes_by_phase": {
            k: round(v["hbm_bytes"]) for k, v in cm["by_phase"].items()
        },
        "mfu": round(rl["mfu"], 6) if rl["mfu"] is not None else None,
        "achieved_flops_per_s": rl["achieved_flops_per_s"],
        "achieved_bytes_per_s": rl["achieved_bytes_per_s"],
        "arithmetic_intensity": rl["arithmetic_intensity"],
        "ridge_intensity": rl["ridge_intensity"],
        "roofline_bound": rl["roofline_bound"],
        "roofline_frac": rl["roofline_frac"],
        "device_spec": rl["device_spec"],
        "nominal_spec": rl["nominal_spec"],
    }


# --- compiled-program facts (backend-reported, not analytic) ---------------


def compiled_memory(compiled) -> Optional[Dict[str, float]]:
    """The backend's own memory analysis of a compiled executable
    (argument/output/temp/code bytes + their peak sum), or None where the
    backend doesn't report one (some CPU builds)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: Dict[str, float] = {}
    for attr in (
        "argument_size_in_bytes", "output_size_in_bytes",
        "temp_size_in_bytes", "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        v = getattr(ma, attr, None)
        if v is not None:
            out[attr] = float(v)
    if not out:
        return None
    out["peak_bytes"] = (
        out.get("argument_size_in_bytes", 0.0)
        + out.get("output_size_in_bytes", 0.0)
        + out.get("temp_size_in_bytes", 0.0)
        - out.get("alias_size_in_bytes", 0.0)
    )
    return out


def compile_timed(fn, *args, registry=None, label: str = "step"):
    """Trace, lower, compile, and first-dispatch `fn(*args)`, recording
    one span per stage ("compile_trace" / "compile_lower" /
    "compile_compile" / "first_dispatch", cat="compile") into `registry`
    when given. Returns (compiled, {stage: seconds}, memory) where
    `memory` is `compiled_memory(compiled)`.

    The lower stage re-traces internally (jax's `.lower()` has no
    public trace-only entry in this version), so compile_trace measures a
    `make_jaxpr` of the same call — the honest per-stage approximation,
    documented here rather than hidden."""
    import time

    import jax

    spans: Dict[str, float] = {}

    @contextlib.contextmanager
    def stage(name):
        cm = (
            registry.span(name, cat="compile", label=label)
            if registry is not None else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with cm:
            yield
        spans[name] = time.perf_counter() - t0

    with stage("compile_trace"):
        jax.make_jaxpr(fn)(*args)
    jitted = jax.jit(fn)
    with stage("compile_lower"):
        lowered = jitted.lower(*args)
    with stage("compile_compile"):
        compiled = lowered.compile()
    with stage("first_dispatch"):
        out = compiled(*args)
        jax.block_until_ready(out)
    return compiled, spans, compiled_memory(compiled)
