"""Elastic recovery: the supervisor detects crashes and hangs, restarts
from the latest snapshot, and the recovered run finishes the job with the
exact trajectory of an uninterrupted one. (The reference has no failure
story: a dead rank blocks its peers' MPI_Recv forever, decent.cpp:200-205.)"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli_args(tmp, tag, extra):
    return [
        "--algo", "eventgrad", "--mesh", "ring:4", "--dataset", "synthetic",
        "--model", "mlp", "--epochs", "3", "--batch-size", "8",
        "--n-synth", "128", "--warmup-passes", "2",
        "--log-file", os.path.join(tmp, f"{tag}.jsonl"),
    ] + extra


def _run_supervised(tmp, tag, extra, timeout=0.0, max_restarts=3,
                    cli_args=None):
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    cmd = [
        sys.executable, "-m", "eventgrad_tpu.supervise",
        "--timeout", str(timeout), "--max-restarts", str(max_restarts), "--",
    ] + (cli_args if cli_args is not None else _cli_args(tmp, tag, extra))
    return subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=600
    )


def _records(tmp, tag):
    with open(os.path.join(tmp, f"{tag}.jsonl")) as f:
        return [json.loads(l) for l in f]


def test_crash_recovery_matches_uninterrupted_run(tmp_path):
    tmp = str(tmp_path)
    ck = os.path.join(tmp, "ck")

    straight = _run_supervised(tmp, "straight", ["--checkpoint-dir",
                                                 os.path.join(tmp, "ck0"),
                                                 "--save-every", "1"])
    assert straight.returncode == 0, straight.stderr[-2000:]

    # crash:1 kills the child (exit 13) right after epoch 1's snapshot; the
    # supervisor must relaunch with --resume and epochs 2-3 must replay the
    # uninterrupted trajectory exactly
    crashed = _run_supervised(
        tmp, "crashed",
        ["--checkpoint-dir", ck, "--save-every", "1",
         "--fault-inject", "crash:1"],
    )
    assert crashed.returncode == 0, crashed.stderr[-2000:]
    assert "attempt 1 failed (exit code 13)" in crashed.stderr

    ref = _records(tmp, "straight")
    got = _records(tmp, "crashed")
    # log has epoch 1 (first attempt) then epochs 2,3 + final (second)
    assert [r.get("epoch") for r in got] == [1, 2, 3, None]
    by_epoch = {r["epoch"]: r for r in ref if "epoch" in r}
    for r in got[:-1]:
        np.testing.assert_allclose(r["loss"], by_epoch[r["epoch"]]["loss"],
                                   atol=1e-6)
        assert r["num_events"] == by_epoch[r["epoch"]]["num_events"]
    assert got[-1]["final"] and ref[-1]["final"]
    np.testing.assert_allclose(got[-1]["accuracy"], ref[-1]["accuracy"],
                               atol=1e-6)


def test_hang_detection_kills_and_recovers(tmp_path):
    tmp = str(tmp_path)
    hung = _run_supervised(
        tmp, "hung",
        ["--checkpoint-dir", os.path.join(tmp, "ck"), "--save-every", "1",
         "--fault-inject", "hang:1"],
        timeout=45.0, max_restarts=1,
    )
    assert hung.returncode == 0, hung.stderr[-2000:]
    assert "no heartbeat" in hung.stderr
    recs = _records(tmp, "hung")
    assert [r.get("epoch") for r in recs] == [1, 2, 3, None]


def test_supervisor_gives_up_after_max_restarts(tmp_path):
    tmp = str(tmp_path)
    # no periodic snapshots -> the resumed run restarts at epoch 1 and hits
    # the same crash every attempt: the supervisor must stop trying
    doomed = _run_supervised(
        tmp, "doomed",
        ["--checkpoint-dir", os.path.join(tmp, "ck"),
         "--fault-inject", "crash:1"],
        max_restarts=1,
    )
    assert doomed.returncode == 13
    assert "giving up" in doomed.stderr


def test_supervisor_requires_checkpoint_dir(tmp_path):
    with pytest.raises(SystemExit):
        from eventgrad_tpu.supervise import supervise

        supervise(["--algo", "dpsgd"])


def test_crash_recovery_hybrid_lm(tmp_path):
    """Elastic recovery composes with hybrid meshes: a dp x sp
    ring-attention LM run crash-injected after epoch 1 is restarted from
    its snapshot and replays the uninterrupted trajectory exactly."""
    tmp = str(tmp_path)

    def go(tag, extra):
        lm_args = [
            "--algo", "eventgrad", "--mesh", "dp:2,sp:2",
            "--model", "transformer", "--attn", "ring",
            "--seq-len", "32", "--vocab", "64", "--dim", "32",
            "--heads", "4", "--layers", "1", "--epochs", "3",
            "--batch-size", "4", "--n-synth", "64", "--lr", "0.1",
            "--warmup-passes", "2",
            "--log-file", os.path.join(tmp, f"{tag}.jsonl"),
        ] + extra
        return _run_supervised(tmp, tag, [], cli_args=lm_args)

    straight = go("straight", ["--checkpoint-dir", os.path.join(tmp, "ck0"),
                               "--save-every", "1"])
    assert straight.returncode == 0, straight.stderr[-2000:]
    crashed = go("crashed", ["--checkpoint-dir", os.path.join(tmp, "ck1"),
                             "--save-every", "1", "--fault-inject", "crash:1"])
    assert crashed.returncode == 0, crashed.stderr[-2000:]
    # the injection must actually have fired and the supervisor restarted
    assert "attempt 1 failed (exit code 13)" in crashed.stderr

    s = [r for r in _records(tmp, "straight") if "epoch" in r]
    c = [r for r in _records(tmp, "crashed") if "epoch" in r]
    assert [r["epoch"] for r in s] == [1, 2, 3]
    assert [r["epoch"] for r in c] == [1, 2, 3]
    for rs, rc in zip(s, c):
        assert rs["num_events"] == rc["num_events"]
        np.testing.assert_allclose(rs["loss"], rc["loss"], atol=1e-6)
