"""Top-k sparsified EventGraD payloads (the reference's `spevent`).

Rebuild of /root/reference/dcifar10/spevent/spevent.cpp:

  * fixed per-parameter k: k_i = ceil(topk_percent/100 * numel_i)
    (spevent.cpp:144-150) — static under jit, so payload shapes never change.
  * selection metric |p − prev_sent| (:344-346), `jax.lax.top_k` replaces
    torch::topk (:349-351); values sent are the *current* parameter at those
    indices (:360-363).
  * sender shadow `prev_sent` updates only at sent indices (:406-413).
  * receiver keeps a persistent full replica per neighbor and scatters the
    (value, index) payload into it (:438-448, :491-502) — unsent coordinates
    retain their last-known values, which is what makes sparsification sound.
  * indices travel as int32 lanes (the reference float-encodes them into the
    float window, :351-357 — a wire-format artifact, not semantics; byte
    accounting in metrics counts 4 bytes/lane either way).

Deviation from the reference, by design: the reference initializes
prev/left/right shadow models as *freshly constructed randomly-initialized
networks* (spevent.cpp:129-136 — the RNG has advanced past the main model's
init), so early averaging mixes in random junk. Here all shadows start as a
copy of the initial parameters, equivalent to one full synchronization at
step 0; with identical cross-rank seeds this is exact and strictly better
conditioned.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from eventgrad_tpu.parallel.topology import Topology
from eventgrad_tpu.parallel import collectives
# selection/scatter live with the topk TriggerPolicy now
# (parallel/policy.py); this module is the wire adapter over them
from eventgrad_tpu.parallel.policy import scatter_into, topk_payload


@dataclasses.dataclass(frozen=True)
class SparseConfig:
    """topk_percent is the reference's argv[4] (spevent.cpp:60)."""

    topk_percent: float = 10.0

    def k_for(self, numel: int) -> int:
        k = int(math.ceil(self.topk_percent / 100.0 * numel))
        return max(1, min(k, numel))


class SparseState(struct.PyTreeNode):
    """prev_sent: sender shadow of last-transmitted values (spevent.cpp:128-131).
    replicas: per-neighbor persistent full-model replicas (:133-136).
    pending: bounded-async delivery queues (staleness >= 2 only; None
    under lockstep/delayed gossip) — per neighbor, `staleness` slots of
    decoded `(vals, idxs, fire)` payload trees. Slot 0 commits into the
    replicas at the start of the NEXT exchange (commit-on-arrival, the
    EventState.pending discipline); sp composes with D >= 2 but not
    chaos lag clauses, so every payload enqueues at slot 0 and slots
    >= 1 only pad the runway. The slot index is the 2nd path component
    (`state/sparse/pending/{i}/{d}/...`), which is what lets checkpoint
    restore sniff the queue depth and refuse a cross-D resume."""

    prev_sent: Any
    replicas: Tuple[Any, ...]
    pending: Any = None

    @classmethod
    def init(
        cls, params: Any, topo: Topology, cfg: "SparseConfig" = None,
        staleness: int = 0,
    ) -> "SparseState":
        copy = jax.tree.map(lambda x: x, params)
        pending = None
        if staleness >= 2:
            if cfg is None:
                raise ValueError(
                    "SparseState.init: staleness >= 2 needs cfg= — the "
                    "queued payload shapes depend on topk_percent"
                )
            zv = jax.tree.map(
                lambda x: jnp.zeros((cfg.k_for(x.size),), x.dtype), params
            )
            zi = jax.tree.map(
                lambda x: jnp.zeros((cfg.k_for(x.size),), jnp.int32), params
            )
            zf = jax.tree.map(lambda x: jnp.zeros((), bool), params)
            slot = (zv, zi, zf)
            pending = tuple(
                tuple(slot for _ in range(int(staleness)))
                for _ in topo.neighbors
            )
        return cls(
            prev_sent=copy,
            replicas=tuple(jax.tree.map(lambda x: x, params) for _ in topo.neighbors),
            pending=pending,
        )


def sparse_exchange(
    params: Any,
    fire: Any,
    sp: SparseState,
    topo: Topology,
    cfg: SparseConfig,
    wire=None,
    buckets=None,
    staleness: int = 0,
) -> SparseState:
    """One step of sparsified gossip: build top-k payloads, ship them to every
    neighbor (masked — receivers apply only when the sender fired), update the
    sender shadow and the neighbor replicas. Returns the new SparseState; the
    caller then mixes `params` with `sp.replicas` (spevent.cpp:539-542).
    `wire` ("bf16"/"int8") compresses the top-k *values* for the transfer;
    indices stay int32. The sender shadow keeps full precision.

    `buckets` (a tuple of parallel/arena.py BucketSpec, the bucketed
    gossip schedule) groups the per-leaf exchange by leaf-aligned
    buckets with pipelined emission: bucket b's lanes ship before bucket
    b-1's replica scatters are emitted, so XLA's scheduler is free to
    overlap one bucket's exchange with another's commit work. Every op
    is per-leaf either way — the result is bitwise the monolithic call
    (tests/test_bucketed.py); the state layout is unchanged.

    `staleness` >= 2 turns on the bounded-async payload queue: this
    pass's received payloads land in `sp.pending` slot 0 instead of the
    replicas, and the slot-0 payload enqueued LAST pass commits into the
    replicas first (commit-on-arrival). The caller then mixes the
    post-exchange replicas directly — payloads from passes <= p-1, i.e.
    bitwise the staleness=1 stale-replica mix, which is the D=2-at-
    baseline-lag ≡ D=1 pin. sp never composes with chaos lag clauses,
    so the deeper slots are runway, never occupied."""
    vals, idxs = topk_payload(params, sp.prev_sent, cfg)

    new_prev = scatter_into(sp.prev_sent, vals, idxs, fire)

    if wire == "int8":
        q, scale_vec, _ = collectives._int8_encode(vals)
    else:
        q, scale_vec = collectives._wire_out(vals, wire), None

    def _decode(got_vals, got_s, like_vals):
        if wire == "int8":
            # bucket-local scale trees decode with their own treedef —
            # per-leaf scales are bucket-invariant, so the values match
            # the monolithic decode bitwise
            return collectives._int8_dequant(
                got_vals,
                jax.tree.unflatten(
                    jax.tree.structure(like_vals),
                    [got_s[i] for i in range(got_s.shape[0])],
                ),
                like_vals,
            )
        return collectives._wire_in(got_vals, like_vals)

    if buckets is None:
        new_replicas = []
        new_pending = [] if staleness >= 2 else sp.pending
        for ni, (nb, replica) in enumerate(zip(topo.neighbors, sp.replicas)):
            got_vals, got_s, got_idxs, got_fire = collectives.recv_from(
                (q, scale_vec, idxs, fire), topo, nb
            )
            got_vals = _decode(got_vals, got_s, vals)
            if staleness >= 2:
                # commit-on-arrival: LAST pass's slot-0 payload lands in
                # the replica; this pass's payload takes its place (lag
                # is always 1 here — sp x chaos stays refused upstream)
                v0, i0, f0 = sp.pending[ni][0]
                new_replicas.append(scatter_into(replica, v0, i0, f0))
                new_pending.append(
                    ((got_vals, got_idxs, got_fire),)
                    + tuple(sp.pending[ni][1:])
                )
            else:
                new_replicas.append(
                    scatter_into(replica, got_vals, got_idxs, got_fire)
                )
        if staleness >= 2:
            new_pending = tuple(new_pending)
        return sp.replace(
            prev_sent=new_prev, replicas=tuple(new_replicas),
            pending=new_pending,
        )

    # bucketed: leaf-sliced lanes per bucket, shipped with pipelined
    # emission (ship b, scatter b-1, ship b+1, ...)
    def _leaves(tree):
        return jax.tree.flatten(tree)[0]

    v_l, i_l, f_l = _leaves(vals), _leaves(idxs), _leaves(fire)
    q_l = _leaves(q)
    r_l = [_leaves(r) for r in sp.replicas]  # [n_nb][L]
    n_nb = len(topo.neighbors)
    B = len(buckets)
    L = len(v_l)
    shipped = [None] * B   # per bucket: per-neighbor received lane lists
    out_l = [list(rl) for rl in r_l]
    # queue mode: the replicas receive slot 0's payload (full-leaf
    # lanes, sliced per bucket inside the same pipelined commit tails);
    # this pass's received lanes assemble into the new slot 0
    queue = staleness >= 2
    if queue:
        p0_v = [_leaves(sp.pending[ni][0][0]) for ni in range(n_nb)]
        p0_i = [_leaves(sp.pending[ni][0][1]) for ni in range(n_nb)]
        p0_f = [_leaves(sp.pending[ni][0][2]) for ni in range(n_nb)]
        recv_v = [[None] * L for _ in range(n_nb)]
        recv_i = [[None] * L for _ in range(n_nb)]
        recv_f = [[None] * L for _ in range(n_nb)]

    def _ship(bi):
        b = buckets[bi]
        lanes = (
            tuple(q_l[b.lo:b.hi]),
            (scale_vec[b.lo:b.hi] if scale_vec is not None else None),
            tuple(i_l[b.lo:b.hi]),
            tuple(f_l[b.lo:b.hi]),
        )
        shipped[bi] = [
            collectives.recv_from(lanes, topo, nb) for nb in topo.neighbors
        ]

    def _scatter(ni, ks, gv, gi, gf):
        for j, k in enumerate(ks):
            scattered = (
                out_l[ni][k].reshape(-1).at[gi[j]]
                .set(gv[j]).reshape(out_l[ni][k].shape)
            )
            out_l[ni][k] = jnp.where(gf[j], scattered, out_l[ni][k])

    def _commit(bi):
        b = buckets[bi]
        like = tuple(v_l[b.lo:b.hi])
        ks = range(b.lo, b.hi)
        for ni in range(n_nb):
            got_q, got_s, got_idxs, got_fire = shipped[bi][ni]
            got_vals = _decode(got_q, got_s, like)
            if queue:
                for j, k in enumerate(ks):
                    recv_v[ni][k] = got_vals[j]
                    recv_i[ni][k] = got_idxs[j]
                    recv_f[ni][k] = got_fire[j]
                _scatter(
                    ni, ks,
                    [p0_v[ni][k] for k in ks],
                    [p0_i[ni][k] for k in ks],
                    [p0_f[ni][k] for k in ks],
                )
            else:
                _scatter(ni, ks, got_vals, got_idxs, got_fire)

    _ship(0)
    for bi in range(1, B):
        _ship(bi)
        _commit(bi - 1)
    _commit(B - 1)

    rep_def = jax.tree.structure(sp.replicas[0])
    new_replicas = tuple(
        jax.tree.unflatten(rep_def, out_l[ni])
        for ni in range(n_nb)
    )
    new_pending = sp.pending
    if queue:
        vdef = jax.tree.structure(vals)
        new_pending = tuple(
            (
                (
                    jax.tree.unflatten(vdef, recv_v[ni]),
                    jax.tree.unflatten(vdef, recv_i[ni]),
                    jax.tree.unflatten(vdef, recv_f[ni]),
                ),
            ) + tuple(sp.pending[ni][1:])
            for ni in range(n_nb)
        )
    return sp.replace(
        prev_sent=new_prev, replicas=tuple(new_replicas),
        pending=new_pending,
    )
