"""Hierarchical data parallelism: a "ddp" axis forms synchronous allreduce
subgroups inside each gossip rank — gossip across pods, allreduce within a
pod. Ranks along ddp hold identical parameters (gradients pmean like any
aux axis) but shard the DATA, so a (dp, ddp) mesh is numerically a dp-ring
whose per-rank batch is the concatenation of its ddp shards."""

import json

import jax
import numpy as np
import pytest

from eventgrad_tpu.cli import main, parse_mesh
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring, Topology
from eventgrad_tpu.train.loop import train


def test_parse_mesh_ddp():
    t = parse_mesh("dp:2,ddp:4")
    assert t.gossip_axes == ("dp",) and t.aux_axes == ("ddp",)
    assert t.data_axes == ("dp", "ddp") and t.n_data_ranks == 8
    assert not t.sharded_axes


def test_ddp_group_equals_bigger_batch_ring():
    """dpsgd on dp:2,ddp:2 with per-rank batch B must match Ring(2) with
    per-rank batch 2B exactly: the ddp gradient pmean is the mean over the
    concatenated shards (cross-entropy is a mean). One full-shard step per
    epoch makes the sample groupings identical between the two layouts
    (with several steps per epoch they'd cover the data in different
    per-step groupings)."""
    x, y = synthetic_dataset(128, (28, 28, 1), seed=8)
    kw = dict(algo="dpsgd", epochs=2, learning_rate=0.05, seed=2,
              log_every_epoch=False)
    topo_h = Topology(axes=("dp", "ddp"), shape=(2, 2), gossip_axes=("dp",),
                      data_aux_axes=("ddp",))
    s_h, h_h = train(MLP(), topo_h, x, y, batch_size=32, **kw)
    s_r, h_r = train(MLP(), Ring(2), x, y, batch_size=64, **kw)

    # dp rank i's params live at stacked indices (2i, 2i+1) — identical
    # across the ddp pair, equal to the plain ring's rank i
    ph = jax.tree.map(np.asarray, s_h.params)
    pr = jax.tree.map(np.asarray, s_r.params)
    for a, b in zip(jax.tree.leaves(ph), jax.tree.leaves(pr)):
        np.testing.assert_allclose(a[0], a[1], atol=1e-6)  # ddp-identical
        np.testing.assert_allclose(a[2], a[3], atol=1e-6)
        np.testing.assert_allclose(a[::2], b, atol=1e-5)   # == ring ranks


def test_eventgrad_ddp_converges_with_consensus_eval(capsys):
    args = ["--algo", "eventgrad", "--mesh", "dp:2,ddp:2",
            "--dataset", "synthetic", "--model", "mlp", "--epochs", "2",
            "--batch-size", "8", "--n-synth", "128", "--warmup-passes", "2"]
    assert main(args) == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert recs[-1]["final"] and "accuracy" in recs[-1]  # consensus eval ran
    assert recs[-2]["msgs_saved_pct"] >= 0


def test_gossipless_mesh_rejected_for_gossip_algos():
    with pytest.raises(SystemExit, match="gossip axis"):
        main(["--algo", "eventgrad", "--mesh", "ddp:8"])


def test_triple_hybrid_dp_ddp_sp_ring_attention():
    """Everything composes: EventGraD gossip across dp, synchronous
    allreduce subgroups across ddp (disjoint data shards), and ring
    attention chunking the sequence across sp — one 8-rank mesh, one
    jitted step; ddp pairs stay parameter-identical and the loss falls."""
    import jax.numpy as jnp

    from eventgrad_tpu.data.datasets import synthetic_lm_dataset
    from eventgrad_tpu.models.transformer import TransformerLM

    topo = Topology(
        axes=("dp", "ddp", "sp"), shape=(2, 2, 2), gossip_axes=("dp",),
        data_aux_axes=("ddp",),
    )
    x, y = synthetic_lm_dataset(64, 32, vocab=64, seed=4)
    model = TransformerLM(vocab=64, dim=32, n_heads=4, n_layers=1,
                          max_len=32, attn="ring", topo=topo, sp_axis="sp")
    state, hist = train(
        model, topo, x, y, algo="eventgrad", epochs=3, batch_size=4,
        learning_rate=0.1,
        event_cfg=EventConfig(adaptive=True, horizon=0.9, warmup_passes=2),
        seed=0, log_every_epoch=False,
    )
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert hist[-1]["msgs_saved_pct"] > 0
    # rank order row-major over (dp, ddp, sp): ranks r and r+2 differ only
    # in ddp index -> identical parameters (same data would differ only
    # along dp); sp pairs (r, r+1) are identical too (replicated aux)
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, state.params)):
        np.testing.assert_allclose(leaf[0], leaf[2], atol=1e-6)  # ddp pair
        np.testing.assert_allclose(leaf[0], leaf[1], atol=1e-6)  # sp pair
        assert not np.allclose(leaf[0], leaf[4], atol=1e-6)      # dp differs
