"""Host-bubble decomposition of a training run's span trace.

The dispatch pipeline's acceptance metric (docs/ARCHITECTURE.md "The
dispatch pipeline") is the HOST BUBBLE: the fraction of the train() wall
during which the device sits idle because the host is doing serialized
work between dispatch blocks — telemetry flush, history records, eval,
checkpoint serialization, input assembly. The r05 TPU flagship put that
bubble at ~38% of EventGraD's wall (851 s wall vs 531 s of steps) vs
~22% for D-PSGD; deleting it, not shaving step time, is what closes the
wall-clock race.

The decomposition reads the `obs.Registry` span trace the loop already
records (the same spans `--obs-dir`/`EG_BENCH_OBS_TRACE` export as
Chrome-trace JSON):

  * device-busy intervals: one per dispatch block, from the
    `dispatch_block` span's start to the block's observed readiness —
    the span's own end in serial mode (it wraps `block_until_ready`),
    the matching `block_ready` span's end in pipelined mode (the
    deferred metrics readback). The UNION of these intervals is
    `steps_s` (pipelined blocks overlap their host work, not each
    other — the union handles that).
  * wall: the `train` root span.
  * `host_bubble_frac` = 1 - steps_s / wall — everything the device was
    NOT kept busy.
  * component sums (`data_s`, `flush_s`, `eval_s`, `checkpoint_s`) are
    raw span-duration sums; under the pipeline they OVERLAP the busy
    intervals (that is the point), so they decompose the serial leg's
    bubble but can exceed the pipelined leg's. `other_s` is the bubble
    left after the named components (records loop, python glue).

Consumed by `tools/bubble_decomposition.py` (the committed
`artifacts/pipeline_bubble_cpu.json` proof), `tools/obs_report.py
--trace`, and bench.py's `host_bubble_frac` field.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


class IncompleteTraceWarning(UserWarning):
    """A span trace is missing a span type the decomposition wants —
    e.g. a run killed mid-flight exported no `train` root, or a
    pipelined block's deferred `block_ready` span never landed. The
    decomposition degrades to a PARTIAL answer (envelope wall, enqueue
    end as readiness) and names what was missing in `missing_spans`
    instead of raising — a truncated trace is evidence, not an error."""


#: span names summed into each named bubble component
_COMPONENTS = {
    "data_s": ("data",),
    "flush_s": ("obs_flush",),
    "eval_s": ("eval", "eval_readback"),
    "checkpoint_s": ("checkpoint", "ckpt_snapshot", "ckpt_write"),
}


def _norm(span: Any) -> Tuple[str, float, float, Dict[str, Any]]:
    """(name, ts_us, dur_us, args) from an obs.registry.Span OR a
    Chrome-trace event dict (so a written trace.json replays)."""
    if isinstance(span, dict):
        return (
            span.get("name", ""),
            float(span.get("ts", 0.0)),
            float(span.get("dur", 0.0)),
            dict(span.get("args") or {}),
        )
    return span.name, float(span.ts_us), float(span.dur_us), dict(span.args)


def _union_s(intervals: List[Tuple[float, float]]) -> float:
    """Total covered length (seconds) of microsecond intervals."""
    cur_start: Optional[float] = None
    cur_end = 0.0
    total = 0.0
    for start, end in sorted(intervals):
        if cur_start is None:
            cur_start, cur_end = start, end
        elif start <= cur_end:
            cur_end = max(cur_end, end)
        else:
            total += cur_end - cur_start
            cur_start, cur_end = start, end
    if cur_start is not None:
        total += cur_end - cur_start
    return total / 1e6


def train_windows(spans: Sequence[Any]) -> List[List[Any]]:
    """Split a span list into per-`train`-root windows (a bench registry
    records several train() legs back to back); spans are assigned to
    the root whose [ts, ts+dur] contains them."""
    normed = [(_norm(s), s) for s in spans]
    roots = [
        (n[1], n[1] + n[2]) for n, _ in normed if n[0] == "train"
    ]
    out: List[List[Any]] = [[] for _ in roots]
    for n, s in normed:
        for i, (lo, hi) in enumerate(roots):
            if n[0] != "train" and lo - 1 <= n[1] and n[1] + n[2] <= hi + 1:
                out[i].append(s)
                break
            if n[0] == "train" and n[1] == lo:
                out[i].append(s)
                break
    return out


def decompose(spans: Iterable[Any]) -> Dict[str, Any]:
    """wall = steps + bubble; bubble >= data + flush + eval + checkpoint
    (serial) — returns the seconds of each plus `host_bubble_frac`."""
    normed = [_norm(s) for s in spans]
    missing: List[str] = []
    train = [n for n in normed if n[0] == "train"]
    if train:
        wall_us = train[0][2]
        t_lo = train[0][1]
    else:  # no root span: fall back to the observed envelope
        if normed:
            missing.append("train")
        t_lo = min((n[1] for n in normed), default=0.0)
        wall_us = max((n[1] + n[2] for n in normed), default=0.0) - t_lo

    # device-busy intervals: dispatch start -> observed readiness
    ready_end = {
        n[3].get("block"): n[1] + n[2]
        for n in normed if n[0] == "block_ready"
    }
    busy: List[Tuple[float, float]] = []
    n_blocks = 0
    n_ready_missing = 0
    pipelined = False
    for n in normed:
        if n[0] != "dispatch_block":
            continue
        n_blocks += 1
        blk_piped = bool(n[3].get("pipelined", False))
        pipelined = pipelined or blk_piped
        # serial blocks: the dispatch span wraps block_until_ready, so its
        # own end IS the observed readiness (the later block_ready span is
        # a no-op recorded after other host work — using it would swallow
        # that work into "busy"). Pipelined blocks: the dispatch span is
        # just the enqueue; readiness is the deferred block_ready end —
        # a truncated trace (run killed before the readback) falls back
        # to the enqueue end, UNDERCOUNTING steps_s, and says so.
        end = n[1] + n[2]
        if blk_piped:
            if n[3].get("block") in ready_end:
                end = max(end, ready_end[n[3].get("block")])
            else:
                n_ready_missing += 1
        busy.append((n[1], end))
    if n_ready_missing:
        missing.append("block_ready")
    if n_blocks == 0 and normed:
        missing.append("dispatch_block")
    if missing:
        warnings.warn(
            "span trace incomplete — missing span types "
            f"{missing}"
            + (
                f" (block_ready absent for {n_ready_missing} pipelined "
                "blocks: their steps intervals end at the enqueue)"
                if n_ready_missing else ""
            )
            + "; returning a PARTIAL decomposition",
            IncompleteTraceWarning,
            stacklevel=2,
        )
    steps_s = _union_s(busy)

    comp = {
        key: sum(n[2] for n in normed if n[0] in names) / 1e6
        for key, names in _COMPONENTS.items()
    }
    wall_s = wall_us / 1e6
    bubble_s = max(0.0, wall_s - steps_s)
    other_s = max(0.0, bubble_s - sum(comp.values()))
    out = {
        "wall_s": round(wall_s, 4),
        "steps_s": round(steps_s, 4),
        "bubble_s": round(bubble_s, 4),
        "host_bubble_frac": round(bubble_s / wall_s, 4) if wall_s else 0.0,
        **{k: round(v, 4) for k, v in comp.items()},
        "other_s": round(other_s, 4),
        "n_blocks": n_blocks,
        "pipelined": pipelined,
    }
    if missing:
        out["missing_spans"] = sorted(set(missing))
    return out


def render_text(d: Dict[str, Any], label: str = "") -> str:
    """Human-readable one-block summary of a decomposition. Tolerates
    PARTIAL dicts (a truncated trace's decomposition, or one written by
    an older tool) — absent components render as 0 rather than raising."""
    head = f"bubble decomposition{' (' + label + ')' if label else ''}:"
    lines = [
        head,
        f"  wall            {d.get('wall_s', 0.0):9.3f} s",
        f"  steps (device)  {d.get('steps_s', 0.0):9.3f} s",
        f"  host bubble     {d.get('bubble_s', 0.0):9.3f} s"
        f"  ({100 * d.get('host_bubble_frac', 0.0):.1f}% of wall)",
    ]
    for key, title in (
        ("data_s", "data"), ("flush_s", "obs flush"), ("eval_s", "eval"),
        ("checkpoint_s", "checkpoint"), ("other_s", "other"),
    ):
        lines.append(f"    {title:<13} {d.get(key, 0.0):9.3f} s")
    lines.append(
        f"  blocks={d.get('n_blocks', 0)} pipelined={d.get('pipelined', False)}"
    )
    if d.get("missing_spans"):
        lines.append(
            f"  PARTIAL: trace missing span types {d['missing_spans']}"
        )
    return "\n".join(lines)
