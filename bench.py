"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): messages-saved-% of EventGraD vs D-PSGD at
the CIFAR-10 operating point (reference claim ~60%, /root/reference/README.md:4),
measured by running the flagship config — ResNet-18-as-coded (3 blocks/stage,
~17.4M params), 8-rank ring, global batch 256, SGD momentum 0.9, adaptive
threshold — with all 8 ranks vmap-simulated on the local accelerator (the
single-chip lifting path; identical trajectories to the shard_map path by
test_train_equivalence.py::test_shard_map_matches_vmap).

Falls back to synthetic CIFAR-shaped data when no dataset is on disk (no
network egress here). Extra context fields ride along in the same JSON line.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main() -> None:
    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.data.sharding import batched_epoch
    from eventgrad_tpu.models import ResNet18
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.spmd import spmd
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.state import init_train_state
    from eventgrad_tpu.train.steps import make_train_step
    from eventgrad_tpu.utils import trees
    from eventgrad_tpu.utils.metrics import msgs_saved_pct

    topo = Ring(8)
    global_batch = 256
    per_rank = global_batch // topo.n_ranks
    epochs = 26  # ~416 passes: warmup (30) stops dominating the savings ratio
    n_train = 4096

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    model = ResNet18(dtype=jnp.bfloat16)
    tx = optax.sgd(1e-2, momentum=0.9)  # dcifar10/event/event.cpp:196-200
    event_cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=30)

    state = init_train_state(model, x.shape[1:], tx, topo, "eventgrad", event_cfg)
    step = make_train_step(model, tx, topo, "eventgrad", event_cfg=event_cfg, augment=True)
    lifted = spmd(step, topo)

    @jax.jit
    def run_epoch(st, xb, yb):
        xs = (jnp.swapaxes(xb, 0, 1), jnp.swapaxes(yb, 0, 1))
        return jax.lax.scan(lambda s, b: lifted(s, b), st, xs)

    sz = trees.tree_num_leaves(jax.tree.map(lambda p: p[0], state.params))

    # compile + warm run
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank, random=True, epoch=0)
    steps_per_epoch = xb.shape[1]
    t0 = time.perf_counter()
    state, m = run_epoch(state, jnp.asarray(xb), jnp.asarray(yb))
    jax.block_until_ready(state.params)
    compile_s = time.perf_counter() - t0

    step_times = []
    for epoch in range(1, epochs):
        xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank, random=True, epoch=epoch)
        t0 = time.perf_counter()
        state, m = run_epoch(state, jnp.asarray(xb), jnp.asarray(yb))
        jax.block_until_ready(state.params)
        step_times.append((time.perf_counter() - t0) / steps_per_epoch)

    total_passes = int(np.asarray(state.pass_num).reshape(-1)[0])
    events = int(np.asarray(state.event.num_events).sum())
    saved = msgs_saved_pct(events, total_passes, sz, topo.n_neighbors, topo.n_ranks)
    bytes_per_step_chip = float(np.asarray(m["sent_bytes"])[..., 0].mean())
    n_params = trees.tree_count_params(jax.tree.map(lambda p: p[0], state.params))
    dense_bytes = float(topo.n_neighbors * 4 * n_params)

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet_eventgrad_msgs_saved",
                "value": round(saved, 2),
                "unit": "%",
                "vs_baseline": round(saved / 60.0, 4),
                "step_ms": round(1000 * float(np.mean(step_times)), 2),
                "sent_bytes_per_step_per_chip": bytes_per_step_chip,
                "dense_bytes_per_step_per_chip": dense_bytes,
                "final_loss": round(float(np.asarray(m["loss"]).mean()), 4),
                "passes": total_passes,
                "compile_s": round(compile_s, 1),
                "platform": jax.devices()[0].platform,
                "n_ranks": topo.n_ranks,
            }
        )
    )


if __name__ == "__main__":
    main()
