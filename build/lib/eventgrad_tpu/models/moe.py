"""Expert parallelism: Mixture-of-Experts layers with all_to_all dispatch.

Not present in the reference (SURVEY §2.5 marks EP "absent" — its models are
small dense CNNs), but part of the framework's scale story: the experts of
an MoE MLP shard across a named mesh axis exactly like Megatron kernels
shard across a TP axis (models/tp.py), and tokens reach their experts via
one `lax.all_to_all` pair riding ICI.

Design (GShard/Switch-style, TPU-dense):

  * Router is a replicated Dense; top-`n_select` gating with renormalized
    probabilities and a load-balancing auxiliary loss (sown into the
    "losses" collection; `train.steps` adds it to the objective).
  * Dispatch/combine are dense one-hot tensors of static shape
    [tokens, experts, capacity] — fully jittable, MXU-friendly einsums,
    no dynamic shapes. Tokens beyond an expert's capacity are dropped
    (their combine weight is zero, so they pass through the residual).
  * Expert weights live `ep_size`-way sharded: rank r owns experts
    [r*E/N, (r+1)*E/N) as leading-axis slices of `tp_wi`/`tp_wo`. The
    `tp_` prefix is the framework's sharded-leaf convention
    (train/steps.py): gradients of these leaves divide by the axis size
    (the all_to_all transpose has already summed every rank's
    contribution), while router/attention/embedding leaves pmean.

The EP axis doubles as a data axis (each rank routes its own tokens), so a
pure-EP topology is `Topology(axes=("ep",), shape=(N,), sharded_axes=("ep",))`
and hybrid gossip×EP meshes work like gossip×TP.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from eventgrad_tpu.models.tp import sharded_lecun_init
from eventgrad_tpu.parallel.ring_attention import full_attention


def _dispatch_combine(probs, n_select: int, capacity: int, dtype):
    """Dense dispatch/combine tensors from router probabilities.

    probs: [S, E] softmax router output. Returns (dispatch [S,E,C] in {0,1},
    combine [S,E,C] floats, routed [S,E] pre-capacity assignment counts for
    the load-balancing loss). Selection is top-`n_select` per token with
    gate weights renormalized over the selected experts; capacity is
    granted in selection-priority order (all first choices before any
    second choices), each expert keeping its first `capacity` takers in
    token order — deterministic and shape-static.
    """
    s, e = probs.shape
    gate_vals, gate_idx = lax.top_k(probs, n_select)  # [S, K]
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    onehot = jax.nn.one_hot(gate_idx.T, e, dtype=jnp.int32)  # [K, S, E]
    flat = onehot.reshape(n_select * s, e)  # priority-major ordering
    pos = jnp.cumsum(flat, axis=0) - flat  # position within each expert
    keep = (pos < capacity) & (flat > 0)
    slot = jax.nn.one_hot(pos, capacity, dtype=dtype) * keep[..., None].astype(dtype)
    comb = slot * gate_vals.T.reshape(-1)[:, None, None]
    dispatch = slot.reshape(n_select, s, e, capacity).sum(0)
    combine = comb.reshape(n_select, s, e, capacity).sum(0)
    return dispatch, combine, onehot.sum(0)  # routed: [S, E] pre-capacity


class ExpertParallelMLP(nn.Module):
    """MoE feed-forward: top-k routed experts sharded over `axis`.

    Input/output [B, T, D] per rank. With ep_size == 1 all experts are
    local and no collective runs (the single-rank twin used by tests).
    """

    dim: int
    hidden: int
    n_experts: int  # GLOBAL expert count; rank-major ownership order
    axis: str = "ep"
    ep_size: int = 1
    n_select: int = 2
    capacity_factor: float = 2.0
    aux_weight: float = 1e-2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        s = b * t
        e = self.n_experts
        if e % self.ep_size:
            raise ValueError(f"n_experts {e} not divisible by ep_size {self.ep_size}")
        e_local = e // self.ep_size
        capacity = max(1, math.ceil(self.n_select * s * self.capacity_factor / e))
        xf = x.reshape(s, d)

        # replicated router (fp32 for stable softmax/top-k)
        logits = nn.Dense(e, use_bias=False, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, routed = _dispatch_combine(
            probs, self.n_select, capacity, jnp.float32
        )

        # GShard load-balancing loss: E * sum_e mean_prob_e * mean_routed_e
        aux = e * jnp.sum(probs.mean(0) * (routed.astype(jnp.float32) / self.n_select).mean(0))
        self.sow("losses", "moe_aux", self.aux_weight * aux)

        xin = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), xf)  # [E, C, D]
        if self.ep_size > 1:
            # ship each owner-rank's expert block to its owner; receive my
            # experts' tokens from every source rank
            xin = xin.reshape(self.ep_size, e_local, capacity, d)
            xin = lax.all_to_all(xin, self.axis, split_axis=0, concat_axis=0, tiled=True)
            # [src, e_local, C, D] -> [e_local, src*C, D]
            xin = xin.transpose(1, 0, 2, 3).reshape(e_local, self.ep_size * capacity, d)

        init = (
            sharded_lecun_init(self.axis)
            if self.ep_size > 1
            else nn.initializers.lecun_normal()
        )
        wi = self.param("tp_wi", init, (e_local, d, self.hidden), jnp.float32)
        wo = self.param("tp_wo", init, (e_local, self.hidden, d), jnp.float32)
        h = jnp.einsum("ecd,edh->ech", xin, wi.astype(self.dtype))
        h = nn.gelu(h)
        out = jnp.einsum("ech,ehd->ecd", h, wo.astype(self.dtype))

        if self.ep_size > 1:
            # route expert outputs back to the token owners
            out = out.reshape(e_local, self.ep_size, capacity, d).transpose(1, 0, 2, 3)
            out = lax.all_to_all(out, self.axis, split_axis=0, concat_axis=0, tiled=True)
            out = out.reshape(e, capacity, d)

        y = jnp.einsum("sec,ecd->sd", combine.astype(out.dtype), out)
        return y.reshape(b, t, d)


class MoEBlock(nn.Module):
    """Pre-LN Transformer block whose MLP is an expert-parallel MoE."""

    dim: int
    n_heads: int
    n_experts: int
    axis: str
    ep_size: int
    n_select: int = 2
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        h = self.n_heads
        d = self.dim // h

        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype)(y)
        q, k, v = jnp.split(qkv.reshape(b, t, 3 * h, d), 3, axis=2)
        o = full_attention(q, k, v, causal=True)
        x = x + nn.Dense(self.dim, use_bias=False, dtype=self.dtype)(
            o.reshape(b, t, self.dim)
        )

        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = ExpertParallelMLP(
            dim=self.dim,
            hidden=4 * self.dim,
            n_experts=self.n_experts,
            axis=self.axis,
            ep_size=self.ep_size,
            n_select=self.n_select,
            capacity_factor=self.capacity_factor,
            dtype=self.dtype,
        )(y)
        return x + y


class MoETransformerLM(nn.Module):
    """Decoder-only LM with MoE blocks; attention/embeddings replicated
    (they gossip normally across dp), experts sharded over the EP axis."""

    vocab: int = 256
    dim: int = 128
    n_heads: int = 8
    n_layers: int = 2
    n_experts: int = 8
    max_len: int = 1024
    axis: str = "ep"
    ep_size: int = 1
    n_select: int = 2
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        b, t = tokens.shape
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype)(tokens)
        x = x + nn.Embed(self.max_len, self.dim, dtype=self.dtype)(jnp.arange(t))
        for _ in range(self.n_layers):
            x = MoEBlock(
                self.dim,
                self.n_heads,
                self.n_experts,
                self.axis,
                self.ep_size,
                self.n_select,
                self.capacity_factor,
                self.dtype,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab, dtype=self.dtype)(x).astype(jnp.float32)
