"""CIFAR-10 training augmentations, pure-JAX and jit/vmap-safe.

Rebuild of /root/reference/dcifar10/common/transform.hpp applied in the order
the reference composes them (dcifar10/event/event.cpp:94-98):
ConstantPad(4) (:79-87) -> RandomHorizontalFlip p=.5 (:68-76) ->
RandomCrop 32x32 (:90-101).

Runs on-device inside the train step (per-batch, keyed by the train PRNG),
so the host never touches pixels after the initial device_put — the TPU-
native answer to the reference's per-sample OpenCV CPU transforms.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pad_flip_crop(key: jax.Array, images: jnp.ndarray, pad: int = 4) -> jnp.ndarray:
    """images: [B, H, W, C] float32 -> same shape, per-sample random
    horizontal flip and random crop from the `pad`-padded canvas."""
    b, h, w, c = images.shape
    kf, kx, ky = jax.random.split(key, 3)

    flip = jax.random.bernoulli(kf, 0.5, (b,))
    images = jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)

    padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    ox = jax.random.randint(kx, (b,), 0, 2 * pad + 1)
    oy = jax.random.randint(ky, (b,), 0, 2 * pad + 1)

    def crop_one(img, x0, y0):
        return jax.lax.dynamic_slice(img, (x0, y0, 0), (h, w, c))

    return jax.vmap(crop_one)(padded, ox, oy)
