"""Flash-kernel sequence parallelism: lse merging, causal offsets, and
ring/ulysses parity with the materialized-score paths (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eventgrad_tpu.ops import flash_attention_lse, flash_attention_reference
from eventgrad_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ulysses_attention,
)
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Topology


def _qkv(key, b=1, t=64, h=2, d=32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32) for k in ks)


def test_lse_matches_reference_logsumexp():
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out, lse = flash_attention_lse(q, k, v, causal=True, interpret=True)
    ref = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(d))
    mask = jnp.tril(jnp.ones((q.shape[1],) * 2, bool))[None, None]
    s = jnp.where(mask, s, -jnp.inf)
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B,H,T]
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(jnp.swapaxes(ref_lse, 1, 2)), atol=2e-5, rtol=2e-5
    )


def test_offsets_shift_the_causal_diagonal():
    """With q_offset = T and k_offset = 0, every key is in the past: the
    result must equal unmasked attention. With q_offset = 0, k_offset = T,
    every key is in the future: lse must be ~-inf (no visible keys)."""
    q, k, v = _qkv(jax.random.PRNGKey(1), t=48)
    t = q.shape[1]

    out_past, _ = flash_attention_lse(
        q, k, v, causal=True, q_offset=t, k_offset=0, interpret=True
    )
    ref = flash_attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out_past), np.asarray(ref), atol=2e-5, rtol=2e-5)

    _, lse_future = flash_attention_lse(
        q, k, v, causal=True, q_offset=0, k_offset=t, interpret=True
    )
    assert np.all(np.asarray(lse_future) < -1e29)


def test_two_hop_merge_equals_joint():
    """Attending one Q block against two KV blocks separately and merging
    with the online-softmax rule must reproduce joint attention over the
    concatenated KV — the exact computation each ring hop does."""
    q, _, _ = _qkv(jax.random.PRNGKey(2), t=32)
    _, k, v = _qkv(jax.random.PRNGKey(6), t=64)
    k1, k2 = jnp.split(k, 2, axis=1)
    v1, v2 = jnp.split(v, 2, axis=1)

    o1, l1 = flash_attention_lse(q, k1, v1, interpret=True)
    o2, l2 = flash_attention_lse(q, k2, v2, interpret=True)
    ln = jnp.logaddexp(l1, l2)
    o = o1 * jnp.exp(l1 - ln)[..., None] + o2 * jnp.exp(l2 - ln)[..., None]

    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(d))
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_ring_jnp(causal):
    topo = Topology(axes=("sp",), shape=(4,), gossip_axes=())
    b, t_local, h, d = 1, 16, 2, 16
    key = jax.random.PRNGKey(3)
    q, k, v = (
        jax.random.normal(kk, (4, b, t_local, h, d)) for kk in jax.random.split(key, 3)
    )

    run = lambda fn: spmd(fn, topo)
    out_jnp = jax.jit(run(
        lambda q, k, v: ring_attention(q, k, v, topo, causal=causal)
    ))(q, k, v)
    out_flash = jax.jit(run(
        lambda q, k, v: ring_attention(q, k, v, topo, causal=causal, use_flash=True)
    ))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_jnp), atol=3e-5, rtol=3e-5
    )

    # and both equal single-device full attention over the gathered sequence
    qf, kf, vf = (jnp.concatenate(list(x), axis=1) for x in (q, k, v))
    ref = full_attention(qf, kf, vf, causal=causal)
    ref_shards = jnp.stack(jnp.split(ref, 4, axis=1))
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(ref_shards), atol=3e-5, rtol=3e-5
    )


def test_ring_flash_gradients_match():
    topo = Topology(axes=("sp",), shape=(4,), gossip_axes=())
    key = jax.random.PRNGKey(4)
    q, k, v = (
        jax.random.normal(kk, (4, 1, 16, 2, 16)) for kk in jax.random.split(key, 3)
    )

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            spmd(lambda q, k, v: fn(q, k, v), topo)(q, k, v) ** 2
        )

    g_flash = jax.grad(loss(
        lambda q, k, v: ring_attention(q, k, v, topo, causal=True, use_flash=True)
    ), argnums=(0, 1, 2))(q, k, v)
    g_jnp = jax.grad(loss(
        lambda q, k, v: ring_attention(q, k, v, topo, causal=True)
    ), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_jnp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3)


def test_ulysses_flash_matches_jnp():
    topo = Topology(axes=("sp",), shape=(4,), gossip_axes=())
    key = jax.random.PRNGKey(5)
    q, k, v = (
        jax.random.normal(kk, (4, 1, 16, 4, 16)) for kk in jax.random.split(key, 3)
    )
    out_jnp = jax.jit(spmd(
        lambda q, k, v: ulysses_attention(q, k, v, topo, causal=True), topo
    ))(q, k, v)
    out_flash = jax.jit(spmd(
        lambda q, k, v: ulysses_attention(q, k, v, topo, causal=True, use_flash=True),
        topo,
    ))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_jnp), atol=3e-5, rtol=3e-5
    )
