"""ctypes bindings for the native data pipeline (native/dataio.cpp).

Auto-builds `libeg_dataio.so` with the in-tree Makefile on first use when a
compiler is available; every entry point has a pure-numpy fallback so the
framework stays fully functional without the native library. The native
paths matter on big datasets: zero-copy idx/CIFAR-binary parsing and
memcpy batch gathers instead of numpy fancy-indexing.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libeg_dataio.so"))
_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _build(force: bool = False) -> bool:
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR)] + (["-B"] if force else []),
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """The shared library, building it on demand; None if unavailable.
    A stale .so from an older commit (missing newer symbols) triggers one
    forced rebuild before giving up. Thread-safe (first JPEG use may come
    from a decode pool)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)
        except (OSError, AttributeError):
            # stale build: rebuild, then load through a fresh temp copy —
            # dlopen caches by path, so reloading _LIB_PATH in-process
            # would hand back the old mapping
            if not _build(force=True):
                return None
            tmp_name = None
            try:
                with tempfile.NamedTemporaryFile(
                    suffix=".so", delete=False
                ) as tf:
                    tmp_name = tf.name
                shutil.copyfile(_LIB_PATH, tmp_name)
                lib = ctypes.CDLL(tmp_name)
                _bind(lib)
            except (OSError, AttributeError):
                return None
            finally:
                # the dlopen mapping outlives the name; never leak the copy
                if tmp_name is not None:
                    try:
                        os.unlink(tmp_name)
                    except OSError:
                        pass
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    i64, i32, f32, u64 = (
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_float,
        ctypes.c_uint64,
    )
    pf = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    pi32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    pi64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")

    lib.eg_load_cifar10_file.restype = i64
    lib.eg_load_cifar10_file.argtypes = [ctypes.c_char_p, pf, pi32, i64]
    lib.eg_load_mnist.restype = i64
    lib.eg_load_mnist.argtypes = [ctypes.c_char_p, ctypes.c_char_p, pf, pi32, i64, f32, f32]
    lib.eg_shard_plan.restype = None
    lib.eg_shard_plan.argtypes = [i64, i64, u64, u64, ctypes.c_int, pi64]
    lib.eg_gather.restype = None
    lib.eg_gather.argtypes = [pf, i64, pi64, i64, pf]
    lib.eg_gather_i32.restype = None
    lib.eg_gather_i32.argtypes = [pi32, pi64, i64, pi32]
    pu8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.eg_jpeg_supported.restype = ctypes.c_int
    lib.eg_load_jpeg_image.restype = ctypes.c_int
    lib.eg_load_jpeg_image.argtypes = [ctypes.c_char_p, pf, i32]
    lib.eg_jpeg_encode_file.restype = ctypes.c_int
    lib.eg_jpeg_encode_file.argtypes = [ctypes.c_char_p, pu8, i32, i32, i32]
    lib.eg_resize_bilinear_rgb.restype = None
    lib.eg_resize_bilinear_rgb.argtypes = [pu8, i32, i32, pu8, i32, i32]
    lib.eg_version.restype = ctypes.c_int


def available() -> bool:
    return load_library() is not None


def load_cifar10_bin(paths) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Read CIFAR-10 binary batch files natively; None if lib unavailable."""
    lib = load_library()
    if lib is None:
        return None
    per_file = 10_000
    x = np.empty((per_file * len(paths), 32, 32, 3), np.float32)
    y = np.empty(per_file * len(paths), np.int32)
    total = 0
    for p in paths:
        got = lib.eg_load_cifar10_file(
            str(p).encode(), x[total:].reshape(-1), y[total:], per_file
        )
        if got < 0:
            return None
        total += int(got)
    return x[:total], y[:total]


def load_mnist_idx(
    images_path: str, labels_path: str, mean: float, std: float
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    lib = load_library()
    if lib is None or not (os.path.exists(images_path) and os.path.exists(labels_path)):
        return None
    cap = 70_000
    x = np.empty((cap, 28, 28, 1), np.float32)
    y = np.empty(cap, np.int32)
    got = lib.eg_load_mnist(
        images_path.encode(), labels_path.encode(), x.reshape(-1), y, cap, mean, std
    )
    if got < 0:
        return None
    return x[: int(got)], y[: int(got)]


def jpeg_supported() -> bool:
    lib = load_library()
    return bool(lib is not None and lib.eg_jpeg_supported())


def load_jpeg_image(path: str, image_size: int = 32) -> np.ndarray:
    """Decode one JPEG to [image_size, image_size, 3] RGB float32 in [0,1]
    (libjpeg decode + bilinear resize, the reference's imread+resize,
    custom.hpp:33-41). Raises on unsupported builds or bad files."""
    lib = load_library()
    if lib is None or not lib.eg_jpeg_supported():
        raise RuntimeError(
            "JPEG support needs native/libeg_dataio.so built against libjpeg"
        )
    out = np.empty((image_size, image_size, 3), np.float32)
    rc = lib.eg_load_jpeg_image(str(path).encode(), out.reshape(-1), image_size)
    if rc != 0:
        raise ValueError(f"JPEG decode failed for {path!r} (rc={rc})")
    return out


def save_jpeg(path: str, rgb: np.ndarray, quality: int = 90) -> None:
    """Encode an HWC uint8 RGB array to a JPEG file (fixtures / export)."""
    lib = load_library()
    if lib is None or not lib.eg_jpeg_supported():
        raise RuntimeError(
            "JPEG support needs native/libeg_dataio.so built against libjpeg"
        )
    rgb = np.ascontiguousarray(rgb, np.uint8)
    if rgb.ndim != 3 or rgb.shape[2] != 3:
        raise ValueError(f"expected HWC RGB uint8, got shape {rgb.shape}")
    rc = lib.eg_jpeg_encode_file(
        str(path).encode(), rgb.reshape(-1), rgb.shape[1], rgb.shape[0], quality
    )
    if rc != 0:
        raise ValueError(f"JPEG encode failed for {path!r} (rc={rc})")


def shard_plan(
    n: int, n_ranks: int, seed: int = 0, epoch: int = 0, shuffle: bool = False
) -> np.ndarray:
    """[n_ranks, n // n_ranks] shard index plan (native or numpy fallback)."""
    per = n // n_ranks
    lib = load_library()
    if lib is None:
        if not shuffle:
            return np.arange(n_ranks * per, dtype=np.int64).reshape(n_ranks, per)
        rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
        return rng.permutation(n)[: n_ranks * per].reshape(n_ranks, per).astype(np.int64)
    out = np.empty(n_ranks * per, np.int64)
    lib.eg_shard_plan(n, n_ranks, seed, epoch, int(shuffle), out)
    return out.reshape(n_ranks, per)


def gather_batches(
    x: np.ndarray, y: np.ndarray, idx: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Assemble [*idx.shape, ...sample] batches with native memcpy gathers.

    Samples may be any shape — images [H, W, C] with scalar labels, or
    token sequences [T] with [T]-shaped targets; integer arrays gather as
    int32, floats as float32 (eg_gather is a 4-byte-row memcpy, so int32
    rides the same kernel through a bit view)."""
    lib = load_library()
    flat_idx = np.ascontiguousarray(idx.reshape(-1), np.int64)

    def _norm(arr: np.ndarray) -> np.ndarray:
        dt = np.int32 if np.issubdtype(arr.dtype, np.integer) else np.float32
        return np.ascontiguousarray(arr, dt)

    if lib is None:
        x2, y2 = _norm(x), _norm(y)
        return (
            x2[flat_idx].reshape(idx.shape + x.shape[1:]),
            y2[flat_idx].reshape(idx.shape + y.shape[1:]),
        )

    def _rowgather(arr: np.ndarray) -> np.ndarray:
        a = _norm(arr)
        elem = int(np.prod(a.shape[1:])) if a.ndim > 1 else 1
        if elem == 1 and a.dtype == np.int32:
            out = np.empty(flat_idx.size, np.int32)
            lib.eg_gather_i32(a.reshape(-1), flat_idx, flat_idx.size, out)
        else:
            out = np.empty((flat_idx.size, elem), a.dtype)
            lib.eg_gather(
                a.reshape(-1).view(np.float32), elem,
                flat_idx, flat_idx.size, out.reshape(-1).view(np.float32),
            )
        return out.reshape(idx.shape + a.shape[1:])

    return _rowgather(x), _rowgather(y)
