"""Preemption & crash-consistency engine (chaos/crashpoint.py).

Three layers: registry mechanics (arming, hit counting, the
instrumentation lint), graceful preemption (SIGTERM and the scheduled
`preempt=` clause both drain at a block boundary, snapshot, mark, and
resume BITWISE), and one end-to-end subprocess kill/resume cell of the
crash matrix (the full matrix lives in tools/crash_matrix.py and ships
as the schema-gated artifacts/crash_matrix_cpu.json).
"""

import json
import os
import re
import signal
import subprocess
import sys

import jax
import numpy as np
import pytest

from eventgrad_tpu import exitcodes
from eventgrad_tpu.chaos import ChaosSchedule, GracefulPreemption, crashpoint
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.utils import checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends disarmed — a leaked arming would kill
    later tests at their first checkpoint."""
    crashpoint.arm(None)
    yield
    crashpoint.arm(None)


def _train_kw():
    return dict(
        algo="eventgrad", epochs=4, batch_size=8, learning_rate=0.05,
        event_cfg=EventConfig(adaptive=True, horizon=0.95, warmup_passes=2),
        seed=5,
    )


def _data():
    return synthetic_dataset(128, (8, 8, 1), seed=3)


def _assert_params_equal(a, b):
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --- exit-code contract -----------------------------------------------------


def test_exit_codes_centralized_and_distinct():
    """One jax-free module holds the contract; every consumer imports
    it (the old supervise re-declaration is gone) and the codes stay
    distinct from each other, from 0, and from fault_inject's 13."""
    from eventgrad_tpu import supervise
    from eventgrad_tpu.chaos import integrity

    assert exitcodes.INTEGRITY_ABORT_EXIT == 77
    assert exitcodes.PREEMPTED_EXIT == 75
    assert exitcodes.CRASHPOINT_EXIT == 83
    codes = {
        exitcodes.INTEGRITY_ABORT_EXIT, exitcodes.PREEMPTED_EXIT,
        exitcodes.CRASHPOINT_EXIT,
    }
    assert len(codes) == 3 and 0 not in codes and 13 not in codes
    assert supervise.INTEGRITY_ABORT_EXIT is exitcodes.INTEGRITY_ABORT_EXIT
    assert supervise.PREEMPTED_EXIT is exitcodes.PREEMPTED_EXIT
    assert integrity.INTEGRITY_ABORT_EXIT is exitcodes.INTEGRITY_ABORT_EXIT
    assert set(exitcodes.EXIT_CODE_NAMES) == codes
    # and the module really is import-bare (the supervisor's constraint)
    import importlib.util

    spec = importlib.util.find_spec("eventgrad_tpu.exitcodes")
    with open(spec.origin) as f:
        src = f.read()
    assert "import" not in re.sub(r'""".*?"""', "", src, flags=re.DOTALL)


# --- registry mechanics -----------------------------------------------------


def test_parse_spec_and_arming():
    assert crashpoint.parse_spec("loop.block_end") == ("loop.block_end", 1)
    assert crashpoint.parse_spec("ckpt.mid_swap:3") == ("ckpt.mid_swap", 3)
    with pytest.raises(ValueError, match="unknown crashpoint"):
        crashpoint.parse_spec("no.such.site")
    with pytest.raises(ValueError, match=">= 1"):
        crashpoint.parse_spec("loop.block_end:0")
    crashpoint.arm("loop.block_end:2")
    assert crashpoint.armed() == {"site": "loop.block_end", "hit": 2}
    crashpoint.arm(None)
    assert crashpoint.armed() is None


def test_hit_rejects_unregistered_site_and_noops_unarmed():
    with pytest.raises(KeyError, match="unregistered crashpoint"):
        crashpoint.hit("definitely.not.a.site")
    # unarmed: every registered site is a no-op
    for site in crashpoint.SITES:
        crashpoint.hit(site)
    # armed at another site: still a no-op here
    crashpoint.arm("ckpt.mid_swap")
    crashpoint.hit("loop.block_end")


def test_every_crashpoint_instrumented_exactly_once():
    """Tier-1 lint: each registered site name appears at EXACTLY one
    `crashpoint.hit("<name>")` call in the package — a dead site would
    hollow out the crash matrix silently, a duplicate would make "kill
    at site X" ambiguous — and every hit() call in the package uses a
    string literal naming a registered site (the lint can only count
    what it can read). The walking/counting lives in the shared AST
    lint framework (eventgrad_tpu/analysis/lint.py,
    CrashpointInstrumented — the old grep plumbing, messages kept)."""
    from eventgrad_tpu.analysis import lint

    offenders = lint.CrashpointInstrumented().check(
        lint.collect_sources(REPO)
    )
    assert not offenders, "\n".join(str(v) for v in offenders)


def test_crashpoint_lint_detects_seeded_violations():
    """The framework rule can FIRE: a non-literal hit(), an
    unregistered site name, and a duplicated site are each flagged
    against a synthetic source set."""
    from eventgrad_tpu.analysis import lint

    sep = os.path.sep
    real = lint.collect_sources(REPO)

    def plus(text, name="seeded_bad.py"):
        return real + [lint.SourceFile(
            path="/" + name, rel=f"eventgrad_tpu{sep}{name}", text=text,
        )]

    rule = lint.CrashpointInstrumented()
    msgs = "\n".join(str(v) for v in rule.check(
        plus("import crashpoint\ncrashpoint.hit(site_var)\n")
    ))
    assert "string literal" in msgs
    msgs = "\n".join(str(v) for v in rule.check(
        plus('import crashpoint\ncrashpoint.hit("no.such.site")\n')
    ))
    assert "unregistered crashpoint names" in msgs
    msgs = "\n".join(str(v) for v in rule.check(
        plus('import crashpoint\ncrashpoint.hit("loop.block_end")\n')
    ))
    assert "more than one site" in msgs


def test_marker_write_and_consume(tmp_path):
    d = str(tmp_path)
    assert crashpoint.consume_marker(d) is None
    assert crashpoint.consume_marker(None) is None
    path = crashpoint.write_marker(d, {"reason": "signal:SIGTERM", "epoch": 3})
    assert os.path.exists(path)
    with open(path) as f:
        assert json.load(f)["epoch"] == 3
    info = crashpoint.consume_marker(d)
    assert info["reason"] == "signal:SIGTERM"
    assert not os.path.exists(path)  # consumed exactly once
    assert crashpoint.consume_marker(d) is None
    # a torn marker is still removed (a half-written witness must not
    # wedge every future startup)
    with open(path, "w") as f:
        f.write("{truncated")
    assert crashpoint.consume_marker(d) is None
    assert not os.path.exists(path)


def test_preempt_clause_round_trips():
    s = ChaosSchedule.parse("drop=0,seed=3,preempt=4@2,preempt=9")
    assert s.preempt == ((4, 2), (9, 1))  # bare E means step 1, sorted
    assert ChaosSchedule.parse(s.to_spec()) == s
    assert ChaosSchedule.from_dict(s.to_dict()) == s
    assert not s.is_noop  # a preemption notice IS an event
    assert "preempt" not in ChaosSchedule().to_dict()  # legacy unchanged
    with pytest.raises(ValueError, match="preempt"):
        ChaosSchedule.parse("preempt=0@1")


# --- graceful preemption (train-level) --------------------------------------


def test_scheduled_preempt_drains_marks_and_resumes_bitwise(tmp_path):
    """The `preempt=E@S` clause drains at the enclosing block boundary:
    boundary snapshot + PREEMPTED marker on disk, GracefulPreemption
    raised; the resume ignores the consumed notice and lands on the
    never-preempted trajectory bitwise — preemption lost nothing."""
    x, y = _data()
    kw = _train_kw()
    base_state, base_hist = train(
        MLP(hidden=8), Ring(4), x, y, chaos="drop=0,seed=1", **kw
    )
    ck = str(tmp_path / "ck")
    with pytest.raises(GracefulPreemption) as ei:
        train(
            MLP(hidden=8), Ring(4), x, y, checkpoint_dir=ck, save_every=2,
            chaos="drop=0,seed=1,preempt=2@1", **kw
        )
    info = ei.value.info
    assert info["reason"] == "schedule:2@1" and info["epoch"] == 2
    assert info["snapshot"] is True
    assert os.path.exists(os.path.join(ck, "PREEMPTED"))
    # the drained snapshot is the boundary state (nothing past it ran)
    raw = checkpoint.peek(checkpoint.latest(os.path.join(ck, "ckpt")))
    assert int(np.asarray(raw["epoch"])) == 2

    st, hist = train(
        MLP(hidden=8), Ring(4), x, y, checkpoint_dir=ck, save_every=2,
        resume=True, chaos="drop=0,seed=1,preempt=2@1", **kw
    )
    assert not os.path.exists(os.path.join(ck, "PREEMPTED"))  # consumed
    assert [h["epoch"] for h in hist] == [3, 4]  # zero recomputed epochs
    _assert_params_equal(base_state, st)
    by_epoch = {r["epoch"]: r for r in base_hist}
    for r in hist:  # history parity, value for value
        assert r["loss"] == by_epoch[r["epoch"]]["loss"]
        assert r["num_events"] == by_epoch[r["epoch"]]["num_events"]


def test_sigterm_drains_at_next_boundary_and_resumes_bitwise(tmp_path):
    """A real SIGTERM mid-run: the handler only sets a flag, the loop
    drains at its next block boundary (pipeline drained, writer joined,
    force-snapshot, marker), raises GracefulPreemption, and RESTORES
    the previous signal disposition; the resume is bitwise."""
    x, y = _data()
    kw = _train_kw()
    base_state, _ = train(MLP(hidden=8), Ring(4), x, y, **kw)
    ck = str(tmp_path / "ck")

    def deliver(rec):
        if rec.get("epoch") == 2:
            os.kill(os.getpid(), signal.SIGTERM)

    before = signal.getsignal(signal.SIGTERM)
    with pytest.raises(GracefulPreemption) as ei:
        train(
            MLP(hidden=8), Ring(4), x, y, checkpoint_dir=ck, save_every=2,
            pipeline=True, on_epoch=deliver, **kw
        )
    assert ei.value.info["reason"] == "signal:SIGTERM"
    assert signal.getsignal(signal.SIGTERM) == before  # handler restored
    assert os.path.exists(os.path.join(ck, "PREEMPTED"))

    st, hist = train(
        MLP(hidden=8), Ring(4), x, y, checkpoint_dir=ck, save_every=2,
        resume=True, **kw
    )
    # <= 1 dispatch block of recomputation (here: zero — the drain
    # snapshots the boundary the signal was noticed at)
    assert [h["epoch"] for h in hist] == [3, 4]
    _assert_params_equal(base_state, st)


def test_unarmed_run_is_today_bitwise_and_armed_rider_stamps(tmp_path):
    """off == absent: with no crashpoint armed and no signal delivered
    the state and history carry no new fields and match a run made
    before this engine existed (the baseline twin here); arming a site
    whose hit count never fires stamps the `crashpoint` rider on record
    1 and changes nothing else."""
    x, y = _data()
    kw = _train_kw()
    st0, h0 = train(MLP(hidden=8), Ring(4), x, y, **kw)
    assert all("crashpoint" not in r and "preempt" not in r for r in h0)

    crashpoint.arm("loop.block_end:999")  # never reached in 4 blocks
    st1, h1 = train(MLP(hidden=8), Ring(4), x, y, **kw)
    crashpoint.arm(None)
    assert h1[0]["crashpoint"] == {"site": "loop.block_end", "hit": 999}
    _assert_params_equal(st0, st1)
    for r0, r1 in zip(h0, h1):
        assert r0["loss"] == r1["loss"]
        assert r0["num_events"] == r1["num_events"]


# --- one subprocess crash-matrix cell ---------------------------------------


def _cli_cmd(tmp, tag, extra):
    return [
        sys.executable, "-m", "eventgrad_tpu.cli",
        "--algo", "eventgrad", "--mesh", "ring:4", "--dataset",
        "synthetic", "--model", "mlp", "--epochs", "4", "--batch-size",
        "8", "--n-synth", "128", "--warmup-passes", "2", "--lr", "0.1",
        "--save-every", "2",
        "--log-file", os.path.join(tmp, f"{tag}.jsonl"),
    ] + extra


def _run_cli(tmp, tag, extra, crash=None):
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "EG_CRASHPOINT")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if crash:
        env["EG_CRASHPOINT"] = crash
    return subprocess.run(
        _cli_cmd(tmp, tag, extra), cwd=REPO, env=env, capture_output=True,
        text=True, timeout=300,
    )


def test_subprocess_kill_at_mid_swap_resumes_bitwise(tmp_path):
    """One full crash-matrix cell at the atomic swap's worst instant
    (old snapshot demoted, new one not yet promoted): the kill exits
    CRASHPOINT_EXIT, leaves only the .prev twin, and the resume
    reproduces the uninterrupted final metrics exactly. The full
    site x config matrix is tools/crash_matrix.py -> the committed
    artifacts/crash_matrix_cpu.json."""
    tmp = str(tmp_path)
    ck = os.path.join(tmp, "ck")
    base = _run_cli(
        tmp, "base", ["--checkpoint-dir", os.path.join(tmp, "ck0")]
    )
    assert base.returncode == 0, base.stderr[-2000:]

    killed = _run_cli(
        tmp, "crash", ["--checkpoint-dir", ck], crash="ckpt.mid_swap"
    )
    assert killed.returncode == exitcodes.CRASHPOINT_EXIT, (
        killed.stderr[-2000:]
    )
    assert "crashpoint ckpt.mid_swap hit 1" in killed.stderr
    # the worst-instant kill left the demoted twin as the newest
    # complete snapshot
    assert checkpoint.latest(os.path.join(ck, "ckpt")).endswith(".prev")
    # the killed run's log names the armed site (rider on record 1)
    with open(os.path.join(tmp, "crash.jsonl")) as f:
        first = next(
            json.loads(line) for line in f if "epoch" in json.loads(line)
        )
    assert first["crashpoint"] == {"site": "ckpt.mid_swap", "hit": 1}

    resumed = _run_cli(
        tmp, "resume", ["--checkpoint-dir", ck, "--resume"]
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]

    def final(tag):
        with open(os.path.join(tmp, f"{tag}.jsonl")) as f:
            return next(
                r for r in map(json.loads, f) if r.get("final")
            )

    fb, fr = final("base"), final("resume")
    assert fb["accuracy"] == fr["accuracy"]
    assert fb["loss"] == fr["loss"]
