"""Peer-health monitoring: who has gone quiet, and why it matters.

An event-triggered link is *supposed* to go quiet — that is the whole
savings claim — so a receiver cannot read "no message" as "peer dead".
What it CAN know:

  * the sender-side trigger bounds silence: with `EventConfig.max_silence
    = M > 0` every parameter fires at least every M passes, so a healthy
    incoming edge is silent at most M consecutive passes (plus wire loss);
  * therefore observed silence far beyond M is evidence of a dead or
    lossy link, not a quiet threshold.

`PeerHealth` carries that evidence through the jitted scan: per-edge
silence counters (passes since the last *delivered* payload), a count of
injected drops actually observed (schedule ground truth, for artifacts),
and the force-fire request bit of `policy.RecoveryPolicy.sync_after`
(receiver-side forced full-sync, applied by the sender one pass later).

The consensus-error probe `||p_i - mean(p)||` is the host-side
ground-truth drift metric, logged into the per-epoch history records
(through the same JSONL stream as every other metric) at dispatch-block
ends.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel.topology import NeighborSpec, Topology


class PeerHealth(struct.PyTreeNode):
    """Per-rank receiver-side link state, threaded through the train scan.

    silence:  int32 [n_neighbors] — passes since a payload last ARRIVED on
              each incoming edge (an undelivered or unfired pass counts).
    sync_req: bool [] — some neighbor asked this rank to force-fire next
              pass (set via the reverse-edge gossip of `sync_requests`).
    drops:    int32 [] — cumulative injected drops observed on this rank's
              incoming edges (messages that WERE sent but the schedule ate).
    """

    silence: jnp.ndarray
    sync_req: jnp.ndarray
    drops: jnp.ndarray

    @classmethod
    def init(cls, topo: Topology) -> "PeerHealth":
        return cls(
            silence=jnp.zeros((topo.n_neighbors,), jnp.int32),
            sync_req=jnp.zeros((), bool),
            drops=jnp.zeros((), jnp.int32),
        )


def update(
    health: PeerHealth,
    delivered_any: jnp.ndarray,
    dropped_any: jnp.ndarray,
) -> PeerHealth:
    """Advance the counters one pass. `delivered_any`/`dropped_any` are
    bool [n_neighbors]: did any parameter's payload arrive / get eaten by
    the schedule on each edge this pass."""
    return health.replace(
        silence=jnp.where(delivered_any, 0, health.silence + 1),
        drops=health.drops + jnp.sum(dropped_any.astype(jnp.int32)),
    )


def sync_requests(need: jnp.ndarray, topo: Topology) -> jnp.ndarray:
    """Gossip each rank's per-edge force-sync requests back to the edge
    SOURCES; returns this rank's aggregated incoming request (bool []).

    My incoming edge with shift `+o` sources from rank `me+o`; my request
    about it must land on that rank, which receives it via the REVERSE
    shift `-o` (ppermute pairs always come in +-o pairs on a gossip axis,
    so the reverse edge exists by construction). One bool per edge on the
    wire — the cheapest possible control channel, and still a collective,
    so it is SPMD-legal under vmap and shard_map alike.
    """
    got = jnp.zeros((), bool)
    for i, nb in enumerate(topo.neighbors):
        rev = NeighborSpec(nb.axis, -nb.offset)
        got = got | collectives.recv_from(need[i], topo, rev)
    return got


@jax.jit
def consensus_error(stacked_params) -> jnp.ndarray:
    """Per-rank consensus error ||p_i - mean_r(p_r)||_2 over the stacked
    rank axis (f32 [n_ranks]) — the drift metric that tells a healthy
    quiet network from a partitioned one. One fused dispatch."""
    flat = jnp.concatenate(
        [
            x.reshape(x.shape[0], -1).astype(jnp.float32)
            for x in jax.tree.leaves(stacked_params)
        ],
        axis=1,
    )
    return jnp.linalg.norm(flat - flat.mean(axis=0, keepdims=True), axis=1)


def edge_status(
    silence: int, max_silence: int, suspect_factor: float = 3.0
) -> str:
    """Host-side classification of one edge's observed silence:

      'healthy'  — silence within the sender-side trigger bound (or the
                   bound is off, in which case any silence is plausible
                   threshold behavior and only 'unbounded' can be said);
      'suspect'  — silence exceeds `suspect_factor` x the sender's
                   max_silence guarantee: the link is losing messages or
                   the peer is dead (policy should force-sync or freeze);
      'unbounded'— no sender-side bound exists (max_silence == 0), so
                   quiet-by-threshold and quiet-by-death are
                   indistinguishable from silence alone: use the
                   consensus-error probe instead.
    """
    if max_silence <= 0:
        return "unbounded"
    return "suspect" if silence > suspect_factor * max_silence else "healthy"


def health_record(
    silence, drops, max_silence: int, edges=None,
) -> Dict[str, object]:
    """Summarize host-fetched PeerHealth counters into JSONL-ready fields:
    per-edge max silence across ranks, its `edge_status` classification,
    and the total injected-drop count. The ONE summarizer behind the
    epoch records of train(), the sweep artifacts, and the telemetry
    registry's per-edge gauges (obs.Registry.observe_health) — `silence`
    is [n_ranks, n_neighbors], `drops` any array of per-rank cumulative
    counts. `edges` (neighbor names, topology order) labels the edges in
    the record; omitted, the lists stay positional as before."""
    import numpy as np

    silence = np.asarray(silence)
    per_edge_max = (
        silence.max(axis=0) if silence.size else np.zeros((0,), np.int64)
    )
    rec = {
        "edge_silence_max": [int(v) for v in per_edge_max],
        "edge_status": [
            edge_status(int(v), max_silence) for v in per_edge_max
        ],
        "chaos_drops": int(np.asarray(drops).sum()),
    }
    if edges is not None:
        rec["edges"] = list(edges)
    return rec
