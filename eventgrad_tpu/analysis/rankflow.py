"""Rank-isolation dataflow analysis over the vmap-lifted train step.

The decentralized-semantics guarantee EventGraD rests on: rank r's new
state depends on other ranks ONLY through the declared neighbor
exchange.  On the single-chip vmap lift every rank lives as one index
of a leading [n_ranks] axis, so the guarantee has a precise structural
form: every equation of the lifted jaxpr must treat that axis
POINTWISE, except the equations `lax.ppermute` lowers to — under vmap,
a gather over the rank axis whose indices are a CONSTANT permutation
(the neighbor shift).  This module is an abstract interpreter that
tracks, for every intermediate, which array dim (if any) carries the
rank coordinate, and reports

  * `exchanges` — the constant-permutation gathers found, each with its
    ring offset, per-neighbor lane shape, and dtype (the wire-truth
    inputs of analysis/audit.py);
  * `psums` — positional cross-rank reductions (`lax.psum`/`pmean`
    under vmap); legal only for configurations that declare them
    (allreduce, aux axes), never for ring gossip;
  * `violations` — every other equation that moves information across
    the rank axis (a data-dependent cross-rank gather, a slice or
    concatenate that cuts the axis, a reduction over it, a reshape that
    folds it away, an unknown primitive the rules cannot prove safe).

The abstract value (`Abs`) carries the rank dim in one of two layouts:

  * PURE — `axis` d with `block == 1`: shape[d] == n_ranks, index d
    IS the rank coordinate (the spmd stacked layout).
  * BLOCKED — `axis` d with `block == B > 1`: shape[d] == n_ranks * B
    laid out RANK-MAJOR (index = r * B + j).  This is exactly what the
    vmap batching rules for `conv_general_dilated` emit: the rank axis
    merges into a batch or feature dim through a transpose-fused
    reshape, the conv runs with `feature_group_count` multiplied by
    n_ranks (group-confined — rank r's channels only convolve rank r's
    filters), and a second reshape splits the rank axis back out.
    Tracking the blocked layout through that sandwich is what lets the
    audit run on the real conv models (LeNetCifar, ResNet18) instead
    of an MLP proxy.

Opaque kernels (`pallas_call`) cannot be looked through; they are legal
ONLY when registered with an explicit rank-dim signature in
analysis/kernels.py (the flash-attention family and the arena/event
engines are the shipped entries) — an unregistered kernel is a
violation even on rank-invariant operands.

Soundness stance: UNKNOWN primitives are violations, not warnings — a
new op in the step must either be provably rank-pointwise (add a rule
here), be a declared exchange, or carry a declared kernel signature.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from eventgrad_tpu.analysis import kernels

#: cap on constant values carried through the fold (the permutation
#: vectors are [n_ranks]; anything big is never needed for an index)
_MAX_CONST_ELEMS = 1 << 16


@dataclasses.dataclass(frozen=True)
class Abs:
    """Abstract value: `axis` is the array dim carrying the rank
    coordinate (None = rank-invariant — the value does not depend on
    any rank's inputs); `block` is the rank-major block size of that
    dim (1 = the pure stacked layout, B > 1 = shape[axis] == n*B with
    index = r*B + j — the conv batching rules' merged layout); `const`
    is the concrete value when statically known (index pipelines)."""

    axis: Optional[int] = None
    const: Optional[np.ndarray] = None
    block: int = 1


@dataclasses.dataclass
class Exchange:
    """One declared cross-rank move: a constant-permutation gather."""

    offset: int  #: signed ring offset (dst reads from dst+offset)
    lane_shape: Tuple[int, ...]  #: per-rank payload shape
    dtype: str
    path: Tuple[str, ...]

    @property
    def lane_elems(self) -> int:
        return int(math.prod(self.lane_shape)) if self.lane_shape else 1


@dataclasses.dataclass
class Finding:
    kind: str  #: "violation" | "psum"
    prim: str
    reason: str
    path: Tuple[str, ...]


@dataclasses.dataclass
class RankFlowReport:
    n_ranks: int
    exchanges: List[Exchange]
    psums: List[Finding]
    violations: List[Finding]

    def exchange_offsets(self) -> List[int]:
        return sorted({e.offset for e in self.exchanges})


# --- primitive rule tables --------------------------------------------------

#: pointwise primitives: every ranked operand shares the rank axis and
#: the output inherits it — no data moves across ranks
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "atan2",
    "max", "min", "and", "or", "xor", "not", "neg", "sign", "abs",
    "exp", "exp2", "log", "log1p", "expm1", "sqrt", "rsqrt", "cbrt",
    "tanh", "tan", "sin", "cos", "asin", "acos", "atan", "sinh", "cosh",
    "asinh", "acosh", "atanh", "logistic", "erf", "erfc", "erf_inv",
    "floor", "ceil", "round", "nextafter", "is_finite", "square",
    "gt", "lt", "ge", "le", "eq", "ne", "select_n", "clamp",
    "gt_to", "lt_to", "ge_to", "le_to", "eq_to", "ne_to",
    "convert_element_type", "stop_gradient", "add_any", "copy",
    "reduce_precision", "real", "imag", "conj",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "population_count", "clz",
})

#: prefix-preserving primitives: output keeps the leading dims of the
#: input (rank axis survives in place); trailing dims may change
_PREFIX = frozenset({
    "random_wrap", "random_unwrap", "random_split", "random_bits",
    "random_fold_in", "random_seed", "bitcast_convert_type",
})

_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_or", "reduce_and", "reduce_xor", "argmax", "argmin",
})

#: windowed sweeps (pooling fwd + bwd): rank-pointwise iff the window
#: never touches the rank dim
_WINDOW = frozenset({
    "reduce_window_sum", "reduce_window_max", "reduce_window_min",
    "reduce_window", "select_and_scatter_add", "select_and_scatter",
})

_CUM = frozenset({"cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp"})

_FOLD = {
    "add": np.add, "sub": np.subtract, "mul": np.multiply,
    "rem": np.mod, "max": np.maximum, "min": np.minimum,
    "lt": np.less, "le": np.less_equal, "gt": np.greater,
    "ge": np.greater_equal, "eq": np.equal, "ne": np.not_equal,
    "and": np.bitwise_and, "or": np.bitwise_or, "xor": np.bitwise_xor,
    "not": np.invert, "neg": np.negative, "abs": np.abs,
}

_COLLECTIVE_VIOLATIONS = frozenset({
    "all_gather", "all_to_all", "reduce_scatter", "pgather", "pbroadcast",
})


def _const_of(v) -> Optional[np.ndarray]:
    if v is None:
        return None
    try:
        arr = np.asarray(v)
    except Exception:
        return None
    if arr.size > _MAX_CONST_ELEMS or arr.dtype == object:
        return None
    return arr


class _Flow:
    def __init__(self, n_ranks: int):
        self.n = n_ranks
        self.exchanges: List[Exchange] = []
        self.psums: List[Finding] = []
        self.violations: List[Finding] = []

    # -- helpers ------------------------------------------------------------

    def _mark(self):
        """Snapshot of the findings lists (fixpoint re-runs and cond
        branches truncate back to a mark so one runtime execution is
        recorded exactly once)."""
        return len(self.exchanges), len(self.psums), len(self.violations)

    def _reset(self, mark):
        e, p, v = mark
        del self.exchanges[e:]
        del self.psums[p:]
        del self.violations[v:]

    def _take_since(self, mark):
        e, p, v = mark
        taken = (self.exchanges[e:], self.psums[p:], self.violations[v:])
        self._reset(mark)
        return taken

    def _bad(self, eqn, path, reason) -> Abs:
        self.violations.append(
            Finding("violation", eqn.primitive.name, reason, path)
        )
        return Abs(None, None)

    def _read(self, env, v) -> Abs:
        if isinstance(v, jax.core.Literal):
            return Abs(None, _const_of(v.val))
        return env.get(v, Abs(None, None))

    def _common_rank(
        self, eqn, path, abs_in
    ) -> Tuple[Optional[int], int, bool]:
        """(axis, block, ok): the single rank layout shared by every
        ranked operand, or a violation if they disagree."""
        layouts = {(a.axis, a.block) for a in abs_in if a.axis is not None}
        if len(layouts) > 1:
            self._bad(
                eqn, path,
                f"operands carry rank layouts {sorted(layouts)} "
                "(axis, block) that do not agree",
            )
            return None, 1, False
        if layouts:
            d, b = next(iter(layouts))
            return d, b, True
        return None, 1, True

    def _blocked_guard(self, eqn, path, abs_in, n_out) -> Optional[List[Abs]]:
        """Conservative refusal: a merged (blocked) rank layout reaching
        a primitive with no blocked rule is a violation, not a guess."""
        for a in abs_in:
            if a.axis is not None and a.block != 1:
                return [self._bad(
                    eqn, path,
                    f"{eqn.primitive.name} over a rank-MERGED layout "
                    f"(axis {a.axis}, block {a.block}) — no blocked rule "
                    "proves this rank-pointwise",
                )] * n_out
        return None

    # -- entry point --------------------------------------------------------

    def run(self, closed, in_abs: Sequence[Abs], path=()) -> List[Abs]:
        jaxpr = closed.jaxpr
        env: Dict[Any, Abs] = {}
        for cv, cval in zip(jaxpr.constvars, closed.consts):
            env[cv] = Abs(None, _const_of(cval))
        if len(in_abs) != len(jaxpr.invars):
            raise ValueError(
                f"rankflow: {len(in_abs)} abstract inputs for "
                f"{len(jaxpr.invars)} invars"
            )
        for v, a in zip(jaxpr.invars, in_abs):
            env[v] = a
        self._run_eqns(jaxpr, env, path)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _run_jaxpr_open(self, jaxpr, consts_abs, in_abs, path) -> List[Abs]:
        """Bare Jaxpr whose constvars get abstract values (scan body)."""
        env: Dict[Any, Abs] = {}
        for cv, a in zip(jaxpr.constvars, consts_abs):
            env[cv] = a
        for v, a in zip(jaxpr.invars, in_abs):
            env[v] = a
        self._run_eqns(jaxpr, env, path)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _run_eqns(self, jaxpr, env, path):
        for eqn in jaxpr.eqns:
            abs_in = [self._read(env, v) for v in eqn.invars]
            abs_out = self._apply(eqn, abs_in, path)
            for v, a in zip(eqn.outvars, abs_out):
                env[v] = a

    # -- the per-primitive transfer function --------------------------------

    def _apply(self, eqn, abs_in: List[Abs], path) -> List[Abs]:
        prim = eqn.primitive.name
        n_out = len(eqn.outvars)
        p = eqn.params

        if prim in _ELEMENTWISE:
            d, blk, ok = self._common_rank(eqn, path, abs_in)
            const = None
            if ok and all(a.const is not None for a in abs_in):
                fn = _FOLD.get(prim)
                if prim == "select_n" and len(abs_in) == 3:
                    const = _const_of(np.where(
                        abs_in[0].const.astype(bool),
                        abs_in[2].const, abs_in[1].const,
                    ))
                elif prim == "convert_element_type":
                    const = _const_of(
                        abs_in[0].const.astype(p["new_dtype"])
                    )
                elif prim in ("stop_gradient", "copy"):
                    const = abs_in[0].const
                elif fn is not None:
                    try:
                        const = _const_of(fn(*[a.const for a in abs_in]))
                    except Exception:
                        const = None
            return [Abs(d, const, blk)] * n_out

        if prim in _PREFIX:
            a = abs_in[0]
            d = a.axis
            out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
            if d is not None and (
                len(out_shape) <= d or out_shape[d] != self.n * a.block
            ):
                return [self._bad(
                    eqn, path, f"{prim} drops the rank axis (dim {d})"
                )] * n_out
            return [Abs(d, None, a.block)] * n_out

        if prim == "broadcast_in_dim":
            a = abs_in[0]
            d = None if a.axis is None else int(p["broadcast_dimensions"][a.axis])
            const = None
            if a.const is not None:
                try:
                    shape = tuple(int(s) for s in p["shape"])
                    with_ones = [1] * len(shape)
                    for src, dst in enumerate(p["broadcast_dimensions"]):
                        with_ones[int(dst)] = a.const.shape[src]
                    const = _const_of(np.broadcast_to(
                        a.const.reshape(with_ones), shape
                    ))
                except Exception:
                    const = None
            return [Abs(d, const, a.block)]

        if prim == "reshape":
            return [self._reshape(eqn, abs_in, path)]

        if prim == "squeeze":
            a = abs_in[0]
            dims = tuple(int(x) for x in p["dimensions"])
            const = None
            if a.const is not None:
                try:
                    const = _const_of(np.squeeze(a.const, axis=dims))
                except Exception:
                    const = None
            if a.axis is None:
                return [Abs(None, const)]
            if a.axis in dims:
                return [self._bad(eqn, path, "squeeze removes the rank axis")]
            return [Abs(
                a.axis - sum(1 for x in dims if x < a.axis), const, a.block
            )]

        if prim == "transpose":
            a = abs_in[0]
            perm = tuple(int(x) for x in p["permutation"])
            d = None if a.axis is None else perm.index(a.axis)
            const = None
            if a.const is not None:
                try:
                    const = _const_of(np.transpose(a.const, perm))
                except Exception:
                    const = None
            return [Abs(d, const, a.block)]

        if prim == "slice":
            a = abs_in[0]
            const = None
            if a.const is not None:
                try:
                    idx = tuple(
                        slice(int(s), int(l), int(st))
                        for s, l, st in zip(
                            p["start_indices"], p["limit_indices"],
                            p["strides"] or [1] * len(p["start_indices"]),
                        )
                    )
                    const = _const_of(a.const[idx])
                except Exception:
                    const = None
            if a.axis is None:
                return [Abs(None, const)]
            d = a.axis
            strides = p["strides"] or [1] * len(p["start_indices"])
            if (
                int(p["start_indices"][d]) != 0
                or int(p["limit_indices"][d]) != self.n * a.block
                or int(strides[d]) != 1
            ):
                return [self._bad(
                    eqn, path,
                    "slice selects a subset of ranks (cross-rank read)",
                )]
            return [Abs(d, const, a.block)]

        if prim == "pad":
            a = abs_in[0]
            if a.axis is not None:
                cfg = p["padding_config"][a.axis]
                if tuple(int(x) for x in cfg) != (0, 0, 0):
                    return [self._bad(eqn, path, "pad alters the rank axis")]
            return [Abs(a.axis, None, a.block)]

        if prim == "concatenate":
            d, blk, ok = self._common_rank(eqn, path, abs_in)
            if not ok:
                return [Abs(None, None)]
            if d is not None and int(p["dimension"]) == d:
                return [self._bad(
                    eqn, path,
                    "concatenate along the rank axis reassembles ranks "
                    "(cross-rank write)",
                )]
            return [Abs(d, None, blk)]

        if prim == "iota":
            const = None
            shape = tuple(int(s) for s in p["shape"])
            if len(shape) == 1 and shape[0] <= _MAX_CONST_ELEMS:
                const = _const_of(
                    np.arange(shape[0]).astype(p["dtype"])
                )
            return [Abs(None, const)]

        if prim in _REDUCE:
            a = abs_in[0]
            axes = tuple(int(x) for x in p["axes"])
            if a.axis is not None and a.axis in axes:
                return [self._bad(
                    eqn, path,
                    f"{prim} reduces over the rank axis — cross-rank "
                    "information flow",
                )] * n_out
            d = (
                None if a.axis is None
                else a.axis - sum(1 for x in axes if x < a.axis)
            )
            return [Abs(d, None, a.block)] * n_out

        if prim in _WINDOW:
            return self._window(eqn, abs_in, path, n_out)

        if prim in _CUM:
            a = abs_in[0]
            if a.axis is not None and int(p["axis"]) == a.axis:
                return [self._bad(
                    eqn, path, f"{prim} scans across the rank axis"
                )]
            return [Abs(a.axis, None, a.block)]

        if prim == "sort":
            d, blk, ok = self._common_rank(eqn, path, abs_in)
            if ok and d is not None and int(p["dimension"]) == d:
                return [self._bad(eqn, path, "sort along the rank axis")] * n_out
            return [Abs(d, None, blk)] * n_out

        if prim == "top_k":
            a = abs_in[0]
            ndim = len(eqn.invars[0].aval.shape)
            if a.axis is not None and a.axis == ndim - 1:
                return [self._bad(eqn, path, "top_k along the rank axis")] * n_out
            return [Abs(a.axis, None, a.block)] * n_out

        if prim == "rev":
            a = abs_in[0]
            if a.axis is not None and a.axis in tuple(
                int(x) for x in p["dimensions"]
            ):
                return [self._bad(
                    eqn, path, "rev reverses the rank axis (a cross-rank "
                    "permutation outside the declared exchange)",
                )]
            return [Abs(a.axis, None, a.block)]

        if prim == "gather":
            blocked = self._blocked_guard(eqn, path, abs_in, 1)
            if blocked is not None:
                return blocked
            return [self._gather(eqn, abs_in, path)]

        if prim in ("scatter", "scatter-add", "scatter-mul", "scatter-min",
                    "scatter-max"):
            blocked = self._blocked_guard(eqn, path, abs_in, 1)
            if blocked is not None:
                return blocked
            return [self._scatter(eqn, abs_in, path)]

        if prim == "dot_general":
            blocked = self._blocked_guard(eqn, path, abs_in, 1)
            if blocked is not None:
                return blocked
            return [self._dot_general(eqn, abs_in, path)]

        if prim == "conv_general_dilated":
            return [self._conv(eqn, abs_in, path)]

        if prim == "pallas_call":
            return self._pallas(eqn, abs_in, path, n_out)

        if prim == "dynamic_slice":
            a = abs_in[0]
            if any(x.axis is not None for x in abs_in[1:]):
                return [self._bad(
                    eqn, path, "rank-dependent dynamic_slice start index"
                )]
            if a.axis is not None and (
                int(p["slice_sizes"][a.axis]) != self.n * a.block
            ):
                return [self._bad(
                    eqn, path, "dynamic_slice cuts the rank axis"
                )]
            return [Abs(a.axis, None, a.block)]

        if prim == "dynamic_update_slice":
            op, upd = abs_in[0], abs_in[1]
            if any(x.axis is not None for x in abs_in[2:]):
                return [self._bad(
                    eqn, path, "rank-dependent dynamic_update_slice index"
                )]
            d, blk, ok = self._common_rank(eqn, path, [op, upd])
            if not ok:
                return [Abs(None, None)]
            if d is not None and (
                tuple(eqn.invars[1].aval.shape)[d] != self.n * blk
            ):
                return [self._bad(
                    eqn, path, "dynamic_update_slice writes a subset of ranks"
                )]
            return [Abs(d, None, blk)]

        if prim == "psum":
            a = abs_in[0]
            axes = tuple(x for x in p["axes"] if isinstance(x, int))
            if a.axis is not None and a.axis in axes:
                self.psums.append(Finding(
                    "psum", prim,
                    "positional psum over the rank axis (allreduce/pmean)",
                    path,
                ))
                d = None  # reduced away: result is rank-invariant
                return [Abs(d, None)] * n_out
            d = (
                None if a.axis is None
                else a.axis - sum(1 for x in axes if x < a.axis)
            )
            return [Abs(d, None, a.block)] * n_out

        if prim == "ppermute":
            # shard_map / pmap form: explicit named-axis permutation
            perm = tuple((int(s), int(d)) for s, d in p["perm"])
            offs = {(s - d) % self.n for s, d in perm}
            off = offs.pop() if len(offs) == 1 else None
            if off is None:
                return [self._bad(
                    eqn, path, "ppermute with a non-uniform permutation"
                )] * n_out
            for ov in eqn.outvars:
                self.exchanges.append(Exchange(
                    offset=off if off <= self.n // 2 else off - self.n,
                    lane_shape=tuple(ov.aval.shape),
                    dtype=str(ov.aval.dtype),
                    path=path,
                ))
            return [Abs(a.axis, None, a.block) for a in abs_in[:n_out]]

        if prim in _COLLECTIVE_VIOLATIONS:
            return [self._bad(
                eqn, path, f"{prim}: undeclared cross-rank collective"
            )] * n_out

        # --- nested jaxprs --------------------------------------------------

        if prim == "pjit":
            return self.run(
                p["jaxpr"], abs_in, path + (p.get("name") or "pjit",)
            )

        if prim in ("closed_call", "core_call", "call"):
            return self.run(p["call_jaxpr"], abs_in, path + (prim,))

        if prim in ("custom_jvp_call", "custom_vjp_call",
                    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"):
            sub = p.get("call_jaxpr") or p.get("fun_jaxpr")
            if sub is None:
                return [self._bad(
                    eqn, path, f"{prim} without an inspectable call_jaxpr"
                )] * n_out
            return self.run(sub, abs_in, path + (prim,))

        if prim in ("remat", "checkpoint", "remat2"):
            sub = p["jaxpr"]
            if isinstance(sub, jax.core.Jaxpr):
                return self._run_jaxpr_open(sub, [], abs_in, path + (prim,))
            return self.run(sub, abs_in, path + (prim,))

        if prim == "scan":
            return self._scan(eqn, abs_in, path)

        if prim == "while":
            return self._while(eqn, abs_in, path)

        if prim == "cond":
            return self._cond(eqn, abs_in, path)

        return [self._bad(
            eqn, path,
            f"primitive '{prim}' has no rank-flow rule — prove it "
            "rank-pointwise (add a rule in analysis/rankflow.py) or "
            "declare it as an exchange",
        )] * n_out

    # -- the interesting primitives -----------------------------------------

    def _reshape(self, eqn, abs_in, path) -> Abs:
        """One rule for every reshape, including the transpose-fused
        form (`dimensions` param) the conv batching rules emit.

        With the rank coordinate at dim `a` (block B) of the (possibly
        pre-permuted) input shape, the flat index decomposes as
        ``flat = o*(n*inner) + r*inner + i`` with ``o < outer``, where
        ``outer = prod(shape[:a])`` and ``inner = B*prod(shape[a+1:])``.
        The output preserves the rank-major structure iff some output
        dim d2 satisfies ``prod(out[:d2]) == outer`` and
        ``out[d2] % n == 0`` — then the rank coordinate sits at d2 with
        block ``out[d2] // n`` (total-size equality makes the inner
        extents match automatically).  This one check covers the merge
        ([n, B, ...] -> [n*B, ...]), the split back, and every
        rank-preserving reshape; anything else cuts rank blocks across
        output dims and is flagged."""
        a = abs_in[0]
        p = eqn.params
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(eqn.outvars[0].aval.shape)
        dims = p.get("dimensions")
        const = None
        if a.const is not None:
            try:
                arr = a.const
                if dims is not None:
                    arr = np.transpose(arr, tuple(int(x) for x in dims))
                const = _const_of(arr.reshape(out_shape))
            except Exception:
                const = None
        if a.axis is None:
            return Abs(None, const)
        ax = a.axis
        shape_perm = in_shape
        if dims is not None:
            dims = tuple(int(x) for x in dims)
            shape_perm = tuple(in_shape[d] for d in dims)
            ax = dims.index(ax)
        outer = int(math.prod(shape_perm[:ax]))
        for d2 in range(len(out_shape)):
            if (
                int(math.prod(out_shape[:d2])) == outer
                and out_shape[d2] >= self.n
                and out_shape[d2] % self.n == 0
            ):
                return Abs(d2, const, out_shape[d2] // self.n)
        return self._bad(
            eqn, path,
            f"reshape {in_shape}->{out_shape} splits the rank axis "
            f"(dim {a.axis}, block {a.block}) across output dims — rank "
            "blocks are no longer separable",
        )

    def _conv(self, eqn, abs_in, path) -> Abs:
        """`conv_general_dilated`: rank-pointwise in exactly three
        shapes, proven via `dimension_numbers` —

        * per-rank batch: lhs carries rank at the lhs BATCH dim, the
          filters are rank-invariant; the window sweep never touches
          the batch dim, so the output batch dim inherits the rank.
        * per-rank filters: rhs carries rank at the OUTPUT-FEATURE dim
          on rank-invariant data; rank r's output channels read only
          rank r's filters.
        * the vmap batching rule's group-confined feature merge: rank
          merged rank-major into the lhs FEATURE dim (and the rhs
          output-feature dim), with `feature_group_count` divisible by
          n_ranks — grouped convolution connects input group g only to
          filter group g, and rank-major blocking makes rank r own
          exactly groups [r*fgc/n, (r+1)*fgc/n), so no output channel
          ever reads another rank's channels.

        Anything else (rank in a spatial dim, a feature merge without
        group confinement, batch_group_count tricks) is a violation."""
        lhs, rhs = abs_in[0], abs_in[1]
        if lhs.axis is None and rhs.axis is None:
            return Abs(None, None)
        p = eqn.params
        dn = p["dimension_numbers"]
        lhs_spec = tuple(int(x) for x in dn.lhs_spec)  # (batch, feat, *spatial)
        rhs_spec = tuple(int(x) for x in dn.rhs_spec)  # (out_f, in_f, *spatial)
        out_spec = tuple(int(x) for x in dn.out_spec)  # (batch, feat, *spatial)
        fgc = int(p.get("feature_group_count", 1))
        bgc = int(p.get("batch_group_count", 1))
        out_shape = tuple(eqn.outvars[0].aval.shape)
        if bgc != 1:
            return self._bad(
                eqn, path,
                "conv with batch_group_count != 1 over a rank-carrying "
                "operand has no rank-flow rule",
            )
        # per-rank batch, shared filters: rank rides the batch dim
        if rhs.axis is None and lhs.axis == lhs_spec[0]:
            return Abs(out_spec[0], None, lhs.block)
        if lhs.axis is not None:
            if lhs.axis != lhs_spec[1]:
                return self._bad(
                    eqn, path,
                    f"conv input carries the rank axis at dim {lhs.axis} — "
                    "neither the batch dim nor the group-confined feature "
                    "dim; rank data would enter the spatial window",
                )
            if fgc % self.n != 0:
                return self._bad(
                    eqn, path,
                    "conv contracts the rank axis across feature groups "
                    f"(feature_group_count {fgc} not divisible by n_ranks "
                    f"{self.n}) — every output channel reads every rank's "
                    "channels",
                )
        if rhs.axis is not None and rhs.axis != rhs_spec[0]:
            return self._bad(
                eqn, path,
                f"conv filters carry the rank axis at dim {rhs.axis}, not "
                "the output-feature dim — rank blocks would contract "
                "together",
            )
        out_feat = out_shape[out_spec[1]]
        if out_feat % self.n != 0:
            return self._bad(
                eqn, path,
                f"conv output feature dim {out_feat} does not split into "
                f"{self.n} rank blocks",
            )
        return Abs(out_spec[1], None, out_feat // self.n)

    def _window(self, eqn, abs_in, path, n_out) -> List[Abs]:
        """reduce_window family + select_and_scatter_add (pooling fwd
        and bwd): rank-pointwise iff the window sweep leaves the rank
        dim untouched — unit window, unit stride, no padding, no
        dilation on that dim."""
        p = eqn.params
        d, blk, ok = self._common_rank(eqn, path, abs_in)
        if not ok:
            return [Abs(None, None)] * n_out
        if d is None:
            return [Abs(None, None)] * n_out
        win = tuple(int(x) for x in p["window_dimensions"])
        strides = tuple(int(x) for x in p["window_strides"])
        pads = tuple(tuple(int(x) for x in q) for q in p["padding"])
        base_dil = p.get("base_dilation")
        win_dil = p.get("window_dilation")
        problems = (
            len(win) <= d
            or win[d] != 1
            or strides[d] != 1
            or pads[d] != (0, 0)
            or (base_dil is not None and int(base_dil[d]) != 1)
            or (win_dil is not None and int(win_dil[d]) != 1)
        )
        if problems:
            return [self._bad(
                eqn, path,
                f"{eqn.primitive.name} window touches the rank dim {d} "
                f"(window {win}, strides {strides}) — values would mix "
                "across ranks",
            )] * n_out
        return [Abs(d, None, blk)] * n_out

    def _pallas(self, eqn, abs_in, path, n_out) -> List[Abs]:
        """`pallas_call` is an opaque boundary: legal ONLY under a
        declared rank-dim signature (analysis/kernels.py).  Unknown
        kernels are violations even on rank-invariant operands —
        registration is the reviewed claim that the kernel body never
        indexes across the lifted grid dim."""
        p = eqn.params
        nsi = p.get("name_and_src_info")
        traced = getattr(nsi, "name", None) or p.get("name") or "<unnamed>"
        sig = kernels.lookup(str(traced))
        if sig is None:
            return [self._bad(
                eqn, path,
                f"unregistered pallas kernel "
                f"'{kernels.base_name(str(traced))}' — an opaque kernel is "
                "legal only with a declared rank-dim signature "
                "(analysis/kernels.py; docs/ANALYSIS.md 'Registering a "
                "kernel')",
            )] * n_out
        ranked = [a for a in abs_in if a.axis is not None]
        if not ranked:
            return [Abs(None, None)] * n_out
        for a in ranked:
            if a.axis != sig.lifted_dim or a.block != 1:
                return [self._bad(
                    eqn, path,
                    f"pallas kernel '{sig.name}' operand carries the rank "
                    f"axis at dim {a.axis} (block {a.block}); the declared "
                    f"signature lifts at dim {sig.lifted_dim}",
                )] * n_out
        outs = []
        for ov in eqn.outvars:
            shape = tuple(ov.aval.shape)
            if len(shape) <= sig.lifted_dim or shape[sig.lifted_dim] != self.n:
                return [self._bad(
                    eqn, path,
                    f"pallas kernel '{sig.name}' output shape {shape} does "
                    f"not carry the rank axis at declared dim "
                    f"{sig.lifted_dim}",
                )] * n_out
            outs.append(Abs(sig.lifted_dim, None))
        return outs

    def _gather(self, eqn, abs_in, path) -> Abs:
        op, idx = abs_in[0], abs_in[1]
        dn = eqn.params["dimension_numbers"]
        offset_dims = tuple(int(x) for x in dn.offset_dims)
        collapsed = tuple(int(x) for x in dn.collapsed_slice_dims)
        start_map = tuple(int(x) for x in dn.start_index_map)
        op_batch = tuple(int(x) for x in getattr(dn, "operand_batching_dims", ()))
        idx_batch = tuple(
            int(x) for x in getattr(dn, "start_indices_batching_dims", ())
        )
        slice_sizes = tuple(int(x) for x in eqn.params["slice_sizes"])
        idx_ndim = len(eqn.invars[1].aval.shape)
        out_ndim = len(eqn.outvars[0].aval.shape)
        # output dims not fed by slices come from the indices' non-vector
        # dims, in order (XLA gather semantics; the last indices dim is
        # the index vector)
        batch_positions = [q for q in range(out_ndim) if q not in offset_dims]
        idx_nonvec = list(range(idx_ndim - 1))

        def out_axis_from_idx(di):
            if di not in idx_nonvec:
                return None
            return batch_positions[idx_nonvec.index(di)]

        if op.axis is None:
            if idx.axis is None:
                return Abs(None, None)
            d_out = out_axis_from_idx(idx.axis)
            if d_out is None:
                return self._bad(
                    eqn, path,
                    "rank axis used as the gather index vector dim",
                )
            # per-rank selection from a rank-invariant table: no
            # cross-rank information flow
            return Abs(d_out, None)

        d = op.axis
        if d in op_batch:
            if idx.axis is None:
                # rank-invariant indices applied within each rank's
                # batch slice: out[r] = operand[r][idx] — pointwise
                di = idx_batch[op_batch.index(d)]
                return Abs(out_axis_from_idx(di), None)
            if idx.axis not in idx_batch:
                return self._bad(
                    eqn, path,
                    "batched gather whose indices carry the rank axis "
                    "outside a batching dim",
                )
            return Abs(out_axis_from_idx(idx.axis), None)

        if d in start_map:
            # data moves ACROSS the rank axis, driven by the indices:
            # legal only as a constant permutation (the ppermute lowering)
            if idx.axis is not None:
                return self._bad(
                    eqn, path,
                    "rank-indexed gather across the rank axis (a rank's "
                    "data chosen by another rank's value)",
                )
            perm = None
            if idx.const is not None:
                flat = np.asarray(idx.const).reshape(-1)
                if (
                    flat.size == self.n
                    and np.issubdtype(flat.dtype, np.integer)
                    and sorted(int(x) for x in flat) == list(range(self.n))
                ):
                    perm = [int(x) for x in flat]
            if perm is None:
                return self._bad(
                    eqn, path,
                    "gather across the rank axis whose indices are not a "
                    "static permutation — undeclared cross-rank data "
                    "movement",
                )
            offs = {(perm[r] - r) % self.n for r in range(self.n)}
            if len(offs) != 1:
                return self._bad(
                    eqn, path,
                    f"cross-rank gather permutation {perm} is not a "
                    "uniform ring shift",
                )
            off = offs.pop()
            out_shape = tuple(eqn.outvars[0].aval.shape)
            d_out = out_axis_from_idx(idx_nonvec[0]) if idx_nonvec else None
            if d_out is None:
                return self._bad(
                    eqn, path, "exchange gather with no output rank dim"
                )
            lane = tuple(
                s for q, s in enumerate(out_shape) if q != d_out
            )
            self.exchanges.append(Exchange(
                offset=off if off <= self.n // 2 else off - self.n,
                lane_shape=lane,
                dtype=str(eqn.outvars[0].aval.dtype),
                path=path,
            ))
            return Abs(d_out, None)

        if d in collapsed:
            return self._bad(
                eqn, path, "gather collapses the rank axis"
            )
        # rank dim passes through whole as a slice dim
        if slice_sizes[d] != self.n:
            return self._bad(
                eqn, path, "gather slices a subset of ranks"
            )
        surviving = [
            q for q in range(len(slice_sizes))
            if q not in collapsed and q not in op_batch
        ]
        return Abs(offset_dims[surviving.index(d)], None)

    def _scatter(self, eqn, abs_in, path) -> Abs:
        op, idx, upd = abs_in[0], abs_in[1], abs_in[2]
        dn = eqn.params["dimension_numbers"]
        op_batch = tuple(int(x) for x in getattr(dn, "operand_batching_dims", ()))
        idx_batch = tuple(
            int(x) for x in getattr(dn, "scatter_indices_batching_dims", ())
        )
        scatter_op_dims = tuple(
            int(x) for x in dn.scatter_dims_to_operand_dims
        )
        update_window_dims = tuple(int(x) for x in dn.update_window_dims)
        inserted = tuple(int(x) for x in dn.inserted_window_dims)
        if op.axis is None and idx.axis is None and upd.axis is None:
            return Abs(None, None)
        if op.axis is not None and op.axis in scatter_op_dims:
            return self._bad(
                eqn, path,
                "scatter writes across the rank axis (cross-rank write)",
            )
        if op.axis is not None and op.axis in op_batch:
            if idx.axis is not None and idx.axis not in idx_batch:
                return self._bad(
                    eqn, path,
                    "batched scatter whose indices carry the rank axis "
                    "outside a batching dim",
                )
            return Abs(op.axis, None)
        if (
            op.axis is None
            and idx.axis is not None and idx.axis in idx_batch
            and op_batch
        ):
            # rank-invariant base (e.g. a zeros buffer) scattered with
            # per-rank batched indices/updates: each rank's slice only
            # receives that rank's updates — pointwise
            return Abs(op_batch[idx_batch.index(idx.axis)], None)
        if op.axis is not None and idx.axis is None and upd.axis is None:
            # rank-invariant updates written identically into every
            # rank's slice of a pass-through rank dim
            return Abs(op.axis, None)
        if (
            idx.axis is None
            and upd.axis is not None and upd.axis in update_window_dims
            and (op.axis is None or op.axis not in op_batch)
        ):
            # the position-embedding-gradient shape: rank rides a WINDOW
            # dim.  Window dims map, in order, to the operand dims that
            # are neither inserted nor operand-batching; when the
            # update's rank dim maps to the operand's rank dim (or the
            # operand is a rank-invariant zeros base), every scatter
            # write stays inside its own rank's slice — the indices
            # (rank-invariant) choose positions along OTHER dims only
            op_ndim = len(eqn.invars[0].aval.shape)
            window_to_op = [
                q for q in range(op_ndim)
                if q not in inserted and q not in op_batch
            ]
            mapped = window_to_op[update_window_dims.index(upd.axis)]
            if (
                mapped not in scatter_op_dims
                and op.axis in (None, mapped)
            ):
                return Abs(mapped, None)
        return self._bad(
            eqn, path, "scatter mixes ranked and unranked operands in a "
            "shape the rules cannot prove rank-pointwise",
        )

    def _dot_general(self, eqn, abs_in, path) -> Abs:
        lhs, rhs = abs_in[0], abs_in[1]
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lc, rc = tuple(int(x) for x in lc), tuple(int(x) for x in rc)
        lb, rb = tuple(int(x) for x in lb), tuple(int(x) for x in rb)
        lhs_ndim = len(eqn.invars[0].aval.shape)
        rhs_ndim = len(eqn.invars[1].aval.shape)
        lhs_free = [q for q in range(lhs_ndim) if q not in lc and q not in lb]
        rhs_free = [q for q in range(rhs_ndim) if q not in rc and q not in rb]

        def out_pos_lhs(d):
            if d in lb:
                return lb.index(d)
            return len(lb) + lhs_free.index(d)

        def out_pos_rhs(d):
            if d in rb:
                return rb.index(d)
            return len(lb) + len(lhs_free) + rhs_free.index(d)

        if lhs.axis is None and rhs.axis is None:
            return Abs(None, None)
        for a, contract in ((lhs, lc), (rhs, rc)):
            if a.axis is not None and a.axis in contract:
                return self._bad(
                    eqn, path,
                    "dot_general contracts over the rank axis — a "
                    "cross-rank reduction",
                )
        if lhs.axis is not None and rhs.axis is not None:
            if lhs.axis in lb and rhs.axis in rb and (
                lb.index(lhs.axis) == rb.index(rhs.axis)
            ):
                return Abs(lb.index(lhs.axis), None)
            return self._bad(
                eqn, path,
                "dot_general pairs two rank-carrying operands outside a "
                "shared batch dim — every rank sees every rank",
            )
        if lhs.axis is not None:
            return Abs(out_pos_lhs(lhs.axis), None)
        return Abs(out_pos_rhs(rhs.axis), None)

    # -- control flow --------------------------------------------------------

    def _scan(self, eqn, abs_in, path) -> List[Abs]:
        p = eqn.params
        nc, ncar = int(p["num_consts"]), int(p["num_carry"])
        consts, carries, xs = (
            abs_in[:nc], abs_in[nc:nc + ncar], abs_in[nc + ncar:],
        )
        xs_body = []
        for a, v in zip(xs, eqn.invars[nc + ncar:]):
            if a.axis == 0:
                return [self._bad(
                    eqn, path, "scan iterates OVER the rank axis — each "
                    "step would see one rank's data with carried state "
                    "across ranks",
                )] * len(eqn.outvars)
            xs_body.append(Abs(
                None if a.axis is None else a.axis - 1, None, a.block
            ))
        carry_abs = list(carries)
        body = p["jaxpr"]  # ClosedJaxpr
        mark = self._mark()
        for _ in range(3):
            # each fixpoint re-run replaces (not appends to) the body's
            # findings: one scan body, one set of exchanges/violations
            self._reset(mark)
            outs = self.run(
                body, list(consts) + carry_abs + xs_body, path + ("scan",)
            )
            new_carry = [Abs(a.axis, None, a.block) for a in outs[:ncar]]
            if (
                [(a.axis, a.block) for a in new_carry]
                == [(a.axis, a.block) for a in carry_abs]
            ):
                break
            carry_abs = [
                Abs(o.axis, None, o.block) if o.axis is not None
                else Abs(i.axis, None, i.block)
                for i, o in zip(carry_abs, new_carry)
            ]
        else:
            return [self._bad(
                eqn, path, "scan carry rank structure did not stabilize"
            )] * len(eqn.outvars)
        ys = [
            Abs(None if a.axis is None else a.axis + 1, None, a.block)
            for a in outs[ncar:]
        ]
        return [Abs(a.axis, None, a.block) for a in outs[:ncar]] + ys

    def _while(self, eqn, abs_in, path) -> List[Abs]:
        p = eqn.params
        cn, bn = int(p["cond_nconsts"]), int(p["body_nconsts"])
        cond_c, body_c = abs_in[:cn], abs_in[cn:cn + bn]
        carry = list(abs_in[cn + bn:])
        mark = self._mark()
        for _ in range(3):
            self._reset(mark)
            self.run(
                p["cond_jaxpr"], list(cond_c) + carry, path + ("while.cond",)
            )
            outs = self.run(
                p["body_jaxpr"], list(body_c) + carry, path + ("while.body",)
            )
            if (
                [(a.axis, a.block) for a in outs]
                == [(a.axis, a.block) for a in carry]
            ):
                break
            carry = [
                Abs(o.axis, None, o.block) if o.axis is not None
                else Abs(i.axis, None, i.block)
                for i, o in zip(carry, outs)
            ]
        else:
            return [self._bad(
                eqn, path, "while carry rank structure did not stabilize"
            )] * len(eqn.outvars)
        return [Abs(a.axis, None, a.block) for a in carry]

    def _cond(self, eqn, abs_in, path) -> List[Abs]:
        pred, ops = abs_in[0], abs_in[1:]
        if pred.axis is not None:
            return [self._bad(
                eqn, path, "cond predicate carries the rank axis "
                "(rank-varying control flow)",
            )] * len(eqn.outvars)
        # at runtime exactly ONE branch executes: record each branch's
        # findings separately, keep every branch's violations/psums, but
        # count the exchange lanes once — and only if the branches agree
        # on them (branches shipping different wires is itself a
        # violation: the step's wire would be control-flow-dependent)
        per_branch, branch_finds = [], []
        for i, br in enumerate(eqn.params["branches"]):
            mark = self._mark()
            per_branch.append(self.run(br, list(ops), path + (f"cond.{i}",)))
            branch_finds.append(self._take_since(mark))
        for exchanges, psums, violations in branch_finds:
            self.psums.extend(psums)
            self.violations.extend(violations)
        sigs = [
            sorted((e.offset, e.lane_shape, e.dtype) for e in ex)
            for ex, _, _ in branch_finds
        ]
        self.exchanges.extend(branch_finds[0][0])
        if any(s != sigs[0] for s in sigs[1:]):
            self.violations.append(Finding(
                "violation", "cond",
                "cond branches ship different exchange lanes — the wire "
                "format would depend on control flow",
                path,
            ))
        outs = []
        for k in range(len(eqn.outvars)):
            layouts = {
                (b[k].axis, b[k].block)
                for b in per_branch if b[k].axis is not None
            }
            if len(layouts) > 1:
                outs.append(self._bad(
                    eqn, path,
                    f"cond branches disagree on output {k}'s rank axis",
                ))
            elif layouts:
                d, blk = next(iter(layouts))
                outs.append(Abs(d, None, blk))
            else:
                outs.append(Abs(None, None))
        return outs


def analyze(
    closed_jaxpr: "jax.core.ClosedJaxpr",
    n_ranks: int,
    in_axes: Optional[Sequence[Optional[int]]] = None,
) -> RankFlowReport:
    """Run the rank-isolation dataflow over a lifted step's closed jaxpr.

    `in_axes` gives the rank-axis position per flat invar; by default
    every invar whose leading dim equals `n_ranks` is assumed stacked at
    axis 0 (the spmd vmap-lift layout) and everything else is
    rank-invariant."""
    if in_axes is None:
        in_axes = [
            0 if (tuple(v.aval.shape)[:1] == (n_ranks,)) else None
            for v in closed_jaxpr.jaxpr.invars
        ]
    flow = _Flow(n_ranks)
    flow.run(closed_jaxpr, [Abs(d, None) for d in in_axes])
    return RankFlowReport(
        n_ranks=n_ranks,
        exchanges=flow.exchanges,
        psums=flow.psums,
        violations=flow.violations,
    )
