"""Test harness: emulate an 8-device mesh on CPU.

The environment pins JAX_PLATFORMS=axon (the real TPU tunnel) and pre-imports
jax via PYTHONPATH sitecustomize, so plain env vars are not enough; we must
also flip the config before any backend initializes. XLA_FLAGS still has to
be set before the CPU client spins up.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, f"expected 8 CPU devices, got {jax.devices()}"
