"""bfloat16 wire format: gossip payloads downcast for the transfer (half
the ICI/DCN bytes of the reference's float32 MPI wire), upcast on receipt;
local parameters, event norms, and thresholds stay full precision."""

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train


def _go(algo, wire_bf16, **kw):
    x, y = synthetic_dataset(128, (28, 28, 1), seed=6)
    return train(
        MLP(), Ring(4), x, y,
        algo=algo, epochs=2, batch_size=8, learning_rate=0.05,
        event_cfg=EventConfig(adaptive=True, horizon=0.9, warmup_passes=2),
        seed=1, log_every_epoch=False, wire_bf16=wire_bf16, **kw,
    )


def test_bytes_halve_and_training_stays_close():
    state32, hist32 = _go("eventgrad", False)
    state16, hist16 = _go("eventgrad", True)
    # accounting: same fired pattern costs half the bytes on the wire
    assert hist16[0]["num_events"] == hist32[0]["num_events"]
    np.testing.assert_allclose(
        hist16[0]["sent_bytes_per_step_per_chip"],
        hist32[0]["sent_bytes_per_step_per_chip"] / 2,
    )
    # training dynamics stay in the same regime (bf16 has ~3 decimal digits)
    assert abs(hist16[-1]["loss"] - hist32[-1]["loss"]) < 0.1
    for a, b in zip(
        jax.tree.leaves(state16.params), jax.tree.leaves(state32.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2)


def test_threshold0_equivalence_holds_on_bf16_wire():
    """eventgrad with threshold 0 must remain bitwise D-PSGD when both ride
    the bf16 wire (identical rounding on both paths)."""
    cfg0 = EventConfig(adaptive=False, constant=0.0, warmup_passes=0)
    x, y = synthetic_dataset(128, (28, 28, 1), seed=6)
    kw = dict(epochs=2, batch_size=8, learning_rate=0.05, seed=1,
              log_every_epoch=False, wire_bf16=True)
    s_ev, _ = train(MLP(), Ring(4), x, y, algo="eventgrad",
                    event_cfg=cfg0, **kw)
    s_dp, _ = train(MLP(), Ring(4), x, y, algo="dpsgd", **kw)
    for a, b in zip(jax.tree.leaves(s_ev.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sparse_wire_bf16_runs_and_counts_6_bytes():
    _, h32 = _go("sp_eventgrad", False)
    _, h16 = _go("sp_eventgrad", True)
    assert h16[0]["num_events"] == h32[0]["num_events"]
    np.testing.assert_allclose(
        h16[0]["sent_bytes_per_step_per_chip"] / h32[0]["sent_bytes_per_step_per_chip"],
        6.0 / 8.0,  # bf16 value + int32 index vs f32 value + int32 index
    )
    assert np.isfinite(h16[-1]["loss"])


def test_cli_wire_bf16_rejects_allreduce():
    import pytest as _pytest

    from eventgrad_tpu.cli import main

    with _pytest.raises(SystemExit, match="wire-bf16"):
        main(["--algo", "allreduce", "--wire-bf16"])
