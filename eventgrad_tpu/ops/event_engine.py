"""Fused flat-arena event engine: propose + gate + pack in one pass.

The tree-path hot chain of the EventGraD step re-derives structure per
consumer — `jax.tree.flatten(params)` for the norms, a fresh
`ravel_pytree` + segment-id materialization + separate masking pass for
the wire, the capacity gate over a rebuilt sizes tuple, and
`_compact_pack`'s own ravel. `event_propose_pack` runs the whole sender
side as ONE pass against the lru-cached ArenaSpec (parallel/arena.py):

    per-leaf drift norms -> threshold check / warmup / silence bound
    (events.propose, unchanged [L]-vector state machine)
    -> capacity_gate admission (compact wire only)
    -> compact pack of the admitted leaves' elements straight off the
       arena-ordered payload (the compact path's single [n] assembly).

The masked-wire builder (`masked_wire`) covers the [n]-sized elementwise
mask/quantize stage as a Pallas TPU kernel with a jnp twin
(`masked_wire_reference`) — the twin is bitwise (same `where`/quantize
elementwise ops) and the flat exchange inlines its per-leaf-fused form
(collectives.masked_neighbor_vals_flat); the kernel is benched
Pallas-vs-XLA in bench_kernels.py (`arena` selector) and earns dispatch
through ops/arena_tuning.py measurements, the same measure-and-demote
policy as fused_update.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

try:  # TPU memory spaces only exist on TPU builds; interpret mode elsewhere
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from eventgrad_tpu.parallel.arena import ArenaSpec
from eventgrad_tpu.parallel.collectives import _compact_pack
from eventgrad_tpu.parallel.events import (
    EventConfig, EventProposal, EventState, capacity_gate, propose,
)

_LANES = 128
_BLOCK_ROWS = 512


def event_propose_pack(
    params: Any,
    state: EventState,
    pass_num: jnp.ndarray,
    cfg: EventConfig,
    spec: ArenaSpec,
    capacity: Optional[int] = None,
    force_fire: Any = None,
    suppress_fire: Any = None,
) -> Tuple[EventProposal, jnp.ndarray, Optional[jnp.ndarray],
           Optional[jnp.ndarray]]:
    """One fused pass of the sender side: trigger -> gate -> pack.

    `suppress_fire` (optional bool scalar or [L]) clears the fire bits
    BEFORE the gate and the pack — the integrity engine's quarantine
    channel (chaos/integrity.py): a rank whose gradients went non-finite
    ships nothing this pass, and receivers see one more event that did
    not fire. Suppression wins over force_fire (a quarantined rank must
    not answer a forced-sync request with poisoned values), and the
    suppressed leaves are never committed, so they re-contend next pass
    exactly like a capacity deferral.

    Returns (proposal, effective fire bits, packed wire buffer, per-
    position leaf ids). With `capacity=None` (dense/masked wires) the
    effective bits are the raw trigger decision and the pack outputs are
    None; with a compact capacity the bits are the `capacity_gate`d
    subset (max_silence-overdue and force-fired leaves claim budget
    first, exactly the tree path's priority rule) and `packed` holds the
    admitted leaves' elements, gathered straight off the arena-ordered
    payload — the single [n] assembly of the compact path, subsuming the
    tree chain's separate flatten -> propose -> gate -> ravel -> pack
    materializations."""
    prop = propose(params, state, pass_num, cfg, force_fire=force_fire)
    fire_vec = prop.fire_vec
    if suppress_fire is not None:
        fire_vec = fire_vec & ~jnp.broadcast_to(suppress_fire, fire_vec.shape)
    packed = leaf_id = None
    if capacity is not None:
        pri = None
        if cfg.max_silence > 0:
            pri = prop.iter_diff >= cfg.max_silence
        if force_fire is not None:
            ff = jnp.broadcast_to(force_fire, fire_vec.shape)
            pri = ff if pri is None else (pri | ff)
        fire_vec = capacity_gate(
            fire_vec, spec.sizes, int(capacity), priority=pri
        )
        # the pack source: leaves in arena order. The gather touches
        # FIRED leaves only (plus a masked-out clip lane), so the
        # unmasked assembly packs bitwise what the masked one would.
        leaves = spec.treedef.flatten_up_to(params)
        if len(leaves) == 1:
            flat_src = leaves[0].reshape(-1)
        else:
            flat_src = jnp.concatenate([l.reshape(-1) for l in leaves])
        packed, leaf_id = _compact_pack(
            flat_src, fire_vec, spec.sizes, spec.starts, int(capacity)
        )
    return prop, fire_vec, packed, leaf_id


# ---------------------------------------------------------------------------
# masked-wire builder kernel: the [n]-sized elementwise stage

def _mask_kernel(f_ref, b_ref, o_ref):
    # INVARIANT: strictly elementwise (partial trailing block relies on
    # Mosaic masking OOB stores; see ops/fused_update.py).
    o_ref[:] = jnp.where(b_ref[:] > 0, f_ref[:], jnp.zeros((), f_ref.dtype))


def _mask_quant_kernel(f_ref, b_ref, s_ref, o_ref):
    masked = jnp.where(b_ref[:] > 0, f_ref[:], jnp.zeros((), f_ref.dtype))
    o_ref[:] = jnp.clip(jnp.round(masked / s_ref[:]), -127, 127)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _masked_wire_pallas(flat, fire_f32, scale_exp, *, interpret):
    n = flat.size
    ragged = n % _LANES != 0
    if ragged:
        padded = -(-n // _LANES) * _LANES
        prep = lambda x: jnp.pad(
            x.reshape(-1).astype(jnp.float32), (0, padded - n)
        ).reshape(-1, _LANES)
    else:
        prep = lambda x: x.reshape(-1, _LANES).astype(jnp.float32)

    args = [prep(flat), prep(fire_f32)]
    if scale_exp is not None:
        # pad scales with 1s: the padded lanes divide by 1, not 0
        pad_one = (
            (lambda x: jnp.pad(
                x.reshape(-1).astype(jnp.float32), (0, padded - n),
                constant_values=1.0,
            ).reshape(-1, _LANES))
            if ragged else prep
        )
        args.append(pad_one(scale_exp))
    rows = args[0].shape[0]
    grid = (pl.cdiv(rows, _BLOCK_ROWS),)
    spec = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES),
        lambda i: (i, 0),
        **({"memory_space": _VMEM}
           if (_VMEM is not None and not interpret) else {}),
    )
    extra = {}
    if not interpret and pltpu is not None:
        extra["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        )
    out = pl.pallas_call(
        _mask_kernel if scale_exp is None else _mask_quant_kernel,
        out_shape=jax.ShapeDtypeStruct(args[0].shape, jnp.float32),
        grid=grid,
        in_specs=[spec] * len(args),
        out_specs=spec,
        interpret=interpret,
        **extra,
    )(*args)
    return out.reshape(-1)[:n]


def masked_wire(
    flat: jnp.ndarray,
    fire_exp: jnp.ndarray,
    scale_exp: Optional[jnp.ndarray] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Build the masked wire buffer in one HBM pass: zero the non-fired
    positions (`fire_exp` = per-position fire bits, i.e. fire_vec[seg]),
    optionally int8-quantizing against per-position scales in the same
    pass. Returns f32 (int8 cast happens at the ship site). Pallas TPU
    kernel; `masked_wire_reference` is the bitwise jnp twin."""
    out = _masked_wire_pallas(
        flat, fire_exp.astype(jnp.float32), scale_exp, interpret=interpret
    )
    return out.astype(flat.dtype) if scale_exp is None else out


def masked_wire_reference(
    flat: jnp.ndarray,
    fire_exp: jnp.ndarray,
    scale_exp: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """jnp twin of `masked_wire` (also the non-TPU path inside the
    collectives flat exchanges)."""
    masked = jnp.where(fire_exp, flat, jnp.zeros_like(flat))
    if scale_exp is None:
        return masked
    return jnp.clip(jnp.round(masked / scale_exp), -127, 127)
