from eventgrad_tpu.train.state import TrainState, init_train_state
from eventgrad_tpu.train.steps import make_train_step, ALGOS
from eventgrad_tpu.train.loop import train, evaluate, consensus_params
