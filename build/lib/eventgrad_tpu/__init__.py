"""eventgrad-tpu: TPU-native communication-efficient decentralized training.

A from-scratch JAX/XLA rebuild of the capabilities of soumyadipghosh/eventgrad
(reference at /root/reference, C++17/LibTorch/MPI): centralized AllReduce
data-parallel SGD, decentralized D-PSGD ring gossip, event-triggered gossip
(EventGraD), and top-k sparsified EventGraD — expressed as pure, jit-compiled
SPMD programs over a named `jax.sharding.Mesh` instead of MPI processes.

Design notes (TPU-first):
  * The reference's MPI rank/ring setup (dmnist/event/event.cpp:105-124)
    becomes a named-axis device mesh (`eventgrad_tpu.parallel.topology`).
  * MPI_Allreduce (dmnist/cent/cent.cpp:135-140) becomes `jax.lax.pmean`.
  * Ring neighbor sends (dmnist/decent/decent.cpp:192-208) become
    `jax.lax.ppermute` shifts on the mesh axis — they ride the ICI torus.
  * Event-triggered one-sided RMA puts (dmnist/event/event.cpp:346-360)
    become *masked* ppermute: a fire bit plus a zero-masked payload, with the
    receiver keeping its stale buffer when the bit is off. Deterministic by
    construction, unlike the reference's torn-read RMA semantics.
  * All mutable per-parameter state (thresholds, slope history, neighbor
    buffers, top-k shadow replicas — event.cpp:181-225, spevent.cpp:128-136)
    is explicit pytree state threaded through the train step.
"""

from eventgrad_tpu.version import __version__

from eventgrad_tpu.parallel.topology import Ring, Torus, Topology
from eventgrad_tpu.parallel.spmd import spmd, stack_for_ranks, build_mesh
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel.events import EventConfig, EventState
from eventgrad_tpu.parallel.sparsify import SparseConfig, SparseState

__all__ = [
    "__version__",
    "Ring",
    "Torus",
    "Topology",
    "spmd",
    "stack_for_ranks",
    "build_mesh",
    "collectives",
    "EventConfig",
    "EventState",
    "SparseConfig",
    "SparseState",
]
