"""Dataset loading: MNIST idx, CIFAR-10 binary, and synthetic fallback.

The reference hard-codes cluster AFS paths (dmnist/cent/cent.cpp:53,
dcifar10/common/custom.hpp:11-12) and reads MNIST via libtorch's built-in
loader / CIFAR-10 via an OpenCV JPEG walker (custom.hpp:26-122). Here:

  * `load_mnist(dir)` reads the standard idx files (train-images-idx3-ubyte
    etc., gz or raw) and applies the reference's Normalize(0.1307, 0.3081)
    (cent.cpp:55).
  * `load_cifar10(dir)` reads the canonical binary batches
    (data_batch_{1..5}.bin / test_batch.bin) or the python-pickle version,
    scaled to [0,1] float32 like OpenCV's CV_32FC3 convertTo path.
  * `synthetic_dataset(...)` builds a deterministic, *learnable* stand-in
    (noisy class-prototype images) so every
    algorithm, test, and benchmark runs hermetically when no dataset is on
    disk (this environment has no network egress).

All loaders return numpy arrays (images NHWC float32, labels int32); the
training layer owns device placement.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct as _struct
from typing import Optional, Tuple

import numpy as np

MNIST_MEAN, MNIST_STD = 0.1307, 0.3081


def _open_maybe_gz(path: str):
    if os.path.exists(path):
        return open(path, "rb")
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    raise FileNotFoundError(path)


def _read_idx(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        data = f.read()
    magic, = _struct.unpack(">I", data[:4])
    ndim = magic & 0xFF
    dims = _struct.unpack(">" + "I" * ndim, data[4 : 4 + 4 * ndim])
    return np.frombuffer(data, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def load_mnist(
    data_dir: str, split: str = "train", normalize: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    prefix = "train" if split == "train" else "t10k"
    ipath = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte")
    lpath = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte")

    # fast path: native idx reader (raw files only; gz falls through)
    from eventgrad_tpu.data import native

    mean, std = (MNIST_MEAN, MNIST_STD) if normalize else (0.0, 0.0)
    out = native.load_mnist_idx(ipath, lpath, mean, std)
    if out is not None:
        return out

    images = _read_idx(ipath)
    labels = _read_idx(lpath)
    x = images.astype(np.float32)[..., None] / 255.0
    if normalize:
        x = (x - MNIST_MEAN) / MNIST_STD
    return x, labels.astype(np.int32)


# the reference's folder-name -> label map (custom.hpp:15-19 uses the same
# alphabetical CIFAR-10 class order)
CIFAR10_CLASSES = (
    "airplane", "automobile", "bird", "cat", "deer",
    "dog", "frog", "horse", "ship", "truck",
)


def load_cifar10_jpeg_dir(
    data_dir: str, split: str = "train", image_size: int = 32
) -> Tuple[np.ndarray, np.ndarray]:
    """The reference's raw-JPEG CIFAR-10 layout (`<root>/<split>/<class>/
    NNNN.jpg`, the "CIFAR-10-images" mirror — custom.hpp:66-122): walk the
    class folders, decode+resize natively (libjpeg + bilinear, standing in
    for cv::imread/cv::resize, custom.hpp:33-41). Deterministic file order
    (sorted); shuffling is the sampler layer's job, unlike the reference's
    hidden global random_shuffle (custom.hpp:119-120)."""
    from eventgrad_tpu.data import native

    root = os.path.join(data_dir, split)
    paths: list = []
    labels: list = []
    for label, cls in enumerate(CIFAR10_CLASSES):
        cls_dir = os.path.join(root, cls)
        if not os.path.isdir(cls_dir):
            continue
        for name in sorted(os.listdir(cls_dir)):
            if name.lower().endswith((".jpg", ".jpeg")):
                paths.append(os.path.join(cls_dir, name))
                labels.append(label)
    if not paths:
        raise FileNotFoundError(f"no <class>/*.jpg under {root}")
    if not native.jpeg_supported():  # also forces the (locked) library load
        raise RuntimeError(
            "JPEG support needs native/libeg_dataio.so built against libjpeg"
        )
    x = np.empty((len(paths), image_size, image_size, 3), np.float32)

    # ctypes drops the GIL during the native decode, so a thread pool scales
    # across cores (60k files decode in parallel, unlike the reference's
    # per-sample synchronous imread inside the training loop)
    from concurrent.futures import ThreadPoolExecutor

    def _decode(i: int) -> None:
        x[i] = native.load_jpeg_image(paths[i], image_size)

    with ThreadPoolExecutor(max_workers=min(16, os.cpu_count() or 1)) as pool:
        list(pool.map(_decode, range(len(paths))))
    return x, np.asarray(labels, np.int32)


def load_cifar10(data_dir: str, split: str = "train") -> Tuple[np.ndarray, np.ndarray]:
    # raw-JPEG directory mirror (the reference's own format) takes priority
    # when present AND decodable; a libjpeg-less build or a jpg-less class
    # tree falls through to the binary/pickle formats (and ultimately the
    # synthetic fallback)
    def _has_jpgs() -> bool:
        for c in CIFAR10_CLASSES:
            d = os.path.join(data_dir, split, c)
            if os.path.isdir(d) and any(
                n.lower().endswith((".jpg", ".jpeg")) for n in os.listdir(d)
            ):
                return True
        return False

    if os.path.isdir(os.path.join(data_dir, split)) and _has_jpgs():
        from eventgrad_tpu.data import native

        if native.jpeg_supported():
            return load_cifar10_jpeg_dir(data_dir, split)

    bin_names = (
        [f"data_batch_{i}.bin" for i in range(1, 6)]
        if split == "train"
        else ["test_batch.bin"]
    )
    if os.path.exists(os.path.join(data_dir, bin_names[0])):
        paths = [os.path.join(data_dir, n) for n in bin_names]

        # fast path: native binary reader
        from eventgrad_tpu.data import native

        out = native.load_cifar10_bin(paths)
        if out is not None:
            return out

        xs, ys = [], []
        for path in paths:
            raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0])
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        x = np.concatenate(xs).astype(np.float32) / 255.0
        return x, np.concatenate(ys).astype(np.int32)

    # python pickle version (cifar-10-batches-py)
    py_names = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    xs, ys = [], []
    for name in py_names:
        with open(os.path.join(data_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(
            np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        )
        ys.append(np.asarray(d[b"labels"], np.int64))
    x = np.concatenate(xs).astype(np.float32) / 255.0
    return x, np.concatenate(ys).astype(np.int32)


def synthetic_dataset(
    n: int,
    image_shape: Tuple[int, int, int] = (28, 28, 1),
    num_classes: int = 10,
    seed: int = 0,
    split: str = "train",
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable classification task.

    Each class has a fixed random prototype image; a sample is its class
    prototype at moderate SNR plus Gaussian noise. Convolutional and dense
    models alike genuinely learn it (unlike a flat linear-teacher labeling,
    which pooling architectures cannot fit), so losses fall, parameters
    settle, and the event dynamics (norm drift, threshold adaptation,
    post-convergence message savings) exercise the way real data does.
    `split` offsets the sample stream so train/test are disjoint.
    """
    rng = np.random.default_rng(seed)
    protos = rng.standard_normal((num_classes,) + tuple(image_shape)).astype(
        np.float32
    )
    offset = 0 if split == "train" else 1_000_003
    sample_rng = np.random.default_rng(seed + 17 + offset)
    y = sample_rng.integers(0, num_classes, n).astype(np.int32)
    noise = sample_rng.standard_normal((n,) + tuple(image_shape)).astype(np.float32)
    x = 0.6 * protos[y] + noise
    return x, y


def load_digits(
    split: str, seed: int = 0, geometry: str = "mnist"
) -> Tuple[np.ndarray, np.ndarray]:
    """Real handwritten-digit scans bundled with scikit-learn (UCI digits:
    1,797 genuine 8x8 grayscale images, 10 classes) — the one real image
    dataset available without network egress. Deterministic shuffle;
    357 test / 1440 train.

    geometry="mnist": upsampled 8x8 -> 32x32 (nearest) and center-cropped
    to 28x28x1 so the MNIST models apply unchanged.
    geometry="cifar32": the full 32x32 upsample replicated to 3 channels —
    real pixels at CIFAR shapes, so the E4/E5 CIFAR path (BN,
    augmentation, 3-channel statistics — dcifar10/common/custom.hpp:26-122
    is the unreachable real counterpart) gets non-synthetic evidence.
    """
    from sklearn.datasets import load_digits as _sk_digits

    d = _sk_digits()
    imgs = d.images.astype(np.float32) / 16.0
    big = np.kron(imgs, np.ones((4, 4), np.float32))
    if geometry == "cifar32":
        big = np.repeat(big[:, :, :, None], 3, axis=3)
    elif geometry == "mnist":
        big = big[:, 2:30, 2:30, None]
    else:
        raise ValueError(f"unknown digits geometry {geometry!r}")
    labels = d.target.astype(np.int32)
    order = np.random.default_rng(seed).permutation(len(labels))
    big, labels = big[order], labels[order]
    n_test = 357
    if split == "train":
        return big[n_test:], labels[n_test:]
    return big[:n_test], labels[:n_test]


def load_or_synthesize(
    dataset: str, data_dir: Optional[str], split: str, n_synth: int = 4096, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Try real data, fall back to the synthetic stand-in of matching shape.

    "digits" (MNIST geometry) and "digits32" (CIFAR geometry) are always
    real (bundled with scikit-learn, no data_dir needed); "mnist"/
    "cifar10" read real bytes from data_dir when present.
    """
    if dataset == "digits":
        return load_digits(split, seed=seed)
    if dataset == "digits32":
        return load_digits(split, seed=seed, geometry="cifar32")
    shape = (28, 28, 1) if dataset == "mnist" else (32, 32, 3)
    if data_dir:
        try:
            if dataset == "mnist":
                return load_mnist(data_dir, split)
            if dataset == "cifar10":
                return load_cifar10(data_dir, split)
        except (FileNotFoundError, OSError):
            pass
    return synthetic_dataset(n_synth, shape, seed=seed, split=split)


def synthetic_lm_dataset(
    n: int,
    seq_len: int = 128,
    vocab: int = 256,
    seed: int = 0,
    split: str = "train",
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable language-modeling task.

    Sequences are sampled from a fixed random first-order Markov chain with
    peaked transition rows (each token has a few likely successors), so a
    next-token model genuinely learns — cross-entropy falls from log(vocab)
    toward the chain's conditional entropy. Returns (tokens[n, seq_len],
    targets[n, seq_len]) int32 with targets the next token. `split` offsets
    the sample stream so train/test are disjoint.
    """
    rng = np.random.default_rng(seed)
    # peaked rows: logits ~ N(0, 3) -> a handful of high-probability successors
    logits = 3.0 * rng.standard_normal((vocab, vocab))
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)
    cum = np.cumsum(probs, axis=1)

    offset = 0 if split == "train" else 1_000_003
    sample_rng = np.random.default_rng(seed + 29 + offset)
    toks = np.empty((n, seq_len + 1), np.int32)
    toks[:, 0] = sample_rng.integers(0, vocab, n)
    u = sample_rng.random((n, seq_len))
    for t in range(seq_len):  # vectorized over sequences; seq_len steps
        # clamp: float cumsum can top out a few ulps below 1.0, and a draw
        # above it would index one past the vocabulary
        toks[:, t + 1] = np.minimum(
            (cum[toks[:, t]] < u[:, t : t + 1]).sum(axis=1), vocab - 1
        ).astype(np.int32)
    return toks[:, :-1].copy(), toks[:, 1:].copy()
