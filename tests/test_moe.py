"""Expert parallelism: EP-sharded MoE == all-experts-local twin, exactly.

Strategy mirrors test_tensor_parallel.py: run the ep_size=N model on an
N-rank mesh, gather its expert shards into an ep_size=1 twin, and demand
(a) identical outputs per rank and (b) identical one-SGD-step updates —
(b) exercises the all_to_all transpose and the sharded-leaf /N rule
through the whole backward pass. Capacity is set high enough that no
token drops, making the twin's routing math literally identical.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from eventgrad_tpu.models.moe import ExpertParallelMLP, MoETransformerLM
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Topology
from eventgrad_tpu.train.state import init_train_state_spmd
from eventgrad_tpu.train.steps import make_train_step

EP = 4
VOCAB, DIM, HEADS, EXPERTS, T = 32, 32, 4, 8, 16


def _gather_expert_params(stacked, n_ranks):
    """Stacked per-rank params [N, ..., E_local, ...] -> twin params with all
    experts local: tp_ leaves concatenate on the expert axis (rank-major,
    matching the global expert ordering); replicated leaves take rank 0
    after asserting equality."""

    def walk(path, leaf):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if "tp_" in name:
            return jnp.concatenate([leaf[r] for r in range(n_ranks)], axis=0)
        for r in range(1, n_ranks):
            np.testing.assert_allclose(
                np.asarray(leaf[0]), np.asarray(leaf[r]), atol=1e-7, err_msg=name
            )
        return leaf[0]

    return jax.tree_util.tree_map_with_path(walk, stacked)


def test_moe_layer_forward_matches_local_twin():
    topo = Topology(axes=("ep",), shape=(EP,), sharded_axes=("ep",))
    layer = ExpertParallelMLP(
        dim=DIM, hidden=2 * DIM, n_experts=EXPERTS, axis="ep", ep_size=EP,
        capacity_factor=float(EXPERTS),  # no drops
    )
    twin = ExpertParallelMLP(
        dim=DIM, hidden=2 * DIM, n_experts=EXPERTS, ep_size=1,
        capacity_factor=float(EXPERTS),
    )

    x = jax.random.normal(jax.random.PRNGKey(3), (EP, 2, T, DIM))
    keys = jnp.broadcast_to(jax.random.PRNGKey(0), (EP, 2))

    def init_rank(key, xr):
        return layer.init(key, xr)["params"]

    params = spmd(init_rank, topo)(keys, x)

    def fwd(p, xr):
        return layer.apply({"params": p}, xr)

    out = spmd(fwd, topo)(params, x)

    twin_params = _gather_expert_params(params, EP)
    for r in range(EP):
        ref = twin.apply({"params": twin_params}, x[r])
        np.testing.assert_allclose(
            np.asarray(out[r]), np.asarray(ref), atol=2e-5, err_msg=f"rank {r}"
        )


def test_moe_capacity_drops_tokens():
    """With capacity 1 slot per expert, overflow tokens contribute zero
    output (they ride the residual in a full block)."""
    layer = ExpertParallelMLP(
        dim=8, hidden=16, n_experts=2, ep_size=1, n_select=1,
        capacity_factor=1e-9,  # capacity clamps to 1
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 8))
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    out = layer.apply({"params": params}, x)
    # at most 2 tokens (1 per expert) produce nonzero output
    nonzero = np.abs(np.asarray(out[0])).sum(-1) > 1e-7
    assert nonzero.sum() <= 2


def test_moe_lm_train_step_matches_twin():
    """One SGD step of the EP=4 MoE LM equals the all-local twin, shard for
    shard, including the aux load-balancing loss in the objective."""
    topo = Topology(axes=("ep",), shape=(EP,), sharded_axes=("ep",))
    kwargs = dict(
        vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=1, n_experts=EXPERTS,
        max_len=T, capacity_factor=float(EXPERTS),
    )
    model = MoETransformerLM(axis="ep", ep_size=EP, **kwargs)
    twin = MoETransformerLM(ep_size=1, **kwargs)

    tx = optax.sgd(0.1)
    state = init_train_state_spmd(model, (T,), tx, topo, "dpsgd", input_dtype=jnp.int32)
    twin_params = _gather_expert_params(state.params, EP)

    toks = jax.random.randint(jax.random.PRNGKey(5), (EP, 2, T), 0, VOCAB)
    tgts = jnp.roll(toks, -1, axis=-1)

    step = make_train_step(model, tx, topo, "dpsgd")
    new_state, m = jax.jit(spmd(step, topo))(state, (toks, tgts))

    def twin_loss(p):
        # mean over ranks of per-rank (xent + aux) — matches the EP
        # objective: replicated-leaf grads pmean over the ep axis
        total = 0.0
        for r in range(EP):
            out, upd = twin.apply(
                {"params": p}, toks[r], train=True, mutable=["losses"]
            )
            logp = jax.nn.log_softmax(out)
            ll = jnp.take_along_axis(logp, tgts[r][..., None], -1)
            total += -jnp.mean(ll) + sum(jax.tree.leaves(upd["losses"]))
        return total / EP

    g = jax.grad(twin_loss)(twin_params)
    twin_new = jax.tree.map(lambda p, g: p - 0.1 * g, twin_params, g)

    got_twin = _gather_expert_params(new_state.params, EP)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(twin_new),
        jax.tree_util.tree_leaves_with_path(got_twin),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5,
            err_msg=jax.tree_util.keystr(pa),
        )


def test_dp_gossip_times_ep():
    """EventGraD gossip across dp while experts shard across ep: 2x4 mesh."""
    from eventgrad_tpu.parallel.events import EventConfig

    topo = Topology(
        axes=("dp", "ep"), shape=(2, EP), gossip_axes=("dp",), sharded_axes=("ep",)
    )
    model = MoETransformerLM(
        vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=1, n_experts=EXPERTS,
        max_len=T, axis="ep", ep_size=EP,
    )
    tx = optax.sgd(0.1)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    state = init_train_state_spmd(
        model, (T,), tx, topo, "eventgrad", cfg, input_dtype=jnp.int32
    )
    step = make_train_step(model, tx, topo, "eventgrad", event_cfg=cfg)
    lifted = jax.jit(spmd(step, topo))

    toks = jax.random.randint(jax.random.PRNGKey(9), (2, 2, T), 0, VOCAB)
    xb = jnp.repeat(toks, EP, axis=0).reshape(8, 2, T)  # replicate over ep
    yb = jnp.roll(xb, -1, axis=-1)

    losses = []
    for _ in range(6):
        state, m = lifted(state, (xb, yb))
        losses.append(float(np.asarray(m["loss"]).mean()))
    assert losses[-1] < losses[0]

    # replicated leaves stay consistent across the ep axis
    emb = state.params["Embed_0"]["embedding"].reshape(2, EP, VOCAB, DIM)
    np.testing.assert_allclose(np.asarray(emb[:, 0]), np.asarray(emb[:, 1]), atol=1e-5)
