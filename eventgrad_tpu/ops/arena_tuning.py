"""Measured dispatch policy for the flat-arena Pallas kernels.

Same mechanism as ops/flash_tuning.py and ops/fused_tuning.py: the
kernels must EARN their place on chip. `bench_kernels.py arena` measures
them against their XLA twins on the active device and (on TPU) writes
`arena_tuning.json` next to this module; the train step consults the
table at build time.

Policies:

  * `masked_wire_ok()` — the masked-wire builder kernel
    (ops/event_engine.masked_wire). The flat exchange's inline form is
    already a single fused mask-into-concat pass under XLA, so the
    kernel only earns a wire-builder slot with a MEASURED win (no
    table -> False); EG_FORCE_ARENA_PALLAS=1 overrides for manual
    experiments.
  * `mix_commit_ok()` — the fused commit+mix+SGD tail
    (ops/arena_update.fused_mix_commit). The arena hands it the shape
    the fused family measured best (one big lane-aligned flat buffer —
    KERNELS_TPU.json's ~1.0x single-leaf case, with the commit pass
    fused in on top), and it is opt-in via train(fused_update=True)
    like fused_mix_sgd, so it runs unless a measurement demotes it.
"""

from __future__ import annotations

import functools
import json
import os

_TABLE_PATH = os.path.join(os.path.dirname(__file__), "arena_tuning.json")


@functools.lru_cache(maxsize=1)
def _table():
    try:
        with open(_TABLE_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def masked_wire_ok() -> bool:
    """Run the Pallas masked-wire builder in the flat exchange?"""
    if os.environ.get("EG_FORCE_ARENA_PALLAS") == "1":
        return True
    ratio = _table().get("masked_wire_speedup")
    return ratio is not None and float(ratio) >= 1.0


def mix_commit_ok() -> bool:
    """Run the fused commit+mix+SGD kernel in the arena fused tail?"""
    if os.environ.get("EG_FORCE_ARENA_PALLAS") == "1":
        return True
    ratio = _table().get("mix_commit_speedup")
    return ratio is None or float(ratio) >= 1.0


def bucketed_tail_ok(k=None) -> bool:
    """Run the fused commit+mix+SGD tail PER BUCKET under the bucketed
    gossip schedule (train/steps.py bucketed= + fused_sgd)?

    The per-bucket form launches K kernels instead of one — the
    many-launch regime the fused family measured as a LOSS on trees
    (ops/fused_tuning.py), so it must earn its place with a measured
    entry (written by `python bench_kernels.py bucketed` on the active
    device). The table carries TWO entry shapes:

      * `bucketed_tail_speedup_by_platform` — per-platform per-K
        ratios, written on EVERY platform (CPU included: there the
        bench times the jnp reference twins, which is exactly the
        dispatch decision CPU runs face). With `k` given, that K's own
        ratio decides; an unmeasured K falls back to the platform's
        WORST measured K (the conservative verdict).
      * `bucketed_tail_speedup` — the legacy worst-K scalar the TPU
        merge writes; consulted only when the active platform has no
        per-K entry.

    No table / no entry for this platform -> False: an unmeasured
    shape falls back to the MONOLITHIC fused path instead of guessing
    (train/loop.py demotes bucketed to K=1 with a warning there).
    EG_FORCE_ARENA_PALLAS=1 overrides for manual experiments."""
    if os.environ.get("EG_FORCE_ARENA_PALLAS") == "1":
        return True
    import jax

    by_k = (
        _table().get("bucketed_tail_speedup_by_platform") or {}
    ).get(jax.default_backend())
    if by_k:
        if k is not None and str(int(k)) in by_k:
            ratio = by_k[str(int(k))]
        else:
            ratio = min(float(v) for v in by_k.values())
        return ratio is not None and float(ratio) >= 1.0
    ratio = _table().get("bucketed_tail_speedup")
    return ratio is not None and float(ratio) >= 1.0
