"""Send-side trace instrumentation (the reference's file_write=1 send{r}.txt)."""

import json

import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train


def test_trace_file_records_send_decisions(tmp_path):
    x, y = synthetic_dataset(128, (28, 28, 1), seed=1)
    path = tmp_path / "send.jsonl"
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    state, hist = train(
        MLP(), Ring(4), x, y,
        algo="eventgrad", epochs=2, batch_size=8, learning_rate=0.05,
        event_cfg=cfg, seed=0, trace_file=str(path),
    )
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    header, allrecs = lines[0], lines[1:]
    recs = [r for r in allrecs if "fired" in r]
    recvs = [r for r in allrecs if "recv" in r]

    assert len(header["trace_params"]) == 4  # MLP: 2 kernels + 2 biases
    assert header["trace_neighbors"] == ["ring_m1", "ring_p1"]
    steps_per_epoch = hist[0]["steps"]
    total = 2 * steps_per_epoch * 4  # passes x ranks
    assert len(recs) == total
    assert len(recvs) == total * 2  # one per neighbor direction
    assert {r["rank"] for r in recs} == {0, 1, 2, 3}
    assert max(r["pass"] for r in recs) == 2 * steps_per_epoch

    for r in recs:
        assert len(r["norm"]) == len(r["thres"]) == len(r["fired"]) == 4
        assert np.isfinite(r["loss"])  # train{r}.txt: per-step loss rides along
        if r["pass"] <= 1:  # warmup: pass_num < warmup_passes always fires
            assert all(f == 1 for f in r["fired"])

    # fired counts must reconcile with the num_events counter (x2 neighbors)
    fired_total = sum(sum(r["fired"]) for r in recs)
    assert 2 * fired_total == int(np.asarray(state.event.num_events).sum())

    # recv records (recv{r}.txt): changed bits mirror the source rank's fire
    # bits, and the logged norm is the sender's norm when changed else the
    # last received value (zero before any message — the window's initial
    # state, event.cpp:177-179)
    send_at = {(r["pass"], r["rank"]): r for r in recs}
    last = {}
    for rv in sorted(recvs, key=lambda r: r["pass"]):
        offset = {"ring_m1": -1, "ring_p1": +1}[rv["recv"]]
        src = (rv["rank"] + offset) % 4
        sent = send_at[(rv["pass"], src)]
        assert rv["changed"] == sent["fired"]
        expect = [
            s_n if ch else prev
            for s_n, ch, prev in zip(
                sent["norm"], sent["fired"],
                last.get((rv["rank"], rv["recv"]), [0.0] * 4),
            )
        ]
        np.testing.assert_allclose(rv["norm"], expect, atol=1e-6)
        last[(rv["rank"], rv["recv"])] = rv["norm"]


def test_trace_survives_resume(tmp_path):
    """The recv-norm staleness carry is part of the snapshot: a run
    interrupted after epoch 1 and resumed must append byte-identical trace
    records to what the uninterrupted run writes."""
    x, y = synthetic_dataset(128, (28, 28, 1), seed=1)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    kw = dict(
        algo="eventgrad", batch_size=8, learning_rate=0.05,
        event_cfg=cfg, seed=0, log_every_epoch=False,
    )
    straight = tmp_path / "straight.jsonl"
    train(MLP(), Ring(4), x, y, epochs=2, trace_file=str(straight), **kw)

    resumed = tmp_path / "resumed.jsonl"
    ck = str(tmp_path / "ck")
    train(MLP(), Ring(4), x, y, epochs=1, trace_file=str(resumed),
          checkpoint_dir=ck, **kw)
    train(MLP(), Ring(4), x, y, epochs=2, trace_file=str(resumed),
          checkpoint_dir=ck, resume=True, **kw)
    assert straight.read_text() == resumed.read_text()


def test_trace_loss_stream_for_non_event_algos(tmp_path):
    """cent/decent write per-step (epoch, loss) to values{r}.txt
    (cent.cpp:124, decent.cpp:166); with --trace-file the dpsgd/allreduce
    paths emit the same stream as (pass, rank, loss) records."""
    x, y = synthetic_dataset(128, (28, 28, 1), seed=1)
    for algo in ("dpsgd", "allreduce"):
        path = tmp_path / f"{algo}.jsonl"
        _, hist = train(
            MLP(), Ring(4), x, y,
            algo=algo, epochs=2, batch_size=8, learning_rate=0.05,
            seed=0, trace_file=str(path), log_every_epoch=False,
        )
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        steps = hist[0]["steps"]
        assert len(recs) == 2 * steps * 4  # passes x ranks
        assert all(set(r) == {"pass", "rank", "loss"} for r in recs)
        assert max(r["pass"] for r in recs) == 2 * steps
        assert all(np.isfinite(r["loss"]) for r in recs)
        # the mean of the per-step stream reconciles with the epoch record
        e1 = [r["loss"] for r in recs if r["pass"] <= steps]
        assert abs(np.mean(e1) - hist[0]["loss"]) < 1e-4
