"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): messages-saved-% of EventGraD vs D-PSGD at
the CIFAR-10 operating point (reference claim ~60%, /root/reference/README.md:4),
with test accuracy of the consensus model compared against a D-PSGD run of
identical op-point (the reference's "comparable accuracy" claim). Flagship
config: ResNet-18-as-coded (3 blocks/stage, ~17.4M params), 8-rank ring,
global batch 256, SGD momentum 0.9, adaptive threshold, ~3.9k passes (the
reference's 20-epoch x ~195-step CIFAR scale, event.cpp:31-36).

All 8 ranks are vmap-simulated on the local accelerator (the single-chip
lifting path; identical trajectories to the shard_map path per
test_train_equivalence.py::test_shard_map_matches_vmap).

Data: synthetic class-prototype CIFAR-shaped set (no network egress here).
Augmentation stays OFF for synthetic data — the class prototypes'
labels are not crop/flip-invariant, so the reference's pad4+flip+crop would
destroy the learning signal (the real-data CLI path applies it).

Secondary metric: the MNIST CNN-2 op-point (batch 64/rank, lr 0.05,
sequential sampler, ~1.17k passes — reference claim ~70% messages saved)
rides along as `mnist_msgs_saved`.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

# Tiers: EG_BENCH_TINY=1 shrinks every dimension so the full bench path
# (both algos, both datasets, the JSON assembly) smoke-runs quickly;
# EG_BENCH_CPU=1 is the dead-accelerator fallback — a reduced op-point
# sized for a single CPU core within the watchdog deadline (the headline
# msgs-saved-% is algorithmic, so it stays meaningful; wall-clock fields
# do not). Full scale is the default and what the TPU runs.
_TINY = os.environ.get("EG_BENCH_TINY") == "1"
_CPU_TIER = os.environ.get("EG_BENCH_CPU") == "1" and not _TINY


def main() -> None:
    import jax.numpy as jnp

    from eventgrad_tpu.utils import compile_cache

    compile_cache.honor_cpu_pin()
    compile_cache.enable()

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import ResNet18, ResNet
    from eventgrad_tpu.models.resnet import BasicBlock
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, train
    from eventgrad_tpu.utils import trees

    topo = Ring(8)
    if _TINY:
        global_batch, n_train, n_test, epochs = 256, 1024, 256, 2
    elif _CPU_TIER:
        # ~768 passes at ~2.3s/pass on one core (~30 min): enough for the
        # adaptive threshold to mature well past the 30-pass warmup, with
        # deadline margin for probe + compile + the MNIST leg
        global_batch, n_train, n_test, epochs = 64, 2048, 512, 24
    else:
        global_batch, n_train, n_test, epochs = 256, 16384, 2048, 61
        # 61 x 64 steps = 3904 passes ~= ref op-point
    per_rank = global_batch // topo.n_ranks

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    xt, yt = load_or_synthesize("cifar10", None, "test", n_synth=n_test)
    model = (
        ResNet18(dtype=jnp.bfloat16)
        if not (_TINY or _CPU_TIER)
        else ResNet(stage_sizes=(1, 1, 1, 1), block_cls=BasicBlock, num_filters=8)
    )
    event_cfg = EventConfig(
        adaptive=True, horizon=0.95, warmup_passes=5 if _TINY else 30
    )

    common = dict(
        epochs=epochs, batch_size=per_rank,
        learning_rate=1e-2, momentum=0.9,  # dcifar10/event/event.cpp:196-200
        random_sampler=True, log_every_epoch=False,
    )

    t0 = time.perf_counter()
    state, hist = train(
        model, topo, x, y, algo="eventgrad", event_cfg=event_cfg, **common
    )
    wall_event = time.perf_counter() - t0
    cons = consensus_params(state.params)
    stats0 = jax.tree.map(lambda s: s[0], state.batch_stats)
    test = evaluate(model, cons, stats0, xt, yt)

    if _CPU_TIER:
        # the savings metric needs no D-PSGD leg (fired fraction is
        # internal); skip the comparison run to fit one core in-deadline
        wall_dpsgd, test_d = 0.0, None
    else:
        t0 = time.perf_counter()
        state_d, hist_d = train(model, topo, x, y, algo="dpsgd", **common)
        wall_dpsgd = time.perf_counter() - t0
        cons_d = consensus_params(state_d.params)
        stats_d = jax.tree.map(lambda s: s[0], state_d.batch_stats)
        test_d = evaluate(model, cons_d, stats_d, xt, yt)

    # secondary op-point: MNIST CNN-2, batch 64/rank, lr 0.05, sequential
    # sampler, ~1.17k passes (event.cpp:103,145,227,255) — reference ~70%
    from eventgrad_tpu.models import CNN2

    if _TINY:
        mnist_n, mnist_epochs, mnist_batch = 1024, 2, 16
    elif _CPU_TIER:
        mnist_n, mnist_epochs, mnist_batch = 4096, 75, 64  # ~600 passes
    else:
        mnist_n, mnist_epochs, mnist_batch = 8192, 73, 64
    xm, ym = load_or_synthesize("mnist", None, "train", n_synth=mnist_n)
    _, hist_m = train(
        CNN2(), topo, xm, ym, algo="eventgrad", event_cfg=event_cfg,
        epochs=mnist_epochs, batch_size=mnist_batch,
        learning_rate=0.05, random_sampler=False, log_every_epoch=False,
    )
    mnist_saved = hist_m[-1]["msgs_saved_pct"]

    saved = hist[-1]["msgs_saved_pct"]
    steady = hist[1:] or hist
    step_ms = 1000 * float(np.mean([h["wall_s"] / h["steps"] for h in steady]))
    n_params = trees.tree_count_params(jax.tree.map(lambda p: p[0], state.params))

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet_eventgrad_msgs_saved",
                "value": round(saved, 2),
                "unit": "%",
                "vs_baseline": round(saved / 60.0, 4),
                "config": "tiny" if _TINY else ("cpu-reduced" if _CPU_TIER else "full"),
                "test_acc": round(test["accuracy"], 2),
                "test_acc_dpsgd": round(test_d["accuracy"], 2) if test_d else None,
                "acc_gap_vs_dpsgd": round(test["accuracy"] - test_d["accuracy"], 2)
                if test_d
                else None,
                "mnist_msgs_saved": round(mnist_saved, 2),
                "mnist_vs_baseline": round(mnist_saved / 70.0, 4),
                "step_ms": round(step_ms, 2),
                "sent_bytes_per_step_per_chip": hist[-1]["sent_bytes_per_step_per_chip"],
                "dense_bytes_per_step_per_chip": float(topo.n_neighbors * 4 * n_params),
                "final_train_loss": round(hist[-1]["loss"], 4),
                "passes": epochs * (n_train // global_batch),
                "wall_s_eventgrad": round(wall_event, 1),
                "wall_s_dpsgd": round(wall_dpsgd, 1),
                "platform": jax.devices()[0].platform,
                "n_ranks": topo.n_ranks,
            }
        )
    )


def _run_deadlined(cmd: list, env: dict, timeout_s: float):
    """subprocess.run(timeout=...) that cannot hang the parent: a child
    stuck in an uninterruptible device op survives SIGKILL-then-reap
    (subprocess.run's TimeoutExpired path waits forever), so kill, give
    it a short grace to be reaped, then abandon it. Returns
    (stdout_or_None, timed_out)."""
    import subprocess

    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return out, False
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            # salvage anything already printed: a child that completed its
            # measurement and then wedged in device teardown is a result
            out, _ = proc.communicate(timeout=10)
            return out, True
        except subprocess.TimeoutExpired:
            pass  # unkillable child; abandon without reaping
        return None, True
    except OSError:
        return None, False


def _probe_device(env: dict, timeout_s: float) -> str:
    """'ok' iff the backend the child would use completes a trivial jit
    in time; 'stalled' on deadline; 'crashed' on fast failure. A wedged
    accelerator tunnel enumerates devices fine but blocks forever on the
    first execution, so probe execution, not enumeration."""
    import sys

    code = (
        "import os, jax, jax.numpy as jnp\n"
        "from eventgrad_tpu.utils import compile_cache\n"
        "compile_cache.honor_cpu_pin()\n"
        "jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((128, 128))))\n"
        "print('EG_PROBE_OK', jax.devices()[0].platform)\n"
    )
    out, timed_out = _run_deadlined(
        [sys.executable, "-c", code], env, timeout_s
    )
    if timed_out:
        return "stalled"
    return "ok" if out and "EG_PROBE_OK" in out else "crashed"


def _supervised() -> None:
    """Run main() in a child with a deadline. The accelerator tunnel can
    wedge a blocked device op forever (no Python-level interrupt works);
    a supervising parent is the only reliable watchdog. Before each
    attempt a short liveness probe runs; if the accelerator stalls, the
    bench falls back to CPU — the headline metric (messages-saved-%) is
    algorithmic and backend-independent, so a dead tunnel still yields
    real numbers (only the wall-clock fields change meaning; the emitted
    `platform` field records which backend ran). If even that stalls, a
    diagnostic JSON line is emitted so the harness always gets its line."""
    import sys

    deadline = float(os.environ.get("EG_BENCH_DEADLINE_S", "4500"))
    probe_s = float(os.environ.get("EG_BENCH_PROBE_S", "240"))
    env = dict(os.environ, EG_BENCH_CHILD="1")
    for attempt in (1, 2):
        if env.get("JAX_PLATFORMS") != "cpu":
            verdict = _probe_device(env, probe_s)
            if verdict != "ok":
                print(
                    f"device probe {verdict}"
                    + (f" after {probe_s:.0f}s" if verdict == "stalled" else "")
                    + "; falling back to the reduced CPU op-point",
                    file=sys.stderr, flush=True,
                )
                env["JAX_PLATFORMS"] = "cpu"
                env.setdefault("EG_BENCH_CPU", "1")
        out, timed_out = _run_deadlined(
            [sys.executable, os.path.abspath(__file__)], env, deadline
        )
        # accept any run that produced a parseable metric line — a
        # teardown crash after a completed measurement is still a result
        for line in reversed((out or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                print(line)
                return
        print(
            f"bench attempt {attempt} "
            + ("stalled" if timed_out else "failed")
            + f" (deadline {deadline}s)",
            file=sys.stderr, flush=True,
        )
        # don't retry a backend that just wedged mid-run
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("EG_BENCH_CPU", "1")
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet_eventgrad_msgs_saved",
                "value": 0.0,
                "unit": "%",
                "vs_baseline": 0.0,
                "error": "device stalled or bench failed twice; see stderr",
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get("EG_BENCH_CHILD") == "1":
        main()
    else:
        _supervised()
