"""Integration tier of the test pyramid (SURVEY §4): real training runs on
the emulated 8-device mesh must actually learn, and EventGraD must do so
while saving messages — the reference's headline claim in miniature."""

import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import consensus_params, evaluate, train
import jax


def test_eventgrad_learns_and_saves_messages():
    x, y = synthetic_dataset(2048, (28, 28, 1), seed=0)
    xt, yt = synthetic_dataset(512, (28, 28, 1), seed=0, split="test")
    # the MLP keeps the reference's ReLU-on-logits quirk (cent.cpp:29),
    # which slows optimization — the reference itself runs 250 epochs
    state, hist = train(
        MLP(), Ring(8), x, y,
        algo="eventgrad", epochs=30, batch_size=16, learning_rate=0.05,
        event_cfg=EventConfig(adaptive=True, horizon=0.95, warmup_passes=10),
        random_sampler=True, seed=0, log_every_epoch=False,
    )
    cons = consensus_params(state.params)
    stats0 = jax.tree.map(lambda s: s[0], state.batch_stats)
    test = evaluate(MLP(), cons, stats0, xt, yt)

    assert hist[-1]["loss"] < 0.25 * hist[0]["loss"], [h["loss"] for h in hist]
    assert test["accuracy"] > 25.0, test  # 10 classes, chance = 10%
    # message savings materialize once warmup (10 of 480 passes) is over
    assert hist[-1]["msgs_saved_pct"] > 35.0, hist[-1]
    # and savings must not have cost convergence vs plain D-PSGD
    state_d, _ = train(
        MLP(), Ring(8), x, y,
        algo="dpsgd", epochs=30, batch_size=16, learning_rate=0.05,
        random_sampler=True, seed=0, log_every_epoch=False,
    )
    cons_d = consensus_params(state_d.params)
    test_d = evaluate(MLP(), cons_d, stats0, xt, yt)
    assert test["accuracy"] > test_d["accuracy"] - 10.0, (test, test_d)
