"""Tensor parallelism: Megatron-style sharded Transformer layers.

Not present in the reference (its models are tiny CNNs — SURVEY §2.5 marks
TP "absent"), but required for the framework's scale story: the same named
mesh axis machinery that carries gossip and ring attention here shards the
weight matrices themselves.

  * `ColParallelDense` — kernel [d_in, d_out/N] per rank; output stays
    sharded over features (no collective).
  * `RowParallelDense` — kernel [d_in/N, d_out] per rank; partial products
    psum over the TP axis (one all-reduce per layer exit, riding ICI).
  * `TPBlock` / `TPTransformerLM` — attention heads and MLP hidden units
    sharded across the TP axis; activations enter and leave each block
    replicated.

Parameter shards are distinct per TP rank, so the topology must list the
axis in `sharded_axes`: gossip and gradient-pmean skip it, and shard
initialization uses a TP-rank-folded RNG (identical across dp/sp ranks,
distinct across tp ranks — see `tp_init_rng`).

All TP layers are bias-free (biases would need post-psum correction and
contribute nothing at these widths — standard Megatron practice).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from eventgrad_tpu.parallel.ring_attention import full_attention
from eventgrad_tpu.parallel.topology import Topology


def sharded_lecun_init(axis: str):
    """lecun_normal folded with the TP-axis index: under a shared init key,
    sharded kernels come out distinct per TP rank while every non-TP
    parameter (initialized with the unfolded key) stays replica-identical
    across the whole mesh."""
    base = nn.initializers.lecun_normal()

    def init(key, shape, dtype=jnp.float32):
        return base(jax.random.fold_in(key, lax.axis_index(axis)), shape, dtype)

    return init


class ColParallelDense(nn.Module):
    features: int  # GLOBAL output features
    axis: str
    tp_size: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        assert self.features % self.tp_size == 0
        local = self.features // self.tp_size
        kernel = self.param(
            "tp_kernel",
            sharded_lecun_init(self.axis) if self.tp_size > 1
            else nn.initializers.lecun_normal(),
            (x.shape[-1], local),
            jnp.float32,
        )
        return x @ kernel.astype(self.dtype)


class RowParallelDense(nn.Module):
    features: int  # GLOBAL output features
    axis: str
    tp_size: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # x: [..., d_in/N] sharded on features; output replicated via psum
        kernel = self.param(
            "tp_kernel",
            sharded_lecun_init(self.axis) if self.tp_size > 1
            else nn.initializers.lecun_normal(),
            (x.shape[-1], self.features),
            jnp.float32,
        )
        y = x @ kernel.astype(self.dtype)
        if self.tp_size > 1:
            y = lax.psum(y, self.axis)
        return y


class TPBlock(nn.Module):
    dim: int
    n_heads: int  # GLOBAL head count
    axis: str
    tp_size: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        assert self.n_heads % self.tp_size == 0
        h_local = self.n_heads // self.tp_size
        d = self.dim // self.n_heads

        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = ColParallelDense(3 * self.dim, self.axis, self.tp_size, self.dtype)(y)
        q, k, v = jnp.split(qkv.reshape(b, t, 3 * h_local, d), 3, axis=2)
        o = full_attention(q, k, v, causal=True)  # local heads, full sequence
        o = RowParallelDense(self.dim, self.axis, self.tp_size, self.dtype)(
            o.reshape(b, t, h_local * d)
        )
        x = x + o

        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = ColParallelDense(4 * self.dim, self.axis, self.tp_size, self.dtype)(y)
        y = nn.gelu(y)
        y = RowParallelDense(self.dim, self.axis, self.tp_size, self.dtype)(y)
        return x + y


class TPTransformerLM(nn.Module):
    """Decoder-only LM with TP-sharded blocks; embeddings and head stay
    replicated (they gossip normally across dp)."""

    vocab: int = 256
    dim: int = 128
    n_heads: int = 8
    n_layers: int = 2
    max_len: int = 1024
    axis: str = "tp"
    tp_size: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        b, t = tokens.shape
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype)(tokens)
        x = x + nn.Embed(self.max_len, self.dim, dtype=self.dtype)(jnp.arange(t))
        for _ in range(self.n_layers):
            x = TPBlock(self.dim, self.n_heads, self.axis, self.tp_size, self.dtype)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab, dtype=self.dtype)(x).astype(jnp.float32)
