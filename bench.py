"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): messages-saved-% of EventGraD vs D-PSGD at
the CIFAR-10 operating point (reference claim ~60%, /root/reference/README.md:4),
with test accuracy of the consensus model compared against a D-PSGD run of
identical op-point (the reference's "comparable accuracy" claim). Flagship
config: ResNet-18-as-coded (3 blocks/stage, ~17.4M params), 8-rank ring,
global batch 256, SGD momentum 0.9, adaptive threshold, ~3.9k passes (the
reference's 20-epoch x ~195-step CIFAR scale, event.cpp:31-36).

All 8 ranks are vmap-simulated on the local accelerator (the single-chip
lifting path; identical trajectories to the shard_map path per
test_train_equivalence.py::test_shard_map_matches_vmap).

Data: synthetic class-prototype CIFAR-shaped set (no network egress here).
Augmentation stays OFF for synthetic data — the class prototypes'
labels are not crop/flip-invariant, so the reference's pad4+flip+crop would
destroy the learning signal (the real-data CLI path applies it).

Secondary metric: the MNIST CNN-2 op-point (batch 64/rank, lr 0.05,
sequential sampler, ~1.17k passes — reference claim ~70% messages saved)
rides along as `mnist_msgs_saved`.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

# EG_BENCH_TINY=1 shrinks every dimension so the full bench path (both
# algos, both datasets, the JSON assembly) smoke-runs on CPU in ~a minute;
# the headline numbers are only meaningful at full scale on TPU.
_TINY = os.environ.get("EG_BENCH_TINY") == "1"


def main() -> None:
    import jax.numpy as jnp

    from eventgrad_tpu.utils import compile_cache

    compile_cache.honor_cpu_pin()
    compile_cache.enable()

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import ResNet18, ResNet
    from eventgrad_tpu.models.resnet import BasicBlock
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, train
    from eventgrad_tpu.utils import trees

    topo = Ring(8)
    global_batch = 256
    per_rank = global_batch // topo.n_ranks
    n_train, n_test = (1024, 256) if _TINY else (16384, 2048)
    epochs = 2 if _TINY else 61  # 61 x 64 steps = 3904 passes ~= ref op-point

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    xt, yt = load_or_synthesize("cifar10", None, "test", n_synth=n_test)
    model = (
        ResNet(stage_sizes=(1, 1, 1, 1), block_cls=BasicBlock, num_filters=8)
        if _TINY
        else ResNet18(dtype=jnp.bfloat16)
    )
    event_cfg = EventConfig(
        adaptive=True, horizon=0.95, warmup_passes=5 if _TINY else 30
    )

    common = dict(
        epochs=epochs, batch_size=per_rank,
        learning_rate=1e-2, momentum=0.9,  # dcifar10/event/event.cpp:196-200
        random_sampler=True, log_every_epoch=False,
    )

    t0 = time.perf_counter()
    state, hist = train(
        model, topo, x, y, algo="eventgrad", event_cfg=event_cfg, **common
    )
    wall_event = time.perf_counter() - t0
    cons = consensus_params(state.params)
    stats0 = jax.tree.map(lambda s: s[0], state.batch_stats)
    test = evaluate(model, cons, stats0, xt, yt)

    t0 = time.perf_counter()
    state_d, hist_d = train(model, topo, x, y, algo="dpsgd", **common)
    wall_dpsgd = time.perf_counter() - t0
    cons_d = consensus_params(state_d.params)
    stats_d = jax.tree.map(lambda s: s[0], state_d.batch_stats)
    test_d = evaluate(model, cons_d, stats_d, xt, yt)

    # secondary op-point: MNIST CNN-2, batch 64/rank, lr 0.05, sequential
    # sampler, ~1.17k passes (event.cpp:103,145,227,255) — reference ~70%
    from eventgrad_tpu.models import CNN2

    xm, ym = load_or_synthesize("mnist", None, "train", n_synth=1024 if _TINY else 8192)
    _, hist_m = train(
        CNN2(), topo, xm, ym, algo="eventgrad", event_cfg=event_cfg,
        epochs=2 if _TINY else 73, batch_size=16 if _TINY else 64,
        learning_rate=0.05, random_sampler=False, log_every_epoch=False,
    )
    mnist_saved = hist_m[-1]["msgs_saved_pct"]

    saved = hist[-1]["msgs_saved_pct"]
    steady = hist[1:] or hist
    step_ms = 1000 * float(np.mean([h["wall_s"] / h["steps"] for h in steady]))
    n_params = trees.tree_count_params(jax.tree.map(lambda p: p[0], state.params))

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet_eventgrad_msgs_saved",
                "value": round(saved, 2),
                "unit": "%",
                "vs_baseline": round(saved / 60.0, 4),
                "test_acc": round(test["accuracy"], 2),
                "test_acc_dpsgd": round(test_d["accuracy"], 2),
                "acc_gap_vs_dpsgd": round(test["accuracy"] - test_d["accuracy"], 2),
                "mnist_msgs_saved": round(mnist_saved, 2),
                "mnist_vs_baseline": round(mnist_saved / 70.0, 4),
                "step_ms": round(step_ms, 2),
                "sent_bytes_per_step_per_chip": hist[-1]["sent_bytes_per_step_per_chip"],
                "dense_bytes_per_step_per_chip": float(topo.n_neighbors * 4 * n_params),
                "final_train_loss": round(hist[-1]["loss"], 4),
                "passes": epochs * (n_train // global_batch),
                "wall_s_eventgrad": round(wall_event, 1),
                "wall_s_dpsgd": round(wall_dpsgd, 1),
                "platform": jax.devices()[0].platform,
                "n_ranks": topo.n_ranks,
            }
        )
    )


def _supervised() -> None:
    """Run main() in a child with a deadline. The accelerator tunnel can
    wedge a blocked device op forever (no Python-level interrupt works);
    a supervising parent is the only reliable watchdog. On timeout the
    child is killed and one retry runs; if that also stalls, a diagnostic
    JSON line is emitted so the harness always gets its one line."""
    import subprocess
    import sys

    deadline = float(os.environ.get("EG_BENCH_DEADLINE_S", "4500"))
    env = dict(os.environ, EG_BENCH_CHILD="1")
    for attempt in (1, 2):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, timeout=deadline, stdout=subprocess.PIPE, text=True,
            )
            # accept any run that produced a parseable metric line — a
            # teardown crash after a completed measurement is still a result
            for line in reversed(proc.stdout.strip().splitlines() or []):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "metric" in rec:
                    print(line)
                    return
        except subprocess.TimeoutExpired:
            pass
        print(
            f"bench attempt {attempt} stalled/failed (deadline {deadline}s)",
            file=sys.stderr, flush=True,
        )
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet_eventgrad_msgs_saved",
                "value": 0.0,
                "unit": "%",
                "vs_baseline": 0.0,
                "error": "device stalled or bench failed twice; see stderr",
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get("EG_BENCH_CHILD") == "1":
        main()
    else:
        _supervised()
