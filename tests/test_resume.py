"""Checkpoint/resume: an interrupted run continues to the exact same state.

The reference has no persistence at all (SURVEY §5); here the whole gossip
TrainState (params, SGD momenta, event thresholds/slopes, stale neighbor
buffers, PRNG keys, pass counter) round-trips through orbax, so a run
killed mid-training and resumed is bit-identical to one that never stopped.
"""

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train


def _run(tmp, *, epochs, resume, save_every=2):
    x, y = synthetic_dataset(256, (28, 28, 1), seed=4)
    model = MLP()
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=3)
    return train(
        model, Ring(4), x, y,
        algo="eventgrad", epochs=epochs, batch_size=16, learning_rate=0.05,
        event_cfg=cfg, random_sampler=True, seed=7,
        checkpoint_dir=str(tmp) if tmp else None,
        save_every=save_every, resume=resume,
    )


def test_interrupt_and_resume_matches_uninterrupted(tmp_path):
    # uninterrupted 4-epoch run
    state_full, hist_full = _run(None, epochs=4, resume=False)

    # "crash" after epoch 2 (checkpoint lands there), then resume to 4
    ck = tmp_path / "ck"
    _run(ck, epochs=2, resume=False)
    state_res, hist_res = _run(ck, epochs=4, resume=True)

    assert [h["epoch"] for h in hist_res] == [3, 4]
    for a, b in zip(jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # event state resumed too, not reset
    np.testing.assert_array_equal(
        np.asarray(state_res.event.num_events), np.asarray(state_full.event.num_events)
    )
    np.testing.assert_allclose(
        np.asarray(state_res.pass_num), np.asarray(state_full.pass_num)
    )


def test_resume_without_checkpoint_starts_fresh(tmp_path):
    state, hist = _run(tmp_path / "none", epochs=2, resume=True)
    assert [h["epoch"] for h in hist] == [1, 2]


def test_interrupted_save_falls_back_to_prev(tmp_path):
    """A kill mid-snapshot-swap leaves ckpt.prev; resume must find it."""
    import os
    import shutil

    from eventgrad_tpu.utils import checkpoint

    ck = tmp_path / "ck"
    _run(ck, epochs=2, resume=False)
    path = os.path.join(str(ck), "ckpt")
    # simulate dying after the old snapshot moved aside but before promotion
    os.rename(path, path + ".prev")
    assert checkpoint.latest(path) == os.path.abspath(path) + ".prev"

    state_res, hist_res = _run(ck, epochs=4, resume=True)
    assert [h["epoch"] for h in hist_res] == [3, 4]


def test_corrupt_primary_resume_recovers_from_prev_loudly(tmp_path):
    """peek/load .prev auto-fallback (ISSUE 8 satellite): a TRUNCATED
    primary snapshot with a complete demoted twin resumes from the twin
    with a loud RuntimeWarning instead of failing the service; with the
    twin also corrupt, the resume fails loudly naming both paths."""
    import os
    import shutil

    import pytest

    from eventgrad_tpu.utils import checkpoint

    def corrupt(tree):
        # the promoted name pointing at zero-length files (a torn write)
        for dirpath, _, files in os.walk(tree):
            for f in files:
                open(os.path.join(dirpath, f), "w").close()

    state_full, _ = _run(None, epochs=4, resume=False)
    ck = tmp_path / "ck"
    _run(ck, epochs=2, resume=False)
    path = os.path.join(str(ck), "ckpt")
    # a complete twin of the epoch-2 snapshot, then a torn primary
    shutil.copytree(path, path + ".prev")
    corrupt(path)

    # both-corrupt leg first (the successful recovery below overwrites
    # the scenario when its epoch-4 save prunes the .prev)
    ck2 = tmp_path / "ck2"
    shutil.copytree(str(ck), str(ck2))
    corrupt(os.path.join(str(ck2), "ckpt.prev"))
    with pytest.raises(RuntimeError, match="both unreadable"):
        _run(ck2, epochs=4, resume=True)

    with pytest.warns(RuntimeWarning, match="RECOVERED"):
        state_res, hist_res = _run(ck, epochs=4, resume=True)
    assert [h["epoch"] for h in hist_res] == [3, 4]
    for a, b in zip(
        jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_hybrid_lm_resume_matches_uninterrupted(tmp_path):
    """Hybrid meshes persist too: an EventGraD dp x sp ring-attention LM run
    interrupted at epoch 2 and resumed matches the straight 4-epoch run."""
    from eventgrad_tpu.data.datasets import synthetic_lm_dataset
    from eventgrad_tpu.models.transformer import TransformerLM
    from eventgrad_tpu.parallel.topology import Topology

    topo = Topology(axes=("dp", "sp"), shape=(2, 2), gossip_axes=("dp",))
    x, y = synthetic_lm_dataset(64, 32, vocab=64, seed=2)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=3)

    def go(ck, *, epochs, resume):
        model = TransformerLM(vocab=64, dim=32, n_heads=4, n_layers=1,
                              max_len=32, attn="ring", topo=topo, sp_axis="sp")
        return train(
            model, topo, x, y,
            algo="eventgrad", epochs=epochs, batch_size=4, learning_rate=0.1,
            event_cfg=cfg, random_sampler=True, seed=5,
            checkpoint_dir=str(ck) if ck else None, save_every=2,
            resume=resume, log_every_epoch=False,
        )

    state_full, _ = go(None, epochs=4, resume=False)
    ck = tmp_path / "ck"
    go(ck, epochs=2, resume=False)
    state_res, hist = go(ck, epochs=4, resume=True)

    assert [h["epoch"] for h in hist] == [3, 4]
    for a, b in zip(
        jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(state_full.event.num_events),
        np.asarray(state_res.event.num_events),
    )


def test_delayed_gossip_resume_matches_uninterrupted(tmp_path):
    """staleness=1 carries its pending exchange in EventState.bufs, which is
    part of the snapshot — an interrupted delayed-gossip run resumes onto
    the exact uninterrupted trajectory."""
    x, y = synthetic_dataset(256, (28, 28, 1), seed=4)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=3)
    kw = dict(
        algo="eventgrad", batch_size=16, learning_rate=0.05, event_cfg=cfg,
        random_sampler=True, seed=7, staleness=1, save_every=2,
    )
    state_full, _ = train(MLP(), Ring(4), x, y, epochs=4, resume=False, **kw)
    ck = str(tmp_path / "ck")
    train(MLP(), Ring(4), x, y, epochs=2, resume=False, checkpoint_dir=ck, **kw)
    state_res, hist = train(MLP(), Ring(4), x, y, epochs=4, resume=True,
                            checkpoint_dir=ck, **kw)
    assert [h["epoch"] for h in hist] == [3, 4]
    for a, b in zip(
        jax.tree.leaves(state_full.params), jax.tree.leaves(state_res.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
