"""Crashpoint registry + graceful preemption: seeded kills, clean drains.

The repo's durability story rests on a handful of state-mutating seams —
`checkpoint.save`'s tmp-write/rename/fsync sequence, the `AsyncWriter`
background thread, dispatch-block boundaries in `train/loop.py`, the
membership bootstrap stream, the integrity rollback-restore. Every one
claims to survive a kill at any instant; none had ever been killed there
ON PURPOSE. This module makes that a first-class drill, and makes the
dominant real-world failure — PREEMPTION — cheaper than a kill at all.

Two mechanisms:

  * **crashpoints** — a registry of named sites (`SITES`), each
    instrumented at exactly ONE seam (a tier-1 lint enforces it). Arm
    one with ``EG_CRASHPOINT=site[:hit_n]`` (or `arm()`): the n-th time
    execution reaches `hit(site)` the process dies instantly via
    `os._exit(CRASHPOINT_EXIT)` — no unwind, no atexit, no flush: the
    honest model of SIGKILL/power loss. Deterministic by hit count, so
    `tools/crash_matrix.py` can kill at every site under every
    configuration, resume, and verify bitwise parity against the
    uninterrupted run. Unarmed, `hit()` is a dict lookup — the loop's
    hot path never pays for the drill it isn't running.

  * **graceful preemption** — `PreemptGuard` installs SIGTERM/SIGINT
    handlers that only SET A FLAG; the training loop checks it at each
    dispatch-block boundary and, when set, drains the pipeline, joins
    the checkpoint writer, force-snapshots, writes a ``PREEMPTED``
    marker into the checkpoint dir, and raises `GracefulPreemption` —
    the CLI exits `exitcodes.PREEMPTED_EXIT`, which the supervisor
    treats as CLEAN (immediate relaunch, no restart-budget charge, no
    backoff). EventGraD makes this nearly free: a rank that vanishes
    between blocks is semantically an event that did not fire, so a
    preemption loses at most one dispatch block of work — versus up to
    a full `--save-every` interval for a hard kill. A second signal
    while the drain is still running falls through to the previous
    (usually default) handler: the escape hatch from a wedged drain.

The scheduled twin of the signal path is the chaos clause
``preempt=EPOCH@STEP`` (chaos/schedule.py): a deterministic, replayable
preemption notice that "arrives" at that pass and drains at the
enclosing block boundary — so the ≤-one-block loss bound is measurable
in CI, not just claimed. See docs/chaos.md "Preemption & crash
consistency".
"""

from __future__ import annotations

import json
import os
import signal
import threading
from typing import Any, Dict, Optional, Tuple

from eventgrad_tpu.exitcodes import CRASHPOINT_EXIT

#: environment variable arming one crashpoint for this process:
#: ``site`` or ``site:hit_n`` (1-based; default 1 = the first hit)
ENV_VAR = "EG_CRASHPOINT"

#: marker file a graceful drain leaves in the checkpoint dir — the
#: on-disk witness that the newest snapshot is a DRAINED one (nothing
#: beyond it existed), consumed by the next incarnation's train()
PREEMPT_MARKER = "PREEMPTED"

#: every named crash site, and the seam it instruments. Each name
#: appears at EXACTLY ONE `crashpoint.hit("<name>")` call in the
#: package (tests/test_crashpoint.py lints it): a registered-but-dead
#: site would silently hollow out the crash matrix, a duplicated one
#: would make "kill at site X" ambiguous.
SITES = {
    "ckpt.tmp_written": (
        "checkpoint.save: the tmp tree is fully serialized, BEFORE the "
        "fsync durability point — on disk: old snapshot intact, tmp "
        "complete but possibly volatile"
    ),
    "ckpt.mid_swap": (
        "checkpoint.save: the old snapshot was demoted to .prev and the "
        "new one is NOT yet promoted — the worst instant of the atomic "
        "swap (latest() must find the .prev)"
    ),
    "ckpt.post_promote": (
        "checkpoint.save: the new snapshot is promoted but .prev is not "
        "yet dropped and the parent dir not yet fsynced"
    ),
    "writer.bg_save": (
        "AsyncWriter: inside the background writer thread, before the "
        "serialization/swap starts — kills the whole process from the "
        "thread the pipeline hides checkpoint cost on"
    ),
    "loop.block_dispatched": (
        "train loop: a dispatch block was just enqueued on device; none "
        "of its host work (records, eval readback, checkpoint) has run"
    ),
    "loop.block_end": (
        "train loop: a block boundary fully processed — host work "
        "drained, any due checkpoint committed, transitions applied"
    ),
    "membership.bootstrap": (
        "membership join: the neighbor snapshot was committed to the "
        "on-disk bootstrap stream but the newcomer row is not yet "
        "restored/inserted"
    ),
    "integrity.rollback": (
        "integrity engine: mid rollback-restore — last-known-good state "
        "restored in memory, replay not yet re-dispatched"
    ),
}


class GracefulPreemption(RuntimeError):
    """Raised by train() after a graceful preemption drain completed:
    the pipeline is drained, the writer joined, the boundary snapshot
    (when a checkpoint_dir exists) and the PREEMPTED marker are on
    disk. The CLI converts it to `exitcodes.PREEMPTED_EXIT`; the
    supervisor relaunches immediately without charging its budget."""

    def __init__(self, info: Dict[str, Any]):
        self.info = dict(info)
        super().__init__(
            f"graceful preemption ({info.get('reason')}) drained at "
            f"epoch {info.get('epoch')}"
        )


def parse_spec(spec: str) -> Tuple[str, int]:
    """``site`` or ``site:hit_n`` -> (site, hit_n); unknown sites and
    non-positive hit counts fail fast (an armed typo that never fires
    would read as 'survived the kill')."""
    site, _, n = spec.partition(":")
    site = site.strip()
    if site not in SITES:
        raise ValueError(
            f"unknown crashpoint {site!r}; registered sites: "
            f"{', '.join(sorted(SITES))}"
        )
    hit_n = int(n) if n else 1
    if hit_n < 1:
        raise ValueError(f"crashpoint hit count must be >= 1, got {hit_n}")
    return site, hit_n


_lock = threading.Lock()
_armed: Optional[Tuple[str, int]] = None
_hits: int = 0
_env_read = False


def _ensure_env() -> None:
    global _env_read, _armed, _hits
    if _env_read:
        return
    _env_read = True
    spec = os.environ.get(ENV_VAR)
    if spec:
        _armed = parse_spec(spec)
        _hits = 0


def arm(spec: Optional[str]) -> None:
    """Arm (or, with None, disarm) a crashpoint for this process —
    the in-process face of the ``EG_CRASHPOINT`` env var (tests)."""
    global _armed, _hits, _env_read
    with _lock:
        _env_read = True  # explicit arming overrides the environment
        _armed = parse_spec(spec) if spec else None
        _hits = 0


def armed() -> Optional[Dict[str, Any]]:
    """The armed site as ``{"site": ..., "hit": n}``, or None — the
    replayability rider train() stamps on the run's first record."""
    with _lock:
        _ensure_env()
        if _armed is None:
            return None
        return {"site": _armed[0], "hit": _armed[1]}


def hit(site: str) -> None:
    """Execution reached the named seam. Unarmed (the normal case):
    validates the name and returns. Armed at this site: counts the hit
    and, on the configured one, writes a one-line witness to stderr and
    dies via `os._exit(CRASHPOINT_EXIT)` — no unwind, no atexit, no
    buffer flush, exactly like a hard kill at this instant."""
    if site not in SITES:
        raise KeyError(
            f"unregistered crashpoint {site!r} — add it to "
            "chaos.crashpoint.SITES (the instrumentation lint indexes "
            "the registry)"
        )
    with _lock:
        _ensure_env()
        if _armed is None or _armed[0] != site:
            return
        global _hits
        _hits += 1
        # capture under the lock: a concurrent arm(None) between lock
        # release and the exit below must not turn the kill into a
        # TypeError on a vanished tuple
        hit_n = _armed[1]
        if _hits < hit_n:
            return
    # outside the lock: nothing below returns
    os.write(
        2,
        f"crashpoint {site} hit {hit_n}: killing process "
        f"(exit {CRASHPOINT_EXIT})\n".encode(),
    )
    os._exit(CRASHPOINT_EXIT)


# --- graceful preemption ---------------------------------------------------


class PreemptGuard:
    """Installs SIGTERM/SIGINT -> request-flag handlers for the duration
    of a training run (context manager). The handler only records the
    signal name; the loop performs the drain at its next block boundary.
    After the first signal the PREVIOUS handlers are restored, so a
    second signal interrupts a wedged drain the platform-default way.

    Installs nothing when `enabled=False` or off the main thread
    (signal.signal is main-thread-only); `requested` then just stays
    None and the loop's check is inert."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.requested: Optional[str] = None
        self._prev: Dict[int, Any] = {}

    def _handler(self, signum, frame):
        self.requested = signal.Signals(signum).name
        self._restore()  # second signal: platform default (escape hatch)

    def _restore(self) -> None:
        for signum, prev in self._prev.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):  # pragma: no cover - teardown race
                pass
        self._prev = {}

    def __enter__(self) -> "PreemptGuard":
        if not self.enabled:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev[signum] = signal.signal(signum, self._handler)
            except (ValueError, OSError):  # pragma: no cover - exotic host
                pass
        return self

    def __exit__(self, *exc) -> None:
        self._restore()


def marker_path(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, PREEMPT_MARKER)


def write_marker(checkpoint_dir: str, info: Dict[str, Any]) -> str:
    """Drop the PREEMPTED witness next to the drained snapshot, fsynced:
    whoever inspects the checkpoint dir (an operator, tools/
    crash_matrix.py) can tell a drained stop from a crash."""
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = marker_path(checkpoint_dir)
    with open(path, "w") as f:
        json.dump(info, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return path


def consume_marker(checkpoint_dir: Optional[str]) -> Optional[Dict[str, Any]]:
    """Read-and-remove the PREEMPTED marker (train() calls this on
    startup): the new incarnation supersedes the drained one, so a
    stale marker must not outlive the resume it announced."""
    if not checkpoint_dir:
        return None
    path = marker_path(checkpoint_dir)
    try:
        with open(path) as f:
            info = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError):
        info = None  # a torn marker still gets removed
    try:
        os.remove(path)
    except FileNotFoundError:
        pass  # multi-process startup: another rank consumed it first
    return info
