"""Flagship full-scale TPU run (VERDICT round-1 item 6).

ResNet-18-as-coded (3 blocks/stage, ~17.4M params), 8-rank vmap-simulated
ring, bf16 compute, the reference CIFAR op-point scale (~3.9k passes,
/root/reference/dcifar10/event/event.cpp:31-36), on the real chip:

  * eventgrad + dpsgd + sp_eventgrad legs with per-epoch JSONL metrics
  * steady-state step_ms and single-chip MFU (utils/flops.py)
  * the MNIST ~70%-headline claim leg at its exact op-point

Artifact (committed): artifacts/tpu_flagship.json (summary, published
atomically after every completed leg). The profiler trace-capture leg was
removed in round 5 — dispatch-overhead evidence lives in the derived
artifacts/tpu_trace/TRACE_SUMMARY.json; use `--profile-dir` on the CLI
for fresh captures.

Usage: python tools/tpu_flagship.py [epochs] [out_name]
       (defaults: 61 = full scale, tpu_flagship.json)
"""

from __future__ import annotations

import json
import os
import sys
import time

# runnable as `python tools/tpu_flagship.py` without installing the
# package (sys.path[0] is tools/, not the repo root)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from eventgrad_tpu.utils import compile_cache

# a JAX_PLATFORMS=cpu pin (the smoke-test path) must win over the axon
# plugin the sitecustomize pre-registered — same rule as bench.py
compile_cache.honor_cpu_pin()


def main() -> None:
    import jax.numpy as jnp
    import optax

    compile_cache.enable()

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import ResNet18
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import (
        consensus_params, evaluate, rank0_slice, train,
    )
    from eventgrad_tpu.utils.flops import (
        chip_peak_flops, mfu, train_step_flops,
    )
    from eventgrad_tpu.utils import profiling

    # EG_FLAGSHIP_ALLOW_CPU=1 is for smoke-testing this script's code path
    # only (a broken flagship would waste a live-tunnel window); artifacts
    # it produces carry platform: "cpu" and never satisfy the watcher's
    # TPU rungs (tpu_watch runs without the knob).
    if os.environ.get("EG_FLAGSHIP_ALLOW_CPU") != "1":
        assert jax.default_backend() == "tpu", (
            f"flagship run wants the real chip; backend is "
            f"{jax.default_backend()}"
        )
    epochs = int(sys.argv[1]) if len(sys.argv) > 1 else 61
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art = os.path.join(repo, "artifacts")
    os.makedirs(art, exist_ok=True)

    # op-point mirrors bench.py's FULL tier (the canonical definition —
    # keep in sync if that tier changes) and honors the same
    # EG_BENCH_HORIZON knob so the two artifacts measure one config
    topo = Ring(8)
    global_batch, n_train, n_test = 256, 16384, 2048
    smoke = os.environ.get("EG_FLAGSHIP_SMOKE") == "1"
    if smoke:
        # full SCRIPT path at toy scale — for validating this launcher
        # off-chip (with EG_FLAGSHIP_ALLOW_CPU=1) so a script bug never
        # burns a live tunnel window; never set by the watcher. LeNet/f32
        # stands in for the flagship ResNet/bf16: XLA-CPU runs the real
        # model at ~1 pass/min (measured — a 55-min toy run timed out),
        # and the smoke validates the script's stages, not the model
        # (which trains everywhere else in the suite).
        from eventgrad_tpu.models import LeNetCifar

        global_batch, n_train, n_test = 64, 512, 128
        model = LeNetCifar()
    else:
        model = ResNet18(dtype=jnp.bfloat16)
    per_rank = global_batch // topo.n_ranks
    from eventgrad_tpu.parallel.events import resolve_bench_trigger

    # same trigger resolution as bench.py — one definition, zero drift
    horizon, max_silence = resolve_bench_trigger(os.environ)
    cfg = EventConfig(adaptive=True, horizon=horizon, warmup_passes=30,
                      max_silence=max_silence)
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    xt, yt = load_or_synthesize("cifar10", None, "test", n_synth=n_test)
    # round-5 dispatch modes: K-epoch jit blocks + device-resident data
    # (auto on TPU) — the fix for the 3.9x wall/device-busy dispatch tax
    # the round-4 trace exposed (artifacts/tpu_trace/TRACE_SUMMARY.json).
    # HEARTBEAT CADENCE: with K-epoch blocks, on_epoch/history advance
    # only at block ends, so any liveness watcher (supervise.py /
    # tpu_watch) sized to per-epoch progress must tolerate ~K epochs of
    # silence — at the flagship default K=8 and ~20 s/epoch-pair that is
    # ~160 s between heartbeats; cli.py keeps K=1, so current supervise
    # users are unaffected. Size supervision timeouts to K * epoch wall,
    # not epoch wall.
    k_disp = int(os.environ.get("EG_EPOCHS_PER_DISPATCH", "8"))
    common = dict(
        epochs=epochs, batch_size=per_rank, learning_rate=1e-2, momentum=0.9,
        random_sampler=True, log_every_epoch=False,
        epochs_per_dispatch=k_disp,
    )

    # capture time stamped INSIDE the json — file mtime is reset by git
    # checkout, so it cannot serve as the capture timestamp
    out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "platform": jax.devices()[0].platform,
           "device_kind": jax.devices()[0].device_kind,
           "epochs": epochs, "passes": epochs * (n_train // global_batch),
           "global_batch": global_batch, "n_ranks": topo.n_ranks,
           "horizon": horizon, "max_silence": max_silence,
           "warmup_passes": 30, "epochs_per_dispatch": k_disp}

    out_name = sys.argv[2] if len(sys.argv) > 2 else "tpu_flagship.json"
    if out["platform"] != "tpu":
        # a non-chip run (smoke/ALLOW_CPU, any argv) must never write the
        # artifact names bench.py embeds and the watcher's rungs gate on
        out_name = "tpu_flagship_smoke.json"
    path = os.path.join(art, out_name)

    def publish() -> None:
        # atomic publish: bench.py may read this file concurrently (it
        # embeds the artifact as tpu_flagship_cached); never let it see a
        # half-write. Called after EVERY leg — the round-4 full capture
        # died to a mid-run device fault with publish() at the end and
        # lost an 850 s eventgrad leg; the tunnel is flaky by nature, so
        # every completed leg is published immediately.
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)

    t0 = time.perf_counter()
    state, hist = train(model, topo, x, y, algo="eventgrad", event_cfg=cfg,
                        **common)
    out["wall_s_eventgrad"] = round(time.perf_counter() - t0, 1)
    cons = consensus_params(state.params)
    stats0 = rank0_slice(state.batch_stats)
    out["test_acc_eventgrad"] = round(
        evaluate(model, cons, stats0, xt, yt)["accuracy"], 2
    )
    out["msgs_saved_pct"] = round(hist[-1]["msgs_saved_pct"], 2)
    from eventgrad_tpu.utils.metrics import steady_records

    steady = steady_records(hist)
    step_s = float(np.mean([h["wall_s"] / h["steps"] for h in steady]))
    out["step_ms_eventgrad"] = round(1000 * step_s, 3)

    # MFU of the flagship step (all 8 vmap-ranks on this one chip).
    # Off-chip (smoke) the peak is unknown -> skip the extra compile,
    # same guard as bench.py
    peak = chip_peak_flops()
    flops = None
    if peak:
        tx = optax.sgd(1e-2, momentum=0.9)
        flops = train_step_flops(
            model, tx, topo, "eventgrad", cfg, x, y, per_rank, state
        )
    out["flops_per_step"] = flops
    out["chip_peak_flops"] = peak or None
    got = mfu(flops, step_s) if flops else None
    out["mfu_eventgrad"] = round(got, 4) if got else None

    # analytic cost model + roofline (obs/costmodel.py, one definition
    # with bench.py's `costmodel` block — obs/schema.py PERF_FIELDS):
    # phase-split FLOPs/bytes of the same step, against the
    # obs/devicespec.py peaks; trace-only, so it costs seconds, and a
    # failure here must never lose the already-measured leg
    try:
        from eventgrad_tpu.obs import costmodel as _costmodel
        from eventgrad_tpu.obs.devicespec import device_spec

        tx_cm = optax.sgd(1e-2, momentum=0.9)
        cm = _costmodel.analyze_step(
            model, tx_cm, topo, "eventgrad", cfg, x, y, per_rank, state
        )
        rl = _costmodel.roofline(
            cm["flops_total"], cm["hbm_bytes_total"], step_s,
            device_spec(),
        )
        out["costmodel"] = _costmodel.record_block(cm, rl)
    except Exception as e:
        print(f"costmodel block skipped: {e!r}", file=sys.stderr)
    publish()

    t0 = time.perf_counter()
    state_d, hist_d = train(model, topo, x, y, algo="dpsgd", **common)
    out["wall_s_dpsgd"] = round(time.perf_counter() - t0, 1)
    cons_d = consensus_params(state_d.params)
    stats_d = rank0_slice(state_d.batch_stats)
    out["test_acc_dpsgd"] = round(
        evaluate(model, cons_d, stats_d, xt, yt)["accuracy"], 2
    )
    steady_d = steady_records(hist_d)
    out["step_ms_dpsgd"] = round(
        1000 * float(np.mean([h["wall_s"] / h["steps"] for h in steady_d])), 3
    )
    out["acc_gap_vs_dpsgd"] = round(
        out["test_acc_eventgrad"] - out["test_acc_dpsgd"], 2
    )

    # collapse guard (same rule as bench.py): a diverged leg must not
    # present as a savings win
    from eventgrad_tpu.utils.metrics import collapse_verdict

    out["collapsed_cifar"] = collapse_verdict(
        [h["loss"] for h in hist], hist_d[-1]["loss"]
    )
    # SPMD wire truth for the headline pair (docs/compaction.md): masked
    # eventgrad moves the full dense payload no matter the fire rate
    out["sent_bytes_wire_real_eventgrad"] = round(
        hist[-1].get("sent_bytes_wire_real_per_step_per_chip", 0.0), 1
    )
    out["sent_bytes_wire_real_dpsgd"] = round(
        hist_d[-1].get("sent_bytes_wire_real_per_step_per_chip", 0.0), 1
    )
    publish()

    # compact-wire leg: the SAME eventgrad op-point through the budgeted
    # compacted exchange (autotuned capacity) — the on-chip step_ms/wall
    # comparison that decides whether event sparsity pays as wall-clock
    # on ICI, next to the masked and dpsgd legs above. Skippable
    # (EG_FLAGSHIP_COMPACT=0); after the headline pair, so a wedge here
    # costs nothing already published.
    if os.environ.get("EG_FLAGSHIP_COMPACT", "1") != "0":
        # EG_FLAGSHIP_COMPACT_FRAC pins the capacity fraction — the
        # max_silence guard can synchronize periodic full-model fires and
        # make the autotuner (correctly) decline; a pinned fraction still
        # measures the compacted wire then, with deferral absorbing the
        # overflow bursts
        frac_env = os.environ.get("EG_FLAGSHIP_COMPACT_FRAC", "")
        t0 = time.perf_counter()
        state_c, hist_c = train(
            model, topo, x, y, algo="eventgrad", event_cfg=cfg,
            gossip_wire="compact",
            compact_frac=float(frac_env) if frac_env else None,
            **common,
        )
        out["wall_s_eventgrad_compact"] = round(time.perf_counter() - t0, 1)
        cons_c = consensus_params(state_c.params)
        stats_c = rank0_slice(state_c.batch_stats)
        out["test_acc_eventgrad_compact"] = round(
            evaluate(model, cons_c, stats_c, xt, yt)["accuracy"], 2
        )
        # steady slice over the COMPACT blocks only — never substitute
        # dense-block times (the whole point of this leg is the compact
        # step_ms); short rungs may leave only cold compact blocks, which
        # then ride along clearly labeled as compile-contaminated
        comp_recs = [
            h for h in hist_c if h.get("gossip_wire") == "compact"
        ]
        steady_c = [
            h for h in steady_records(hist_c)
            if h.get("gossip_wire") == "compact"
        ]
        timed = steady_c or comp_recs
        out["step_ms_eventgrad_compact"] = (
            round(1000 * float(
                np.mean([h["wall_s"] / h["steps"] for h in timed])
            ), 3) if timed else None
        )
        out["step_ms_eventgrad_compact_cold"] = bool(timed and not steady_c)
        out["compact_capacity"] = hist_c[-1].get("compact_capacity")
        out["compact_activated"] = (
            hist_c[-1].get("gossip_wire") == "compact"
        )
        out["compact_num_deferred"] = hist_c[-1].get("num_deferred")
        out["sent_bytes_wire_real_compact"] = round(
            hist_c[-1].get("sent_bytes_wire_real_per_step_per_chip", 0.0), 1
        )
        out["compact_msgs_saved_pct"] = round(
            hist_c[-1].get("msgs_saved_pct", 0.0), 2
        )
        publish()

    # E5 sparsified leg at the same op-point (round-4 verdict missing #2:
    # sp_eventgrad had never touched the chip) — top-k 10%, the reference's
    # spevent default (spevent.cpp:60). Skippable for the cheapest quick
    # rung (EG_FLAGSHIP_SPEVENT=0). After the headline pair: a wedge here
    # must not cost the eventgrad/dpsgd evidence.
    if os.environ.get("EG_FLAGSHIP_SPEVENT", "1") != "0":
        from eventgrad_tpu.parallel.sparsify import SparseConfig

        t0 = time.perf_counter()
        state_s, hist_s = train(
            model, topo, x, y, algo="sp_eventgrad", event_cfg=cfg,
            sparse_cfg=SparseConfig(10.0), **common,
        )
        out["wall_s_spevent"] = round(time.perf_counter() - t0, 1)
        cons_s = consensus_params(state_s.params)
        stats_s = rank0_slice(state_s.batch_stats)
        out["test_acc_spevent"] = round(
            evaluate(model, cons_s, stats_s, xt, yt)["accuracy"], 2
        )
        out["spevent_msgs_saved_pct"] = round(hist_s[-1]["msgs_saved_pct"], 2)
        out["spevent_sent_bytes_per_step"] = round(
            hist_s[-1]["sent_bytes_per_step_per_chip"], 1
        )
        out["step_ms_spevent"] = round(1000 * float(np.mean(
            [h["wall_s"] / h["steps"] for h in steady_records(hist_s)]
        )), 3)
        out["spevent_final_loss"] = round(hist_s[-1]["loss"], 4)
        out["spevent_acc_gap_vs_dpsgd"] = round(
            out["test_acc_spevent"] - out["test_acc_dpsgd"], 2
        )
        publish()

    # MNIST claim leg, live on the same window: the ~70% headline's exact
    # full-scale op-point (events.MNIST_FULLSCALE_OP_POINT — CNN-2,
    # batch 64/rank, lr 0.05, sequential sampler, 1168 passes,
    # dmnist/event/event.cpp:103,145,227,255). On-chip this leg is cheap
    # next to the ResNet legs, and it is the number
    # mnist_vs_baseline >= 1.0 rides on (round-3 verdict item 3).
    from eventgrad_tpu.models import CNN2
    from eventgrad_tpu.parallel.events import (
        MNIST_FULLSCALE_OP_POINT, resolve_bench_trigger_mnist,
    )

    mnist_n, mnist_epochs, mnist_batch = MNIST_FULLSCALE_OP_POINT
    mnist_warmup = 30
    if smoke:
        # warmup scales with the miniature so the smoke exercises the
        # post-warmup trigger math, not just warmup-forced fires
        mnist_n, mnist_epochs, mnist_batch = 512, 4, 16
        mnist_warmup = 2
    mnist_horizon = resolve_bench_trigger_mnist(os.environ, max_silence)
    mnist_cfg = EventConfig(
        adaptive=True, horizon=mnist_horizon, warmup_passes=mnist_warmup,
        max_silence=max_silence,
    )
    xm, ym = load_or_synthesize("mnist", None, "train", n_synth=mnist_n)
    t0 = time.perf_counter()
    _, hist_m = train(
        CNN2(), topo, xm, ym, algo="eventgrad", event_cfg=mnist_cfg,
        epochs=mnist_epochs, batch_size=mnist_batch, learning_rate=0.05,
        random_sampler=False, log_every_epoch=False,
        epochs_per_dispatch=k_disp,
    )
    out["wall_s_mnist"] = round(time.perf_counter() - t0, 1)
    out["mnist_msgs_saved"] = round(hist_m[-1]["msgs_saved_pct"], 2)
    out["mnist_passes"] = mnist_epochs * (
        mnist_n // (mnist_batch * topo.n_ranks)
    )
    out["mnist_horizon"] = mnist_horizon
    out["collapsed_mnist"] = collapse_verdict([h["loss"] for h in hist_m])
    out["mnist_vs_baseline"] = (
        0.0 if out["collapsed_mnist"]
        else round(out["mnist_msgs_saved"] / 70.0, 4)
    )
    steady_m = steady_records(hist_m)
    out["step_ms_mnist"] = round(1000 * float(
        np.mean([h["wall_s"] / h["steps"] for h in steady_m])
    ), 3)

    publish()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
