"""Flat parameter arena: cached leaf-major metadata + flat views.

The EventGraD hot path used to re-derive tree structure every step —
duplicate `jax.tree.flatten(params)` calls in train/steps.py, a fresh
ravel + segment-id materialization inside `masked_neighbor_vals` /
`compact_neighbor_vals`, per-neighbor unravels back to pytrees just so
the next op could flatten again. All of that structure is STATIC: it
depends only on (treedef, leaf shapes, leaf dtypes), never on values.

`ArenaSpec` computes it once per distinct structure and caches it with
`lru_cache` (`arena_spec`); the traced step then works on ONE contiguous
[n_total] buffer per rank ("the arena") with thin `ravel`/`unravel`
shims at the loop boundary, so models, checkpointing, and obs see the
same pytrees as before while the hot path is flat segment ops
(collectives.*_flat, ops/event_engine.py, ops/arena_update.py).

Bitwise contract: `ravel` concatenates leaves in the canonical flatten
order `jax.flatten_util.ravel_pytree` uses, `unravel` slices them back
out, and `seg_expand()` maps each flat position to its leaf index with
the exact integer values `_segment_ids` produced — every flat-path
consumer is elementwise-identical to its tree twin (tests/test_arena.py
proves the whole train step bitwise).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ArenaSpec:
    """Static leaf-major layout of one pytree structure.

    Everything here is plain Python — hashable, computed once per
    (treedef, shapes, dtypes) and cached. Methods that return arrays
    build them from this static metadata inside the current trace; the
    builds are loop-invariant, so XLA hoists them out of the scanned
    step body (they cost trace time, not step time).
    """

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    sizes: Tuple[int, ...]
    starts: Tuple[int, ...]
    n_total: int
    #: smallest legal compact capacity (largest leaf must ship whole)
    floor: int

    @property
    def n_leaves(self) -> int:
        return len(self.sizes)

    @property
    def homogeneous(self) -> bool:
        """One dtype across leaves — the arena packs one contiguous
        buffer, so heterogeneous trees stay on the tree path."""
        return len(set(self.dtypes)) <= 1

    @property
    def dtype(self):
        return jnp.dtype(self.dtypes[0])

    def sizes_arr(self) -> jnp.ndarray:
        return jnp.asarray(self.sizes, jnp.int32)

    def starts_arr(self) -> jnp.ndarray:
        return jnp.asarray(self.starts, jnp.int32)

    def seg_expand(self) -> jnp.ndarray:
        """[n_total] int32 leaf index per flat position — the values of
        collectives._segment_ids, built as one repeat over the static
        sizes (O(n), loop-invariant under scan)."""
        return jnp.repeat(
            jnp.arange(self.n_leaves, dtype=jnp.int32),
            self.sizes_arr(),
            total_repeat_length=self.n_total,
        )

    def ravel(self, tree: Any) -> jnp.ndarray:
        """One contiguous [n_total] buffer, bitwise what `ravel_pytree`
        produces for a single-dtype tree.

        NOTE the hot path deliberately does NOT call this per step: an
        [n]-assembly is a serial dependency chain that cannot overlap
        the conv/matmul work the way independent per-leaf ops do
        (measured on CPU XLA: the assembled-arena step formulations ran
        ~8 ms/step slower at the LeNetCifar ring-8 op point purely from
        the serialized assembly). The ONE per-step assembly the arena
        keeps is the wire build, fused with its masking
        (`collectives.masked_neighbor_vals_flat`); everything else works
        leaf-parallel against flat-buffer slices."""
        leaves = self.treedef.flatten_up_to(tree)
        if len(leaves) == 1:
            return leaves[0].reshape(-1)
        return jnp.concatenate(
            [l.reshape(-1).astype(self.dtype) for l in leaves]
        )

    def leaf_views(self, flat: jnp.ndarray):
        """Static per-leaf slices of the arena (no data movement until
        consumed; the elements are exactly `leaf.reshape(-1)`)."""
        return [
            flat[s : s + z] for s, z in zip(self.starts, self.sizes)
        ]

    def unravel(self, flat: jnp.ndarray) -> Any:
        """Thin unflatten shim back to the pytree view (loop boundary)."""
        leaves = [
            v.reshape(shape).astype(dt)
            for v, shape, dt in zip(
                self.leaf_views(flat), self.shapes, self.dtypes
            )
        ]
        return jax.tree.unflatten(self.treedef, leaves)

    def buckets(self, k: int) -> "Tuple[BucketSpec, ...]":
        """Segment the arena into up to `k` contiguous LEAF-ALIGNED
        buckets (the bucketed gossip schedule's unit, train/steps.py).

        Boundaries sit on leaf edges — no leaf ever straddles a bucket,
        so every bucket is itself a small arena (its own sizes/starts/
        floor) and the per-bucket wire, commit, and mix operate on whole
        leaves exactly like the monolithic path. Cut points are chosen
        element-balanced (each interior cut lands on the leaf edge
        nearest i*n_total/k), `k` clamps to the leaf count, and the
        result is lru-cached per (spec, k) like every other piece of
        leaf metadata — callers may re-derive freely inside a traced
        step."""
        return _buckets_cached(self, int(k))


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """One contiguous leaf-aligned segment of an arena.

    `lo`/`hi` are leaf indices into the parent ArenaSpec (half-open),
    `start`/`size` the element range, `sizes`/`starts_rel` the
    bucket-local leaf layout (starts_rel[0] == 0), and `floor` the
    largest leaf — the smallest legal per-bucket compact capacity
    (collectives.split_capacity)."""

    index: int
    lo: int
    hi: int
    start: int
    size: int
    sizes: Tuple[int, ...]
    starts_rel: Tuple[int, ...]
    floor: int

    @property
    def n_leaves(self) -> int:
        return self.hi - self.lo

    def sizes_arr(self) -> jnp.ndarray:
        return jnp.asarray(self.sizes, jnp.int32)

    def starts_arr(self) -> jnp.ndarray:
        return jnp.asarray(self.starts_rel, jnp.int32)

    def seg_expand(self) -> jnp.ndarray:
        """[size] int32 bucket-local leaf index per flat position — the
        bucket's slice of the parent seg map, re-based to 0."""
        return jnp.repeat(
            jnp.arange(self.n_leaves, dtype=jnp.int32),
            self.sizes_arr(),
            total_repeat_length=self.size,
        )


@functools.lru_cache(maxsize=256)
def _buckets_cached(spec: ArenaSpec, k: int) -> Tuple[BucketSpec, ...]:
    n_leaves = spec.n_leaves
    k = max(1, min(int(k), n_leaves))
    ends = [s + z for s, z in zip(spec.starts, spec.sizes)]
    cuts = []
    prev = 0
    for i in range(1, k):
        target = i * spec.n_total / k
        # the leaf edge nearest the element-balanced target, constrained
        # so every remaining bucket keeps at least one leaf (ties break
        # toward the earlier edge — deterministic)
        lo_c, hi_c = prev + 1, n_leaves - (k - i)
        best = min(
            range(lo_c, hi_c + 1),
            key=lambda c: (abs(ends[c - 1] - target), c),
        )
        cuts.append(best)
        prev = best
    bounds = [0] + cuts + [n_leaves]
    out = []
    for b in range(k):
        lo, hi = bounds[b], bounds[b + 1]
        sizes = spec.sizes[lo:hi]
        base = spec.starts[lo]
        out.append(BucketSpec(
            index=b,
            lo=lo,
            hi=hi,
            start=base,
            size=int(sum(sizes)),
            sizes=sizes,
            starts_rel=tuple(s - base for s in spec.starts[lo:hi]),
            floor=max(sizes),
        ))
    return tuple(out)


@functools.lru_cache(maxsize=256)
def _spec_cached(
    treedef, shapes: Tuple[Tuple[int, ...], ...], dtypes: Tuple[str, ...]
) -> ArenaSpec:
    sizes = tuple(
        int(math.prod(s)) if s else 1 for s in shapes
    )
    starts = tuple(int(x) for x in np.cumsum((0,) + sizes[:-1]))
    return ArenaSpec(
        treedef=treedef,
        shapes=shapes,
        dtypes=dtypes,
        sizes=sizes,
        starts=starts,
        n_total=int(sum(sizes)),
        floor=max(sizes) if sizes else 0,
    )


def arena_spec(tree: Any) -> ArenaSpec:
    """The cached ArenaSpec of `tree`'s structure.

    Safe to call inside a traced step: only static attributes (treedef,
    shapes, dtypes) form the cache key, and repeated calls on the same
    structure are cache hits — no caller can re-derive leaf metadata
    per step (asserted in tests/test_arena.py via cache_info())."""
    leaves, treedef = jax.tree.flatten(tree)
    shapes = tuple(tuple(int(d) for d in l.shape) for l in leaves)
    dtypes = tuple(str(jnp.dtype(l.dtype)) for l in leaves)
    return _spec_cached(treedef, shapes, dtypes)


def cache_info():
    """Hit/miss stats of the spec cache (regression-tested)."""
    return _spec_cached.cache_info()


# ---------------------------------------------------------------------------
# carrier-resident buffer layout: the EventState receive buffers can be
# stored in the WIRE dtype (bf16/int8 carrier + per-leaf f32 dequant
# scales) instead of dequantized f32 — the dequant multiply moves into
# the commit/mix reads, which is bitwise-free because the f32 buffers
# only ever held exactly `dequant(carrier)` (docs/ARCHITECTURE.md
# "Carrier-resident receive buffers").

#: wire codes that have a resident carrier cheaper than f32
_CARRIER_DTYPES = {"bf16": jnp.bfloat16, "int8": jnp.int8}


def carrier_dtype(wire) -> "Any":
    """Resident dtype for a wire code — the carrier the bytes crossed
    the wire in (bf16 -> bfloat16, int8 -> int8) — or None when the
    buffers stay at the arena dtype (dense/f32 wires have no cheaper
    carrier, so carrier residency is a no-op for them)."""
    if wire is None:
        return None
    return _CARRIER_DTYPES.get(str(wire))


def carrier_needs_scales(wire) -> bool:
    """int8 carriers dequantize through per-leaf f32 scales; bf16
    dequant is a pure (exact) upcast and needs none."""
    return str(wire) == "int8"


def alloc_event_bufs(
    spec: ArenaSpec, n_neighbors: int, *, wire=None, buckets: int = 1,
):
    """THE arena EventState.bufs allocation site (lint rule
    `carrier-dtype-declared`: every buffer allocation must route through
    here — no ad-hoc `astype`/`zeros` on receive buffers, so the
    resident dtype is always declared against the wire code).

    Returns `(bufs, buf_scales)`: per-neighbor zero receive buffers in
    the RESIDENT dtype — the arena dtype classically, the wire carrier
    under carrier-resident gossip — plus per-leaf f32 dequant scale
    slots (int8 carrier only; one scalar per leaf per neighbor, because
    leaves commit wholesale so every element of a leaf shares the scale
    it crossed the wire with). `buckets=K` gives both the per-bucket
    tuple layout of the bucketed gossip schedule. A zero carrier
    dequantizes to exactly +0.0 under every scale, so the zero init is
    bitwise the classic f32 zero init (event.cpp:177-179)."""
    cdt = carrier_dtype(wire)
    dt = spec.dtype if cdt is None else cdt
    k = int(buckets) if buckets else 1
    if k > 1:
        buf0 = tuple(jnp.zeros((b.size,), dt) for b in spec.buckets(k))
    else:
        buf0 = jnp.zeros((spec.n_total,), dt)
    bufs = tuple(buf0 for _ in range(int(n_neighbors)))
    if cdt is None or not carrier_needs_scales(wire):
        return bufs, None
    if k > 1:
        s0 = tuple(
            jnp.ones((len(b.sizes),), jnp.float32) for b in spec.buckets(k)
        )
    else:
        s0 = jnp.ones((spec.n_leaves,), jnp.float32)
    return bufs, tuple(s0 for _ in range(int(n_neighbors)))


def alloc_event_queue(
    spec: ArenaSpec, n_neighbors: int, depth: int, *, wire=None,
    buckets: int = 1,
):
    """Bounded-async delivery-queue slot allocation (`EventState.pending`
    for staleness=D >= 2) — the queue twin of `alloc_event_bufs`, and
    routed THROUGH it so the carrier layout (resident dtype + dequant
    scales) stays declared in exactly one place.

    Per neighbor: `depth` slots of
        (candidate, eff fire bits, sent-pass i32, late-count i32
         [, dequant scales — int8 carrier only])
    where the candidate (and scales) carry the SAME layout as the
    receive buffers themselves: flat [n_total] monolithic or the
    per-bucket tuple of the bucketed schedule, in the wire dtype under
    carrier residency. A queued zero slot commits nothing (eff all
    False) and a zero carrier dequantizes to exactly +0.0, so the zero
    init is bitwise the empty queue. The slot index stays the second
    path component of the checkpoint layout
    (`state/event/pending/<edge>/<slot>/...`), which the cross-depth
    restore guard keys on."""
    k = int(buckets) if buckets else 1
    bufs, scales = alloc_event_bufs(spec, 1, wire=wire, buckets=k)
    cand0, scale0 = bufs[0], (scales[0] if scales is not None else None)
    if k > 1:
        eff0 = tuple(
            jnp.zeros((b.n_leaves,), bool) for b in spec.buckets(k)
        )
    else:
        eff0 = jnp.zeros((spec.n_leaves,), bool)
    slot0 = (
        cand0,                      # zero candidate (immutable — shared)
        eff0,                       # eff: commits are no-ops
        jnp.zeros((), jnp.int32),   # sent pass 0 = empty
        jnp.zeros((), jnp.int32),   # late messages in the slot
    ) + ((scale0,) if scale0 is not None else ())
    return tuple(
        tuple(slot0 for _ in range(int(depth)))
        for _ in range(int(n_neighbors))
    )
