"""Per-rank train steps for all four algorithm families.

One builder per reference executable:

  algo="allreduce"    — E1 `cent`: psum-mean of gradients, then SGD
                        (/root/reference/dmnist/cent/cent.cpp:130-145).
  algo="dpsgd"        — E2 `decent`: ppermute params to both ring neighbors,
                        mix (p+l+r)/3 between backward and step — exact
                        D-PSGD ordering (decent.cpp:173-246).
  algo="eventgrad"    — E3/E4 `event`: per-parameter event bits gate a
                        masked exchange; receivers hold stale buffers
                        (event.cpp:306-488).
  algo="sp_eventgrad" — E5 `spevent`: fired parameters ship top-k
                        (value, index) payloads scattered into persistent
                        neighbor replicas (spevent.cpp:339-542).

The returned `step(state, batch)` is pure per-rank SPMD code (collectives on
named axes); lift it with `parallel.spmd` under either a real mesh or the
single-chip vmap simulator, and wrap in `jax.jit` with donated state.

Loss: softmax cross-entropy on the model output. For models that already
emit log-probabilities this equals the reference's double-log_softmax
(nll_loss∘log_softmax of a log_softmax output, event.cpp:291) because
log_softmax is idempotent; for logit models (MLP/ResNet) it equals
nll_loss∘log_softmax (cent.cpp:119) and cross_entropy
(dcifar10/event/event.cpp:268) respectively.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from eventgrad_tpu.chaos import inject as chaos_inject
from eventgrad_tpu.chaos import monitor as chaos_monitor
from eventgrad_tpu.obs import device as obs_device
from eventgrad_tpu.obs.costmodel import phase_scope as _phase
from eventgrad_tpu.chaos.policy import RecoveryPolicy, alive_mask
from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.data.augment import pad_flip_crop
from eventgrad_tpu.ops import arena_tuning, event_engine
from eventgrad_tpu.ops.arena_update import (
    fused_mix_commit,
    fused_mix_commit_carrier,
    mix_commit_carrier_reference,
    mix_commit_reference,
)
from eventgrad_tpu.ops.fused_update import fused_mix_sgd
from eventgrad_tpu.parallel import arena as arena_lib
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel import policy as policy_lib
from eventgrad_tpu.parallel.events import (
    EventConfig, async_bucket_commit, async_delivery_commit,
    async_delivery_plan, capacity_gate,
)
from eventgrad_tpu.parallel.sparsify import SparseConfig, sparse_exchange
from eventgrad_tpu.parallel.topology import Topology
from eventgrad_tpu.utils import trees

ALGOS = ("allreduce", "dpsgd", "eventgrad", "sp_eventgrad")


def _fired_accounting(fire_vec: jnp.ndarray, sizes) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(fired payload elements, fired leaf count) as f32 scalars, summed
    in int32 — exact to 2^31 elements, where the old per-leaf f32 add
    chain started rounding past 2^24 fired elements (the flagship
    ResNet's 17.4M-param full-fire case). ONE definition shared by the
    tree and arena event branches so their metrics stay bitwise."""
    sizes_arr = jnp.asarray(sizes, jnp.int32)
    fired_elems = jnp.sum(
        jnp.where(fire_vec, sizes_arr, 0)
    ).astype(jnp.float32)
    fired_leaves = jnp.sum(fire_vec.astype(jnp.int32)).astype(jnp.float32)
    return fired_elems, fired_leaves


def _xent(output: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Softmax cross-entropy over the trailing class axis; `labels` has the
    output's shape minus that axis (so this serves both [B,C] classification
    and [B,T,V] next-token prediction)."""
    logp = jax.nn.log_softmax(output, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    topo: Topology,
    algo: str = "dpsgd",
    event_cfg: Optional[EventConfig] = None,
    sparse_cfg: Optional[SparseConfig] = None,
    augment: bool = False,
    sync_bn: bool = False,
    fused_sgd: Optional[Tuple[float, float]] = None,
    trace: bool = False,
    wire_bf16: bool = False,
    wire: "Optional[str]" = None,
    staleness: int = 0,
    chaos: Optional[ChaosSchedule] = None,
    chaos_policy: Optional[RecoveryPolicy] = None,
    gossip_wire: str = "dense",
    compact_capacity: Optional[int] = None,
    obs: bool = False,
    arena: bool = False,
    integrity: Optional[Any] = None,
    bucketed: Optional[int] = None,
    trigger_policy: Optional[str] = None,
    carrier_resident: Optional[bool] = None,
) -> Callable:
    """Build the per-rank step. `batch` is (images [B,H,W,C], labels [B]).

    arena=True routes the gossip hot path through the flat parameter
    arena (parallel/arena.py): the wire ships as ONE contiguous
    [n_params] buffer with the event mask fused into its assembly,
    stale neighbor buffers are carried flat in EventState.bufs (the
    state MUST then come from EventState.init(..., arena=True) — the
    loop handles this), the trigger/gate/pack sender side runs as one
    fused pass (ops/event_engine.event_propose_pack) over lru-cached
    leaf metadata, and the receive commit + mix read the flat buffers
    through wide selects and per-leaf views feeding the optimizer tail
    directly. Training is BITWISE the tree path (tests/test_arena.py);
    models, checkpoint pytrees, and obs schemas are untouched. Requires
    a single parameter dtype — heterogeneous trees silently keep the
    tree path. With fused_sgd, the arena tail is the fused_mix_commit
    kernel (ops/arena_update.py): buffer commit + mix + SGD in ONE
    pass instead of fused_mix_sgd's separate scatter.

    fused_sgd=(lr, momentum): replace the mix + optax tail of gossip
    algorithms with the Pallas fused_mix_sgd kernel (ops/fused_update.py) —
    one HBM read/write per parameter element. The values MUST match the
    `tx` the state was initialized with (plain SGD, optional trace
    momentum); interpret mode is selected automatically off-TPU.

    wire ("bf16" | "int8"; wire_bf16=True is shorthand for "bf16")
    compresses gossip payloads for the transfer — bf16 halves the
    reference's float32 MPI wire bytes, int8 quarters them via per-leaf
    absmax-scaled quantization (one f32 scale per parameter tensor rides
    along). Local parameters, event norms, and thresholds stay full
    precision — only the received neighbor values round. Gossip
    algorithms only (allreduce gradients keep full precision).

    staleness=1 (event algorithms only) mixes with the PREVIOUS step's
    received buffers and lets this step's exchange land for the next one —
    the deterministic model of the reference's real RMA asynchrony (a rank
    may read its window before the neighbor's Put arrives,
    event.cpp:348-360 vs :399-438; pass 1 then averages the zero-initial
    window exactly as event.cpp:177-179,469-471 allows). On TPU this also
    frees XLA to overlap the ppermute with the next step's compute, since
    nothing in the current step consumes its result.

    staleness=D for D >= 2 (eventgrad + arena only) is the BOUNDED-ASYNC
    gossip engine: each edge carries a D-slot delivery queue in
    EventState.pending, a received candidate commits when its scheduled
    lag (chaos `lag=`/`slow=` clauses, clamped to D —
    chaos.inject.lag_vector) elapses, and the mix reads whatever has
    landed — a rank runs up to D passes ahead of a late neighbor
    instead of stalling the ring. A late delivery is committed on
    arrival through the same `where(eff, cand, stale)` select as every
    other path, so late ≡ a fire deferred to its arrival pass, bitwise
    (events.async_delivery_commit; tests/test_bounded_async.py). With
    no lag schedule every edge runs at the baseline lag 1, and the
    trajectory is bitwise staleness=1's. Per-edge staleness clocks and
    a late-commit counter ride the metrics (`edge_staleness`,
    `late_commits`) and — with obs — the telemetry. Not combinable
    with bucketed/fused/trace; see docs/chaos.md "Bounded-async gossip
    & stragglers".

    trace=True (event algorithms only) adds per-parameter send-side trace
    vectors to the metrics — current norm, threshold, fired bit, leaf-major
    order — the reference's `file_write=1` send{r}.txt instrumentation
    (event.cpp:337-339,385-391).

    gossip_wire="compact" (eventgrad only) replaces the masked dense
    exchange with the budgeted compacted wire
    (collectives.compact_neighbor_vals): only fired leaves' elements
    travel, through a static buffer of `compact_capacity` elements; fired
    leaves beyond the budget are DEFERRED — their fire bit clears and
    their event state rolls back (events.capacity_gate/commit), with
    max_silence-overdue leaves claiming budget first. Pick the capacity
    with collectives.choose_capacity (train/loop.py autotunes it from the
    observed post-warmup fire rate and keeps the dense path through
    warmup). The `sent_bytes_wire_real` metric reports the bytes each
    mode ACTUALLY moves per step; `sent_bytes` stays the reference-MPI
    accounting model. See docs/compaction.md.

    obs=True accumulates the on-device telemetry counters
    (obs.device.TelemetryState — per-leaf fire/deferral counts, threshold
    and drift-norm sums, silence histogram, per-edge wire-real bytes)
    into `state.telemetry`, which MUST then be a TelemetryState (the loop
    initializes it; see train(obs=...)). All updates are fused vector ops
    carried by the scan — no host syncs, no extra dispatches; with
    obs=False the traced program is bit-identical to before the telemetry
    subsystem existed (regression-tested in tests/test_obs.py).

    integrity (a chaos.integrity.IntegrityConfig) arms the in-step
    integrity defenses on the event exchange (algo="eventgrad" only):
    with `checksum`, every masked/compact payload ships a
    collectives.wire_checksum and a failed verification is treated
    exactly as an event that did not fire (stale buffer kept, rejection
    counted per edge and — with chaos — fed into PeerHealth silence so
    the existing sync/freeze policies escalate); with `quarantine`,
    non-finite local gradients make the rank skip its optimizer update
    and suppress its sends for the step (it keeps mixing — gossip is
    the recovery), incoming payloads are finite-checked like a failed
    checksum, and a non-finite post-update parameter set rolls the rank
    back to its pre-step state. With both flags off (or integrity=None)
    the traced step is bit-identical to a pre-integrity build; with
    them on but no faults firing, the trajectory is bitwise-unchanged
    (gates that never trip select the same values). The chaos
    `bitflip=` / `nanstep=` clauses inject the corresponding faults —
    with integrity off they land silently (the measured counterfactual
    of tools/integrity_sweep.py). Not combinable with the fused Pallas
    tail (the quarantine gate rides the optax tail).

    bucketed=K (None/1 = off) restructures the event-exchange hot path
    into the BUCKETED gossip schedule: the flat arena is segmented into
    K contiguous leaf-aligned buckets (parallel/arena.py
    ArenaSpec.buckets) and the per-bucket gate -> pack -> exchange ->
    commit -> mix chain is emitted software-pipelined (bucket k's
    ppermute is dispatched between bucket k-1's commit and mix, with no
    dataflow edge forcing that order), so XLA's scheduler can overlap
    one bucket's exchange with another bucket's update work — the
    on-device analogue of the reference's non-blocking MPI sends and of
    the zero-bubble host pipeline (docs/ARCHITECTURE.md "Bucketed
    gossip schedule"). Training is BITWISE the monolithic path
    (tests/test_bucketed.py): every bucket's wire lanes are the
    bucket's slice of the monolithic wire, per-leaf int8 scales are
    bucket-invariant, and the [L] trigger state machine stays global.
    The compact wire's capacity splits per bucket
    (collectives.split_capacity: element-proportional, per-bucket
    floors, exact total) and deferral re-contention is BUCKET-LOCAL.
    eventgrad needs arena=True (the buckets segment the flat arena;
    EventState.bufs is then carried per-bucket — cross-layout
    checkpoint restores fail loudly); sp_eventgrad groups its per-leaf
    exchange by the same buckets with unchanged state. Not combinable
    with in-step integrity or chaos bitflips (whole-wire contracts),
    and the per-bucket fused tail requires a measured
    ops/arena_tuning.bucketed_tail_ok() entry (bench_kernels.py
    bucketed) — unmeasured shapes keep the monolithic fused path.

    chaos (a chaos.ChaosSchedule) injects deterministic message loss into
    the gossip edges inside this fused step: a dropped message keeps the
    receiver's stale buffer (eventgrad) or leaves the edge out of a
    weight-renormalized mix (dpsgd) — see chaos/inject.py. chaos_policy
    (chaos.RecoveryPolicy, requires chaos; ChaosSchedule() is the no-fault
    schedule if only monitoring/recovery is wanted) adds receiver-side
    forced full-sync and edge-freeze recovery, with per-edge PeerHealth
    carried in state.chaos and surfaced in the metrics. Gossip exchange
    algorithms only (allreduce has no edges to drop; sp_eventgrad's
    scatter replicas are future work), and not combinable with the fused
    Pallas tail (whose mix weight is baked in, incompatible with
    edge-gated renormalization).

    trigger_policy names a registered TriggerPolicy (parallel/policy.py:
    norm_delta | topk | micro | hybrid; None = the algo's default, the
    exact pre-refactor behavior). The policy's propose/commit delegates
    drive every event branch, and partitioned policies (micro/hybrid)
    contribute (force, suppress) leaf masks merged into the existing
    chaos force-fire / quarantine-suppress seams. The compact guard
    consults the policy's WireSpec instead of matching on algo.

    carrier_resident=True (eventgrad + arena + bf16/int8 wire;
    staleness <= 1; no integrity/bitflip riders) keeps EventState.bufs
    CARRIER-RESIDENT: the buffers store the wire dtype the bytes
    arrived in, plus per-leaf f32 dequant scales in
    EventState.buf_scales (int8 only), and the dequant multiply runs
    inside the commit/mix reads — 1-2 bytes/element of buffer traffic
    instead of 4, bitwise-identical training (the f32 buffers only
    ever held exactly dequant(carrier); tests/test_arena.py
    carrier cells). The state MUST come from
    EventState.init(..., resident_wire=wire); the resident dtype is
    checkpoint layout (cross-layout restores fail loudly in both
    directions, train/loop.py). sp_eventgrad accepts the flag as a
    documented no-op (its replicas are tree state). Default OFF.
    """
    if algo not in ALGOS:
        raise ValueError(f"unknown algo {algo!r}; expected one of {ALGOS}")
    staleness = int(staleness)
    if staleness < 0:
        raise ValueError(
            f"staleness must be >= 0, got {staleness}: 0 = synchronous "
            "mixing, 1 = one-pass-stale (the deterministic RMA model), "
            "D >= 2 = the bounded-async gossip engine (a rank runs up "
            "to D passes ahead of a late neighbor; algo='eventgrad' "
            "with arena=True)"
        )
    if staleness and algo not in ("eventgrad", "sp_eventgrad"):
        raise ValueError(
            f"staleness={staleness} models the one-sided RMA asynchrony "
            "of the event algorithms (eventgrad, sp_eventgrad); "
            "allreduce/dpsgd are synchronous in the reference"
        )
    if staleness and trace:
        raise ValueError(
            "trace records model the synchronous exchange; not available "
            "with staleness > 0"
        )
    if staleness >= 2:
        # the bounded-async engine: per-edge delivery queues carried in
        # EventState.pending (eventgrad; D slots deep, per-bucket under
        # bucketed=K, carrier-resident under carrier_resident=True) or
        # SparseState.pending (sp_eventgrad payload queues) —
        # commit-on-arrival semantics either way
        if algo == "eventgrad" and not arena:
            raise ValueError(
                f"staleness={staleness} carries its delivery queues as "
                "flat arena buffers — algo='eventgrad' needs arena=True "
                "(the loop's auto mode resolves this; see "
                "train(staleness=...)) — drop staleness to <= 1 or "
                "pass arena=True"
            )
        if fused_sgd is not None:
            raise ValueError(
                f"staleness={staleness} is not combinable with the "
                "fused update tail: the kernel bakes in a mix-stale "
                "bool, not a D-deep delivery queue — drop fused_update "
                "(or staleness to <= 1) to compose"
            )
    if chaos is not None and algo not in ("dpsgd", "eventgrad"):
        raise ValueError(
            "chaos injection targets the gossip exchange algorithms "
            f"(dpsgd, eventgrad); got algo={algo!r}"
        )
    if chaos is not None and fused_sgd is not None:
        raise ValueError(
            "chaos is not combinable with the fused update tail: the "
            "Pallas kernel bakes in the uniform mix weight, which "
            "edge-freeze renormalization must vary per step"
        )
    if chaos_policy is not None and chaos is None:
        raise ValueError(
            "chaos_policy requires chaos (pass ChaosSchedule() to run "
            "monitoring/recovery without injected faults)"
        )
    integ_checksum = integrity is not None and integrity.checksum
    integ_quar = integrity is not None and integrity.quarantine
    if (integ_checksum or integ_quar) and algo != "eventgrad":
        raise ValueError(
            "integrity checksums/quarantine ride the event exchange's "
            f"not-fired semantics (algo='eventgrad'); got algo={algo!r}"
        )
    if (integ_checksum or integ_quar) and fused_sgd is not None:
        raise ValueError(
            "integrity is not combinable with the fused update tail: the "
            "quarantine gate selects between the mixed and updated "
            "parameters in the optax tail"
        )
    if chaos is not None and (chaos.has_bitflips or chaos.has_nansteps):
        if algo != "eventgrad":
            raise ValueError(
                "bitflip=/nanstep= faults target the event exchange "
                f"(algo='eventgrad'); got algo={algo!r}"
            )
    n_buckets = int(bucketed) if bucketed else 1
    if n_buckets < 1:
        raise ValueError(f"bucketed must be >= 1 (or None), got {bucketed}")
    if n_buckets > 1:
        if algo not in ("eventgrad", "sp_eventgrad"):
            raise ValueError(
                "bucketed=K pipelines the event-exchange hot path "
                f"(eventgrad, sp_eventgrad); got algo={algo!r}"
            )
        if algo == "eventgrad" and not arena:
            raise ValueError(
                "bucketed=K segments the flat parameter arena — "
                "algo='eventgrad' needs arena=True (the loop's auto "
                "mode resolves this; see train(bucketed=...))"
            )
        if integ_checksum or integ_quar:
            raise ValueError(
                "bucketed is not combinable with the in-step integrity "
                "defenses: checksums and rejection verdicts are "
                "whole-wire per-edge contracts, not per-bucket ones"
            )
        if chaos is not None and chaos.has_bitflips:
            raise ValueError(
                "bucketed is not combinable with chaos bitflip= faults: "
                "the corruption transform targets ONE wire buffer per "
                "edge, which the bucketed schedule splits K ways"
            )
        if fused_sgd is not None:
            if algo != "eventgrad":
                raise ValueError(
                    "bucketed + fused_sgd rides the arena fused tail "
                    f"(algo='eventgrad'); got algo={algo!r}"
                )
            if not arena_tuning.bucketed_tail_ok(bucketed):
                raise ValueError(
                    "bucketed + fused_sgd needs a measured winning "
                    "bucketed_tail_speedup entry for this K in "
                    "ops/arena_tuning.json (run `python "
                    "bench_kernels.py bucketed` on this device to "
                    "write one) — unmeasured/losing shapes keep the "
                    "monolithic fused path (train/loop.py demotes "
                    "with a warning)"
                )
    chaos_policy = chaos_policy or RecoveryPolicy()
    if chaos is not None:
        chaos_policy.validate_against(event_cfg.max_silence if event_cfg else 0)
        if chaos_policy.sync_after and algo != "eventgrad":
            raise ValueError(
                "sync_after rides the event fire decision (force_fire); "
                "dpsgd already sends everything every pass — a dropped "
                "message there is final (use freeze_after / ring heal)"
            )
    event_cfg = event_cfg or EventConfig()
    sparse_cfg = sparse_cfg or SparseConfig()
    n_nb = topo.n_neighbors
    fused_interpret = jax.default_backend() != "tpu"
    if wire_bf16:
        wire = wire or "bf16"
    if wire not in collectives.WIRE_MODES:
        raise ValueError(f"wire must be one of {collectives.WIRE_MODES}")
    if gossip_wire not in ("dense", "compact"):
        raise ValueError(
            f"gossip_wire must be 'dense' or 'compact', got {gossip_wire!r}"
        )
    # trigger-policy resolution (parallel/policy.py): the algo's default
    # when unset — the base delegates are the SAME events.* function
    # objects the branches below always called, so default builds are
    # trace-identical to the pre-refactor step. dpsgd/allreduce have no
    # trigger; an explicit policy there is a configuration error.
    pol = None
    if algo in policy_lib.DEFAULT_FOR_ALGO or trigger_policy is not None:
        pol = policy_lib.resolve(trigger_policy, algo)
    pol_partitioned = pol is not None and pol.wire_spec().partitioned
    if gossip_wire == "compact":
        wspec = pol.wire_spec() if pol is not None else None
        if wspec is None or "compact" not in wspec.gossip_wires:
            raise ValueError(
                "gossip_wire='compact' rides the statically-sized wire "
                "of an event trigger policy (algos: eventgrad, "
                f"sp_eventgrad); algo={algo!r} with policy "
                f"{pol.name if pol else 'none'!r} declares no compact "
                "wire (parallel/policy.py WireSpec)"
            )
        if wspec.compact_needs_capacity:
            if compact_capacity is None or int(compact_capacity) < 1:
                raise ValueError(
                    "gossip_wire='compact' needs a static compact_capacity "
                    "(elements); pick one with collectives.choose_capacity "
                    "or let train(gossip_wire='compact') autotune it"
                )
            compact_capacity = int(compact_capacity)
        else:
            # sp_eventgrad's top-k lanes are already physically sparse and
            # statically sized — compact is a no-op alias of its native
            # wire; no element budget, no dense warmup
            compact_capacity = None
    # --- carrier-resident resolution: EventState.bufs stay in the WIRE
    # dtype (+ per-leaf int8 scales in EventState.buf_scales) and the
    # dequant runs inside the commit/mix reads — bitwise the f32-resident
    # step (the f32 buffers only ever held exactly dequant(carrier)), at
    # 1-2 B/elem of buffer traffic instead of 4. The state must then come
    # from EventState.init(..., resident_wire=wire) — the loop handles
    # this (train(carrier_resident=...)). Default OFF: the resident dtype
    # is checkpoint layout, so flipping it is an explicit opt-in.
    carrier_wire = None
    if carrier_resident:
        if algo == "sp_eventgrad":
            # sp's top-k replicas are tree state (nothing arena-resident
            # to re-dtype) — accepted as a documented no-op so sweeps can
            # hold the flag fixed across algos
            pass
        else:
            if algo != "eventgrad":
                raise ValueError(
                    "carrier_resident=True re-dtypes the event exchange's "
                    f"receive buffers (algo='eventgrad'); got algo={algo!r}"
                )
            if not arena:
                raise ValueError(
                    "carrier_resident=True rides the flat arena buffer "
                    "layout — needs arena=True (the loop's auto mode "
                    "resolves this; see train(carrier_resident=...))"
                )
            if wire not in ("bf16", "int8"):
                raise ValueError(
                    "carrier_resident=True keeps the buffers in the wire "
                    f"carrier dtype, but wire={wire!r} has none — use "
                    "wire='bf16'/'int8' (f32 wires are already resident)"
                )
            if integ_checksum or integ_quar:
                raise ValueError(
                    "carrier_resident=True is not combinable with the "
                    "in-step integrity defenses (their verdicts read "
                    "dequantized wire values)"
                )
            if chaos is not None and (chaos.has_bitflips or chaos.has_nansteps):
                raise ValueError(
                    "carrier_resident=True is not combinable with chaos "
                    "bitflip=/nanstep= faults (the corruption transform "
                    "targets the dequantized wire buffer)"
                )
            carrier_wire = wire

    def step(state, batch):
        x, y = batch
        rng, k_aug, k_drop = jax.random.split(state.rng, 3)
        pass_num = state.pass_num + 1

        if augment:
            x = pad_flip_crop(k_aug, x)

        has_bn = bool(jax.tree.leaves(state.batch_stats))

        def loss_fn(params):
            variables = {"params": params}
            if has_bn:
                variables["batch_stats"] = state.batch_stats
            # "losses" collects auxiliary objectives sown by the model (e.g.
            # the MoE load-balancing loss, models/moe.py); empty otherwise.
            out, updated = model.apply(
                variables,
                x,
                train=True,
                rngs={"dropout": k_drop},
                mutable=["batch_stats", "losses"],
            )
            new_stats = updated["batch_stats"] if has_bn else state.batch_stats
            loss = _xent(out, y)
            for leaf in jax.tree.leaves(updated.get("losses", {})):
                loss = loss + jnp.sum(leaf)
            return loss, (out, new_stats)

        # explicit jax.vjp (what value_and_grad wraps — bitwise the same
        # cotangent pull-back): the backward pass is a plain function
        # call here, so the bucketed schedule below can begin emitting
        # per-bucket exchange work against its outputs with no
        # value_and_grad closure in between. The phase scope is trace
        # metadata for the cost model (obs/costmodel.py) — the backward
        # equations inherit it through vjp transposition.
        with _phase("grad"):
            loss, vjp_fn, (out, new_stats) = jax.vjp(
                loss_fn, state.params, has_aux=True
            )
            (grads,) = vjp_fn(jnp.ones((), loss.dtype))

        # auxiliary (non-gossip) parallelism axes — e.g. sequence parallelism:
        # ranks along them hold identical parameters and share one logical
        # batch, so gradients (and BN stats) are plain data-parallel pmeans
        # there; gossip applies only across topo.gossip_axes.
        for aux in topo.aux_axes:
            grads = lax.pmean(grads, aux)
            if has_bn:
                new_stats = lax.pmean(new_stats, aux)

        # tensor/expert-parallel axes: each rank owns distinct shards of the
        # parameters named with the `tp_` prefix (models/tp.py convention).
        # JAX's psum transpose under both vmap and shard_map(check_vma=False)
        # scales every cotangent by the axis size (transpose(psum) == psum of
        # replicated cotangents), so: sharded leaves divide by N (their
        # per-rank grad is already the right shard), replicated leaves pmean
        # (sum of per-rank path contributions / N) — verified against an
        # unsharded twin in tests/test_tensor_parallel.py.
        for ax in topo.sharded_axes:
            n_ax = topo.axis_size(ax)

            def fix(path, g, _ax=ax, _n=n_ax):
                sharded = any(
                    getattr(p, "key", "").startswith("tp_") for p in path
                )
                return g / _n if sharded else lax.pmean(g, _ax)

            grads = jax.tree_util.tree_map_with_path(fix, grads)

        # chaos nanstep= injection: poison this rank's step with NaN on
        # the scheduled pass. The poison is a SCALAR NaN/1.0 factor per
        # rank, so "NaN gradients" and "NaN optimizer updates/state"
        # are the same fault (every float leaf of a poisoned rank goes
        # NaN either way; an unpoisoned pass multiplies by exactly
        # 1.0). It is applied to the optimizer TAIL (_poison below) — a
        # purely elementwise chain, fusion-order-exact — rather than to
        # `grads`: a multiply consuming the vjp outputs hands XLA:CPU
        # an extra dataflow edge into the batch-reduction fusion group,
        # which it resolves DIFFERENTLY under the vmap and shard_map
        # lifts (optimization barriers are stripped on CPU, so they
        # cannot pin it), breaking the cross-lift bitwise contract
        # (tests/test_integrity.py test_integrity_bitwise_shard_map,
        # tests/test_mesh_parity.py).
        poison = None
        bad = None
        if chaos is not None and chaos.has_nansteps:
            poison = chaos_inject.nanstep_mask(chaos, topo, pass_num)
            bad = jnp.where(poison, jnp.float32(jnp.nan), jnp.float32(1.0))

        def _poison(tree_):
            """NaN every float leaf of a poisoned rank (identity off).
            Applied at the three optax tails — the only tails reachable
            here, since chaos (any clause) + fused_sgd is rejected
            above, so no fused/bucketed-fused path can skip it."""
            if bad is None:
                return tree_
            return jax.tree.map(
                lambda v: v * bad.astype(v.dtype)
                if jnp.issubdtype(v.dtype, jnp.inexact) else v,
                tree_,
            )

        # non-finite quarantine (chaos/integrity.py): a rank whose grads
        # went NaN/Inf skips its update and suppresses its sends this
        # pass. One stacked [L]-scalar reduction — the guard's whole
        # cost. The verdict on a poisoned step is (organically
        # non-finite) | poison — bitwise what a finite-check on
        # post-poison gradients returns (the scalar factor NaNs every
        # element), with the reduction reading the PRISTINE vjp outputs.
        quar = None
        if integ_quar:
            quar = ~jnp.all(jnp.stack(
                [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
            ))
            if poison is not None:
                quar = quar | poison

        # chaos bitflip= injection: the per-edge in-transit corruption
        # transform the event exchanges apply to received wire buffers
        corrupt_fn = None
        if chaos is not None and chaos.has_bitflips:
            cbits, csalts = chaos_inject.corrupt_mask(chaos, topo, pass_num)
            corrupt_fn = lambda i, buf: chaos_inject.flip_one_bit(
                buf, cbits[i], csalts[i]
            )
        integ_wire = bool(
            integ_checksum or integ_quar or corrupt_fn is not None
        )
        oks = None  # per-edge wire verdicts (bool [n_nb]) when integ_wire

        params = state.params
        event_state = state.event
        sparse_state = state.sparse
        # wire accounting: bytes per payload element on the exchange; int8
        # additionally ships one f32 scale per parameter leaf
        # (collectives._int8_encode). The accounting models the reference's
        # MPI wire: a non-fired parameter sends nothing — no payload, no
        # scale — so the event algorithms count scales per FIRED leaf only;
        # the always-shipped fire-bit/scale vectors of the SPMD ppermute
        # are artifacts with no reference-wire counterpart.
        val_bytes = collectives.WIRE_VAL_BYTES[wire]
        scale_bytes_per_leaf = 4.0 if wire == "int8" else 0.0
        n_params_static = trees.tree_count_params(params)
        n_leaves_static = trees.tree_num_leaves(params)
        total_bytes = jnp.float32(
            val_bytes * n_params_static
            + scale_bytes_per_leaf * n_leaves_static
        )
        fired_frac = jnp.float32(1.0)
        sent_bytes = jnp.float32(n_nb) * total_bytes
        # wire truth (vs the accounting model above): bytes the SPMD
        # collective actually moves per step — dense payloads ship whole
        # regardless of fire bits; only the compact wire (and sp's top-k
        # lanes) shrink this number. Constant per step per mode.
        fired_elems = jnp.float32(n_params_static)
        wire_real = jnp.float32(n_nb) * collectives.wire_real_bytes_per_neighbor(
            n_params_static, n_leaves_static, wire
        )

        # chaos: per-edge delivered bits for this pass (deterministic in
        # (seed, pass, rank, edge) — see chaos/inject.py); [n_nb] bool
        health = state.chaos
        deliver = None
        if chaos is not None:
            deliver = chaos_inject.delivery_mask(chaos, topo, pass_num)

        # telemetry inputs captured by the algo branches (obs=True only):
        # the event proposal and the EFFECTIVE (post-gate) fire vector
        obs_prop = None
        obs_fire_vec = None
        # bounded-async outputs (staleness >= 2 only): per-edge
        # staleness gauge [n_nb] and this pass's late-commit count
        edge_stale = None
        late_now = None
        # message-lifecycle ledger observables (obs/ledger.py; obs=True
        # only): the suppress mask the branch actually applied to the
        # proposal, the per-edge census of the neighbor's raw wire fire
        # bits, and the bounded-async lag vector (the per-edge integrity
        # verdicts `oks` and chaos `deliver` are already in scope) —
        # ledger_update derives every disposition from these, so no
        # counter math lives in this file
        obs_suppress = None
        obs_n_msgs = None
        obs_lag_vec = None

        # flat-arena lift (static, trace-time decision): one contiguous
        # [n_params] buffer per rank carries the gossip hot path; the
        # arena needs a single parameter dtype, and allreduce has no
        # gossip hot path to flatten
        spec = arena_lib.arena_spec(params) if arena else None
        use_arena = bool(
            spec is not None and spec.homogeneous and spec.n_leaves
            and algo in ("dpsgd", "eventgrad")  # the consuming algos
        )
        if staleness >= 2 and algo == "eventgrad" and not use_arena:
            # sp_eventgrad is exempt: its payload queues are tree state
            # (SparseState.pending), no arena flattening involved
            raise ValueError(
                f"staleness={staleness} (bounded-async) needs the "
                "flat-arena hot path, and this model's parameters are "
                "not arena-eligible (heterogeneous dtypes?) — use "
                "staleness<=1"
            )
        arena_bufs = None    # flat neighbor buffers for the flat mix/tail
        arena_pending = None # (cands, effs, lasts) awaiting the fused commit
        arena_fire_vec = None
        # carrier-resident: per-neighbor [L] dequant scales riding the
        # buffers above (int8 carrier only; None for f32/bf16 residency)
        use_carrier = carrier_wire is not None
        arena_buf_scales = None     # scales of the buffers the mix reads
        arena_pending_scales = None # (cand_scales, last_scales) for the tail
        # bucketed gossip schedule (static, trace-time): the leaf-aligned
        # segmentation the per-bucket pipeline below runs over
        buckets_eff = None
        if n_buckets > 1:
            if algo == "eventgrad":
                if not use_arena:
                    raise ValueError(
                        "bucketed=K needs the flat-arena hot path, and "
                        "this model's parameters are not arena-eligible "
                        "(heterogeneous dtypes?) — use bucketed=None"
                    )
                buckets_eff = spec.buckets(n_buckets)
            else:  # sp_eventgrad groups its per-leaf exchange
                buckets_eff = arena_lib.arena_spec(params).buckets(n_buckets)
        bucketed_mixed = None      # mixed pytree awaiting the optax tail
        bucketed_tail_done = False # per-bucket fused tail already applied
        wire_real_bucket = None    # f32 [K] per-bucket wire-real metric
        # the fused-tail decision is needed inside the event branch (the
        # buffer commit defers into the fused kernel); static either way
        use_fused = fused_sgd is not None and algo != "allreduce"
        if use_fused and not use_arena:
            # measured dispatch policy (ops/fused_tuning.py): the chip
            # capture showed the many-launch tree case losing to XLA's
            # fused chains (0.87x on the 86-leaf ResNet) — auto-demote to
            # the optax tail there; EG_FORCE_FUSED=1 overrides. The arena
            # is exempt: it hands the kernel ONE lane-aligned flat launch
            # (the measured ~1.0x best case), not 86.
            from eventgrad_tpu.ops.fused_tuning import tree_fused_ok

            use_fused = tree_fused_ok(trees.tree_num_leaves(params))

        bufs = ()
        if algo == "allreduce":
            # E1: average gradients over the data-parallel (gossip) axes
            # only — aux axes were pmean'd above and sharded (tp/ep) leaves
            # got their per-axis fix; a blanket all-axes pmean would
            # elementwise-average gradients of distinct parameter shards.
            # Gradients keep full precision (4 bytes/elem) regardless of
            # the gossip wire dtype.
            for ax in topo.gossip_axes:
                grads = lax.pmean(grads, ax)
            sent_bytes = jnp.float32(4.0 * n_params_static)
            # XLA owns the all-reduce schedule; report the logical
            # full-precision gradient volume as the wire number too
            wire_real = sent_bytes

        elif algo == "dpsgd":
            with _phase("exchange"):
                if use_arena:
                    arena_bufs = collectives.neighbor_vals_flat(
                        params, topo, spec, wire
                    )
                else:
                    bufs = collectives.neighbor_vals(params, topo, wire)
            if deliver is not None:
                # lossy D-PSGD has no stale buffer to fall back to: a
                # dropped edge leaves this pass's mix and the weight
                # renormalizes (mix_weighted below)
                health = chaos_monitor.update(health, deliver, ~deliver)

        elif algo == "eventgrad" and use_arena and buckets_eff is not None:
            # ---- bucketed gossip schedule (ISSUE 10) ----------------
            # The [L] trigger state machine stays GLOBAL — its per-leaf
            # ops are bucket-invariant and microscopic; the heavy chain
            # (gate -> pack -> wire -> ppermute -> commit -> mix) runs
            # per bucket, emitted software-pipelined: bucket k's
            # exchange is dispatched between bucket k-1's buffer commit
            # and its mix, with no dataflow edge forcing that order, so
            # the scheduler can overlap one bucket's transfer with
            # another's update math (the jaxpr interleaving gate in
            # analysis/walker.py proves the emission; tests/
            # test_bucketed.py proves bitwise parity with the
            # monolithic path).
            force_fire = (
                health.sync_req
                if (chaos is not None and chaos_policy.sync_after)
                else None
            )
            # partitioned trigger policies (micro/hybrid) contribute
            # (force, suppress) leaf masks through the same seams chaos
            # sync / quarantine already use; suppression wins (applied
            # after every force OR), the quarantine precedent
            pol_force, pol_suppress = pol.masks(spec, topo, pass_num, event_cfg)
            if pol_force is not None:
                force_fire = (
                    pol_force if force_fire is None
                    else (force_fire | pol_force)
                )
            with _phase("gate_pack"):
                prop = pol.propose(
                    params, event_state, pass_num, event_cfg,
                    force_fire=force_fire,
                )
                fire_raw = prop.fire_vec
                if quar is not None:
                    fire_raw = fire_raw & ~jnp.broadcast_to(
                        quar, fire_raw.shape
                    )
                if pol_suppress is not None:
                    fire_raw = fire_raw & ~pol_suppress
                leaves = spec.treedef.flatten_up_to(params)
                B = len(buckets_eff)
                caps = None
                pri = None
                if gossip_wire == "compact":
                    # per-bucket capacity split: element-proportional with
                    # per-bucket floors, exact total (split_capacity);
                    # admission and deferral re-contention are BUCKET-LOCAL
                    caps = collectives.split_capacity(
                        compact_capacity, buckets_eff
                    )
                    if event_cfg.max_silence > 0:
                        pri = prop.iter_diff >= event_cfg.max_silence
                    if force_fire is not None:
                        ff = jnp.broadcast_to(force_fire, fire_raw.shape)
                        pri = ff if pri is None else (pri | ff)
                fire_bs = []
                for b in buckets_eff:
                    fb = fire_raw[b.lo:b.hi]
                    if caps is not None:
                        pb = pri[b.lo:b.hi] if pri is not None else None
                        fb = capacity_gate(
                            fb, b.sizes, caps[b.index], priority=pb
                        )
                    fire_bs.append(fb)
                fire_vec = jnp.concatenate(fire_bs)
                event_state = pol.commit(
                    event_state, prop, fire_vec, event_cfg, n_nb
                )
                obs_prop, obs_fire_vec = prop, fire_vec
                if quar is not None or pol_suppress is not None:
                    obs_suppress = jnp.zeros_like(prop.fire_vec)
                    if quar is not None:
                        obs_suppress = obs_suppress | jnp.broadcast_to(
                            quar, prop.fire_vec.shape
                        )
                    if pol_suppress is not None:
                        obs_suppress = obs_suppress | pol_suppress
                arena_fire_vec = fire_vec
                scale_vec = (
                    collectives._masked_scales(
                        collectives._leaf_absmax(leaves), fire_vec
                    )
                    if wire == "int8" else None
                )
            lasts = event_state.bufs  # per-neighbor tuples of buckets
            # per-neighbor tuples of per-bucket [L_b] dequant scales
            # (carrier-resident int8 only; None otherwise)
            last_scales = event_state.buf_scales
            shipped = [None] * B      # (cands, effs, raws[, scales]) per bucket
            new_bufs_b = [None] * B   # per bucket: per-neighbor tuple
            new_scales_b = [None] * B # per bucket: per-neighbor [L_b] scales
            mixed_leaves = [None] * spec.n_leaves
            # bounded-async (staleness >= 2): the delivery queue's scalar
            # half — arrival clocks, late drain, per-slot (sent, late)
            # shift+merge — is bucket-invariant, so it runs ONCE here;
            # the array half (async_bucket_commit) is fused into each
            # per-bucket commit tail below, keeping the pipelined
            # ship/commit/mix emission the jaxpr interleaving gate pins
            q_plan = None
            pend_cands = pend_effs = pend_scales = None
            if staleness >= 2:
                lag_vec_e = chaos_inject.lag_vector(
                    chaos, topo, pass_num, bound=staleness
                )
                obs_lag_vec = lag_vec_e
                q_plan = async_delivery_plan(
                    event_state, deliver, lag_vec_e, pass_num, staleness
                )
                pend_cands = [
                    [[None] * B for _ in range(staleness)]
                    for _ in range(n_nb)
                ]
                pend_effs = [
                    [[None] * B for _ in range(staleness)]
                    for _ in range(n_nb)
                ]
                if last_scales is not None:
                    pend_scales = [
                        [[None] * B for _ in range(staleness)]
                        for _ in range(n_nb)
                    ]

            def _bflat(xs):
                if len(xs) == 1:
                    return xs[0].reshape(-1).astype(spec.dtype)
                return jnp.concatenate(
                    [x.reshape(-1).astype(spec.dtype) for x in xs]
                )

            def _ship(bi):
                b = buckets_eff[bi]
                lv = leaves[b.lo:b.hi]
                sv = (
                    scale_vec[b.lo:b.hi] if scale_vec is not None
                    else None
                )
                if caps is not None:
                    with _phase(f"gate_pack.b{bi}"):
                        packed, leaf_id = collectives._compact_pack(
                            _bflat(lv), fire_bs[bi], b.sizes,
                            b.starts_rel, caps[bi],
                        )
                    with _phase(f"exchange.b{bi}"):
                        shipped[bi] = (
                            collectives.compact_neighbor_vals_bucket(
                                packed, leaf_id, fire_bs[bi], topo, b,
                                caps[bi], spec.dtype, wire,
                                deliver=deliver, scale_vec=sv,
                                carrier=use_carrier,
                            )
                        )
                else:
                    with _phase(f"exchange.b{bi}"):
                        shipped[bi] = (
                            collectives.masked_neighbor_vals_bucket(
                                lv, fire_bs[bi], topo, b, spec.dtype,
                                wire, deliver=deliver, scale_vec=sv,
                                carrier=use_carrier,
                            )
                        )

            def _commit_bufs(bi):
                with _phase(f"commit_mix.b{bi}"):
                    b = buckets_eff[bi]
                    cands, effs, _raws = shipped[bi][:3]
                    last_b = tuple(lasts[i][bi] for i in range(n_nb))
                    if q_plan is not None:
                        # D >= 2: this pass's candidates enter the
                        # delivery queue; what commits into the bucket
                        # buffers is whatever queue slot 0 says ARRIVED
                        # this pass (commit-on-arrival). The scalar half
                        # (q_plan) is shared across buckets; only the
                        # [L_b] array half runs here, inside the same
                        # commit tail slot of the pipeline.
                        here_all = q_plan[0]
                        seg_b = b.seg_expand()
                        bufs_i, scales_i = [], []
                        for i in range(n_nb):
                            cs = (
                                shipped[bi][3][i]
                                if (last_scales is not None
                                    and shipped[bi][3] is not None)
                                else None
                            )
                            ls = (
                                last_scales[i][bi]
                                if last_scales is not None else None
                            )
                            buf_i, ncs, nes, nss, bs_i = (
                                async_bucket_commit(
                                    event_state.pending[i], here_all[i],
                                    cands[i], effs[i], last_b[i], seg_b,
                                    bucket=bi, cand_scale=cs,
                                    last_scale=ls,
                                )
                            )
                            bufs_i.append(buf_i)
                            if bs_i is not None:
                                scales_i.append(bs_i)
                            for r in range(staleness):
                                pend_cands[i][r][bi] = ncs[r]
                                pend_effs[i][r][bi] = nes[r]
                                if pend_scales is not None:
                                    pend_scales[i][r][bi] = nss[r]
                        new_bufs_b[bi] = tuple(bufs_i)
                        if scales_i:
                            new_scales_b[bi] = tuple(scales_i)
                        return
                    new_bufs_b[bi] = collectives.commit_bufs_flat(
                        cands, effs, last_b, b
                    )
                    if use_carrier and shipped[bi][3] is not None:
                        new_scales_b[bi] = collectives.commit_carrier_scales(
                            shipped[bi][3], effs,
                            tuple(last_scales[i][bi] for i in range(n_nb)),
                        )

            def _mix(bi, w, gate):
                # per-leaf slices of the bucket buffers feeding the
                # optax tail directly — the bucketed twin of
                # mix_flat_into_tree, same neighbor add order, bitwise
                # (int8 dequant products are exactly representable —
                # collectives._contract_safe — so FMA fusion into these
                # adds cannot change a bit on either SPMD lift); under
                # carrier residency each per-view slice dequantizes on
                # the fly with the leaf's scalar committed/stale scale
                with _phase(f"commit_mix.b{bi}"):
                    b = buckets_eff[bi]
                    # staleness == 1 mixes the pre-exchange buffers (the
                    # classic one-pass delay); D >= 2 mixes POST-arrival
                    # buffers — the queue's commit-on-arrival at lag 1
                    # already supplies exactly that one-pass delay, which
                    # is what makes D=2-at-baseline-lag ≡ D=1 bitwise
                    use_b = (
                        tuple(lasts[i][bi] for i in range(n_nb))
                        if staleness == 1 else new_bufs_b[bi]
                    )
                    use_s = None
                    if use_carrier and last_scales is not None:
                        use_s = (
                            tuple(last_scales[i][bi] for i in range(n_nb))
                            if staleness == 1 else new_scales_b[bi]
                        )
                    for j, k in enumerate(range(b.lo, b.hi)):
                        p = leaves[k]
                        acc = p
                        for i, buf in enumerate(use_b):
                            piece = lax.dynamic_slice_in_dim(
                                buf, b.starts_rel[j], b.sizes[j], 0
                            )
                            if use_carrier:
                                piece = piece.astype(p.dtype)
                                if use_s is not None:
                                    piece = piece * use_s[i][j].astype(
                                        p.dtype
                                    )
                            piece = piece.reshape(p.shape)
                            if gate is not None:
                                piece = jnp.where(
                                    gate[i], piece, jnp.zeros_like(piece)
                                )
                            acc = jnp.add(acc, piece)
                        mixed_leaves[k] = acc * w

            if use_fused:
                # per-bucket fused tail: commit + mix + SGD in one
                # kernel launch per bucket (measured-gated —
                # arena_tuning.bucketed_tail_ok; chaos is already
                # excluded from fused tails, so no gate plumbing here)
                lr_f, mom_f = fused_sgd
                g_leaves = spec.treedef.flatten_up_to(grads)
                t_leaves = (
                    spec.treedef.flatten_up_to(state.opt_state[0].trace)
                    if mom_f else None
                )
                p_new = [None] * spec.n_leaves
                t_new = [None] * spec.n_leaves
                kernel_ok = arena_tuning.mix_commit_ok()
                tail_fn = (
                    functools.partial(
                        fused_mix_commit, interpret=fused_interpret
                    )
                    if kernel_ok
                    else mix_commit_reference
                )
                carrier_tail_fn = (
                    functools.partial(
                        fused_mix_commit_carrier, interpret=fused_interpret
                    )
                    if kernel_ok
                    else mix_commit_carrier_reference
                )

                def _fused_tail(bi):
                    with _phase(f"commit_mix.b{bi}"):
                        b = buckets_eff[bi]
                        cands, effs, _raws = shipped[bi][:3]
                        seg_b = b.seg_expand()
                        keeps = tuple(e[seg_b] for e in effs)
                        last_b = tuple(lasts[i][bi] for i in range(n_nb))
                        flat_b = _bflat(leaves[b.lo:b.hi])
                        g_b = _bflat(g_leaves[b.lo:b.hi])
                        t_b = (
                            _bflat(t_leaves[b.lo:b.hi]) if mom_f
                            else jnp.zeros_like(flat_b)
                        )
                        if use_carrier:
                            # the bucket's carrier fused tail: scales
                            # commit outside the kernel ([L_b] select),
                            # the buffer reads stay in the wire dtype
                            mix_scales = None
                            if shipped[bi][3] is not None:
                                last_s = tuple(
                                    last_scales[i][bi] for i in range(n_nb)
                                )
                                new_scales_b[bi] = (
                                    collectives.commit_carrier_scales(
                                        shipped[bi][3], effs, last_s
                                    )
                                )
                                src = (
                                    last_s if staleness
                                    else new_scales_b[bi]
                                )
                                mix_scales = tuple(s[seg_b] for s in src)
                            p_b, t_b2, nb_b = carrier_tail_fn(
                                flat_b, cands, keeps, last_b, g_b, t_b,
                                float(lr_f), float(mom_f),
                                topo.mix_weight, mix_scales=mix_scales,
                                mix_stale=bool(staleness),
                            )
                        else:
                            p_b, t_b2, nb_b = tail_fn(
                                flat_b, cands, keeps, last_b, g_b, t_b,
                                float(lr_f), float(mom_f),
                                topo.mix_weight,
                                mix_stale=bool(staleness),
                            )
                        new_bufs_b[bi] = nb_b
                        for j, k in enumerate(range(b.lo, b.hi)):
                            sl = slice(
                                b.starts_rel[j],
                                b.starts_rel[j] + b.sizes[j],
                            )
                            p_new[k] = p_b[sl].reshape(leaves[k].shape)
                            if mom_f:
                                t_new[k] = t_b2[sl].reshape(
                                    t_leaves[k].shape
                                )

                _ship(0)
                for bi in range(1, B):
                    _fused_tail(bi - 1)
                    _ship(bi)
                _fused_tail(B - 1)
                params = jax.tree.unflatten(spec.treedef, p_new)
                if mom_f:
                    opt_state = (
                        state.opt_state[0]._replace(
                            trace=jax.tree.unflatten(spec.treedef, t_new)
                        ),
                    ) + tuple(state.opt_state[1:])
                else:
                    opt_state = state.opt_state
                bucketed_tail_done = True
            elif deliver is None:
                # the pipelined emission: ship(k) sits between
                # commit(k-1) and mix(k-1) in the trace — the
                # interleaving the jaxpr gate checks
                _ship(0)
                for bi in range(1, B):
                    _commit_bufs(bi - 1)
                    _ship(bi)
                    _mix(bi - 1, topo.mix_weight, None)
                _commit_bufs(B - 1)
                _mix(B - 1, topo.mix_weight, None)
            else:
                # chaos delivery masks ride per-bucket (the same
                # per-edge bit gates every bucket of an edge — a drop
                # drops the whole message, bitwise the monolithic
                # semantics); the health update reads every bucket's
                # raw fire-bit lanes, so ships are emitted first and
                # the commit/mix sweep follows the verdict
                for bi in range(B):
                    _ship(bi)
                sent_any = jnp.stack([
                    jnp.any(jnp.concatenate([
                        shipped[bi][2][i] for bi in range(B)
                    ]))
                    for i in range(n_nb)
                ])
                delivered = sent_any & deliver
                health = chaos_monitor.update(
                    health, delivered, sent_any & ~deliver
                )
                if chaos_policy.sync_after:
                    need = health.silence >= chaos_policy.sync_after
                    health = health.replace(
                        sync_req=chaos_monitor.sync_requests(need, topo)
                    )
                for bi in range(B):
                    _commit_bufs(bi)
                gate = alive_mask(health.silence, chaos_policy)
                if gate is None:
                    for bi in range(B):
                        _mix(bi, topo.mix_weight, None)
                else:
                    n_alive = jnp.sum(gate.astype(jnp.float32))
                    w_g = 1.0 / (1.0 + n_alive)
                    for bi in range(B):
                        _mix(bi, w_g, gate)
            if obs:
                # ledger census: the neighbor's raw wire bits, every
                # bucket of an edge concatenated (a leaf lives in
                # exactly one bucket, so the concat counts leaf-fire
                # messages exactly once)
                obs_n_msgs = collectives.raw_msg_counts([
                    jnp.concatenate([
                        shipped[bi][2][i] for bi in range(B)
                    ])
                    for i in range(n_nb)
                ])
            event_state = event_state.replace(bufs=tuple(
                tuple(new_bufs_b[bi][i] for bi in range(B))
                for i in range(n_nb)
            ))
            if use_carrier and last_scales is not None:
                event_state = event_state.replace(buf_scales=tuple(
                    tuple(new_scales_b[bi][i] for bi in range(B))
                    for i in range(n_nb)
                ))
            if q_plan is not None:
                # reassemble the per-bucket queue: every bucket's array
                # half (filled inside its commit tail) joins the shared
                # scalar stamps computed once up front
                _, sent_all, late_all, q_clock, q_late = q_plan
                new_pending = []
                for i in range(n_nb):
                    slots_i = []
                    for r in range(staleness):
                        slot = (
                            tuple(pend_cands[i][r][bi] for bi in range(B)),
                            tuple(pend_effs[i][r][bi] for bi in range(B)),
                            sent_all[i][r],
                            late_all[i][r],
                        )
                        if pend_scales is not None:
                            slot = slot + (tuple(
                                pend_scales[i][r][bi] for bi in range(B)
                            ),)
                        slots_i.append(slot)
                    new_pending.append(tuple(slots_i))
                event_state = event_state.replace(
                    pending=tuple(new_pending),
                    edge_clock=q_clock,
                    late_commits=event_state.late_commits + q_late,
                )
                edge_stale = jnp.asarray(pass_num, jnp.int32) - q_clock
                late_now = q_late
            if not bucketed_tail_done:
                bucketed_mixed = jax.tree.unflatten(
                    spec.treedef, mixed_leaves
                )
            fired_elems, fired_leaves = _fired_accounting(
                fire_vec, spec.sizes
            )
            sent_bytes = jnp.float32(n_nb) * (
                val_bytes * fired_elems + scale_bytes_per_leaf * fired_leaves
            )
            fired_frac = fired_leaves / spec.n_leaves
            per_bucket = collectives.bucketed_wire_real_bytes_per_neighbor(
                buckets_eff, wire, caps
            )
            # same expression shape as the monolithic branch
            # (f32(n_nb) * python-float) so the f32 roundings agree and
            # the metric stays bitwise across schedules
            wire_real = jnp.float32(n_nb) * float(sum(per_bucket))
            wire_real_bucket = jnp.float32(n_nb) * jnp.asarray(
                per_bucket, jnp.float32
            )

        elif algo == "eventgrad" and use_arena:
            force_fire = (
                health.sync_req
                if (chaos is not None and chaos_policy.sync_after)
                else None
            )
            # partitioned policy masks ride the fused engine's existing
            # force/suppress seams (suppression is applied after force
            # ORs in — event_engine.event_propose_pack — so it wins)
            pol_force, pol_suppress = pol.masks(spec, topo, pass_num, event_cfg)
            if pol_force is not None:
                force_fire = (
                    pol_force if force_fire is None
                    else (force_fire | pol_force)
                )
            suppress = quar
            if pol_suppress is not None:
                suppress = (
                    pol_suppress if suppress is None
                    else (suppress | pol_suppress)
                )
            # ONE fused sender pass: trigger -> gate -> pack
            # (ops/event_engine.py), replacing the tree path's flatten /
            # propose / capacity_gate / _compact_pack chain below
            with _phase("gate_pack"):
                prop, fire_vec, packed, leaf_id = (
                    event_engine.event_propose_pack(
                        params, event_state, pass_num, event_cfg, spec,
                        capacity=(
                            compact_capacity if gossip_wire == "compact"
                            else None
                        ),
                        force_fire=force_fire,
                        # quarantine / non-owned partition: send nothing
                        suppress_fire=suppress,
                    )
                )
                event_state = pol.commit(
                    event_state, prop, fire_vec, event_cfg, n_nb
                )
            obs_prop, obs_fire_vec = prop, fire_vec
            obs_suppress = suppress
            arena_fire_vec = fire_vec
            if gossip_wire == "compact":
                with _phase("exchange"):
                    res = collectives.compact_neighbor_vals_flat(
                        params, fire_vec, packed, leaf_id, topo,
                        compact_capacity, spec, wire, deliver=deliver,
                        checksum=integ_checksum, finite=integ_quar,
                        corrupt=corrupt_fn, carrier=use_carrier,
                    )
                wire_real = jnp.float32(n_nb) * (
                    collectives.wire_real_bytes_per_neighbor(
                        n_params_static, n_leaves_static, wire,
                        compact_capacity=compact_capacity, fire_bits=True,
                    )
                )
            else:
                # the Pallas masked-wire builder runs only where the
                # chip measured a win (ops/arena_tuning.py, written by
                # bench_kernels.py arena); the inline fused-concat form
                # is bitwise and is what every other backend runs
                wb = None
                if not fused_interpret and arena_tuning.masked_wire_ok():
                    wb = lambda f, fe, se: event_engine.masked_wire(
                        f, fe, se, interpret=False
                    )
                with _phase("exchange"):
                    res = collectives.masked_neighbor_vals_flat(
                        params, fire_vec, topo, spec, wire,
                        deliver=deliver, wire_builder=wb,
                        checksum=integ_checksum, finite=integ_quar,
                        corrupt=corrupt_fn, carrier=use_carrier,
                    )
                wire_real = jnp.float32(n_nb) * (
                    collectives.wire_real_bytes_per_neighbor(
                        n_params_static, n_leaves_static, wire,
                        fire_bits=True,
                    )
                )
            cand_scales = None
            if use_carrier:
                # carrier contract: candidates stay in the wire dtype,
                # plus the received per-leaf dequant scales (int8 only)
                cands, effs, raws, cand_scales = res
            elif integ_wire:
                cands, effs, raws, oks = res
            else:
                cands, effs, raws = res
            if obs:
                obs_n_msgs = collectives.raw_msg_counts(raws)
            if deliver is not None:
                # raws are the RAW sender bits (what was on the wire); a
                # rejected payload is NOT a delivery — its silence keeps
                # growing, so persistent corruption escalates through
                # the existing sync/freeze policies
                sent_any = jnp.stack([jnp.any(rv) for rv in raws])
                delivered = sent_any & deliver
                if oks is not None:
                    delivered = delivered & oks
                health = chaos_monitor.update(
                    health, delivered, sent_any & ~deliver
                )
                if chaos_policy.sync_after:
                    need = health.silence >= chaos_policy.sync_after
                    health = health.replace(
                        sync_req=chaos_monitor.sync_requests(need, topo)
                    )
            lasts = event_state.bufs
            last_scales = event_state.buf_scales
            if use_fused:
                # receive-commit fuses into the mix+SGD kernel below
                # (fused_mix_commit): the stale buffers are read once
                arena_pending = (cands, effs, lasts)
                if use_carrier:
                    arena_pending_scales = (cand_scales, last_scales)
            elif staleness >= 2:
                # bounded-async engine: this pass's candidates enter the
                # per-edge delivery queues at their scheduled lag
                # (chaos lag=/slow= clauses, clamped to the bound D);
                # whatever arrives this pass commits, and the mix reads
                # the post-arrival buffers — a late delivery is bitwise
                # a fire deferred to its arrival pass
                with _phase("commit_mix"):
                    lag_vec_e = chaos_inject.lag_vector(
                        chaos, topo, pass_num, bound=staleness
                    )
                    obs_lag_vec = lag_vec_e
                    delivered_bits = deliver
                    if oks is not None:
                        delivered_bits = (
                            oks if delivered_bits is None
                            else delivered_bits & oks
                        )
                    event_state, arena_bufs, edge_stale, late_now = (
                        async_delivery_commit(
                            event_state, cands, effs, delivered_bits,
                            lag_vec_e, pass_num, spec, staleness,
                            cand_scales=cand_scales,
                        )
                    )
                    # carrier: the queue committed scales alongside their
                    # payloads — the mix dequantizes post-arrival buffers
                    # through post-arrival scales
                    arena_buf_scales = event_state.buf_scales
            else:
                with _phase("commit_mix"):
                    # dtype-agnostic wide select: carriers commit through
                    # the same where() as f32 buffers; a fired leaf also
                    # adopts its candidate's dequant scale
                    new_bufs = collectives.commit_bufs_flat(
                        cands, effs, lasts, spec
                    )
                    new_scales = last_scales
                    if cand_scales is not None:
                        new_scales = collectives.commit_carrier_scales(
                            cand_scales, effs, last_scales
                        )
                # staleness=1: mix with what had arrived as of the
                # PREVIOUS step; this step's exchange lands for the next
                arena_bufs = lasts if staleness else new_bufs
                arena_buf_scales = last_scales if staleness else new_scales
                event_state = event_state.replace(
                    bufs=new_bufs, buf_scales=new_scales
                )
            fired_elems, fired_leaves = _fired_accounting(
                fire_vec, spec.sizes
            )
            sent_bytes = jnp.float32(n_nb) * (
                val_bytes * fired_elems + scale_bytes_per_leaf * fired_leaves
            )
            fired_frac = fired_leaves / spec.n_leaves

        elif algo == "eventgrad":
            force_fire = (
                health.sync_req
                if (chaos is not None and chaos_policy.sync_after)
                else None
            )
            p_leaves, p_def = jax.tree.flatten(params)
            # the tree path has no arena, but partition geometry only
            # needs the cached leaf layout — same masks as the arena twin
            pol_force, pol_suppress = pol.masks(
                arena_lib.arena_spec(params), topo, pass_num, event_cfg
            )
            if pol_force is not None:
                force_fire = (
                    pol_force if force_fire is None
                    else (force_fire | pol_force)
                )
            with _phase("gate_pack"):
                prop = pol.propose(
                    params, event_state, pass_num, event_cfg,
                    force_fire=force_fire,
                )
                fire_vec = prop.fire_vec
                if quar is not None:
                    # quarantine: send nothing this pass (suppression wins
                    # over force_fire — never answer a sync request with
                    # poisoned values); suppressed leaves re-contend next
                    # pass like a capacity deferral
                    fire_vec = fire_vec & ~quar
                if pol_suppress is not None:
                    fire_vec = fire_vec & ~pol_suppress
                if gossip_wire == "compact":
                    # wire-budget admission: overdue leaves (max_silence)
                    # and chaos forced syncs claim capacity first; the
                    # overflow is deferred — commit() below rolls its
                    # state back so it re-contends next pass
                    leaf_sizes = tuple(int(l.size) for l in p_leaves)
                    pri = None
                    if event_cfg.max_silence > 0:
                        pri = prop.iter_diff >= event_cfg.max_silence
                    if force_fire is not None:
                        ff = jnp.broadcast_to(force_fire, fire_vec.shape)
                        pri = ff if pri is None else (pri | ff)
                    fire_vec = capacity_gate(
                        fire_vec, leaf_sizes, compact_capacity,
                        priority=pri,
                    )
                event_state = pol.commit(
                    event_state, prop, fire_vec, event_cfg, n_nb
                )
            obs_prop, obs_fire_vec = prop, fire_vec
            if quar is not None or pol_suppress is not None:
                obs_suppress = jnp.zeros_like(prop.fire_vec)
                if quar is not None:
                    obs_suppress = obs_suppress | jnp.broadcast_to(
                        quar, prop.fire_vec.shape
                    )
                if pol_suppress is not None:
                    obs_suppress = obs_suppress | pol_suppress
            fire = jax.tree.unflatten(
                p_def, [fire_vec[i] for i in range(len(p_leaves))]
            )
            if gossip_wire == "compact":
                with _phase("exchange"):
                    res = collectives.compact_neighbor_vals(
                        params, fire, event_state.bufs, topo,
                        compact_capacity, wire, deliver=deliver,
                        checksum=integ_checksum, finite=integ_quar,
                        corrupt=corrupt_fn,
                    )
                wire_real = jnp.float32(n_nb) * (
                    collectives.wire_real_bytes_per_neighbor(
                        n_params_static, n_leaves_static, wire,
                        compact_capacity=compact_capacity, fire_bits=True,
                    )
                )
            else:
                with _phase("exchange"):
                    res = collectives.masked_neighbor_vals(
                        params, fire, event_state.bufs, topo, wire,
                        deliver=deliver,
                        checksum=integ_checksum, finite=integ_quar,
                        corrupt=corrupt_fn,
                    )
                wire_real = jnp.float32(n_nb) * (
                    collectives.wire_real_bytes_per_neighbor(
                        n_params_static, n_leaves_static, wire,
                        fire_bits=True,
                    )
                )
            if integ_wire:
                new_bufs, recv_fires, oks = res
            else:
                new_bufs, recv_fires = res
            if obs:
                obs_n_msgs = collectives.raw_msg_counts(recv_fires)
            if deliver is not None:
                # recv_fires are the RAW sender bits: sent & delivered
                # resets silence, sent & ~delivered is an observed
                # injected drop, ~sent is legitimate event quiet — and a
                # REJECTED payload is not a delivery (silence grows, so
                # persistent corruption escalates via sync/freeze)
                sent_any = jnp.stack([
                    jnp.any(jnp.stack(jax.tree.leaves(rf)))
                    for rf in recv_fires
                ])
                delivered = sent_any & deliver
                if oks is not None:
                    delivered = delivered & oks
                health = chaos_monitor.update(
                    health, delivered, sent_any & ~deliver
                )
                if chaos_policy.sync_after:
                    need = health.silence >= chaos_policy.sync_after
                    health = health.replace(
                        sync_req=chaos_monitor.sync_requests(need, topo)
                    )
            # staleness=1: mix with what had arrived as of the PREVIOUS
            # step; this step's exchange lands for the next one
            bufs = event_state.bufs if staleness else new_bufs
            event_state = event_state.replace(bufs=new_bufs)
            fired_elems, fired_leaves = _fired_accounting(
                fire_vec, tuple(int(l.size) for l in p_leaves)
            )
            sent_bytes = jnp.float32(n_nb) * (
                val_bytes * fired_elems + scale_bytes_per_leaf * fired_leaves
            )
            fired_frac = fired_leaves / len(p_leaves)

        elif algo == "sp_eventgrad":
            # the topk TriggerPolicy's propose/commit delegates — the
            # same norm-delta trigger state machine, with the proposal
            # feeding the telemetry accumulators. (The arena lift leaves
            # sp alone: its top-k scatter replicas are tree-shaped
            # state, and the trigger already reads leaves leaf-parallel.)
            with _phase("gate_pack"):
                prop = pol.propose(params, event_state, pass_num, event_cfg)
                event_state = pol.commit(
                    event_state, prop, prop.fire_vec, event_cfg, n_nb
                )
            p_leaves, p_def = jax.tree.flatten(params)
            fire = jax.tree.unflatten(
                p_def, [prop.fire_vec[i] for i in range(len(p_leaves))]
            )
            obs_prop, obs_fire_vec = prop, prop.fire_vec
            if obs:
                # sp ships no raw fire bits on the wire (the top-k lanes
                # are masked on receipt), so the ledger's receiver census
                # is the neighbor's fired-leaf count itself: one scalar
                # ppermute per edge. sp supports neither chaos nor
                # integrity, so every censused message is a delivery.
                _sp_cnt = jnp.sum(prop.fire_vec.astype(jnp.int32))
                obs_n_msgs = jnp.stack([
                    collectives.recv_from(_sp_cnt, topo, nb)
                    for nb in topo.neighbors
                ]).astype(jnp.int32)
            stale_replicas = sparse_state.replicas
            with _phase("exchange"):
                sparse_state = sparse_exchange(
                    params, fire, sparse_state, topo, sparse_cfg, wire,
                    buckets=buckets_eff, staleness=staleness,
                )
            # staleness == 1 mixes the pre-exchange replicas; D >= 2
            # mixes POST-exchange replicas, whose newest content is the
            # queue's slot-0 commit (payloads from passes <= p-1) — the
            # same one-pass delay, which is the D=2 ≡ D=1 bitwise pin
            bufs = (
                stale_replicas if staleness == 1 else sparse_state.replicas
            )
            if staleness >= 2 and obs:
                # sp composes with D >= 2 but never with chaos lag, so
                # the ledger's queue twin sees every message at lag 1
                obs_lag_vec = jnp.ones((n_nb,), jnp.int32)
            ks = tuple(
                sparse_cfg.k_for(p.size) for p in jax.tree.leaves(params)
            )
            # values + int32 indices per selected element per neighbor
            fired_elems, fired_leaves = _fired_accounting(prop.fire_vec, ks)
            sent_bytes = jnp.float32(n_nb) * (
                (val_bytes + 4.0) * fired_elems
                + scale_bytes_per_leaf * fired_leaves
            )
            fired_frac = fired_leaves / len(ks)
            # the top-k lanes physically ship every pass (masked on
            # receipt): k values + k int32 indices per leaf per neighbor,
            # plus the fire bits (and int8 scales)
            k_total = sum(sparse_cfg.k_for(p.size) for p in jax.tree.leaves(params))
            wire_real = jnp.float32(n_nb) * (
                (val_bytes + 4.0) * k_total
                + 1.0 * n_leaves_static
                + scale_bytes_per_leaf * n_leaves_static
            )
            if buckets_eff is not None:
                # per-bucket split of the same formula (k lanes + fire
                # bits + int8 scales group by leaf, so the bucket sums
                # reproduce the total exactly)
                per_bucket = []
                for b in buckets_eff:
                    k_b = sum(ks[b.lo:b.hi])
                    per_bucket.append(
                        (val_bytes + 4.0) * k_b
                        + 1.0 * b.n_leaves
                        + scale_bytes_per_leaf * b.n_leaves
                    )
                wire_real_bucket = jnp.float32(n_nb) * jnp.asarray(
                    per_bucket, jnp.float32
                )

        # the whole receive-commit / mix / optimizer tail is ONE
        # cost-model phase (obs/costmodel.py "commit_mix"); the
        # bucketed schedule annotated its per-bucket twins above
        with _phase("commit_mix"):
            if bucketed_tail_done:
                # bucketed fused tail: params/opt_state already updated per
                # bucket inside the pipelined schedule above
                pass
            elif bucketed_mixed is not None:
                # bucketed mix emitted per bucket above; the optimizer tail
                # stays the monolithic optax call on the assembled mixed
                # pytree — bitwise the arena tail (same values, same order)
                updates, opt_state = tx.update(
                    grads, state.opt_state, bucketed_mixed
                )
                updates, opt_state = _poison(updates), _poison(opt_state)
                params = optax.apply_updates(bucketed_mixed, updates)
            elif use_fused and (arena_pending is not None or arena_bufs is not None):
                # arena fused tail: buffer commit + mix + momentum-SGD in one
                # flat pass (ops/arena_update.fused_mix_commit); dpsgd has no
                # commit, so it rides fused_mix_sgd on the single flat leaf
                lr_f, mom_f = fused_sgd
                flat = spec.ravel(params)
                g_flat = spec.ravel(grads)
                if mom_f:
                    t_flat = spec.ravel(state.opt_state[0].trace)
                else:
                    t_flat = jnp.zeros_like(flat)
                if arena_pending is not None:
                    cands, effs, lasts = arena_pending
                    seg = spec.seg_expand()  # [n] keeps for the kernel only
                    keeps = tuple(e[seg] for e in effs)
                    if use_carrier:
                        # carrier fused tail: the kernel's buffer reads
                        # stay in the wire dtype; the scales commit
                        # outside (an [L]-sized select, not an HBM pass)
                        # and ride in per-position for the mix dequant
                        cand_scales, last_scales = arena_pending_scales
                        new_scales = last_scales
                        mix_scales = None
                        if cand_scales is not None:
                            new_scales = collectives.commit_carrier_scales(
                                cand_scales, effs, last_scales
                            )
                            src = last_scales if staleness else new_scales
                            mix_scales = tuple(s[seg] for s in src)
                        tail_fn = (
                            functools.partial(
                                fused_mix_commit_carrier,
                                interpret=fused_interpret,
                            )
                            if arena_tuning.mix_commit_ok()
                            else mix_commit_carrier_reference
                        )
                        p_flat, new_t_flat, new_bufs = tail_fn(
                            flat, cands, keeps, lasts, g_flat, t_flat,
                            float(lr_f), float(mom_f), topo.mix_weight,
                            mix_scales=mix_scales,
                            mix_stale=bool(staleness),
                        )
                        event_state = event_state.replace(
                            bufs=new_bufs, buf_scales=new_scales
                        )
                    else:
                        tail_fn = (
                            functools.partial(
                                fused_mix_commit, interpret=fused_interpret
                            )
                            if arena_tuning.mix_commit_ok()
                            else mix_commit_reference
                        )
                        p_flat, new_t_flat, new_bufs = tail_fn(
                            flat, cands, keeps, lasts, g_flat, t_flat,
                            float(lr_f), float(mom_f), topo.mix_weight,
                            mix_stale=bool(staleness),
                        )
                        event_state = event_state.replace(bufs=new_bufs)
                else:
                    buf_sum = jnp.zeros_like(flat)
                    for b in arena_bufs:
                        buf_sum = jnp.add(buf_sum, b)
                    p_flat, new_t_flat = fused_mix_sgd(
                        flat, buf_sum, g_flat, t_flat, lr_f, mom_f,
                        topo.mix_weight, interpret=fused_interpret,
                    )
                params = spec.unravel(p_flat)
                if mom_f:
                    opt_state = (
                        state.opt_state[0]._replace(
                            trace=spec.unravel(new_t_flat)
                        ),
                    ) + tuple(state.opt_state[1:])
                else:
                    opt_state = state.opt_state
            elif use_fused:
                # Pallas fused tail: mix + momentum-SGD in one HBM pass.
                lr_f, mom_f = fused_sgd
                buf_sum = trees.tree_zeros_like(params)
                for buf in bufs:
                    buf_sum = jax.tree.map(jnp.add, buf_sum, buf)
                if mom_f:
                    mom_trace = state.opt_state[0].trace
                else:
                    mom_trace = trees.tree_zeros_like(params)
                params, new_trace = fused_mix_sgd(
                    params, buf_sum, grads, mom_trace,
                    lr_f, mom_f, topo.mix_weight, interpret=fused_interpret,
                )
                if mom_f:
                    opt_state = (state.opt_state[0]._replace(trace=new_trace),) + tuple(
                        state.opt_state[1:]
                    )
                else:
                    opt_state = state.opt_state
            elif arena_bufs is not None:
                # arena mix + SGD tail: the mix reads the FLAT neighbor
                # buffers through per-leaf slices and emits the mixed pytree
                # directly (mix_flat_into_tree) — each leaf is an
                # independent fusion feeding the optax tail, bitwise the
                # tree mix, with no assembled intermediate on the critical
                # path. Chaos gate semantics identical to the tree branch.
                gate = None
                if deliver is not None and arena_bufs:
                    alive = alive_mask(health.silence, chaos_policy)
                    if algo == "dpsgd":
                        gate = deliver if alive is None else deliver & alive
                    elif alive is not None:
                        gate = alive
                if arena_bufs and use_carrier:
                    # per-view dequant: slice the carrier, upcast,
                    # multiply by the leaf's scalar scale — bitwise the
                    # f32-resident mix (the f32 buffer held exactly
                    # dequant(carrier))
                    mixed = collectives.mix_carrier_flat_into_tree(
                        params, arena_bufs, arena_buf_scales, spec, topo,
                        gate=gate,
                    )
                elif arena_bufs:
                    mixed = collectives.mix_flat_into_tree(
                        params, arena_bufs, spec, topo, gate=gate
                    )
                else:
                    mixed = params
                updates, opt_state = tx.update(grads, state.opt_state, mixed)
                updates, opt_state = _poison(updates), _poison(opt_state)
                params = optax.apply_updates(mixed, updates)
            else:
                # chaos edge gating of the mix: dpsgd drops leave this pass's
                # average (no stale buffer exists); a frozen edge (silence >=
                # freeze_after) leaves it for either algorithm. Weights
                # renormalize to 1/(1 + n_live) — with every gate on,
                # mix_weighted is bitwise mix (the drop-rate-0 guarantee).
                gate = None
                if deliver is not None and bufs:
                    alive = alive_mask(health.silence, chaos_policy)
                    if algo == "dpsgd":
                        gate = deliver if alive is None else deliver & alive
                    elif alive is not None:
                        gate = alive
                if gate is not None:
                    mixed = collectives.mix_weighted(params, bufs, gate)
                else:
                    mixed = collectives.mix(params, bufs, topo) if bufs else params
                # optimizer applies gradients (computed at pre-mix params) to the
                # mixed parameters — exact D-PSGD ordering (decent.cpp:232-246).
                updates, opt_state = tx.update(grads, state.opt_state, mixed)
                updates, opt_state = _poison(updates), _poison(opt_state)
                params = optax.apply_updates(mixed, updates)

        quar_eff = None
        if integ_quar:
            # quarantine tail: the rank skips its gradient update (it
            # keeps the gossip mix — healthy neighbors are the recovery
            # path) and freezes its optimizer/BN state for the pass; a
            # non-finite post-update parameter set (lr blowup — the
            # fault the grad guard can't see) rolls the whole rank back
            # to its pre-step state. Gates that never trip select the
            # same values, so a fault-free trajectory is bitwise
            # unchanged (tests/test_integrity.py).
            params = jax.tree.map(
                lambda m, p: jnp.where(quar, m, p), mixed, params
            )
            opt_state = jax.tree.map(
                lambda o, n: jnp.where(quar, o, n),
                state.opt_state, opt_state,
            )
            new_stats = jax.tree.map(
                lambda o, n: jnp.where(quar, o, n),
                state.batch_stats, new_stats,
            )
            params_ok = jnp.all(jnp.stack(
                [jnp.all(jnp.isfinite(p)) for p in jax.tree.leaves(params)]
            ))
            params = jax.tree.map(
                lambda old, n: jnp.where(params_ok, n, old),
                state.params, params,
            )
            opt_state = jax.tree.map(
                lambda old, n: jnp.where(params_ok, n, old),
                state.opt_state, opt_state,
            )
            quar_eff = quar | ~params_ok

        if sync_bn and has_bn:
            new_stats = collectives.allreduce_mean(new_stats, topo)

        telemetry = state.telemetry
        if obs:
            # per-edge wire-real bytes: the gossip exchange ships the same
            # payload to every neighbor, so the split is uniform today —
            # the [n_nb] vector is the schema's shape, not a claim that
            # it must stay uniform (allreduce has no edges to attribute)
            per_edge = (
                jnp.broadcast_to(wire_real / n_nb, (n_nb,))
                if algo != "allreduce" and n_nb
                else None
            )
            # per-bucket wire bytes ride the telemetry under the
            # bucketed schedule; the monolithic path is the one-bucket
            # degenerate ([1] vector), so the field's sum always equals
            # the edge_bytes total. Gated like per_edge: allreduce has
            # no gossip wire to attribute (docs/OBSERVABILITY.md)
            per_bucket_tel = None
            if algo != "allreduce" and n_nb:
                per_bucket_tel = (
                    wire_real_bucket if wire_real_bucket is not None
                    else jnp.reshape(wire_real, (1,))
                )
            if obs_prop is not None:
                # message-lifecycle ledger inputs: every disposition is
                # derived inside obs.ledger.ledger_update from the
                # branch's raw observables — no counter arithmetic here
                # (analysis/lint.py telemetry-counter-ledgered)
                ledger_inputs = None
                if obs_n_msgs is not None:
                    ledger_inputs = dict(
                        prop_fire=obs_prop.fire_vec,
                        suppress=obs_suppress,
                        fire_vec=obs_fire_vec,
                        n_msgs=obs_n_msgs,
                        deliver=deliver,
                        oks=oks,
                        lag_vec=obs_lag_vec,
                    )
                telemetry = obs_device.accumulate(
                    telemetry,
                    fire_vec=obs_fire_vec,
                    defer_vec=obs_prop.fire_vec & ~obs_fire_vec,
                    thres=obs_prop.thres,
                    drift=obs_prop.value_diff,
                    silence=obs_prop.iter_diff,
                    fired_elems=fired_elems,
                    edge_bytes=per_edge,
                    bucket_bytes=per_bucket_tel,
                    wire_reject=(~oks if oks is not None else None),
                    quarantined=quar_eff,
                    edge_staleness=edge_stale,
                    late_commits=late_now,
                    ledger_inputs=ledger_inputs,
                )
            else:
                # dense gossip (dpsgd) still moves messages: every leaf
                # proposes and fires every pass, and chaos drops are the
                # only non-delivery (no integrity, no deferral). The
                # ledger sees the same taxonomy with degenerate inputs.
                ledger_inputs = None
                if algo == "dpsgd" and n_nb:
                    ones_l = jnp.ones((n_leaves_static,), bool)
                    ledger_inputs = dict(
                        prop_fire=ones_l,
                        fire_vec=ones_l,
                        n_msgs=jnp.full(
                            (n_nb,), n_leaves_static, jnp.int32
                        ),
                        deliver=deliver,
                    )
                telemetry = obs_device.accumulate(
                    telemetry, edge_bytes=per_edge,
                    bucket_bytes=per_bucket_tel,
                    ledger_inputs=ledger_inputs,
                )

        new_state = state.replace(
            params=params,
            opt_state=opt_state,
            batch_stats=new_stats,
            pass_num=pass_num,
            rng=rng,
            event=event_state,
            sparse=sparse_state,
            chaos=health,
            telemetry=telemetry,
        )
        metrics = {
            "loss": loss,
            "correct": jnp.sum(jnp.argmax(out, axis=-1) == y).astype(jnp.int32),
            "fired_frac": fired_frac,
            "sent_bytes": sent_bytes,
            # fired payload elements this step (autotune input for the
            # compact wire) and the bytes the collective ACTUALLY moved
            "fired_elems": jnp.asarray(fired_elems, jnp.float32),
            "sent_bytes_wire_real": jnp.asarray(wire_real, jnp.float32),
            "num_events": (
                event_state.num_events if event_state is not None else jnp.int32(0)
            ),
            "num_deferred": (
                event_state.num_deferred
                if event_state is not None else jnp.int32(0)
            ),
        }
        if wire_real_bucket is not None:
            # per-bucket wire truth of the bucketed schedule — static
            # per step (the sum is sent_bytes_wire_real exactly)
            metrics["sent_bytes_wire_real_per_bucket"] = wire_real_bucket
        if edge_stale is not None:
            # bounded-async failure surface: how stale each edge's view
            # is (passes since the newest committed delivery was sent)
            # and the cumulative late (lag >= 2) commits
            metrics["edge_staleness"] = edge_stale  # int32 [n_nb]
            metrics["late_commits"] = event_state.late_commits
        if chaos is not None:
            metrics["edge_silence"] = health.silence  # int32 [n_nb]
            metrics["chaos_drops"] = health.drops  # cumulative int32
        if integrity is not None:
            # per-step integrity verdicts (the loop sums them into the
            # epoch records and the sentinel/artifact accounting)
            metrics["integrity_wire_reject"] = (
                (~oks).astype(jnp.int32) if oks is not None
                else jnp.zeros((n_nb,), jnp.int32)
            )
            metrics["integrity_quarantined"] = (
                quar_eff.astype(jnp.int32) if quar_eff is not None
                else jnp.int32(0)
            )
        if trace and algo in ("eventgrad", "sp_eventgrad"):
            # send{r}.txt columns: norm of the (pre-mix) param at the event
            # check, the post-decay/post-fire threshold, and the fire bit
            metrics["trace_norm"] = jnp.stack(
                jax.tree.leaves(trees.tree_norm(state.params))
            )
            metrics["trace_thres"] = event_state.thres  # already [L]-vector
            metrics["trace_fired"] = (
                arena_fire_vec.astype(jnp.float32)
                if arena_fire_vec is not None
                else jnp.stack(
                    [f.astype(jnp.float32) for f in jax.tree.leaves(fire)]
                )
            )
        return new_state, metrics

    return step
