"""Multi-host (multi-process) runtime: the DCN story.

The reference scales across nodes with `mpirun` + MPI over the cluster
interconnect (/root/reference/dcifar10/README.md:9). Here multi-host is
JAX's global-mesh model: every process calls `init()` (a thin wrapper over
`jax.distributed.initialize`), after which `jax.devices()` is the GLOBAL
device list, `parallel.spmd.build_mesh` spans hosts, and the same per-rank
programs run unchanged — XLA routes collectives over ICI within a host and
DCN (or Gloo on CPU) between hosts. Verified end-to-end by
`tests/test_multihost.py`, which trains EventGraD over a 2-process × 4-CPU
mesh and checks bit-parity with the single-process simulation.

Host-side helpers cover the two things that differ in multi-process mode:
arrays must be *placed* as global jax.Arrays (`put_stacked`), and reading
a sharded array back on the host needs an allgather (`to_host`).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from eventgrad_tpu.parallel.spmd import stacked_spec
from eventgrad_tpu.parallel.topology import Topology


def init(coordinator: str, num_processes: int, process_id: int) -> None:
    """Join the global runtime (MPI_Init's role). Call before any device
    computation, with the same coordinator on every process."""
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def init_from_env() -> bool:
    """Join a multi-process mesh from the environment (mpirun's
    env-propagation role): EG_COORDINATOR=host:port plus
    EG_NUM_PROCESSES / EG_PROCESS_ID. Returns True when a coordinator
    was configured (and the runtime joined), False when unset — callers
    (cli.py, drivers) call this unconditionally before any device
    computation. Missing count/id with a set coordinator raise rather
    than silently running single-process."""
    import os

    coordinator = os.environ.get("EG_COORDINATOR")
    if not coordinator:
        return False
    try:
        num = int(os.environ["EG_NUM_PROCESSES"])
        pid = int(os.environ["EG_PROCESS_ID"])
    except KeyError as e:
        raise RuntimeError(
            f"EG_COORDINATOR={coordinator!r} is set but {e.args[0]} is "
            "not — a multi-process mesh needs all three of "
            "EG_COORDINATOR / EG_NUM_PROCESSES / EG_PROCESS_ID"
        ) from None
    init(coordinator, num, pid)
    return True


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def put_stacked(tree: Any, mesh: Mesh, topo: Topology) -> Any:
    """Place a host pytree (every leaf stacked [n_ranks, ...]) as global
    arrays sharded over the mesh. Every process must call this with the
    same values (deterministic seeding guarantees it)."""
    sharding = NamedSharding(mesh, stacked_spec(topo))
    return jax.device_put(tree, sharding)


def to_host(tree: Any) -> Any:
    """Fetch a (possibly non-fully-addressable) pytree to host numpy,
    allgathering across processes when needed. Fully-addressable leaves are
    read directly — allgathering those would concatenate each process's
    identical copy along axis 0 (process_allgather's contract for local
    arrays), silently doubling them."""
    if is_multiprocess():
        from jax.experimental import multihost_utils

        def fetch(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                return np.asarray(multihost_utils.process_allgather(x, tiled=True))
            return np.asarray(x)

        return jax.tree.map(fetch, tree)
    return jax.tree.map(np.asarray, tree)


def is_primary() -> bool:
    """True on the process that should own logging / file output."""
    return jax.process_index() == 0


def barrier(name: str) -> None:
    """Block until every process reaches this point (no-op single-process)."""
    if is_multiprocess():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
