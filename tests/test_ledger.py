"""Message-lifecycle ledger (ISSUE 18): conservation across the algo ×
wire × staleness × chaos × integrity matrix, the seeded leak oracles,
the Prometheus export-coverage partition, the perf-ledger tolerant
renderer, and the conservation tool's --fast smoke.

The load-bearing claim: every message the training step touches lands
in EXACTLY one disposition (obs/schema.py DISPOSITIONS), so the
integer balance laws

    proposed = suppressed + deferred + fired              (sender)
    fired    = delivered + dropped + rejected + in_flight (receiver)

hold bitwise-exactly per edge per flush window on REAL runs — not
"approximately, modulo the branch someone forgot".  The leak-oracle
tests prove the auditor is not vacuous: a deliberately mis-counted
drop / double-counted reject breaks a law by name.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from _spmd import requires_shard_map

from eventgrad_tpu.chaos.integrity import IntegrityConfig
from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.obs import ledger as obs_ledger
from eventgrad_tpu.obs import schema as obs_schema
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train

N_RANKS = 4
CHAOS_SPEC = "seed=7,drop=0.25,bitflip=0-24@0.3"


def _run(algo="eventgrad", wire="dense", staleness=0, chaos=None,
         integrity=None, epochs=2, **kw):
    x, y = synthetic_dataset(64, (8, 8, 1), seed=1)
    kw.setdefault("event_cfg", EventConfig(
        adaptive=True, horizon=0.95, warmup_passes=2, max_silence=4))
    if wire == "compact" and algo == "eventgrad":
        kw.setdefault("compact_frac", 0.5)
    return train(
        MLP(hidden=8), Ring(N_RANKS), x, y, algo=algo, epochs=epochs,
        batch_size=8, learning_rate=0.1, obs="epoch", seed=0,
        staleness=staleness, gossip_wire=wire, chaos=chaos,
        integrity=integrity, log_every_epoch=False, **kw,
    )


def _blocks(history):
    out = [h["obs"] for h in history
           if "obs" in h and "message_ledger" in h["obs"]]
    assert out, "obs='epoch' gossip runs must carry message_ledger blocks"
    return out


def _totals(blocks):
    tot = {k: 0 for k in obs_schema.LEDGER_COUNTER_ROWS}
    for b in blocks:
        for k in tot:
            tot[k] += sum(b["message_ledger"][k])
    tot["in_flight"] = sum(blocks[-1]["message_ledger"]["in_flight"])
    return tot


def _assert_conserved(tot, *, chaos_on, staleness):
    assert tot["proposed"] == (
        tot["suppressed"] + tot["deferred"] + tot["fired"]), tot
    assert tot["fired"] == (
        tot["delivered"] + tot["dropped"] + tot["rejected"]
        + tot["in_flight"]), tot
    assert tot["late_committed"] <= tot["delivered"], tot
    assert tot["proposed"] > 0 and tot["delivered"] > 0, tot
    if not chaos_on:
        assert tot["dropped"] == 0 and tot["rejected"] == 0, tot
    if staleness < 2:
        assert tot["in_flight"] == 0 and tot["late_committed"] == 0, tot


# --- the conservation matrix -------------------------------------------

MATRIX = [
    # (algo, wire, staleness, chaos_on, integrity_on) — each wire,
    # each staleness depth, the chaos/integrity axes, and both event
    # algos appear; the fully-composed chaos+integrity legs ride the
    # hardest op point (compact wire, bounded-async D=2) and dense D=1
    ("eventgrad", "dense", 0, False, False),
    ("eventgrad", "dense", 1, True, True),
    ("eventgrad", "dense", 2, False, False),
    ("eventgrad", "compact", 0, False, False),
    ("eventgrad", "compact", 2, True, True),
    ("sp_eventgrad", "dense", 0, False, False),
    ("sp_eventgrad", "compact", 1, False, False),
    # ISSUE 20: sp payload queues at D >= 2 (bounded-async sparse
    # carrier) must keep the same books as the shallow depths
    ("sp_eventgrad", "dense", 2, False, False),
]


@pytest.mark.parametrize("algo,wire,staleness,chaos_on,integrity_on",
                         MATRIX)
def test_conservation_matrix(algo, wire, staleness, chaos_on,
                             integrity_on):
    """Every flush window's auditor verdict is ok and the run totals
    balance integer-exactly, across wires, staleness depths, drop/flip
    chaos, and the integrity reject path.  sp_eventgrad legs carry
    neither chaos nor integrity (steps.py guards)."""
    chaos = ChaosSchedule.parse(CHAOS_SPEC) if chaos_on else None
    integrity = (IntegrityConfig(checksum=True, quarantine=True)
                 if integrity_on else None)
    _, hist = _run(algo=algo, wire=wire, staleness=staleness,
                   chaos=chaos, integrity=integrity)
    blocks = _blocks(hist)
    for b in blocks:
        assert b["ledger_audit"]["ok"], b["ledger_audit"]["violations"]
        assert b["ledger_audit"]["checks"] > 0
    _assert_conserved(_totals(blocks), chaos_on=chaos_on,
                      staleness=staleness)


def test_conservation_composed_overlap_stack():
    """The ISSUE 20 production composition — bounded-async D=2,
    bucketed K=4 commit->mix tails, compact wire at half capacity,
    int8 carrier-resident delivery queues, arena slots — keeps the
    books under drop chaos and a straggler: every flush window audits
    clean and the run totals balance integer-exactly, with real late
    commits in the ledger (the queue path is exercised, not idle)."""
    chaos = ChaosSchedule.parse("seed=7,drop=0.25,slow=1@3")
    x, y = synthetic_dataset(64, (8, 8, 1), seed=1)
    _, hist = train(
        MLP(hidden=8), Ring(N_RANKS), x, y, algo="eventgrad",
        epochs=3, batch_size=8, learning_rate=0.1, obs="epoch",
        seed=0, staleness=2, gossip_wire="compact", compact_frac=0.5,
        wire="int8", arena=True, bucketed=4, carrier_resident=True,
        chaos=chaos, log_every_epoch=False,
        event_cfg=EventConfig(adaptive=True, horizon=0.95,
                              warmup_passes=2, max_silence=4),
    )
    blocks = _blocks(hist)
    for b in blocks:
        assert b["ledger_audit"]["ok"], b["ledger_audit"]["violations"]
        assert b["ledger_audit"]["checks"] > 0
    tot = _totals(blocks)
    _assert_conserved(tot, chaos_on=True, staleness=2)
    assert tot["late_committed"] > 0, tot


def test_conservation_dpsgd_dense_census():
    """dpsgd ships every leaf every pass: proposed == fired == L per
    edge per pass (no suppression/deferral rows to exercise), and with
    drop chaos the receiver side still balances exactly."""
    chaos = ChaosSchedule.parse("seed=5,drop=0.3")
    _, hist = _run(algo="dpsgd", chaos=chaos)
    blocks = _blocks(hist)
    for b in blocks:
        assert b["ledger_audit"]["ok"], b["ledger_audit"]["violations"]
    tot = _totals(blocks)
    assert tot["proposed"] == tot["fired"]
    assert tot["suppressed"] == 0 and tot["deferred"] == 0
    assert tot["dropped"] > 0, "drop=0.3 over 24 passes must land"
    _assert_conserved(tot, chaos_on=True, staleness=0)


@requires_shard_map
def test_conservation_shard_map_backend():
    """The mesh lift keeps the books identically: per-window audits
    pass and totals balance under backend='shard_map'."""
    _, hist = _run(backend="shard_map")
    blocks = _blocks(hist)
    for b in blocks:
        assert b["ledger_audit"]["ok"], b["ledger_audit"]["violations"]
    _assert_conserved(_totals(blocks), chaos_on=False, staleness=0)


# --- the leak oracles: the auditor is not vacuous ----------------------


@pytest.mark.slow  # tier-1 proves both oracles via the tool's --fast
# leg below (all_leaks_caught is schema-pinned); this is the direct
# in-harness replay with law attribution
@pytest.mark.parametrize("leak", obs_ledger.LEAKS)
def test_leak_oracles_break_a_law_by_name(leak, monkeypatch):
    """Arming EG_LEDGER_LEAK plants a deliberate accounting bug
    (uncounted drop / double-counted reject) in the traced update; the
    conservation auditor must catch it and name a receiver-side law."""
    monkeypatch.setenv(obs_ledger.LEAK_ENV, leak)
    chaos = ChaosSchedule.parse(CHAOS_SPEC)
    _, hist = _run(chaos=chaos,
                   integrity=IntegrityConfig(checksum=True,
                                             quarantine=True))
    blocks = _blocks(hist)
    bad = [b["ledger_audit"] for b in blocks
           if not b["ledger_audit"]["ok"]]
    assert bad, f"leak {leak!r} slipped past the auditor"
    laws = {v["law"] for a in bad for v in a["violations"]}
    assert any("fired" in law for law in laws), laws


def test_leak_env_rejects_unknown_value(monkeypatch):
    monkeypatch.setenv(obs_ledger.LEAK_ENV, "bogus_leak")
    with pytest.raises(ValueError, match="EG_LEDGER_LEAK"):
        _run(epochs=1)


# --- Prometheus export coverage (satellite) ----------------------------


def test_prometheus_export_partition():
    """Every field of every *_FIELDS schema group is either exported
    (PROM_EXPORTED names its gauge) or excluded with a reason — no
    overlap, no stragglers, no stale entries."""
    exported = set(obs_schema.PROM_EXPORTED)
    excluded = set(obs_schema.PROM_EXCLUDED)
    assert not exported & excluded, sorted(exported & excluded)
    groups = obs_schema.field_groups()
    assert "LEDGER_FIELDS" in groups
    all_fields = set()
    for name, fields in groups.items():
        missing = set(fields) - exported - excluded
        assert not missing, (name, sorted(missing))
        all_fields |= set(fields)
    stale = (exported | excluded) - all_fields
    assert not stale, sorted(stale)


def test_prometheus_gauges_have_live_emit_sites():
    """Each exported gauge name appears literally in package source
    outside schema.py — the contract names real registry.gauge sites,
    not aspirational ones."""
    import eventgrad_tpu

    pkg = os.path.dirname(os.path.abspath(eventgrad_tpu.__file__))
    src = []
    for dirpath, _, names in os.walk(pkg):
        for n in names:
            if n.endswith(".py") and n != "schema.py":
                with open(os.path.join(dirpath, n)) as f:
                    src.append(f.read())
    src = "\n".join(src)
    for field, gauge in obs_schema.PROM_EXPORTED.items():
        assert f'"{gauge}"' in src, (field, gauge)


# --- the tools: tolerant perf-ledger renderer + the --fast smoke -------


def _load_tool(name):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_ledger_renders_legacy_and_partial_rows():
    """tools/perf_ledger.py must render rows from BEFORE a given key
    existed (satellite: tolerant rendering).  The committed artifact
    renders as-is; so does a stripped variant with policy/backend/
    resident-dtype/round/source keys popped, gate group/prev keys
    popped, a half-filled failing gate appended, and the summary
    counters removed."""
    pl = _load_tool("perf_ledger")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "artifacts", "perf_ledger_cpu.json")) as f:
        rec = json.load(f)
    full = pl.render_text(rec)
    assert "perf ledger" in full.lower() or full

    for row in rec["rounds"]:
        for k in ("policy", "backend", "resident_dtype", "round",
                  "source"):
            row.pop(k, None)
    for g in rec.get("gates", []):
        for k in ("group", "prev", "prev_round"):
            g.pop(k, None)
    rec.setdefault("gates", []).append(
        {"metric": "step_ms", "round": 9, "ok": False, "cur": None,
         "kind": "max-ratio"})
    for k in ("n_rounds", "rounds_with_mfu", "gates_all_ok"):
        rec.pop(k, None)
    out = pl.render_text(rec)
    assert out  # no KeyError on any legacy shape
    # delta formatting survives rows with no shared keys at all
    assert pl.format_delta({}, {"step_ms": 4.2}) is not None


def test_ledger_audit_fast_leg_schema_valid(tmp_path, monkeypatch):
    """The conservation tool's --fast leg runs end to end (composed
    chaos+integrity+staleness run, both leak oracles in-process, the
    obs-off determinism legs) and its output validates against
    LEDGER_CONSERVATION_SCHEMA — the same gates the committed artifact
    is held to."""
    monkeypatch.setenv("EG_COMPACT_MIN_SAMPLES", "4")
    monkeypatch.delenv(obs_ledger.LEAK_ENV, raising=False)
    tool = _load_tool("ledger_audit")
    va = _load_tool("validate_artifacts")
    out = str(tmp_path / "ledger_fast.json")
    assert tool.main(["--fast", "--out", out]) == 0
    with open(out) as f:
        rec = json.load(f)
    errs = va.validate(rec, va.LEDGER_CONSERVATION_SCHEMA)
    assert errs == [], errs
    assert rec["conservation"]["violations"] == 0
    assert all(leg["caught"] for leg in rec["leak_oracles"])
    assert {leg["leak"] for leg in rec["leak_oracles"]} == set(
        obs_ledger.LEAKS)
