"""The ONE versioned schema behind every observability surface.

Before this package, the repo had three disconnected observability
fragments — `utils.metrics.JsonlLogger` records, `chaos.monitor` per-edge
health counters, and `utils.profiling.timed_steps` latencies — each with
its own ad-hoc field names. Everything here (the on-device
`TelemetryState` accumulators, the per-record `obs` block in train()
history, the Registry's Prometheus gauges) names its fields from the
tables below, and `docs/OBSERVABILITY.md` mirrors them field-for-field
(a test keeps the doc honest).

Bump OBS_SCHEMA_VERSION when a field changes meaning or units; adding a
field is backward compatible (readers must tolerate unknown keys).
"""

from __future__ import annotations

#: version stamp carried by every Registry record (`obs_schema`) and every
#: per-block `obs` telemetry dict in train() history
OBS_SCHEMA_VERSION = 1

#: silence histogram geometry: bucket k counts leaf-passes with silence in
#: [2^k, 2^(k+1)) passes (bucket 0 = fired on the previous pass); the last
#: bucket absorbs everything >= 2^(SILENCE_BUCKETS-1)
SILENCE_BUCKETS = 16

#: Prometheus metric-name prefix for every exported gauge
PROM_PREFIX = "eventgrad"

#: The message-lifecycle disposition taxonomy (obs/ledger.py): every
#: per-edge message a pass can affect lands in EXACTLY ONE leaf
#: disposition, so integer balance laws hold per edge per flush window
#: (docs/OBSERVABILITY.md "Message-lifecycle ledger"):
#:
#:   proposed  = suppressed + deferred + fired            (sender side)
#:   fired     = delivered + dropped + rejected + in_flight
#:                                         (receiver side, rank-summed)
#:   sender.fired(e) = receiver.(delivered+dropped+rejected+
#:                     in_flight)(e)       (cross-rank, per edge)
#:
#: name -> (parent disposition or None, description). The dict order IS
#: the counter-row order of MessageLedger.counts.
DISPOSITIONS = {
    "proposed": (
        None,
        "trigger raised the leaf this pass (threshold crossing, "
        "max-silence forced fire, or membership force-fire) — the root "
        "of the sender-side tree",
    ),
    "suppressed": (
        "proposed",
        "proposal cancelled before the wire: quarantine (non-finite "
        "grads/params) or a trigger-policy veto — nothing shipped",
    ),
    "deferred": (
        "proposed",
        "proposal admitted by the trigger but pushed past this pass by "
        "the compact-wire capacity gate — ships on a later pass",
    ),
    "fired": (
        "proposed",
        "proposal actually put on the wire this pass (post-suppression, "
        "post-capacity-gate) — the sender-side leaf that the "
        "receiver-side tree partitions",
    ),
    "delivered": (
        "fired",
        "message committed into the receiver's gossip buffer (same pass "
        "on the synchronous paths; on arrival under bounded async)",
    ),
    "dropped": (
        "fired",
        "message lost on the wire (chaos delivery mask) — the receiver "
        "kept the stale buffer",
    ),
    "rejected": (
        "fired",
        "message refused at the wire by the integrity engine (checksum "
        "mismatch or non-finite payload) — stale buffer kept, "
        "bitwise an event that did not fire",
    ),
    "in_flight": (
        "fired",
        "message accepted into the bounded-async delivery queue but not "
        "yet committed (a gauge, not a cumulative counter: the queued "
        "census drains into delivered)",
    ),
    "late_committed": (
        "in_flight",
        "delivered message that committed >= 2 passes after its send — "
        "the genuinely-late arrivals the staleness bound admitted "
        "(a sub-count of delivered, never exceeding it)",
    ),
}

#: the cumulative-counter rows of MessageLedger.counts, in row order —
#: every DISPOSITIONS leaf except the in_flight gauge (derived from the
#: ledger's delivery queue instead)
LEDGER_COUNTER_ROWS = tuple(d for d in DISPOSITIONS if d != "in_flight")

#: On-device accumulator fields (obs.device.TelemetryState). All counters
#: are CUMULATIVE on device — the host diffs consecutive flushes, so a
#: flush costs one device->host read and zero device writes.
#: name -> (units, wire modes that populate it, description)
TELEMETRY_FIELDS = {
    "steps": (
        "passes", "all",
        "passes accumulated since telemetry init",
    ),
    "fire_count": (
        "fires[leaf]", "event algos",
        "per-leaf EFFECTIVE fires (committed after any capacity gating); "
        "sum * n_neighbors reconciles with EventState.num_events",
    ),
    "defer_count": (
        "deferrals[leaf]", "compact wire",
        "per-leaf fires proposed by the trigger but deferred by the "
        "compact wire budget; sums to EventState.num_deferred",
    ),
    "thres_sum": (
        "threshold-sum[leaf]", "event algos",
        "per-leaf post-decay threshold sums (mean = /steps): the "
        "threshold trajectory at block granularity",
    ),
    "drift_sum": (
        "norm-drift-sum[leaf]", "event algos",
        "per-leaf |  ||p||_2 - last_sent_norm | sums — the trigger's "
        "drive signal",
    ),
    "silence_hist": (
        "leaf-passes[bucket]", "event algos",
        "log2-bucketed histogram of per-leaf silence (passes since last "
        "send) observed at each pass; bucket k = [2^k, 2^(k+1))",
    ),
    "fired_elems_sum": (
        "elements", "event algos",
        "payload elements admitted to the wire, summed over passes "
        "(capacity-utilization numerator on the compact wire)",
    ),
    "fired_elems_peak": (
        "elements", "event algos",
        "max per-pass admitted payload elements since init",
    ),
    "edge_bytes": (
        "bytes[edge]", "gossip algos",
        "per-edge wire-real bytes accumulated (the SPMD bytes the "
        "collective actually moved — dense/masked ship the full payload, "
        "compact ships the static capacity; see docs/compaction.md)",
    ),
    "wire_reject": (
        "rejections[edge]", "integrity runs",
        "per-edge payloads rejected at the wire (checksum mismatch or "
        "non-finite content) — each rejection kept the stale buffer, "
        "bitwise an event that did not fire (docs/chaos.md)",
    ),
    "quarantined": (
        "passes", "integrity runs",
        "passes this rank spent quarantined (non-finite local gradients "
        "or post-update parameters: update skipped, sends suppressed)",
    ),
    "bucket_bytes": (
        "bytes[bucket]", "gossip algos",
        "per-bucket wire-real bytes accumulated under the bucketed "
        "gossip schedule (train(bucketed=K)); [1] on the monolithic "
        "path — the sum always equals the edge_bytes total (see "
        "docs/ARCHITECTURE.md 'Bucketed gossip schedule')",
    ),
    "edge_staleness": (
        "staleness-sum[edge]", "bounded-async runs",
        "per-edge staleness gauge accumulated per pass (passes between "
        "the newest committed delivery's send and now; mean = /steps) "
        "under the bounded-async engine (train(staleness=D >= 2)) — "
        "bounded by D plus any drop streak; also the Prometheus gauge "
        "eventgrad_edge_staleness (docs/chaos.md 'Bounded-async gossip "
        "& stragglers')",
    ),
    "staleness_hist": (
        "edge-passes[bucket]", "bounded-async runs",
        "log2-bucketed histogram of the per-edge-pass staleness gauge "
        "(same bucket geometry as silence_hist)",
    ),
    "late_commits": (
        "commits", "bounded-async runs",
        "deliveries committed >= 2 passes after their send — the "
        "genuinely-late arrivals the bound admitted (each one bitwise "
        "a fire deferred to its arrival pass); reconciles with "
        "EventState.late_commits",
    ),
}

#: Host-side `obs` block attached to block-end history records
#: (train/loop.py). Every count is the DELTA over the flush window, per
#: rank summed unless noted. name -> (units, wire modes, description)
RECORD_FIELDS = {
    "schema": ("int", "all", "OBS_SCHEMA_VERSION of the writer"),
    "steps": ("passes", "all", "passes in this flush window"),
    "fire_count": (
        "fires[leaf]", "event algos",
        "per-leaf effective fires, summed over ranks",
    ),
    "defer_count": (
        "deferrals[leaf]", "compact wire",
        "per-leaf deferrals, summed over ranks",
    ),
    "thres_mean": (
        "threshold[leaf]", "event algos",
        "per-leaf mean post-decay threshold over the window (rank mean)",
    ),
    "drift_mean": (
        "norm-drift[leaf]", "event algos",
        "per-leaf mean norm drift over the window (rank mean)",
    ),
    "silence_hist": (
        "leaf-passes[bucket]", "event algos",
        "silence histogram delta, summed over ranks",
    ),
    "fired_elems_mean": (
        "elements", "event algos",
        "mean per-pass admitted payload elements (rank mean)",
    ),
    "fired_elems_peak": (
        "elements", "event algos",
        "peak per-pass admitted payload elements (max over ranks, "
        "cumulative since init — peaks cannot be windowed from a "
        "running max)",
    ),
    "edge_bytes_per_step": (
        "bytes[edge]", "gossip algos",
        "per-edge wire-real bytes per pass (rank mean)",
    ),
    "wire_reject_count": (
        "rejections[edge]", "integrity runs",
        "per-edge wire rejections in this flush window, summed over ranks",
    ),
    "quarantined_steps": (
        "rank-passes", "integrity runs",
        "quarantined rank-passes in this flush window, summed over ranks",
    ),
    "bucket_bytes_per_step": (
        "bytes[bucket]", "gossip algos",
        "per-bucket wire-real bytes per pass (rank mean) — the bucketed "
        "gossip schedule's wire split; a single entry on the "
        "monolithic path",
    ),
    "edge_staleness_per_step": (
        "staleness[edge]", "bounded-async runs",
        "per-edge mean staleness per pass over the window (rank mean) "
        "— 1.0 is the no-fault asynchrony baseline, a persistent "
        "straggler's edges sit at min(f, D)",
    ),
    "late_commit_count": (
        "commits", "bounded-async runs",
        "late (lag >= 2) delivery commits in this flush window, summed "
        "over ranks",
    ),
}

#: keys the first obs-carrying record of a run additionally carries
RECORD_META_FIELDS = {
    "leaves": ("names[leaf]", "all", "parameter leaf names, leaf-major"),
    "edges": ("names[edge]", "all", "gossip edge names (topology order)"),
    "silence_buckets": (
        "int", "all", "histogram bucket count (log2 geometry)",
    ),
    "n_ranks": ("int", "all", "ranks contributing to summed counts"),
    "n_neighbors": ("int", "all", "gossip neighbors per rank"),
    "wire": (
        "str|null", "all", "gossip wire dtype (null = f32, bf16, int8)",
    ),
}


#: Elastic-membership surfaces (chaos/membership.py): per-epoch history
#: record fields plus the Prometheus gauges `eventgrad_active_ranks` and
#: `eventgrad_membership_transitions_total`. name -> (units, modes,
#: description)
MEMBERSHIP_FIELDS = {
    "active_ranks": (
        "int", "all",
        "ranks alive during the record's dispatch block (constant "
        "without membership; the elasticity trajectory with it) — also "
        "a Prometheus gauge",
    ),
    "membership": (
        "schedule dict", "membership runs",
        "the serialized MembershipSchedule, stamped on the run's first "
        "record (replayability rider, like `chaos`)",
    ),
    "membership_transitions": (
        "records[transition]", "membership runs",
        "transition info dicts (kind, epoch, index, src, "
        "n_ranks_before/after, bootstrap_streamed, apply_s) on the "
        "record FOLLOWING the block boundary they were applied at; "
        "their cumulative count is the "
        "membership_transitions_total gauge",
    ),
}


#: Integrity-engine surfaces (chaos/integrity.py): per-epoch history
#: record fields plus the Prometheus gauges
#: `eventgrad_wire_rejects_total`, `eventgrad_quarantined_steps_total`,
#: and `eventgrad_integrity_rollbacks_total`.
#: name -> (units, modes, description)
INTEGRITY_FIELDS = {
    "wire_rejects": (
        "rejections", "integrity runs",
        "payloads rejected at the wire this epoch (checksum mismatch or "
        "non-finite content), summed over ranks and edges — cumulative "
        "form is the wire_rejects_total gauge",
    ),
    "quarantined_steps": (
        "rank-passes", "integrity runs",
        "rank-passes quarantined this epoch (update skipped, sends "
        "suppressed) — cumulative form is the quarantined_steps_total "
        "gauge",
    ),
    "integrity": (
        "config dict", "integrity runs",
        "the serialized IntegrityConfig, stamped on the run's first "
        "record (replayability rider, like `chaos`)",
    ),
    "integrity_rollbacks": (
        "int", "integrity runs",
        "rollbacks performed so far (cumulative; also the "
        "integrity_rollbacks_total gauge)",
    ),
    "integrity_rollback": (
        "info dict", "integrity runs",
        "rollback info (reason, tripped_epoch, restored_epoch, "
        "hardened) on the first record AFTER the engine restored the "
        "last-known-good snapshot",
    ),
}


#: Preemption & crash-drill surfaces (chaos/crashpoint.py): terminal/
#: first-record riders plus the Prometheus gauge
#: `eventgrad_preemptions_total`. name -> (units, modes, description)
PREEMPTION_FIELDS = {
    "preempted": (
        "record", "preemption runs",
        "terminal record the CLI writes after a graceful drain: reason "
        "(signal:SIGTERM|signal:SIGINT|schedule:E@S), epoch (the "
        "drained block boundary), snapshot (a boundary snapshot is on "
        "disk), drain_s, marker (the PREEMPTED file path) — the "
        "process then exits exitcodes.PREEMPTED_EXIT and the "
        "supervisor relaunches without charging its restart budget",
    ),
    "drain_s": (
        "seconds", "preemption runs",
        "time the graceful drain spent (pipeline drain + writer join + "
        "boundary snapshot), inside the `preempted` record",
    ),
    "crashpoint": (
        "rider", "crash-drill runs",
        "the armed EG_CRASHPOINT as {site, hit}, stamped on the run's "
        "first record (replayability rider, like `chaos`): the log of "
        "a killed run names the site it died at",
    ),
    "preemptions_total": (
        "count", "preemption runs",
        "Prometheus gauge: graceful preemption drains this process "
        "performed (0 normally, 1 after a drain)",
    ),
}


#: Performance-ledger surfaces (obs/costmodel.py + obs/devicespec.py +
#: tools/perf_ledger.py): the `costmodel` block bench.py and
#: tools/tpu_flagship.py attach to their records, and the per-round
#: trajectory fields of artifacts/perf_ledger*.json.
#: name -> (units, modes, description)
PERF_FIELDS = {
    "flops_per_step": (
        "FLOP", "all",
        "analytic FLOPs of one full train step (all vmap-ranks), from "
        "the obs.costmodel jaxpr walk: dot_general/conv exactly from "
        "shapes, elementwise/reductions per operand element — "
        "backend-independent, unlike the XLA cost_analysis number it "
        "rides next to",
    ),
    "hbm_bytes_per_step": (
        "bytes", "all",
        "analytic per-step memory-traffic CEILING (operand + result "
        "bytes of every traced equation, no fusion credit) — stable "
        "across rounds by construction, the regression ledger's "
        "bytes denominator",
    ),
    "flops_by_phase": (
        "FLOP[phase]", "all",
        "the per-phase split grad / gate_pack / exchange / commit_mix "
        "/ other from the egphase named scopes in train/steps.py "
        "(per-bucket labels <phase>.bK under bucketed=K)",
    ),
    "hbm_bytes_by_phase": (
        "bytes[phase]", "all",
        "the same phase split for the analytic byte ceiling",
    ),
    "mfu": (
        "fraction", "all",
        "model-FLOPs utilization: flops_per_step / (step_s * "
        "peak_flops) of the device spec — on a nominal spec "
        "(generic-cpu) a cross-round TRACKING number, not a hardware "
        "claim (obs/devicespec.py)",
    ),
    "achieved_flops_per_s": (
        "FLOP/s", "all", "flops_per_step / measured step seconds",
    ),
    "achieved_bytes_per_s": (
        "bytes/s", "all",
        "hbm_bytes_per_step / measured step seconds (against the "
        "analytic ceiling, so a lower bound on achieved bandwidth "
        "efficiency)",
    ),
    "arithmetic_intensity": (
        "FLOP/byte", "all",
        "flops_per_step / hbm_bytes_per_step — the roofline x-axis",
    ),
    "ridge_intensity": (
        "FLOP/byte", "all",
        "peak_flops / peak_hbm_bytes_per_s of the device spec: the "
        "roofline ridge — below it memory-bound, above compute-bound",
    ),
    "roofline_bound": (
        "compute|memory", "all",
        "which roofline regime the step sits in (arithmetic_intensity "
        "vs ridge_intensity)",
    ),
    "roofline_frac": (
        "fraction", "all",
        "achieved FLOP/s over the ATTAINABLE ceiling at this "
        "intensity, min(peak_flops, intensity * peak_bw) — the honest "
        "utilization for memory-bound steps where raw MFU reads low",
    ),
    "device_spec": (
        "str", "all",
        "obs.devicespec name the peaks came from (tpu-v5e, ..., "
        "generic-cpu); nominal_spec=true marks placeholder peaks",
    ),
    "peak_hbm_bytes": (
        "bytes", "all",
        "the backend's own compiled-program memory analysis "
        "(obs.costmodel.compiled_memory: argument/output/temp/code "
        "bytes + peak_bytes), when the backend reports one",
    ),
    "compile_spans": (
        "seconds[stage]", "all",
        "trace / lower / compile / first-dispatch wall spans "
        "(obs.costmodel.compile_timed; span names compile_trace, "
        "compile_lower, compile_compile, first_dispatch in the span "
        "registry, cat=\"compile\")",
    ),
    "resident_dtype": (
        "f32|bf16|int8", "event algos",
        "resident dtype of the EventState receive buffers — 'f32' "
        "unless the run is carrier-resident (train "
        "carrier_resident=True keeps the buffers in the wire carrier "
        "dtype); part of every history record and of the perf "
        "ledger's residency rows, so byte comparisons are keyed on "
        "the layout that actually ran",
    ),
}


#: Message-lifecycle ledger surfaces (obs/ledger.py): the per-edge
#: disposition counters inside TelemetryState, the `message_ledger`
#: block window_record attaches to the record's `obs` dict, and the
#: host-side conservation auditor's verdict. name -> (units, modes,
#: description)
LEDGER_FIELDS = {
    "ledger": (
        "counts[disposition][edge]", "gossip algos",
        "the on-device MessageLedger block of TelemetryState: cumulative "
        "int32 per-edge counters, one row per DISPOSITIONS leaf (plus "
        "the bounded-async in-flight delivery queue the in_flight gauge "
        "derives from); every message-affecting path increments exactly "
        "one disposition through obs.ledger.ledger_update",
    ),
    "message_ledger": (
        "counts[disposition][edge]", "gossip algos",
        "record-surface twin of the device ledger: per-disposition "
        "per-edge window deltas summed over ranks, plus the in_flight "
        "gauge at the window end",
    ),
    "ledger_audit": (
        "verdict dict", "gossip algos",
        "the host-side conservation auditor's verdict for the flush "
        "window (obs.ledger.audit_window): ok, checks performed, and "
        "the first few violations with edge/rank/law attribution",
    ),
    "in_flight": (
        "messages[edge]", "bounded-async runs",
        "gauge: messages accepted into the bounded-async delivery queue "
        "but not yet committed (row-sum of the ledger's queue) — the "
        "balancing term that makes fired = delivered + dropped + "
        "rejected + in_flight exact mid-flight",
    ),
}


#: The Prometheus export contract (satellite of ISSUE 18): every field
#: of every *_FIELDS group above is either exported as a gauge (its
#: entry here names the gauge, sans PROM_PREFIX) or listed in
#: PROM_EXCLUDED with a reason — a new field can no longer silently
#: skip the exporter (tests/test_ledger.py keeps the partition total).
PROM_EXPORTED = {
    # TELEMETRY_FIELDS
    "edge_staleness": "edge_staleness",          # {edge=...} labels
    "late_commits": "late_commits_total",
    "wire_reject": "wire_rejects_total",         # cumulative twin
    "quarantined": "quarantined_steps_total",    # cumulative twin
    # MEMBERSHIP_FIELDS
    "active_ranks": "active_ranks",
    "membership_transitions": "membership_transitions_total",
    # INTEGRITY_FIELDS
    "wire_rejects": "wire_rejects_total",
    "quarantined_steps": "quarantined_steps_total",
    "integrity_rollbacks": "integrity_rollbacks_total",
    # PREEMPTION_FIELDS
    "preemptions_total": "preemptions_total",
    # LEDGER_FIELDS: one gauge per cumulative disposition row (summed
    # over ranks and edges) + the in-flight gauge + the audit verdict
    "ledger": "ledger_disposition_total",        # {disposition=...}
    "message_ledger": "ledger_disposition_total",
    "in_flight": "ledger_in_flight",
    "ledger_audit": "ledger_audit_failures_total",
}

#: field -> why it is NOT a Prometheus gauge. Vectors/histograms stay on
#: the JSONL/report surface (Prometheus gauges are scalars per label
#: set and these would explode cardinality); config/info dicts are
#: replayability riders, not time series; perf/report fields live in
#: artifacts, not the live exporter.
PROM_EXCLUDED = {
    # TELEMETRY_FIELDS — per-leaf/bucket vectors and report-only scalars
    "steps": "window bookkeeping; wall-clock rates come from the span "
             "registry, not a pass counter",
    "fire_count": "per-leaf vector (one gauge per leaf would explode "
                  "cardinality); report surface renders the heatmap",
    "defer_count": "per-leaf vector; the ledger's deferred row carries "
                   "the per-edge scalar twin",
    "thres_sum": "per-leaf vector; report-surface heatmap",
    "drift_sum": "per-leaf vector; report-surface heatmap",
    "silence_hist": "histogram; chaos.monitor exports edge_silence_max "
                    "as the live scalar",
    "fired_elems_sum": "capacity-utilization numerator; report surface",
    "fired_elems_peak": "running max, not a rate; report surface",
    "edge_bytes": "per-edge byte vector; sent_bytes rides the history "
                  "records and bench artifacts",
    "bucket_bytes": "per-bucket vector; report surface",
    "staleness_hist": "histogram; edge_staleness is the live gauge",
    # RECORD_FIELDS — window-delta twins of the device counters above;
    # the JSONL history is their surface
    "schema": "version stamp, not a metric",
    "thres_mean": "per-leaf vector (see thres_sum)",
    "drift_mean": "per-leaf vector (see drift_sum)",
    "fired_elems_mean": "report surface (see fired_elems_sum)",
    "edge_bytes_per_step": "per-edge vector (see edge_bytes)",
    "wire_reject_count": "window delta; wire_rejects_total is the "
                         "cumulative gauge",
    "bucket_bytes_per_step": "per-bucket vector (see bucket_bytes)",
    "edge_staleness_per_step": "window delta; edge_staleness is the "
                               "live gauge",
    "late_commit_count": "window delta; late_commits_total is the "
                         "cumulative gauge",
    # RECORD_META_FIELDS — run metadata, not time series
    "leaves": "metadata rider", "edges": "metadata rider",
    "silence_buckets": "metadata rider", "n_ranks": "metadata rider",
    "n_neighbors": "metadata rider", "wire": "metadata rider",
    # MEMBERSHIP / INTEGRITY / PREEMPTION info dicts
    "membership": "config dict replayability rider",
    "integrity": "config dict replayability rider",
    "integrity_rollback": "info dict; integrity_rollbacks_total is the "
                          "gauge",
    "preempted": "terminal record; preemptions_total is the gauge",
    "drain_s": "inside the terminal preempted record",
    "crashpoint": "replayability rider",
    # PERF_FIELDS — artifact surface (perf ledger), not the live
    # exporter: one reason for the whole group
    **{f: "perf-ledger artifact surface (tools/perf_ledger.py), not a "
          "live exporter metric" for f in (
        "flops_per_step", "hbm_bytes_per_step", "flops_by_phase",
        "hbm_bytes_by_phase", "mfu", "achieved_flops_per_s",
        "achieved_bytes_per_s", "arithmetic_intensity",
        "ridge_intensity", "roofline_bound", "roofline_frac",
        "device_spec", "peak_hbm_bytes", "compile_spans",
        "resident_dtype",
    )},
    # REPORT_FIELDS — derived report series
    **{f: "derived report series (tools/obs_report.py), not a live "
          "exporter metric" for f in (
        "msgs_saved_pct_per_leaf", "fire_rate_heatmap", "thres_heatmap",
        "capacity_utilization", "consensus_error", "message_lifecycle",
    )},
}


#: derived series emitted by obs.report.build_report (tools/obs_report.py)
REPORT_FIELDS = {
    "msgs_saved_pct_per_leaf": (
        "%[leaf] per window", "event algos",
        "per-leaf messages saved vs D-PSGD "
        "(utils.metrics.msgs_saved_pct_per_leaf over window fire counts)",
    ),
    "fire_rate_heatmap": (
        "rate[window][leaf]", "event algos",
        "per-leaf fire rate per flush window (fire_count / (steps * "
        "n_ranks)) — heatmap rows",
    ),
    "thres_heatmap": (
        "threshold[window][leaf]", "event algos",
        "per-leaf mean post-decay threshold per flush window",
    ),
    "capacity_utilization": (
        "fraction", "compact wire",
        "mean admitted payload elements / compact_capacity per window, "
        "with fired bytes vs the capacity bytes and the deferral rate",
    ),
    "consensus_error": (
        "l2-norm", "all",
        "||p_i - mean(p)||_2 trajectory at block ends (max/mean over "
        "ranks)",
    ),
    "message_lifecycle": (
        "counts[disposition][edge]", "gossip algos",
        "run-total per-edge disposition table + per-window timeline + "
        "aggregated conservation-audit verdict, folded from the "
        "message_ledger / ledger_audit blocks of the obs records",
    ),
}


def all_field_names():
    """Every schema field name, for doc-coverage tests."""
    names = set(TELEMETRY_FIELDS) | set(RECORD_FIELDS)
    names |= set(RECORD_META_FIELDS) | set(REPORT_FIELDS)
    names |= set(MEMBERSHIP_FIELDS) | set(INTEGRITY_FIELDS)
    names |= set(PREEMPTION_FIELDS) | set(PERF_FIELDS)
    names |= set(LEDGER_FIELDS) | set(DISPOSITIONS)
    return sorted(names)


def field_groups():
    """name -> fields for every *_FIELDS group in this module, for the
    Prometheus export-coverage test (each field must be PROM_EXPORTED
    or PROM_EXCLUDED)."""
    import sys

    mod = sys.modules[__name__]
    return {
        name: getattr(mod, name)
        for name in dir(mod)
        if name.endswith("_FIELDS") and isinstance(getattr(mod, name), dict)
    }
