"""FLOPs/MFU accounting (utils/flops.py): XLA cost-model plumbing works on
any backend; chip-peak lookup and the MFU quotient behave sanely."""

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.utils.flops import chip_peak_flops, compiled_flops, mfu


def test_compiled_flops_counts_a_matmul():
    n = 256
    a = jnp.ones((n, n), jnp.float32)
    flops = compiled_flops(lambda x: x @ x, a)
    # dense matmul is 2*n^3 FLOPs; XLA's cost model reports exactly that
    # (allow slack for fused epilogues / model differences across versions)
    assert flops >= 2 * n**3 * 0.5, flops
    assert flops <= 2 * n**3 * 2.0, flops


def test_compiled_flops_scales_with_size():
    a = jnp.ones((128, 128))
    b = jnp.ones((256, 256))
    fa = compiled_flops(lambda x: x @ x, a)
    fb = compiled_flops(lambda x: x @ x, b)
    assert fb > 4 * fa  # 8x FLOPs for 2x dimensions


def test_chip_peak_is_zero_on_cpu_mesh_and_mfu_none():
    assert chip_peak_flops() == 0.0  # conftest pins the CPU backend
    assert mfu(1e12, 0.001) is None


def test_mfu_quotient():
    class FakeTPU:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    assert chip_peak_flops(FakeTPU()) == 197e12
    got = mfu(197e9, 0.001, FakeTPU())  # 197 GFLOP in 1 ms = peak
    np.testing.assert_allclose(got, 1.0)
    assert mfu(0.0, 0.001, FakeTPU()) is None


def test_train_step_flops_cover_fwd_and_bwd():
    """The flagship bench MFU path: step FLOPs of a train step must exceed
    ~3x the forward pass (fwd + 2x-ish bwd), so the metric can't silently
    count only inference."""
    import optax

    from eventgrad_tpu.models import MLP
    from eventgrad_tpu.parallel.spmd import spmd
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.state import init_train_state
    from eventgrad_tpu.train.steps import make_train_step

    topo = Ring(4)
    model = MLP(hidden=64)
    tx = optax.sgd(0.1)
    state = init_train_state(model, (28, 28, 1), tx, topo, "dpsgd")
    step = make_train_step(model, tx, topo, "dpsgd")
    xb = jnp.zeros((4, 8, 28, 28, 1))
    yb = jnp.zeros((4, 8), jnp.int32)

    step_flops = compiled_flops(spmd(step, topo), state, (xb, yb))
    params0 = state.params
    fwd_flops = compiled_flops(
        lambda p, x: model.apply({"params": jax.tree.map(lambda l: l[0], p)}, x),
        params0, xb[0],
    )
    assert step_flops > 0 and fwd_flops > 0
    # fwd + bwd per rank; the 2-layer MLP's bwd skips the input-gradient
    # matmul, so the honest lower bound is 2x fwd per rank, not 3x
    assert step_flops > 2.0 * 4 * fwd_flops
