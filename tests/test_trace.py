"""Send-side trace instrumentation (the reference's file_write=1 send{r}.txt)."""

import json

import numpy as np

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train


def test_trace_file_records_send_decisions(tmp_path):
    x, y = synthetic_dataset(128, (28, 28, 1), seed=1)
    path = tmp_path / "send.jsonl"
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    state, hist = train(
        MLP(), Ring(4), x, y,
        algo="eventgrad", epochs=2, batch_size=8, learning_rate=0.05,
        event_cfg=cfg, seed=0, trace_file=str(path),
    )
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    header, recs = lines[0], lines[1:]

    assert len(header["trace_params"]) == 4  # MLP: 2 kernels + 2 biases
    steps_per_epoch = hist[0]["steps"]
    total = 2 * steps_per_epoch * 4  # passes x ranks
    assert len(recs) == total
    assert {r["rank"] for r in recs} == {0, 1, 2, 3}
    assert max(r["pass"] for r in recs) == 2 * steps_per_epoch

    for r in recs:
        assert len(r["norm"]) == len(r["thres"]) == len(r["fired"]) == 4
        if r["pass"] <= 1:  # warmup: pass_num < warmup_passes always fires
            assert all(f == 1 for f in r["fired"])

    # fired counts must reconcile with the num_events counter (x2 neighbors)
    fired_total = sum(sum(r["fired"]) for r in recs)
    assert 2 * fired_total == int(np.asarray(state.event.num_events).sum())
