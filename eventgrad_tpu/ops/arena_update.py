"""Pallas TPU kernel: fused buffer-commit + gossip-mix + momentum-SGD.

`ops/fused_update.fused_mix_sgd` fuses the mix + SGD tail into one HBM
pass but still consumes neighbor buffers that an earlier pass had to
materialize: the event exchanges first scatter received payloads into
the stale buffers (`where(fired, new, stale)` — one full read+write of
every buffer), then the mix reads them again. On the flat arena both
stages are elementwise over the same [n] positions, so this kernel does
them together — per element and per neighbor:

    buf_new_i = where(keep_i, candidate_i, stale_i)     # the commit
    mixed     = (p + sum(buf_mix_*)) * w                # gossip mix
    trace     = momentum * trace + grad                 # optax sgd trace
    p_new     = mixed - lr * trace                      # optimizer step

writing (p_new, trace_new, buf_new_0..k) in one guaranteed single
read/write per element. `mix_stale=True` accumulates the STALE buffers
into the mix while still committing the new ones — the staleness=1 mode
of the event step (mix with last step's arrivals, land this step's for
the next).

The event-STATE commit (events.commit — [L]-sized threshold/slope
rollback) deliberately stays outside: it is a few hundred bytes, not an
HBM pass, and fusing it would couple the kernel to the trigger's state
layout for nothing.

`mix_commit_reference` is the jnp twin (bitwise: same elementwise ops)
used for tests and as the non-TPU path. Both forms are bitwise-equal to
the unfused optax tail: `momentum*t + g` == `g + momentum*t` and
`mixed - lr*t` == `mixed + (-lr)*t` exactly in IEEE arithmetic.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

try:  # TPU memory spaces only exist on TPU builds; interpret mode elsewhere
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANES = 128
#: 512x128 f32 = 256 KiB per ref; with 2 neighbors that is 13 refs
#: (~3.3 MiB of VMEM working set) — comfortably inside a TensorCore's
#: VMEM while keeping the grid long enough to split across megacores.
_BLOCK_ROWS = 512


def _commit_kernel(*refs, lr, momentum, w, nb, mix_stale):
    # INVARIANT: strictly elementwise — the partial trailing block
    # relies on Mosaic masking out-of-bounds stores (ops/fused_update).
    p_ref, g_ref, t_ref = refs[:3]
    cands = refs[3 : 3 + nb]
    keeps = refs[3 + nb : 3 + 2 * nb]
    lasts = refs[3 + 2 * nb : 3 + 3 * nb]
    po_ref, to_ref = refs[3 + 3 * nb : 5 + 3 * nb]
    bufs_out = refs[5 + 3 * nb :]

    acc = p_ref[:]
    for i in range(nb):
        new_b = jnp.where(keeps[i][:] > 0, cands[i][:], lasts[i][:])
        bufs_out[i][:] = new_b
        acc = acc + (lasts[i][:] if mix_stale else new_b)
    mixed = acc * w
    trace = momentum * t_ref[:] + g_ref[:]
    po_ref[:] = mixed - lr * trace
    to_ref[:] = trace


@functools.partial(
    jax.jit,
    static_argnames=("lr", "momentum", "w", "nb", "mix_stale", "interpret"),
)
def _fused_commit_flat(
    p, g, t, cands, keeps, lasts, *, lr, momentum, w, nb, mix_stale,
    interpret,
):
    n = p.size
    ragged = n % _LANES != 0
    if ragged:  # pad to a lane-tile multiple (copies; small n only)
        padded = -(-n // _LANES) * _LANES
        prep = lambda x: jnp.pad(
            x.reshape(-1).astype(jnp.float32), (0, padded - n)
        ).reshape(-1, _LANES)
    else:  # free reshape: no data movement outside the kernel
        prep = lambda x: x.reshape(-1, _LANES).astype(jnp.float32)

    args = [prep(p), prep(g), prep(t)]
    args += [prep(c) for c in cands]
    args += [prep(k) for k in keeps]
    args += [prep(l) for l in lasts]
    rows = args[0].shape[0]
    grid = (pl.cdiv(rows, _BLOCK_ROWS),)
    spec = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES),
        lambda i: (i, 0),
        **({"memory_space": _VMEM}
           if (_VMEM is not None and not interpret) else {}),
    )
    extra = {}
    if not interpret and pltpu is not None:
        extra["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        )
    shape = jax.ShapeDtypeStruct(args[0].shape, jnp.float32)
    outs = pl.pallas_call(
        functools.partial(
            _commit_kernel, lr=lr, momentum=momentum, w=w, nb=nb,
            mix_stale=mix_stale,
        ),
        out_shape=tuple([shape] * (2 + nb)),
        grid=grid,
        in_specs=[spec] * len(args),
        out_specs=tuple([spec] * (2 + nb)),
        interpret=interpret,
        **extra,
    )(*args)
    # restore each output's input dtype (the kernel computes in f32, like
    # ops/fused_update.py): p_new/trace/bufs feed scan-carried state whose
    # dtype must not drift across steps
    out_dtypes = [p.dtype, t.dtype] + [l.dtype for l in lasts]
    unpad = lambda x, dt: x.reshape(-1)[:n].astype(dt)
    return tuple(unpad(o, dt) for o, dt in zip(outs, out_dtypes))


def fused_mix_commit(
    p: jnp.ndarray,
    cands: Tuple[jnp.ndarray, ...],
    keeps: Tuple[jnp.ndarray, ...],
    lasts: Tuple[jnp.ndarray, ...],
    g: jnp.ndarray,
    t: jnp.ndarray,
    lr: float,
    momentum: float,
    mix_weight: float,
    mix_stale: bool = False,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """Fused commit+mix+SGD over flat [n] arenas.

    `cands`/`keeps`/`lasts` are one entry per neighbor: the received
    candidate values, per-POSITION keep bits (fire bits expanded by the
    segment map, 0/1 floats or bools), and the stale buffers. Returns
    (p_new, trace_new, committed_bufs). All f32 in/out.
    """
    nb = len(cands)
    assert len(keeps) == nb and len(lasts) == nb
    keeps = tuple(k.astype(jnp.float32) for k in keeps)
    outs = _fused_commit_flat(
        p, g, t, tuple(cands), keeps, tuple(lasts),
        lr=float(lr), momentum=float(momentum), w=float(mix_weight),
        nb=nb, mix_stale=bool(mix_stale), interpret=interpret,
    )
    return outs[0], outs[1], tuple(outs[2:])


def mix_commit_reference(
    p: jnp.ndarray,
    cands: Tuple[jnp.ndarray, ...],
    keeps: Tuple[jnp.ndarray, ...],
    lasts: Tuple[jnp.ndarray, ...],
    g: jnp.ndarray,
    t: jnp.ndarray,
    lr: float,
    momentum: float,
    mix_weight: float,
    mix_stale: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """jnp twin of the kernel (also the non-TPU fallback path)."""
    bufs = tuple(
        jnp.where(k.astype(jnp.float32) > 0, c, l)
        for c, k, l in zip(cands, keeps, lasts)
    )
    acc = p
    for i in range(len(bufs)):
        acc = acc + (lasts[i] if mix_stale else bufs[i])
    mixed = acc * mix_weight
    trace = momentum * t + g
    return mixed - lr * trace, trace, bufs


# ---------------------------------------------------------------------------
# carrier-resident fused tail: the commit+mix+SGD pass READS THE WIRE
# CARRIER (bf16/int8 candidates and stale buffers; 1-2 B/elem instead
# of 4) and dequantizes in-register — the select runs on the carrier,
# the committed buffer is written back in the carrier dtype, and the
# mix multiplies the selected carrier by the already-COMMITTED
# per-position scale (`mix_scales`). Bitwise the f32 kernel: within a
# leaf the keep bit is constant, so
#     where(keep, cand_q, last_q) * s_committed
#   == where(keep, cand_q * s_cand, last_q * s_last)
# elementwise (s_committed is s_cand where the leaf fired, s_last where
# it kept), and each `q * s` is the exact same f32 multiply the
# dequantize-at-receive path ran (`collectives._contract_safe`).

def _carrier_commit_kernel(*refs, lr, momentum, w, nb, mix_stale,
                           has_scales):
    # INVARIANT: strictly elementwise, like _commit_kernel.
    p_ref, g_ref, t_ref = refs[:3]
    cands = refs[3 : 3 + nb]
    keeps = refs[3 + nb : 3 + 2 * nb]
    lasts = refs[3 + 2 * nb : 3 + 3 * nb]
    off = 3 + 3 * nb
    sm = refs[off : off + nb] if has_scales else ()
    out0 = off + (nb if has_scales else 0)
    po_ref, to_ref = refs[out0 : out0 + 2]
    bufs_out = refs[out0 + 2 :]

    acc = p_ref[:]
    for i in range(nb):
        new_q = jnp.where(keeps[i][:] > 0, cands[i][:], lasts[i][:])
        bufs_out[i][:] = new_q
        val = (lasts[i][:] if mix_stale else new_q).astype(jnp.float32)
        if has_scales:
            val = val * sm[i][:]
        acc = acc + val
    mixed = acc * w
    trace = momentum * t_ref[:] + g_ref[:]
    po_ref[:] = mixed - lr * trace
    to_ref[:] = trace


@functools.partial(
    jax.jit,
    static_argnames=(
        "lr", "momentum", "w", "nb", "mix_stale", "interpret",
    ),
)
def _fused_commit_carrier_flat(
    p, g, t, cands, keeps, lasts, mix_scales, *, lr, momentum, w, nb,
    mix_stale, interpret,
):
    has_scales = mix_scales is not None
    cdt = lasts[0].dtype
    n = p.size
    ragged = n % _LANES != 0

    def prep(x, dt):
        x = x.reshape(-1).astype(dt)
        if ragged:
            x = jnp.pad(x, (0, -(-n // _LANES) * _LANES - n))
        return x.reshape(-1, _LANES)

    args = [prep(p, jnp.float32), prep(g, jnp.float32),
            prep(t, jnp.float32)]
    args += [prep(c, cdt) for c in cands]
    args += [prep(k, jnp.float32) for k in keeps]
    args += [prep(l, cdt) for l in lasts]
    if has_scales:
        args += [prep(s, jnp.float32) for s in mix_scales]
    rows = args[0].shape[0]
    grid = (pl.cdiv(rows, _BLOCK_ROWS),)
    spec = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES),
        lambda i: (i, 0),
        **({"memory_space": _VMEM}
           if (_VMEM is not None and not interpret) else {}),
    )
    extra = {}
    if not interpret and pltpu is not None:
        extra["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        )
    f32 = jax.ShapeDtypeStruct(args[0].shape, jnp.float32)
    carr = jax.ShapeDtypeStruct(args[0].shape, cdt)
    outs = pl.pallas_call(
        functools.partial(
            _carrier_commit_kernel, lr=lr, momentum=momentum, w=w,
            nb=nb, mix_stale=mix_stale, has_scales=has_scales,
        ),
        out_shape=(f32, f32) + tuple([carr] * nb),
        grid=grid,
        in_specs=[spec] * len(args),
        out_specs=(spec, spec) + tuple([spec] * nb),
        interpret=interpret,
        **extra,
    )(*args)
    out_dtypes = [p.dtype, t.dtype] + [cdt] * nb
    unpad = lambda x, dt: x.reshape(-1)[:n].astype(dt)
    return tuple(unpad(o, dt) for o, dt in zip(outs, out_dtypes))


def fused_mix_commit_carrier(
    p: jnp.ndarray,
    cands: Tuple[jnp.ndarray, ...],
    keeps: Tuple[jnp.ndarray, ...],
    lasts: Tuple[jnp.ndarray, ...],
    g: jnp.ndarray,
    t: jnp.ndarray,
    lr: float,
    momentum: float,
    mix_weight: float,
    mix_scales: Optional[Tuple[jnp.ndarray, ...]] = None,
    mix_stale: bool = False,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """Fused commit+mix+SGD whose buffer reads stay in the CARRIER.

    `cands`/`lasts` are per-neighbor bf16/int8 carriers, `mix_scales`
    the per-position f32 dequant scales of the values the mix consumes
    (the COMMITTED scales for mix_stale=False, the stale buffers' for
    mix_stale=True; None for bf16, whose dequant is the bare upcast).
    Returns (p_new, trace_new, committed_carrier_bufs) — the buffers
    come back in the carrier dtype."""
    nb = len(cands)
    assert len(keeps) == nb and len(lasts) == nb
    keeps = tuple(k.astype(jnp.float32) for k in keeps)
    outs = _fused_commit_carrier_flat(
        p, g, t, tuple(cands), keeps, tuple(lasts),
        None if mix_scales is None else tuple(mix_scales),
        lr=float(lr), momentum=float(momentum), w=float(mix_weight),
        nb=nb, mix_stale=bool(mix_stale), interpret=interpret,
    )
    return outs[0], outs[1], tuple(outs[2:])


def mix_commit_carrier_reference(
    p: jnp.ndarray,
    cands: Tuple[jnp.ndarray, ...],
    keeps: Tuple[jnp.ndarray, ...],
    lasts: Tuple[jnp.ndarray, ...],
    g: jnp.ndarray,
    t: jnp.ndarray,
    lr: float,
    momentum: float,
    mix_weight: float,
    mix_scales: Optional[Tuple[jnp.ndarray, ...]] = None,
    mix_stale: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """jnp twin of the carrier kernel (also the non-TPU path)."""
    bufs = tuple(
        jnp.where(k.astype(jnp.float32) > 0, c, l)
        for c, k, l in zip(cands, keeps, lasts)
    )
    acc = p
    for i in range(len(bufs)):
        val = (lasts[i] if mix_stale else bufs[i]).astype(jnp.float32)
        if mix_scales is not None:
            val = val * mix_scales[i]
        acc = acc + val
    mixed = acc * mix_weight
    trace = momentum * t + g
    return mixed - lr * trace, trace, bufs
