"""Dataset loading: MNIST idx, CIFAR-10 binary, and synthetic fallback.

The reference hard-codes cluster AFS paths (dmnist/cent/cent.cpp:53,
dcifar10/common/custom.hpp:11-12) and reads MNIST via libtorch's built-in
loader / CIFAR-10 via an OpenCV JPEG walker (custom.hpp:26-122). Here:

  * `load_mnist(dir)` reads the standard idx files (train-images-idx3-ubyte
    etc., gz or raw) and applies the reference's Normalize(0.1307, 0.3081)
    (cent.cpp:55).
  * `load_cifar10(dir)` reads the canonical binary batches
    (data_batch_{1..5}.bin / test_batch.bin) or the python-pickle version,
    scaled to [0,1] float32 like OpenCV's CV_32FC3 convertTo path.
  * `synthetic_dataset(...)` builds a deterministic, *learnable* stand-in
    (random inputs labeled by a fixed random teacher network) so every
    algorithm, test, and benchmark runs hermetically when no dataset is on
    disk (this environment has no network egress).

All loaders return numpy arrays (images NHWC float32, labels int32); the
training layer owns device placement.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct as _struct
from typing import Optional, Tuple

import numpy as np

MNIST_MEAN, MNIST_STD = 0.1307, 0.3081


def _open_maybe_gz(path: str):
    if os.path.exists(path):
        return open(path, "rb")
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    raise FileNotFoundError(path)


def _read_idx(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        data = f.read()
    magic, = _struct.unpack(">I", data[:4])
    ndim = magic & 0xFF
    dims = _struct.unpack(">" + "I" * ndim, data[4 : 4 + 4 * ndim])
    return np.frombuffer(data, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def load_mnist(
    data_dir: str, split: str = "train", normalize: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    prefix = "train" if split == "train" else "t10k"
    ipath = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte")
    lpath = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte")

    # fast path: native idx reader (raw files only; gz falls through)
    from eventgrad_tpu.data import native

    mean, std = (MNIST_MEAN, MNIST_STD) if normalize else (0.0, 0.0)
    out = native.load_mnist_idx(ipath, lpath, mean, std)
    if out is not None:
        return out

    images = _read_idx(ipath)
    labels = _read_idx(lpath)
    x = images.astype(np.float32)[..., None] / 255.0
    if normalize:
        x = (x - MNIST_MEAN) / MNIST_STD
    return x, labels.astype(np.int32)


def load_cifar10(data_dir: str, split: str = "train") -> Tuple[np.ndarray, np.ndarray]:
    bin_names = (
        [f"data_batch_{i}.bin" for i in range(1, 6)]
        if split == "train"
        else ["test_batch.bin"]
    )
    if os.path.exists(os.path.join(data_dir, bin_names[0])):
        paths = [os.path.join(data_dir, n) for n in bin_names]

        # fast path: native binary reader
        from eventgrad_tpu.data import native

        out = native.load_cifar10_bin(paths)
        if out is not None:
            return out

        xs, ys = [], []
        for path in paths:
            raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0])
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        x = np.concatenate(xs).astype(np.float32) / 255.0
        return x, np.concatenate(ys).astype(np.int32)

    # python pickle version (cifar-10-batches-py)
    py_names = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    xs, ys = [], []
    for name in py_names:
        with open(os.path.join(data_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(
            np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        )
        ys.append(np.asarray(d[b"labels"], np.int64))
    x = np.concatenate(xs).astype(np.float32) / 255.0
    return x, np.concatenate(ys).astype(np.int32)


def synthetic_dataset(
    n: int,
    image_shape: Tuple[int, int, int] = (28, 28, 1),
    num_classes: int = 10,
    seed: int = 0,
    split: str = "train",
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable classification task.

    Inputs are unit Gaussians; labels come from a fixed random linear teacher
    over the flattened input, so models genuinely reduce loss and the event
    dynamics (norm drift, threshold adaptation) exercise realistically.
    `split` offsets the sample stream so train/test are disjoint.
    """
    rng = np.random.default_rng(seed)
    teacher = rng.standard_normal((int(np.prod(image_shape)), num_classes)).astype(
        np.float32
    )
    offset = 0 if split == "train" else 1_000_003
    sample_rng = np.random.default_rng(seed + 17 + offset)
    x = sample_rng.standard_normal((n,) + tuple(image_shape)).astype(np.float32)
    logits = x.reshape(n, -1) @ teacher
    y = np.argmax(logits, axis=1).astype(np.int32)
    return x, y


def load_or_synthesize(
    dataset: str, data_dir: Optional[str], split: str, n_synth: int = 4096, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Try real data, fall back to the synthetic stand-in of matching shape."""
    shape = (28, 28, 1) if dataset == "mnist" else (32, 32, 3)
    if data_dir:
        try:
            if dataset == "mnist":
                return load_mnist(data_dir, split)
            if dataset == "cifar10":
                return load_cifar10(data_dir, split)
        except (FileNotFoundError, OSError):
            pass
    return synthetic_dataset(n_synth, shape, seed=seed, split=split)
