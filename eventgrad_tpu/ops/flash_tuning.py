"""Per-shape dispatch plan for the flash-attention kernels.

The round-2 TPU capture (KERNELS_TPU.json) showed the fixed 128-row block
losing to XLA's materialized-score attention at some sequence lengths
(0.67x at T=512 fwd) while winning at others (1.35x at T=1024) — kernel
win/loss is a per-shape property. VERDICT r2 item 4's contract: every
*used* config must beat XLA or demote itself per shape, with the decision
recorded.

`plan(t, mode)` returns (use_pallas, block_rows) for a sequence length:

  * measured entries come from `flash_tuning.json` next to this module —
    written from an on-chip `bench_kernels.py --tune` sweep (block sizes x
    sequence lengths, pallas vs XLA), committed with the capture;
  * with no table at all, every shape defaults to the Pallas kernel at
    DEFAULT_BLOCK.

Table format (flash_tuning.json):
  {"platform": "...", "entries": [
     {"t": 512, "mode": "fwd", "pallas": false, "block": 128,
      "pallas_ms": ..., "xla_ms": ...}, ...]}

Lookup: exact t match first; within the measured range, the nearest
LARGER measured t's verdict applies (attention cost grows with t^2 — the
larger neighbor's trade-off is the safer read). Beyond the measured range
the kernel runs regardless of the largest entry's win/loss verdict —
Pallas keeps VMEM residency O(block) where XLA materializes the O(t^2)
score tensor, so at unmeasured long t the asymptotics, not an
extrapolated demote, decide — UNLESS no Pallas config even compiled at
the largest measured t (a hard failure extrapolates as a failure).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Optional, Tuple

DEFAULT_BLOCK = 128
MODES = ("fwd", "fwd_bwd")

_TABLE_PATH = os.path.join(os.path.dirname(__file__), "flash_tuning.json")


@functools.lru_cache(maxsize=1)
def _table():
    try:
        with open(_TABLE_PATH) as f:
            data = json.load(f)
        entries = data.get("entries", [])
        return [e for e in entries if e.get("mode") in MODES]
    except (OSError, json.JSONDecodeError):
        return []


def plan(t: int, mode: str = "fwd_bwd") -> Tuple[bool, int]:
    """(use_pallas, block_rows) for sequence length `t`.

    `mode`: "fwd" for inference-only attention, "fwd_bwd" for training
    (the backward kernels' measurement governs, since that is where the
    step time goes).
    """
    assert mode in MODES, mode
    entries = [e for e in _table() if e["mode"] == mode]
    if not entries:
        return True, DEFAULT_BLOCK
    exact = [e for e in entries if e["t"] == t]
    if exact:
        e = exact[0]
        return bool(e["pallas"]), int(e.get("block", DEFAULT_BLOCK))
    # within the measured range: interpolate from the nearest larger
    # neighbor (attention cost grows with t^2 — its trade-off is the
    # safer read). BEYOND the measured range the kernel always runs:
    # Pallas keeps VMEM residency O(block) while XLA materializes the
    # O(t^2) score tensor, so at unmeasured long context the asymptotics
    # — not an extrapolated demote verdict — decide.
    larger = sorted((e for e in entries if e["t"] > t), key=lambda e: e["t"])
    if larger:
        e = larger[0]
        return bool(e["pallas"]), int(e.get("block", DEFAULT_BLOCK))
    e = max(entries, key=lambda e: e["t"])
    if e.get("pallas_ms") is None:
        # at the largest measured t NO Pallas block even compiled/ran on
        # this chip — a hard failure, not a speed loss; never extrapolate
        # the kernel into longer context it was observed broken at
        return False, DEFAULT_BLOCK
    return True, int(e.get("block", DEFAULT_BLOCK))


def override(t: Optional[int] = None) -> Optional[int]:
    """EG_FLASH_BLOCK env override (manual experiments); None if unset."""
    v = os.environ.get("EG_FLASH_BLOCK")
    return int(v) if v else None
