from eventgrad_tpu.utils import trees
