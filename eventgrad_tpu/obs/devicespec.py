"""Device peak specs: the denominators of MFU and the roofline.

One table owns (peak FLOP/s, peak HBM bytes/s) per device kind —
`utils.flops.chip_peak_flops` reads its TPU peaks from here, and
`obs.costmodel.roofline` divides its analytic FLOP/byte counts by the
same numbers, so the MFU in bench.py and the roofline position in the
perf ledger can never disagree about what "peak" means.

TPU entries carry the public peak dense-matmul throughput (bf16) and the
public HBM bandwidth of the generation. Non-TPU backends fall back to
GENERIC_CPU, a NOMINAL spec (order-of-magnitude single-core numbers,
`nominal=True`): the CPU "MFU" it yields is a cross-round regression
TRACKING number for the perf ledger — comparable between rounds on the
same container, never a hardware-utilization claim. Every consumer that
prints a nominal-spec MFU must carry the spec name next to it
(`device_spec` in obs.schema.PERF_FIELDS) so a reader can tell the two
apart.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Peak throughputs of one device (a single chip / a single core)."""

    name: str
    #: peak dense-matmul FLOP/s (bf16 on TPU; nominal f32 on generic-cpu)
    peak_flops: float
    #: peak main-memory bandwidth, bytes/s (HBM on TPU; DRAM on CPU)
    peak_hbm_bytes_per_s: float
    #: True = documented placeholder numbers for regression tracking,
    #: not a measured/published hardware peak
    nominal: bool = False

    @property
    def ridge_intensity(self) -> float:
        """FLOP/byte where the roofline's memory slope meets the compute
        ceiling: below it a kernel is bandwidth-bound, above compute-bound."""
        return self.peak_flops / self.peak_hbm_bytes_per_s


#: device-kind substring -> spec, most-specific first (same matching rule
#: as the pre-existing utils.flops.PEAK_FLOPS_BY_KIND, which now reads
#: its peaks from this table)
TPU_SPECS: Tuple[Tuple[str, DeviceSpec], ...] = (
    ("v5 lite", DeviceSpec("tpu-v5e", 197e12, 819e9)),
    ("v5litepod", DeviceSpec("tpu-v5e", 197e12, 819e9)),
    ("v5e", DeviceSpec("tpu-v5e", 197e12, 819e9)),
    ("v5p", DeviceSpec("tpu-v5p", 459e12, 2765e9)),
    ("v6 lite", DeviceSpec("tpu-v6e", 918e12, 1640e9)),
    ("v6e", DeviceSpec("tpu-v6e", 918e12, 1640e9)),
    ("v4", DeviceSpec("tpu-v4", 275e12, 1228e9)),
    ("v3", DeviceSpec("tpu-v3", 123e12, 900e9)),
    ("v2", DeviceSpec("tpu-v2", 46e12, 700e9)),
)

#: the non-TPU fallback: one nominal modern core (~50 f32 GFLOP/s, ~20
#: GB/s effective stream bandwidth). Deliberately round placeholder
#: numbers — they make CPU MFU/roofline figures comparable ACROSS ROUNDS
#: on the same container (the ledger's regression signal), nothing more.
GENERIC_CPU = DeviceSpec("generic-cpu", 5e10, 2e10, nominal=True)


def spec_for_kind(platform: Optional[str], device_kind: Optional[str]) -> DeviceSpec:
    """Spec from the (platform, device_kind) STRINGS a committed record
    carries — so the perf ledger can assign peaks to rounds captured on
    hardware this process doesn't have. Same matching rule as
    `device_spec`; unknown kinds and non-TPU platforms get GENERIC_CPU."""
    if platform == "tpu" and device_kind:
        kind = device_kind.lower()
        for sub, spec in TPU_SPECS:
            if sub in kind:
                return spec
    return GENERIC_CPU


def device_spec(device: Optional[Any] = None) -> DeviceSpec:
    """Spec of `device` (default: jax.devices()[0]). Unknown TPU kinds and
    every non-TPU backend get GENERIC_CPU — recognizable by `.nominal`."""
    import jax

    device = device or jax.devices()[0]
    return spec_for_kind(
        getattr(device, "platform", None),
        getattr(device, "device_kind", None),
    )
