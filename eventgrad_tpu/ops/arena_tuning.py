"""Measured dispatch policy for the flat-arena Pallas kernels.

Same mechanism as ops/flash_tuning.py and ops/fused_tuning.py: the
kernels must EARN their place on chip. `bench_kernels.py arena` measures
them against their XLA twins on the active device and (on TPU) writes
`arena_tuning.json` next to this module; the train step consults the
table at build time.

Policies:

  * `masked_wire_ok()` — the masked-wire builder kernel
    (ops/event_engine.masked_wire). The flat exchange's inline form is
    already a single fused mask-into-concat pass under XLA, so the
    kernel only earns a wire-builder slot with a MEASURED win (no
    table -> False); EG_FORCE_ARENA_PALLAS=1 overrides for manual
    experiments.
  * `mix_commit_ok()` — the fused commit+mix+SGD tail
    (ops/arena_update.fused_mix_commit). The arena hands it the shape
    the fused family measured best (one big lane-aligned flat buffer —
    KERNELS_TPU.json's ~1.0x single-leaf case, with the commit pass
    fused in on top), and it is opt-in via train(fused_update=True)
    like fused_mix_sgd, so it runs unless a measurement demotes it.
"""

from __future__ import annotations

import functools
import json
import os

_TABLE_PATH = os.path.join(os.path.dirname(__file__), "arena_tuning.json")


@functools.lru_cache(maxsize=1)
def _table():
    try:
        with open(_TABLE_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def masked_wire_ok() -> bool:
    """Run the Pallas masked-wire builder in the flat exchange?"""
    if os.environ.get("EG_FORCE_ARENA_PALLAS") == "1":
        return True
    ratio = _table().get("masked_wire_speedup")
    return ratio is not None and float(ratio) >= 1.0


def mix_commit_ok() -> bool:
    """Run the fused commit+mix+SGD kernel in the arena fused tail?"""
    if os.environ.get("EG_FORCE_ARENA_PALLAS") == "1":
        return True
    ratio = _table().get("mix_commit_speedup")
    return ratio is None or float(ratio) >= 1.0


def bucketed_tail_ok() -> bool:
    """Run the fused commit+mix+SGD tail PER BUCKET under the bucketed
    gossip schedule (train/steps.py bucketed= + fused_sgd)?

    The per-bucket form launches K kernels instead of one — the
    many-launch regime the fused family measured as a LOSS on trees
    (ops/fused_tuning.py), so it must earn its place with a measured
    `bucketed_tail_speedup` entry (written by `bench_kernels.py
    bucketed` on the active device). No table / no entry -> False: an
    unmeasured shape falls back to the MONOLITHIC fused path instead of
    guessing (train/loop.py demotes bucketed to K=1 with a warning
    there). EG_FORCE_ARENA_PALLAS=1 overrides for manual experiments."""
    if os.environ.get("EG_FORCE_ARENA_PALLAS") == "1":
        return True
    ratio = _table().get("bucketed_tail_speedup")
    return ratio is not None and float(ratio) >= 1.0
