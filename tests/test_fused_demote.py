"""Auto-demotion of the fused_mix_sgd tail on measured-losing trees
(VERDICT r4 item 6: 0.87x on the 86-leaf ResNet tree -> the dispatch must
measure-and-demote like flash_tuning does)."""

import json

import jax
import numpy as np
import optax
import pytest

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.models import MLP
from eventgrad_tpu.ops import fused_tuning
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step


@pytest.fixture
def table(tmp_path, monkeypatch):
    """Point the policy at a scratch table; clear the lru cache around it."""
    path = tmp_path / "fused_tuning.json"

    def write(rec):
        path.write_text(json.dumps(rec))
        fused_tuning._table.cache_clear()

    monkeypatch.setattr(fused_tuning, "_TABLE_PATH", str(path))
    fused_tuning._table.cache_clear()
    yield write
    fused_tuning._table.cache_clear()


def test_policy_verdicts(table, monkeypatch):
    monkeypatch.delenv("EG_FORCE_FUSED", raising=False)
    # no table: legacy opt-in behavior (kernel runs)
    assert fused_tuning.tree_fused_ok(86)
    # measured loss: demote multi-leaf trees, keep small ones
    table({"tree_speedup": 0.87})
    assert not fused_tuning.tree_fused_ok(86)
    assert fused_tuning.tree_fused_ok(fused_tuning.SMALL_TREE_LEAVES)
    # measured win: keep
    table({"tree_speedup": 1.12})
    assert fused_tuning.tree_fused_ok(86)
    # manual override
    table({"tree_speedup": 0.5})
    monkeypatch.setenv("EG_FORCE_FUSED", "1")
    assert fused_tuning.tree_fused_ok(86)


def test_demoted_step_equals_optax_tail(table, monkeypatch):
    """With a losing table entry, fused_update=True silently takes the
    optax tail — bitwise the same step as fused off (MLP has 6 leaves,
    so shrink the small-tree floor to cover it)."""
    monkeypatch.delenv("EG_FORCE_FUSED", raising=False)
    monkeypatch.setattr(fused_tuning, "SMALL_TREE_LEAVES", 0)
    table({"tree_speedup": 0.87})
    topo = Ring(4)
    model = MLP(hidden=16)
    tx = optax.sgd(0.05, momentum=0.9)
    x, y = synthetic_dataset(4 * 8, (28, 28, 1), seed=3)
    xb, yb = batched_epoch(x, y, 4, 8)

    outs = []
    for fused in (None, (0.05, 0.9)):
        state = init_train_state(model, (28, 28, 1), tx, topo, "dpsgd")
        step = make_train_step(model, tx, topo, "dpsgd", fused_sgd=fused)
        lifted = jax.jit(spmd(step, topo))
        state, _ = lifted(state, (xb[:, 0], yb[:, 0]))
        outs.append(state)
    for a, b in zip(jax.tree.leaves(outs[0].params),
                    jax.tree.leaves(outs[1].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
