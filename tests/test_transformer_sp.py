"""Hybrid-mesh training: EventGraD gossip across dp × ring-attention SP.

The strongest structural test in the suite: a Transformer LM whose sequence
is sharded over an `sp` mesh axis (ring attention) while its parameters
gossip event-triggered over a `dp` ring — both collectives in one jitted
step on a 4x2 mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from eventgrad_tpu.models.transformer import TransformerLM
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import build_mesh, spmd
from eventgrad_tpu.parallel.topology import Ring, Topology
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step

VOCAB, DIM, HEADS, LAYERS = 64, 32, 4, 2
B, T_GLOBAL = 2, 32


def _lm_batch(key, n_ranks_dp, n_sp, t_local):
    """Token batches: dp ranks get different sequences; sp ranks share one
    sequence, each holding its chunk. targets are the next token globally."""
    toks = jax.random.randint(key, (n_ranks_dp, B, T_GLOBAL), 0, VOCAB)
    tgts = jnp.roll(toks, -1, axis=-1)
    xs, ys = [], []
    for dp in range(n_ranks_dp):
        for sp in range(n_sp):
            sl = slice(sp * t_local, (sp + 1) * t_local)
            xs.append(toks[dp, :, sl])
            ys.append(tgts[dp, :, sl])
    return jnp.stack(xs), jnp.stack(ys)


def test_transformer_full_attention_trains():
    topo = Ring(4)
    model = TransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                          max_len=T_GLOBAL)
    tx = optax.sgd(0.1)
    state = init_train_state(
        model, (T_GLOBAL,), tx, topo, "dpsgd", input_dtype=jnp.int32
    )
    step = make_train_step(model, tx, topo, "dpsgd")
    lifted = jax.jit(spmd(step, topo))

    key = jax.random.PRNGKey(3)
    toks = jax.random.randint(key, (4, B, T_GLOBAL), 0, VOCAB)
    tgts = jnp.roll(toks, -1, axis=-1)
    losses = []
    for i in range(8):
        state, m = lifted(state, (toks, tgts))
        losses.append(float(np.asarray(m["loss"]).mean()))
    assert losses[-1] < losses[0]  # memorizes the fixed batch


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_hybrid_dp_gossip_sp_attention(attn):
    n_dp, n_sp = 4, 2
    t_local = T_GLOBAL // n_sp
    topo = Topology(axes=("dp", "sp"), shape=(n_dp, n_sp), gossip_axes=("dp",))
    assert topo.aux_axes == ("sp",)
    assert len(topo.neighbors) == 2  # dp ring only

    model = TransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                          max_len=T_GLOBAL, attn=attn, topo=topo, sp_axis="sp")
    tx = optax.sgd(0.1)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)

    # init params outside the mesh context with the full-attention twin
    twin = TransformerLM(vocab=VOCAB, dim=DIM, n_heads=HEADS, n_layers=LAYERS,
                         max_len=T_GLOBAL)
    variables = twin.init(jax.random.PRNGKey(0), jnp.zeros((1, t_local), jnp.int32))
    from eventgrad_tpu.parallel.events import EventState
    from eventgrad_tpu.parallel.spmd import stack_for_ranks
    from eventgrad_tpu.train.state import TrainState

    per_rank = TrainState(
        params=variables["params"],
        opt_state=tx.init(variables["params"]),
        batch_stats={},
        pass_num=jnp.zeros((), jnp.int32),
        rng=jax.random.PRNGKey(1),
        event=EventState.init(variables["params"], topo, cfg),
        sparse=None,
    )
    state = stack_for_ranks(per_rank, topo)
    state = state.replace(rng=jax.random.split(jax.random.PRNGKey(2), topo.n_ranks))

    step = make_train_step(model, tx, topo, "eventgrad", event_cfg=cfg)
    lifted = jax.jit(spmd(step, topo))

    xb, yb = _lm_batch(jax.random.PRNGKey(5), n_dp, n_sp, t_local)
    losses = []
    for i in range(6):
        state, m = lifted(state, (xb, yb))
        losses.append(float(np.asarray(m["loss"]).mean()))
    assert losses[-1] < losses[0]

    # sp ranks must remain parameter-identical (they pmean grads and receive
    # identical gossip); dp gossip must have fired some events
    p = jax.tree.leaves(state.params)[0].reshape(n_dp, n_sp, -1)
    np.testing.assert_allclose(np.asarray(p[:, 0]), np.asarray(p[:, 1]), atol=1e-6)
    assert int(np.asarray(state.event.num_events).sum()) > 0
