"""JIT-compatible fault injection inside the gossip mixing step.

A dropped message is implemented as "the receiver keeps its stale buffer":
`collectives.masked_neighbor_vals` already selects
`where(neighbor_fired, payload, stale)` per edge, so injection just ANDs a
per-edge `delivered` bit into that select — one fused program handles both
event-triggered silence and injected loss, and an injected drop is
*bitwise-identical* to an event that did not fire (tests/test_chaos.py).

Determinism: the delivered bit for (pass, receiver rank, edge index) is a
pure function of the schedule seed via counter-style `fold_in` chains —
no carried RNG state, so the scan body stays shape-stable and the whole
schedule replays from its serialized form. `delivery_table` computes the
same bits on the host (same ops, same seeds) for replay analysis and
tests.

Everything here runs under `jax.vmap(axis_name=...)` and `jax.shard_map`
alike: rank identity comes from `lax.axis_index` on the topology's named
axes, exactly like the collectives.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.parallel.topology import Topology

#: fold_in tags separating the independent per-schedule random streams
#: (drop draws vs. delivery-thinning phases); arbitrary but frozen —
#: changing them changes every serialized schedule's replay.
_TAG_DROP = 0x5EED
_TAG_PHASE = 0x9A5E


def rank_and_sources(topo: Topology) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(my flat rank, per-edge source flat rank [n_neighbors]) from inside
    the SPMD context — the traced twin of `Topology.neighbor_source`'s
    row-major arithmetic."""
    coords = [lax.axis_index(a) for a in topo.axes]

    def ravel(cs) -> jnp.ndarray:
        r = jnp.int32(0)
        for c, size in zip(cs, topo.shape):
            r = r * size + c.astype(jnp.int32)
        return r

    srcs = []
    for nb in topo.neighbors:
        ax = topo.axes.index(nb.axis)
        shifted = list(coords)
        shifted[ax] = (coords[ax] + nb.offset) % topo.shape[ax]
        srcs.append(ravel(shifted))
    me = ravel(coords)
    if not srcs:  # neighborless topology: keep a well-formed empty vector
        return me, jnp.zeros((0,), jnp.int32)
    return me, jnp.stack(srcs)


def delivery_mask(
    sched: ChaosSchedule,
    topo: Topology,
    pass_num: jnp.ndarray,
    rank: Optional[jnp.ndarray] = None,
    srcs: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Per-edge delivered bits (bool [n_neighbors]) for the current pass.

    Inside the SPMD step leave `rank`/`srcs` None (derived from
    `lax.axis_index`); the host-side `delivery_table` passes them
    explicitly so both paths run the identical fold_in chain. A True bit
    means "a message sent on this edge this pass arrives"; the event
    fire bit still decides whether anything WAS sent.
    """
    n_nb = topo.n_neighbors
    if rank is None or srcs is None:
        rank, srcs = rank_and_sources(topo)
    rank = jnp.asarray(rank, jnp.int32)
    srcs = jnp.asarray(srcs, jnp.int32)
    pass_i = jnp.asarray(pass_num, jnp.int32)
    key = jax.random.PRNGKey(sched.seed)

    # iid drop draw, one uniform per (pass, receiver, edge)
    u = jax.random.uniform(
        jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(key, _TAG_DROP), pass_i),
            rank,
        ),
        (n_nb,),
    )
    p = jnp.full((n_nb,), sched.drop_p, jnp.float32)
    for w in sched.flaky:
        in_window = (pass_i >= w.start_pass) & (pass_i < w.end_pass)
        p = jnp.where(in_window, jnp.maximum(p, jnp.float32(w.drop_p)), p)
    deliver = u >= p  # u in [0, 1): drop_p == 0 can never drop

    if sched.deliver_every > 1:
        # k-pass thinning: each edge refreshes only when the pass hits its
        # seed-derived phase — staleness up to k-1 extra passes
        phase = jax.random.randint(
            jax.random.fold_in(
                jax.random.fold_in(key, _TAG_PHASE), rank
            ),
            (n_nb,), 0, sched.deliver_every,
        )
        deliver = deliver & ((pass_i % sched.deliver_every) == phase)

    for dead_rank, t in sched.death:
        dead_now = pass_i >= t
        # a dead peer neither sends (its outgoing edges drop) nor receives
        # (every edge INTO it drops too); its rows are excluded at
        # heal/consensus time (policy.heal_ring, survivor evaluation)
        deliver = deliver & ~(dead_now & (srcs == dead_rank))
        deliver = deliver & ~(dead_now & (rank == dead_rank))
    return deliver


def delivery_table(
    sched: ChaosSchedule, topo: Topology, n_passes: int, start_pass: int = 1
) -> np.ndarray:
    """Host-side replay of the full schedule: bool [n_passes, n_ranks,
    n_neighbors], pass axis starting at `start_pass` (passes are 1-based
    in the step, event.cpp:273). Runs the exact fold_in chain of
    `delivery_mask`, so it IS the ground truth of what a run saw."""
    srcs = np.array(
        [
            [topo.neighbor_source(r, nb) for nb in topo.neighbors]
            for r in range(topo.n_ranks)
        ],
        np.int32,
    ).reshape(topo.n_ranks, topo.n_neighbors)
    out = np.zeros((n_passes, topo.n_ranks, topo.n_neighbors), bool)
    fn = jax.jit(
        lambda p, r, s: delivery_mask(sched, topo, p, rank=r, srcs=s),
        static_argnums=(),
    )
    for pi in range(n_passes):
        for r in range(topo.n_ranks):
            out[pi, r] = np.asarray(
                fn(jnp.int32(start_pass + pi), jnp.int32(r), srcs[r])
            )
    return out
