"""Collective primitives for decentralized training, on named axes.

The reference uses three MPI paradigms; each maps to one function here:

  * `MPI_Allreduce` of gradients (/root/reference/dmnist/cent/cent.cpp:135-142)
     -> `allreduce_mean`  (jax.lax.pmean, XLA all-reduce over ICI)
  * two-sided ring sends `MPI_Issend`/`MPI_Recv`
    (/root/reference/dmnist/decent/decent.cpp:192-208)
     -> `neighbor_vals` (jax.lax.ppermute ring shift)
  * one-sided event-triggered `MPI_Put` into an RMA window
    (/root/reference/dmnist/event/event.cpp:346-360)
     -> two SPMD-legal forms of "maybe send":
        `masked_neighbor_vals`: ppermute of (fire-bit, zero-masked payload);
        the receiver keeps its previous buffer when the bit is off. The
        collective still moves the FULL dense payload — its savings are an
        accounting metric, not wire bytes.
        `compact_neighbor_vals`: ppermute of a fixed-capacity compacted
        buffer holding only the fired leaves' elements — event sparsity as
        real ICI/DCN bytes (see docs/compaction.md). True wire savings
        materialize here, via sparsification (sparsify.py), or through the
        compressed wire dtypes (bf16/int8).

All functions operate on pytrees and work identically under `jax.shard_map`
(real mesh) and `jax.vmap(axis_name=...)` (single-chip simulation).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree

from eventgrad_tpu.parallel import arena
from eventgrad_tpu.parallel.topology import NeighborSpec, Topology


def allreduce_mean(tree: Any, topo: Topology) -> Any:
    """Mean over every rank in the topology (all axes)."""
    for axis in topo.axes:
        tree = lax.pmean(tree, axis)
    return tree


def allreduce_sum(tree: Any, topo: Topology) -> Any:
    for axis in topo.axes:
        tree = lax.psum(tree, axis)
    return tree


def recv_from(tree: Any, topo: Topology, nb: NeighborSpec) -> Any:
    """Each rank receives the pytree held by the rank `nb.offset` away along
    `nb.axis` (offset -1 == "from my left neighbor"). One ppermute per leaf."""
    n = topo.axis_size(nb.axis)
    perm = [((r + nb.offset) % n, r) for r in range(n)]
    return jax.tree.map(lambda x: lax.ppermute(x, nb.axis, perm), tree)


def _packable(tree: Any) -> bool:
    """One contiguous wire buffer needs a single dtype across leaves."""
    leaves = jax.tree.leaves(tree)
    return len(leaves) > 1 and all(l.dtype == leaves[0].dtype for l in leaves)


#: wire modes: None = native dtype; "bf16" = bfloat16 transfer (2 B/elem);
#: "int8" = per-leaf absmax-scaled int8 transfer (1 B/elem + one f32
#: scale per leaf). Local state always stays full precision.
WIRE_MODES = (None, "bf16", "int8")

#: wire bytes per payload element (the reference's f32 MPI wire is the
#: 4-byte baseline — deliberately a constant, not the param dtype's
#: itemsize, so accounting and wire-real numbers stay comparable across
#: models; see train/steps.py)
WIRE_VAL_BYTES = {None: 4.0, "bf16": 2.0, "int8": 1.0}


def _wire_out(x: Any, wire) -> Any:
    """Downcast a wire payload (array or pytree of floats) for transfer
    (bf16 mode; int8 has its own quantize/dequantize pair below)."""
    dt = jnp.bfloat16 if wire == "bf16" else None
    cast = lambda a: (
        a.astype(dt)
        if dt is not None and jnp.issubdtype(a.dtype, jnp.floating)
        and a.dtype != dt
        else a
    )
    return jax.tree.map(cast, x)


def _wire_in(x: Any, like: Any) -> Any:
    """Upcast received payload back to the local dtypes."""
    return jax.tree.map(lambda a, ref: a.astype(ref.dtype), x, like)


def _contract_safe(scale):
    """Truncate an f32 quantization scale to 17 significand bits (clear
    the low 7 stored mantissa bits), making every dequantized product
    EXACTLY representable: |q| <= 127 carries <= 7 significand bits, so
    q * scale needs <= 7 + 17 = 24 — f32's full significand. An exact
    product renders the receive path contraction-invariant: fma(q, s,
    acc) and add(round(q*s), acc) round identically, so the compiled
    mix is bitwise the same whether or not the backend fuses the
    dequant multiply into the gossip adds — which XLA:CPU decides
    differently for the vmap and shard_map lifts of the same step
    (tests/test_mesh_parity.py int8 cells caught it; XLA strips
    `optimization_barrier` on CPU, so barriers cannot pin it). Cost:
    <= 2^-17 relative scale perturbation — float noise against int8's
    ~2^-8 quantization error (values that now round just past +/-127
    hit the existing clip)."""
    bits = lax.bitcast_convert_type(
        scale.astype(jnp.float32), jnp.int32
    )
    return lax.bitcast_convert_type(
        bits & jnp.int32(~0x7F), jnp.float32
    )


def _int8_scales(tree: Any) -> Any:
    """Per-leaf absmax/127 quantization scales (zero-safe,
    contraction-safe — see `_contract_safe`)."""
    return jax.tree.map(
        lambda a: _contract_safe(
            jnp.maximum(jnp.max(jnp.abs(a)), 1e-30) / 127.0
        ),
        tree,
    )


def _int8_quant(tree: Any, scales: Any) -> Any:
    return jax.tree.map(
        lambda a, s: jnp.clip(jnp.round(a / s), -127, 127).astype(jnp.int8),
        tree, scales,
    )


def _int8_dequant(q: Any, scales: Any, like: Any) -> Any:
    return jax.tree.map(
        lambda v, s, ref: (v.astype(ref.dtype) * s.astype(ref.dtype)),
        q, scales, like,
    )


def _int8_encode(tree: Any):
    """Quantize a float pytree for the wire: (int8 tree, stacked per-leaf
    scale vector, the scales' treedef for decode). One codec shared by the
    dense, masked, and sparse exchange paths."""
    scales = _int8_scales(tree)
    q = _int8_quant(tree, scales)
    return q, jnp.stack(jax.tree.leaves(scales)), jax.tree.structure(scales)


def _int8_decode(got_q: Any, got_s: Any, scale_def, like: Any) -> Any:
    got_scales = jax.tree.unflatten(
        scale_def, [got_s[i] for i in range(got_s.shape[0])]
    )
    return _int8_dequant(got_q, got_scales, like)


# ---------------------------------------------------------------------------
# wire integrity: per-neighbor payload checksums (chaos/integrity.py)

def wire_checksum(buf: jnp.ndarray) -> jnp.ndarray:
    """int32 [] checksum of a wire buffer's exact bit pattern.

    The buffer's storage words (f32/bf16 bitcast to ints; int8 as-is)
    sum in int32 with wraparound — integer addition is exact and
    associative, so the sum is bitwise-deterministic under any reduction
    order, identical on sender and receiver, and any single flipped bit
    changes it. Cost: one [n] integer reduction per exchange — the
    integrity engine's entire wire-side overhead."""
    flat = buf.reshape(-1)
    if jnp.issubdtype(flat.dtype, jnp.floating):
        nbits = jnp.finfo(flat.dtype).bits
        int_dt = {16: jnp.int16, 32: jnp.int32, 64: jnp.int64}[nbits]
        flat = lax.bitcast_convert_type(flat, int_dt)
    return jnp.sum(flat.astype(jnp.int32))


def _verify_wire(got_buf, got_csum, decoded, checksum: bool, finite: bool):
    """bool [] per-neighbor wire verdict: checksum of the received buffer
    matches what the sender computed, and (optionally) the DECODED
    payload is finite. Shared by all four event exchanges so tree and
    arena reject bit-identically."""
    ok = jnp.ones((), bool)
    if checksum:
        ok = ok & (wire_checksum(got_buf) == got_csum)
    if finite:
        ok = ok & jnp.all(jnp.isfinite(decoded))
    return ok


# ---------------------------------------------------------------------------
# flat-segment helpers: leaf-major views of the packed (raveled) model

def _leaf_meta(tree: Any) -> Tuple[Tuple[int, ...], Tuple[int, ...], int]:
    """Static leaf-major metadata: (sizes, flat start offsets, total
    elements), in the canonical flatten order `ravel_pytree` uses.
    Served from the lru-cached ArenaSpec (parallel/arena.py) — repeated
    calls on the same structure are cache hits, never re-derivations
    inside a traced step."""
    spec = arena.arena_spec(tree)
    return spec.sizes, spec.starts, spec.n_total


def _segment_ids(sizes: Tuple[int, ...], n: int) -> jnp.ndarray:
    """[n] int32 mapping each flat position to its leaf index. Computed
    from the [L] static ends with one searchsorted (loop-invariant under
    scan) instead of embedding an [n]-sized constant in the program."""
    ends = jnp.asarray(np.cumsum(sizes), jnp.int32)
    return jnp.searchsorted(ends, jnp.arange(n, dtype=jnp.int32), side="right")


def _leaf_absmax(leaves) -> jnp.ndarray:
    """[L] per-leaf absmax — stacked per-leaf reductions (cheaper than a
    flat segment reduction on every backend, and max is exact so the bits
    match either way)."""
    return jnp.stack([jnp.max(jnp.abs(l)) for l in leaves])


def _masked_scales(absmax_vec: jnp.ndarray, fire_vec: jnp.ndarray):
    """Per-leaf int8 wire scales with non-fired leaves bottomed out —
    bitwise what `_int8_scales` computes on the zero-masked pytree (a
    masked leaf's absmax is the raw absmax when fired, 0 when not). ONE
    definition shared by the masked and compact paths so their wires stay
    bit-identical. Contraction-safe like `_int8_scales`."""
    return _contract_safe(
        jnp.maximum(jnp.where(fire_vec, absmax_vec, 0.0), 1e-30) / 127.0
    )


def _int8_encode_flat(masked_flat: jnp.ndarray, scale_vec: jnp.ndarray,
                      seg: jnp.ndarray):
    """Quantize the raveled masked buffer against [L] per-leaf scales:
    bitwise the same values as `_int8_quant` of the equivalent pytree (the
    elementwise quantize divides by the identical per-leaf scalar)."""
    return jnp.clip(
        jnp.round(masked_flat / scale_vec[seg]), -127, 127
    ).astype(jnp.int8)


def neighbor_vals(tree: Any, topo: Topology, wire=None) -> Tuple[Any, ...]:
    """D-PSGD exchange: the full pytree from every gossip neighbor.

    Ring: returns (from_left, from_right) — the payloads of
    decent.cpp:200-205's two blocking receives, with no lockstep deadlock
    risk because ppermute is a collective. Packed: one contiguous wire
    buffer per neighbor regardless of how many parameter tensors the model
    has — the reference pays the per-tensor cost (86 x 2 MPI_Puts per step
    on its ResNet, dcifar10/event/event.cpp:282,320-332); packing amortizes
    every per-message overhead and gives the ICI DMA one large contiguous
    op. The ravel/encode work happens ONCE and is reused for every
    neighbor (2 shifts on a ring, 4 on a torus — the payload is identical,
    only the permutation differs). `wire` ("bf16"/"int8") compresses the
    buffer for the transfer and restores full precision on receipt.
    """
    if wire == "int8":
        q, scale_vec, scale_def = _int8_encode(tree)
        if _packable(q):
            flatq, unravel_q = ravel_pytree(q)

            def one(nb):
                got_q, got_s = recv_from((flatq, scale_vec), topo, nb)
                return _int8_decode(unravel_q(got_q), got_s, scale_def, tree)
        else:

            def one(nb):
                got_tree, got_s = recv_from((q, scale_vec), topo, nb)
                return _int8_decode(got_tree, got_s, scale_def, tree)
    elif _packable(tree):
        flat, unravel = ravel_pytree(tree)
        wire_buf = _wire_out(flat, wire)

        def one(nb):
            got = recv_from(wire_buf, topo, nb)
            return unravel(got.astype(flat.dtype))
    else:
        wire_tree = _wire_out(tree, wire)

        def one(nb):
            return _wire_in(recv_from(wire_tree, topo, nb), tree)

    return tuple(one(nb) for nb in topo.neighbors)


def masked_neighbor_vals(
    payload: Any,
    fire: Any,
    last_bufs: Tuple[Any, ...],
    topo: Topology,
    wire=None,
    deliver: "Optional[Any]" = None,
    checksum: bool = False,
    finite: bool = False,
    corrupt=None,
):
    """Event-triggered exchange (EventGraD's RMA window, deterministic form).

    `payload` — pytree of parameters; `fire` — matching pytree of boolean
    scalars (per-parameter event bits, event.cpp:343); `last_bufs` — one
    pytree per neighbor holding the last received values (the local RMA
    window halves, event.cpp:169-179).

    Returns (new_bufs, recv_fires). For every neighbor:
      new_buf_i = where(neighbor_fired_i, neighbor_payload_i, last_buf_i)
    Non-fired payloads are zero-masked before the shift so the wire content
    is well-defined (and compressible); receivers never read torn data,
    unlike the reference's MPI_LOCK_SHARED races (event.cpp:348-360 vs
    :399-438) — staleness is explicit carried state instead. The masking
    happens directly on the raveled wire buffer (one segment-wise `where`)
    rather than on the pytree, so the step materializes ONE full-model
    buffer, not two. NOTE the dense payload still ships whole: for wire
    bytes that shrink with the fire rate, see `compact_neighbor_vals`.

    `deliver` (chaos.inject): optional bool [n_neighbors] of per-edge
    delivered bits — a False edge keeps its stale buffer even when the
    sender fired, making an injected message drop bitwise-identical to an
    event that did not fire. `recv_fires` stays the RAW sender bits
    (what was on the wire), so callers can count injected drops as
    `sent & ~delivered`.

    Integrity (chaos/integrity.py, packable payloads only): `checksum`
    ships an int32 `wire_checksum` of the wire buffer and verifies it on
    receive; `finite` additionally rejects payloads carrying NaN/Inf;
    `corrupt` is an optional `(edge_index, wire_buf) -> wire_buf`
    transform modeling in-transit corruption (chaos.inject.flip_one_bit),
    applied BEFORE verification — so an injected flip is either caught or
    (with verification off) silently accepted, exactly like a real wire.
    A failed check clears the edge's effective bits like an undelivered
    message: the stale buffer is kept, bitwise the not-fired path. With
    any of the three set, a third return value `oks` (bool [n_neighbors])
    reports the per-edge verdicts; otherwise the return signature is the
    legacy (new_bufs, recv_fires).
    """
    integrity = checksum or finite or corrupt is not None
    fire_leaves, fire_def = jax.tree.flatten(fire)
    fire_vec = jnp.stack(fire_leaves)

    def _unflat_fire(got_vec):
        return jax.tree.unflatten(
            fire_def, [got_vec[i] for i in range(len(fire_leaves))]
        )

    if integrity and not _packable(payload):
        raise ValueError(
            "wire integrity (checksum/finite/corrupt) rides the packed "
            "single-buffer wire and needs a packable (single-dtype, "
            "multi-leaf) payload"
        )
    if _packable(payload):
        # one wire buffer (+ one fire-bit vector) per neighbor: the whole
        # model rides a single ICI transfer instead of one per tensor
        flat, unravel = ravel_pytree(payload)
        sizes, _, _ = _leaf_meta(payload)
        seg = _segment_ids(sizes, flat.size)
        masked_flat = jnp.where(fire_vec[seg], flat, jnp.zeros_like(flat))
        if wire == "int8":
            # quantized wire: int8 buffer + one f32 scale per leaf
            # (non-fired leaves are all-zero, so their scale bottoms out
            # and decodes to 0)
            scale_vec = _masked_scales(
                _leaf_absmax(jax.tree.leaves(payload)), fire_vec
            )
            q = _int8_encode_flat(masked_flat, scale_vec, seg)
            csum = wire_checksum(q) if checksum else None

            def receive(nb, i):
                lanes = (q, scale_vec, fire_vec) + (
                    (csum,) if checksum else ()
                )
                got = recv_from(lanes, topo, nb)
                got_q, got_s, got_vec = got[0], got[1], got[2]
                if corrupt is not None:
                    got_q = corrupt(i, got_q)
                deq = got_q.astype(flat.dtype) * got_s[seg].astype(flat.dtype)
                ok = (
                    _verify_wire(
                        got_q, got[3] if checksum else None, deq,
                        checksum, finite,
                    )
                    if integrity else None
                )
                return unravel(deq), _unflat_fire(got_vec), ok
        else:
            wire_buf = _wire_out(masked_flat, wire)
            csum = wire_checksum(wire_buf) if checksum else None

            def receive(nb, i):
                lanes = (wire_buf, fire_vec) + ((csum,) if checksum else ())
                got = recv_from(lanes, topo, nb)
                got_flat, got_vec = got[0], got[1]
                if corrupt is not None:
                    got_flat = corrupt(i, got_flat)
                deq = got_flat.astype(flat.dtype)
                ok = (
                    _verify_wire(
                        got_flat, got[2] if checksum else None, deq,
                        checksum, finite,
                    )
                    if integrity else None
                )
                return unravel(deq), _unflat_fire(got_vec), ok
    else:
        masked = jax.tree.map(
            lambda p, f: jnp.where(f, p, jnp.zeros_like(p)), payload, fire
        )
        if wire == "int8":
            q, scale_vec, scale_def = _int8_encode(masked)

            def receive(nb, i):
                got_tree, got_s, got_vec = recv_from(
                    (q, scale_vec, fire_vec), topo, nb
                )
                return _int8_decode(got_tree, got_s, scale_def, masked), (
                    _unflat_fire(got_vec)
                ), None
        else:
            wire_tree = _wire_out(masked, wire)

            def receive(nb, i):
                got_p, got_f = recv_from((wire_tree, fire), topo, nb)
                return _wire_in(got_p, masked), got_f, None

    new_bufs, recv_fires, oks = [], [], []
    for i, (nb, last) in enumerate(zip(topo.neighbors, last_bufs)):
        got_p, got_f, ok = receive(nb, i)
        eff_f = got_f
        if ok is not None:
            # a failed wire check is an event that did not fire: the
            # stale buffer survives bitwise (same where as deliver)
            eff_f = jax.tree.map(
                lambda f, _o=ok: jnp.logical_and(f, _o), eff_f
            )
        if deliver is not None:
            eff_f = jax.tree.map(
                lambda f, _d=deliver[i]: jnp.logical_and(f, _d), eff_f
            )
        buf = jax.tree.map(
            lambda f, new, old: jnp.where(f, new, old), eff_f, got_p, last
        )
        new_bufs.append(buf)
        recv_fires.append(got_f)
        oks.append(ok)
    if integrity:
        return tuple(new_bufs), tuple(recv_fires), jnp.stack(oks)
    return tuple(new_bufs), tuple(recv_fires)


# ---------------------------------------------------------------------------
# budgeted compacted exchange: event sparsity as real wire bytes

@functools.lru_cache(maxsize=256)
def _capacity_floor_cached(sizes: Tuple[int, ...]) -> int:
    return max(sizes)


def compact_capacity_floor(sizes) -> int:
    """Smallest legal compact capacity: the largest leaf must fit whole —
    a leaf bigger than the buffer could never ship and would starve.
    lru-cached per sizes tuple (same no-re-derivation rule as
    `_leaf_meta`)."""
    return _capacity_floor_cached(tuple(int(s) for s in sizes))


def bucketed_capacity_floor(buckets) -> int:
    """Smallest legal TOTAL compact capacity under a bucketed schedule:
    every bucket must be able to ship its own largest leaf whole, so the
    floor is the SUM of per-bucket floors — strictly above the monolithic
    floor whenever K > 1 (the price of bucket-local budgets; see
    docs/compaction.md)."""
    return int(sum(b.floor for b in buckets))


def split_capacity(capacity: int, buckets) -> Tuple[int, ...]:
    """Split a total compact capacity into per-bucket static budgets.

    Element-proportional shares with two invariants: each bucket gets at
    least its own floor (largest leaf in the bucket — a smaller budget
    could never ship that leaf and would starve it), and the splits SUM
    EXACTLY to `capacity` (largest-remainder rounding), so the bucketed
    wire moves the same total value lanes the monolithic wire would.
    Deterministic in (capacity, bucket layout) — both static, so the
    split is part of the compiled program, never a recompile source.
    Raises when sum(floors) > capacity: the bucketed schedule needs at
    least `bucketed_capacity_floor` elements."""
    capacity = int(capacity)
    floors = [int(b.floor) for b in buckets]
    if sum(floors) > capacity:
        raise ValueError(
            f"compact capacity {capacity} is below the bucketed floor "
            f"{sum(floors)} (sum of per-bucket largest leaves): some "
            "bucket's largest leaf could never ship and would starve — "
            "raise the capacity or lower the bucket count"
        )
    total = sum(int(b.size) for b in buckets)
    raw = [capacity * int(b.size) / total for b in buckets]
    caps = [max(f, int(r)) for f, r in zip(floors, raw)]
    rem = capacity - sum(caps)
    # largest fractional remainder first; deterministic tie-break on index
    order = sorted(
        range(len(caps)), key=lambda i: (-(raw[i] - int(raw[i])), i)
    )
    j = 0
    while rem != 0:
        i = order[j % len(order)]
        if rem > 0:
            caps[i] += 1
            rem -= 1
        elif caps[i] > floors[i]:
            caps[i] -= 1
            rem += 1
        j += 1
    return tuple(caps)


def choose_capacity(
    n_params: int,
    max_fired_elems: float,
    floor: int,
    headroom: float = 1.25,
    granularity: int = 8192,
) -> int:
    """Static compact-buffer capacity from an observed post-warmup fired
    peak. Bucketed (rounded up to `granularity` elements) so nearby
    observations map to the IDENTICAL capacity — one jit program, no
    recompile churn across dispatches. `headroom` absorbs fire-rate drift;
    underestimates are safe anyway (overflow defers, bounded by
    max_silence). Clamped to [floor, n_params]."""
    want = int(math.ceil(float(max_fired_elems) * float(headroom)))
    c = max(int(floor), want, 1)
    c = ((c + granularity - 1) // granularity) * granularity
    return int(min(int(n_params), c))


def _compact_pack(
    flat: jnp.ndarray,
    fire_vec: jnp.ndarray,
    sizes: Tuple[int, ...],
    starts: Tuple[int, ...],
    capacity: int,
):
    """Gather the fired leaves' elements into a [capacity] wire buffer.

    Offsets are the exclusive cumsum of fired leaf sizes in leaf order
    (jnp.cumsum — static shapes throughout); each packed position finds
    its source leaf with one searchsorted over the fired ends, then a
    single static-shape gather pulls the values. The caller guarantees
    (events.capacity_gate) that the fired total fits. Returns
    (packed [capacity], leaf_id [capacity] — the per-position source leaf,
    reused by the int8 codec for per-position scales)."""
    sizes_arr = jnp.asarray(sizes, jnp.int32)
    starts_arr = jnp.asarray(starts, jnp.int32)
    fired_sizes = jnp.where(fire_vec, sizes_arr, 0)
    ends = jnp.cumsum(fired_sizes)
    offsets = ends - fired_sizes
    j = jnp.arange(capacity, dtype=jnp.int32)
    leaf_id = jnp.minimum(
        jnp.searchsorted(ends, j, side="right"), len(sizes) - 1
    )
    src = starts_arr[leaf_id] + (j - offsets[leaf_id])
    valid = j < ends[-1]
    packed = jnp.where(
        valid,
        flat[jnp.clip(src, 0, flat.size - 1)],
        jnp.zeros((), flat.dtype),
    )
    return packed, leaf_id


def compact_neighbor_vals(
    payload: Any,
    fire: Any,
    last_bufs: Tuple[Any, ...],
    topo: Topology,
    capacity: int,
    wire=None,
    deliver: "Optional[Any]" = None,
    checksum: bool = False,
    finite: bool = False,
    corrupt=None,
):
    """Event-triggered exchange through a fixed-capacity compacted buffer:
    non-fired leaves never touch the interconnect.

    Wire format per neighbor: `(fire_vec [L] bool, packed [capacity])`
    (+ `scales [L] f32` on the int8 wire). The conceptual `offsets` lane
    is implicit — both sides recompute it as the exclusive cumsum of fired
    leaf sizes from the fire bits, bit-identically, so it costs zero wire
    bytes. Receivers slice each fired leaf back out at its offset and
    scatter it into the stale buffer (`where(fire, new, stale)` per leaf);
    semantics are EXACTLY `masked_neighbor_vals` whenever every fired leaf
    fits the budget — proven bitwise in tests/test_compact.py. The caller
    must gate `fire` through `events.capacity_gate(capacity=...)` first;
    a fired total beyond `capacity` would silently truncate.

    `capacity` is static (jit-shape); pick it with `choose_capacity` from
    the observed post-warmup fire rate. Requires a single parameter dtype
    and `capacity >= max leaf size` (a bigger leaf could never ship).
    `deliver` has the masked-path chaos semantics, and `checksum` /
    `finite` / `corrupt` the masked-path integrity semantics (the
    checksum covers the packed wire buffer; a failed check keeps every
    stale leaf, and the third return value `oks` carries the per-edge
    verdicts). See docs/compaction.md.
    """
    integrity = checksum or finite or corrupt is not None
    leaves, treedef = jax.tree.flatten(payload)
    if len(leaves) < 1:
        raise ValueError("compact exchange needs a non-empty payload")
    dt = leaves[0].dtype
    if any(l.dtype != dt for l in leaves):
        raise ValueError(
            "compact wire packs one contiguous buffer and needs a single "
            f"parameter dtype; got {set(str(l.dtype) for l in leaves)}"
        )
    sizes, starts, n_total = _leaf_meta(payload)
    capacity = int(capacity)
    if capacity < compact_capacity_floor(sizes):
        raise ValueError(
            f"compact capacity {capacity} is below the largest leaf "
            f"({compact_capacity_floor(sizes)} elements): that leaf could "
            "never ship and would starve"
        )

    fire_leaves, fire_def = jax.tree.flatten(fire)
    fire_vec = jnp.stack(fire_leaves)

    def _unflat_fire(got_vec):
        return jax.tree.unflatten(
            fire_def, [got_vec[i] for i in range(len(fire_leaves))]
        )

    flat, _ = ravel_pytree(payload)
    packed, leaf_id = _compact_pack(flat, fire_vec, sizes, starts, capacity)
    if wire == "int8":
        # per-leaf scales match the masked path bitwise (_masked_scales:
        # a masked leaf's absmax is the raw absmax when fired, bottomed
        # out when not) — without materializing the masked full model
        scale_vec = _masked_scales(_leaf_absmax(leaves), fire_vec)
        # same codec call as the masked wire — the bit-identity guarantee
        # rests on the two sites sharing one quantize
        wire_packed = _int8_encode_flat(packed, scale_vec, leaf_id)
        csum = wire_checksum(wire_packed) if checksum else None

        def ship(nb):
            lanes = (wire_packed, scale_vec, fire_vec) + (
                (csum,) if checksum else ()
            )
            got = recv_from(lanes, topo, nb)
            return got[0], got[1], got[2], (got[3] if checksum else None)
    else:
        wire_packed = _wire_out(packed, wire)
        csum = wire_checksum(wire_packed) if checksum else None

        def ship(nb):
            lanes = (wire_packed, fire_vec) + ((csum,) if checksum else ())
            got = recv_from(lanes, topo, nb)
            return got[0], None, got[1], (got[2] if checksum else None)

    sizes_arr = jnp.asarray(sizes, jnp.int32)
    new_bufs, recv_fires, oks = [], [], []
    for i, (nb, last) in enumerate(zip(topo.neighbors, last_bufs)):
        got_packed, got_scales, got_vec, got_c = ship(nb)
        if corrupt is not None:
            got_packed = corrupt(i, got_packed)
        ok = None
        if integrity:
            # finite guard: the float wire carries values directly; the
            # int8 wire's values are finite by construction but decode
            # through the f32 scale lane — verify whichever can go bad
            dec = (
                got_packed.astype(jnp.float32)
                if jnp.issubdtype(got_packed.dtype, jnp.floating)
                else got_scales
            )
            ok = _verify_wire(got_packed, got_c, dec, checksum, finite)
        # offsets recomputed from the received fire bits (implicit lane)
        got_fired = jnp.where(got_vec, sizes_arr, 0)
        got_offsets = jnp.cumsum(got_fired) - got_fired
        eff_vec = got_vec
        if ok is not None:
            eff_vec = eff_vec & ok
        if deliver is not None:
            eff_vec = eff_vec & deliver[i]
        stale_leaves, last_def = jax.tree.flatten(last)
        out = []
        for k, stale in enumerate(stale_leaves):
            data = lax.dynamic_slice(got_packed, (got_offsets[k],), (sizes[k],))
            if got_scales is not None:
                val = data.astype(stale.dtype) * got_scales[k].astype(stale.dtype)
            else:
                val = data.astype(stale.dtype)
            out.append(jnp.where(eff_vec[k], val.reshape(stale.shape), stale))
        new_bufs.append(jax.tree.unflatten(last_def, out))
        recv_fires.append(_unflat_fire(got_vec))
        oks.append(ok)
    if integrity:
        return tuple(new_bufs), tuple(recv_fires), jnp.stack(oks)
    return tuple(new_bufs), tuple(recv_fires)


def raw_msg_counts(raws) -> jnp.ndarray:
    """Per-edge census of the neighbor's RAW fire bits on the wire —
    int32 [n_neighbors] message counts for the lifecycle ledger
    (obs/ledger.py). `raws` is the per-neighbor third return of the
    masked/compact exchanges: a [L] bool vector on the flat paths, a
    pytree of per-leaf fire bools on the tree paths, or a tuple of
    per-bucket [L_b] vectors concatenated by the bucketed step — every
    form counts the same leaf-fire messages, whatever else (drops,
    rejections, lag) later happens to them."""
    counts = [
        sum(
            jnp.sum(l.astype(jnp.int32))
            for l in jax.tree.leaves(r)
        )
        for r in raws
    ]
    if not counts:
        return jnp.zeros((0,), jnp.int32)
    return jnp.stack(counts).astype(jnp.int32)


def wire_real_bytes_per_neighbor(
    n_params: int,
    n_leaves: int,
    wire=None,
    compact_capacity: "Optional[int]" = None,
    fire_bits: bool = False,
) -> float:
    """Bytes ONE neighbor exchange actually moves through the collective —
    the SPMD wire truth, as opposed to the reference-MPI accounting model
    of train/steps.py (which charges only fired payloads). Dense/masked
    exchanges ship `n_params` value lanes regardless of fire bits; the
    compacted exchange ships `compact_capacity`. `fire_bits` adds the
    [n_leaves] bool vector of the event paths; the int8 wire always ships
    its [n_leaves] f32 scale vector. Value lanes use the same 4/2/1-byte
    constants as the accounting (WIRE_VAL_BYTES) so the two numbers are
    directly comparable."""
    elems = n_params if compact_capacity is None else int(compact_capacity)
    b = WIRE_VAL_BYTES[wire] * float(elems)
    if fire_bits:
        b += 1.0 * n_leaves
    if wire == "int8":
        b += 4.0 * n_leaves
    return b


def bucketed_wire_real_bytes_per_neighbor(
    buckets, wire=None, caps: "Optional[Tuple[int, ...]]" = None,
) -> Tuple[float, ...]:
    """Per-bucket wire truth of the bucketed gossip schedule: bucket b's
    exchange ships its value lanes (`caps[b]` on the compact wire, the
    bucket's element count otherwise) plus its own fire-bit vector (and
    int8 scale vector). ONE definition shared by the step's
    `sent_bytes_wire_real` metric, the per-bucket metric vector, and the
    trace auditor's expected-lane formula (analysis/audit.py) — lanes ==
    formula == executed, summed over buckets. The masked sum equals the
    monolithic number exactly (same value elements, same [L] fire/scale
    vectors, just segmented); the compact sum equals it whenever
    `split_capacity` preserved the total (it always does)."""
    out = []
    for i, b in enumerate(buckets):
        out.append(wire_real_bytes_per_neighbor(
            int(b.size), b.n_leaves, wire,
            compact_capacity=None if caps is None else int(caps[i]),
            fire_bits=True,
        ))
    return tuple(out)


def fired_wire_bytes_per_neighbor(
    fired_elems: float, fired_leaves: float, wire=None,
) -> float:
    """Bytes of USEFUL (fired) payload one neighbor exchange carries —
    the compact wire's capacity-utilization numerator (vs the
    `wire_real_bytes_per_neighbor` it actually moves, which is the static
    capacity). Same per-element/per-leaf constants as the accounting
    model (WIRE_VAL_BYTES; int8 ships one f32 scale per fired leaf), so
    `fired / capacity` bytes and elements tell the same story. Consumed
    by obs.report's capacity-utilization section."""
    b = WIRE_VAL_BYTES[wire] * float(fired_elems)
    if wire == "int8":
        b += 4.0 * float(fired_leaves)
    return b


def mix(params: Any, bufs: Tuple[Any, ...], topo: Topology) -> Any:
    """Uniform gossip averaging with neighbor buffers:
    p <- (p + sum(bufs)) / (1 + n_neighbors)   (event.cpp:469-471: /3 on a
    ring; /5 on a 2D torus). Stale or zero-initialized buffers participate
    exactly as in the reference (event.cpp:177-179). One fused tree pass:
    per element the adds run in the same left-to-right order as the old
    per-buffer accumulation loop, so the result is bitwise-unchanged while
    XLA sees a single traversal instead of n_neighbors+1. Wire-decode
    multiplies feeding these adds (the int8 dequant) are exact products
    by construction (`_contract_safe`), so FMA fusion cannot change a
    bit on either SPMD lift (tests/test_mesh_parity.py)."""
    w = topo.mix_weight

    def leaf(p, *bs):
        acc = p
        for b in bs:
            acc = jnp.add(acc, b)
        return acc * w

    return jax.tree.map(leaf, params, *bufs)


def mix_weighted(params: Any, bufs: Tuple[Any, ...], gate: Any) -> Any:
    """Gossip averaging over a data-dependent subset of edges:
    p <- (p + sum(gate_i * buf_i)) / (1 + sum(gate_i)).

    `gate` is bool [n_neighbors] (chaos.policy.alive_mask and the lossy
    D-PSGD path): a gated-off edge leaves the mix entirely and the weight
    renormalizes over the survivors, instead of averaging in a frozen
    buffer forever. Fused like `mix` into one weighted tree pass
    (n_neighbors+1 traversals -> 1) with the per-element add order
    preserved. With every gate on this reproduces `mix` bitwise:
    where(True, b, 0) == b, the adds run in the same order, and the f32
    reciprocal of a small integer equals the cast Python double (both
    correctly rounded to the same float32) — guarded by the drop-rate-0
    chaos regression tests."""
    n_alive = jnp.sum(gate.astype(jnp.float32))
    w = 1.0 / (1.0 + n_alive)

    def leaf(p, *bs):
        acc = p
        for i, b in enumerate(bs):
            acc = acc + jnp.where(gate[i], b, jnp.zeros_like(b))
        return acc * w

    return jax.tree.map(leaf, params, *bufs)


# ---------------------------------------------------------------------------
# flat-arena exchange family: the same wire semantics as the pytree
# functions above, with the WIRE and the persistent receive buffers in
# one contiguous [n_total] arena layout (parallel/arena.py) while the
# compute stays leaf-parallel. Each function is bitwise-identical to its
# tree twin — same elementwise ops on the same values, only the views
# differ (proven in tests/test_arena.py).
#
# Formulation notes (measured on CPU XLA, LeNetCifar ring-8):
#   * The ONE per-step assembly is the wire build, and it fuses the
#     event mask into the concatenation pieces — the tree path pays a
#     ravel pass AND a separate [n] masking pass.
#   * Receive-side work is single [n]-wide data-parallel ops (gathers
#     of [L] vectors by the static segment map, wide selects): they
#     split across the intra-op thread pool and overlap the model's
#     conv/matmul thunks. Serial per-leaf region-write chains
#     (dynamic_update_slice) and extra assemblies measurably do not.
#   * Candidates and effective-bits are returned separately from the
#     buffer commit (`commit_bufs_flat`, or the fused
#     ops/arena_update.fused_mix_commit kernel) so the commit can fuse
#     into the mix+SGD tail.

def _wire_concat(pieces, dtype):
    """The arena wire build: one concatenation of per-leaf pieces —
    bitwise the concatenation of the same values, with any per-leaf
    masking/quantization already fused into the pieces."""
    if len(pieces) == 1:
        return pieces[0].reshape(-1).astype(dtype)
    return jnp.concatenate([x.reshape(-1).astype(dtype) for x in pieces])


def neighbor_vals_flat(
    payload: Any, topo: Topology, spec: "arena.ArenaSpec", wire=None,
) -> Tuple[jnp.ndarray, ...]:
    """D-PSGD exchange on the arena: one flat wire buffer per neighbor,
    already upcast to the local dtype. `payload` is the parameter
    pytree; the receiver consumes the buffer flat (no per-neighbor
    unravel)."""
    leaves = spec.treedef.flatten_up_to(payload)
    dt = spec.dtype
    if wire == "int8":
        # bitwise _int8_scales: per-leaf absmax/127, zero-safe,
        # contraction-safe (the truncation must match the tree path's
        # exactly or arena-vs-tree int8 parity breaks)
        scale_vec = _contract_safe(
            jnp.maximum(_leaf_absmax(leaves), 1e-30) / 127.0
        )
        q = _wire_concat(
            [
                jnp.clip(jnp.round(l.reshape(-1) / scale_vec[k]), -127, 127)
                for k, l in enumerate(leaves)
            ],
            jnp.int8,
        )
        seg = spec.seg_expand()

        def one(nb):
            got_q, got_s = recv_from((q, scale_vec), topo, nb)
            return got_q.astype(dt) * got_s[seg].astype(dt)
    else:
        wire_buf = _wire_out(_wire_concat(leaves, dt), wire)

        def one(nb):
            return recv_from(wire_buf, topo, nb).astype(dt)

    return tuple(one(nb) for nb in topo.neighbors)


def masked_neighbor_vals_flat(
    payload: Any,
    fire_vec: jnp.ndarray,
    topo: Topology,
    spec: "arena.ArenaSpec",
    wire=None,
    deliver: "Optional[Any]" = None,
    wire_builder=None,
    checksum: bool = False,
    finite: bool = False,
    corrupt=None,
    carrier: bool = False,
):
    """Event-triggered masked exchange on the arena.

    The zero-masking of non-fired leaves fuses into the wire build
    (`where(fire_k, leaf, 0)` per concatenation piece — bitwise the
    tree path's ravel-then-mask, one pass instead of two). Returns
    (candidate flat values, effective [L] fire bits, raw [L] sender
    bits) per neighbor; the caller commits
    `where(eff, candidate, stale)` — via `commit_bufs_flat` or fused
    into the update kernel. `deliver` has the tree path's chaos
    semantics (a dropped edge's eff bits clear; raw bits stay what was
    on the wire). `wire_builder` — a callable (flat, fire_exp,
    scale_exp|None) -> f32 wire buffer — swaps in the Pallas
    masked-wire kernel (ops.event_engine.masked_wire; the step gates it
    on TPU + a measured ops/arena_tuning.py win): the payload is then
    assembled raw and masked/quantized by the kernel in its own single
    HBM pass, bitwise the inline fused form.

    `checksum` / `finite` / `corrupt` have the tree masked path's
    integrity semantics (a failed check clears the edge's eff bits; the
    verdicts come back as a fourth return value `oks`, bool
    [n_neighbors] stacked).

    `carrier=True` (bf16/int8 wires only, no integrity riders) returns
    the candidates STILL IN THE WIRE DTYPE plus a fourth value: the
    per-neighbor received [L] dequant scale vectors (int8; None for
    bf16) — the carrier-resident buffer contract, where the dequant
    multiply happens at the commit/mix reads instead of here."""
    integrity = checksum or finite or corrupt is not None
    if carrier:
        if integrity:
            raise ValueError(
                "carrier-resident exchange does not compose with the "
                "integrity riders (their verdicts read dequantized "
                "values) — use carrier=False"
            )
        if wire not in ("bf16", "int8"):
            raise ValueError(
                f"carrier-resident exchange needs a bf16/int8 wire; "
                f"got {wire!r}"
            )
    leaves = spec.treedef.flatten_up_to(payload)
    dt = spec.dtype
    if wire == "int8":
        scale_vec = _masked_scales(_leaf_absmax(leaves), fire_vec)
        seg = spec.seg_expand()
        if wire_builder is not None:
            q = wire_builder(
                _wire_concat(leaves, dt), fire_vec[seg], scale_vec[seg]
            ).astype(jnp.int8)
        else:
            # mask + quantize fused into the wire pieces — bitwise
            # _int8_encode_flat of the zero-masked ravel (within leaf k
            # every position shares fire_vec[k] and scale_vec[k])
            q = _wire_concat(
                [
                    jnp.clip(
                        jnp.round(
                            jnp.where(fire_vec[k], l.reshape(-1),
                                      jnp.zeros((), dt))
                            / scale_vec[k]
                        ),
                        -127, 127,
                    )
                    for k, l in enumerate(leaves)
                ],
                jnp.int8,
            )
        csum = wire_checksum(q) if checksum else None

        def receive(nb, i):
            lanes = (q, scale_vec, fire_vec) + ((csum,) if checksum else ())
            got = recv_from(lanes, topo, nb)
            got_q, got_s, got_vec = got[0], got[1], got[2]
            if corrupt is not None:
                got_q = corrupt(i, got_q)
            if carrier:
                # keep the int8 carrier + its [L] scales: the dequant
                # multiply moves into the commit/mix reads
                return got_q, got_vec, None, got_s
            cand = got_q.astype(dt) * got_s[seg].astype(dt)
            ok = (
                _verify_wire(
                    got_q, got[3] if checksum else None, cand,
                    checksum, finite,
                )
                if integrity else None
            )
            return cand, got_vec, ok, None
    else:
        if wire_builder is not None:
            masked = wire_builder(
                _wire_concat(leaves, dt),
                fire_vec[spec.seg_expand()], None,
            ).astype(dt)
        else:
            masked = _wire_concat(
                [
                    jnp.where(fire_vec[k], l.reshape(-1), jnp.zeros((), dt))
                    for k, l in enumerate(leaves)
                ],
                dt,
            )
        wire_buf = _wire_out(masked, wire)
        csum = wire_checksum(wire_buf) if checksum else None

        def receive(nb, i):
            lanes = (wire_buf, fire_vec) + ((csum,) if checksum else ())
            got = recv_from(lanes, topo, nb)
            got_flat, got_vec = got[0], got[1]
            if corrupt is not None:
                got_flat = corrupt(i, got_flat)
            if carrier:
                # bf16 carrier: the resident buffer IS the wire buffer;
                # dequant is the (exact) upcast at the reads
                return got_flat, got_vec, None, None
            cand = got_flat.astype(dt)
            ok = (
                _verify_wire(
                    got_flat, got[2] if checksum else None, cand,
                    checksum, finite,
                )
                if integrity else None
            )
            return cand, got_vec, ok, None

    cands, effs, raws, oks, scls = [], [], [], [], []
    for i, nb in enumerate(topo.neighbors):
        got_flat, got_vec, ok, got_s = receive(nb, i)
        eff = got_vec
        if ok is not None:
            eff = eff & ok
        if deliver is not None:
            eff = eff & deliver[i]
        cands.append(got_flat)
        effs.append(eff)
        raws.append(got_vec)
        oks.append(ok)
        scls.append(got_s)
    if carrier:
        return tuple(cands), tuple(effs), tuple(raws), (
            tuple(scls) if wire == "int8" else None
        )
    if integrity:
        return tuple(cands), tuple(effs), tuple(raws), jnp.stack(oks)
    return tuple(cands), tuple(effs), tuple(raws)


def compact_neighbor_vals_flat(
    payload: Any,
    fire_vec: jnp.ndarray,
    packed: jnp.ndarray,
    leaf_id: jnp.ndarray,
    topo: Topology,
    capacity: int,
    spec: "arena.ArenaSpec",
    wire=None,
    deliver: "Optional[Any]" = None,
    checksum: bool = False,
    finite: bool = False,
    corrupt=None,
    carrier: bool = False,
):
    """Budgeted compacted exchange on the arena.

    `packed`/`leaf_id` come pre-built from the single-pass
    `ops.event_engine.event_propose_pack` (fire_vec must already be its
    capacity-gated output). The receiver replaces the tree path's
    per-leaf dynamic-slice scatter with ONE [n_total]-wide gather:
    position i of leaf k reads `got_packed[got_offsets[k] + (i -
    starts[k])]` — the exact elements `compact_neighbor_vals` slices
    out, selected by the same `where(eff, new, stale)` rule at commit
    time. Returns the same (candidates, eff bits, raw bits) triple as
    the masked flat path, plus the per-edge `oks` verdicts when any of
    `checksum` / `finite` / `corrupt` (tree compact path semantics) is
    set.

    `carrier=True` has the masked flat path's carrier-resident
    contract: candidates come back in the wire dtype (the [n]-wide
    gather runs on the carrier — 1-2 B/elem instead of 4) plus the
    per-neighbor received [L] scale vectors (int8; None for bf16)."""
    integrity = checksum or finite or corrupt is not None
    if carrier:
        if integrity:
            raise ValueError(
                "carrier-resident exchange does not compose with the "
                "integrity riders (their verdicts read dequantized "
                "values) — use carrier=False"
            )
        if wire not in ("bf16", "int8"):
            raise ValueError(
                f"carrier-resident exchange needs a bf16/int8 wire; "
                f"got {wire!r}"
            )
    capacity = int(capacity)
    if capacity < spec.floor:
        raise ValueError(
            f"compact capacity {capacity} is below the largest leaf "
            f"({spec.floor} elements): that leaf could never ship and "
            "would starve"
        )
    dt = spec.dtype
    if wire == "int8":
        scale_vec = _masked_scales(
            _leaf_absmax(spec.treedef.flatten_up_to(payload)), fire_vec
        )
        # same codec as the masked wire (per-position scale is the
        # packed element's source-leaf scale)
        wire_packed = _int8_encode_flat(packed, scale_vec, leaf_id)
        csum = wire_checksum(wire_packed) if checksum else None

        def ship(nb):
            lanes = (wire_packed, scale_vec, fire_vec) + (
                (csum,) if checksum else ()
            )
            got = recv_from(lanes, topo, nb)
            return got[0], got[1], got[2], (got[3] if checksum else None)
    else:
        wire_packed = _wire_out(packed, wire)
        csum = wire_checksum(wire_packed) if checksum else None

        def ship(nb):
            lanes = (wire_packed, fire_vec) + ((csum,) if checksum else ())
            got = recv_from(lanes, topo, nb)
            return got[0], None, got[1], (got[2] if checksum else None)

    seg = spec.seg_expand()
    sizes_arr = spec.sizes_arr()
    # arena position within its leaf (static; shared by every neighbor)
    pos_in_leaf = (
        jnp.arange(spec.n_total, dtype=jnp.int32) - spec.starts_arr()[seg]
    )
    cands, effs, raws, oks, scls = [], [], [], [], []
    for i, nb in enumerate(topo.neighbors):
        got_packed, got_scales, got_vec, got_c = ship(nb)
        if corrupt is not None:
            got_packed = corrupt(i, got_packed)
        ok = None
        if integrity:
            dec = (
                got_packed.astype(jnp.float32)
                if jnp.issubdtype(got_packed.dtype, jnp.floating)
                else got_scales
            )
            ok = _verify_wire(got_packed, got_c, dec, checksum, finite)
        # offsets recomputed from the received fire bits (implicit lane)
        got_fired = jnp.where(got_vec, sizes_arr, 0)
        got_offsets = jnp.cumsum(got_fired) - got_fired
        src = got_offsets[seg] + pos_in_leaf
        data = got_packed[jnp.clip(src, 0, capacity - 1)]
        if carrier:
            # keep the wire carrier; non-fired positions hold clipped
            # garbage exactly like the dequantized path — the commit's
            # where(eff, ...) discards them (and their scales) alike
            val = data
        else:
            val = data.astype(dt)
            if got_scales is not None:
                val = val * got_scales[seg].astype(dt)
        eff = got_vec
        if ok is not None:
            eff = eff & ok
        if deliver is not None:
            eff = eff & deliver[i]
        cands.append(val)
        effs.append(eff)
        raws.append(got_vec)
        oks.append(ok)
        scls.append(got_scales)
    if carrier:
        return tuple(cands), tuple(effs), tuple(raws), (
            tuple(scls) if wire == "int8" else None
        )
    if integrity:
        return tuple(cands), tuple(effs), tuple(raws), jnp.stack(oks)
    return tuple(cands), tuple(effs), tuple(raws)


# ---------------------------------------------------------------------------
# bucketed exchange family: one leaf-aligned bucket of the arena per
# call (parallel/arena.py BucketSpec), the same wire semantics as the
# flat functions above — each bucket's lanes are bitwise the bucket's
# slice of the monolithic wire, so the K-bucket schedule reproduces the
# monolithic step exactly (tests/test_bucketed.py). Integrity riders are
# whole-wire contracts and stay monolithic-only (train/steps.py guards).

def masked_neighbor_vals_bucket(
    leaves,
    fire_vec: jnp.ndarray,
    topo: Topology,
    bucket: "arena.BucketSpec",
    dtype,
    wire=None,
    deliver: "Optional[Any]" = None,
    scale_vec: "Optional[jnp.ndarray]" = None,
    carrier: bool = False,
):
    """One bucket of the event-triggered masked exchange.

    `leaves` are the bucket's parameter leaves (spec order), `fire_vec`
    the bucket-local [L_b] fire bits, `scale_vec` the bucket's slice of
    the per-leaf int8 scales (required iff wire == 'int8'; per-leaf
    scales are bucket-invariant, so the slice quantizes bitwise what the
    monolithic wire does). Returns the flat family's (candidates,
    effective bits, raw bits) triple, every array bucket-sized;
    `carrier=True` has the flat family's carrier-resident contract
    (wire-dtype candidates + a fourth value: per-neighbor received
    [L_b] scale vectors for int8, None for bf16)."""
    if carrier and wire not in ("bf16", "int8"):
        raise ValueError(
            f"carrier-resident exchange needs a bf16/int8 wire; got "
            f"{wire!r}"
        )
    seg = bucket.seg_expand()
    if wire == "int8":
        q = _wire_concat(
            [
                jnp.clip(
                    jnp.round(
                        jnp.where(fire_vec[k], l.reshape(-1),
                                  jnp.zeros((), dtype))
                        / scale_vec[k]
                    ),
                    -127, 127,
                )
                for k, l in enumerate(leaves)
            ],
            jnp.int8,
        )

        def receive(nb):
            got_q, got_s, got_vec = recv_from(
                (q, scale_vec, fire_vec), topo, nb
            )
            if carrier:
                return got_q, got_vec, got_s
            return (
                got_q.astype(dtype) * got_s[seg].astype(dtype),
                got_vec, None,
            )
    else:
        masked = _wire_concat(
            [
                jnp.where(fire_vec[k], l.reshape(-1), jnp.zeros((), dtype))
                for k, l in enumerate(leaves)
            ],
            dtype,
        )
        wire_buf = _wire_out(masked, wire)

        def receive(nb):
            got_flat, got_vec = recv_from((wire_buf, fire_vec), topo, nb)
            return (
                got_flat if carrier else got_flat.astype(dtype),
                got_vec, None,
            )

    cands, effs, raws, scls = [], [], [], []
    for i, nb in enumerate(topo.neighbors):
        got_flat, got_vec, got_s = receive(nb)
        eff = got_vec if deliver is None else got_vec & deliver[i]
        cands.append(got_flat)
        effs.append(eff)
        raws.append(got_vec)
        scls.append(got_s)
    if carrier:
        return tuple(cands), tuple(effs), tuple(raws), (
            tuple(scls) if wire == "int8" else None
        )
    return tuple(cands), tuple(effs), tuple(raws)


def compact_neighbor_vals_bucket(
    packed: jnp.ndarray,
    leaf_id: jnp.ndarray,
    fire_vec: jnp.ndarray,
    topo: Topology,
    bucket: "arena.BucketSpec",
    capacity: int,
    dtype,
    wire=None,
    deliver: "Optional[Any]" = None,
    scale_vec: "Optional[jnp.ndarray]" = None,
    carrier: bool = False,
):
    """One bucket of the budgeted compacted exchange.

    `packed`/`leaf_id` come from `_compact_pack` over the bucket's flat
    payload with its bucket-local `capacity` (one split of
    `split_capacity`); `fire_vec` must be the bucket-local
    capacity-gated bits. Offsets stay the implicit lane — both sides
    recompute them from the bucket's fire bits. Deferral re-contention
    is bucket-local by construction: a deferred leaf competes only for
    its own bucket's budget next pass (docs/compaction.md).
    `carrier=True` has the flat family's carrier-resident contract."""
    if carrier and wire not in ("bf16", "int8"):
        raise ValueError(
            f"carrier-resident exchange needs a bf16/int8 wire; got "
            f"{wire!r}"
        )
    capacity = int(capacity)
    if capacity < bucket.floor:
        raise ValueError(
            f"bucket {bucket.index}: compact capacity {capacity} is "
            f"below its largest leaf ({bucket.floor} elements) — use "
            "split_capacity, which enforces per-bucket floors"
        )
    if wire == "int8":
        wire_packed = _int8_encode_flat(packed, scale_vec, leaf_id)

        def ship(nb):
            got = recv_from((wire_packed, scale_vec, fire_vec), topo, nb)
            return got[0], got[1], got[2]
    else:
        wire_packed = _wire_out(packed, wire)

        def ship(nb):
            got = recv_from((wire_packed, fire_vec), topo, nb)
            return got[0], None, got[1]

    seg = bucket.seg_expand()
    sizes_arr = bucket.sizes_arr()
    pos_in_leaf = (
        jnp.arange(bucket.size, dtype=jnp.int32) - bucket.starts_arr()[seg]
    )
    cands, effs, raws, scls = [], [], [], []
    for i, nb in enumerate(topo.neighbors):
        got_packed, got_scales, got_vec = ship(nb)
        got_fired = jnp.where(got_vec, sizes_arr, 0)
        got_offsets = jnp.cumsum(got_fired) - got_fired
        src = got_offsets[seg] + pos_in_leaf
        data = got_packed[jnp.clip(src, 0, capacity - 1)]
        if carrier:
            val = data
        else:
            val = data.astype(dtype)
            if got_scales is not None:
                val = val * got_scales[seg].astype(dtype)
        eff = got_vec if deliver is None else got_vec & deliver[i]
        cands.append(val)
        effs.append(eff)
        raws.append(got_vec)
        scls.append(got_scales)
    if carrier:
        return tuple(cands), tuple(effs), tuple(raws), (
            tuple(scls) if wire == "int8" else None
        )
    return tuple(cands), tuple(effs), tuple(raws)


def commit_bufs_flat(
    cands: Tuple[jnp.ndarray, ...],
    effs: Tuple[jnp.ndarray, ...],
    lasts: Tuple[jnp.ndarray, ...],
    spec: "arena.ArenaSpec",
) -> Tuple[jnp.ndarray, ...]:
    """new_buf_i = where(eff_i per position, candidate_i, stale_i) —
    the receive-buffer commit of the event exchanges, one wide select
    per neighbor (bitwise the tree path's per-leaf `where`: within leaf
    k every position shares eff[k])."""
    seg = spec.seg_expand()
    return tuple(
        jnp.where(e[seg], c, l) for c, e, l in zip(cands, effs, lasts)
    )


def mix_flat_into_tree(
    params: Any,
    bufs: Tuple[jnp.ndarray, ...],
    spec: "arena.ArenaSpec",
    topo: Topology,
    gate: "Optional[Any]" = None,
) -> Any:
    """Gossip mix of tree-shaped params with FLAT neighbor buffers,
    emitting the mixed pytree directly: per leaf,
    `(p_k + buf_0[s:e] + buf_1[s:e] + ...) * w` with the same add order
    as `mix` — bitwise identical (slices are exact copies), and each
    leaf is an independent fusion (no assembled intermediate between
    the mix and the optimizer tail). With `gate` (bool [n_neighbors])
    this is `mix_weighted`: gated-off edges leave the sum and the
    weight renormalizes over survivors."""
    if gate is None:
        w = topo.mix_weight
    else:
        n_alive = jnp.sum(gate.astype(jnp.float32))
        w = 1.0 / (1.0 + n_alive)
    leaves = spec.treedef.flatten_up_to(params)
    out = []
    for k, (p, s, z) in enumerate(zip(leaves, spec.starts, spec.sizes)):
        acc = p
        for i, b in enumerate(bufs):
            piece = lax.dynamic_slice_in_dim(b, s, z, 0).reshape(p.shape)
            if gate is not None:
                piece = jnp.where(gate[i], piece, jnp.zeros_like(piece))
            acc = jnp.add(acc, piece)
        out.append(acc * w)
    return jax.tree.unflatten(spec.treedef, out)


# ---------------------------------------------------------------------------
# carrier-resident buffer consumers: the receive buffers stay in the
# wire dtype (+ per-leaf int8 scales, parallel/arena.py
# alloc_event_bufs) and the dequant multiply happens HERE, inside the
# commit/mix reads. Bitwise-free: the f32 buffers only ever held
# exactly `dequant(carrier)` (the receiver sees post-wire values and
# dequant is deterministic), leaves commit wholesale so one scale per
# leaf is exact, and the `_contract_safe` scale truncation makes
# `q * s` a single exact f32 multiply — the same multiply the
# dequantize-at-receive path ran.

def commit_carrier_scales(
    cand_scales: Tuple[jnp.ndarray, ...],
    effs: Tuple[jnp.ndarray, ...],
    last_scales: Tuple[jnp.ndarray, ...],
) -> Tuple[jnp.ndarray, ...]:
    """Per-neighbor [L] scale commit riding the carrier buffer commit:
    a fired leaf adopts the scale its candidate crossed the wire with,
    a stale leaf keeps the scale of its resident carrier — the scalar
    twin of `commit_bufs_flat`'s wide select (within leaf k every
    element shares eff[k], so selecting the scale per leaf selects it
    for exactly the elements the carrier select kept)."""
    return tuple(
        jnp.where(e, sc, sl)
        for sc, e, sl in zip(cand_scales, effs, last_scales)
    )


def mix_carrier_flat_into_tree(
    params: Any,
    bufs: Tuple[jnp.ndarray, ...],
    scales: "Optional[Tuple[jnp.ndarray, ...]]",
    spec: "arena.ArenaSpec",
    topo: Topology,
    gate: "Optional[Any]" = None,
) -> Any:
    """`mix_flat_into_tree` over CARRIER buffers: each per-view slice
    dequantizes on the fly — upcast the carrier piece, multiply by the
    leaf's scalar scale (int8; bf16 is the bare upcast) — then the
    identical ordered adds. Elementwise the dequantized values equal
    what the f32-resident buffer stored, so the mix is bitwise the
    f32-resident mix (the bucketed schedule's per-bucket mix closures
    in train/steps.py apply the same per-view dequant inline)."""
    if gate is None:
        w = topo.mix_weight
    else:
        n_alive = jnp.sum(gate.astype(jnp.float32))
        w = 1.0 / (1.0 + n_alive)
    leaves = spec.treedef.flatten_up_to(params)
    out = []
    for k, (p, s, z) in enumerate(zip(leaves, spec.starts, spec.sizes)):
        dt = p.dtype
        acc = p
        for i, b in enumerate(bufs):
            piece = (
                lax.dynamic_slice_in_dim(b, s, z, 0)
                .astype(dt)
                .reshape(p.shape)
            )
            if scales is not None:
                piece = piece * scales[i][k].astype(dt)
            if gate is not None:
                piece = jnp.where(gate[i], piece, jnp.zeros_like(piece))
            acc = jnp.add(acc, piece)
        out.append(acc * w)
    return jax.tree.unflatten(spec.treedef, out)


def dequant_carrier_bufs(
    bufs: Tuple[Any, ...],
    scales: "Optional[Tuple[Any, ...]]",
    spec: "arena.ArenaSpec",
    buckets: int = 1,
) -> Tuple[Any, ...]:
    """The f32 view of carrier-resident receive buffers — exactly what
    the f32-resident layout would have stored (the parity/test shim;
    the hot path never materializes this). Handles both the monolithic
    [n_total] layout and the per-bucket tuple layout."""
    dt = spec.dtype
    k = int(buckets) if buckets else 1

    def one(buf, svec, seg):
        val = buf.astype(dt)
        if svec is not None:
            val = val * svec[seg].astype(dt)
        return val

    if k > 1:
        bks = spec.buckets(k)
        return tuple(
            tuple(
                one(
                    nb_bufs[bi],
                    None if scales is None else scales[i][bi],
                    bks[bi].seg_expand(),
                )
                for bi in range(k)
            )
            for i, nb_bufs in enumerate(bufs)
        )
    seg = spec.seg_expand()
    return tuple(
        one(nb_buf, None if scales is None else scales[i], seg)
        for i, nb_buf in enumerate(bufs)
    )
