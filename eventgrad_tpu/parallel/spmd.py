"""Lift per-rank SPMD functions onto devices (shard_map) or one chip (vmap).

The reference runs N MPI processes, each executing one `main()` body
(/root/reference/dmnist/event/event.cpp:86). Here the per-rank program is a
*pure function* written against named collective axes, and this module lifts
it two ways:

  * `spmd(fn, topo, mesh=...)` — `jax.shard_map` over a real
    `jax.sharding.Mesh`: one rank per device/chip, collectives ride ICI/DCN.
  * `spmd(fn, topo)` — nested `jax.vmap(axis_name=...)`: all ranks batched
    onto whatever device the arrays live on. `lax.ppermute`/`psum` work
    identically over vmap axes, so the *same* per-rank code simulates an
    N-rank ring on a single TPU chip — the MXU sees one big batched matmul
    per step, which is exactly how a TPU wants this workload shaped.

Global arrays use the "stacked" layout: one leading axis of size
`topo.n_ranks` (row-major over `topo.shape`). The per-rank `fn` never sees
that axis.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from eventgrad_tpu.parallel.topology import Topology


def _resolve_shard_map():
    """(shard_map callable, replication-check kwarg name) for this jax.

    Newer jax exposes `jax.shard_map(..., check_vma=)` at top level;
    the 0.4.x line ships the same transform as
    `jax.experimental.shard_map.shard_map(..., check_rep=)`. One
    resolution point so the mesh lift (and the tier-1 skip condition in
    tests/_spmd.py) sees "shard_map available" wherever EITHER spelling
    exists — the pre-shim skip keyed on `hasattr(jax, "shard_map")`
    alone, which mis-read every 0.4.x environment as mesh-less and left
    the whole shard_map test surface dark.
    """
    fn = getattr(jax, "shard_map", None)
    if callable(fn):
        return fn, "check_vma"
    try:
        from jax.experimental.shard_map import shard_map as exp_fn
    except ImportError:
        return None, None
    return exp_fn, "check_rep"


_SHARD_MAP, _CHECK_KW = _resolve_shard_map()


def shard_map_available() -> bool:
    """True when this jax provides the shard_map transform under either
    spelling (the condition `tests/_spmd.py:requires_shard_map` skips
    on — genuinely unavailable, not merely renamed)."""
    return _SHARD_MAP is not None


def build_mesh(topo: Topology, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A `jax.sharding.Mesh` shaped like the topology.

    Replaces `MPI_Init`/`MPI_Comm_size`/`MPI_Comm_rank`
    (/root/reference/dmnist/cent/cent.cpp:42-44). On real hardware pass the
    TPU devices; JAX handles multi-host DCN meshes with the same API.
    """
    if devices is None:
        devices = jax.devices()
    n = topo.n_ranks
    if len(devices) < n:
        raise ValueError(
            f"topology needs {n} devices, only {len(devices)} available; "
            "use spmd(fn, topo) with mesh=None to simulate on one device"
        )
    dev_array = np.asarray(devices[:n]).reshape(topo.shape)
    return Mesh(dev_array, topo.axes)


#: the two lifting paths of `spmd` (docs/ARCHITECTURE.md "Mesh backends")
BACKENDS = ("vmap", "shard_map")


def resolve_backend(
    backend: Optional[str],
    topo: Topology,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Optional[Mesh]:
    """Resolve a backend request to the mesh `spmd` should lift over
    (None = the single-chip vmap simulator).

    "vmap" pins the simulator; "shard_map" demands a real device mesh
    (one rank per device — raises when shard_map or the devices are
    missing, never silently downgrades a mesh request); "auto" takes the
    mesh whenever shard_map exists and enough devices are attached, and
    falls back to vmap otherwise — the default-capable path callers like
    train(backend="auto") ride. None defers to the caller's explicit
    `mesh` argument (legacy wiring).
    """
    if backend is None or backend == "vmap":
        return None
    if backend not in BACKENDS + ("auto",):
        raise ValueError(
            f"backend must be one of {BACKENDS + ('auto',)} or None; "
            f"got {backend!r}"
        )
    if devices is None:
        devices = jax.devices()
    if backend == "auto":
        if not shard_map_available() or len(devices) < topo.n_ranks:
            return None
        return build_mesh(topo, devices)
    if not shard_map_available():
        raise RuntimeError(
            "backend='shard_map' requested but this jax provides no "
            "shard_map transform (see parallel/spmd.py:_resolve_shard_map)"
        )
    return build_mesh(topo, devices)


def stacked_spec(topo: Topology) -> P:
    """PartitionSpec of the stacked layout: the single leading [n_ranks]
    axis sharded over every mesh axis, row-major."""
    return P(topo.axes if len(topo.axes) > 1 else topo.axes[0])


def stack_for_ranks(tree: Any, topo: Topology) -> Any:
    """Broadcast a per-rank pytree to the stacked layout: every leaf gains a
    leading `n_ranks` axis holding identical copies (the reference seeds all
    ranks identically — torch::manual_seed(0), event.cpp:150 — so replicated
    initial state is the faithful starting point)."""
    n = topo.n_ranks
    return jax.tree.map(lambda x: jax.numpy.broadcast_to(x[None], (n,) + x.shape), tree)


def _reshape_leading(tree: Any, new_lead: tuple) -> Any:
    return jax.tree.map(lambda x: x.reshape(new_lead + x.shape[1:]), tree)


def spmd(
    fn: Callable,
    topo: Topology,
    mesh: Optional[Mesh] = None,
    check_vma: bool = False,
) -> Callable:
    """Lift per-rank `fn(*args) -> out` to stacked global arrays.

    All positional args and outputs must be pytrees whose every leaf carries
    the stacked leading axis of size `topo.n_ranks`. Python scalars/static
    config must be closed over in `fn`, not passed as args.
    """
    n = topo.n_ranks

    if mesh is None:
        # vmap simulation path: reshape [N, ...] -> topo.shape + [...] and
        # nest one named vmap per topology axis (outermost axis first).
        inner = fn
        for axis in reversed(topo.axes):
            inner = jax.vmap(inner, axis_name=axis)

        n_axes = len(topo.shape)

        @functools.wraps(fn)
        def simulated(*args):
            args = tuple(_reshape_leading(a, topo.shape) for a in args)
            out = inner(*args)
            return jax.tree.map(lambda x: x.reshape((n,) + x.shape[n_axes:]), out)

        return simulated

    # shard_map path: leading stacked axis sharded over all mesh axes
    # (row-major, matching the stacked layout); per-shard leading dim is 1,
    # squeezed away so `fn` sees true per-rank shapes.
    if _SHARD_MAP is None:
        raise RuntimeError(
            "spmd(fn, topo, mesh=...) needs the shard_map transform, "
            "which this jax provides under neither `jax.shard_map` nor "
            "`jax.experimental.shard_map.shard_map`; run the vmap lift "
            "(mesh=None) instead"
        )
    spec = stacked_spec(topo)

    def shard_body(*args):
        args = tuple(jax.tree.map(lambda x: x[0], a) for a in args)
        out = fn(*args)
        return jax.tree.map(lambda x: x[None], out)

    mapped = _SHARD_MAP(
        shard_body, mesh=mesh, in_specs=spec, out_specs=spec,
        **{_CHECK_KW: check_vma},
    )

    @functools.wraps(fn)
    def sharded(*args):
        return mapped(*args)

    return sharded


def rank_index(topo: Topology) -> jax.Array:
    """Flattened rank id inside a per-rank fn (replaces MPI_Comm_rank)."""
    import jax.lax as lax

    idx = lax.axis_index(topo.axes[0])
    for axis in topo.axes[1:]:
        idx = idx * topo.axis_size(axis) + lax.axis_index(axis)
    return idx
