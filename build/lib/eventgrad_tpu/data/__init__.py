from eventgrad_tpu.data.datasets import load_mnist, load_cifar10, synthetic_dataset
from eventgrad_tpu.data.sharding import (
    shard_sequential,
    shard_random,
    batched_epoch,
)
from eventgrad_tpu.data.augment import pad_flip_crop
