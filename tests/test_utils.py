"""Checkpoint roundtrip, metrics helpers, profiling harness."""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.spmd import spmd
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils import checkpoint
from eventgrad_tpu.utils.metrics import msgs_saved_pct
from eventgrad_tpu.utils.profiling import timed_steps


def _setup(algo="eventgrad"):
    topo = Ring(4)
    model = MLP(hidden=8)
    tx = optax.sgd(0.1, momentum=0.9)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    state = init_train_state(model, (8, 8, 1), tx, topo, algo, cfg)
    step = jax.jit(spmd(make_train_step(model, tx, topo, algo, event_cfg=cfg), topo))
    return topo, state, step


def test_checkpoint_roundtrip_midtraining():
    topo, state, step = _setup()
    x, y = synthetic_dataset(4 * 8 * 4, (8, 8, 1), seed=2)
    xb, yb = batched_epoch(x, y, 4, 8)
    for s in range(2):
        state, _ = step(state, (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])))

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt")
        checkpoint.save(path, state)
        restored = checkpoint.restore(path, state)

    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resumed training continues identically
    s1, _ = step(state, (jnp.asarray(xb[:, 2]), jnp.asarray(yb[:, 2])))
    s2, _ = step(restored, (jnp.asarray(xb[:, 2]), jnp.asarray(yb[:, 2])))
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_peek_corrupted_checkpoint_fails_loudly():
    """Satellite: `peek` on a truncated/corrupted snapshot raises an
    actionable RuntimeError naming the path and the recovery options —
    never half-restores (a resume that silently proceeded from garbage
    would train on it)."""
    payload = {"a": np.arange(5.0), "epoch": np.int64(3)}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt")
        checkpoint.save(path, payload)
        # sanity: intact snapshot peeks fine
        assert int(checkpoint.peek(path)["epoch"]) == 3
        # a host crash mid-write without the fsync fix: promoted names
        # pointing at zero-length files
        for dirpath, _, files in os.walk(path):
            for f in files:
                open(os.path.join(dirpath, f), "w").close()
        with pytest.raises(RuntimeError, match="unreadable"):
            checkpoint.peek(path)
        try:
            checkpoint.peek(path)
        except RuntimeError as e:
            msg = str(e)
            assert path in msg  # the offending path
            assert "last-known-good" in msg  # the recovery option
        # with a COMPLETE demoted .prev twin present, peek auto-recovers
        # from it instead of only hinting — LOUDLY (RuntimeWarning
        # naming both paths), and the recovered payload is the twin's
        checkpoint.save(path + ".prev", payload)
        with pytest.warns(RuntimeWarning, match="RECOVERED"):
            got = checkpoint.peek(path)
        assert int(got["epoch"]) == 3
        # the corrupt primary was SIDELINED, not left in place: the
        # next save must never demote the corrupt tree over the good
        # twin (a kill inside that swap would strand the run), and a
        # kill before that save still resumes from the intact twin
        assert not os.path.exists(path)
        assert os.path.exists(path + ".corrupt")
        assert checkpoint.latest(path) == path + ".prev"
        assert int(checkpoint.peek(path + ".prev")["epoch"]) == 3
        # the warning names the corrupt primary and the twin it used
        with pytest.warns(RuntimeWarning) as rec:
            checkpoint.peek(path)
        assert path in str(rec[0].message)
        assert path + ".prev" in str(rec[0].message)
        # both sides corrupt: loud failure naming BOTH paths and the
        # remaining options — never a half-restore
        for dirpath, _, files in os.walk(path + ".prev"):
            for f in files:
                open(os.path.join(dirpath, f), "w").close()
        with pytest.raises(RuntimeError, match="both unreadable"):
            checkpoint.peek(path)
        try:
            checkpoint.peek(path)
        except RuntimeError as e:
            assert path in str(e) and path + ".prev" in str(e)
            assert "last-known-good" in str(e)
        # and after all that wreckage a fresh save still commits a
        # clean primary (the sidelined .corrupt tree never interferes)
        checkpoint.save(path, {"a": np.arange(5.0), "epoch": np.int64(4)})
        assert checkpoint.latest(path) == path
        assert int(checkpoint.peek(path)["epoch"]) == 4


def test_rolling_retention_never_deletes_only_validated_snapshot():
    """Satellite: `RollingRetention` prunes BEFORE dispatching a new
    save, so the newest `keep` committed snapshots — in particular the
    ONLY one — survive at every instant, even if an in-flight save dies
    mid-write."""
    payload = {"w": np.arange(3.0)}
    with tempfile.TemporaryDirectory() as d:
        ret = checkpoint.RollingRetention(os.path.join(d, "good"), keep=1)
        assert ret.latest_good() is None
        ret.save_good(1, payload)
        assert [e for e, _ in ret.snapshots()] == [1]

        # keep=1 with one snapshot: prune must delete nothing
        assert ret.prune() == 0
        assert [e for e, _ in ret.snapshots()] == [1]

        # a newer save supersedes; the old one goes only AFTER commit
        ret.save_good(2, payload)
        assert [e for e, _ in ret.snapshots()] == [2]

        # an in-flight save dying mid-write (stale .tmp tree) is not a
        # committed snapshot: it neither counts nor endangers the last
        # good one
        stale = ret.path_for(3) + ".tmp"
        os.makedirs(stale)
        with open(os.path.join(stale, "junk"), "w") as f:
            f.write("partial")
        assert [e for e, _ in ret.snapshots()] == [2]
        assert ret.prune() == 0
        assert os.path.exists(ret.path_for(2))

        # the retained snapshot restores (each rides save's atomic swap)
        epoch, path = ret.latest_good()
        got = checkpoint.peek(path)
        np.testing.assert_array_equal(np.asarray(got["w"]), payload["w"])

        # keep=2 retains the newest two, drops the third
        ret2 = checkpoint.RollingRetention(os.path.join(d, "good2"), keep=2)
        for e in (10, 11, 12):
            ret2.save_good(e, payload)
        assert [e for e, _ in ret2.snapshots()] == [11, 12]

        with pytest.raises(ValueError, match="keep"):
            checkpoint.RollingRetention(d, keep=0)


def test_msgs_saved_pct():
    # 4 ranks, 2 neighbors, 10 passes, 4 tensors: 320 possible; 80 events
    assert msgs_saved_pct(80, 10, 4, 2, 4) == 75.0
    assert msgs_saved_pct(0, 0, 0, 0, 0) == 0.0


def test_timed_steps_harness():
    topo, state, step = _setup("dpsgd")
    x, y = synthetic_dataset(4 * 8 * 6, (8, 8, 1), seed=3)
    xb, yb = batched_epoch(x, y, 4, 8)
    batches = [(jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])) for s in range(6)]
    out = timed_steps(step, state, batches, warmup=1)
    assert out["compile_s"] > 0
    assert out["step_ms_mean"] > 0
    assert out["step_ms_p95"] >= out["step_ms_p50"]


def test_digits_real_dataset_loader():
    """--dataset digits: real scikit-learn handwritten scans in the MNIST
    geometry, deterministic disjoint splits, no data_dir needed."""
    from eventgrad_tpu.data.datasets import load_digits, load_or_synthesize

    x, y = load_digits("train")
    xt, yt = load_digits("test")
    assert x.shape == (1440, 28, 28, 1) and xt.shape == (357, 28, 28, 1)
    assert x.dtype == np.float32 and y.dtype == np.int32
    assert 0.0 <= x.min() and x.max() <= 1.0
    assert set(np.unique(y)) == set(range(10))
    # deterministic and disjoint: re-load matches, splits don't overlap
    x2, y2 = load_digits("train")
    np.testing.assert_array_equal(x, x2)
    assert not np.array_equal(x[: len(xt)], xt)
    # the load_or_synthesize dispatch ignores data_dir for digits
    x3, _ = load_or_synthesize("digits", "/nonexistent", "train")
    np.testing.assert_array_equal(x, x3)


def test_collapse_verdict_knee_fixture():
    """The measured stabilizer cliff must flag as collapsed (round-3
    verdict item 7). Fixture: artifacts/mnist_knee_r3_cpu.jsonl's
    horizon-1.05/silence-50/360-pass row finished at 36.5% test accuracy
    — final 10-class cross-entropy ~1.8 vs a converged twin's ~0.1 —
    while presenting 81.66% messages saved."""
    from eventgrad_tpu.utils.metrics import collapse_verdict

    # the cliff's trajectory shape: trains through warmup, then climbs
    # once the trigger silences the exchange — with and without a twin
    cliff = [2.3, 1.2, 0.9, 1.4, 1.8]
    assert collapse_verdict(cliff, 0.1)
    assert collapse_verdict(cliff)
    # UNDERtrained is not collapsed: a short smoke tier ends high but
    # still descending (the tiny tier's 64-pass MNIST leg measures 2.24)
    assert not collapse_verdict([2.30, 2.29, 2.27, 2.25, 2.235])
    assert not collapse_verdict([2.30, 2.28, 2.26], 2.25)
    # ...but a run stuck AT random the whole way is flagged
    assert collapse_verdict([2.38, 2.37, 2.36])
    # two converged runs with a large RATIO are not a collapse
    assert not collapse_verdict([1.0, 0.2, 0.06], 0.02)
    # healthy op-points (every non-cliff knee row finishes well under 0.5)
    assert not collapse_verdict([2.0, 0.8, 0.12])
    assert not collapse_verdict([1.5, 0.5, 0.3], 0.2)
    # boundary behavior: the abs floor gates both twin and bounce checks
    assert not collapse_verdict([2.0, 0.4, 0.45], 0.01)
    assert collapse_verdict([2.0, 0.4, 0.6], 0.01)
    # scalar input is accepted as a 1-entry history (twin check only)
    assert collapse_verdict(1.8, 0.1)
    assert not collapse_verdict(0.3)
    # NaN/inf = the hardest divergence; compare-False semantics must not
    # let it through any signal
    assert collapse_verdict([0.5, float("nan")])
    assert collapse_verdict([0.5, float("inf")], 0.1)
    assert collapse_verdict(float("nan"), 0.1)
    # twin agreement vetoes the bounce: a late noise bounce the dense
    # twin shares is SGD noise, not collapse
    assert not collapse_verdict([1.5, 0.78, 1.0], 0.95)


def test_digits32_cifar_geometry_loader():
    """digits32: the same real scans at the 32x32x3 CIFAR geometry — the
    E4/E5 pipeline's real-pixel feed (round-3 verdict item 6)."""
    from eventgrad_tpu.data.datasets import load_digits, load_or_synthesize

    x, y = load_digits("train", geometry="cifar32")
    assert x.shape == (1440, 32, 32, 3) and y.shape == (1440,)
    assert x.dtype == np.float32
    # channel replication: all three channels identical real pixels
    np.testing.assert_array_equal(x[..., 0], x[..., 1])
    np.testing.assert_array_equal(x[..., 0], x[..., 2])
    # same underlying scans and split as the MNIST-geometry loader
    xm, ym = load_digits("train")
    np.testing.assert_array_equal(y, ym)
    np.testing.assert_array_equal(x[:, 2:30, 2:30, 0], xm[..., 0])
    x2, _ = load_or_synthesize("digits32", None, "train")
    np.testing.assert_array_equal(x, x2)
    import pytest

    with pytest.raises(ValueError):
        load_digits("train", geometry="bogus")


def test_steady_records_flags_all_cold_fallback():
    """ADVICE r5 #2: when every dispatch block was cold, the fallback must
    drop the first record unconditionally (legacy hist[1:] rule) and mark
    the returned COPIES steady_contaminated, so benches can report compile
    contamination instead of silently absorbing it."""
    from eventgrad_tpu.utils.metrics import steady_records

    warm = [
        {"epoch": 1, "dispatch_cold": True},
        {"epoch": 2, "dispatch_cold": False},
        {"epoch": 3, "dispatch_cold": False},
    ]
    out = steady_records(warm)
    assert [h["epoch"] for h in out] == [2, 3]
    assert not any(h.get("steady_contaminated") for h in out)

    all_cold = [
        {"epoch": 1, "dispatch_cold": True},
        {"epoch": 2, "dispatch_cold": True},
    ]
    out = steady_records(all_cold)
    assert [h["epoch"] for h in out] == [2]
    assert all(h["steady_contaminated"] for h in out)
    # inputs must stay pristine (history is reused by callers)
    assert "steady_contaminated" not in all_cold[1]
    # a single all-cold record: full-history fallback, still flagged
    out = steady_records(all_cold[:1])
    assert [h["epoch"] for h in out] == [1] and out[0]["steady_contaminated"]
    # legacy histories without dispatch_cold tags: epoch-1 drop + no flag
    legacy = [{"epoch": 1}, {"epoch": 2}]
    assert [h["epoch"] for h in steady_records(legacy)] == [2]
