"""Soak harness (tools/soak.py, ISSUE 6): the supervised
kill/join/leave/flaky schedule survives, recovers within one save
interval, replays bitwise, and emits a SOAK_SCHEMA-valid artifact.

The smoke leg runs the full pipeline (baseline + supervised-kill
subprocess + replay) at a reduced op point — ~60-90 s on the shared CPU,
tier-1 eligible; the full op point (the committed artifacts/soak_cpu.json
geometry) sits behind the `slow` marker.
"""

import importlib.util
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check(out):
    va = _load_tool("validate_artifacts")
    assert va.validate(out, va.SOAK_SCHEMA) == [], out
    # the claims the schema gates, asserted directly for a readable
    # failure: transitions survived (active_ranks tracked the schedule),
    # zero escalations, bounded recovery, bitwise replay, accuracy gap
    assert out["supervisor_escalations"] == 0
    assert out["supervisor_restarts"] >= 1
    assert out["active_ranks_verified"] is True
    assert out["recovery_ok"] is True
    assert out["replay_bitwise"] is True
    assert out["n_transitions"] >= 6 and out["n_joins"] >= 2
    assert out["final_acc_gap_pt"] <= 0.5


def test_soak_smoke_schema_valid(tmp_path):
    soak = _load_tool("soak")
    out = soak.run_soak(
        str(tmp_path / "soak.json"), mode="smoke",
        workdir=str(tmp_path / "w"),
    )
    _check(out)
    assert os.path.exists(str(tmp_path / "soak.json"))


@pytest.mark.slow
def test_soak_full_schedule(tmp_path):
    soak = _load_tool("soak")
    out = soak.run_soak(
        str(tmp_path / "soak.json"), mode="full",
        workdir=str(tmp_path / "w"),
    )
    _check(out)
    assert out["n_transitions"] >= 8