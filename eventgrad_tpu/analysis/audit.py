"""The trace auditor: machine-checked invariants of the fused train step.

For each configuration of the step (model geometry x algo x wire x
gossip wire x arena x obs x chaos x integrity x staleness x bucketed)
the auditor traces the vmap-lifted step to a closed jaxpr and proves,
ON THE MODELS THE HEADLINE NUMBERS SHIP — LeNetCifar, ResNet18, and a
small TransformerLM (full + flash attention) alongside the cheap MLP
regression base:

  1. RANK ISOLATION (analysis/rankflow.py): the only cross-rank
     information flow is the declared neighbor exchange — constant-
     permutation gathers whose ring offsets equal the topology's
     neighbor offsets; no undeclared collective, reduction, slice, or
     data-dependent gather touches the rank axis.
  2. WIRE-BYTE TRUTH: the bytes each exchange moves, derived from the
     exchange lanes' shapes/dtypes in the jaxpr, equal (a) the shipped
     accounting formula (`collectives.wire_real_bytes_per_neighbor`,
     or the sp_eventgrad inline formula in train/steps.py) and (b) the
     `sent_bytes_wire_real` metric the executed step actually reports —
     exactly, not approximately.  Integrity checksums are a DOCUMENTED
     rider (one int32 per neighbor, excluded from the formula by
     contract); any other unexpected lane is a violation.
  3. STEP HYGIENE: no host callbacks inside the traced step; full-model
     materializations (concatenates producing an [n_params] buffer)
     within the per-configuration budget; wire value lanes carried at
     the declared wire dtype (no silent bf16/int8 -> f32 promotion);
     donation aliasing of the state buffers intact under the loop's
     `donate_argnums=(0,)` jit.

Every check has a seeded ORACLE violation (`run_oracles`) proving it
can fire: an undeclared ppermute offset, a cross-rank roll, a wire
dtype upcast, an extra full-tree ravel, a broken byte formula, a host
callback, a conv whose rank-merged features contract across ranks, an
unregistered pallas kernel, a data-dependent cross-rank attention
gather.  `tools/audit.py` runs the matrix + oracles and commits the
schema-gated artifacts/audit_cpu.json.  See docs/ANALYSIS.md.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.flatten_util import ravel_pytree

from eventgrad_tpu.analysis import rankflow, walker
from eventgrad_tpu.chaos import monitor as chaos_monitor
from eventgrad_tpu.chaos.integrity import IntegrityConfig
from eventgrad_tpu.chaos.schedule import ChaosSchedule
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.models.cnn import LeNetCifar
from eventgrad_tpu.models.resnet import ResNet18
from eventgrad_tpu.models.transformer import TransformerLM
from eventgrad_tpu.obs import device as obs_device
from eventgrad_tpu.parallel import arena as arena_lib
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel import policy as policy_lib
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.sparsify import SparseConfig
from eventgrad_tpu.parallel.spmd import spmd, stack_for_ranks
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step
from eventgrad_tpu.utils import trees

#: the audit geometries: the MLP's 4-leaf tree (a dominant kernel plus
#: ragged tails) remains the cheap regression base for the algo/obs/
#: chaos/integrity dimensions, and the PRODUCTION models join the
#: matrix at real geometry (ISSUE 12) — LeNetCifar and ResNet18
#: (rankflow tracks the conv batching rule's rank-major feature merge
#: as a BLOCKED layout) and a small TransformerLM, full-attention and
#: flash (the Pallas kernel passes via the declared-kernel registry,
#: analysis/kernels.py).  All on a Ring(4).
N_RANKS = 4
IN_SHAPE = (8, 8, 1)
PER_RANK = 4
#: production-geometry cells trace bigger programs; a smaller per-rank
#: batch keeps the executed metric leg tractable on CPU
PER_RANK_PROD = 2
SEQ_LEN = 16
VOCAB = 32
MODEL = dict(hidden=16)
#: model name -> (constructor, input shape, input dtype, per-rank batch)
GEOMETRIES = {
    "mlp": (lambda attn: MLP(**MODEL), IN_SHAPE, jnp.float32, PER_RANK),
    "lenet": (
        lambda attn: LeNetCifar(), (32, 32, 3), jnp.float32, PER_RANK_PROD
    ),
    "resnet18": (
        lambda attn: ResNet18(), (32, 32, 3), jnp.float32, PER_RANK_PROD
    ),
    "transformer": (
        lambda attn: TransformerLM(
            vocab=VOCAB, dim=16, n_heads=2, n_layers=1, max_len=SEQ_LEN,
            attn=attn,
        ),
        (SEQ_LEN,), jnp.int32, PER_RANK_PROD,
    ),
}
CFG = EventConfig(adaptive=True, horizon=0.95, warmup_passes=2,
                  max_silence=4)
#: fits Dense_0's kernel+bias, defers the second layer when all fire
CAPACITY = 1100
#: bucketed compact cells need sum(per-bucket floors) <= capacity; with
#: K=4 on the 4-leaf MLP every leaf is its own bucket, so the floor is
#: the full model (collectives.bucketed_capacity_floor)
BUCKETED_CAPACITY = 1210

_ITEMSIZE = {
    "float32": 4.0, "bfloat16": 2.0, "float16": 2.0, "int8": 1.0,
    "uint8": 1.0, "bool": 1.0, "int32": 4.0, "uint32": 4.0,
    "float64": 8.0, "int64": 8.0,
}

_WIRE_DTYPE = {None: "float32", "bf16": "bfloat16", "int8": "int8"}


@dataclasses.dataclass(frozen=True)
class AuditConfig:
    """One cell of the audit matrix."""

    name: str
    algo: str = "eventgrad"
    #: audit geometry (GEOMETRIES key): mlp | lenet | resnet18 | transformer
    model: str = "mlp"
    #: attention mode for the transformer geometry ("full" | "flash";
    #: flash exercises the Pallas kernels through the declared-kernel
    #: registry, analysis/kernels.py)
    attn: str = "full"
    wire: Optional[str] = None
    gossip_wire: str = "dense"
    #: compact wire capacity; -1 = auto (the model's static capacity
    #: floor — largest leaf, or the bucketed floor sum at K)
    capacity: Optional[int] = None
    arena: bool = False
    obs: bool = False
    chaos: bool = False
    integrity: bool = False
    staleness: int = 0
    #: bucketed gossip schedule (train/steps.py bucketed=): 0 = off;
    #: K >= 2 splits every exchange into K leaf-aligned bucket wires,
    #: each with its own declared-offset ppermute lanes
    bucketed: int = 0
    #: full-model concatenates allowed in the traced step (the arena
    #: contract is ONE — the fused wire build; the tree paths pay one
    #: ravel_pytree per exchange family; sp's per-leaf top-k never
    #: materializes a full buffer)
    ravel_budget: int = 1
    #: verify donation aliasing under the loop's donate_argnums=(0,)
    #: jit (a second trace+lower — run on representative cells only)
    donation: bool = False
    #: heavy cells (ResNet18's 17.4M-param trace, the flash interpret
    #: run) stay out of the fast tier-1 matrix — tests mark them `slow`;
    #: tools/audit.py always runs them
    heavy: bool = False
    #: trigger policy (parallel/policy.py registry); None = the algo's
    #: default. Partitioned policies (micro/hybrid) additionally get
    #: their partition geometry validated and declared in the report
    #: (`partitions` / `partitions_ok`), like the fire-bit offsets
    policy: Optional[str] = None
    #: carrier-resident gossip state (ISSUE 17): EventState.bufs stay
    #: in the wire dtype with the dequant fused into the commit/mix
    #: reads. The WIRE format is unchanged (the exchange already
    #: shipped the carrier), so the same rank-isolation, declared-
    #: offset, and three-way wire-byte truth must hold over the
    #: carrier program's jaxpr
    carrier: bool = False


#: the audit matrix: every dimension of the step's configuration space
#: exercised against at least one other (the test_arena.py CASES rule),
#: per ISSUE 9 — dpsgd/eventgrad/sp x masked|compact x arena on/off x
#: obs/chaos/integrity on/off, wire dtypes crossed through
CONFIGS: Tuple[AuditConfig, ...] = (
    AuditConfig("dpsgd_f32_tree", algo="dpsgd"),
    AuditConfig("dpsgd_int8_arena", algo="dpsgd", wire="int8", arena=True,
                donation=True),
    AuditConfig("event_masked_f32_tree"),
    AuditConfig("event_masked_f32_arena_obs", arena=True, obs=True,
                donation=True),
    AuditConfig("event_masked_bf16_arena", arena=True, wire="bf16"),
    AuditConfig("event_masked_int8_tree_chaos", wire="int8", chaos=True),
    AuditConfig("event_compact_f32_tree", gossip_wire="compact",
                capacity=CAPACITY),
    AuditConfig("event_compact_int8_arena_obs", gossip_wire="compact",
                capacity=CAPACITY, wire="int8", arena=True, obs=True),
    AuditConfig("event_masked_f32_arena_integrity", arena=True,
                integrity=True),
    AuditConfig("event_compact_bf16_arena_stale", gossip_wire="compact",
                capacity=CAPACITY, wire="bf16", arena=True, staleness=1),
    # bounded-async gossip (ISSUE 15): the per-edge delivery queues add
    # NO wire lanes (the exchange is unchanged — only the commit is
    # deferred), so the same rank-isolation + exact wire-byte truth
    # must hold with the D-deep clocks in the traced program; the
    # chaos cell carries a slow= straggler so the lag path itself is
    # in the audited jaxpr
    AuditConfig("event_masked_f32_arena_stale2_chaos", arena=True,
                staleness=2, chaos=True),
    AuditConfig("event_compact_int8_arena_stale4", gossip_wire="compact",
                capacity=CAPACITY, wire="int8", arena=True, staleness=4),
    AuditConfig("sp_f32_tree", algo="sp_eventgrad"),
    # the composed overlap stack (ISSUE 20): bounded-async delivery
    # queues CARRIED PER-BUCKET in the carrier dtype under the compact
    # wire — every overlap mechanism at once, exactly the production
    # configuration tools/straggler_ablation.py --measured times. The
    # queue slots add no wire lanes (commit deferral is state, not
    # traffic), so the same declared offsets and exact wire-byte
    # equality must hold over the fully composed program; the seeded
    # bucket_queue_skew oracle proves the queue-in-bucket carry is
    # actually checked
    AuditConfig("event_compact_int8_arena_b4_stale2_carrier",
                gossip_wire="compact", capacity=BUCKETED_CAPACITY,
                wire="int8", arena=True, bucketed=4, staleness=2,
                carrier=True),
    # sp_eventgrad's payload queues at D=2 (SparseState.pending): the
    # top-k lanes are unchanged — the deferred scatter is state too
    AuditConfig("sp_f32_tree_stale2", algo="sp_eventgrad", staleness=2),
    # carrier-resident gossip state (ISSUE 17): the receive buffers live
    # in the wire dtype and the dequant runs inside the commit/mix
    # reads — the exchange lanes themselves are UNCHANGED, so the
    # auditor must see the exact same declared offsets and three-way
    # wire-byte equality over the carrier program, across both carrier
    # dtypes and both gossip wires (the seeded stale_scale_reuse oracle
    # proves the value-level harness bites)
    AuditConfig("event_masked_int8_arena_carrier", wire="int8",
                arena=True, carrier=True),
    AuditConfig("event_compact_bf16_arena_carrier", gossip_wire="compact",
                capacity=CAPACITY, wire="bf16", arena=True, carrier=True),
    # partitioned trigger policies (ISSUE 16): micro's rotating owned-
    # partition sends and hybrid's gated twin must keep the SAME
    # rank-isolation, declared-offset, and three-way wire-byte truth —
    # the wire format is unchanged (the masks ride the force/suppress
    # seams), and the partition geometry itself is validated and
    # declared in the report (partitions/partitions_ok), with the
    # seeded partition_overlap oracle proving the check bites
    AuditConfig("event_micro_compact_f32_arena", gossip_wire="compact",
                capacity=-1, arena=True, policy="micro"),
    AuditConfig("event_micro_masked_int8_tree", wire="int8",
                policy="micro"),
    AuditConfig("event_hybrid_masked_f32_arena_obs", arena=True,
                obs=True, policy="hybrid"),
    AuditConfig("event_hybrid_compact_int8_arena_b4",
                gossip_wire="compact", capacity=BUCKETED_CAPACITY,
                wire="int8", arena=True, bucketed=4, policy="hybrid"),
    # bucketed gossip schedule (ISSUE 10): the auditor must see K
    # declared-offset ppermute lane groups per neighbor and the SAME
    # three-way wire-byte equality, summed over buckets
    AuditConfig("event_masked_f32_arena_b4", arena=True, bucketed=4),
    AuditConfig("event_compact_int8_arena_b4", gossip_wire="compact",
                capacity=BUCKETED_CAPACITY, wire="int8", arena=True,
                bucketed=4),
    # production geometries (ISSUE 12): the models the headline numbers
    # ship on, audited at real geometry — conv rank-major feature
    # merges tracked as BLOCKED layouts, the flash Pallas kernels
    # passing via the declared-kernel registry — across masked|compact
    # x f32/int8 x arena x bucketed K=4
    AuditConfig("lenet_masked_f32_arena", model="lenet", arena=True,
                donation=True),
    AuditConfig("lenet_compact_int8_arena", model="lenet",
                gossip_wire="compact", capacity=-1, wire="int8",
                arena=True),
    AuditConfig("lenet_masked_f32_arena_b4", model="lenet", arena=True,
                bucketed=4),
    AuditConfig("resnet18_masked_f32_arena", model="resnet18", arena=True,
                heavy=True),
    AuditConfig("resnet18_compact_f32_arena", model="resnet18",
                gossip_wire="compact", capacity=-1, arena=True,
                heavy=True),
    AuditConfig("xfmr_masked_f32_arena", model="transformer", arena=True),
    AuditConfig("xfmr_compact_int8_tree", model="transformer",
                gossip_wire="compact", capacity=-1, wire="int8"),
    AuditConfig("xfmr_flash_masked_f32_tree", model="transformer",
                attn="flash", heavy=True),
)


def config_by_name(name: str) -> AuditConfig:
    for c in CONFIGS:
        if c.name == name:
            return c
    raise KeyError(f"unknown audit config {name!r}")


# --- building the step under audit -----------------------------------------


def _geometry(cfg: AuditConfig):
    """(model, input shape, input dtype, per-rank batch) of a cell."""
    make, in_shape, in_dtype, per_rank = GEOMETRIES[cfg.model]
    return make(cfg.attn), in_shape, in_dtype, per_rank


def _batch(cfg: AuditConfig):
    _, in_shape, in_dtype, per_rank = GEOMETRIES[cfg.model]
    if in_dtype == jnp.int32:
        # token LM: next-token targets on a fixed random sequence
        toks = jax.random.randint(
            jax.random.PRNGKey(0), (N_RANKS, per_rank) + tuple(in_shape),
            0, VOCAB,
        )
        return toks, jnp.roll(toks, -1, axis=-1)
    x, y = synthetic_dataset(N_RANKS * per_rank, in_shape, seed=0)
    return (
        jnp.asarray(x.reshape((N_RANKS, per_rank) + tuple(in_shape))),
        jnp.asarray(y.reshape((N_RANKS, per_rank))),
    )


def resolved_capacity(cfg: AuditConfig, state) -> Optional[int]:
    """The compact capacity a cell actually runs at.  `capacity=-1`
    means auto: the model's STATIC capacity floor (largest leaf, or the
    sum of per-bucket floors under a bucketed schedule) — derived from
    the same ArenaSpec / collectives helpers the step itself uses, so
    the audited wire format can never drift from the program's."""
    if cfg.gossip_wire != "compact":
        return None
    if cfg.capacity is not None and cfg.capacity >= 0:
        return cfg.capacity
    params = jax.tree.map(lambda x: x[0], state.params)
    if cfg.bucketed and cfg.bucketed >= 2:
        buckets = arena_lib.arena_spec(params).buckets(cfg.bucketed)
        return int(collectives.bucketed_capacity_floor(buckets))
    sizes = [int(p.size) for p in jax.tree.leaves(params)]
    return int(collectives.compact_capacity_floor(sizes))


def build(cfg: AuditConfig):
    """(state, per-rank step, topo) for one audit cell — the same
    construction tests/test_arena.py uses, so the audited program IS the
    tested program."""
    topo = Ring(N_RANKS)
    model, in_shape, in_dtype, _ = _geometry(cfg)
    tx = optax.sgd(0.05)
    chaos = None
    if cfg.chaos:
        # bounded-async cells add a persistent straggler so the lag
        # schedule (not just the queue carry) is in the audited jaxpr
        slow = ((1, 3),) if cfg.staleness >= 2 else ()
        chaos = ChaosSchedule(seed=3, drop_p=0.4, slow=slow)
    state = init_train_state(
        model, in_shape, tx, topo, cfg.algo, CFG, seed=0, arena=cfg.arena,
        bucketed=cfg.bucketed or 1, input_dtype=in_dtype,
        # init_train_state routes the depth itself: eventgrad's queues
        # live in EventState.pending, sp's in SparseState.pending
        staleness=cfg.staleness,
        resident_wire=(
            cfg.wire if cfg.carrier and cfg.algo == "eventgrad" else None
        ),
    )
    if chaos is not None:
        state = state.replace(
            chaos=stack_for_ranks(chaos_monitor.PeerHealth.init(topo), topo)
        )
    if cfg.obs:
        state = state.replace(
            telemetry=stack_for_ranks(
                obs_device.TelemetryState.init(
                    len(jax.tree.leaves(state.params)), topo.n_neighbors
                ),
                topo,
            )
        )
    step = make_train_step(
        model, tx, topo, cfg.algo, event_cfg=CFG, wire=cfg.wire,
        gossip_wire=cfg.gossip_wire,
        compact_capacity=resolved_capacity(cfg, state),
        staleness=cfg.staleness, obs=cfg.obs, chaos=chaos,
        arena=cfg.arena,
        integrity=IntegrityConfig() if cfg.integrity else None,
        bucketed=cfg.bucketed or None,
        trigger_policy=cfg.policy,
        carrier_resident=cfg.carrier,
    )
    return state, step, topo


def _meta(state):
    params = jax.tree.map(lambda x: x[0], state.params)
    n_params = trees.tree_count_params(params)
    n_leaves = trees.tree_num_leaves(params)
    k_total = sum(
        SparseConfig().k_for(p.size) for p in jax.tree.leaves(params)
    )
    return n_params, n_leaves, k_total


# --- wire classification ----------------------------------------------------


def _bucket_info(cfg: AuditConfig, state):
    """(buckets, caps) of a bucketed cell, None otherwise — the same
    ArenaSpec.buckets/split_capacity the step itself runs, so the
    expected lanes and formula can never drift from the program."""
    if not cfg.bucketed or cfg.bucketed < 2:
        return None
    params = jax.tree.map(lambda x: x[0], state.params)
    buckets = arena_lib.arena_spec(params).buckets(cfg.bucketed)
    caps = (
        collectives.split_capacity(resolved_capacity(cfg, state), buckets)
        if cfg.gossip_wire == "compact" else None
    )
    return buckets, caps


def _expected_lanes(cfg: AuditConfig, n_params: int, n_leaves: int,
                    binfo=None, capacity: Optional[int] = None):
    """[(role, elems, dtype)] one neighbor's exchange must ship; riders
    are transfer metadata documented OUTSIDE the wire-byte formula.
    Bucketed cells expect K lane GROUPS per neighbor — one value lane
    (bucket elems or its capacity split) + one fire vector (+ one int8
    scale vector) per bucket."""
    if cfg.algo == "sp_eventgrad":
        return None  # per-leaf top-k lanes: totals-only comparison
    if binfo is not None:
        buckets, caps = binfo
        lanes = []
        for i, b in enumerate(buckets):
            val_elems = b.size if caps is None else caps[i]
            lanes.append(("value", val_elems, _WIRE_DTYPE[cfg.wire]))
            lanes.append(("fire", b.n_leaves, "bool"))
            if cfg.wire == "int8":
                lanes.append(("scale", b.n_leaves, "float32"))
        return lanes, []
    val_elems = (
        capacity if cfg.gossip_wire == "compact" else n_params
    )
    lanes = [("value", val_elems, _WIRE_DTYPE[cfg.wire])]
    if cfg.algo == "eventgrad":
        lanes.append(("fire", n_leaves, "bool"))
    if cfg.wire == "int8":
        lanes.append(("scale", n_leaves, "float32"))
    riders = [("checksum", 1, "int32")] if cfg.integrity else []
    return lanes, riders


def _formula_bytes_per_neighbor(
    cfg: AuditConfig, n_params: int, n_leaves: int, k_total: int,
    binfo=None, capacity: Optional[int] = None,
) -> float:
    """The SHIPPED accounting formula the metric is built from — what
    the jaxpr-derived truth is checked against. Bucketed cells sum the
    per-bucket formula (the step's own definition)."""
    if cfg.algo == "sp_eventgrad":
        val = collectives.WIRE_VAL_BYTES[cfg.wire]
        scale = 4.0 if cfg.wire == "int8" else 0.0
        return (val + 4.0) * k_total + 1.0 * n_leaves + scale * n_leaves
    if binfo is not None:
        buckets, caps = binfo
        return float(sum(collectives.bucketed_wire_real_bytes_per_neighbor(
            buckets, cfg.wire, caps
        )))
    return collectives.wire_real_bytes_per_neighbor(
        n_params, n_leaves, cfg.wire,
        compact_capacity=(
            capacity if cfg.gossip_wire == "compact" else None
        ),
        fire_bits=(cfg.algo == "eventgrad"),
    )


def _classify_exchanges(
    cfg: AuditConfig,
    report: rankflow.RankFlowReport,
    n_params: int,
    n_leaves: int,
    binfo=None,
    capacity: Optional[int] = None,
) -> Dict[str, Any]:
    """Group the detected exchange lanes by ring offset and check them
    against the expected wire format; returns per-neighbor derived
    bytes (riders excluded) and lane problems."""
    groups: Dict[int, List[rankflow.Exchange]] = {}
    for ex in report.exchanges:
        groups.setdefault(ex.offset, []).append(ex)
    problems: List[str] = []
    per_offset_bytes: Dict[int, float] = {}
    rider_bytes: Dict[int, float] = {}
    expected = _expected_lanes(cfg, n_params, n_leaves, binfo, capacity)
    for off, lanes in groups.items():
        got = sorted((e.lane_elems, e.dtype) for e in lanes)
        if expected is None:
            # sp: every lane is payload; no rider vocabulary
            per_offset_bytes[off] = sum(
                e.lane_elems * _ITEMSIZE[e.dtype] for e in lanes
            )
            rider_bytes[off] = 0.0
            continue
        want, riders = expected
        want_set = sorted((elems, dt) for _, elems, dt in want)
        rider_set = sorted((elems, dt) for _, elems, dt in riders)
        remaining = list(got)
        matched_riders = []
        for lane in want_set:
            if lane in remaining:
                remaining.remove(lane)
            else:
                problems.append(
                    f"offset {off:+d}: missing expected lane "
                    f"{lane[0]} elems of {lane[1]}"
                )
        for lane in rider_set:
            if lane in remaining:
                remaining.remove(lane)
                matched_riders.append(lane)
            else:
                problems.append(
                    f"offset {off:+d}: missing declared rider "
                    f"{lane[0]} elems of {lane[1]}"
                )
        for lane in remaining:
            problems.append(
                f"offset {off:+d}: UNDECLARED lane {lane[0]} elems of "
                f"{lane[1]} on the wire"
            )
        # derived bytes come from the ACTUAL traced lanes (riders
        # excluded) — NOT from the expectation, or a dtype upcast
        # would launder itself through the comparison
        rider_bytes[off] = sum(
            elems * _ITEMSIZE[dt] for elems, dt in matched_riders
        )
        per_offset_bytes[off] = (
            sum(elems * _ITEMSIZE[dt] for elems, dt in got)
            - rider_bytes[off]
        )
        # dtype fidelity: the value lane must be carried at the wire
        # dtype — a silent promotion to f32 doubles/quadruples the
        # actual transfer while the accounting keeps lying
        for role, elems, dt in want:
            if role == "value" and (elems, dt) not in got:
                problems.append(
                    f"offset {off:+d}: value lane not carried as "
                    f"{dt} ({cfg.wire or 'native f32'} wire) — "
                    "silent dtype promotion"
                )
    return {
        "offsets": sorted(groups),
        "per_offset_bytes": per_offset_bytes,
        "rider_bytes": rider_bytes,
        "problems": problems,
    }


# --- hygiene ---------------------------------------------------------------

_CALLBACK_PRIMS = ("callback", "infeed", "outfeed")


def count_callbacks(jaxpr) -> int:
    """Host round-trips inside the traced step: any callback-family
    primitive (pure_callback / io_callback / debug_callback) or
    infeed/outfeed, at any nesting."""
    total = 0
    for eqn, _ in walker.iter_eqns(jaxpr):
        name = eqn.primitive.name
        if any(tok in name for tok in _CALLBACK_PRIMS):
            total += 1
    return total


_ALIAS_ARG_RE = re.compile(
    r"%arg\d+:\s*tensor<([0-9x]*)x?([a-z0-9]+)>\s*"
    r"(\{[^}]*tf\.aliasing_output[^}]*\})"
)


def donation_aliases(lowered_text: str) -> List[Tuple[Tuple[int, ...], str]]:
    """(shape, dtype) of every donated-and-aliased argument in a lowered
    module's entry signature."""
    out = []
    for m in _ALIAS_ARG_RE.finditer(lowered_text):
        dims = tuple(int(d) for d in m.group(1).split("x") if d)
        out.append((dims, m.group(2)))
    return out


def check_donation(lifted, state, batch) -> Tuple[bool, str]:
    """The loop jits the lifted step with donate_argnums=(0,)
    (train/loop.py); verify XLA actually aliases the big state buffers
    — every params leaf (and flat event buffer) must appear among the
    aliased arguments."""
    low = jax.jit(lifted, donate_argnums=(0,)).lower(state, batch)
    aliased = donation_aliases(low.as_text())
    need: List[Tuple[Tuple[int, ...], str]] = []
    for leaf in jax.tree.leaves(state.params):
        need.append((tuple(leaf.shape), _mlir_dtype(leaf.dtype)))
    if getattr(state, "event", None) is not None:
        for buf in jax.tree.leaves(state.event.bufs):
            need.append((tuple(buf.shape), _mlir_dtype(buf.dtype)))
    pool = list(aliased)
    for item in need:
        if item in pool:
            pool.remove(item)
        else:
            return False, (
                f"state buffer {item} not donation-aliased (of "
                f"{len(aliased)} aliased args)"
            )
    return True, f"{len(need)} state buffers aliased"


def _mlir_dtype(dt) -> str:
    s = str(jnp.dtype(dt))
    return {
        "float32": "f32", "bfloat16": "bf16", "float16": "f16",
        "int32": "i32", "int8": "i8", "bool": "i1", "uint32": "ui32",
    }.get(s, s)


# --- the per-configuration audit -------------------------------------------


def audit_config(
    cfg: AuditConfig,
    run_metric: bool = True,
    check_donation_alias: Optional[bool] = None,
) -> Dict[str, Any]:
    """Trace one audit cell and run every check; returns the report
    dict `tools/audit.py` serializes (all findings, no asserts — the
    caller decides what is fatal)."""
    state, step, topo = build(cfg)
    batch = _batch(cfg)
    lifted = spmd(step, topo)
    closed = jax.make_jaxpr(lifted)(state, batch)
    n_params, n_leaves, k_total = _meta(state)
    capacity = resolved_capacity(cfg, state)

    report = rankflow.analyze(closed, N_RANKS)
    violations = [
        {"prim": f.prim, "reason": f.reason, "path": "/".join(f.path)}
        for f in report.violations
    ]
    # ring gossip declares NO cross-rank reduction: any positional psum
    # over the rank axis is a violation here (allreduce/aux-axis
    # configurations would declare theirs)
    violations += [
        {"prim": f.prim, "reason": f.reason, "path": "/".join(f.path)}
        for f in report.psums
    ]

    declared = sorted(nb.offset for nb in topo.neighbors)
    binfo = _bucket_info(cfg, state)
    wire = _classify_exchanges(
        cfg, report, n_params, n_leaves, binfo, capacity
    )
    undeclared_offsets = sorted(set(wire["offsets"]) - set(declared))
    missing_offsets = sorted(set(declared) - set(wire["offsets"]))

    formula = _formula_bytes_per_neighbor(
        cfg, n_params, n_leaves, k_total, binfo, capacity
    )
    derived_each = list(wire["per_offset_bytes"].values())
    derived_total = float(sum(derived_each))
    wire_match = (
        not wire["problems"]
        and not undeclared_offsets
        and not missing_offsets
        and all(b == formula for b in derived_each)
    )

    metric_total = None
    metric_match = None
    if run_metric:
        _, m = lifted(state, batch)  # eager vmap: no jit required
        metric_total = float(np.asarray(m["sent_bytes_wire_real"])[0])
        # the step carries the metric as an f32 scalar (train/steps.py);
        # at ResNet18 scale (~1.4e8 B/step) integer byte counts exceed
        # f32's 24-bit mantissa, so the derived truth is compared AFTER
        # the same quantization — still exact, in the metric's carrier
        metric_match = metric_total == float(np.float32(derived_total))

    n_total = int(n_params)
    ravels = walker.count_full_ravels(closed.jaxpr, n_total)
    callbacks = count_callbacks(closed.jaxpr)

    donation_ok, donation_note = None, "not checked"
    if check_donation_alias if check_donation_alias is not None else cfg.donation:
        donation_ok, donation_note = check_donation(lifted, state, batch)

    # partitioned policies: validate and DECLARE the partition geometry
    # the traced step's ownership masks were built from — the element
    # offsets are static like the fire-bit offsets, so they publish the
    # same way; validate_partitions checks the masks themselves
    # (disjoint / exact cover / element-balanced), which is what the
    # seeded partition_overlap oracle sabotages
    partitions = None
    partitions_ok = None
    if cfg.policy in ("micro", "hybrid"):
        params0 = jax.tree.map(lambda x: x[0], state.params)
        pspec = arena_lib.arena_spec(params0)
        pr = policy_lib.validate_partitions(pspec, N_RANKS)
        partitions = list(policy_lib.partition_table(pspec, N_RANKS))
        partitions_ok = bool(pr["ok"])

    return {
        "name": cfg.name,
        "algo": cfg.algo,
        "model": cfg.model,
        "attn": cfg.attn,
        "capacity": capacity,
        "wire": cfg.wire,
        "gossip_wire": cfg.gossip_wire,
        "arena": cfg.arena,
        "obs": cfg.obs,
        "chaos": cfg.chaos,
        "integrity": cfg.integrity,
        "staleness": cfg.staleness,
        "bucketed": int(cfg.bucketed),
        "policy": cfg.policy,
        "partitions": partitions,
        "partitions_ok": partitions_ok,
        "n_params": int(n_params),
        "n_leaves": int(n_leaves),
        "violations": len(violations),
        "violation_details": violations,
        "exchange_offsets": wire["offsets"],
        "declared_offsets": declared,
        "undeclared_offsets": undeclared_offsets,
        "missing_offsets": missing_offsets,
        "wire_problems": wire["problems"],
        "wire_bytes_per_neighbor_derived": (
            derived_each[0] if derived_each else 0.0
        ),
        "wire_bytes_per_neighbor_formula": float(formula),
        "wire_rider_bytes_per_neighbor": (
            list(wire["rider_bytes"].values())[0]
            if wire["rider_bytes"] else 0.0
        ),
        "wire_metric_total": metric_total,
        "wire_match": bool(wire_match),
        "metric_match": metric_match,
        "ravel_count": int(ravels),
        "ravel_budget": int(cfg.ravel_budget),
        "ravel_ok": ravels <= cfg.ravel_budget,
        "callbacks": int(callbacks),
        "donation_ok": donation_ok,
        "donation_note": donation_note,
    }


def clean(report: Dict[str, Any]) -> bool:
    """The acceptance predicate for one cell."""
    return (
        report["violations"] == 0
        and report["wire_match"]
        and report["metric_match"] in (None, True)
        and report["ravel_ok"]
        and report["callbacks"] == 0
        and report["donation_ok"] in (None, True)
        and report.get("partitions_ok") in (None, True)
    )


def audit_matrix(run_metric: bool = True) -> List[Dict[str, Any]]:
    return [audit_config(c, run_metric=run_metric) for c in CONFIGS]


# --- the shard_map (real-mesh) lift ----------------------------------------

_NAMED_COLLECTIVES = frozenset({
    "ppermute", "psum", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "axis_index", "pbroadcast",
})


def collect_collectives(jaxpr, n_ranks: int) -> List[Dict[str, Any]]:
    """Named-axis collectives at any nesting — the shard_map lift's
    audit surface: inside the mesh-lifted program the per-rank body
    keeps its collectives as primitives (no vmap batching rewrites
    them), so rank isolation reduces to 'only declared collectives
    appear'.  `n_ranks` is the ring size the signed offsets fold
    against."""
    out = []
    for eqn, path in walker.iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _NAMED_COLLECTIVES:
            rec = {"prim": name, "path": "/".join(path)}
            if name == "ppermute":
                perm = tuple(
                    (int(s), int(d)) for s, d in eqn.params["perm"]
                )
                offs = {(s - d) % n_ranks for s, d in perm}
                rec["offsets"] = sorted(
                    o if o <= n_ranks // 2 else o - n_ranks for o in offs
                )
            out.append(rec)
    return out


def shard_lift_report(closed, topo, name: str) -> Dict[str, Any]:
    """The mesh-program analysis of `audit_shard_lift`, on an
    already-traced jaxpr: only declared-offset ppermutes (plus
    axis_index) may appear, zero host callbacks. Split out so the
    seeded mesh oracle (and tools/mesh_ablation.py) can point it at a
    SABOTAGED lift."""
    declared = sorted(nb.offset for nb in topo.neighbors)
    colls = collect_collectives(closed.jaxpr, topo.n_ranks)
    bad = []
    offsets = set()
    for rec in colls:
        if rec["prim"] == "ppermute":
            offsets.update(rec["offsets"])
        elif rec["prim"] != "axis_index":
            bad.append(rec)
    return {
        "name": name,
        "collectives": colls,
        "undeclared_collectives": bad,
        "exchange_offsets": sorted(offsets),
        "declared_offsets": declared,
        "offsets_ok": offsets == set(declared),
        "callbacks": count_callbacks(closed.jaxpr),
    }


def audit_shard_lift(cfg: AuditConfig) -> Dict[str, Any]:
    """Audit the real-mesh (shard_map) lift of one cell: the only
    collectives in the traced program are ppermutes at the declared
    neighbor offsets (plus axis_index), and the hygiene checks hold.
    Requires a jax with shard_map and >= N_RANKS devices."""
    from eventgrad_tpu.parallel.spmd import build_mesh

    state, step, topo = build(cfg)
    mesh = build_mesh(topo)
    lifted = spmd(step, topo, mesh=mesh)
    closed = jax.make_jaxpr(lifted)(state, _batch(cfg))
    return shard_lift_report(closed, topo, cfg.name)


def shard_lift_clean(report: Dict[str, Any]) -> bool:
    """Acceptance predicate for one mesh-lift report."""
    return (
        report["offsets_ok"]
        and not report["undeclared_collectives"]
        and report["callbacks"] == 0
    )


# --- seeded oracle violations ----------------------------------------------
#
# Each oracle sabotages a CLEAN configuration in exactly one way and
# returns (detected, reason). A check that cannot fire proves nothing —
# these legs are tier-1 (tests/test_audit.py) and part of the artifact.


def _audit_lifted(cfg, lifted, state, batch, run_metric=False):
    closed = jax.make_jaxpr(lifted)(state, batch)
    n_params, n_leaves, k_total = _meta(state)
    capacity = resolved_capacity(cfg, state)
    report = rankflow.analyze(closed, N_RANKS)
    topo = Ring(N_RANKS)
    declared = sorted(nb.offset for nb in topo.neighbors)
    binfo = _bucket_info(cfg, state)
    wire = _classify_exchanges(
        cfg, report, n_params, n_leaves, binfo, capacity
    )
    formula = _formula_bytes_per_neighbor(
        cfg, n_params, n_leaves, k_total, binfo, capacity
    )
    derived_total = float(sum(wire["per_offset_bytes"].values()))
    out = {
        "violations": len(report.violations) + len(report.psums),
        "violation_details": [f.reason for f in report.violations],
        "undeclared_offsets": sorted(set(wire["offsets"]) - set(declared)),
        "wire_problems": wire["problems"],
        "formula_match": all(
            b == formula for b in wire["per_offset_bytes"].values()
        ),
        "ravel_count": walker.count_full_ravels(closed.jaxpr, int(n_params)),
        "callbacks": count_callbacks(closed.jaxpr),
    }
    if run_metric:
        _, m = lifted(state, batch)
        out["metric_total"] = float(np.asarray(m["sent_bytes_wire_real"])[0])
        # f32-quantized comparison — the metric's on-device carrier
        # (see audit_config)
        out["metric_match"] = (
            out["metric_total"] == float(np.float32(derived_total))
        )
    return out


def oracle_rank_coupling() -> Tuple[bool, str]:
    """An undeclared ppermute (offset +2) smuggled into the metrics:
    cross-rank information flow outside the declared exchange."""
    cfg = config_by_name("event_masked_f32_arena_obs")
    state, step, topo = build(cfg)

    def bad(state, batch):
        ns, m = step(state, batch)
        m = dict(m)
        m["leak"] = lax.ppermute(
            m["loss"], topo.axes[0],
            [((r + 2) % N_RANKS, r) for r in range(N_RANKS)],
        )
        return ns, m

    rep = _audit_lifted(cfg, spmd(bad, topo), state, _batch(cfg))
    detected = bool(rep["undeclared_offsets"]) or bool(rep["wire_problems"])
    return detected, (
        f"undeclared exchange offsets {rep['undeclared_offsets']}"
    )


def oracle_rank_roll() -> Tuple[bool, str]:
    """A roll across the STACKED rank axis outside the per-rank fn —
    the classic 'peek at your neighbor through the lift' bug."""
    cfg = config_by_name("event_masked_f32_tree")
    state, step, topo = build(cfg)
    inner = spmd(step, topo)

    def bad(state, batch):
        ns, m = inner(state, batch)
        leaf = jax.tree.leaves(ns.params)[0]
        m = dict(m)
        m["leak"] = jnp.sum(leaf * jnp.roll(leaf, 1, axis=0), axis=tuple(
            range(1, leaf.ndim)
        ))
        return ns, m

    rep = _audit_lifted(cfg, bad, state, _batch(cfg))
    return rep["violations"] > 0, (
        f"{rep['violations']} rank-flow violations: "
        f"{rep['violation_details'][:2]}"
    )


def oracle_wire_dtype_upcast() -> Tuple[bool, str]:
    """The bf16 wire downcast silently dropped: lanes ship f32 while
    the accounting still claims 2 bytes/element."""
    cfg = config_by_name("event_masked_bf16_arena")
    orig = collectives._wire_out
    try:
        collectives._wire_out = lambda x, wire: x  # the sabotage
        state, step, topo = build(cfg)
        rep = _audit_lifted(cfg, spmd(step, topo), state, _batch(cfg))
    finally:
        collectives._wire_out = orig
    detected = bool(rep["wire_problems"]) and not rep["formula_match"]
    return detected, f"wire problems {rep['wire_problems'][:2]}"


def oracle_extra_ravel() -> Tuple[bool, str]:
    """A second full-model flatten creeping into the arena step — the
    regression the op budget exists to stop."""
    cfg = config_by_name("event_masked_f32_arena_obs")
    state, step, topo = build(cfg)

    def bad(state, batch):
        ns, m = step(state, batch)
        m = dict(m)
        m["extra"] = jnp.sum(ravel_pytree(ns.params)[0])
        return ns, m

    rep = _audit_lifted(cfg, spmd(bad, topo), state, _batch(cfg))
    return rep["ravel_count"] > cfg.ravel_budget, (
        f"{rep['ravel_count']} full-model ravels > budget "
        f"{cfg.ravel_budget}"
    )


def oracle_byte_formula_drift() -> Tuple[bool, str]:
    """The accounting formula forgets the fire-bit lane: the metric the
    step reports no longer equals what the trace actually ships."""
    cfg = config_by_name("event_masked_f32_tree")
    orig = collectives.wire_real_bytes_per_neighbor

    def broken(n_params, n_leaves, wire=None, compact_capacity=None,
               fire_bits=False):
        return orig(n_params, n_leaves, wire,
                    compact_capacity=compact_capacity, fire_bits=False)

    try:
        collectives.wire_real_bytes_per_neighbor = broken
        state, step, topo = build(cfg)
        rep = _audit_lifted(
            cfg, spmd(step, topo), state, _batch(cfg), run_metric=True
        )
    finally:
        collectives.wire_real_bytes_per_neighbor = orig
    return rep["metric_match"] is False, (
        f"metric {rep['metric_total']} != derived wire truth"
    )


def oracle_host_callback() -> Tuple[bool, str]:
    """A host callback inside the traced step — the sync the zero-
    bubble pipeline exists to delete."""
    cfg = config_by_name("event_masked_f32_tree")
    state, step, topo = build(cfg)

    def bad(state, batch):
        ns, m = step(state, batch)
        jax.debug.callback(lambda x: None, m["loss"])
        return ns, m

    rep = _audit_lifted(cfg, spmd(bad, topo), state, _batch(cfg))
    return rep["callbacks"] > 0, f"{rep['callbacks']} host callbacks"


def _run_steps(cfg: AuditConfig, n_steps: int = 4, sabotage=None,
               sabotage_bucket=None):
    """Final params after `n_steps` eager vmap steps of one cell —
    the value harness the bounded-async oracles drive. `sabotage`
    temporarily rebinds train.steps' async_delivery_commit (the
    monolithic queue seam); `sabotage_bucket` rebinds
    async_bucket_commit (the per-bucket queue seam of the composed
    schedule)."""
    from eventgrad_tpu.train import steps as steps_mod

    batch = _batch(cfg)
    orig = steps_mod.async_delivery_commit
    orig_b = steps_mod.async_bucket_commit
    try:
        if sabotage is not None:
            # steps.py resolves the name at TRACE time (module global),
            # so building the step under the rebinding suffices
            steps_mod.async_delivery_commit = sabotage
        if sabotage_bucket is not None:
            steps_mod.async_bucket_commit = sabotage_bucket
        state, step, topo = build(cfg)
        lifted = spmd(step, topo)
        for _ in range(n_steps):
            state, _m = lifted(state, batch)
    finally:
        steps_mod.async_delivery_commit = orig
        steps_mod.async_bucket_commit = orig_b
    return state


def oracle_late_delivery_drift() -> Tuple[bool, str]:
    """The bounded-async commit sabotaged by ONE pass: the visible
    buffers handed to the mix are the PRE-arrival ones (a classic
    off-by-one between commit-on-arrival and the mix read). The
    equivalence contract — staleness=2 under the all-baseline lag
    schedule is BITWISE staleness=1 (a late delivery is a deferred
    fire, nothing more) — must catch it: the sabotaged engine's
    trajectory diverges from the staleness=1 reference."""
    from eventgrad_tpu.parallel import events as events_mod

    cfg2 = config_by_name("event_masked_f32_arena_stale2_chaos")
    cfg2 = dataclasses.replace(cfg2, chaos=False)  # pure-baseline lags
    cfg1 = dataclasses.replace(cfg2, name="stale1_ref", staleness=1)

    def sabotaged(state, cands, effs, delivered, lag_vec, pass_num,
                  spec, bound, cand_scales=None):
        new_state, bufs, stale, late = events_mod.async_delivery_commit(
            state, cands, effs, delivered, lag_vec, pass_num, spec, bound,
            cand_scales=cand_scales,
        )
        return new_state, state.bufs, stale, late  # mix reads PRE-arrival

    ref = _run_steps(cfg1)
    good = _run_steps(cfg2)
    bad = _run_steps(cfg2, sabotage=sabotaged)

    def _same(a, b):
        return all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params))
        )

    clean_holds = _same(ref, good)
    detected = clean_holds and not _same(ref, bad)
    return detected, (
        "clean D=2 == D=1 bitwise; sabotaged commit-on-arrival "
        "diverges from the deferred-fire reference"
        if detected else "equivalence harness failed to fire"
    )


def oracle_bucket_queue_skew() -> Tuple[bool, str]:
    """ONE bucket's delivery queue shifted by a slot WITHOUT its edge
    clock (the composed queue-in-bucket carry desynchronized: payload
    slots rotated, the scalar sent/late ledger untouched). The
    bitwise contract of the composed stack — bucketed D=2 under
    all-baseline lags ≡ D=1 — must catch it: a queue whose slots no
    longer line up with the clock commits the wrong pass's payload
    for that bucket, and the trajectory diverges from the reference
    while the clean composed run stays bitwise."""
    from eventgrad_tpu.parallel import events as events_mod

    cfg2 = dataclasses.replace(
        config_by_name("event_masked_f32_arena_b4"),
        name="b4_stale2", staleness=2,
    )
    cfg1 = dataclasses.replace(cfg2, name="b4_stale1_ref", staleness=1)

    def skewed(slots, here, cand, eff, last, seg, bucket=None,
               cand_scale=None, last_scale=None):
        buf, ncs, nes, nss, bs = events_mod.async_bucket_commit(
            slots, here, cand, eff, last, seg, bucket=bucket,
            cand_scale=cand_scale, last_scale=last_scale,
        )
        if bucket == 0:
            # rotate bucket 0's payload queue one slot; the clock
            # (async_delivery_plan's sent/late scalars) stays put
            ncs = tuple(ncs[1:]) + (ncs[0],)
            nes = tuple(nes[1:]) + (nes[0],)
        return buf, ncs, nes, nss, bs

    ref = _run_steps(cfg1)
    good = _run_steps(cfg2)
    bad = _run_steps(cfg2, sabotage_bucket=skewed)

    def _same(a, b):
        return all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves(a.params),
                            jax.tree.leaves(b.params))
        )

    clean_holds = _same(ref, good)
    detected = clean_holds and not _same(ref, bad)
    return detected, (
        "clean composed bucketed D=2 == D=1 bitwise; skewing one "
        "bucket's queue against its clock diverges"
        if detected else "composed equivalence harness failed to fire"
    )


def oracle_bucket_undeclared_offset() -> Tuple[bool, str]:
    """One BUCKET's wire lane re-shipped at an undeclared offset (+2)
    in the bucketed schedule — per-bucket exchanges must stay on the
    topology's declared offsets like every monolithic lane (ISSUE 10's
    seeded oracle leg)."""
    cfg = config_by_name("event_masked_f32_arena_b4")
    state, step, topo = build(cfg)

    def bad(state, batch):
        ns, m = step(state, batch)
        m = dict(m)
        # neighbor 0's bucket-1 receive buffer, shipped off-ring
        m["leak"] = lax.ppermute(
            ns.event.bufs[0][1], topo.axes[0],
            [((r + 2) % N_RANKS, r) for r in range(N_RANKS)],
        )
        return ns, m

    rep = _audit_lifted(cfg, spmd(bad, topo), state, _batch(cfg))
    detected = bool(rep["undeclared_offsets"]) or bool(rep["wire_problems"])
    return detected, (
        f"undeclared exchange offsets {rep['undeclared_offsets']}"
    )


def oracle_conv_rank_merge() -> Tuple[bool, str]:
    """The conv batching rule's rank-major feature merge WITHOUT the
    group confinement that makes it legal: per-rank channels folded
    into one feature dim and convolved with feature_group_count=1 —
    every output channel reads every rank's channels (ISSUE 12's conv
    seeded oracle; the legal merge carries feature_group_count
    divisible by n_ranks and audits clean in the lenet/resnet cells)."""
    cfg = config_by_name("lenet_masked_f32_arena")
    state, step, topo = build(cfg)
    inner = spmd(step, topo)

    def bad(state, batch):
        ns, m = inner(state, batch)
        x, _ = batch  # stacked [n, B, H, W, C]
        n, b = x.shape[0], x.shape[1]
        # the rank-major merge itself is the LEGAL blocked layout...
        merged = jnp.transpose(x, (1, 2, 3, 0, 4)).reshape(
            b, x.shape[2], x.shape[3], n * x.shape[4]
        )
        # ...but convolving it with fgc=1 contracts across ranks
        kern = jnp.ones((3, 3, n * x.shape[4], 2), x.dtype)
        mixed = lax.conv_general_dilated(
            merged, kern, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        m = dict(m)
        m["leak"] = jnp.sum(mixed)
        return ns, m

    rep = _audit_lifted(cfg, bad, state, _batch(cfg))
    detected = any(
        "feature groups" in r or "conv" in r
        for r in rep["violation_details"]
    )
    return detected and rep["violations"] > 0, (
        f"{rep['violations']} violations: {rep['violation_details'][:1]}"
    )


def oracle_unregistered_kernel() -> Tuple[bool, str]:
    """A pallas_call whose kernel has NO declared rank-dim signature —
    an opaque boundary the dataflow cannot see through must stay a
    violation, or any future kernel would silently bypass the audit."""
    from jax.experimental import pallas as pl

    cfg = config_by_name("event_masked_f32_arena_obs")
    state, step, topo = build(cfg)

    def _leak_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def bad(state, batch):
        ns, m = step(state, batch)
        val = jnp.broadcast_to(m["loss"], (8, 128)).astype(jnp.float32)
        m = dict(m)
        m["leak"] = jnp.sum(pl.pallas_call(
            _leak_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(val))
        return ns, m

    rep = _audit_lifted(cfg, spmd(bad, topo), state, _batch(cfg))
    detected = any(
        "unregistered pallas kernel" in r for r in rep["violation_details"]
    )
    return detected, f"{rep['violation_details'][:1]}"


def oracle_attention_cross_rank_gather() -> Tuple[bool, str]:
    """A data-dependent gather ACROSS the rank axis — the bug a sloppy
    cross-rank attention port would introduce (each rank attending to a
    peer chosen by its own activations instead of the topology's
    declared ring offsets)."""
    cfg = config_by_name("xfmr_masked_f32_arena")
    state, step, topo = build(cfg)
    inner = spmd(step, topo)

    def bad(state, batch):
        ns, m = inner(state, batch)
        leaf = jax.tree.leaves(ns.params)[0]
        # route by data: the 'key' rank each rank reads is picked by
        # the per-rank losses, not a declared constant permutation
        idx = jnp.argsort(m["loss"])
        m = dict(m)
        m["leak"] = jnp.sum(
            jnp.take(leaf, idx, axis=0),
            axis=tuple(range(1, leaf.ndim)),
        )
        return ns, m

    rep = _audit_lifted(cfg, bad, state, _batch(cfg))
    detected = any(
        "across the rank axis" in r for r in rep["violation_details"]
    )
    return detected and rep["violations"] > 0, (
        f"{rep['violations']} violations: {rep['violation_details'][:1]}"
    )


def oracle_partition_overlap() -> Tuple[bool, str]:
    """A partition geometry that double-claims a leaf (two ranks both
    'own' it) — the silent corruption a hand-edited partition table
    would introduce: overlapping sends are last-writer-wins on the
    receive buffer, so training still runs, just wrong. The sabotaged
    masks feed BOTH the traced ownership vectors and the audit's
    validate_partitions check; the micro cell's partitions_ok must go
    false."""
    cfg = config_by_name("event_micro_compact_f32_arena")
    orig = policy_lib.partition_masks

    def overlapping(spec, n_parts):
        masks = [list(m) for m in orig(spec, n_parts)]
        if len(masks) >= 2:
            # partition 0 also claims partition 1's first leaf
            grab = next(
                (i for i, on in enumerate(masks[1]) if on), None
            )
            if grab is not None:
                masks[0][grab] = True
        return tuple(tuple(m) for m in masks)

    try:
        policy_lib.partition_masks = overlapping
        rep = audit_config(cfg, run_metric=False)
    finally:
        policy_lib.partition_masks = orig
    return rep["partitions_ok"] is False and not clean(rep), (
        f"partitions_ok={rep['partitions_ok']} "
        f"(sizes {[p['size'] for p in (rep['partitions'] or [])]})"
    )


def oracle_stale_scale_reuse() -> Tuple[bool, str]:
    """The carrier-resident commit sabotaged to REUSE the resident
    scales: a fired leaf's int8 carrier rows are overwritten with the
    candidate's payload but keep the PREVIOUS quantization scale — the
    classic value/scale tearing a hand-rolled carrier commit would
    introduce (the buffers still look plausible; the dequantized mix
    just reads wrongly-scaled neighbors). The carrier contract —
    resident wire-dtype buffers dequantized at the mix read are
    BITWISE the f32-resident twin — must catch it: the clean carrier
    cell stays equal to the f32 reference while the torn commit's
    trajectory diverges."""
    cfgc = config_by_name("event_masked_int8_arena_carrier")
    cfgf = dataclasses.replace(cfgc, name="carrier_f32_ref", carrier=False)

    def torn(cand_scales, effs, last_scales):
        return last_scales  # values commit, scales don't

    def run(cfg, sabotage=None):
        orig = collectives.commit_carrier_scales
        try:
            if sabotage is not None:
                # steps.py resolves the name at TRACE time (module
                # global), so building the step under the rebinding
                # suffices
                collectives.commit_carrier_scales = sabotage
            state, step, topo = build(cfg)
            lifted = spmd(step, topo)
            batch = _batch(cfg)
            for _ in range(4):
                state, _m = lifted(state, batch)
        finally:
            collectives.commit_carrier_scales = orig
        return state

    ref = run(cfgf)
    good = run(cfgc)
    bad = run(cfgc, sabotage=torn)

    def _same(a, b):
        return all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(
                jax.tree.leaves(a.params), jax.tree.leaves(b.params)
            )
        )

    clean_holds = _same(ref, good)
    detected = clean_holds and not _same(ref, bad)
    return detected, (
        "clean carrier cell == f32-resident twin bitwise; the "
        "scale-reuse commit diverges"
        if detected else "carrier equivalence harness failed to fire"
    )


ORACLES = {
    "rank_coupling_ppermute": oracle_rank_coupling,
    "late_delivery_drift": oracle_late_delivery_drift,
    # ISSUE 20: the composed queue-in-bucket carry
    "bucket_queue_skew": oracle_bucket_queue_skew,
    "bucket_undeclared_offset": oracle_bucket_undeclared_offset,
    "rank_coupling_roll": oracle_rank_roll,
    "wire_dtype_upcast": oracle_wire_dtype_upcast,
    "extra_full_ravel": oracle_extra_ravel,
    "byte_formula_drift": oracle_byte_formula_drift,
    "host_callback": oracle_host_callback,
    # ISSUE 12: the full-geometry legs
    "conv_rank_merge": oracle_conv_rank_merge,
    "unregistered_kernel": oracle_unregistered_kernel,
    "attention_cross_rank_gather": oracle_attention_cross_rank_gather,
    # ISSUE 16: partitioned trigger policies
    "partition_overlap": oracle_partition_overlap,
    # ISSUE 17: carrier-resident gossip state
    "stale_scale_reuse": oracle_stale_scale_reuse,
}


def run_oracles() -> List[Dict[str, Any]]:
    out = []
    for name, fn in ORACLES.items():
        detected, reason = fn()
        out.append({"name": name, "detected": bool(detected),
                    "reason": reason})
    return out


# --- seeded MESH oracles (shard_map lift) ----------------------------------
#
# Kept in their own registry: they trace real-mesh programs, so they
# need the shard_map transform plus >= N_RANKS devices — environments
# without either still run every vmap oracle above. Exercised tier-1
# behind `requires_shard_map` (tests/test_audit.py) and pinned in
# artifacts/mesh_ablation_cpu.json (tools/mesh_ablation.py).


def oracle_mesh_undeclared_offset() -> Tuple[bool, str]:
    """An undeclared ppermute (offset +2) smuggled into the MESH
    program's metrics: the shard_map twin of `oracle_rank_coupling` —
    inside the mesh lift collectives stay primitives, so the auditor
    must flag the stray offset in `shard_lift_report` directly."""
    from eventgrad_tpu.parallel.spmd import build_mesh

    cfg = config_by_name("event_masked_f32_arena_obs")
    state, step, topo = build(cfg)

    def bad(state, batch):
        ns, m = step(state, batch)
        m = dict(m)
        m["leak"] = lax.ppermute(
            m["loss"], topo.axes[0],
            [((r + 2) % N_RANKS, r) for r in range(N_RANKS)],
        )
        return ns, m

    mesh = build_mesh(topo)
    lifted = spmd(bad, topo, mesh=mesh)
    closed = jax.make_jaxpr(lifted)(state, _batch(cfg))
    rep = shard_lift_report(closed, topo, cfg.name + "+mesh_leak")
    detected = not rep["offsets_ok"]
    extra = sorted(
        set(rep["exchange_offsets"]) - set(rep["declared_offsets"])
    )
    return detected, f"undeclared mesh ppermute offsets {extra}"


MESH_ORACLES = {
    "mesh_undeclared_offset": oracle_mesh_undeclared_offset,
}


def run_mesh_oracles() -> List[Dict[str, Any]]:
    out = []
    for name, fn in MESH_ORACLES.items():
        detected, reason = fn()
        out.append({"name": name, "detected": bool(detected),
                    "reason": reason})
    return out
