"""Accuracy parity at the FULL MNIST CNN-2 op-point (VERDICT item 4).

Both legs at the reference scale — 1168 passes (10 epochs x ~117 steps,
dmnist/event/event.cpp:255 scaled to the synthetic set), batch 64/rank,
lr 0.05, sequential sampler, warmup 30, horizon 1.0 — eventgrad vs dpsgd,
consensus-model test accuracy for each. This is the "comparable accuracy
at ~70% savings" half of the reference's headline claim
(/root/reference/README.md:4), measured rather than asserted.

Output: one JSON line; committed as artifacts/mnist_parity_r2_cpu.json.
Usage: JAX_PLATFORMS=cpu python tools/mnist_fullscale_parity.py
"""

import json
import os
import time

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # runnable uninstalled

import jax

from eventgrad_tpu.utils import compile_cache

compile_cache.honor_cpu_pin()
# persistent XLA cache: repeated invocations must not re-pay the jit
# compile per process (no-op on the CPU backend)
compile_cache.enable()

from eventgrad_tpu.data.datasets import load_or_synthesize
from eventgrad_tpu.models import CNN2
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train


def main() -> None:
    topo = Ring(8)
    cfg = EventConfig(adaptive=True, horizon=1.0, warmup_passes=30)
    x, y = load_or_synthesize("mnist", None, "train", n_synth=2048)
    xt, yt = load_or_synthesize("mnist", None, "test", n_synth=512)
    kw = dict(epochs=292, batch_size=64, learning_rate=0.05,
              random_sampler=False, log_every_epoch=False)

    out = {"passes": 1168, "horizon": 1.0, "warmup": 30, "n_ranks": 8}
    t0 = time.time()
    st, hist = train(CNN2(), topo, x, y, algo="eventgrad", event_cfg=cfg, **kw)
    cons = consensus_params(st.params)
    stats = rank0_slice(st.batch_stats)
    out["test_acc_eventgrad"] = round(
        evaluate(CNN2(), cons, stats, xt, yt)["accuracy"], 2
    )
    out["msgs_saved_pct"] = round(hist[-1]["msgs_saved_pct"], 2)
    out["final_loss_eventgrad"] = round(hist[-1]["loss"], 4)
    out["wall_s_eventgrad"] = round(time.time() - t0, 1)

    t0 = time.time()
    st, hist = train(CNN2(), topo, x, y, algo="dpsgd", **kw)
    cons = consensus_params(st.params)
    stats = rank0_slice(st.batch_stats)
    out["test_acc_dpsgd"] = round(
        evaluate(CNN2(), cons, stats, xt, yt)["accuracy"], 2
    )
    out["final_loss_dpsgd"] = round(hist[-1]["loss"], 4)
    out["wall_s_dpsgd"] = round(time.time() - t0, 1)
    out["acc_gap_vs_dpsgd"] = round(
        out["test_acc_eventgrad"] - out["test_acc_dpsgd"], 2
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.makedirs(os.path.join(repo, "artifacts"), exist_ok=True)
    with open(os.path.join(repo, "artifacts", "mnist_parity_r2_cpu.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
