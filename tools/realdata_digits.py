"""Real-data accuracy validation within a zero-egress environment.

VERDICT round-2 'What's missing' #3: every accuracy figure so far is on
synthetic class-prototype data; real MNIST/CIFAR bytes are unreachable
(no egress, no on-disk mirror — only loader code ships in the image).
The one real image dataset available offline is scikit-learn's bundled
UCI handwritten digits (1,797 genuine 8x8 grayscale scans, 10 classes) —
not MNIST, but real pixels with real intra-class variation, which is the
property the synthetic prototypes lack.

This runs the full EventGraD vs D-PSGD comparison end-to-end on those
real images (upsampled 8x8 -> 32x32, center-cropped to the 28x28 MNIST
geometry so the unmodified CNN-2 model and the reference MNIST op-point
apply): same 8-rank ring, batch 64/rank equivalent scaled to the tiny
corpus, lr 0.05, sequential sampler (event.cpp:103,145,227,255).

Writes artifacts/realdata_digits_r3_cpu.json.

Usage: python tools/realdata_digits.py
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def _load() -> tuple:
    # one loader repo-wide: the same real scans ship as the CLI's
    # `--dataset digits` (data/datasets.py::load_digits)
    from eventgrad_tpu.data.datasets import load_digits

    return load_digits("train"), load_digits("test")


def main() -> None:
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from eventgrad_tpu.models import CNN2
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    (x, y), (xt, yt) = _load()
    # 1440 train / 8 ranks / batch 20 = 9 steps per epoch
    topo = Ring(8)
    batch, epochs = 20, 60  # 540 passes
    x, y, xt, yt = jnp.asarray(x), jnp.asarray(y), jnp.asarray(xt), jnp.asarray(yt)

    out = {"dataset": "sklearn-digits (real 8x8 scans, upsampled to 28x28)",
           "n_train": int(x.shape[0]), "n_test": int(xt.shape[0]),
           "n_ranks": topo.n_ranks, "batch_per_rank": batch,
           "epochs": epochs,
           "passes": epochs * (int(x.shape[0]) // (batch * topo.n_ranks))}
    common = dict(epochs=epochs, batch_size=batch, learning_rate=0.05,
                  random_sampler=False, log_every_epoch=False)

    for tag, algo, cfg in (
        ("refpure", "eventgrad",
         EventConfig(adaptive=True, horizon=1.0, warmup_passes=30)),
        ("stabilized", "eventgrad",
         EventConfig(adaptive=True, horizon=1.05, warmup_passes=30,
                     max_silence=50)),
        ("dpsgd", "dpsgd", None),
    ):
        kw = dict(common)
        if cfg is not None:
            kw["event_cfg"] = cfg
        t0 = time.perf_counter()
        state, hist = train(CNN2(), topo, x, y, algo=algo, **kw)
        cons = consensus_params(state.params)
        stats0 = rank0_slice(state.batch_stats)
        acc = evaluate(CNN2(), cons, stats0, xt, yt)["accuracy"]
        out[f"test_acc_{tag}"] = round(acc, 2)
        out[f"wall_s_{tag}"] = round(time.perf_counter() - t0, 1)
        if algo == "eventgrad":
            out[f"msgs_saved_pct_{tag}"] = round(
                hist[-1]["msgs_saved_pct"], 2
            )
        print(tag, out.get(f"msgs_saved_pct_{tag}"), acc, flush=True)

    out["acc_gap_refpure"] = round(
        out["test_acc_refpure"] - out["test_acc_dpsgd"], 2
    )
    out["acc_gap_stabilized"] = round(
        out["test_acc_stabilized"] - out["test_acc_dpsgd"], 2
    )
    path = os.path.join(repo, "artifacts", "realdata_digits_r3_cpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
