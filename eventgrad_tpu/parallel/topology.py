"""Neighbor topologies on named mesh axes.

TPU-native replacement for the reference's MPI ring arithmetic
(`left = (rank-1+N) % N`, `right = (rank+1) % N`,
/root/reference/dmnist/event/event.cpp:113-122,
/root/reference/dmnist/decent/decent.cpp:56-64): instead of integer rank
bookkeeping, a topology names mesh axes and enumerates neighbor *shifts*.
Each shift compiles to a single `jax.lax.ppermute` that rides the ICI
links of the physical TPU torus.

A `Ring` has two neighbors (offset -1 and +1 on one axis) and reproduces
the reference exactly. A `Torus` generalizes to 4 neighbors on two axes —
the BASELINE stress configuration (v4-256 2D torus) — with uniform
1/(1+n_neighbors) mixing.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class NeighborSpec:
    """One neighbor direction: a shift of `offset` along mesh axis `axis`.

    `offset=-1` means "the value I receive comes from my left neighbor"
    (rank r receives from rank r-1 mod n, matching the reference's `left`).
    """

    axis: str
    offset: int

    @property
    def name(self) -> str:
        sign = "m" if self.offset < 0 else "p"
        return f"{self.axis}_{sign}{abs(self.offset)}"


@dataclasses.dataclass(frozen=True)
class Topology:
    """A named-axis layout of ranks plus the gossip neighbor set.

    Three axis classes:
      * gossip axes (`gossip_axes`, default all): carry the decentralized
        neighbor exchanges; per-rank parameters differ and mix by averaging.
      * replicated aux axes (everything else not in `sharded_axes`): e.g. a
        sequence-parallel axis — ranks hold identical parameters and pmean
        their gradients (see `ring_attention` and `train.steps`).
      * sharded axes (`sharded_axes`): tensor/expert parallelism — each rank
        owns a distinct parameter shard; activations are synchronized inside
        the model (psum/all_to_all in the TP layers), so the train step must
        NOT average parameters or gradients across them.
    """

    axes: Tuple[str, ...]
    shape: Tuple[int, ...]
    gossip_axes: Tuple[str, ...] = None  # type: ignore[assignment]
    sharded_axes: Tuple[str, ...] = ()
    #: aux axes that SHARD the data (hierarchical data parallelism):
    #: ranks along them hold identical parameters and pmean gradients like
    #: any aux axis, but each sees its own data shard — synchronous
    #: allreduce subgroups inside every gossip rank
    data_aux_axes: Tuple[str, ...] = ()

    def __post_init__(self):
        if len(self.axes) != len(self.shape):
            raise ValueError(f"axes {self.axes} vs shape {self.shape} length mismatch")
        if any(s < 1 for s in self.shape):
            raise ValueError(f"invalid topology shape {self.shape}")
        if self.gossip_axes is None:
            object.__setattr__(
                self,
                "gossip_axes",
                tuple(
                    a
                    for a in self.axes
                    if a not in self.sharded_axes
                    and a not in self.data_aux_axes
                ),
            )
        elif any(a not in self.axes for a in self.gossip_axes):
            raise ValueError(f"gossip_axes {self.gossip_axes} not all in {self.axes}")
        if any(a not in self.axes for a in self.sharded_axes):
            raise ValueError(f"sharded_axes {self.sharded_axes} not all in {self.axes}")
        if set(self.gossip_axes) & set(self.sharded_axes):
            raise ValueError("an axis cannot be both gossip and sharded")
        if any(a not in self.axes for a in self.data_aux_axes):
            raise ValueError(
                f"data_aux_axes {self.data_aux_axes} not all in {self.axes}"
            )
        if set(self.data_aux_axes) & (
            set(self.gossip_axes) | set(self.sharded_axes)
        ):
            raise ValueError(
                "data_aux_axes must be replicated aux axes (not gossip or "
                "sharded)"
            )

    @property
    def n_ranks(self) -> int:
        return math.prod(self.shape)

    @property
    def n_gossip_ranks(self) -> int:
        """Extent of the gossip axes."""
        return math.prod(self.axis_size(a) for a in self.gossip_axes)

    @property
    def is_hybrid(self) -> bool:
        """True when the mesh carries axes that do NOT shard the data
        (sp/tp/pp/ep): batches then need `expand_to_mesh` replication or
        chunking, and consensus averaging across all ranks would mix
        differently-sharded parameters."""
        return self.n_data_ranks != self.n_ranks

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Axes that shard the DATA: the gossip axes plus any declared
        `data_aux_axes` (hierarchical data parallelism). Other aux/sharded
        axes (sp/tp/pp/ep) replicate or chunk batches instead."""
        return tuple(
            a
            for a in self.axes
            if a in self.gossip_axes or a in self.data_aux_axes
        )

    @property
    def n_data_ranks(self) -> int:
        """The data-parallel degree: batches shard across `data_axes`."""
        return math.prod(self.axis_size(a) for a in self.data_axes)

    @property
    def aux_axes(self) -> Tuple[str, ...]:
        """Replicated non-gossip axes (sequence/aux parallelism); ranks along
        these hold identical parameters and synchronize gradients by pmean."""
        return tuple(
            a
            for a in self.axes
            if a not in self.gossip_axes and a not in self.sharded_axes
        )

    @property
    def neighbors(self) -> Tuple[NeighborSpec, ...]:
        """Neighbor shifts, one per gossip partner.

        On an axis of size 1 there are no neighbors in that direction;
        on an axis of size 2, -1 and +1 are the same rank but the reference
        still sends both messages (two puts), so we keep both shifts.
        """
        specs = []
        for axis, size in zip(self.axes, self.shape):
            if size > 1 and axis in self.gossip_axes:
                specs.append(NeighborSpec(axis, -1))
                specs.append(NeighborSpec(axis, +1))
        return tuple(specs)

    @property
    def n_neighbors(self) -> int:
        return len(self.neighbors)

    @property
    def mix_weight(self) -> float:
        """Uniform gossip mixing weight: 1/3 on a ring (event.cpp:469-471),
        1/5 on a 2D torus."""
        return 1.0 / (1.0 + self.n_neighbors)

    def axis_size(self, axis: str) -> int:
        return self.shape[self.axes.index(axis)]

    def neighbor_source(self, rank: int, spec: NeighborSpec) -> int:
        """Flat rank whose payload arrives at `rank` via `spec`, under the
        row-major stacked layout (matches collectives.recv_from's ppermute:
        rank r receives from the rank `spec.offset` away along `spec.axis`,
        so offset=-1 is the reference's `left`, decent.cpp:56-64)."""
        import numpy as np

        ax = self.axes.index(spec.axis)
        coords = list(np.unravel_index(rank, self.shape))
        coords[ax] = (coords[ax] + spec.offset) % self.shape[ax]
        return int(np.ravel_multi_index(coords, self.shape))


def Ring(n: int, axis: str = "ring") -> Topology:
    """1-D ring of `n` ranks — the reference's only topology."""
    return Topology(axes=(axis,), shape=(n,))


def Torus(nx: int, ny: int, axes: Tuple[str, str] = ("x", "y")) -> Topology:
    """2-D torus (nx × ny) with 4 neighbors per rank."""
    return Topology(axes=tuple(axes), shape=(nx, ny))
