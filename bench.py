"""Benchmark harness — the LAST JSON line on stdout is the driver's result.

(One line in the common case; under a generous wall budget the two-phase
supervisor flushes a guaranteed conservative line early and may follow
it with one strictly-better upgraded line — see EG_BENCH_TOTAL_S below.)

Headline metric (BASELINE.json): messages-saved-% of EventGraD vs D-PSGD at
the CIFAR-10 operating point (reference claim ~60%, /root/reference/README.md:4),
with test accuracy of the consensus model compared against a D-PSGD run of
the SAME op-point (the reference's "comparable accuracy" claim) — the
D-PSGD comparison leg runs in EVERY tier; epochs shrink before the leg is
ever dropped. Flagship config: ResNet-18-as-coded (3 blocks/stage, ~17.4M
params), 8-rank ring, global batch 256, SGD momentum 0.9, adaptive
threshold, ~3.9k passes (the reference's 20-epoch x ~195-step CIFAR scale,
dcifar10/event/event.cpp:31-36).

All 8 ranks are vmap-simulated on the local accelerator (the single-chip
lifting path; identical trajectories to the shard_map path per
test_train_equivalence.py::test_shard_map_matches_vmap).

Also emitted: single-chip MFU for the flagship step (analytic XLA FLOPs from
compiled cost_analysis / measured steady-state step time / chip peak), the
`costmodel` block (obs/costmodel.py jaxpr walk: phase-split FLOPs/bytes,
roofline position against obs/devicespec.py peaks — populated on every
tier; obs/schema.py PERF_FIELDS), and wire-mode byte accounting (f32
native plus the derived bf16/int8 wire points — deterministic functions of
the measured fired counts, see train/steps.py wire accounting). The run
ends with a one-line step_ms/MFU trajectory delta against the committed
perf ledger (tools/perf_ledger.py) on stderr.

Data: synthetic class-prototype CIFAR-shaped set (no network egress here).
Augmentation stays OFF for synthetic data — the class prototypes' labels
are not crop/flip-invariant (the real-data CLI path applies it).

Secondary metric: the MNIST CNN-2 op-point (batch 64/rank, lr 0.05,
sequential sampler — reference claim ~70% messages saved) rides along as
`mnist_msgs_saved`.

Env contract (single source of truth, mirrored in REPRO.md):
  EG_BENCH_TIER       full | reduced | tiny | auto   (default auto:
                      full when the probed backend is TPU, reduced on CPU)
  EG_BENCH_DEADLINE_S per-attempt child wall budget (default 700)
  EG_BENCH_TOTAL_S    whole-bench wall budget (default 1150). Two-phase:
                      the attempt loop sizes itself against
                      min(total, 560) — the conservative window that
                      always yields a result line by ~7 min (an
                      accelerator attempt 1 reserves ~230 s of it so
                      the CPU fallback stays reachable even when the
                      tunnel wedges mid-run; the fallback tier
                      auto-shrinks reduced -> tiny). Budget left after
                      that guaranteed line funds ONE upgrade attempt
                      (reduced tier, full remaining budget, ladder top
                      rungs); its line prints only if strictly better
                      and uncollapsed. The LAST JSON line on stdout is
                      the result.
  EG_BENCH_UPGRADE    0 disables the upgrade phase (default on)
  EG_BENCH_FULL_REHEARSAL  1 + EG_BENCH_TIER=full: execute the full-tier
                      code path at miniature scale off-chip (config
                      "full-rehearsal"; never a real measurement)
  EG_BENCH_PROBE_S    device liveness probe deadline (default 60)
  EG_BENCH_HORIZON    CIFAR-leg adaptive horizon (default 1.05 — the
                      stabilized aggressive op-point; requires the
                      max-silence guard below)
  EG_BENCH_HORIZON_MNIST  MNIST-leg horizon (default per tier: 1.05 on
                      the full tier — proven 75.5% saved at -1.17pp over
                      1168 passes — and 1.0 reference-pure on the short
                      CPU tiers, whose MNIST miniature is fragile)
  EG_BENCH_MAX_SILENCE    bounded-staleness guard (default 50; 0 =
                      reference-pure trigger — see events.py)
  EG_BENCH_ATTEMPT_S  (internal: supervisor -> child) the wall budget
                      this attempt actually got; the full tier ladders
                      its CIFAR legs by it (events.pick_full_epochs:
                      61 / 30 / 12 epochs at >=420 / >=300 / below),
                      and the reduced tier sizes its own rungs from it
                      (pick_cifar_epochs, pick_mnist_rung). Manual
                      full-scale run: EG_BENCH_CHILD=1
                      EG_BENCH_ATTEMPT_S=3600 EG_BENCH_TIER=full
  EG_BENCH_OBS_TRACE  path: export a Chrome-trace/Perfetto span JSON of
                      the bench's own phases (the obs.Registry spans
                      around each train/eval leg — docs/OBSERVABILITY.md)
                      so a bench run can be inspected in chrome://tracing;
                      unset = spans are still recorded (host-side, ~free)
                      but nothing is written
  EG_BENCH_PIPELINE   0 pins the serial dispatch schedule (default on:
                      the zero-bubble pipeline of train/loop.py —
                      bitwise-identical training, host work overlapped;
                      the record carries `pipeline` and the measured
                      `host_bubble_frac` next to step_ms)
  EG_BENCH_CHAOS      chaos mode (robustness instead of savings): run the
                      tools/chaos_sweep.py drop-rate/recovery sweep and
                      emit ITS record as the last JSON line. "1" =
                      default points, or a comma list of drop rates
                      ("0,0.1,0.3"). In-process (no supervisor): the
                      sweep is a deterministic CPU-scale miniature.
Legacy aliases EG_BENCH_TINY=1 / EG_BENCH_CPU=1 map to tier tiny/reduced.
Identical behavior from `python bench.py` and the driver's invocation:
every knob above has exactly one default, read in one place.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

_VALID_TIERS = ("full", "reduced", "tiny", "auto")


def _tier() -> str:
    t = os.environ.get("EG_BENCH_TIER", "auto")
    # legacy aliases apply only when no explicit tier was requested
    if t == "auto" and os.environ.get("EG_BENCH_TINY") == "1":
        t = "tiny"
    elif t == "auto" and os.environ.get("EG_BENCH_CPU") == "1":
        t = "reduced"
    if t not in _VALID_TIERS:
        raise SystemExit(f"EG_BENCH_TIER={t!r}; expected one of {_VALID_TIERS}")
    if t == "auto":
        t = "full" if jax.default_backend() == "tpu" else "reduced"
    return t


def main() -> None:
    t_main = time.perf_counter()
    import jax.numpy as jnp

    from eventgrad_tpu.utils import compile_cache

    compile_cache.honor_cpu_pin()
    compile_cache.enable()

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import CNN2, LeNetCifar, ResNet18
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import (
        consensus_params, evaluate, rank0_slice, train,
    )
    from eventgrad_tpu.utils import trees

    tier = _tier()
    topo = Ring(8)
    # CIFAR headline leg: the stabilized op-point — aggressive horizon
    # (threshold GROWS between fires) with the bounded-staleness guard.
    # Measured at the reduced tier's 640-pass LeNet op-point: 64.6% saved
    # at accuracy gap 0.0 vs the D-PSGD twin, rising to 67.3% at 960
    # passes (artifacts/cifar_knee_r3_cpu.jsonl; without the guard
    # horizon 1.05 collapses on some seeds —
    # artifacts/horizon_stability_r2_cpu.jsonl). The MNIST leg's horizon
    # is per-tier (set with the tier op-points below): stabilized 1.05 at
    # full scale, the reference's neutral 1.0 on the short CPU tiers
    # whose CNN2/lr-0.05 miniature is accuracy-fragile.
    # The trigger config (incl. the reference-pure horizon drop — round-2
    # advisor finding) has ONE definition, shared with tools/
    # tpu_flagship.py: events.resolve_bench_trigger.
    from eventgrad_tpu.parallel.events import resolve_bench_trigger

    horizon, max_silence = resolve_bench_trigger(os.environ)

    # --- tier op-points -------------------------------------------------
    # full: the reference CIFAR scale (20 ep x ~195 steps ~= 3.9k passes,
    #   event.cpp:31-36) on the real ResNet-as-coded, bf16 compute.
    # reduced: sized for ONE CPU core inside the driver window — a few
    #   minutes of compute TOTAL across eventgrad + dpsgd + mnist legs,
    #   shrinking epochs/model, never dropping the D-PSGD leg.
    # tiny: smoke-runs the full code path in seconds (CI).
    downshifted = False
    if tier == "full":
        from eventgrad_tpu.parallel.events import (
            MNIST_FULLSCALE_OP_POINT, resolve_bench_trigger_mnist,
        )

        global_batch, n_train, n_test, epochs = 256, 16384, 2048, 61
        model = ResNet18(dtype=jnp.bfloat16)
        warmup = 30
        mnist_n, mnist_epochs, mnist_batch = MNIST_FULLSCALE_OP_POINT
        rehearsal = os.environ.get("EG_BENCH_FULL_REHEARSAL") == "1"
        # the supervisor exports the wall budget this child actually got
        # (EG_BENCH_ATTEMPT_S). The 61-epoch reference scale (3904
        # passes x 2 CIFAR legs + 1168 MNIST passes + up to 4 TPU
        # compiles) has never been timed through the flaky tunnel; under
        # a tight driver budget run the 30-epoch variant (1920 passes —
        # past the savings knee, ~70% on the measured trail) rather than
        # risk the deadline. An UNSET var means no deadline (direct
        # child run): full scale.
        att = os.environ.get("EG_BENCH_ATTEMPT_S")
        if att is not None and not rehearsal:
            # downshift the ResNet legs only (ladder in
            # events.pick_full_epochs — a short live window should still
            # capture chip evidence rather than lose the tier to the CPU
            # fallback): the MNIST CNN-2 leg is seconds on-chip and 1168
            # passes IS the ~70% claim's op-point
            from eventgrad_tpu.parallel.events import pick_full_epochs

            # same spawn-overhead convention as the reduced-tier rungs:
            # the kill clock started at child spawn, ~15 s before this
            # line (interpreter + jax import)
            new_epochs = pick_full_epochs(
                float(att) - (time.perf_counter() - t_main) - 15.0
            )
            if new_epochs != epochs:
                epochs = new_epochs
                downshifted = True
                import sys as _sys
                print(
                    f"full tier: budget {float(att):.0f}s, running the "
                    f"{epochs}-epoch CIFAR variant "
                    f"({epochs * (n_train // global_batch)} passes; MNIST "
                    "leg stays at full scale)", file=_sys.stderr,
                )
        # at full scale the stabilized MNIST op-point is proven: 75.5%
        # saved at -1.17pp over 1168 passes (artifacts/
        # mnist_stabilized_fullscale_r2_cpu.jsonl). The aggressive
        # horizon REQUIRES the guard — with it disabled
        # (EG_BENCH_MAX_SILENCE=0, the reference-pure request) the MNIST
        # leg drops back to the neutral horizon rather than run the
        # known-unstable 1.05-unguarded combination
        mnist_silence = max_silence
        # one definition with tools/tpu_flagship.py (events.py helper);
        # the generic EG_BENCH_HORIZON_MNIST read below re-applies the
        # same env override idempotently
        mnist_horizon_default = resolve_bench_trigger_mnist(
            os.environ, mnist_silence
        )
        if rehearsal:
            # off-chip rehearsal of the full-tier code path (round-3
            # verdict item 4: the 61-epoch tier had never executed
            # end-to-end before its first live TPU window). Identical
            # branches, model (ResNet18 bf16), and trigger resolution —
            # the scale (and, below, the warmup) is miniature, because XLA-CPU
            # runs the bf16 ResNet via emulation (a 256-global-batch
            # 2-epoch rehearsal blew an 83-minute deadline; 64/128 is
            # the measured-feasible size). The emitted JSON carries
            # config "full-rehearsal" so the run can never pass for a
            # real full-tier measurement.
            global_batch, n_train, n_test, epochs = 64, 128, 32, 2
            mnist_n, mnist_epochs, mnist_batch = 256, 2, 16
            # scale warmup with the miniature: at 4 passes a 30-pass
            # warmup would force-fire every pass and the post-warmup
            # trigger path — the thing worth rehearsing — would never run
            warmup = 2
            tier = "full-rehearsal"
    elif tier == "reduced":
        # CPU fallback: the reference's own LeNet-5 CIFAR model (M5,
        # dcifar10/common/nnet.hpp:3-33) instead of a gutted ResNet — it
        # is the faithful cheap CIFAR model AND ~5x cheaper per pass on
        # one core, buying the pass count the savings metric actually
        # needs. The epoch count is a pass-count ladder (mirrors the
        # MNIST one below): the floor is the measured 640-pass op-point
        # (stabilized 64.6% saved at accuracy gap 0.0, ~61 s + ~57 s on
        # one core — tier wall ~260 s against the ~300 s supervised
        # attempt); a window that also still funds the MNIST top rung
        # upgrades to 960 passes (67.31%) — events.pick_cifar_epochs
        # documents the budget math.
        from eventgrad_tpu.parallel.events import pick_cifar_epochs

        global_batch, n_train, n_test = 64, 1024, 256
        _att = os.environ.get("EG_BENCH_ATTEMPT_S")
        epochs = pick_cifar_epochs(
            float(_att) - 15.0 if _att else float("inf")
        )
        model = LeNetCifar()
        warmup = 10
        mnist_n, mnist_epochs, mnist_batch = 2048, 40, 64  # 160 passes
        # the short MNIST miniature is accuracy-fragile above horizon 1.0
        # even with the silence guard (measured knee,
        # artifacts/mnist_knee_r3_cpu.jsonl: 81.7% saved at 36.5% acc) —
        # reference-pure trigger here; the claim-level op-points ride in
        # mnist_proven and the full tier measures 1168 passes live.
        # When the attempt budget affords it, the leg upgrades itself to
        # a measured honest op-point (the budget-adaptive ladder below).
        mnist_horizon_default, mnist_silence = 1.0, 0
    else:  # tiny: ~2 min on one CPU core — the late-fallback budget tier
        global_batch, n_train, n_test, epochs = 64, 512, 128, 6  # 48 passes
        model = LeNetCifar()
        warmup = 5
        mnist_n, mnist_epochs, mnist_batch = 1024, 8, 16
        mnist_horizon_default, mnist_silence = 1.0, 0
    per_rank = global_batch // topo.n_ranks

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    xt, yt = load_or_synthesize("cifar10", None, "test", n_synth=n_test)
    event_cfg = EventConfig(
        adaptive=True, horizon=horizon, warmup_passes=warmup,
        max_silence=max_silence,
    )

    # Full (chip) tier: K-epoch jit blocks + device-resident data
    # (train/loop.py round-5 dispatch modes) amortize the tunnel's
    # per-dispatch latency — the wall/device-busy gap was 3.9x with
    # per-epoch dispatch (artifacts/tpu_trace/TRACE_SUMMARY.json). CPU
    # tiers keep per-epoch dispatch: no tunnel, and the measured rung
    # ladders were calibrated against it.
    k_disp = (
        int(os.environ.get("EG_EPOCHS_PER_DISPATCH", "8"))
        if tier in ("full", "full-rehearsal") else 1
    )
    # flat-arena hot path (train() auto-enables it; EG_BENCH_ARENA=0
    # pins the legacy tree path for A/B runs — tools/overhead_ablation.py
    # measures the same pair in isolation)
    bench_arena = os.environ.get("EG_BENCH_ARENA", "1") != "0"
    # zero-bubble dispatch pipeline (train/loop.py): host work overlaps
    # device compute; EG_BENCH_PIPELINE=0 pins the serial schedule (the
    # A/B knob of tools/bubble_decomposition.py). Training is bitwise-
    # identical either way — only the host schedule moves.
    bench_pipeline = os.environ.get("EG_BENCH_PIPELINE", "1") != "0"
    # bucketed gossip schedule (train/steps.py bucketed=K): pipeline the
    # per-bucket exchange under the update work — event legs only (the
    # D-PSGD twin has no event exchange to bucket); EG_BENCH_BUCKETED=K
    # turns it on, 0 (default) keeps the monolithic schedule. Training
    # is bitwise-identical either way (tests/test_bucketed.py).
    bench_bucketed = int(os.environ.get("EG_BENCH_BUCKETED", "0")) or None
    # SPMD lift: vmap (single-chip simulator, the historical default) vs
    # shard_map (real device mesh — one rank per device, the exchange is
    # actual ppermute collectives; docs/ARCHITECTURE.md "Mesh backends").
    # EG_BENCH_BACKEND=shard_map|auto runs the mesh; records carry the
    # backend so the perf ledger never gates mesh rows against vmap rows.
    bench_backend = os.environ.get("EG_BENCH_BACKEND", "vmap")
    # trigger policy of the event legs (parallel/policy.py registry):
    # EG_BENCH_POLICY=norm_delta|micro|hybrid pins it, empty/unset keeps
    # the algo default (norm_delta — the reference trigger the measured
    # rungs were calibrated against). Records carry rec["policy"], so
    # the perf ledger never gates one policy's rows against another's.
    bench_policy = os.environ.get("EG_BENCH_POLICY", "") or None
    # bounded-async gossip (train/steps.py staleness=D): event legs only
    # — D >= 2 carries per-edge D-slot delivery queues with commit-on-
    # arrival, the straggler-tolerant production config (composes with
    # bucketed/compact/carrier-resident; tools/straggler_ablation.py
    # measures the wall-clock claim). EG_BENCH_STALENESS=D turns it on,
    # 0 (default) keeps the lockstep step. Records carry rec["staleness"]
    # so the perf ledger never gates a bounded-async row against a
    # lockstep one.
    bench_staleness = int(os.environ.get("EG_BENCH_STALENESS", "0"))
    common = dict(
        epochs=epochs, batch_size=per_rank,
        learning_rate=1e-2, momentum=0.9,  # dcifar10/event/event.cpp:196-200
        random_sampler=True, log_every_epoch=False,
        epochs_per_dispatch=k_disp,
        arena=bench_arena,
        pipeline=bench_pipeline,
        backend=bench_backend,
    )

    # host span trace of the bench's own phases (obs.Registry): always
    # recorded (host-side tuples, ~free), exported only when
    # EG_BENCH_OBS_TRACE names a path
    from eventgrad_tpu.obs import Registry

    obs_reg = Registry(run_meta={"tool": "bench", "tier": tier})

    t0 = time.perf_counter()
    with obs_reg.span("cifar_eventgrad", cat="leg", tier=tier):
        state, hist = train(
            model, topo, x, y, algo="eventgrad", event_cfg=event_cfg,
            registry=obs_reg, bucketed=bench_bucketed,
            trigger_policy=bench_policy, staleness=bench_staleness,
            **common
        )
    wall_event = time.perf_counter() - t0
    with obs_reg.span("eval_eventgrad", cat="leg"):
        cons = consensus_params(state.params)
        stats0 = rank0_slice(state.batch_stats)
        test = evaluate(model, cons, stats0, xt, yt)

    # D-PSGD comparison leg — SAME op-point, every tier (the other half of
    # the reference's claim: comparable accuracy at the savings)
    t0 = time.perf_counter()
    with obs_reg.span("cifar_dpsgd", cat="leg", tier=tier):
        state_d, hist_d = train(
            model, topo, x, y, algo="dpsgd", registry=obs_reg, **common
        )
    wall_dpsgd = time.perf_counter() - t0
    with obs_reg.span("eval_dpsgd", cat="leg"):
        cons_d = consensus_params(state_d.params)
        stats_d = rank0_slice(state_d.batch_stats)
        test_d = evaluate(model, cons_d, stats_d, xt, yt)

    # secondary op-point: MNIST CNN-2, batch 64/rank, lr 0.05, sequential
    # sampler (event.cpp:103,145,227,255) — reference ~70%.
    # Budget-adaptive ladder (reduced tier): the 160-pass reference-pure
    # miniature is the floor that always fits; when the remaining attempt
    # budget affords a measured honest op-point the leg upgrades itself —
    # rung table and measured numbers live in events.pick_mnist_rung.
    # A direct child run with no EG_BENCH_ATTEMPT_S (= no deadline)
    # takes the top rung.
    if tier == "reduced":
        from eventgrad_tpu.parallel.events import pick_mnist_rung

        att_env = os.environ.get("EG_BENCH_ATTEMPT_S")
        # the supervisor's kill clock starts at child SPAWN, t_main at
        # main() entry — allow ~15 s for interpreter + jax import so the
        # rung pick never overshoots the real deadline
        remaining = (
            float(att_env) - (time.perf_counter() - t_main) - 15.0
            if att_env else float("inf")
        )
        # refpure = the already-resolved trigger config (one definition,
        # resolve_bench_trigger above), not a re-parse of the env
        rung = pick_mnist_rung(remaining, refpure=max_silence == 0)
        if rung is not None:
            mnist_n, mnist_epochs, mnist_horizon_default, mnist_silence = rung
    xm, ym = load_or_synthesize("mnist", None, "train", n_synth=mnist_n)
    horizon_mnist = float(
        os.environ.get("EG_BENCH_HORIZON_MNIST", str(mnist_horizon_default))
    )
    mnist_cfg = EventConfig(
        adaptive=True, horizon=horizon_mnist, warmup_passes=warmup,
        max_silence=mnist_silence,
    )
    with obs_reg.span("mnist_eventgrad", cat="leg", tier=tier):
        _, hist_m = train(
            CNN2(), topo, xm, ym, algo="eventgrad", event_cfg=mnist_cfg,
            epochs=mnist_epochs, batch_size=mnist_batch,
            learning_rate=0.05, random_sampler=False, log_every_epoch=False,
            epochs_per_dispatch=k_disp, registry=obs_reg,
            backend=bench_backend, trigger_policy=bench_policy,
            staleness=bench_staleness,
        )
    mnist_saved = hist_m[-1]["msgs_saved_pct"]

    # collapse guard (round-3 verdict item 7): a diverged event run must
    # never present as a savings win — the measured cliff is one env var
    # away (EG_BENCH_HORIZON_MNIST=1.05 at the reduced tier's 360-pass
    # scale: 81.66% "saved" at 36.5% accuracy, mnist_knee_r3_cpu.jsonl).
    # The CIFAR leg compares against its D-PSGD twin; the MNIST leg has
    # no twin and uses the absolute-loss call.
    from eventgrad_tpu.utils.metrics import collapse_verdict

    collapsed_cifar = collapse_verdict(
        [h["loss"] for h in hist], hist_d[-1]["loss"]
    )
    collapsed_mnist = collapse_verdict([h["loss"] for h in hist_m])

    saved = hist[-1]["msgs_saved_pct"]
    from eventgrad_tpu.utils.metrics import steady_records

    steady = steady_records(hist)
    step_s = float(np.mean([h["wall_s"] / h["steps"] for h in steady]))
    # the honest event-overhead number is the STEADY-STATE step ratio, not
    # the wall ratio: the first train() of the process absorbs ~7-9 s of
    # one-time jit/backend warmup regardless of algo (measured both ways,
    # artifacts/overhead_order_r4_cpu.jsonl), and the eventgrad leg runs
    # first here. Micro bounds: trigger state machine 0.9 ms, masked
    # exchange no dearer than dense, in-loop step delta +6.8% at the
    # reduced op-point (artifacts/overhead_ablation_r4_cpu.json).
    steady_d = steady_records(hist_d)
    step_s_d = float(np.mean([h["wall_s"] / h["steps"] for h in steady_d]))
    # host-bubble fraction of the eventgrad leg (wall the device sat idle
    # between dispatch blocks — the thing the dispatch pipeline deletes),
    # decomposed from the span trace of the FIRST train() window
    # (obs.bubble; tools/bubble_decomposition.py is the A/B proof)
    from eventgrad_tpu.obs import bubble as obs_bubble

    host_bubble_frac = None
    _windows = obs_bubble.train_windows(obs_reg.spans)
    if _windows:
        host_bubble_frac = obs_bubble.decompose(_windows[0])[
            "host_bubble_frac"
        ]
    # shape/dtype metadata of the stacked tree — no device dispatch needed
    n_params = trees.tree_count_params(state.params) // topo.n_ranks
    n_leaves = trees.tree_num_leaves(state.params)
    param_bytes = int(
        np.dtype(jax.tree.leaves(state.params)[0].dtype).itemsize
    )

    # single-chip MFU of the flagship eventgrad step: all 8 vmap-ranks run
    # on this one chip, so total step FLOPs / step time / chip peak IS the
    # chip's utilization
    from eventgrad_tpu.utils.flops import (
        chip_peak_flops, mfu as _mfu, train_step_flops,
    )

    peak = chip_peak_flops()
    flops = 0.0
    if peak:  # MFU is a TPU metric; skip the extra compile on CPU tiers
        tx = __import__("optax").sgd(1e-2, momentum=0.9)
        flops = train_step_flops(
            model, tx, topo, "eventgrad", event_cfg, x, y, per_rank, state
        )
    mfu = _mfu(flops, step_s)
    mfu = round(mfu, 4) if mfu is not None else None

    # analytic cost model + roofline (obs/costmodel.py, PERF_FIELDS in
    # obs/schema.py): backend-independent FLOP/byte counts of the SAME
    # step traced phase-split (grad / gate_pack / exchange / commit_mix),
    # against the obs/devicespec.py peaks. Populated on EVERY tier — the
    # CPU tiers' MFU rides the NOMINAL generic-cpu spec, a cross-round
    # tracking number for tools/perf_ledger.py, never a hardware claim
    # (nominal_spec marks it). Trace-only: nothing extra compiles.
    costmodel_rec = None
    try:
        from eventgrad_tpu.obs import costmodel as _costmodel
        from eventgrad_tpu.obs.devicespec import device_spec

        tx_cm = __import__("optax").sgd(1e-2, momentum=0.9)
        # the traced step's buffer layout auto-matches the state the
        # training leg produced (arena/bucketed — flops.step_layout_kwargs)
        cm = _costmodel.analyze_step(
            model, tx_cm, topo, "eventgrad", event_cfg, x, y, per_rank,
            state,
        )
        rl = _costmodel.roofline(
            cm["flops_total"], cm["hbm_bytes_total"], step_s,
            device_spec(),
        )
        costmodel_rec = _costmodel.record_block(cm, rl)
    except Exception as e:  # the bench result line must never die to it
        import sys as _sys
        print(f"costmodel block skipped: {e!r}", file=_sys.stderr)

    # wire accounting: measured f32-native bytes plus the derived bf16/int8
    # wire points (deterministic in the fired counts; the training effect
    # of the compressed wires is unit-tested in test_wire_bf16.py). int8
    # ships one f32 scale per FIRED leaf (steps.py wire accounting);
    # fired_frac approximates the fired leaf count for the derivation.
    sent = float(hist[-1]["sent_bytes_per_step_per_chip"])
    # the SPMD wire truth riding next to the accounting model: bytes the
    # exchange collective ACTUALLY moved per step (identical to the dense
    # payload on the masked path — the whole point of the compact gossip
    # wire is to pull this number down to the accounting one; see
    # docs/compaction.md and the gossip_wire micro-bench in bench_kernels)
    sent_real = float(
        hist[-1].get("sent_bytes_wire_real_per_step_per_chip", 0.0)
    )
    sent_real_d = float(
        hist_d[-1].get("sent_bytes_wire_real_per_step_per_chip", 0.0)
    )
    # 4.0 = steps.py's native-wire bytes/elem (the reference's f32 MPI
    # wire), deliberately NOT the param dtype's itemsize — sent_bytes was
    # measured against that constant, so the derivation must divide by it
    fired_elems = sent / (topo.n_neighbors * 4.0)  # per step per neighbor
    fired_leaves = float(hist[-1].get("fired_frac", 1.0)) * n_leaves
    n_nb = topo.n_neighbors
    wire_bytes = {
        "f32": sent,
        "bf16": n_nb * 2.0 * fired_elems,
        "int8": n_nb * (1.0 * fired_elems + 4.0 * fired_leaves),
    }

    # last TPU-captured flagship artifact (tools/tpu_flagship.py /
    # tools/tpu_watch.py) rides along so the driver-visible record carries
    # chip numbers even when the tunnel is wedged at capture time —
    # clearly labeled with its own capture timestamp (VERDICT r2 item 2)
    cached = None
    for name in ("tpu_flagship.json", "tpu_flagship_quick.json"):
        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "artifacts", name)
        try:
            with open(p) as f:
                rec = json.load(f)
            if not isinstance(rec, dict) or rec.get("platform") != "tpu":
                continue  # only chip-captured artifacts may ride as cached
        except (OSError, json.JSONDecodeError):
            continue
        # the artifact stamps its own capture time; mtime is only a
        # legacy fallback (git checkout resets it to clone time)
        if "captured_at" not in rec:
            rec["captured_at_mtime_fallback"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(p))
            )
        rec["artifact"] = f"artifacts/{name}"
        cached = rec
        break

    # The short-tier MNIST miniature cannot honestly reach the reference's
    # ~70% inside a driver-window pass budget: the measured knee
    # (artifacts/mnist_knee_r3_cpu.jsonl) shows the reference-pure trigger
    # plateauing at 62-66%, horizon 1.05 collapsing accuracy (81.7% saved
    # at 36.5% acc), and the cheapest honest ~70% op-point (horizon 1.02 +
    # guard, 544 passes x 4096 samples: 69.96% at -0.8pp) costing ~350 s —
    # beyond the leg's share of the CPU attempt. The claim-level op-points
    # ride along, clearly labeled as cached builder artifacts; the full
    # (TPU) tier measures the 1168-pass leg live.
    mnist_proven = None
    if tier != "full":
        mnist_proven = {
            "fullscale_stabilized": {
                "msgs_saved_pct": 78.9, "test_acc": 98.9,
                "passes": 1168, "n_train": 8192, "warmup": 30,
                "artifact": "artifacts/mnist_knee_r3_cpu.jsonl",
                "r2_with_dpsgd_twin": {
                    "msgs_saved_pct": 75.5, "acc_gap_vs_dpsgd": -1.17,
                    "artifact":
                        "artifacts/mnist_stabilized_fullscale_r2_cpu.jsonl",
                },
            },
            "fullscale_reference_pure": {
                "msgs_saved_pct": 69.56, "test_acc": 99.1,
                "passes": 1168, "n_train": 8192, "warmup": 30,
                "artifact": "artifacts/mnist_knee_r3_cpu.jsonl",
            },
            # the reduced-tier ladder's top rung, measured (round 4) —
            # what this very leg runs live when the budget affords it
            "reduced_ladder_top": {
                "msgs_saved_pct": 71.09, "test_acc": 97.7,
                "passes": 544, "n_train": 4096, "warmup": 10,
                "horizon": 1.025, "max_silence": 50,
                "artifact": "artifacts/mnist_knee_r4_cpu.jsonl",
            },
        }

    def _trigger_kind(h: float, silence: int) -> str:
        # reference-pure = the paper's trigger exactly (neutral horizon,
        # no bounded-staleness guard); anything else is the stabilized
        # beyond-reference variant (VERDICT r2 weak #5)
        return "reference-pure" if (h == 1.0 and silence == 0) else "stabilized"

    print(
        json.dumps(
            {
                # honesty: name the model actually measured (r2 carried a
                # resnet-named metric measured on LeNet — VERDICT weak #3)
                "metric": (
                    f"cifar10_{type(model).__name__.lower()}"
                    "_eventgrad_msgs_saved"
                ),
                "value": round(saved, 2),
                "unit": "%",
                # a collapsed leg's savings are meaningless — zero its
                # baseline ratio so the driver record can't read as a win
                "vs_baseline": (
                    0.0 if collapsed_cifar else round(saved / 60.0, 4)
                ),
                "collapsed": collapsed_cifar or collapsed_mnist,
                "collapsed_cifar": collapsed_cifar,
                "collapsed_mnist": collapsed_mnist,
                "config": tier,
                "downshifted": downshifted,
                "epochs": epochs,
                "epochs_per_dispatch": k_disp,
                "mnist_epochs": mnist_epochs,
                "mnist_passes": mnist_epochs * (mnist_n // (mnist_batch * topo.n_ranks)),
                "trigger": _trigger_kind(horizon, max_silence),
                "trigger_mnist": _trigger_kind(horizon_mnist, mnist_silence),
                "data": "synthetic-prototype",
                "test_acc": round(test["accuracy"], 2),
                "test_acc_dpsgd": round(test_d["accuracy"], 2),
                "acc_gap_vs_dpsgd": round(
                    test["accuracy"] - test_d["accuracy"], 2
                ),
                "model": type(model).__name__,
                "mnist_msgs_saved": round(mnist_saved, 2),
                "mnist_vs_baseline": (
                    0.0 if collapsed_mnist else round(mnist_saved / 70.0, 4)
                ),
                "mnist_proven": mnist_proven,
                "horizon": horizon,
                "horizon_mnist": horizon_mnist,
                "max_silence": max_silence,
                "mnist_max_silence": mnist_silence,
                "warmup_passes": warmup,
                "step_ms": round(1000 * step_s, 2),
                "step_ms_dpsgd": round(1000 * step_s_d, 2),
                # device-idle fraction of the eventgrad leg's wall (span-
                # trace decomposition; ~0 with the pipeline on, the r05
                # serialized chain measured ~38% on TPU)
                "host_bubble_frac": host_bubble_frac,
                "pipeline": bench_pipeline,
                "step_overhead_ratio": round(step_s / step_s_d, 4),
                # bucketed gossip schedule: bucket count of the event
                # leg (1 = monolithic) and its per-bucket wire split —
                # the in-step comm/compute-overlap knob next to step_ms
                "buckets": int(hist[-1].get("buckets", 1)),
                # bounded-async staleness bound of the event legs (0 =
                # lockstep; D >= 2 = delivery-queue config) — a
                # comparability-group axis, like backend and policy
                "staleness": bench_staleness,
                "sent_bytes_wire_real_per_bucket": hist[-1].get(
                    "sent_bytes_wire_real_per_bucket"
                ),
                # both legs ran with the flat-arena hot path? (the
                # step_overhead_ratio acceptance metric is arena-on;
                # EG_BENCH_ARENA=0 gives the legacy-tree comparison)
                "arena": bench_arena,
                # the trigger policy the event legs ran (EG_BENCH_POLICY;
                # resolved from the history so the record reports what RAN)
                "policy": hist[-1].get("policy", "norm_delta"),
                # the SPMD lift that produced these numbers (vmap sim vs
                # shard_map device mesh) — resolved from the history
                # records, so EG_BENCH_BACKEND=auto reports what RAN
                "backend": hist[-1].get("backend", "vmap"),
                # every block was cold (steady_records fell back): the
                # step timings above include compile contamination
                "steady_contaminated": bool(
                    any(h.get("steady_contaminated") for h in steady)
                    or any(h.get("steady_contaminated") for h in steady_d)
                ),
                "mfu": mfu,
                "flops_per_step": flops or None,
                "chip_peak_flops": peak or None,
                # analytic cost model + roofline of the eventgrad step
                # (obs/costmodel.py; field meanings in obs/schema.py
                # PERF_FIELDS) — populated on every tier, nominal-spec
                # flagged on CPU
                "costmodel": costmodel_rec,
                "param_dtype_bytes": param_bytes,
                "sent_bytes_per_step_per_chip": round(sent, 1),
                "sent_bytes_wire_real": round(sent_real, 1),
                "sent_bytes_wire_real_dpsgd": round(sent_real_d, 1),
                "sent_bytes_wire": {
                    k: round(v, 1) for k, v in wire_bytes.items()
                },
                "dense_bytes_per_step_per_chip": float(
                    n_nb * 4.0 * n_params  # f32 wire, matching steps.py
                ),
                "final_train_loss": round(hist[-1]["loss"], 4),
                "passes": epochs * (n_train // global_batch),
                "wall_s_eventgrad": round(wall_event, 1),
                "wall_s_dpsgd": round(wall_dpsgd, 1),
                "platform": jax.devices()[0].platform,
                "device_kind": jax.devices()[0].device_kind,
                "n_ranks": topo.n_ranks,
                "tpu_flagship_cached": cached,
            }
        )
    )

    # one-line perf-trajectory delta vs the committed ledger
    # (tools/perf_ledger.py) — stderr, because stdout is the result-line
    # contract; comparability = same (platform, model, config, backend,
    # policy, staleness) so a CPU smoke never reads as a regression of a
    # chip round, a shard_map mesh run never reads against a vmap one,
    # and a bounded-async row never gates against a lockstep round
    try:
        import sys as _sys

        from tools import perf_ledger as _pl

        _led_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "artifacts",
            "perf_ledger_cpu.json",
        )
        with open(_led_path) as f:
            _led = json.load(f)
        _cur = {
            "round": _led["n_rounds"] + 1, "source": "(this run)",
            "status": "ok", "platform": jax.devices()[0].platform,
            "model": type(model).__name__, "config": tier,
            "backend": hist[-1].get("backend", "vmap"),
            "staleness": bench_staleness,
            "step_ms": round(1000 * step_s, 2),
            "mfu": (
                mfu if mfu is not None
                else (costmodel_rec or {}).get("mfu")
            ),
        }
        _prev = _pl.last_comparable(_led, _cur)
        if _prev is not None:
            print(_pl.format_delta(_prev, _cur), file=_sys.stderr)
        else:
            print(
                "perf trajectory: no comparable previous round in "
                f"{os.path.basename(_led_path)} "
                f"(group={_pl.comparable_key(_cur)})",
                file=_sys.stderr,
            )
    except Exception as e:
        import sys as _sys
        print(f"perf trajectory line skipped: {e!r}", file=_sys.stderr)

    trace_path = os.environ.get("EG_BENCH_OBS_TRACE")
    if trace_path:
        # bench step timings ride as gauges next to the leg spans
        obs_reg.gauge("bench_step_ms", 1000 * step_s)
        obs_reg.gauge("bench_step_ms_dpsgd", 1000 * step_s_d)
        obs_reg.write_chrome_trace(trace_path)


# deadlined-subprocess + executed-jit probe logic is shared with
# tools/tpu_watch.py — one definition of "tunnel alive" repo-wide
from eventgrad_tpu.utils.procwatch import probe_device as _probe_device
from eventgrad_tpu.utils.procwatch import run_deadlined as _run_deadlined_3


def _run_deadlined(cmd: list, env: dict, timeout_s: float):
    out, timed_out, _rc = _run_deadlined_3(cmd, env, timeout_s)
    return out, timed_out


def _last_metric_line(out):
    """(line, record) of the last parseable metric line in a child's
    stdout, or (None, None) — ONE definition for both supervisor phases
    (a teardown crash after a completed measurement is still a
    result)."""
    for line in reversed((out or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return line, rec
    return None, None


def _upgrade_eligible(first_rec: dict, environ) -> bool:
    """Should the upgrade phase run at all after the guaranteed line?
    No when disabled, when the first line is an un-downshifted chip
    record (nothing above it on the ladder), or when the user pinned a
    tier other than reduced. A DOWNSHIFTED chip line stays eligible —
    the remaining real budget can fund a longer full-tier run."""
    if environ.get("EG_BENCH_UPGRADE", "1") == "0":
        return False
    if first_rec.get("platform") == "tpu" and not first_rec.get(
        "downshifted"
    ):
        return False
    if (
        environ.get("EG_BENCH_TINY") == "1"
        or environ.get("EG_BENCH_TIER", "reduced") != "reduced"
    ):
        return False
    return True


def _upgrade_wins(first: dict, second) -> bool:
    """Should the upgrade attempt's record supersede the already-printed
    conservative line? Only a strictly better combined baseline ratio
    from an uncollapsed run — or a chip-captured record at an equal
    score, since platform/step_ms/MFU evidence is the round's #1 ask.
    A chip-captured first line is NEVER superseded by a non-chip one:
    higher CPU ladder ratios must not discard the platform/step_ms/MFU
    evidence (the upgrade phase exists to extend chip runs, not replace
    them)."""
    if not isinstance(second, dict) or second.get("collapsed"):
        return False
    if first.get("platform") == "tpu" and second.get("platform") != "tpu":
        return False
    old = (
        (first.get("vs_baseline") or 0.0)
        + (first.get("mnist_vs_baseline") or 0.0)
    )
    new = (
        (second.get("vs_baseline") or 0.0)
        + (second.get("mnist_vs_baseline") or 0.0)
    )
    return new > old or (second.get("platform") == "tpu" and new >= old)


def _supervised() -> None:
    """Run main() in a child under a deadline sized for the driver window.

    The accelerator tunnel can wedge a blocked device op forever (no
    Python-level interrupt works); a supervising parent is the only
    reliable watchdog. Before each attempt a short liveness probe runs
    (EG_BENCH_PROBE_S, default 60s — an *executed* jit, since a wedged
    tunnel enumerates fine but blocks on first use). If the accelerator
    stalls, the bench falls back to the reduced CPU op-point — the
    headline metric (messages-saved-%) is algorithmic and backend-
    independent, so a dead tunnel still yields real numbers with a
    D-PSGD leg (wall-clock/MFU fields change meaning; `platform`
    records which backend ran). If everything stalls, a diagnostic JSON
    line is emitted so the harness always gets its line."""
    import sys

    # 700: large enough that a generous EG_BENCH_TOTAL_S window can fund
    # the reduced tier's top MNIST ladder rung (~390 s remaining needed
    # at the leg) AND the 960-pass CIFAR upgrade in front of it (the
    # pick_cifar_epochs 640 s gate); under the default 560 s total the
    # reservation math bounds attempts well below this anyway
    deadline = float(os.environ.get("EG_BENCH_DEADLINE_S", "700"))
    probe_s = float(os.environ.get("EG_BENCH_PROBE_S", "60"))
    total_s = float(os.environ.get("EG_BENCH_TOTAL_S", "1150"))
    # Two-phase budget (round 4): the attempt loop below sizes itself
    # against the CONSERVATIVE window (<= 560 s — the round-1..3
    # assumption that always produced a result line by ~7 min), so the
    # guaranteed first line is emitted exactly as before no matter how
    # large the total is. Whatever real budget remains after that line
    # funds ONE optional upgrade attempt (_maybe_upgrade): the reduced
    # tier re-run with the full remaining budget so the measured ladder
    # rungs (pick_cifar_epochs / pick_mnist_rung) can take their top
    # op-points; its line prints ONLY if strictly better and
    # uncollapsed. The final JSON line on stdout is the result — a
    # driver that stops reading after the first line records the same
    # conservative result rounds 1-3 produced.
    base_total = min(total_s, 560.0)
    #: wall budget a late tiny-tier fallback attempt needs (~2 min run
    #: + compile); EVERY attempt 1 — accelerator or CPU — reserves this
    #: much so one wedge/overrun still leaves room for an attempt that
    #: produces real numbers (round 1 died by betting the whole budget
    #: on one attempt)
    _FALLBACK_S = 200.0
    #: floor for attempt 1 even when reserving — below this a
    #: healthy-but-cold full-tier TPU run couldn't finish either
    _ATTEMPT1_FLOOR_S = 270.0
    #: minimum budget to pick the reduced tier: measured 1-core wall
    #: ~252 s (REPRO.md) plus ~40 s startup/compile-variance slack —
    #: below this, drop to tiny rather than half-finish
    _REDUCED_S = 290.0

    def _pick_cpu_tier(env: dict, budget: float) -> None:
        """Pick the largest CPU tier that fits the deadline the child will
        actually get."""
        env["JAX_PLATFORMS"] = "cpu"
        # any explicit user tier wins — the new-style knob or either
        # legacy alias (the child's _tier() resolves those itself)
        user_set_tier = any(
            k in os.environ
            for k in ("EG_BENCH_TIER", "EG_BENCH_TINY", "EG_BENCH_CPU")
        )
        if not user_set_tier:
            env["EG_BENCH_TIER"] = (
                "reduced" if budget >= _REDUCED_S else "tiny"
            )

    t_start = time.monotonic()
    env = dict(os.environ, EG_BENCH_CHILD="1")

    def _attempt_deadline(reserve: bool, plat, floor_ok: bool = True) -> float:
        """Wall budget this attempt's child gets. A non-final attempt
        reserves the tiny fallback budget — a wedged accelerator or an
        overloaded core must not consume the whole bench. Attempt 1 may
        additionally apply a floor below which a healthy run of the
        intended tier couldn't finish anyway (floor_ok); a RETRY attempt
        never gets the floor — its reservation is absolute, because the
        backstop behind it is the last chance at real numbers. The floor
        never exceeds the remaining budget: EG_BENCH_TOTAL_S is a hard
        contract."""
        remaining = base_total - (time.monotonic() - t_start)
        d = min(deadline, remaining)
        if reserve and remaining - d < _FALLBACK_S:
            d = remaining - _FALLBACK_S
            if floor_ok:
                floor = (
                    _ATTEMPT1_FLOOR_S if plat not in ("cpu", None)
                    else _REDUCED_S + 20.0
                )
                d = max(min(floor, remaining), d)
        return d

    def _maybe_upgrade(first_rec: dict) -> None:
        """One opportunistic upgrade attempt after the guaranteed line.

        Re-probes the accelerator first (the tunnel may have woken up
        mid-bench — round-2 verdict item 2; this phase is now where
        that retry lives): a live chip runs the full tier, otherwise
        the reduced tier re-runs on CPU with the remaining budget so
        the measured pass-count ladders take their top rungs (544-pass
        MNIST op-point: 71.09% saved -> mnist_vs_baseline 1.0156 even
        with a dead tunnel, artifacts/bench_default_twophase_r4_cpu.log).
        The upgraded line prints only when strictly better on the
        baseline ratios and not collapse-flagged (and a chip-captured
        first line is never replaced by a CPU one — _upgrade_wins);
        otherwise the already-printed conservative line stands. Skipped
        when the first result came from the chip at its un-downshifted
        scale (a DOWNSHIFTED chip line stays eligible: the remaining
        real budget can fund a longer full-tier run), when the user
        pinned a tier other than reduced, or with EG_BENCH_UPGRADE=0."""
        if not _upgrade_eligible(first_rec, os.environ):
            return
        if first_rec.get("platform") == "tpu":
            # a chip re-run is only worth the budget if it funds a
            # strictly HIGHER epoch rung than the first line captured
            # (else the whole remaining window re-buys the same tier).
            # The budget estimate charges the re-probe's REAL allowance
            # (up to min(probe_s, 75) + spawn margin), not a flat 50 s —
            # ADVICE r4: the probe could eat the cushion and land the
            # child back on the rung this gate predicted it would exceed.
            from eventgrad_tpu.parallel.events import pick_full_epochs

            probe_allow = min(probe_s, 75.0) + 15.0
            rem_est = total_s - (time.monotonic() - t_start)
            d2_est = min(deadline, rem_est - 20.0 - probe_allow)
            if pick_full_epochs(d2_est) <= int(
                first_rec.get("epochs") or 0
            ):
                return
        remaining = total_s - (time.monotonic() - t_start)
        if remaining < 540.0:  # top-rung child (~500 s) + margin
            if remaining > 60:
                print(
                    f"upgrade attempt skipped: {remaining:.0f}s left < "
                    "540s (the top-rung child needs ~500s) — raise "
                    "EG_BENCH_TOTAL_S to fund it",
                    file=sys.stderr, flush=True,
                )
            return
        env2 = dict(os.environ, EG_BENCH_CHILD="1")
        plat2 = "cpu"
        if os.environ.get("JAX_PLATFORMS") != "cpu":
            verdict2, p2 = _probe_device(
                dict(os.environ), min(probe_s, 75.0)
            )
            if verdict2 == "ok":
                plat2 = p2 or "accelerator"
        if first_rec.get("platform") == "tpu" and plat2 == "cpu":
            # ADVICE r4: a CPU child can never supersede a chip first
            # line (_upgrade_wins) — don't spend the whole remaining
            # budget on a run whose output is guaranteed to be discarded
            return
        if plat2 == "cpu":
            env2["JAX_PLATFORMS"] = "cpu"
            env2.setdefault("EG_BENCH_TIER", "reduced")
        # else: tier resolves per auto rule in the child (full on TPU)
        remaining = total_s - (time.monotonic() - t_start)
        d2 = min(deadline, remaining - 20.0)  # per-attempt cap holds here too
        env2["EG_BENCH_ATTEMPT_S"] = str(d2)
        print(
            f"upgrade attempt on {plat2}: re-running with {d2:.0f}s so "
            "the measured ladder rungs apply",
            file=sys.stderr, flush=True,
        )
        out2, _ = _run_deadlined(
            [sys.executable, os.path.abspath(__file__)], env2, d2
        )
        line2, rec2 = _last_metric_line(out2)
        if _upgrade_wins(first_rec, rec2):
            print(line2, flush=True)

    # 2 attempts normally; a 3rd exists ONLY as the CPU backstop behind
    # an attempt-2 accelerator retry (the retry must never re-create
    # round 1's bet-everything failure: any accelerator attempt with
    # budget left behind it reserves the fallback)
    plat = None
    for attempt in (1, 2, 3):
        if attempt == 3 and plat == "cpu":
            break  # attempt 2 already was the CPU fallback; nothing new
        remaining = base_total - (time.monotonic() - t_start)
        if remaining < 90:  # not enough budget for a meaningful attempt
            break
        plat = "cpu"
        if env.get("JAX_PLATFORMS") != "cpu":
            verdict, plat = _probe_device(env, min(probe_s, remaining - 60))
            if verdict != "ok":
                print(
                    f"device probe {verdict}"
                    + (f" after {probe_s:.0f}s" if verdict == "stalled" else "")
                    + "; falling back to the CPU op-point",
                    file=sys.stderr, flush=True,
                )
                plat = "cpu"
        # one deadline per iteration: the tier pick and the child's
        # budget must see the SAME number (time.monotonic() advances
        # between calls; near the _REDUCED_S+20 boundary two evaluations
        # could size the tier against more slack than the child gets —
        # round-2 advisor finding). Reserve fallback budget behind every
        # accelerator attempt and behind attempt 1 regardless.
        reserve = attempt == 1 or (attempt < 3 and plat not in ("cpu", None))
        attempt_deadline = _attempt_deadline(reserve, plat,
                                             floor_ok=attempt == 1)
        if plat == "cpu":
            # size the tier from the deadline the child will REALLY get
            # (post-reservation), not the nominal one — on every CPU
            # path: probe failure, healthy CPU-only host, or an env pin
            _pick_cpu_tier(env, attempt_deadline)
        env["EG_BENCH_ATTEMPT_S"] = str(attempt_deadline)
        out, timed_out = _run_deadlined(
            [sys.executable, os.path.abspath(__file__)], env,
            attempt_deadline,
        )
        line, rec = _last_metric_line(out)
        if rec is not None:
            # flush: the upgrade phase keeps the process alive past
            # this print, and a pipe-buffered line would be lost if
            # the driver kills us mid-upgrade
            print(line, flush=True)
            _maybe_upgrade(rec)
            return
        print(
            f"bench attempt {attempt} "
            + ("stalled" if timed_out else "failed")
            + f" (deadline {attempt_deadline:.0f}s)",
            file=sys.stderr, flush=True,
        )
        # don't retry a backend that just wedged mid-run — but if this
        # attempt already ran on CPU (e.g. after a stalled probe), give
        # the accelerator one more probe on attempt 2: the tunnel may
        # have woken up mid-bench (VERDICT r2 item 2). Only when the
        # remaining CONSERVATIVE budget can absorb another stalled probe
        # AND still fund the CPU backstop attempt — within base_total
        # that is effectively never, and the woken-tunnel retry now
        # lives in the upgrade phase (_maybe_upgrade re-probes the
        # accelerator with the REAL remaining budget after the
        # guaranteed line is out). This in-loop gate is kept for
        # explicitly raised EG_BENCH_DEADLINE_S/PROBE_S combinations
        # that shrink attempt 1 below the floor.
        remaining_now = base_total - (time.monotonic() - t_start)
        if plat != "cpu":
            env["JAX_PLATFORMS"] = "cpu"
        elif (
            attempt == 1
            and os.environ.get("JAX_PLATFORMS") != "cpu"
            and remaining_now - probe_s - _FALLBACK_S >= _ATTEMPT1_FLOOR_S
        ):
            env.pop("JAX_PLATFORMS", None)
            if "EG_BENCH_TIER" not in os.environ:
                env.pop("EG_BENCH_TIER", None)
    err_rec = {
        # no model ran on this path — keep the name model-agnostic
        # (the success path derives its name from the model used)
        "metric": "cifar10_eventgrad_msgs_saved",
        "value": 0.0,
        "unit": "%",
        "vs_baseline": 0.0,
        "error": "device stalled or bench failed twice; see stderr",
    }
    print(json.dumps(err_rec), flush=True)
    # even the all-attempts-failed path gets the upgrade try: a transient
    # core overload that blew the conservative deadlines may clear, and
    # any honest result strictly beats the zero line (which is already
    # out as the guarantee)
    _maybe_upgrade(err_rec)


def _chaos_mode() -> None:
    """Chaos bench mode: the robustness sweep (drop-rate vs accuracy +
    recovery latency, tools/chaos_sweep.py) replaces the savings headline;
    schedules are serialized into the record so the run replays.
    EG_BENCH_CHAOS=1 -> default points, or a comma list of drop rates
    ("0,0.1,0.3,0.6"). Runs in-process, no supervisor: the sweep is a
    deterministic CPU-scale miniature (~30 s) with none of the
    accelerator-tunnel wedge risk the supervisor exists for. Result is
    the LAST JSON line, the same contract as every other bench mode."""
    from eventgrad_tpu.utils import compile_cache

    compile_cache.honor_cpu_pin()
    compile_cache.enable()
    from tools.chaos_sweep import run_sweep

    spec = os.environ["EG_BENCH_CHAOS"]
    drops = (
        tuple(float(d) for d in spec.split(","))
        if spec != "1" else (0.0, 0.2, 0.5)
    )
    art = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "artifacts",
        f"chaos_sweep_{jax.default_backend()}.json",
    )
    out = run_sweep(drops, out_path=art)
    out["config"] = "chaos"
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    # "0"/unset = off, matching the EG_BENCH_CHILD-style on/off convention
    # (a disable attempt must run the normal bench, not crash chaos mode)
    if os.environ.get("EG_BENCH_CHAOS", "0") != "0":
        _chaos_mode()
    elif os.environ.get("EG_BENCH_CHILD") == "1":
        main()
    else:
        _supervised()
