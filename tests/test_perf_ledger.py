"""Perf ledger (tools/perf_ledger.py): backfill ingestion of the
committed BENCH rounds, the regression gates (real trajectory passes, a
seeded 2x step_ms regression fails), and the committed artifact's schema
gate.  The ingestion tests run --no-costmodel style (no traces) so the
suite stays fast; the committed artifact proves the backfill WITH the
cost model ran.
"""

import copy
import importlib.util
import json
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

pl = _load("perf_ledger")
va = _load("validate_artifacts")

_LEDGER_PATH = os.path.join(_ROOT, "artifacts", "perf_ledger_cpu.json")


def _committed_ledger():
    with open(_LEDGER_PATH) as f:
        return json.load(f)


# --- ingestion --------------------------------------------------------------


def test_ingests_every_bench_round():
    ledger = pl.build_ledger(_ROOT, with_costmodel=False)
    assert ledger["n_rounds"] >= 6
    rounds = {e["round"]: e for e in ledger["rounds"]}
    assert set(rounds) >= {1, 2, 3, 4, 5, 6}
    # r01 stalled (rc=124, no metric line) — an explicit no-data entry,
    # not a silently dropped round
    assert rounds[1]["status"] == "no-data"
    assert "no parseable metric line" in rounds[1]["note"]
    # data rounds carry the trajectory fields + provenance + git round
    for n in (2, 3, 4, 5, 6):
        e = rounds[n]
        assert e["status"] == "ok"
        assert e["git_round"] == n
        assert e["provenance"] == "synthetic-prototype"
        assert e["step_ms"] and e["msgs_saved_pct"] is not None
    # the chip rounds keep their record-carried (XLA-compiled) MFU even
    # without the cost-model backfill
    assert rounds[5]["mfu"] == 0.1669
    assert rounds[5]["mfu_source"] == "record"
    # multichip + ablation snapshots ride along
    assert len(ledger["multichip"]) >= 5
    assert "bucketed" in ledger["ablations"]
    assert ledger["ablations"]["bucketed"]["value"] is not None


def test_comparability_groups_separate_platforms_and_tiers():
    ledger = pl.build_ledger(_ROOT, with_costmodel=False)
    rounds = {e["round"]: e for e in ledger["rounds"]}
    # the r06 tiny CPU smoke must never be gated against the r05 TPU
    # flagship or the r03 reduced tier
    assert pl.comparable_key(rounds[6]) != pl.comparable_key(rounds[5])
    assert pl.comparable_key(rounds[6]) != pl.comparable_key(rounds[3])
    assert pl.comparable_key(rounds[2]) == pl.comparable_key(rounds[3])
    # a bounded-async row (EG_BENCH_STALENESS=D, ISSUE 20) is its own
    # group: D >= 2 carries queue-commit work a lockstep round doesn't
    assert (pl.comparable_key(dict(rounds[6], staleness=4))
            != pl.comparable_key(rounds[6]))
    # pre-field rows (no staleness key) read as lockstep
    assert (pl.comparable_key(dict(rounds[6], staleness=0))
            == pl.comparable_key(rounds[6]))
    gated_pairs = {
        (g["prev_round"], g["round"]) for g in ledger["gates"]
    }
    assert (2, 3) in gated_pairs
    assert (4, 5) in gated_pairs
    assert (3, 6) not in gated_pairs and (5, 6) not in gated_pairs


# --- regression gates -------------------------------------------------------


def test_real_trajectory_passes_gates():
    ledger = pl.build_ledger(_ROOT, with_costmodel=False)
    bad = [g for g in ledger["gates"] if not g["ok"]]
    assert ledger["gates_all_ok"], bad


def test_seeded_2x_step_ms_regression_fails_gate():
    ledger = pl.build_ledger(_ROOT, with_costmodel=False)
    entries = ledger["rounds"]
    last = max(
        (e for e in entries if e["status"] == "ok"
         and pl.comparable_key(e) is not None),
        key=lambda e: e["round"],
    )
    seeded = copy.deepcopy(last)
    seeded["round"] = last["round"] + 1
    seeded["source"] = "BENCH_seeded.json"
    seeded["step_ms"] = 2.0 * float(last["step_ms"])
    gates = pl.evaluate_gates(entries + [seeded])
    failing = [g for g in gates if not g["ok"]]
    assert failing, "2x step_ms regression was not caught"
    assert any(
        g["metric"] == "step_ms" and g["round"] == seeded["round"]
        for g in failing
    )
    # and the un-seeded trajectory still passes the same evaluator
    assert all(g["ok"] for g in pl.evaluate_gates(entries))


def test_seeded_mfu_collapse_fails_gate():
    ledger = pl.build_ledger(_ROOT, with_costmodel=False)
    entries = [e for e in ledger["rounds"]]
    base = next(e for e in entries if e["round"] == 5)
    seeded = copy.deepcopy(base)
    seeded["round"] = 7
    seeded["mfu"] = 0.5 * float(base["mfu"]) - 1e-6
    gates = pl.evaluate_gates(entries + [seeded])
    assert any(
        g["metric"] == "mfu" and not g["ok"] and g["round"] == 7
        for g in gates
    )


# --- the committed artifact -------------------------------------------------


def test_committed_ledger_covers_six_rounds_with_mfu_and_roofline():
    led = _committed_ledger()
    assert led["n_rounds"] >= 6
    assert led["rounds_with_mfu"] >= 5
    assert led["gates_all_ok"] is True
    for e in led["rounds"]:
        if e["status"] != "ok":
            continue
        if e.get("config") == "mesh-scale64":
            # the 64-rank scale leg is a wire-exactness smoke (tiny
            # MLP, 3 steps) riding as a mesh-backend row; it carries
            # step_ms but no MFU-bearing op-point
            assert e["backend"] != "vmap" and e["step_ms"]
            continue
        if str(e.get("config", "")).startswith("frontier-"):
            # ISSUE 16/17: frontier rows are bytes-vs-accuracy
            # instruments (policy x wire at a fixed capacity point),
            # not timed data rounds — no MFU, but the policy must be
            # on the comparability key with real byte/accuracy payload
            assert e["policy"] and e["sent_bytes_wire_real"]
            assert e["test_accuracy"] is not None
            continue
        if str(e.get("config", "")).startswith("resident-"):
            # ISSUE 17: carrier-residency rows record where the HBM
            # bytes went when the receive buffers shrank — analytic
            # bytes + roofline next to the scanned step time, no MFU
            assert e["resident_dtype"] in ("f32", "bf16", "int8")
            assert e["hbm_bytes_per_step"] and e["step_ms"]
            assert e["roofline_bound"] in ("compute", "memory")
            continue
        # the acceptance instrument: every data round carries MFU and a
        # roofline verdict (cost-model-backfilled on the CPU rounds,
        # record-carried on chip), nominal-spec flagged honestly
        assert e["mfu"] is not None, e["round"]
        assert e["roofline_bound"] in ("compute", "memory"), e["round"]
        assert e["mfu_source"] in ("record", "costmodel")
        if e["platform"] == "cpu":
            assert e["nominal_spec"] is True
            assert e["device_spec"] == "generic-cpu"
        else:
            assert e["device_spec"].startswith("tpu-")


def test_committed_ledger_schema_gated():
    errs = va.validate_json_file(_LEDGER_PATH, va.PERF_LEDGER_SCHEMA)
    assert errs == []
    # the schema actually bites: a failing gate or a thin trajectory is
    # a schema violation, so neither can be committed silently
    led = _committed_ledger()
    broken = dict(led, gates_all_ok=False)
    assert va.validate(broken, va.PERF_LEDGER_SCHEMA)
    thin = dict(led, rounds_with_mfu=2)
    assert va.validate(thin, va.PERF_LEDGER_SCHEMA)


# --- bench's trajectory-delta helpers ---------------------------------------


def test_last_comparable_and_format_delta():
    led = _committed_ledger()
    cur = {
        "platform": "cpu", "model": "LeNetCifar", "config": "reduced",
        "step_ms": 100.0, "mfu": 0.05, "round": 99, "source": "(run)",
        "status": "ok",
    }
    prev = pl.last_comparable(led, cur)
    assert prev is not None and prev["round"] == 3
    line = pl.format_delta(prev, cur)
    assert "step_ms" in line and "->" in line and "mfu" in line
    # no comparable group -> None, caller prints the no-prior line
    assert pl.last_comparable(led, {
        "platform": "cpu", "model": "ViT", "config": "reduced",
    }) is None
