"""TriggerPolicy subsystem (parallel/policy.py, ISSUE 16).

Four pins:

  1. norm_delta == the default eventgrad path BITWISE on full TrainState
     + metrics across the masked|compact x f32/int8 x staleness x
     bucketed matrix — the policy seam adds zero ops when no masks are
     in play. (Equivalence to the PRE-refactor engine is pinned by the
     untouched eventgrad regression suite — test_events/test_compact/
     test_bucketed all run through the policy seam now.)
  2. The micro partition geometry: element-balanced leaf-aligned static
     partitions, disjoint, exact cover; ownership rotates (r + pass)
     mod K under the SPMD lift; suppression engages only post-warmup
     (the measured collapse guard — see Micro's class doc).
  3. topk IS the sp_eventgrad path: the payload helpers moved (not
     copied) out of sparsify.py, and sp's compact wire is a capacity-
     free alias accepted end to end, bitwise-equal to masked.
  4. The registry/guards: resolve() rejects unknown names, no-trigger
     algos, and algo/policy mismatches; history records stamp
     rec["policy"]; the frontier tool's --fast leg runs end to end.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel import arena as arena_lib
from eventgrad_tpu.parallel import policy as policy_lib
from eventgrad_tpu.parallel import sparsify
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODEL = dict(hidden=16)
IN_SHAPE = (8, 8, 1)
N_RANKS = 4


def _data(n=256):
    x, y = synthetic_dataset(n, IN_SHAPE, seed=3)
    return x, y


def _run(algo="eventgrad", policy=None, epochs=2, **kw):
    x, y = _data()
    cfg = kw.pop("event_cfg", None) or EventConfig(
        adaptive=True, horizon=0.95, warmup_passes=3, max_silence=10,
    )
    return train(
        MLP(**MODEL), Ring(N_RANKS), x, y, algo=algo, epochs=epochs,
        batch_size=8, learning_rate=0.05, event_cfg=cfg, seed=0,
        trigger_policy=policy, log_every_epoch=True, **kw,
    )


def _state_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def _spec():
    params = MLP(**MODEL).init(
        jax.random.PRNGKey(0), jnp.zeros((1,) + IN_SHAPE)
    )["params"]
    return arena_lib.arena_spec(params)


# --- 1. norm_delta == default, bitwise, across the wire matrix --------------


@pytest.mark.parametrize("kw", [
    dict(),
    dict(wire="int8"),
    dict(gossip_wire="compact", compact_frac=0.9),
    dict(gossip_wire="compact", compact_frac=0.9, wire="int8"),
    dict(staleness=1),
    dict(bucketed=4),
    dict(bucketed=4, gossip_wire="compact", compact_frac=1.0),
], ids=["masked_f32", "masked_int8", "compact_f32", "compact_int8",
        "staleness1", "bucketed4", "bucketed4_compact"])
def test_norm_delta_is_the_default_bitwise(kw):
    # one epoch = 32 passes: past warmup (3), adaptive thresholds live,
    # max_silence (10) fires — bitwise divergence anywhere would show.
    s_def, h_def = _run(epochs=1, **kw)
    s_pol, h_pol = _run(policy="norm_delta", epochs=1, **kw)
    assert _state_equal(s_def, s_pol)
    for rd, rp in zip(h_def, h_pol):
        assert rd["loss"] == rp["loss"]
        assert rd.get("num_events") == rp.get("num_events")
        assert rd["policy"] == rp["policy"] == "norm_delta"


# --- 2. partition geometry + rotation ---------------------------------------


def test_partition_masks_disjoint_exact_cover():
    spec = _spec()
    for k in (1, 2, 3, 4):
        v = policy_lib.validate_partitions(spec, k)
        assert v["ok"], v
        assert v["disjoint"] and v["exact_cover"] and v["balanced"]
        assert sum(v["sizes"]) == spec.n_total
        assert max(v["sizes"]) == v["max_partition_elems"]
        assert v["max_partition_elems"] == policy_lib.max_partition_elems(
            spec, k
        )
        masks = policy_lib.partition_masks(spec, k)
        # leaf-aligned bools, each leaf claimed exactly once
        counts = [sum(m[i] for m in masks) for i in range(spec.n_leaves)]
        assert counts == [1] * spec.n_leaves


def test_partition_table_offsets_are_static_and_contiguous():
    spec = _spec()
    tbl = policy_lib.partition_table(spec, 4)
    assert [d["index"] for d in tbl] == list(range(len(tbl)))
    pos = 0
    for d in tbl:
        assert d["start"] == pos
        pos += d["size"]
    assert pos == spec.n_total


def test_ownership_rotates_under_the_lift():
    """ownership_vec under the vmap axis: every pass the rank rows are
    a disjoint exact cover, and rank r's partition at pass t+1 is rank
    r+1's at pass t — the (r + pass) mod K rotation."""
    spec = _spec()
    topo = Ring(N_RANKS)

    def owned_at(t):
        f = lambda _: policy_lib.ownership_vec(spec, topo, t)
        return np.asarray(
            jax.vmap(f, axis_name="ring")(jnp.arange(N_RANKS))
        )

    rows = {t: owned_at(t) for t in range(N_RANKS + 1)}
    for t, m in rows.items():
        # [n_ranks, L] bools: each leaf owned by exactly one rank
        assert m.dtype == bool and m.shape == (N_RANKS, spec.n_leaves)
        assert (m.sum(axis=0) == 1).all()
    for t in range(N_RANKS):
        assert (rows[t + 1] == np.roll(rows[t], -1, axis=0)).all()
    # period K
    assert (rows[N_RANKS] == rows[0]).all()


def test_suppression_gated_on_warmup():
    """Suppression engages only at pass >= warmup_passes: the warmup
    full-fire still synchronizes the ranks (suppressing it collapses
    training — the measured LeNetCifar/Ring(8) failure in Micro's
    class doc)."""
    spec = _spec()
    topo = Ring(N_RANKS)
    cfg = EventConfig(warmup_passes=5)
    for pol in (policy_lib.Micro(), policy_lib.Hybrid()):
        def at(t):
            f = lambda _: pol.masks(spec, topo, t, cfg)[1]
            return np.asarray(
                jax.vmap(f, axis_name="ring")(jnp.arange(N_RANKS))
            )
        assert not at(0).any()   # warm: nothing suppressed
        assert not at(4).any()
        assert at(5).any()       # post-warmup: ~owned suppressed
        assert (~at(5)).sum() >= N_RANKS  # owned never suppressed
    # micro's force mask is the owned partition, warm or not
    m = policy_lib.Micro()
    f = lambda _: m.masks(spec, topo, 0, cfg)[0]
    force = np.asarray(jax.vmap(f, axis_name="ring")(jnp.arange(N_RANKS)))
    assert (force.sum(axis=0) == 1).all()


def test_micro_trains_and_saves_messages():
    """Post-warmup, micro fires exactly the owned partition: fired_frac
    == 1/K once warm, and the history stamps the policy."""
    s, h = _run(policy="micro", epochs=3)
    assert all(r["policy"] == "micro" for r in h)
    # epoch 1 contains the 3 warmup full-fire passes; later epochs are
    # pure rotation at exactly 1/K of the leaves
    assert h[-1]["fired_frac"] == pytest.approx(1.0 / N_RANKS)
    assert h[-1]["msgs_saved_pct"] > 50.0


def test_hybrid_fires_at_most_the_owned_partition():
    s, h = _run(policy="hybrid", epochs=3)
    assert all(r["policy"] == "hybrid" for r in h)
    assert h[-1]["fired_frac"] <= 1.0 / N_RANKS + 1e-6


# --- 3. topk IS sp_eventgrad ------------------------------------------------


def test_topk_helpers_moved_not_copied():
    assert sparsify.topk_payload is policy_lib.topk_payload
    assert sparsify.scatter_into is policy_lib.scatter_into


def test_sp_compact_is_capacity_free_alias_bitwise():
    """--gossip-wire compact on sp_eventgrad: accepted (the old guard
    rejected it), needs no capacity, and is bitwise the masked wire —
    the top-k lanes were statically sized all along."""
    s_masked, h_masked = _run(algo="sp_eventgrad", epochs=1)
    s_compact, h_compact = _run(algo="sp_eventgrad", epochs=1,
                                gossip_wire="compact")
    assert _state_equal(s_masked, s_compact)
    assert h_compact[-1]["gossip_wire"] == "compact"
    assert all(r["policy"] == "topk" for r in h_compact)
    # capacity-free: compact_frac would size an autotune that does not
    # exist for this wire
    with pytest.raises(ValueError, match="capacity-free"):
        _run(algo="sp_eventgrad", gossip_wire="compact",
             compact_frac=0.5)


# --- 4. registry / guards ---------------------------------------------------


def test_resolve_registry():
    assert policy_lib.resolve(None, "eventgrad").name == "norm_delta"
    assert policy_lib.resolve(None, "sp_eventgrad").name == "topk"
    assert policy_lib.resolve("micro", "eventgrad").name == "micro"
    with pytest.raises(ValueError, match="unknown trigger policy"):
        policy_lib.resolve("bogus", "eventgrad")
    with pytest.raises(ValueError, match="no event trigger"):
        policy_lib.resolve(None, "dpsgd")
    with pytest.raises(ValueError, match="drives"):
        policy_lib.resolve("norm_delta", "dpsgd")
    with pytest.raises(ValueError, match="drives"):
        policy_lib.resolve("micro", "sp_eventgrad")


def test_wire_specs_declare_capabilities():
    P = policy_lib.POLICIES
    assert set(P) == {"norm_delta", "topk", "micro", "hybrid"}
    assert P["topk"].wire_spec().indexed
    assert not P["topk"].wire_spec().compact_needs_capacity
    for name in ("micro", "hybrid"):
        ws = P[name].wire_spec()
        assert ws.partitioned and not ws.indexed
        assert "compact" in ws.gossip_wires
    assert not P["norm_delta"].wire_spec().partitioned


def test_non_event_algo_rejects_policy():
    with pytest.raises(ValueError, match="drives"):
        _run(algo="dpsgd", policy="micro")


# --- the frontier tool's fast leg (tier-1 smoke) ----------------------------


def test_frontier_sweep_fast_leg(tmp_path):
    """The frontier instrument's --fast leg runs end to end: all four
    policies train, micro's measured bytes undercut topk's strictly at
    the shared capacity point, and the f32 legs replay bitwise."""
    spec = importlib.util.spec_from_file_location(
        "frontier_sweep", os.path.join(ROOT, "tools", "frontier_sweep.py")
    )
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    out = str(tmp_path / "frontier_fast.json")
    assert tool.main(["--fast", "--out", out]) == 0
    with open(out) as f:
        rec = json.load(f)
    assert rec["bench"] == "frontier"
    assert rec["n_policies"] == 4
    assert rec["micro_below_topk_bytes"] is True
    assert rec["replay_bitwise"] is True
    by_pol = {l["policy"]: l for l in rec["legs"]}
    assert by_pol["micro"]["bytes_per_step_per_chip"] < (
        by_pol["topk"]["bytes_per_step_per_chip"]
    )
