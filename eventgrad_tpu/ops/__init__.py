from eventgrad_tpu.ops.fused_update import fused_mix_sgd, mix_sgd_reference
