"""Opportunistic TPU capture watchdog (VERDICT round-2 item 1).

Rounds 1-2 both lost their live-tunnel window by waiting for the driver's
bench run to coincide with a healthy tunnel. This watcher inverts that:
it polls the device tunnel continuously and, the moment an *executed* jit
succeeds, runs the capture ladder — cheapest artifact first so a tunnel
that dies mid-window still leaves evidence:

  1. quick flagship  (tools/tpu_flagship.py 8)   -> artifacts/tpu_flagship_quick.json
  2. full flagship   (tools/tpu_flagship.py 61)  -> artifacts/tpu_flagship.json
  3. flash tuning    (bench_kernels.py tune)     -> eventgrad_tpu/ops/flash_tuning.json
  4. kernel grid     (bench_kernels.py)          -> KERNELS_TPU.json re-capture
                                                    (rows reflect the tuned dispatch)

Every probe attempt is appended to artifacts/tpu_probe_log.jsonl so a
never-live tunnel is itself documented evidence (VERDICT item 1's "if the
tunnel never answers all round, commit the probe log").

Each ladder step runs in a deadlined subprocess (a wedged tunnel blocks
device ops uninterruptibly; only a supervising parent can recover).

Usage: python tools/tpu_watch.py [max_hours]   (default 11)
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from eventgrad_tpu.utils.procwatch import probe_device_diag, run_deadlined

ART = os.path.join(REPO, "artifacts")
LOG = os.path.join(ART, "tpu_probe_log.jsonl")


def _log(rec: dict) -> None:
    rec["t"] = round(time.time(), 1)
    rec["iso"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


#: wall deadline of the whole watch (set by main); rung timeouts clamp to
#: it so no child can hold the window hours past the session's end
_deadline = None


def _run(cmd: list, timeout_s: float, tag: str, artifact=None,
         env=None) -> bool:
    """Deadlined child. With `artifact`, success means exactly one thing:
    the artifact file was (re)published after the rung started. That both
    salvages a child that published and then wedged in device teardown
    (bench.py's supervisor applies the same rule to its metric line) and
    rejects a clean exit that silently skipped the write (e.g. a CPU
    fallback between probe and child init). Without `artifact`, success =
    clean exit 0 within the deadline. A tunnel that only answered the
    long-deadline probe (EG_SLOW_TUNNEL in env) gets doubled rung
    deadlines — proven-slow must not be held to healthy-tunnel budgets."""
    if (env or os.environ).get("EG_SLOW_TUNNEL"):
        timeout_s *= 2
    if _deadline is not None:
        # never past the watch window itself (+60s grace so a rung
        # started just before the deadline still gets a token chance)
        timeout_s = min(timeout_s, max(60.0, _deadline - time.monotonic()))
    t0_wall = time.time()
    t0 = time.monotonic()
    out, timed_out, rc = run_deadlined(
        cmd, dict(env if env is not None else os.environ), timeout_s,
        cwd=REPO, capture_stderr=True,
    )
    if artifact is not None:
        # the artifact IS the deliverable: a clean exit that didn't
        # (re)publish it — e.g. a child that silently fell back to CPU
        # and skipped the write — has not earned the rung
        try:
            ok = os.path.getmtime(artifact) >= t0_wall - 1.0
        except OSError:
            ok = False
    else:
        ok = rc == 0 and not timed_out
    rec = {"event": tag, "ok": ok, "rc": rc,
           "wall_s": round(time.monotonic() - t0, 1),
           "tail": (out or "")[-2000:]}
    if timed_out:
        rec["timeout_s"] = timeout_s
        rec["salvaged_artifact"] = bool(ok)
    _log(rec)
    return ok


_probe_fails = 0

#: loopback orchestrator relay port. Round-3's wedge correlated with a
#: refused connect here, but round 4 proved the signal non-causal: the
#: tunnel can be fully live with this port closed (probe ok at
#: relay_tcp=refused, 2026-08-02T15:31:29Z in the probe log). Logged as
#: a diagnostic field only — it gates nothing.
_RELAY_ADDR = ("127.0.0.1", 10000)


def _relay_tcp() -> str:
    import socket

    try:
        with socket.create_connection(_RELAY_ADDR, timeout=2.0):
            return "open"
    except ConnectionRefusedError:
        return "refused"
    except OSError as e:
        return type(e).__name__


def _probe(timeout_s: float = 75.0):
    """Diagnostic probe with scheduled resurrection variants (round-3
    verdict item 1): the baseline probe uses the inherited env; every
    4th consecutive failure retries with an explicit JAX_PLATFORMS=axon
    pin (rules out plugin-priority misresolution); every 12th runs a
    long-deadline probe (rules out a tunnel that is merely very slow
    rather than wedged). Each attempt logs the stage the child reached
    and its stderr tail, so the wedge's failure mode is on record.

    Returns the env dict the probe SUCCEEDED with (so the ladder runs
    its rungs under the exact environment that just proved live — a
    variant success must not launch workloads with the base env the
    variant exists to work around), or None on failure. A long-deadline
    success additionally marks the env EG_SLOW_TUNNEL=1 for any rung
    that wants to stretch its own internal budgets."""
    global _probe_fails
    env, variant = dict(os.environ), "base"
    if _probe_fails and _probe_fails % 12 == 0:
        variant, timeout_s = "long_deadline", 600.0
    elif _probe_fails and _probe_fails % 4 == 0:
        variant = "axon_pin"
        env["JAX_PLATFORMS"] = "axon"
    relay = _relay_tcp()
    # round-4 finding: the tunnel can be live with the relay port closed
    # (the claim path no longer rides 127.0.0.1:10000), so the relay
    # status is informational only — every probe runs the real jit child.
    d = probe_device_diag(env, timeout_s, require_tpu=True)
    ok = d["verdict"] == "ok"
    rec = {"event": "probe", "ok": ok, "verdict": d["verdict"],
           "platform": d["platform"], "stage": d["stage"],
           "variant": variant, "relay_tcp": relay}
    if d.get("tail"):
        rec["tail"] = d["tail"][-600:]
    _log(rec)
    _probe_fails = 0 if ok else _probe_fails + 1
    if not ok:
        return None
    if variant == "long_deadline":
        env["EG_SLOW_TUNNEL"] = "1"
    return env


def _is_swept_table(path: str) -> bool:
    """True only for a table written by a real on-chip block sweep
    (bench_kernels.py tune stamps swept=true) — a hand-seeded table from
    prior single-block captures must NOT satisfy the tune rung."""
    try:
        with open(path) as f:
            return bool(json.load(f).get("swept"))
    except (OSError, json.JSONDecodeError, AttributeError):
        return False


def _is_tpu_grid(path: str) -> bool:
    """Only a grid whose header line says platform 'tpu' may replace the
    committed TPU artifact — bench_kernels.py has no TPU assert and its
    kernels silently run in CPU interpret mode if the plugin falls back
    between the probe and the child's init."""
    try:
        with open(path) as f:
            head = json.loads(f.readline())
        return isinstance(head, dict) and head.get("platform") == "tpu"
    except (OSError, json.JSONDecodeError):
        return False


# a committed full artifact supersedes the quick rung entirely — never
# spend a live window (or risk any overwrite) re-earning a lesser one.
# Only chip-captured artifacts count (platform == "tpu"): a stray
# CPU-written file must not gate a rung shut. The FULL rung latches
# only on a COMPLETE artifact: the flagship publishes its ResNet legs
# before the MNIST claim leg (wedge insurance), and a partial publish
# must leave the rung open so a later window completes the MNIST
# numbers the round-4 brief exists to capture.
def _is_tpu_artifact(path, required=()):
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec.get("platform") == "tpu" and all(
            k in rec for k in required
        )
    except (OSError, json.JSONDecodeError, AttributeError):
        return False


_FULL_KEYS = ("mnist_msgs_saved", "mnist_vs_baseline")


def main() -> None:
    global _deadline
    os.makedirs(ART, exist_ok=True)
    max_hours = float(sys.argv[1]) if len(sys.argv) > 1 else 11.0
    deadline = _deadline = time.monotonic() + max_hours * 3600
    have_full = _is_tpu_artifact(
        os.path.join(ART, "tpu_flagship.json"), required=_FULL_KEYS
    )
    have_quick = have_full or _is_tpu_artifact(
        os.path.join(ART, "tpu_flagship_quick.json"), required=_FULL_KEYS
    )
    have_kernels = False  # always re-capture once: round-2 grid had <1x configs
    have_tune = _is_swept_table(
        os.path.join(REPO, "eventgrad_tpu", "ops", "flash_tuning.json")
    )
    flagship = os.path.join(REPO, "tools", "tpu_flagship.py")
    _log({"event": "start", "max_hours": max_hours})

    full_fails = 0
    while time.monotonic() < deadline:
        if have_quick and have_full and have_tune and have_kernels:
            _log({"event": "done"})
            return
        live_env = _probe()
        if live_env is None:
            time.sleep(120)
            continue
        # tunnel is live — climb the ladder, cheapest first, every rung
        # under the exact env the probe succeeded with. The full rung
        # gets 2 tries before the kernels rung takes the window (a
        # full run that can't finish must not starve the re-capture);
        # once kernels are in, leftover windows go back to the full rung.
        if not have_quick:
            quick_env = dict(live_env, EG_FLAGSHIP_TRACE="0")  # cheapest first
            ran = _run(
                [sys.executable, flagship, "8", "tpu_flagship_quick.json"],
                900, "flagship_quick",
                artifact=os.path.join(ART, "tpu_flagship_quick.json"),
                env=quick_env,
            )
            # same completeness latch as the full rung: a partial
            # (pre-MNIST) publish is kept as evidence, rung stays open
            have_quick = ran and _is_tpu_artifact(
                os.path.join(ART, "tpu_flagship_quick.json"),
                required=_FULL_KEYS,
            )
            continue  # re-probe before committing to a longer run
        if not have_full and (full_fails < 2 or (have_tune and have_kernels)):
            ran = _run(
                [sys.executable, flagship, "61"], 3600, "flagship_full",
                artifact=os.path.join(ART, "tpu_flagship.json"),
                env=live_env,
            )
            # the rung is earned only by a COMPLETE artifact (ResNet +
            # MNIST legs); a partial publish is kept as evidence but the
            # rung stays open for the next window
            have_full = ran and _is_tpu_artifact(
                os.path.join(ART, "tpu_flagship.json"), required=_FULL_KEYS
            )
            if not have_full:
                full_fails += 1
            continue
        if not have_tune:
            # per-shape flash block sweep; writes the dispatch table the
            # kernels grid (and all flash users) then consult
            have_tune = _run(
                [sys.executable, os.path.join(REPO, "bench_kernels.py"),
                 "tune", "--out", os.path.join(ART, "flash_tune_grid.jsonl")],
                1800, "flash_tune",
                artifact=os.path.join(REPO, "eventgrad_tpu", "ops",
                                      "flash_tuning.json"),
                env=live_env,
            )
            continue
        if not have_kernels:
            # bench_kernels --out APPENDS: stage to a fresh temp, publish
            # over KERNELS_TPU.json only on success
            staged = os.path.join(ART, "kernels_tpu_staged.jsonl")
            try:
                os.remove(staged)
            except FileNotFoundError:
                pass
            if _run(
                [sys.executable, os.path.join(REPO, "bench_kernels.py"),
                 "--out", staged],
                1800, "kernels", env=live_env,
            ):
                if _is_tpu_grid(staged):
                    os.replace(staged, os.path.join(REPO, "KERNELS_TPU.json"))
                    have_kernels = True
                else:
                    # a non-TPU grid must not linger in the committed
                    # artifacts dir under a TPU-implying name
                    try:
                        os.remove(staged)
                    except FileNotFoundError:
                        pass
    _log({"event": "deadline", "have_quick": have_quick,
          "have_full": have_full, "have_tune": have_tune,
          "have_kernels": have_kernels})


if __name__ == "__main__":
    main()
