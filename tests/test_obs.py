"""Telemetry subsystem: off-path bitwise identity, per-leaf oracle,
span traces, exporters, and the hardened logging/profiling satellites.

The two contracts that matter most (ISSUE acceptance):
  * obs="off" leaves the traced step bit-identical to a telemetry-free
    build, and obs="block"/"epoch" never perturbs the training math —
    only observes it (params bitwise equal across modes);
  * per-leaf fire counts reconcile EXACTLY with the aggregate
    num_events counter from a real 4-rank CPU run (the oracle
    cross-check for msgs_saved_pct_per_leaf).
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _spmd import requires_shard_map

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.obs import (
    OBS_SCHEMA_VERSION, Registry, SILENCE_BUCKETS, TelemetryState,
)
from eventgrad_tpu.obs import device as obs_device
from eventgrad_tpu.obs.report import build_report, load_history_jsonl
from eventgrad_tpu.parallel.events import EventConfig
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.utils.metrics import (
    JsonlLogger, msgs_saved_pct, msgs_saved_pct_per_leaf,
)

_KW = dict(
    algo="eventgrad", epochs=4, batch_size=8, learning_rate=0.1,
    event_cfg=EventConfig(adaptive=True, horizon=0.95, warmup_passes=3),
    seed=0, log_every_epoch=False,
)


def _data():
    return synthetic_dataset(256, (8, 8, 1), seed=1)


def test_obs_off_and_on_trajectories_bitwise_identical():
    """Telemetry observes the run; it must never change it. obs='off'
    (the current-loop default) and obs='block' produce bitwise-identical
    parameters and identical core history fields."""
    x, y = _data()
    s_off, h_off = train(MLP(hidden=16), Ring(4), x, y, **_KW)
    s_on, h_on = train(
        MLP(hidden=16), Ring(4), x, y, obs="block",
        epochs_per_dispatch=2, **_KW
    )
    for a, b in zip(jax.tree.leaves(s_off.params), jax.tree.leaves(s_on.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for r_off, r_on in zip(h_off, h_on):
        assert r_off["loss"] == r_on["loss"]
        assert r_off["num_events"] == r_on["num_events"]
    # off: no telemetry state, no obs blocks anywhere in the history
    assert s_off.telemetry is None
    assert not any("obs" in h for h in h_off)


def test_obs_per_leaf_oracle_against_num_events():
    """4-rank CPU run: summed per-leaf fire counts * n_neighbors ==
    EventState.num_events, and the mean of the per-leaf msgs-saved-%
    equals the aggregate msgs_saved_pct over the same window."""
    x, y = _data()
    state, hist = train(
        MLP(hidden=16), Ring(4), x, y, obs="block", **_KW
    )
    obs_recs = [h["obs"] for h in hist if "obs" in h]
    assert obs_recs, "block-end records must carry obs telemetry"
    total_fires = sum(sum(o["fire_count"]) for o in obs_recs)
    assert total_fires * 2 == int(np.asarray(state.event.num_events).sum())
    # window oracle: per-leaf mean == aggregate over the SAME passes
    passes = sum(o["steps"] for o in obs_recs)
    fire_total = np.sum([o["fire_count"] for o in obs_recs], axis=0)
    per_leaf = msgs_saved_pct_per_leaf(fire_total, passes, 2, 4)
    agg = msgs_saved_pct(int(total_fires) * 2, passes, len(fire_total), 2, 4)
    assert abs(np.mean(per_leaf) - agg) < 1e-9
    # meta rides the first obs record only
    assert obs_recs[0]["meta"]["leaves"] == [
        "Dense_0/bias", "Dense_0/kernel", "Dense_1/bias", "Dense_1/kernel",
    ]
    assert obs_recs[0]["meta"]["n_ranks"] == 4
    assert all("meta" not in o for o in obs_recs[1:])
    # consensus-error probe lands at block ends on obs runs too
    assert "consensus_err_max" in hist[-1]
    # schema stamp and histogram geometry
    assert obs_recs[0]["schema"] == OBS_SCHEMA_VERSION
    assert len(obs_recs[0]["silence_hist"]) == SILENCE_BUCKETS
    # silence histogram counts leaf-passes: one entry per leaf per pass
    assert sum(obs_recs[0]["silence_hist"]) == obs_recs[0]["steps"] * 4 * 4


def test_obs_epoch_granularity_forces_per_epoch_blocks():
    """obs='epoch' pins the dispatch to one epoch per block, so EVERY
    epoch record carries telemetry even when the caller asked for fused
    multi-epoch dispatch."""
    x, y = _data()
    _, hist = train(
        MLP(hidden=16), Ring(4), x, y, obs="epoch",
        epochs_per_dispatch=4, **_KW
    )
    assert len(hist) == _KW["epochs"]
    assert all("obs" in h for h in hist)
    assert all(h["obs"]["steps"] == h["steps"] for h in hist)


def test_obs_compact_wire_utilization_and_deferrals():
    """Compact-wire run: deferral counts in the telemetry reconcile with
    EventState.num_deferred, and admitted elements never exceed the
    static capacity."""
    x, y = _data()
    kw = dict(_KW)
    kw["event_cfg"] = EventConfig(
        adaptive=True, horizon=0.95, warmup_passes=2, max_silence=20
    )
    state, hist = train(
        MLP(hidden=16), Ring(4), x, y, obs="block",
        gossip_wire="compact", compact_frac=0.6, **kw
    )
    cap_recs = [h for h in hist if h.get("compact_capacity")]
    assert cap_recs, "compact_frac run must activate the compact wire"
    cap = cap_recs[-1]["compact_capacity"]
    obs_recs = [h["obs"] for h in hist if "obs" in h]
    defer_total = sum(sum(o["defer_count"]) for o in obs_recs)
    assert defer_total == int(np.asarray(state.event.num_deferred).sum())
    # admitted payload is bounded by the budget on every compact window
    compact_epochs = {h["epoch"] for h in cap_recs}
    for h in hist:
        if h["epoch"] in compact_epochs and "obs" in h:
            assert h["obs"]["fired_elems_mean"] <= cap + 1e-6
    # report renders the utilization section from this history
    report = build_report(hist)
    cu = report["capacity_utilization"]
    assert cu["compact_capacity"] == cap
    assert 0.0 <= cu["deferral_rate"] <= 1.0
    assert cu["per_window"], "per-window utilization series expected"
    assert report["msgs_saved_pct_per_leaf"]["pct"]
    assert report["consensus_error"]["max"]


def test_loop_spans_nest_under_train_root():
    """train(registry=...) records dispatch/flush/eval spans nested
    under one 'train' root span — the structure the Chrome-trace export
    preserves."""
    x, y = _data()
    xt, yt = synthetic_dataset(64, (8, 8, 1), seed=1, split="test")
    reg = Registry()
    kw = dict(_KW)
    kw.pop("log_every_epoch")
    train(
        MLP(hidden=16), Ring(4), x, y, obs="block", registry=reg,
        epochs_per_dispatch=2, x_test=xt, y_test=yt, **kw
    )
    by_name = {}
    for s in reg.spans:
        by_name.setdefault(s.name, []).append(s)
    assert len(by_name["train"]) == 1
    root = by_name["train"][0]
    assert root.depth == 0
    # 4 epochs at K=2 -> 2 dispatch blocks, each with one flush
    assert len(by_name["dispatch_block"]) == 2
    assert len(by_name["obs_flush"]) == 2
    assert len(by_name["eval"]) == 2  # block-end evals
    for s in reg.spans:
        if s.name == "train":
            continue
        assert s.depth == 1
        # temporal containment inside the root span
        assert s.ts_us >= root.ts_us - 1
        assert s.ts_us + s.dur_us <= root.ts_us + root.dur_us + 1


def test_chrome_trace_loads_and_keeps_nesting(tmp_path):
    """The exported JSON is Chrome Trace Event Format: a traceEvents
    list of complete ('X') events with us timestamps — what Perfetto
    and chrome://tracing load directly."""
    reg = Registry(run_meta={"run": "test"})
    with reg.span("outer", cat="run", block=0):
        with reg.span("inner_a", cat="device"):
            pass
        with reg.span("inner_b", cat="host"):
            pass
    path = tmp_path / "trace.json"
    reg.write_chrome_trace(str(path))
    trace = json.loads(path.read_text())
    evs = trace["traceEvents"]
    assert len(evs) == 3
    assert {e["ph"] for e in evs} == {"X"}
    assert all(
        set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid"}
        for e in evs
    )
    assert trace["otherData"]["obs_schema"] == OBS_SCHEMA_VERSION
    by = {e["name"]: e for e in evs}
    # nesting: children contained in the parent, deeper depth arg
    for child in ("inner_a", "inner_b"):
        assert by[child]["args"]["depth"] == 1
        assert by[child]["ts"] >= by["outer"]["ts"]
        assert (
            by[child]["ts"] + by[child]["dur"]
            <= by["outer"]["ts"] + by["outer"]["dur"] + 0.2
        )
    # events are start-time sorted (the viewers' expectation)
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
    # gauges ride otherData so a trace file is self-contained
    reg.gauge("bench_step_ms", 12.5)
    assert reg.chrome_trace()["otherData"]["gauges"] == {
        "bench_step_ms": 12.5,
    }


def test_registry_prometheus_and_unified_fragments():
    """The registry folds all three legacy fragments — JSONL records,
    timed_steps latencies, chaos peer health — behind one schema."""
    reg = Registry()
    reg.record({"epoch": 1, "loss": 0.5})
    assert reg.n_records == 1
    reg.observe_latency(
        {"compile_s": 1.5, "step_ms_mean": 2.0, "step_ms_p50": 1.9,
         "step_ms_p95": 2.5}
    )
    rec = reg.observe_health(
        np.array([[3, 50], [2, 7]]), np.array([4, 1]), max_silence=10,
        edges=["ring_m1", "ring_p1"],
    )
    assert rec["edge_silence_max"] == [3, 50]
    assert rec["edge_status"] == ["healthy", "suspect"]
    assert rec["edges"] == ["ring_m1", "ring_p1"]
    text = reg.prometheus_text()
    assert "# TYPE eventgrad_step_ms_p50 gauge" in text
    assert "eventgrad_step_ms_p50 1.9" in text
    assert 'eventgrad_edge_silence_max{edge="ring_p1"} 50' in text
    assert "eventgrad_chaos_drops_total 5" in text


def test_registry_jsonl_superset_and_ownership(tmp_path):
    """Records forwarded through the registry are a superset of the raw
    logger's (same keys + obs_schema); an owned logger closes with the
    registry, a wrapped one stays open."""
    path = tmp_path / "log.jsonl"
    with Registry(jsonl_path=str(path), echo=False) as reg:
        reg.record({"epoch": 1, "loss": 0.25})
    rec = json.loads(path.read_text().splitlines()[0])
    assert rec["obs_schema"] == OBS_SCHEMA_VERSION
    assert rec["epoch"] == 1 and rec["loss"] == 0.25 and "ts" in rec

    outer = JsonlLogger(str(tmp_path / "outer.jsonl"), echo=False)
    reg2 = Registry(logger=outer)
    reg2.record({"epoch": 2})
    reg2.close()
    outer.log({"after": True})  # wrapped logger must still be open
    outer.close()
    lines = (tmp_path / "outer.jsonl").read_text().splitlines()
    assert len(lines) == 2


def test_jsonl_logger_context_manager_and_fsync(tmp_path):
    """Satellite: `with JsonlLogger(...)` closes on exception paths, and
    fsync=True keeps every record durable without an explicit close."""
    path = tmp_path / "log.jsonl"
    with pytest.raises(RuntimeError):
        with JsonlLogger(str(path), echo=False) as log:
            log.log({"n": 1})
            raise RuntimeError("boom")
    assert log._fh is None  # closed despite the exception
    assert json.loads(path.read_text().splitlines()[0])["n"] == 1
    # close is idempotent (with-block + explicit close)
    log.close()

    fpath = tmp_path / "fsync.jsonl"
    flog = JsonlLogger(str(fpath), echo=False, fsync=True)
    flog.log({"n": 2})
    # durable before close: read through a fresh descriptor
    with open(fpath) as f:
        assert json.loads(f.read().splitlines()[0])["n"] == 2
    flog.close()


def test_jsonl_logger_nonfinite_values_stay_valid_json(tmp_path):
    """Satellite: NaN/Inf metric values (a diverging loss — exactly the
    record an operator most needs) serialize as null plus a
    `nonfinite_fields` rider instead of the bare `NaN` token
    `json.loads` rejects (or a mid-run ValueError from allow_nan=False).
    Finite records stay byte-for-byte the legacy serialization."""
    path = tmp_path / "log.jsonl"
    with JsonlLogger(str(path), echo=False) as log:
        log.log({"epoch": 1, "loss": 0.5})  # finite: legacy path
        log.log({
            "epoch": 2,
            "loss": float("nan"),
            "per_edge": [1.0, float("inf"), 2.0],
            "nested": {"acc": float("-inf"), "ok": 3.0},
            "npval": np.float32("nan"),  # numpy scalars scrub too
            "label": "diverged",
        })
    lines = path.read_text().splitlines()
    finite = json.loads(lines[0])  # every line must parse
    assert finite["loss"] == 0.5 and "nonfinite_fields" not in finite
    rec = json.loads(lines[1])
    assert rec["loss"] is None
    assert rec["per_edge"] == [1.0, None, 2.0]
    assert rec["nested"]["acc"] is None and rec["nested"]["ok"] == 3.0
    assert rec["npval"] is None
    assert rec["label"] == "diverged" and rec["epoch"] == 2
    assert sorted(rec["nonfinite_fields"]) == [
        "loss", "nested.acc", "npval", "per_edge[1]",
    ]


def test_profiling_trace_warns_and_still_yields(monkeypatch):
    """Satellite: the no-op path emits a capturable `warnings` warning
    (not a bare stderr print) and the context still runs its body."""
    from eventgrad_tpu.utils import profiling

    def boom(*a, **k):
        raise RuntimeError("no backend")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    ran = []
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with profiling.trace("/tmp/nonexistent-trace-dir"):
            ran.append(True)
    assert ran == [True]
    assert any("trace unavailable" in str(w.message) for w in caught)


def test_msgs_saved_pct_per_leaf_guard_and_values():
    """Satellite: the per-leaf variant shares the aggregate's division
    guard (zero possible messages -> 0.0) and its arithmetic."""
    assert msgs_saved_pct_per_leaf([5, 0], 0, 2, 4) == [0.0, 0.0]
    assert msgs_saved_pct_per_leaf([5, 0], 10, 0, 4) == [0.0, 0.0]
    # 10 passes x 4 ranks possible per leaf: 40; 10 fired -> 75% saved
    assert msgs_saved_pct_per_leaf([10, 0, 40], 10, 2, 4) == [
        75.0, 100.0, 0.0,
    ]
    # the existing aggregate guard (kept under test here too)
    assert msgs_saved_pct(0, 0, 0, 0, 0) == 0.0


def test_window_record_diffs_cumulative_snapshots():
    """Host flush math: per-window deltas from cumulative stacked
    counters, counts summed over ranks, means averaged."""
    def snap(steps, fire, thres, edge):
        return TelemetryState(
            steps=np.full((2,), steps, np.int32),
            fire_count=np.asarray(fire, np.int32),
            defer_count=np.zeros((2, 2), np.int32),
            thres_sum=np.asarray(thres, np.float32),
            drift_sum=np.zeros((2, 2), np.float32),
            silence_hist=np.zeros((2, SILENCE_BUCKETS), np.int32),
            fired_elems_sum=np.full((2,), 100.0, np.float32),
            fired_elems_peak=np.asarray([30.0, 40.0], np.float32),
            edge_bytes=np.asarray(edge, np.float32),
        )

    prev = snap(10, [[4, 2], [6, 0]], [[10.0, 0], [30.0, 0]],
                [[100.0, 100.0], [100.0, 100.0]])
    cur = snap(14, [[8, 2], [8, 4]], [[18.0, 0], [34.0, 0]],
               [[180.0, 180.0], [180.0, 180.0]])
    rec = obs_device.window_record(cur, prev)
    assert rec["steps"] == 4
    assert rec["fire_count"] == [6, 4]  # summed over the 2 ranks
    assert rec["thres_mean"][0] == pytest.approx((8 + 4) / 2 / 4)
    assert rec["fired_elems_peak"] == 40.0
    assert rec["edge_bytes_per_step"] == [20.0, 20.0]
    # first flush: prev=None means "since init"
    first = obs_device.window_record(prev)
    assert first["steps"] == 10 and first["fire_count"] == [10, 2]


def test_obs_resume_continues_counters(tmp_path):
    """Telemetry is snapshot state: an interrupted+resumed obs run ends
    with the same cumulative counters as the uninterrupted one."""
    x, y = _data()
    kw = dict(_KW)
    straight, _ = train(
        MLP(hidden=16), Ring(4), x, y, obs="block", **kw
    )
    ck = str(tmp_path / "ck")
    kw2 = dict(kw)
    kw2["epochs"] = 2
    train(MLP(hidden=16), Ring(4), x, y, obs="block",
          checkpoint_dir=ck, **kw2)
    resumed, _ = train(
        MLP(hidden=16), Ring(4), x, y, obs="block",
        checkpoint_dir=ck, resume=True, **kw
    )
    for a, b in zip(
        jax.tree.leaves(straight.telemetry), jax.tree.leaves(resumed.telemetry)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_report_from_jsonl_stream(tmp_path):
    """tools/obs_report.py path: history JSONL -> report with per-leaf
    savings and consensus series (the committed-artifact pipeline)."""
    x, y = _data()
    path = tmp_path / "hist.jsonl"
    with JsonlLogger(str(path), echo=False) as log:
        reg = Registry(logger=log)
        train(
            MLP(hidden=16), Ring(4), x, y, obs="block",
            registry=reg, on_epoch=reg.record, **_KW
        )
    history = load_history_jsonl(str(path))
    assert len(history) == _KW["epochs"]
    report = build_report(history)
    assert report["obs_schema"] == OBS_SCHEMA_VERSION
    pls = report["msgs_saved_pct_per_leaf"]
    assert pls["leaves"] and pls["pct"]
    assert len(pls["pct"][0]) == len(pls["leaves"])
    assert report["fire_rate_heatmap"]["rows"]
    assert report["thres_heatmap"]["rows"]
    assert report["consensus_error"]["epochs"]
    assert report["capacity_utilization"] is None  # dense run


def test_bubble_truncated_trace_degrades_gracefully(tmp_path):
    """A trace missing span types — a run killed before the pipelined
    block_ready readbacks landed, or with no train root at all — yields
    a NAMED warning and a PARTIAL decomposition instead of a KeyError
    (the report of a dead run is exactly when the tool is needed)."""
    from eventgrad_tpu.obs import bubble
    from eventgrad_tpu.obs.bubble import IncompleteTraceWarning

    truncated = [
        {"name": "train", "ph": "X", "ts": 0.0, "dur": 1e6, "args": {}},
        {"name": "dispatch_block", "ph": "X", "ts": 100.0, "dur": 1000.0,
         "args": {"block": 0, "pipelined": True}},
        # block 1's block_ready made it; block 0's was lost to the kill
        {"name": "dispatch_block", "ph": "X", "ts": 2e5, "dur": 1000.0,
         "args": {"block": 1, "pipelined": True}},
        {"name": "block_ready", "ph": "X", "ts": 3e5, "dur": 50.0,
         "args": {"block": 1}},
    ]
    with pytest.warns(IncompleteTraceWarning, match="block_ready"):
        d = bubble.decompose(truncated)
    assert d["missing_spans"] == ["block_ready"]
    assert d["n_blocks"] == 2 and d["pipelined"]
    assert 0.0 <= d["host_bubble_frac"] <= 1.0
    # rootless trace: envelope fallback, named as missing
    with pytest.warns(IncompleteTraceWarning, match="train"):
        d2 = bubble.decompose(truncated[1:])
    assert "train" in d2["missing_spans"]
    # a COMPLETE trace stays warning-free
    complete = truncated[:1] + [
        {"name": "dispatch_block", "ph": "X", "ts": 100.0, "dur": 1000.0,
         "args": {"block": 0, "pipelined": False}},
    ]
    with warnings.catch_warnings():
        warnings.simplefilter("error", IncompleteTraceWarning)
        bubble.decompose(complete)
    # render_text tolerates partial dicts (older-tool artifacts) and
    # flags partial decompositions
    assert "PARTIAL" in bubble.render_text(d)
    assert bubble.render_text({"wall_s": 1.0})  # no KeyError
    # the CLI path: a truncated/broken trace file degrades the same way
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report_tool",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "obs_report.py",
        ),
    )
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    hist = tmp_path / "h.jsonl"
    hist.write_text('{"epoch": 1, "loss": 1.0}\n')
    broken = tmp_path / "broken.json"
    broken.write_text('{"traceEvents": [')
    with pytest.warns(IncompleteTraceWarning, match="unreadable"):
        rc = tool.main([str(hist), "--trace", str(broken), "--quiet"])
    assert rc == 0
    empty = tmp_path / "empty.json"
    empty.write_text('{"otherData": {}}')
    with pytest.warns(IncompleteTraceWarning, match="no traceEvents"):
        rc = tool.main([str(hist), "--trace", str(empty), "--quiet"])
    assert rc == 0


def test_docs_cover_every_schema_field():
    """docs/OBSERVABILITY.md mirrors obs/schema.py field-for-field — the
    doc is the schema's human surface and must not drift."""
    from eventgrad_tpu.obs import schema

    doc_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "OBSERVABILITY.md",
    )
    with open(doc_path) as f:
        doc = f.read()
    missing = [n for n in schema.all_field_names() if n not in doc]
    assert not missing, f"fields undocumented in OBSERVABILITY.md: {missing}"


# the vmap lift proves the telemetry math even where the mesh lift is
# unavailable (tests/_spmd.py)
@requires_shard_map
def test_telemetry_matches_across_lifts():
    """Telemetry counters under the shard_map lift equal the vmap
    simulation's, like every other state leaf."""
    import optax

    from eventgrad_tpu.parallel.spmd import build_mesh, spmd, stack_for_ranks
    from eventgrad_tpu.train.state import init_train_state
    from eventgrad_tpu.train.steps import make_train_step
    from eventgrad_tpu.utils import trees

    topo = Ring(4)
    model = MLP(hidden=8)
    tx = optax.sgd(0.1)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=1)
    state = init_train_state(model, (8, 8, 1), tx, topo, "eventgrad", cfg)
    state = state.replace(telemetry=stack_for_ranks(
        TelemetryState.init(
            trees.tree_num_leaves(state.params), topo.n_neighbors
        ), topo,
    ))
    step = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=cfg, obs=True
    )
    x, y = synthetic_dataset(32, (8, 8, 1), seed=2)
    batch = (
        jnp.asarray(x.reshape(4, 8, 8, 8, 1)), jnp.asarray(y.reshape(4, 8))
    )
    out_v, _ = jax.jit(spmd(step, topo))(state, batch)
    out_s, _ = jax.jit(spmd(step, topo, mesh=build_mesh(topo)))(state, batch)
    for a, b in zip(
        jax.tree.leaves(out_v.telemetry), jax.tree.leaves(out_s.telemetry)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
