"""Recovery policies: forced sync, edge freeze, ring heal.

Three escalating answers to a quiet edge, keyed off `monitor.PeerHealth`
silence counters:

  * forced full-sync (`sync_after`) — the receiver-side generalization of
    `EventConfig.max_silence`: when an incoming edge has been silent
    `sync_after` passes, the receiver gossips a 1-bit request back along
    the reverse edge (`monitor.sync_requests`) and the sender force-fires
    EVERY parameter on its next pass (`decide_and_update(force_fire=...)`),
    refreshing the stale buffer through the normal exchange. Works through
    loss (the request repeats every pass while silence persists), costs
    real messages (counted in num_events — robustness spends savings).

  * edge freeze (`freeze_after`) — when silence exceeds the bound, the
    edge's stale buffer leaves the mix and the weights renormalize:
    p <- (p + sum(alive bufs)) / (1 + n_alive)  (collectives.mix_weighted)
    instead of averaging in a years-old value forever. Un-freezes itself
    the moment a payload arrives again (silence resets) — a flaky window
    ends and the edge rejoins.

  * ring heal (`heal_ring` / `apply_ring_heal`) — permanent peer death:
    survivors bridge the gap by rewriting the `Topology` to the (n-1)-rank
    ring and slicing the dead rank's rows out of the stacked state. The
    healed ring's `neighbor_source` is exactly `Ring(n-1)`'s, so every
    downstream collective just works; receive buffers are kept (stale
    values are legal gossip input by construction, event.cpp:177-179) and
    refresh within one fire cycle, while PeerHealth silence resets so the
    new edges start healthy.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from eventgrad_tpu.parallel.topology import Ring, Topology


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Receiver-side recovery bounds (0 disables a mechanism).

    Both bounds should comfortably exceed the sender's
    `EventConfig.max_silence` guarantee (see `monitor.edge_status`):
    below it they would fight legitimate event-triggered silence and
    spend messages on healthy links.
    """

    sync_after: int = 0
    freeze_after: int = 0

    def __post_init__(self):
        if self.sync_after < 0 or self.freeze_after < 0:
            raise ValueError(
                f"recovery bounds must be >= 0, got {self}"
            )

    @property
    def is_noop(self) -> bool:
        return self.sync_after == 0 and self.freeze_after == 0

    def validate_against(self, max_silence: int) -> None:
        """Loud guard: bounds at or below the sender-side silence
        guarantee would force-sync/freeze healthy edges every cycle."""
        for name, bound in (
            ("sync_after", self.sync_after),
            ("freeze_after", self.freeze_after),
        ):
            if bound and max_silence and bound <= max_silence:
                raise ValueError(
                    f"{name}={bound} is within the sender's "
                    f"max_silence={max_silence} guarantee: healthy "
                    "event-triggered silence would trip it every cycle "
                    f"(use {name} > max_silence)"
                )

    def to_dict(self) -> dict:
        return {
            "sync_after": self.sync_after,
            "freeze_after": self.freeze_after,
        }


def alive_mask(health_silence: jnp.ndarray, policy: "RecoveryPolicy"):
    """bool [n_neighbors]: edges whose buffers stay in the mix. With
    freeze disabled this is None (callers keep the untouched mix path,
    which is bitwise-identical to pre-chaos trajectories)."""
    if not policy.freeze_after:
        return None
    return health_silence < policy.freeze_after


def heal_ring(
    topo: Topology, dead: Iterable[int]
) -> Tuple[Topology, Tuple[int, ...]]:
    """Rewrite a ring topology without the dead ranks.

    Returns (healed topology, survivors) where survivors[j] is the OLD
    flat rank now living at healed rank j: surviving neighbors bridge the
    gap, i.e. healed `neighbor_source` is `Ring(n_survivors)`'s, which in
    old-rank terms wires each survivor to the cyclically-next survivor.
    Ring (single-gossip-axis) topologies only — a torus heal has
    non-unique bridge choices and is future work.
    """
    dead_set = set(int(d) for d in dead)
    if len(topo.gossip_axes) != 1 or len(topo.axes) != 1:
        raise ValueError(
            f"heal_ring handles single-axis rings; got axes {topo.axes}"
        )
    bad = [d for d in dead_set if not 0 <= d < topo.n_ranks]
    if bad:
        raise ValueError(f"dead ranks {bad} outside 0..{topo.n_ranks - 1}")
    survivors = tuple(r for r in range(topo.n_ranks) if r not in dead_set)
    if len(survivors) < 2:
        raise ValueError(
            f"cannot heal: only {len(survivors)} of {topo.n_ranks} ranks "
            "survive (a ring needs >= 2)"
        )
    return Ring(len(survivors), axis=topo.axes[0]), survivors


def apply_ring_heal(state, topo: Topology, dead: Iterable[int]):
    """Slice a stacked train state down to the survivors of a ring heal.

    Returns (healed state, healed topology, survivors). Every leaf keeps
    its meaning — params/optimizer/event thresholds are per-rank rows;
    receive buffers now face the bridged neighbors and are stale until
    the next fire, which gossip tolerates by construction. PeerHealth
    silence resets so recovery policies don't instantly re-trip on the
    fresh edges.
    """
    healed, survivors = heal_ring(topo, dead)
    idx = jnp.asarray(np.asarray(survivors, np.int32))
    new_state = jax.tree.map(lambda x: jnp.take(x, idx, axis=0), state)
    chaos = getattr(new_state, "chaos", None)
    if chaos is not None:
        chaos = chaos.replace(
            silence=jnp.zeros_like(chaos.silence),
            sync_req=jnp.zeros_like(chaos.sync_req),
        )
        new_state = new_state.replace(chaos=chaos)
    return new_state, healed, survivors
