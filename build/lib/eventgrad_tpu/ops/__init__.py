from eventgrad_tpu.ops.attention import (
    flash_attention,
    flash_attention_lse,
    flash_attention_reference,
)
from eventgrad_tpu.ops.fused_update import fused_mix_sgd, mix_sgd_reference
