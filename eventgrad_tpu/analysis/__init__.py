"""Static analysis over the traced train step + the project lint rules.

Four layers, each importable on its own:

  * `walker` — structure-blind traversal of a jaxpr through every nested
    sub-jaxpr (pjit / scan / cond / while / custom_jvp / remat /
    pallas_call kernel bodies), plus the op-accounting primitives the
    regression gates are built from (`count_primitives`,
    `count_full_ravels`).
  * `rankflow` — a dataflow analysis over the vmap-lifted step proving
    RANK ISOLATION: every intermediate is tracked for which array axis
    (if any) carries the rank coordinate — pure or BLOCKED (the conv
    batching rules' rank-major merges) — and the only equations allowed
    to move information ACROSS that axis are the declared neighbor
    exchanges (the constant-permutation gathers `lax.ppermute` lowers to
    under vmap) — anything else is a violation.
  * `kernels` — the declared-kernel registry: rank-dim signatures for
    opaque `pallas_call` boundaries (the flash family, the arena/event
    engines); unregistered kernels stay rankflow violations.
  * `audit` — the per-configuration auditor ON the production
    geometries (LeNetCifar / ResNet18 / transformer full+flash / MLP
    base): rank isolation, wire-byte truth (bytes derived from the
    exchange lanes' shapes/dtypes == the independent formula == the
    step's `sent_bytes_wire_real` metric), and step hygiene (no host
    callbacks, full-model ravel budget, wire dtype fidelity, donation
    aliasing) — with seeded ORACLE violations proving each check can
    actually fire.

`lint` is the AST-based source lint framework (exit-code literals,
`os._exit` confinement, host syncs in traced paths, the shard_map
skip-pattern, crashpoint instrumentation); tier-1 tests and
`tools/audit.py` both run it.  See docs/ANALYSIS.md.

Submodules import explicitly (`from eventgrad_tpu.analysis import
lint`): no eager package-level imports, so the AST-only lint never
pays the auditor's jax/optax/model import chain and the
`python -m eventgrad_tpu.analysis.lint` CLI runs warning-free.
"""
