"""Integrity engine: wire checksums, non-finite quarantine, rollback.

The chaos subsystem (PR 1) and elastic membership (PR 6) handle LOST and
DEPARTED peers; this module handles LYING peers and SICK ranks. Three
defense layers, each riding an existing seam:

  * **wire checksums** — every masked/compact gossip payload ships an
    int32 `collectives.wire_checksum` of its exact wire bits; the
    receiver recomputes and compares. A failed check (an injected
    `bitflip=`, a real link error) is treated exactly as an event that
    did not fire: the stale buffer survives, bitwise-defined, and the
    rejection is counted per edge. Rejections keep the edge's PeerHealth
    silence growing, so persistent corruption escalates to the EXISTING
    recovery policies (forced full-sync, edge freeze) with no new
    machinery.

  * **non-finite quarantine** — finite-guards at three points of the
    fused step: local gradients (a `nanstep=`-poisoned rank, an
    overflowed loss), incoming payloads (belt-and-suspenders on the
    wire), and post-update parameters (an lr blowup). A rank whose
    gradients go non-finite QUARANTINES for the step: it skips its
    optimizer update, suppresses its sends (receivers see one more quiet
    pass), but keeps mixing with healthy neighbors — gossip itself is
    the recovery path.

  * **rollback-to-last-good** — detection can come too late: a finite-
    but-wrong payload accepted before checksums were enabled, or
    divergence from an unguarded fault class. A host-side
    `DivergenceSentinel` rides the per-block telemetry flush (loss-spike
    + consensus-error escalation detector); on trip, the loop restores
    every rank from the retained last-known-good snapshot
    (utils/checkpoint.RollingRetention), re-arms all event buffers
    through the membership engine's `force_refresh`, HARDENS the step
    (checksums + quarantine on, one recompile) and replays. The whole
    run — faults, rollback, replay — is bitwise-reproducible from the
    seed. A second trip beyond `max_rollbacks` raises
    `IntegrityEscalation`; the CLI exits `INTEGRITY_ABORT_EXIT` and the
    supervisor gives up WITHOUT a restart (a restart would replay the
    same divergence).

Fault vocabulary (`chaos/schedule.py`): `bitflip=S-E@p` corrupts one
payload bit per hit, `nanstep=R@P` poisons rank R's gradients — both
seeded and replayable, so every defense above is exercised by
deterministic injection (tools/integrity_sweep.py commits the proof as
artifacts/integrity_cpu.json).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

# process exit code for "integrity engine gave up" (sentinel tripped
# beyond max_rollbacks): the supervisor treats it as PERMANENT and does
# not restart — a relaunch would replay the same divergence. The value
# lives in the jax-free exit-code contract module (eventgrad_tpu/
# exitcodes.py, shared with the supervisor); re-exported here for the
# existing importers (cli, chaos.__init__, tests).
from eventgrad_tpu.exitcodes import INTEGRITY_ABORT_EXIT  # noqa: F401


class IntegrityEscalation(RuntimeError):
    """The divergence sentinel tripped beyond the rollback budget: the
    retained last-known-good state cannot outrun the fault. Human (or
    supervisor-policy) attention required; restarting is not it."""


@dataclasses.dataclass(frozen=True)
class IntegrityConfig:
    """Static integrity-engine configuration (train(integrity=...)).

    checksum / quarantine gate the in-step defenses (trace-time static:
    with both off the traced step is bit-identical to integrity=None).
    sentinel / rollback control the host-side engine. The sentinel
    thresholds are deliberately loose — they exist to catch order-of-
    magnitude divergence (a flipped exponent bit, a poisoned rank), not
    SGD noise; `loss_floor` keeps early high-loss epochs from tripping.

    escalate=True re-builds the step with checksum+quarantine ON after a
    rollback (one recompile): the replayed segment meets the same
    scheduled faults — replay is pass-keyed — so rolling back without
    hardening would diverge identically and burn the budget.
    """

    checksum: bool = True
    quarantine: bool = True
    sentinel: bool = True
    rollback: bool = True
    escalate: bool = True
    #: sentinel: trip when a block's mean loss exceeds loss_spike x the
    #: best (finite) block loss seen so far AND the loss_floor, or goes
    #: non-finite
    loss_spike: float = 4.0
    loss_floor: float = 1.0
    #: sentinel: trip when the block consensus-error max exceeds
    #: consensus_spike x the best block value seen so far AND the floor
    consensus_spike: float = 100.0
    consensus_floor: float = 10.0
    #: rollbacks allowed before IntegrityEscalation
    max_rollbacks: int = 1
    #: validated last-known-good snapshots retained on disk (with a
    #: checkpoint_dir; an in-memory snapshot always backs the rollback)
    keep_good: int = 2

    def __post_init__(self):
        if self.max_rollbacks < 0 or self.keep_good < 1:
            raise ValueError(
                f"max_rollbacks >= 0 and keep_good >= 1 required, got {self}"
            )
        for name in ("loss_spike", "consensus_spike"):
            if getattr(self, name) <= 1.0:
                raise ValueError(f"{name} must be > 1, got {getattr(self, name)}")

    @property
    def is_noop(self) -> bool:
        return not (
            self.checksum or self.quarantine or self.sentinel or self.rollback
        )

    def hardened(self) -> "IntegrityConfig":
        """The post-rollback escalation target: full in-step defenses."""
        return dataclasses.replace(self, checksum=True, quarantine=True)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "IntegrityConfig":
        return cls(**{
            f.name: d[f.name] for f in dataclasses.fields(cls) if f.name in d
        })

    @classmethod
    def parse(cls, spec: str) -> "IntegrityConfig":
        """CLI spec grammar (`--integrity`): `on`, `off`, or comma-
        separated `field=value` clauses over the config fields —
        e.g. `checksum=0,quarantine=0,max_rollbacks=2`. Booleans take
        0/1/true/false; `off` is `IntegrityConfig` with every engine
        disabled (resolve() maps it to None)."""
        spec = spec.strip()
        if spec == "on":
            return cls()
        if spec == "off":
            return cls(
                checksum=False, quarantine=False,
                sentinel=False, rollback=False,
            )
        fields = {f.name: f.type for f in dataclasses.fields(cls)}
        kw: Dict[str, Any] = {}
        for clause in spec.split(","):
            name, sep, val = clause.partition("=")
            name = name.strip()
            if not sep or name not in fields:
                raise ValueError(
                    f"integrity clause {clause!r} invalid; expected 'on', "
                    "'off', or comma-separated field=value over "
                    f"{sorted(fields)}"
                )
            val = val.strip()
            if fields[name] in ("bool", bool):
                if val.lower() not in ("0", "1", "true", "false"):
                    raise ValueError(
                        f"integrity {name}= takes 0/1/true/false, got {val!r}"
                    )
                kw[name] = val.lower() in ("1", "true")
            elif fields[name] in ("int", int):
                kw[name] = int(val)
            else:
                kw[name] = float(val)
        return cls(**kw)


def resolve(integrity) -> Optional[IntegrityConfig]:
    """Accept an IntegrityConfig, a spec string ("on"/"off"/"k=v,..."),
    a serialized dict, or None — the one coercion used by train() and
    the CLI. A config with every engine off resolves to None."""
    if integrity is None:
        return None
    if isinstance(integrity, IntegrityConfig):
        return None if integrity.is_noop else integrity
    if isinstance(integrity, str):
        return resolve(IntegrityConfig.parse(integrity))
    if isinstance(integrity, dict):
        return resolve(IntegrityConfig.from_dict(integrity))
    raise TypeError(
        "integrity must be an IntegrityConfig, a spec string, dict, or "
        f"None; got {type(integrity)}"
    )


class DivergenceSentinel:
    """Host-side divergence detector riding the per-block drain.

    Tracks the best (minimum, finite) block-mean loss and the best block
    consensus-error max seen so far; `observe()` returns a trip verdict
    when the current block departs by the configured spike factors (or
    the loss goes non-finite — NaN's compare-False semantics must not
    slip through). State is tiny and host-only; after a rollback the
    loop calls `rewind()` so the replayed blocks are judged against the
    pre-divergence baseline, deterministically.
    """

    def __init__(self, cfg: IntegrityConfig):
        self.cfg = cfg
        self.best_loss: Optional[float] = None
        self.best_cerr: Optional[float] = None
        self.trips = 0

    def snapshot(self) -> Dict[str, Optional[float]]:
        """The baseline state a last-known-good snapshot retains (so
        `rewind` restores the sentinel along with the model)."""
        return {"best_loss": self.best_loss, "best_cerr": self.best_cerr}

    def rewind(self, snap: Dict[str, Optional[float]]) -> None:
        self.best_loss = snap["best_loss"]
        self.best_cerr = snap["best_cerr"]

    def observe(
        self, loss: float, consensus_err: Optional[float] = None,
    ) -> Optional[str]:
        """Judge one block; returns a trip reason string or None. A
        healthy block advances the baselines; a tripped block does not
        (the divergent values must never become the yardstick)."""
        cfg = self.cfg
        loss = float(loss)
        if not math.isfinite(loss):
            self.trips += 1
            return f"non-finite block loss ({loss})"
        if (
            self.best_loss is not None
            and loss > cfg.loss_spike * self.best_loss
            and loss > cfg.loss_floor
        ):
            self.trips += 1
            return (
                f"loss spike: {loss:.4g} > {cfg.loss_spike:g} x best "
                f"{self.best_loss:.4g}"
            )
        if consensus_err is not None:
            cerr = float(consensus_err)
            if not math.isfinite(cerr):
                self.trips += 1
                return f"non-finite consensus error ({cerr})"
            if (
                self.best_cerr is not None
                and cerr > cfg.consensus_spike * max(self.best_cerr, 1e-12)
                and cerr > cfg.consensus_floor
            ):
                self.trips += 1
                return (
                    f"consensus-error escalation: {cerr:.4g} > "
                    f"{cfg.consensus_spike:g} x best {self.best_cerr:.4g}"
                )
            if self.best_cerr is None or cerr < self.best_cerr:
                self.best_cerr = cerr
        if self.best_loss is None or loss < self.best_loss:
            self.best_loss = loss
        return None
