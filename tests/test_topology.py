import jax.numpy as jnp
import numpy as np
import pytest

from eventgrad_tpu.parallel.topology import Ring, Torus


def test_ring_neighbors():
    topo = Ring(4)
    assert topo.n_ranks == 4
    offsets = [(nb.axis, nb.offset) for nb in topo.neighbors]
    assert offsets == [("ring", -1), ("ring", 1)]
    assert topo.mix_weight == pytest.approx(1 / 3)


def test_torus_neighbors():
    topo = Torus(4, 2)
    assert topo.n_ranks == 8
    assert topo.n_neighbors == 4
    assert topo.mix_weight == pytest.approx(1 / 5)


def test_degenerate_axis_has_no_neighbors():
    topo = Ring(1)
    assert topo.n_neighbors == 0
    topo = Torus(4, 1)
    assert topo.n_neighbors == 2  # only the size-4 axis gossips


# --- Ring(2) degenerate: both shifts resolve to the SAME peer ----------
# (ISSUE 6 satellite: heal-to-2 / join-from-2 must not double-count that
# peer in mix_weighted.) Verified semantics: the reference ships TWO puts
# on a 2-ring and weighs 1/3 (topology.neighbors keeps both shifts), so
# the uniform mix intentionally sees the peer twice — that is reference
# parity, mean-preserving, and what a fresh Ring(2) run does. What must
# NOT happen is a HALF-counted peer under gating: both directed edges
# share one source, so their health/delivery state agrees and
# mix_weighted's renormalization either keeps the peer (weight over the
# alive edge count) or drops it entirely — pinned below.


def test_ring2_both_shifts_same_peer():
    topo = Ring(2)
    assert topo.n_neighbors == 2  # two puts, like the reference
    assert topo.mix_weight == pytest.approx(1 / 3)
    srcs = [
        [topo.neighbor_source(r, nb) for nb in topo.neighbors]
        for r in range(2)
    ]
    assert srcs == [[1, 1], [0, 0]]  # -1 and +1 are the same rank


def test_ring2_heal_is_exactly_ring2():
    """Heal-to-2 hands downstream collectives EXACTLY Ring(2): same
    neighbor specs, same (shared-peer) sources, same 1/3 weight — no
    special case for the degenerate size."""
    from eventgrad_tpu.chaos.policy import heal_ring

    healed, survivors = heal_ring(Ring(3), {1})
    ref = Ring(2)
    assert survivors == (0, 2)
    assert healed.n_ranks == 2 and healed.n_neighbors == 2
    assert healed.mix_weight == ref.mix_weight
    for r in range(2):
        for nb_h, nb_r in zip(healed.neighbors, ref.neighbors):
            assert healed.neighbor_source(r, nb_h) == ref.neighbor_source(
                r, nb_r
            )


def test_ring2_mix_counts_peer_per_reference_two_puts():
    """Uniform mix on Ring(2): (p + q + q) / 3 — the reference's two-put
    semantics, mean-preserving (sum over ranks is conserved)."""
    from eventgrad_tpu.parallel import collectives
    from eventgrad_tpu.parallel.spmd import spmd

    topo = Ring(2)
    p = jnp.array([3.0, 9.0])

    def fn(pp):
        return collectives.mix(pp, collectives.neighbor_vals(pp, topo), topo)

    out = np.asarray(spmd(fn, topo)(p))
    np.testing.assert_allclose(out, [(3 + 9 + 9) / 3, (9 + 3 + 3) / 3])
    assert out.sum() == pytest.approx(12.0)  # mean-preserving


def test_ring2_mix_weighted_never_half_counts_the_peer():
    """Gated mixing on Ring(2): with BOTH edges alive the peer enters
    twice at weight 1/3 (bitwise the uniform mix — reference parity);
    with both edges dead it leaves entirely (weight renormalizes to 1).
    The one-edge-off state weighs the single delivered copy at 1/2 —
    the renormalization, not a half-counted peer (per-edge delivery is
    real on the wire: each put can be lost independently)."""
    from eventgrad_tpu.parallel import collectives
    from eventgrad_tpu.parallel.spmd import spmd

    topo = Ring(2)
    p = jnp.array([3.0, 9.0])

    def fn(pp, gate):
        bufs = collectives.neighbor_vals(pp, topo)
        return collectives.mix_weighted(pp, bufs, gate)

    both = np.asarray(spmd(lambda pp: fn(pp, jnp.array([True, True])), topo)(p))
    np.testing.assert_array_equal(
        both,
        np.asarray(spmd(
            lambda pp: collectives.mix(
                pp, collectives.neighbor_vals(pp, topo), topo
            ), topo,
        )(p)),
    )
    none = np.asarray(
        spmd(lambda pp: fn(pp, jnp.array([False, False])), topo)(p)
    )
    np.testing.assert_allclose(none, [3.0, 9.0])  # peer fully out
    one = np.asarray(
        spmd(lambda pp: fn(pp, jnp.array([False, True])), topo)(p)
    )
    np.testing.assert_allclose(one, [(3 + 9) / 2, (9 + 3) / 2])
