"""Checkpoint/resume — absent from the reference (no torch::save anywhere;
the consensus model is evaluated then dropped, event.cpp:517-586). Cheap win
on TPU: orbax snapshots of the full stacked TrainState (params, optimizer
moments, event thresholds/slopes/buffers, sparsifier replicas, PRNG keys),
so an interrupted decentralized run resumes with its exact gossip state.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


def save(path: str, state: Any) -> None:
    """Crash-safe snapshot: write to `<path>.tmp`, swap the old snapshot to
    `<path>.prev`, promote tmp, drop prev. A kill at any point leaves either
    `<path>` or `<path>.prev` complete — `latest()` finds whichever survived."""
    path = os.path.abspath(path)
    tmp, prev = path + ".tmp", path + ".prev"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp, state, force=True)
    if os.path.exists(path):
        # make room for the demotion; the current primary covers the gap
        if os.path.exists(prev):
            shutil.rmtree(prev)
        os.rename(path, prev)
    # primary may be absent (first save, or resumed-from-.prev); never touch
    # a surviving .prev until the new primary is in place
    os.rename(tmp, path)
    if os.path.exists(prev):
        shutil.rmtree(prev)


def latest(path: str) -> Optional[str]:
    """The newest complete snapshot for `path` (the primary, or the .prev
    left by a save interrupted mid-swap); None if neither exists."""
    path = os.path.abspath(path)
    for cand in (path, path + ".prev"):
        if os.path.exists(cand):
            return cand
    return None


def restore(path: str, template: Any) -> Any:
    """Restore into the structure of `template` (an abstract or concrete
    TrainState with the same shapes/dtypes)."""
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        target = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return ckptr.restore(path, item=target)
