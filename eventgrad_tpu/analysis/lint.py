"""AST-based project lint framework + the repo's rule set.

One framework replaces the grep lints that had accumulated in
tests/test_lint_spmd.py and tests/test_crashpoint.py: each rule walks
parsed ASTs (so docstrings/comments never false-positive) or — where
the invariant is genuinely textual, like the shard_map skip-pattern —
the raw source, and reports `Violation(rule, path, line, message)`
records.  Tier-1 tests assert `run()` is empty; `tools/audit.py` runs
the same rules and pins `lint_violations: 0` in the audit artifact;
`python -m eventgrad_tpu.analysis.lint` is the CLI.

The rules (docs/ANALYSIS.md has the rationale for each):

  * exit-code-literals — the process exit codes are a cross-process
    contract owned by `eventgrad_tpu/exitcodes.py`; a literal 75/77/83
    anywhere else in the package is a re-declaration waiting to drift.
  * os-exit-confined — `os._exit` is the crashpoint engine's honest
    SIGKILL model and belongs to `chaos/crashpoint.py` (one named
    exemption: train/loop.py's fault_inject `crash:N`, which predates
    the registry and exits 13 by a separate contract).
  * no-host-sync-in-traced — `block_until_ready`/`device_get` in
    `parallel/`, `ops/`, or `train/steps.py` is a host round-trip on a
    traced path; the dispatch pipeline exists to delete exactly those.
  * shard-map-marker / shard-map-respell / shard-map-exempt-honest —
    the tests/_spmd.py skip-pattern rules (formerly
    tests/test_lint_spmd.py, messages preserved verbatim).
  * crashpoint-instrumented — every registered crash site appears at
    EXACTLY one literal `crashpoint.hit("<name>")` call (formerly a
    grep in tests/test_crashpoint.py, messages preserved).
  * wall-clock-confined — `time.time`/`time.perf_counter`/
    `time.monotonic` in the package belong to `obs/` (spans are the one
    timing API; tools/ and bench.py are host-side tooling outside this
    lint's scope).  Pre-existing metric sites are EXEMPT by name with
    the reason on record, honesty-checked like os-exit-confined.
  * pallas-kernel-registered — every `pl.pallas_call` site in the
    package must reference a kernel with a declared rank-dim signature
    in analysis/kernels.py (the trace auditor refuses unregistered
    kernels; this rule catches the drift at the SOURCE before a trace
    ever runs), and every registry entry must still name a live call
    site in its declared module (stale entries flag).
  * carrier-dtype-declared — the resident dtype of the EventState
    receive buffers is declared ONCE, by the arena carrier-layout
    helper (`parallel/arena.py alloc_event_bufs`, which allocates the
    carrier arenas and their dequant scales together); an ad-hoc
    `.astype(...)` inside a `bufs=`/`buf_scales=` allocation or commit
    site would silently fork the checkpoint layout the carrier-resident
    restore guard keys on.  Honesty runs the other way too: the
    EventState owner must still route its arena allocation through the
    helper, or the rule covers nothing.
  * telemetry-counter-ledgered — message-lifecycle disposition
    counters move ONLY through `obs/ledger.py ledger_update`; outside
    `obs/` a `ledger=` keyword must be a pass-through and the ledger's
    counter arrays must never be `.at[...]`-mutated, else a path can
    double-count or skip a fate — exactly the leaks the conservation
    auditor (tools/ledger_audit.py) exists to catch.  Honesty-checked:
    the helper must still perform the scatter-adds itself.
  * trigger-policy-registered — every trigger-policy name referenced
    as a string (train's `trigger_policy=`, the CLI's
    `--trigger-policy` choices, bench's `EG_BENCH_POLICY` default,
    `AuditConfig(policy=...)`) must resolve to a
    `parallel/policy.py` POLICIES entry, and every registry entry
    must appear in the CLI flag's choices (stale/unreachable flag
    both directions; bench.py is loaded by the rule itself since it
    sits outside collect_sources' subdirs).

Adding a rule: subclass `Rule`, implement `check(files)`, append to
`RULES`.  Scope rules by `rel` prefix; prefer AST matching; when a
file must be exempt, name it AND assert the exemption is still honest
(a stale exemption silently covers nothing).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from functools import cached_property
from typing import Dict, Iterable, List, Optional, Sequence

from eventgrad_tpu import exitcodes

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str  #: repo-relative
    line: int
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class SourceFile:
    path: str
    rel: str
    text: str

    @cached_property
    def tree(self) -> ast.AST:
        return ast.parse(self.text, filename=self.rel)


def collect_sources(
    root: str = REPO_ROOT, subdirs: Sequence[str] = ("eventgrad_tpu", "tests")
) -> List[SourceFile]:
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                with open(path) as f:
                    out.append(SourceFile(
                        path=path,
                        rel=os.path.relpath(path, root),
                        text=f.read(),
                    ))
    return out


class Rule:
    name: str = "rule"
    description: str = ""

    def check(self, files: Sequence[SourceFile]) -> List[Violation]:
        raise NotImplementedError

    def _v(self, sf: SourceFile, line: int, message: str) -> Violation:
        return Violation(self.name, sf.rel, line, message)


def _in_package(sf: SourceFile) -> bool:
    return sf.rel.startswith("eventgrad_tpu" + os.sep)


def _is_test(sf: SourceFile) -> bool:
    return (
        sf.rel.startswith("tests" + os.sep)
        and os.path.basename(sf.rel).startswith("test_")
    )


# --- package rules ----------------------------------------------------------


class ExitCodeLiterals(Rule):
    """The exit codes are a contract; the package spells them
    `exitcodes.<NAME>`, never by value."""

    name = "exit-code-literals"
    #: the contract values, read FROM the contract module (this file
    #: itself must pass its own rule)
    CODES = frozenset(exitcodes.EXIT_CODE_NAMES)
    ALLOWED = "eventgrad_tpu" + os.sep + "exitcodes.py"

    def check(self, files):
        out = []
        for sf in files:
            if not _in_package(sf) or sf.rel == self.ALLOWED:
                continue
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.Constant)
                    and type(node.value) is int
                    and node.value in self.CODES
                ):
                    out.append(self._v(
                        sf, node.lineno,
                        f"exit-code literal {node.value} outside "
                        "exitcodes.py — import eventgrad_tpu.exitcodes "
                        f"({exitcodes.EXIT_CODE_NAMES[node.value]}) "
                        "instead of re-declaring the contract by value",
                    ))
        return out


class OsExitConfined(Rule):
    """`os._exit` belongs to the crashpoint engine."""

    name = "os-exit-confined"
    OWNER = os.path.join("eventgrad_tpu", "chaos", "crashpoint.py")
    #: named exemptions with the reason on record; each exempt file must
    #: still contain EXACTLY one os._exit or the exemption has gone stale
    EXEMPT = {
        os.path.join("eventgrad_tpu", "train", "loop.py"):
            "fault_inject crash:N — the seeded hard-kill predates the "
            "crashpoint registry and exits 13 by its own contract",
    }

    @staticmethod
    def _os_exit_calls(sf: SourceFile):
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_exit"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "os"
            ):
                yield node

    def check(self, files):
        out = []
        for sf in files:
            if not _in_package(sf) or sf.rel == self.OWNER:
                continue
            calls = list(self._os_exit_calls(sf))
            if sf.rel in self.EXEMPT:
                if len(calls) != 1:
                    out.append(self._v(
                        sf, calls[1].lineno if len(calls) > 1 else 1,
                        f"exempt file has {len(calls)} os._exit calls "
                        "(the exemption covers exactly one: "
                        f"{self.EXEMPT[sf.rel]})",
                    ))
                continue
            for call in calls:
                out.append(self._v(
                    sf, call.lineno,
                    "os._exit outside chaos/crashpoint.py — the hard-"
                    "kill model belongs to the crashpoint engine "
                    "(raise, or register a crash site)",
                ))
        return out


class NoHostSyncInTraced(Rule):
    """No host round-trips on the traced-step paths."""

    name = "no-host-sync-in-traced"
    SCOPES = (
        os.path.join("eventgrad_tpu", "parallel") + os.sep,
        os.path.join("eventgrad_tpu", "ops") + os.sep,
        os.path.join("eventgrad_tpu", "train", "steps.py"),
    )
    BANNED_ATTRS = ("block_until_ready", "device_get")

    def check(self, files):
        out = []
        for sf in files:
            if not any(
                sf.rel.startswith(s) or sf.rel == s for s in self.SCOPES
            ):
                continue
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in self.BANNED_ATTRS
                ):
                    out.append(self._v(
                        sf, node.lineno,
                        f"{node.attr} on a traced path — a host sync "
                        "the dispatch pipeline cannot hide; read back "
                        "at the loop boundary instead",
                    ))
        return out


class CrashpointInstrumented(Rule):
    """Every registered crash site is instrumented at exactly one
    literal `crashpoint.hit("<name>")` call (messages preserved from
    tests/test_crashpoint.py's grep lint)."""

    name = "crashpoint-instrumented"
    OWNER = os.path.join("eventgrad_tpu", "chaos", "crashpoint.py")

    def check(self, files):
        from eventgrad_tpu.chaos import crashpoint

        out = []
        used: Dict[str, List[str]] = {}
        for sf in files:
            if not _in_package(sf) or sf.rel == self.OWNER:
                continue
            for node in ast.walk(sf.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "hit"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "crashpoint"
                ):
                    continue
                arg = node.args[0] if node.args else None
                if not (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                ):
                    out.append(self._v(
                        sf, node.lineno,
                        "crashpoint.hit() must take a string literal "
                        "(the instrumentation lint counts literal sites)",
                    ))
                    continue
                used.setdefault(arg.value, []).append(sf.rel)
        unregistered = set(used) - set(crashpoint.SITES)
        dead = set(crashpoint.SITES) - set(used)
        dupes = {n: fs for n, fs in used.items() if len(fs) > 1}
        if unregistered:
            out.append(Violation(
                self.name, "eventgrad_tpu", 1,
                f"unregistered crashpoint names instrumented: "
                f"{sorted(unregistered)}",
            ))
        if dead:
            out.append(Violation(
                self.name, "eventgrad_tpu", 1,
                f"registered crashpoints with NO instrumented site: "
                f"{sorted(dead)}",
            ))
        if dupes:
            out.append(Violation(
                self.name, "eventgrad_tpu", 1,
                f"crashpoints instrumented at more than one site: {dupes}",
            ))
        return out


class WallClockConfined(Rule):
    """Wall-clock timing belongs to the observability layer: `obs/`
    owns durations (Registry spans) and `tools/`/bench.py the host-side
    tooling (outside this lint's package scope).  A stray
    `time.perf_counter()` pair elsewhere in the package is a timing
    fragment the span trace cannot see — the pre-obs fragmentation this
    repo already consolidated once (PR 3).  Pre-existing metric sites
    are exempt BY NAME with the reason on record; each exemption is
    honesty-checked (the file must still contain a wall-clock call, or
    the exemption has gone stale)."""

    name = "wall-clock-confined"
    ALLOWED_PREFIX = os.path.join("eventgrad_tpu", "obs") + os.sep
    #: banned attribute reads on the `time` module (calls AND aliases)
    BANNED = frozenset({
        "time", "perf_counter", "perf_counter_ns", "monotonic",
        "monotonic_ns",
    })
    EXEMPT = {
        os.path.join("eventgrad_tpu", "utils", "profiling.py"):
            "timed_steps — the pre-span latency helper whose output "
            "feeds Registry.observe_latency; migrating it is a rename, "
            "not a timing fragment",
        os.path.join("eventgrad_tpu", "utils", "metrics.py"):
            "JsonlLogger's per-record `ts` wall TIMESTAMP (not a "
            "duration measurement)",
        os.path.join("eventgrad_tpu", "supervise.py"):
            "restart-budget / backoff clocks of the process supervisor "
            "(injectable now= callables; no train-loop timing)",
        os.path.join("eventgrad_tpu", "train", "loop.py"):
            "block-boundary wall_s / preemption drain_s metrics — the "
            "numbers the spans WRAP (spans record them too; the record "
            "fields predate the registry)",
        os.path.join("eventgrad_tpu", "chaos", "membership.py"):
            "membership transition apply_s metric (same vintage as "
            "loop.py's wall_s)",
    }

    def _hits(self, sf: SourceFile):
        # every local name the time module is bound to — `import time`,
        # `import time as clock` — so aliasing cannot dodge the rule
        aliases = {"time"}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        aliases.add(a.asname or "time")
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in self.BANNED
                and isinstance(node.value, ast.Name)
                and node.value.id in aliases
            ):
                yield node.lineno
            elif (
                isinstance(node, ast.ImportFrom)
                and node.module == "time"
                and any(a.name in self.BANNED for a in node.names)
            ):
                yield node.lineno

    def check(self, files):
        out = []
        for sf in files:
            if not _in_package(sf) or sf.rel.startswith(self.ALLOWED_PREFIX):
                continue
            hits = list(self._hits(sf))
            if sf.rel in self.EXEMPT:
                if not hits:
                    out.append(self._v(
                        sf, 1,
                        "exempt file no longer reads the wall clock — "
                        "drop it from WallClockConfined.EXEMPT "
                        f"({self.EXEMPT[sf.rel]})",
                    ))
                continue
            for line in hits:
                out.append(self._v(
                    sf, line,
                    "wall-clock timing outside obs/ — spans are the one "
                    "timing API (obs.Registry.span); host-side tooling "
                    "belongs in tools/ or bench.py, not the package",
                ))
        return out


class PallasKernelRegistered(Rule):
    """Every `pallas_call` site in the package references a kernel with
    a declared rank-dim signature (analysis/kernels.py).  The trace
    auditor (analysis/rankflow.py) already refuses unregistered kernels
    at trace time; this rule catches the drift at the SOURCE — a new
    kernel fails lint the moment it is called, not the first time a
    config that reaches it is audited.  Honesty runs both ways: a
    registry entry whose declared module no longer calls the kernel has
    gone stale and flags too."""

    name = "pallas-kernel-registered"
    #: the one named exemption: the auditor's own seeded-oracle source
    #: DELIBERATELY calls an unregistered kernel to prove the check can
    #: fire.  Honesty-checked — the file must still contain at least one
    #: unregistered site, or the exemption has gone stale.
    EXEMPT = {
        "eventgrad_tpu/analysis/audit.py":
            "oracle_unregistered_kernel's seeded `_leak_kernel` — the "
            "violation that proves the auditor's registry check fires",
    }

    @staticmethod
    def _kernel_names(node) -> Optional[List[str]]:
        """Kernel-function candidates of a pallas_call's first arg:
        a bare name, `functools.partial(name, ...)`, or a conditional
        between those.  None = statically unresolvable."""
        if isinstance(node, ast.Name):
            return [node.id]
        if isinstance(node, ast.Attribute):
            return [node.attr]
        if isinstance(node, ast.Call):
            fn = node.func
            is_partial = (
                isinstance(fn, ast.Name) and fn.id == "partial"
            ) or (
                isinstance(fn, ast.Attribute) and fn.attr == "partial"
            )
            if is_partial and node.args:
                return PallasKernelRegistered._kernel_names(node.args[0])
            return None
        if isinstance(node, ast.IfExp):
            body = PallasKernelRegistered._kernel_names(node.body)
            orelse = PallasKernelRegistered._kernel_names(node.orelse)
            if body is None or orelse is None:
                return None
            return body + orelse
        return None

    def check(self, files):
        from eventgrad_tpu.analysis import kernels

        out = []
        #: registry-module rel path -> kernel names referenced there
        referenced: Dict[str, List[str]] = {}
        for sf in files:
            if not _in_package(sf):
                continue
            sf_viol: List[Violation] = []
            for node in ast.walk(sf.tree):
                if not (
                    isinstance(node, ast.Call)
                    and (
                        (
                            isinstance(node.func, ast.Attribute)
                            and node.func.attr == "pallas_call"
                        )
                        or (
                            isinstance(node.func, ast.Name)
                            and node.func.id == "pallas_call"
                        )
                    )
                ):
                    continue
                names = (
                    self._kernel_names(node.args[0]) if node.args else None
                )
                if names is None:
                    sf_viol.append(self._v(
                        sf, node.lineno,
                        "pallas_call kernel argument is not statically "
                        "resolvable — pass the kernel function directly "
                        "(or via functools.partial / a conditional of "
                        "named kernels) so the declared-kernel registry "
                        "lint can check it",
                    ))
                    continue
                rel_posix = sf.rel.replace(os.sep, "/")
                for nm in names:
                    sig = kernels.REGISTRY.get(nm)
                    if sig is None:
                        sf_viol.append(self._v(
                            sf, node.lineno,
                            f"pallas_call kernel '{nm}' has no declared "
                            "rank-dim signature — register it in "
                            "analysis/kernels.py (the trace auditor "
                            "refuses unregistered kernels; see "
                            "docs/ANALYSIS.md 'Registering a kernel')",
                        ))
                    elif sig.module != rel_posix:
                        sf_viol.append(self._v(
                            sf, node.lineno,
                            f"pallas_call kernel '{nm}' is registered "
                            f"for {sig.module}, called from {rel_posix} "
                            "— one signature per kernel site; register "
                            "this module's kernel under its own entry",
                        ))
                    else:
                        referenced.setdefault(rel_posix, []).append(nm)
            if sf.rel.replace(os.sep, "/") in self.EXEMPT:
                if not sf_viol:
                    out.append(self._v(
                        sf, 1,
                        "exempt file no longer calls an unregistered "
                        "pallas kernel — drop it from "
                        "PallasKernelRegistered.EXEMPT ("
                        f"{self.EXEMPT[sf.rel.replace(os.sep, '/')]})",
                    ))
                continue
            out.extend(sf_viol)
        # stale entries: a registry module present in the scanned set
        # must still call every kernel it declares
        scanned = {sf.rel.replace(os.sep, "/") for sf in files}
        for nm, sig in sorted(kernels.REGISTRY.items()):
            if sig.module in scanned and nm not in referenced.get(
                sig.module, []
            ):
                out.append(Violation(
                    self.name, sig.module, 1,
                    f"registered kernel '{nm}' has no pallas_call site "
                    f"left in {sig.module} — the registry entry has gone "
                    "stale; drop it from analysis/kernels.py",
                ))
        return out


# --- shard_map skip-pattern rules (tests/) ----------------------------------

#: files allowed to touch shard_map WITHOUT importing the shared
#: marker. The seed trio (test_collectives / test_ring_attention /
#: test_train_equivalence) lived here as the recorded pre-existing
#: tier-1 failures while the mesh lift was dark; since the shard_map
#: compat resolution (parallel/spmd.py) turned the whole surface on,
#: they import `requires_shard_map` like everyone else and the list is
#: EMPTY — any new entry is new un-skipped debt, which is exactly what
#: this lint exists to stop.
SEED_EXEMPT: frozenset = frozenset()

_IMPORT_RE = re.compile(
    r"^\s*from\s+_spmd\s+import\s+.*\brequires_shard_map\b", re.MULTILINE
)
#: a hand-rolled respelling: a skipif whose condition mentions shard_map
#: (tests/_spmd.py holds the one allowed instance)
_RESPELL_RE = re.compile(r"skipif\s*\([^)]*shard_map", re.DOTALL)

#: the lint runner test's own docstrings quote the patterns
_LINT_TEST = "test_lint_spmd.py"


class ShardMapMarkerImport(Rule):
    name = "shard-map-marker"

    def check(self, files):
        out = []
        for sf in files:
            name = os.path.basename(sf.rel)
            if not _is_test(sf) or name == _LINT_TEST:
                continue
            if (
                "shard_map" in sf.text
                and name not in SEED_EXEMPT
                and not _IMPORT_RE.search(sf.text)
            ):
                out.append(self._v(
                    sf, 1,
                    f"{name} touches shard_map without importing the "
                    "shared `requires_shard_map` marker from "
                    "tests/_spmd.py (ROADMAP Open item 1); add `from "
                    "_spmd import requires_shard_map` instead of "
                    "re-spelling the skipif",
                ))
        return out


class ShardMapRespell(Rule):
    name = "shard-map-respell"

    def check(self, files):
        out = []
        for sf in files:
            name = os.path.basename(sf.rel)
            if not _is_test(sf) or name == _LINT_TEST:
                continue
            if _RESPELL_RE.search(sf.text):
                out.append(self._v(
                    sf, 1,
                    f"{name} re-spells the shard_map skipif; use "
                    "`requires_shard_map` from tests/_spmd.py (single "
                    "definition, single reason string)",
                ))
        return out


class ShardMapExemptHonest(Rule):
    """The exemption list stays honest: every exempt file still exists
    and still touches shard_map."""

    name = "shard-map-exempt-honest"

    def check(self, files):
        out = []
        by_name = {os.path.basename(sf.rel): sf for sf in files if _is_test(sf)}
        for name in sorted(SEED_EXEMPT):
            sf = by_name.get(name)
            if sf is None:
                out.append(Violation(
                    self.name, os.path.join("tests", name), 1,
                    f"exempt file {name} no longer exists",
                ))
            elif "shard_map" not in sf.text:
                out.append(self._v(
                    sf, 1,
                    f"exempt file {name} no longer touches shard_map — "
                    "drop it from SEED_EXEMPT",
                ))
        return out


class TriggerPolicyRegistered(Rule):
    """Every trigger-policy name referenced by train/CLI/bench/audit
    resolves to a parallel/policy.py registry entry, and every registry
    entry is reachable from the CLI.

    Policy names travel as plain strings (`train(trigger_policy=...)`,
    `--trigger-policy` choices, the `EG_BENCH_POLICY` env default,
    AuditConfig(policy=...)); a typo'd or stale name fails only at
    runtime, deep inside a training run. This rule resolves every such
    string reference against `policy_lib.POLICIES` at the SOURCE, and —
    the stale direction — flags registry entries missing from the CLI's
    `--trigger-policy` choices (a policy the flag can't reach is dead
    surface). bench.py sits at the repo root, outside collect_sources'
    subdirs, so the rule loads it itself — the EG_BENCH_POLICY knob
    cannot drift unchecked."""

    name = "trigger-policy-registered"

    #: repo-root sources outside collect_sources' subdirs that
    #: reference policy names
    EXTRA_FILES = ("bench.py",)

    @staticmethod
    def _const_str(node) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    def _sites(self, sf):
        """(line, name, is_cli_choice) policy-name string references."""
        sites = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fn_name = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute)
                else None
            )
            for kw in node.keywords:
                if kw.arg == "trigger_policy" or (
                    kw.arg == "policy" and fn_name == "AuditConfig"
                ):
                    s = self._const_str(kw.value)
                    if s is not None:
                        sites.append((kw.value.lineno, s, False))
                elif kw.arg == "choices" and fn_name == "add_argument":
                    flag = (
                        self._const_str(node.args[0]) if node.args else None
                    )
                    if flag == "--trigger-policy" and isinstance(
                        kw.value, (ast.List, ast.Tuple)
                    ):
                        for el in kw.value.elts:
                            s = self._const_str(el)
                            if s is not None:
                                sites.append((el.lineno, s, True))
            # the EG_BENCH_POLICY env knob's default value
            if fn_name == "get" and len(node.args) >= 2:
                if self._const_str(node.args[0]) == "EG_BENCH_POLICY":
                    s = self._const_str(node.args[1])
                    if s:  # "" = inherit the algo default, fine
                        sites.append((node.args[1].lineno, s, False))
        return sites

    def check(self, files):
        from eventgrad_tpu.parallel import policy as policy_lib

        files = list(files)
        scanned = {sf.rel.replace(os.sep, "/") for sf in files}
        for extra in self.EXTRA_FILES:
            path = os.path.join(REPO_ROOT, extra)
            if extra not in scanned and os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    files.append(
                        SourceFile(path=path, rel=extra, text=f.read())
                    )
        out = []
        cli_rel = None
        cli_choices: Dict[str, int] = {}
        for sf in files:
            rel = sf.rel.replace(os.sep, "/")
            # package + bench only: test files seed bad names on purpose
            if not (_in_package(sf) or rel in self.EXTRA_FILES):
                continue
            for line, nm, is_choice in self._sites(sf):
                if is_choice:
                    cli_rel = sf.rel
                    cli_choices.setdefault(nm, line)
                if nm not in policy_lib.POLICIES:
                    out.append(self._v(
                        sf, line,
                        f"trigger policy '{nm}' is not a registry entry "
                        "— register it in parallel/policy.py POLICIES "
                        f"(known: {', '.join(sorted(policy_lib.POLICIES))})",
                    ))
        # stale direction: every registry entry must be reachable from
        # the CLI flag (checked only when the flag is in the file set)
        if cli_rel is not None:
            for reg in sorted(policy_lib.POLICIES):
                if reg not in cli_choices:
                    out.append(Violation(
                        self.name, cli_rel, 1,
                        f"registered trigger policy '{reg}' is missing "
                        "from --trigger-policy choices — a policy the "
                        "CLI can't name is dead surface; add it to the "
                        "flag (or drop the registry entry)",
                    ))
        return out


class CarrierDtypeDeclared(Rule):
    """The resident dtype of EventState's receive buffers is declared
    ONCE, by the arena carrier-layout helper (`parallel/arena.py
    alloc_event_bufs` — carrier arenas and their int8 dequant scales
    allocated together, so the layout can never half-change).  An
    ad-hoc `.astype(...)` inside a `bufs=`/`buf_scales=` keyword — an
    EventState construction, a `.replace(...)` commit — re-dtypes the
    buffers outside that declaration: the carrier-resident restore
    guard (train/loop.py) keys on the declared layout, so a forked
    dtype trains on silently-cast state until the next checkpoint
    round-trip.  The stale direction flags too: the EventState owner
    module must still route its arena allocation through the helper,
    or this rule covers nothing."""

    name = "carrier-dtype-declared"
    OWNER = os.path.join("eventgrad_tpu", "parallel", "events.py")
    HELPER = "alloc_event_bufs"
    BUF_KWARGS = frozenset({"bufs", "buf_scales"})

    def check(self, files):
        out = []
        owner_seen = False
        owner_routes = False
        for sf in files:
            if not _in_package(sf):
                continue
            if sf.rel == self.OWNER:
                owner_seen = True
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if sf.rel == self.OWNER and (
                    (isinstance(fn, ast.Name) and fn.id == self.HELPER)
                    or (
                        isinstance(fn, ast.Attribute)
                        and fn.attr == self.HELPER
                    )
                ):
                    owner_routes = True
                for kw in node.keywords:
                    if kw.arg not in self.BUF_KWARGS:
                        continue
                    for sub in ast.walk(kw.value):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "astype"
                        ):
                            out.append(self._v(
                                sf, sub.lineno,
                                f"ad-hoc astype inside an EventState "
                                f"{kw.arg}= site — the resident dtype "
                                "of the receive buffers is declared "
                                "once by parallel/arena.py "
                                "alloc_event_bufs (carrier layout + "
                                "dequant scales together); re-dtyping "
                                "at an allocation/commit site forks "
                                "the checkpoint layout the carrier-"
                                "resident restore guard keys on",
                            ))
        if owner_seen and not owner_routes:
            out.append(Violation(
                self.name, self.OWNER, 1,
                "EventState's owner no longer routes its arena buffer "
                "allocation through alloc_event_bufs — the carrier-"
                "layout helper is the one place the resident dtype (and "
                "its scales) is declared; allocate through it "
                "(parallel/arena.py), not ad hoc",
            ))
        return out


class TelemetryCounterLedgered(Rule):
    """Message-lifecycle disposition counters move ONLY through the
    ledger helper (`obs/ledger.py ledger_update`) — that single site is
    what makes the conservation laws auditable (tools/ledger_audit.py):
    a path that increments a disposition with its own `.at[...].add` or
    `+ 1` can double-count or skip a fate, exactly the leaks the
    auditor exists to catch.  Outside `eventgrad_tpu/obs/`, a
    `ledger=` keyword must be a pass-through (a bare name/attribute or
    None), never computed in place, and the ledger's `counts`/`queue`
    arrays must never be `.at[...]`-mutated.  The stale direction
    flags too: `obs/ledger.py` must still define `ledger_update` and
    perform the counter scatter-adds itself, or the rule covers
    nothing."""

    name = "telemetry-counter-ledgered"
    OWNER = os.path.join("eventgrad_tpu", "obs", "ledger.py")
    HELPER = "ledger_update"
    #: ledger= values that are NOT ad-hoc counter math: a pass-through
    #: reference, None (the known-added default), or a call to the
    #: helper / the ledger constructor
    ALLOWED_CALLS = frozenset({"ledger_update", "init", "replace"})

    @staticmethod
    def _chain(node) -> list:
        """Attribute chain names of `a.b.c` -> ['a', 'b', 'c'] (best
        effort; non-name bases contribute nothing)."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
        return parts[::-1]

    def _is_ledger_mutation(self, node) -> bool:
        """`<...>.ledger.counts.at[...]` / `<...>.ledger.queue.at[...]`
        — an in-place scatter on the ledger's counter arrays."""
        if not (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "at"
        ):
            return False
        chain = self._chain(node.value.value)
        return any("ledger" in p for p in chain) and (
            "counts" in chain or "queue" in chain or "late_queue" in chain
        )

    def check(self, files):
        out = []
        owner_seen = False
        owner_scatter = False
        for sf in files:
            if not _in_package(sf):
                continue
            in_obs = sf.rel.startswith(
                os.path.join("eventgrad_tpu", "obs") + os.sep
            )
            if sf.rel == self.OWNER:
                owner_seen = True
                for node in ast.walk(sf.tree):
                    if (
                        isinstance(node, ast.FunctionDef)
                        and node.name == self.HELPER
                    ):
                        for sub in ast.walk(node):
                            if (
                                isinstance(sub, ast.Attribute)
                                and sub.attr == "add"
                                and isinstance(sub.value, ast.Subscript)
                            ):
                                owner_scatter = True
            if in_obs:
                continue
            for node in ast.walk(sf.tree):
                if self._is_ledger_mutation(node):
                    out.append(self._v(
                        sf, node.lineno,
                        "ad-hoc mutation of the message ledger's "
                        "counter arrays — disposition counters move "
                        "only through obs.ledger.ledger_update (the "
                        "one site the conservation auditor can hold "
                        "to account; tools/ledger_audit.py)",
                    ))
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg != "ledger":
                        continue
                    v = kw.value
                    if isinstance(v, (ast.Name, ast.Attribute)):
                        continue  # pass-through
                    if isinstance(v, ast.Constant) and v.value is None:
                        continue  # known-added default
                    if (
                        isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)
                        and v.func.attr in self.ALLOWED_CALLS
                    ):
                        continue
                    out.append(self._v(
                        sf, v.lineno,
                        "computed ledger= value outside obs/ — "
                        "disposition accounting lives in "
                        "obs.ledger.ledger_update; pass the branch's "
                        "raw observables (ledger_inputs=) instead of "
                        "doing counter math at the call site",
                    ))
        if owner_seen and not owner_scatter:
            out.append(Violation(
                self.name, self.OWNER, 1,
                "obs/ledger.py no longer performs the disposition "
                "counter scatter-adds inside ledger_update — the "
                "helper is the ONE place message counters move; "
                "without it this rule covers nothing",
            ))
        return out


RULES: Sequence[Rule] = (
    ExitCodeLiterals(),
    OsExitConfined(),
    NoHostSyncInTraced(),
    CrashpointInstrumented(),
    WallClockConfined(),
    PallasKernelRegistered(),
    ShardMapMarkerImport(),
    ShardMapRespell(),
    ShardMapExemptHonest(),
    TriggerPolicyRegistered(),
    CarrierDtypeDeclared(),
    TelemetryCounterLedgered(),
)


def run(
    rules: Optional[Iterable[Rule]] = None,
    root: str = REPO_ROOT,
    files: Optional[Sequence[SourceFile]] = None,
) -> List[Violation]:
    """Run every rule over the repo (or an injected file set — the
    oracle tests feed seeded-violation sources through here)."""
    if files is None:
        files = collect_sources(root)
    out: List[Violation] = []
    for rule in rules if rules is not None else RULES:
        out.extend(rule.check(files))
    return out


def main(argv=None) -> int:
    violations = run()
    for v in violations:
        print(str(v), file=sys.stderr)
    print(f"lint: {len(RULES)} rules, {len(violations)} violations")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
