"""Worker process for tests/test_multihost.py (not a test module).

Each of N processes owns 4 virtual CPU devices; together they form one
global 8-device mesh. Trains MLP/EventGraD on an 8-ring (gossip hops cross
the process boundary) and a ring-attention transformer on an sp:2,dp:4
hybrid (sp outer, so every sequence hop crosses the process boundary)
through the train() path, then compares the allgathered final parameters
against an in-process single-device vmap simulation of the identical runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
ckpt_dir = sys.argv[4]  # shared checkpoint dir: the resume leg is mandatory
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from eventgrad_tpu.parallel import multihost  # noqa: E402

multihost.init(f"localhost:{port}", nprocs, pid)
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

import numpy as np  # noqa: E402

from eventgrad_tpu.data.datasets import synthetic_dataset  # noqa: E402
from eventgrad_tpu.models import MLP  # noqa: E402
from eventgrad_tpu.parallel.events import EventConfig  # noqa: E402
from eventgrad_tpu.parallel.spmd import build_mesh  # noqa: E402
from eventgrad_tpu.parallel.topology import Ring  # noqa: E402
from eventgrad_tpu.train.loop import train  # noqa: E402

topo = Ring(8)
x, y = synthetic_dataset(512, (28, 28, 1), seed=11)
kwargs = dict(
    algo="eventgrad", epochs=2, batch_size=8, learning_rate=0.05,
    event_cfg=EventConfig(adaptive=True, horizon=0.9, warmup_passes=3),
    random_sampler=True, seed=3, log_every_epoch=False,
)

# global-mesh run: ranks 0-3 on this process, 4-7 on the peer; the primary
# snapshots the allgathered gossip state every epoch (multi-host checkpoint)
state_mesh, hist_mesh = train(
    MLP(), topo, x, y, mesh=build_mesh(topo),
    checkpoint_dir=ckpt_dir, save_every=1, **kwargs
)

# reference: same run simulated on one device (no mesh), one epoch further
# (the resume leg below continues the mesh run to epoch 3)
kwargs_sim = dict(kwargs, epochs=3)
state_sim, hist_sim = train(MLP(), topo, x, y, mesh=None, **kwargs_sim)
params_sim = jax.tree.map(np.asarray, state_sim.params)

for hm, hs in zip(hist_mesh, hist_sim):
    assert hm["num_events"] == hs["num_events"], (hm, hs)
    np.testing.assert_allclose(hm["loss"], hs["loss"], atol=1e-5)
    # train_acc divides by the true step count: catches to_host duplication
    np.testing.assert_allclose(hm["train_acc"], hs["train_acc"], atol=1e-6)
    assert hm["steps"] == hs["steps"]

# resume the interrupted mesh run from the epoch-2 snapshot: every process
# restores the primary's snapshot (shared filesystem), places it back on
# the global mesh, and runs epoch 3 — bit-for-bit the same trajectory as
# the uninterrupted single-process simulation
state_res, hist_res = train(
    MLP(), topo, x, y, mesh=build_mesh(topo),
    checkpoint_dir=ckpt_dir, resume=True, **kwargs_sim
)
assert [h["epoch"] for h in hist_res] == [3], hist_res
assert hist_res[0]["num_events"] == hist_sim[2]["num_events"]
np.testing.assert_allclose(hist_res[0]["loss"], hist_sim[2]["loss"], atol=1e-5)
params_res = multihost.to_host(state_res.params)
for a, b in zip(jax.tree.leaves(params_res), jax.tree.leaves(params_sim)):
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)

# hybrid leg: EventGraD gossip across dp while ring attention shards the
# sequence across sp. sp is the OUTER mesh axis: build_mesh reshapes the 8
# global devices row-major, so sp partners pair device i (process 0) with
# device i+4 (process 1) — every ring-attention sequence hop crosses the
# process boundary (cross-process dp gossip is covered by the ring leg
# above). Must match the in-process vmap simulation exactly.
from eventgrad_tpu.data.datasets import synthetic_lm_dataset  # noqa: E402
from eventgrad_tpu.models.transformer import TransformerLM  # noqa: E402
from eventgrad_tpu.parallel.topology import Topology  # noqa: E402

topo_h = Topology(axes=("sp", "dp"), shape=(2, 4), gossip_axes=("dp",))
xl, yl = synthetic_lm_dataset(64, 32, vocab=64, seed=13)


def lm_model():
    return TransformerLM(vocab=64, dim=32, n_heads=4, n_layers=1,
                         max_len=32, attn="ring", topo=topo_h, sp_axis="sp")


kwargs_h = dict(
    algo="eventgrad", epochs=2, batch_size=4, learning_rate=0.1,
    event_cfg=EventConfig(adaptive=True, horizon=0.9, warmup_passes=2),
    seed=9, log_every_epoch=False,
)
state_hm, hist_hm = train(lm_model(), topo_h, xl, yl,
                          mesh=build_mesh(topo_h), **kwargs_h)
state_hs, hist_hs = train(lm_model(), topo_h, xl, yl, mesh=None, **kwargs_h)
for hm, hs in zip(hist_hm, hist_hs):
    assert hm["num_events"] == hs["num_events"], (hm, hs)
    np.testing.assert_allclose(hm["loss"], hs["loss"], atol=1e-5)
params_hm = multihost.to_host(state_hm.params)
for a, b in zip(
    jax.tree.leaves(params_hm),
    jax.tree.leaves(jax.tree.map(np.asarray, state_hs.params)),
):
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)

print(f"MH-WORKER-{pid}-OK", flush=True)
