"""Test harness: emulate an 8-device mesh on CPU.

The environment pins JAX_PLATFORMS=axon (the real TPU tunnel) and pre-imports
jax via PYTHONPATH sitecustomize, so plain env vars are not enough; we must
also flip the config before any backend initializes. XLA_FLAGS still has to
be set before the CPU client spins up.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import pytest

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, f"expected 8 CPU devices, got {jax.devices()}"

#: suites that dominate the wall clock, measured per-file on one core
#: (pytest --durations=0 aggregate, round 3): launcher end-to-end trainings
#: (test_cli 217 s), the parallelism-family integration parities
#: (moe/tp/pp/sp/hierarchical 35-69 s each), wire codecs (34 s),
#: multi-epoch convergence runs, Pallas-interpret flash sweeps,
#: multi-process meshes, and supervisor drills. The default
#: `pytest -m "not slow"` core tier — the event state machine, oracle
#: cross-checks, algorithm equivalences, collectives, models, resume,
#: trace — runs in ~4.5 min on one CPU core (VERDICT r2 weak #6); the
#: full suite is the nightly tier. Both commands + runtimes: README.md.
SLOW_MODULES = {
    "test_cli",
    "test_convergence",
    "test_flash_attention",
    "test_flash_ring",
    "test_hierarchical_dp",
    "test_lm",
    "test_moe",
    "test_multihost",
    "test_pipeline_parallel",
    "test_supervise",
    "test_tensor_parallel",
    "test_transformer_sp",
    "test_wire_bf16",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        # an explicit @pytest.mark.tier1 promotes a single test out of
        # its module's blanket slow marker (e.g. test_cli.py's
        # vmap-vs-shard_map backend parity — a fast tier-1 gate living
        # in an otherwise wall-clock-heavy launcher suite)
        if item.get_closest_marker("tier1") is not None:
            continue
        mod = getattr(item, "module", None)
        if mod is not None and mod.__name__ in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
