"""Straggler ablation: does shedding the lockstep actually buy anything?

The robustness claim of the bounded-async gossip engine (ISSUE 15 /
ROADMAP open item 5): under a persistent straggler — one rank whose
sends arrive `f` passes late (`slow=R@f`, chaos/schedule.py) — a
lockstep ring (staleness D <= 1) throttles every rank to the
straggler's delivery rate, while bounded-async EventGraD (D >= 2) keeps
stepping at compute speed, mixing the straggler's values up to D
passes late, at a bounded accuracy cost. This tool EXERCISES that
claim instead of asserting it, one leg per bound D:

  * ACCURACY — measured: a real train() run with the straggler
    schedule injected (the D >= 2 legs genuinely mix stale values on
    the straggler's edges; the D <= 1 legs clamp the lag away and
    train synchronously — the lockstep semantics), evaluated on a
    held-out set. The artifact gates the D >= 2 accuracy within 0.5 pt
    of the lockstep's.
  * STEP TIME — modeled, deterministically, from the same schedule:
    a dependency recurrence over (pass, rank) in compute-time units
    (`modeled_timeline`). Rank r's pass t cannot start before the
    messages its bound requires have arrived: with delivery lag f and
    bound D, the arrival it waits for is the pass t-min(f,D) send,
    physically available f passes of wall time after it left — so
    f <= D never stalls and f > D throttles the ring to ~f/D of
    compute speed (D=0 commits the same pass: the classic
    one-straggler-stalls-everyone barrier). The model's inputs (the
    lag table) are the exact values the traced step consumes
    (chaos.inject.lag_table == lag_vector, clamped).
  * WALL CLOCK — measured (`--measured`): the SPMD step fuses every
    rank into one device program, so in-process nothing ever waits on
    a slow peer — the modeled leg alone could hide a wrong dependency
    structure. `measured_timeline` executes that structure for real:
    one host thread per rank runs `n_passes` passes of genuine
    busy-wait compute (per-pass seconds CALIBRATED from a real run of
    the composed config — bounded-async x bucketed x compact-int8 x
    carrier-resident — by differencing two train() timings so jit
    compile cancels out), publishing each pass's send with a
    timestamp at the host dispatch seam. The straggler's sends ride a
    busy-waited delivery delay of `lag` passes, and a receiver at
    bound D blocks on the pass t-min(lag,D) send — exactly
    modeled_timeline's recurrence, but in wall seconds on a real
    clock. The artifact gates `measured_ratio` (lockstep wall /
    bounded wall) > 1 and direction agreement with the modeled leg.
    In --measured mode the accuracy legs ALSO train the composed
    config, so the wall-clock claim attaches to the configuration the
    overlap stack actually ships.
  * REPLAY — every bounded leg runs twice from its seed; final params
    must match bitwise (the whole story, faults included, replays).

Writes the schema-gated artifact (tools/validate_artifacts.py
STRAGGLER_ABLATION_SCHEMA): `bounded_async_beats_lockstep` must be
true, `acc_gap_pt` <= 0.5, `replay_bitwise` true — a regression cannot
commit silently.

Usage:
  python tools/straggler_ablation.py [--out artifacts/...json] [--fast]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time
from typing import Any, Dict, List

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STRAGGLER_SCHEMA_VERSION = 1


def modeled_timeline(
    topo, lags_raw: np.ndarray, bound: int, compute: float = 1.0,
) -> Dict[str, Any]:
    """Deterministic wall-clock model of a ring under per-edge delivery
    lag (see module doc). `lags_raw` is the UNCLAMPED schedule
    (chaos.inject.lag_table(bound=None)) — the network's behavior; the
    bound decides how much of it the receiver must wait out.

    Recurrence, in compute-time units (one pass of local work = 1),
    keyed by the SEND pass u — the engine's semantics (lag_vector is
    evaluated at enqueue time), so windowed `lag=` schedules model
    correctly, not just constant `slow=` ones:
      arrive(u, e) = S[u, s] + f(u)*compute       (payload leaves at
                                                   end of sender's pass,
                                                   f(u)-1 extra in flight)
      D >= 1: pass t waits for every send u whose CLAMPED commit pass
              u + min(f(u), D) equals t:
              S[t, r] = max(F[t-1, r], arrive(u, e) ...)
      D == 0: the same-pass commit: F[t, r] = max(S[t, r] + compute,
              arrive(t, e) ...)
    Returns steady-state per-pass step time (post-warmup slope of the
    makespan) and the stall count (rank-passes that waited on an
    arrival, in either regime)."""
    n_passes = lags_raw.shape[0]
    n = topo.n_ranks
    srcs = [
        [topo.neighbor_source(r, nb) for nb in topo.neighbors]
        for r in range(n)
    ]
    S = np.zeros((n_passes + 1, n))
    F = np.zeros((n_passes + 1, n))
    stalls = 0
    for t in range(1, n_passes + 1):
        for r in range(n):
            start = F[t - 1, r]
            if bound >= 1:
                for e, s in enumerate(srcs[r]):
                    for u in range(max(1, t - bound), t):
                        f = int(lags_raw[u - 1, r, e])
                        if u + min(f, bound) == t:
                            start = max(start, S[u, s] + f * compute)
            if start > F[t - 1, r] + 1e-12:
                stalls += 1
            S[t, r] = start
        for r in range(n):
            fin = S[t, r] + compute
            if bound == 0:
                for e, s in enumerate(srcs[r]):
                    f = int(lags_raw[t - 1, r, e])
                    fin = max(fin, S[t, s] + f * compute)
                if fin > S[t, r] + compute + 1e-12:
                    stalls += 1
            F[t, r] = fin
    warm = max(1, n_passes // 4)
    span = F[n_passes].max() - F[warm].max()
    step_time = span / max(1, n_passes - warm)
    return {
        "modeled_step_time": round(float(step_time), 4),
        "modeled_steps_per_unit": round(1.0 / float(step_time), 4),
        "stall_passes": int(stalls),
        "makespan": round(float(F[n_passes].max()), 2),
    }


def measured_timeline(
    topo, bound: int, n_passes: int, compute: float,
    straggler_rank: int, straggler_lag: int,
) -> float:
    """REAL wall-clock of the ring's dependency structure under a
    throttled rank. One host thread per rank; each pass is `compute`
    seconds of busy-wait (spinning on the wall clock, so GIL
    contention cannot stretch it — the deadline is absolute), and the
    send publishes at the end of the pass with its start timestamp.
    Delivery latency is the throttle: a send from the straggler may
    not be consumed before `sender_start + lag*compute` — the
    receiver busy-waits it out at its dispatch seam. Bound D decides
    WHICH send pass t blocks on (the pass t - min(lag, D) send, the
    engine's clamped commit pass): lockstep (D <= 1) waits the
    latency out every pass, D >= 2 hides up to D passes of it behind
    the delivery runway. Same recurrence as modeled_timeline, on a
    real clock. Returns elapsed seconds for the whole ring."""
    import threading

    n = topo.n_ranks
    srcs = [
        [topo.neighbor_source(r, nb) for nb in topo.neighbors]
        for r in range(n)
    ]
    done = [
        [threading.Event() for _ in range(n_passes + 1)] for _ in range(n)
    ]
    start_ts = [[0.0] * (n_passes + 1) for _ in range(n)]

    # shrink the GIL switch interval for the measurement: n spinning
    # threads hand the lock around every interval, and the default 5 ms
    # granularity would swamp a ~10 ms compute quantum
    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)

    t0 = time.perf_counter()
    for r in range(n):
        start_ts[r][0] = t0
        done[r][0].set()

    def _spin_until(deadline):
        while time.perf_counter() < deadline:
            pass

    def _rank(r):
        lags = [
            straggler_lag if s == straggler_rank else 1 for s in srcs[r]
        ]

        def _await(s, u, f):
            done[s][u].wait()
            _spin_until(start_ts[s][u] + f * compute)

        for t in range(1, n_passes + 1):
            if bound >= 1:
                for e, s in enumerate(srcs[r]):
                    u = t - min(lags[e], bound)
                    if u >= 1:
                        _await(s, u, lags[e])
            ts = time.perf_counter()
            start_ts[r][t] = ts
            _spin_until(ts + compute)
            done[r][t].set()
            if bound == 0:
                # same-pass commit: the barrier closes before the next
                # pass may start
                for e, s in enumerate(srcs[r]):
                    _await(s, t, lags[e])

    threads = [
        threading.Thread(target=_rank, args=(r,)) for r in range(n)
    ]
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    finally:
        sys.setswitchinterval(old_switch)
    return time.perf_counter() - t0


def _calibrate_compute(model_fn, topo, x, y, sched, bound, batch_size,
                       event_cfg, seed, composed, steps_per_epoch,
                       lo_epochs=1, hi_epochs=3):
    """Per-pass seconds of the REAL composed config, by differencing
    two train() timings (hi_epochs vs lo_epochs): jit compile and
    fixed setup cancel, leaving pure steady-state step time."""
    walls = []
    for ep in (lo_epochs, hi_epochs):
        t0 = time.perf_counter()
        _run_leg(model_fn, topo, x, y, None, None, sched, bound,
                 ep, batch_size, event_cfg, seed, composed=composed)
        walls.append(time.perf_counter() - t0)
    d_passes = (hi_epochs - lo_epochs) * steps_per_epoch
    return max(0.0, walls[1] - walls[0]) / max(1, d_passes)


def _run_leg(model_fn, topo, x, y, x_test, y_test, sched, bound,
             epochs, batch_size, event_cfg, seed, composed=None):
    from eventgrad_tpu.train.loop import train

    state, hist = train(
        model_fn(), topo, x, y, algo="eventgrad", epochs=epochs,
        batch_size=batch_size, learning_rate=0.05, event_cfg=event_cfg,
        seed=seed, chaos=sched, staleness=bound,
        x_test=x_test, y_test=y_test, log_every_epoch=True,
        **(composed or {}),
    )
    return state, hist


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "straggler_ablation_cpu.json",
    ))
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 smoke leg: tiny run, bounds (1, 2)")
    ap.add_argument("--measured", action="store_true",
                    help="run the composed config (bounded-async x "
                         "bucketed x compact-int8 x carrier-resident) "
                         "and add a REAL wall-clock leg: threaded "
                         "per-rank executor, busy-wait throttle on "
                         "the straggler's sends (measured_timeline)")
    ap.add_argument("--measured-passes", type=int, default=32,
                    help="passes per measured wall-clock leg")
    ap.add_argument("--ranks", type=int, default=8)
    # 45 epochs converges EVERY leg of the COMPOSED config (all four
    # bounds land within the 0.5 pt gate of 98%); at 30 the D=4
    # compact+int8 leg still sits ~1.4 pt below its plateau — shorter
    # runs compare mid-descent snapshots where staleness noise swamps
    # the claim
    ap.add_argument("--epochs", type=int, default=45)
    ap.add_argument("--n-synth", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--straggler-rank", type=int, default=2)
    ap.add_argument("--straggler-lag", type=int, default=6)
    ap.add_argument("--bounds", default="0,1,2,4")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    import jax  # noqa: F401  (import after argparse: --help stays fast)

    from eventgrad_tpu.chaos import inject as chaos_inject
    from eventgrad_tpu.chaos.schedule import ChaosSchedule
    from eventgrad_tpu.data.datasets import synthetic_dataset
    from eventgrad_tpu.models import MLP
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring

    if args.fast:
        args.ranks, args.epochs, args.n_synth = 4, 2, 256
        args.bounds = "1,2"
        args.straggler_lag = 4
        args.measured_passes = min(args.measured_passes, 10)
    bounds = [int(b) for b in args.bounds.split(",")]
    if not any(b >= 2 for b in bounds) or not any(b <= 1 for b in bounds):
        raise SystemExit("--bounds needs a lockstep (<=1) and a "
                         "bounded-async (>=2) leg to compare")

    topo = Ring(args.ranks)
    model_fn = lambda: MLP(hidden=16)
    in_shape = (8, 8, 1)
    x, y = synthetic_dataset(args.n_synth, in_shape, seed=3)
    x_test, y_test = synthetic_dataset(
        max(256, args.n_synth // 4), in_shape, seed=3, split="test",
    )
    event_cfg = EventConfig(adaptive=True, horizon=0.95, warmup_passes=5,
                            max_silence=20)
    sched = ChaosSchedule(
        seed=args.seed + 7,
        slow=((args.straggler_rank, args.straggler_lag),),
    )
    steps = (args.n_synth // args.ranks) // args.batch_size
    n_passes = max(8, args.epochs * steps)
    lags_raw = chaos_inject.lag_table(sched, topo, n_passes, bound=None)

    # --measured trains the composed overlap stack — the production
    # configuration the wall-clock claim is about (ISSUE 20)
    composed = None
    if args.measured:
        composed = dict(
            gossip_wire="compact", compact_frac=0.5, wire="int8",
            arena=True, bucketed=4, carrier_resident=True,
        )

    t0 = time.time()
    legs: List[Dict[str, Any]] = []
    for D in bounds:
        model = modeled_timeline(topo, lags_raw, D)
        state, hist = _run_leg(
            model_fn, topo, x, y, x_test, y_test, sched, D,
            args.epochs, args.batch_size, event_cfg, args.seed,
            composed=composed,
        )
        leg = {
            "staleness": D,
            "lockstep": D <= 1,
            **model,
            "test_accuracy": float(hist[-1]["test_accuracy"]),
            "loss": float(hist[-1]["loss"]),
            "msgs_saved_pct": float(hist[-1].get("msgs_saved_pct", 0.0)),
        }
        if D >= 2:
            leg["edge_staleness_max"] = int(hist[-1]["edge_staleness_max"])
            leg["late_commits"] = int(hist[-1]["late_commits"])
            # replay: the whole story (straggler included) from its seed
            state2, hist2 = _run_leg(
                model_fn, topo, x, y, x_test, y_test, sched, D,
                args.epochs, args.batch_size, event_cfg, args.seed,
                composed=composed,
            )
            leg["replay_bitwise"] = bool(all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(jax.tree.leaves(state.params),
                                jax.tree.leaves(state2.params))
            ) and hist2[-1]["test_accuracy"] == hist[-1]["test_accuracy"])
        legs.append(leg)

    lock = [l for l in legs if l["lockstep"]]
    async_ = [l for l in legs if not l["lockstep"]]
    lock_time = min(l["modeled_step_time"] for l in lock)
    async_time = min(l["modeled_step_time"] for l in async_)
    lock_acc = max(l["test_accuracy"] for l in lock)
    acc_gap = max(
        0.0, max(lock_acc - l["test_accuracy"] for l in async_)
    )

    measured_rec: Dict[str, Any] = {}
    if args.measured:
        d_lock = max(l["staleness"] for l in lock)
        d_async = max(l["staleness"] for l in async_)
        # calibrate the per-pass quantum from the composed config's
        # REAL step time (differenced, so compile cancels), floored so
        # GIL handoff jitter (~0.5 ms/thread) stays < 10% of a pass
        raw = _calibrate_compute(
            model_fn, topo, x, y, sched, d_async, args.batch_size,
            event_cfg, args.seed, composed, steps,
        )
        compute = min(0.05, max(0.008, raw))
        wall_lock = measured_timeline(
            topo, d_lock, args.measured_passes, compute,
            args.straggler_rank, args.straggler_lag,
        )
        wall_async = measured_timeline(
            topo, d_async, args.measured_passes, compute,
            args.straggler_rank, args.straggler_lag,
        )
        ratio = wall_lock / wall_async
        measured_rec = {
            "measured": True,
            "measured_config": "eventgrad+compact0.5+int8+bucketed4"
                               "+carrier_resident",
            "measured_passes": args.measured_passes,
            "measured_compute_s": round(compute, 5),
            "measured_compute_raw_s": round(raw, 5),
            "measured_lockstep_staleness": d_lock,
            "measured_bounded_staleness": d_async,
            "measured_lockstep_wall_s": round(wall_lock, 3),
            "measured_bounded_wall_s": round(wall_async, 3),
            "measured_ratio": round(ratio, 3),
            # both instruments must tell the same story: modeled says
            # bounded-async wins, the wall clock must agree
            "measured_agrees_with_modeled": bool(
                (ratio > 1.0) == (lock_time > async_time)
            ),
        }
    rec = {
        "bench": "straggler_ablation",
        "schema_version": STRAGGLER_SCHEMA_VERSION,
        "platform": f"{platform.system()}-{jax.default_backend()}",
        "topo": f"ring:{args.ranks}",
        "algo": "eventgrad",
        "op_point": {
            "epochs": args.epochs, "batch_size": args.batch_size,
            "n_synth": args.n_synth, "passes": n_passes,
            "model": "mlp16", "seed": args.seed,
            "config": ("composed" if composed else "plain"),
        },
        "chaos": sched.to_dict(),
        "straggler": {
            "rank": args.straggler_rank, "lag": args.straggler_lag,
        },
        "legs": legs,
        "lockstep_step_time": lock_time,
        "bounded_async_step_time": async_time,
        "speedup_vs_lockstep": round(lock_time / async_time, 3),
        "bounded_async_beats_lockstep": bool(async_time < lock_time),
        "acc_gap_pt": round(acc_gap, 3),
        "replay_bitwise": bool(all(
            l.get("replay_bitwise", True) for l in legs
        )),
        **measured_rec,
        "wall_s": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps({k: v for k, v in rec.items() if k != "legs"},
                     indent=1))
    for leg in legs:
        print(f"  D={leg['staleness']}: step_time="
              f"{leg['modeled_step_time']} acc={leg['test_accuracy']:.2f}"
              + (f" late={leg['late_commits']}"
                 if "late_commits" in leg else ""))
    ok = (rec["bounded_async_beats_lockstep"]
          and rec["acc_gap_pt"] <= 0.5 and rec["replay_bitwise"])
    if args.measured:
        print(f"  measured: lockstep D={rec['measured_lockstep_staleness']}"
              f" {rec['measured_lockstep_wall_s']}s vs bounded "
              f"D={rec['measured_bounded_staleness']} "
              f"{rec['measured_bounded_wall_s']}s -> "
              f"ratio {rec['measured_ratio']}x "
              f"(compute {rec['measured_compute_s']*1e3:.1f} ms/pass)")
        ok = ok and rec["measured_ratio"] > 1.0 \
            and rec["measured_agrees_with_modeled"]
    print(f"straggler ablation: {'OK' if ok else 'FAILED'} -> {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
