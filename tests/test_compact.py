"""Budgeted compacted gossip: wire-format equivalence, deferral, autotune.

The compact exchange's contract (docs/compaction.md): whenever every
fired leaf fits the budget, `compact_neighbor_vals` is BITWISE
`masked_neighbor_vals` — on every wire dtype and both lifting paths —
while moving capacity/n_params of the dense value lanes; overflow defers
fired leaves (rolled-back event state, max_silence-overdue priority)
instead of dropping them; the autotuned capacity is a static bucketed
number so the switched-to step compiles exactly once.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _spmd import requires_shard_map

from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel import collectives
from eventgrad_tpu.parallel.events import (
    EventConfig, EventState, capacity_gate, commit, propose,
)
from eventgrad_tpu.parallel.spmd import build_mesh, spmd
from eventgrad_tpu.parallel.topology import Ring, Torus
from eventgrad_tpu.train.loop import train

# the equivalence still gets proven on the vmap lift where the mesh
# lift is unavailable (tests/_spmd.py)
BACKENDS = [
    "vmap",
    pytest.param("shard_map", marks=requires_shard_map),
]


def _lift(fn, topo, backend):
    if backend == "vmap":
        return spmd(fn, topo)
    return spmd(fn, topo, mesh=build_mesh(topo))


def _tree(rng, n_ranks):
    return {
        "a": jnp.asarray(rng.standard_normal((n_ranks, 3, 2)), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n_ranks, 5)), jnp.float32),
        "c": jnp.asarray(rng.standard_normal((n_ranks, 7)), jnp.float32),
    }


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("wire", [None, "bf16", "int8"])
def test_compact_bitwise_matches_masked(backend, wire):
    """capacity >= fired payload => identical buffers AND identical
    received fire bits, per wire dtype, per lift."""
    topo = Ring(4)
    rng = np.random.default_rng(0)
    p = _tree(rng, 4)
    fire = {
        "a": jnp.array([True, False, True, False]),
        "b": jnp.array([False, True, True, False]),
        "c": jnp.array([True, True, False, False]),
    }
    last = jax.tree.map(lambda x: jnp.full_like(x, -9.0), p)

    def f_mask(p, f, l):
        return collectives.masked_neighbor_vals(p, f, (l, l), topo, wire)

    def f_comp(p, f, l):
        # capacity 18 >= worst-case fired total (a+c = 13, b+c = 12, ...)
        return collectives.compact_neighbor_vals(
            p, f, (l, l), topo, 18, wire
        )

    bm, fm = _lift(f_mask, topo, backend)(p, fire, last)
    bc, fc = _lift(f_comp, topo, backend)(p, fire, last)
    for a, b in zip(jax.tree.leaves(bm), jax.tree.leaves(bc)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(fm), jax.tree.leaves(fc)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compact_torus_four_neighbors():
    """4-neighbor torus: every edge's buffer matches the masked path."""
    topo = Torus(4, 2)
    rng = np.random.default_rng(1)
    p = _tree(rng, 8)
    fire = {
        "a": jnp.asarray(rng.random(8) < 0.5),
        "b": jnp.asarray(rng.random(8) < 0.5),
        "c": jnp.asarray(rng.random(8) < 0.5),
    }
    last = jax.tree.map(lambda x: jnp.full_like(x, -3.0), p)
    n_nb = topo.n_neighbors

    def f_mask(p, f, l):
        return collectives.masked_neighbor_vals(
            p, f, (l,) * n_nb, topo
        )

    def f_comp(p, f, l):
        return collectives.compact_neighbor_vals(
            p, f, (l,) * n_nb, topo, 18
        )

    bm, _ = spmd(f_mask, topo)(p, fire, last)
    bc, _ = spmd(f_comp, topo)(p, fire, last)
    for a, b in zip(jax.tree.leaves(bm), jax.tree.leaves(bc)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compact_deliver_gating_matches_masked():
    """Chaos per-edge delivery bits gate the compact scatter exactly like
    the masked where()."""
    topo = Ring(4)
    rng = np.random.default_rng(2)
    p = _tree(rng, 4)
    fire = jax.tree.map(lambda x: jnp.ones((4,), bool), p)
    last = jax.tree.map(lambda x: jnp.full_like(x, -1.0), p)
    deliver = jnp.tile(jnp.array([[True, False]]), (4, 1))  # right edge down

    def f_mask(p, f, l, d):
        return collectives.masked_neighbor_vals(
            p, f, (l, l), topo, deliver=d
        )

    def f_comp(p, f, l, d):
        return collectives.compact_neighbor_vals(
            p, f, (l, l), topo, 18, deliver=d
        )

    bm, fm = spmd(f_mask, topo)(p, fire, last, deliver)
    bc, fc = spmd(f_comp, topo)(p, fire, last, deliver)
    for a, b in zip(jax.tree.leaves((bm, fm)), jax.tree.leaves((bc, fc))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the gated edge really kept its stale buffer
    np.testing.assert_array_equal(np.asarray(bc[1]["a"]), -1.0)


def test_compact_capacity_below_largest_leaf_rejected():
    topo = Ring(4)
    p = _tree(np.random.default_rng(0), 4)
    fire = jax.tree.map(lambda x: jnp.ones((4,), bool), p)
    last = jax.tree.map(jnp.zeros_like, p)
    with pytest.raises(ValueError, match="largest leaf"):
        spmd(
            lambda p, f, l: collectives.compact_neighbor_vals(
                p, f, (l, l), topo, 5  # < leaf c's 7 elements
            ),
            topo,
        )(p, fire, last)


def test_capacity_gate_greedy_and_priority():
    sizes = (6, 5, 7)
    fire = jnp.array([True, True, True])
    # leaf order: a(6)+b(5)=11 fit a 12-budget, c(7) defers
    np.testing.assert_array_equal(
        np.asarray(capacity_gate(fire, sizes, 12)), [True, True, False]
    )
    # c overdue -> admitted first; a/b no longer fit
    np.testing.assert_array_equal(
        np.asarray(capacity_gate(
            fire, sizes, 12, priority=jnp.array([False, False, True])
        )),
        [False, False, True],
    )
    # gate output is always a subset of the proposal
    np.testing.assert_array_equal(
        np.asarray(capacity_gate(
            jnp.array([False, True, False]), sizes, 12
        )),
        [False, True, False],
    )


def test_deferral_rolls_back_and_silence_bound_holds():
    """Under a budget that fits one leaf per pass, max_silence-overdue
    leaves take priority, so no leaf's silence exceeds the bound plus the
    overdue-queue drain time; deferrals are counted and committed state
    for deferred leaves is untouched."""
    topo = Ring(2)
    cfg = EventConfig(adaptive=False, constant=0.0, warmup_passes=0,
                      max_silence=3)
    params = {"a": jnp.zeros(4), "b": jnp.zeros(4), "c": jnp.zeros(4)}
    sizes = (4, 4, 4)
    st = EventState.init(params, topo, cfg)
    max_silence_seen = 0
    deferred_total = 0
    for p in range(1, 25):
        prop = propose(params, st, jnp.int32(p), cfg)
        # constant-0 threshold: every leaf proposes to fire every pass
        assert bool(np.all(np.asarray(prop.fire_vec)))
        overdue = prop.iter_diff >= cfg.max_silence
        eff = capacity_gate(prop.fire_vec, sizes, 4, priority=overdue)
        assert int(np.asarray(eff).sum()) == 1  # budget fits one leaf
        st = commit(st, prop, eff, cfg, topo.n_neighbors)
        silence = p - np.asarray(st.last_sent_iter)
        max_silence_seen = max(max_silence_seen, int(silence.max()))
        deferred_total = int(np.asarray(st.num_deferred))
    # bound: max_silence + (n_leaves - 1) passes to drain the overdue queue
    assert max_silence_seen <= cfg.max_silence + len(sizes) - 1
    assert deferred_total > 0
    # num_events counts EFFECTIVE sends: one leaf x n_neighbors per pass
    assert int(np.asarray(st.num_events)) == 24 * topo.n_neighbors


def test_choose_capacity_bucketing_and_clamps():
    # nearby observations land in the SAME bucket: no recompile churn
    a = collectives.choose_capacity(1_000_000, 30_000, 100)
    b = collectives.choose_capacity(1_000_000, 30_500, 100)
    assert a == b
    assert a % 8192 == 0 and a >= 30_500 * 1.25
    # floor (largest leaf) and ceiling (whole model) hold
    assert collectives.choose_capacity(1_000_000, 10, 50_000) >= 50_000
    assert collectives.choose_capacity(1_000_000, 2_000_000, 100) == 1_000_000


def _go(gossip_wire="dense", compact_frac=None, **kw):
    x, y = synthetic_dataset(128, (28, 28, 1), seed=6)
    kw.setdefault(
        "event_cfg", EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    )
    return train(
        MLP(), Ring(4), x, y,
        algo="eventgrad", epochs=4, batch_size=8, learning_rate=0.05,
        seed=1, log_every_epoch=False, gossip_wire=gossip_wire,
        compact_frac=compact_frac, **kw,
    )


def test_train_compact_frac1_bitwise_equals_masked():
    """compact_frac=1.0 (capacity = n_params, nothing defers) must
    reproduce the masked run bit-for-bit end to end, dense warmup phase
    and all."""
    sm, hm = _go()
    sc, hc = _go(gossip_wire="compact", compact_frac=1.0)
    for a, b in zip(jax.tree.leaves(sm.params), jax.tree.leaves(sc.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the mode switch really happened after the warmup block...
    assert [h["gossip_wire"] for h in hc] == [
        "dense", "compact", "compact", "compact"
    ]
    # ...at a constant static capacity (no churn)
    caps = {h["compact_capacity"] for h in hc if "compact_capacity" in h}
    assert len(caps) == 1
    # and events/savings accounting is unchanged by the wire mode
    assert hm[-1]["num_events"] == hc[-1]["num_events"]
    assert hc[-1]["num_deferred"] == 0


def test_train_wire_real_bytes_reported():
    """Every mode reports the SPMD wire truth; masked = dense payload +
    fire bytes regardless of the fire rate."""
    _, h = _go()
    n_params, n_leaves, n_nb = 101770, 4, 2
    np.testing.assert_allclose(
        h[-1]["sent_bytes_wire_real_per_step_per_chip"],
        n_nb * (4.0 * n_params + n_leaves),
    )
    # the accounting number is far below it at this op-point's fire rate
    assert (
        h[-1]["sent_bytes_per_step_per_chip"]
        < h[-1]["sent_bytes_wire_real_per_step_per_chip"]
    )


def test_train_autotune_declines_when_floor_pins_capacity():
    """MLP's 98.6%-of-model kernel makes the largest-leaf floor reach
    n_params: the autotuner must stay dense and say so, not compile a
    pointless full-capacity program."""
    os.environ["EG_COMPACT_MIN_SAMPLES"] = "4"
    try:
        _, h = _go(gossip_wire="compact")
    finally:
        del os.environ["EG_COMPACT_MIN_SAMPLES"]
    assert all(r["gossip_wire"] == "dense" for r in h)
    skipped = [r for r in h if "compact_skipped" in r]
    assert len(skipped) == 1 and skipped[0]["compact_autotuned"]


class _ManyLeafMLP:
    """8 balanced Dense blocks: a geometry where compaction CAN pay
    (largest leaf ~1/8 of the model), unlike the reference's CNNs."""

    def __new__(cls):
        import flax.linen as nn

        class M(nn.Module):
            @nn.compact
            def __call__(self, x, train=False, **kw):
                x = x.reshape((x.shape[0], -1))
                x = nn.Dense(64)(x)
                for _ in range(6):
                    x = nn.relu(nn.Dense(64)(x))
                return nn.Dense(10)(x)

        return M()


def test_train_autotune_activates_on_many_leaf_model():
    os.environ["EG_COMPACT_MIN_SAMPLES"] = "4"
    try:
        x, y = synthetic_dataset(128, (8, 8, 1), seed=6)
        cfg = EventConfig(adaptive=True, horizon=1.1, warmup_passes=2)
        _, h = train(
            _ManyLeafMLP(), Ring(4), x, y,
            algo="eventgrad", epochs=6, batch_size=8, learning_rate=0.05,
            seed=1, log_every_epoch=False, gossip_wire="compact",
            event_cfg=cfg,
        )
    finally:
        del os.environ["EG_COMPACT_MIN_SAMPLES"]
    modes = [r["gossip_wire"] for r in h]
    assert modes[0] == "dense" and modes[-1] == "compact", modes
    compact_recs = [r for r in h if r["gossip_wire"] == "compact"]
    caps = {r["compact_capacity"] for r in compact_recs}
    assert len(caps) == 1  # static across dispatches
    cap = caps.pop()
    n_params = h[0]["n_params"]
    assert cap < n_params
    # wire truth dropped with the switch: compact blocks move fewer bytes
    dense_real = h[0]["sent_bytes_wire_real_per_step_per_chip"]
    comp_real = compact_recs[-1]["sent_bytes_wire_real_per_step_per_chip"]
    n_leaves = 16
    np.testing.assert_allclose(
        comp_real, 2 * (4.0 * cap + n_leaves)
    )
    assert comp_real < dense_real


def test_train_compact_tight_budget_defers_but_trains():
    """An explicit under-sized budget exercises deferral inside the jitted
    step: deferrals accumulate, training stays finite, and the guard
    keeps staleness bounded."""
    x, y = synthetic_dataset(128, (8, 8, 1), seed=6)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2,
                      max_silence=4)
    _, h = train(
        _ManyLeafMLP(), Ring(4), x, y,
        algo="eventgrad", epochs=6, batch_size=8, learning_rate=0.05,
        seed=1, log_every_epoch=False, gossip_wire="compact",
        compact_frac=0.30, event_cfg=cfg,
    )
    assert h[-1]["gossip_wire"] == "compact"
    assert h[-1]["num_deferred"] > 0
    assert np.isfinite(h[-1]["loss"])


def test_train_compact_rejected_for_non_event_algos():
    with pytest.raises(ValueError, match="eventgrad"):
        x, y = synthetic_dataset(64, (28, 28, 1), seed=0)
        train(MLP(), Ring(4), x, y, algo="dpsgd", epochs=1, batch_size=8,
              gossip_wire="compact", log_every_epoch=False)
    with pytest.raises(ValueError, match="compact_frac"):
        x, y = synthetic_dataset(64, (28, 28, 1), seed=0)
        train(MLP(), Ring(4), x, y, algo="eventgrad", epochs=1,
              batch_size=8, compact_frac=0.5, log_every_epoch=False)


def test_cli_gossip_wire_validation():
    from eventgrad_tpu.cli import main

    with pytest.raises(SystemExit, match="eventgrad"):
        main(["--algo", "dpsgd", "--gossip-wire", "compact"])
    with pytest.raises(SystemExit, match="compact-frac"):
        main(["--algo", "eventgrad", "--compact-frac", "0.5"])
    with pytest.raises(SystemExit, match="0, 1"):
        main(["--algo", "eventgrad", "--gossip-wire", "compact",
              "--compact-frac", "1.5"])


def test_resume_migrates_pre_compaction_snapshot(tmp_path):
    """A snapshot saved before EventState.num_deferred existed must still
    resume: the counter grafts in at zero (checkpoint.restore_with_fill)
    instead of failing orbax's exact-structure match."""
    import shutil
    import warnings

    import orbax.checkpoint as ocp

    from eventgrad_tpu.utils import checkpoint

    d = str(tmp_path)
    x, y = synthetic_dataset(128, (28, 28, 1), seed=6)
    cfg = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2)
    kw = dict(algo="eventgrad", epochs=2, batch_size=8, learning_rate=0.05,
              seed=1, log_every_epoch=False, event_cfg=cfg)
    train(MLP(), Ring(4), x, y, checkpoint_dir=d, **kw)

    # rewrite the snapshot with the PRE-compaction state structure
    p = os.path.join(d, "ckpt")
    with ocp.PyTreeCheckpointer() as c:
        old = c.restore(p)
    del old["state"]["event"]["num_deferred"]
    shutil.rmtree(p)
    checkpoint.save(p, old)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        s2, h2 = train(MLP(), Ring(4), x, y, checkpoint_dir=d, resume=True,
                       **{**kw, "epochs": 3})
    assert [r["epoch"] for r in h2] == [3]
    np.testing.assert_array_equal(np.asarray(s2.event.num_deferred) >= 0,
                                  True)
    assert any("num_deferred" in str(x.message) for x in w)


def test_mix_weighted_fused_stays_bitwise_vs_reference_loop():
    """Satellite guard: the single-traversal mix_weighted must equal the
    old per-edge accumulation bitwise, gates on or off."""
    rng = np.random.default_rng(3)
    params = {"a": jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
              "b": jnp.asarray(rng.standard_normal(5), jnp.float32)}
    bufs = tuple(
        jax.tree.map(
            lambda x, _i=i: x + np.float32(0.1) * (_i + 1), params
        )
        for i in range(3)
    )

    def reference(params, bufs, gate):
        acc = params
        for i, buf in enumerate(bufs):
            acc = jax.tree.map(
                lambda x, b, _g=gate[i]: x + jnp.where(
                    _g, b, jnp.zeros_like(b)
                ),
                acc, buf,
            )
        w = 1.0 / (1.0 + jnp.sum(gate.astype(jnp.float32)))
        return jax.tree.map(lambda x: x * w, acc)

    for bits in ([True, True, True], [True, False, True], [False] * 3):
        gate = jnp.asarray(bits)
        got = collectives.mix_weighted(params, bufs, gate)
        want = reference(params, bufs, gate)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
