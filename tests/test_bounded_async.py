"""Bounded-async gossip (ISSUE 15): per-edge staleness clocks, delivery
queues, and the lockstep-shedding contract.

The three-way bitwise contract of `train(staleness=D)` for D >= 2:

  (a) D <= 1 is bitwise-unchanged vs today's step — the legacy code
      path is untouched, and D=2 under the all-baseline lag schedule
      reproduces staleness=1 EXACTLY (every message lands one pass
      late, which is what staleness=1 already models);
  (b) a LATE delivery is committed on arrival through the same
      `where(eff, cand, stale)` select as a synchronous one, so late
      ≡ a fire deferred to its arrival pass with the sender's original
      payload — pinned here at the `async_delivery_commit` op level
      the same way chaos pinned drop ≡ not-fired;
  (c) the whole straggler story replays bitwise from its seed
      (tools/straggler_ablation.py's committed artifact re-proves it).
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from _spmd import requires_shard_map
from eventgrad_tpu.chaos import inject as chaos_inject
from eventgrad_tpu.chaos import monitor as chaos_monitor
from eventgrad_tpu.chaos.schedule import ChaosSchedule, LagWindow
from eventgrad_tpu.data.datasets import synthetic_dataset
from eventgrad_tpu.data.sharding import batched_epoch
from eventgrad_tpu.models import MLP
from eventgrad_tpu.parallel import arena as arena_lib
from eventgrad_tpu.parallel.events import (
    EventConfig, EventState, async_delivery_commit,
)
from eventgrad_tpu.parallel.spmd import spmd, stack_for_ranks
from eventgrad_tpu.parallel.topology import Ring
from eventgrad_tpu.train.loop import train
from eventgrad_tpu.train.state import init_train_state
from eventgrad_tpu.train.steps import make_train_step

N_RANKS = 4
IN_SHAPE = (8, 8, 1)
CFG = EventConfig(adaptive=True, horizon=0.9, warmup_passes=2,
                  max_silence=4)
MODEL = dict(hidden=8)


# --- unit level: the delivery-queue state machine ----------------------


def _unit_state(D, n=6, L=2, n_nb=1):
    """A hand-built 1-neighbor EventState with a D-deep queue over a
    tiny 2-leaf arena ([4] + [2] elements)."""
    params = {"a": jnp.zeros((4,)), "b": jnp.zeros((2,))}
    spec = arena_lib.arena_spec(params)
    topo = Ring(2)
    st = EventState.init(params, topo, CFG, arena=True, staleness=D)
    # Ring(2) has 2 neighbors; keep neighbor 0 only for the unit
    st = st.replace(
        bufs=st.bufs[:n_nb], pending=st.pending[:n_nb],
        edge_clock=st.edge_clock[:n_nb],
    )
    return st, spec


def _commit(st, spec, D, pass_num, cand, eff, lag, delivered=True):
    return async_delivery_commit(
        st,
        (jnp.asarray(cand, jnp.float32),),
        (jnp.asarray(eff, bool),),
        jnp.asarray([delivered], bool),
        jnp.asarray([lag], jnp.int32),
        jnp.int32(pass_num),
        spec,
        D,
    )


def test_commit_on_arrival_is_deferred_fire_bitwise():
    """A message sent at pass t with lag d leaves the buffer untouched
    for d-1 passes and commits at pass t+d as EXACTLY
    `where(eff, sender's pass-t payload, stale)` — a deferred fire."""
    D = 3
    st, spec = _unit_state(D)
    payload = np.arange(6, dtype=np.float32) + 1.0
    eff = [True, False]  # leaf a fired, leaf b did not
    # pass 1: enqueue at lag 3 — nothing visible
    st, bufs, stale, late = _commit(st, spec, D, 1, payload, eff, 3)
    np.testing.assert_array_equal(np.asarray(bufs[0]), np.zeros(6))
    # passes 2, 3: quiet exchanges (nothing fired) — the lag-3 message
    # from pass 1 is still in flight, the buffer stays untouched
    for p in (2, 3):
        st, bufs, stale, late = _commit(
            st, spec, D, p, np.zeros(6), [False, False], 1,
        )
        np.testing.assert_array_equal(np.asarray(bufs[0]), np.zeros(6))
    # pass 4: arrival — the deferred-fire select, bitwise
    st, bufs, stale, late = _commit(
        st, spec, D, 4, np.zeros(6), [False, False], 1,
    )
    seg = spec.seg_expand()
    expect = np.where(
        np.asarray(jnp.asarray([True, False])[seg]), payload, 0.0
    )
    np.testing.assert_array_equal(np.asarray(bufs[0]), expect)
    # leaf b (not fired) stayed stale; the commit counted as late
    assert int(late) == 1
    assert int(st.late_commits) == 1


def test_clock_advance_and_staleness_gauge():
    """The per-edge clock tracks the newest DELIVERED send; drops keep
    the gauge growing, deliveries snap it back to the lag."""
    D = 2
    st, spec = _unit_state(D)
    gauges = []
    for p in range(1, 6):
        delivered = p != 3  # pass 3's exchange is dropped
        st, bufs, stale, _ = _commit(
            st, spec, D, p, np.zeros(6), [False, False], 1,
            delivered=delivered,
        )
        gauges.append(int(stale[0]))
    # pass 1: nothing committed yet (clock 0) -> gauge 1; from pass 2
    # the lag-1 deliveries hold the gauge at 1, except pass 4 where the
    # dropped pass-3 message leaves the clock at 2 (gauge 4 - 2 = 2)
    assert gauges == [1, 1, 1, 2, 1]


def test_same_pass_merge_later_sent_wins():
    """Two in-flight messages arriving on the same pass merge
    later-sent-wins: committing the merge == committing old then new."""
    D = 2
    st, spec = _unit_state(D)
    old = np.full(6, 5.0, np.float32)
    new = np.full(6, 9.0, np.float32)
    # pass 1: lag 2 (arrives pass 3), both leaves fired
    st, bufs, _, _ = _commit(st, spec, D, 1, old, [True, True], 2)
    # pass 2: lag 1 (arrives pass 3 too), only leaf b fired
    st, bufs, _, _ = _commit(st, spec, D, 2, new, [False, True], 1)
    # pass 3: leaf a shows the OLD payload (only the old message fired
    # it), leaf b the NEW one (later-sent wins)
    st, bufs, _, late = _commit(
        st, spec, D, 3, np.zeros(6), [False, False], 1,
    )
    got = np.asarray(bufs[0])
    np.testing.assert_array_equal(got[:4], old[:4])
    np.testing.assert_array_equal(got[4:], new[4:])
    # exactly one of the two merged arrivals was late (the lag-2 one)
    assert int(st.late_commits) == 1


def test_lag_vector_bound_enforcement():
    """Scheduled lag beyond the bound clamps to D — the rank waits
    instead of running further ahead — and lag_table(bound=) replays
    the exact in-step values while bound=None exposes the raw f."""
    topo = Ring(N_RANKS)
    sched = ChaosSchedule(seed=0, slow=((2, 9),), lag=(LagWindow(5, 8, 3),))
    for D in (2, 4):
        tab = chaos_inject.lag_table(sched, topo, 10, bound=D)
        for p in range(1, 11):
            for r in range(N_RANKS):
                vec = np.asarray(jax.jit(
                    lambda pp, ss: chaos_inject.lag_vector(
                        sched, topo, pp, bound=D, srcs=ss,
                    )
                )(
                    jnp.int32(p),
                    jnp.asarray([
                        topo.neighbor_source(r, nb) for nb in topo.neighbors
                    ], jnp.int32),
                ))
                np.testing.assert_array_equal(vec, tab[p - 1, r])
        assert tab.max() == D  # f=9 clamped to the bound
    raw = chaos_inject.lag_table(sched, topo, 10, bound=None)
    assert raw.max() == 9  # the unclamped network truth
    assert raw[5, 0].min() >= 3  # the lag window covers every edge


# --- step level: parity and the straggler surface ----------------------


def _batches(steps=5, seed=3):
    x, y = synthetic_dataset(N_RANKS * 8 * steps, IN_SHAPE, seed=seed)
    xb, yb = batched_epoch(x, y, N_RANKS, 8)
    return [
        (jnp.asarray(xb[:, s]), jnp.asarray(yb[:, s])) for s in range(steps)
    ]


def _run(staleness, chaos=None, gossip_wire="dense", wire=None, steps=5,
         bucketed=None, carrier=False):
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05)
    state = init_train_state(
        model, IN_SHAPE, tx, topo, "eventgrad", CFG, seed=0, arena=True,
        staleness=staleness, bucketed=bucketed or 1,
        resident_wire=wire if carrier else None,
    )
    if chaos is not None:
        state = state.replace(
            chaos=stack_for_ranks(chaos_monitor.PeerHealth.init(topo), topo)
        )
    capacity = None
    if gossip_wire == "compact":
        if bucketed:
            from eventgrad_tpu.parallel import collectives
            params0 = jax.tree.map(lambda x: x[0], state.params)
            capacity = int(collectives.bucketed_capacity_floor(
                arena_lib.arena_spec(params0).buckets(bucketed)
            ))
        else:
            from eventgrad_tpu.utils import trees
            capacity = trees.tree_count_params(state.params) // topo.n_ranks
    step = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=CFG, arena=True,
        staleness=staleness, chaos=chaos, gossip_wire=gossip_wire,
        compact_capacity=capacity, wire=wire, bucketed=bucketed,
        carrier_resident=carrier,
    )
    lifted = jax.jit(spmd(step, topo))
    m = None
    for b in _batches(steps):
        state, m = lifted(state, b)
    return state, m


@pytest.mark.parametrize("wire", [None, "int8"])
@pytest.mark.parametrize("gossip_wire", ["dense", "compact"])
def test_baseline_lag_reproduces_staleness1_bitwise(gossip_wire, wire):
    """D=2 with no lag schedule == staleness=1 bitwise on params,
    optimizer, event trigger state, receive buffers, and every shared
    metric: with every message exactly one pass late, the bounded
    engine IS the one-pass-stale model."""
    s1, m1 = _run(1, gossip_wire=gossip_wire, wire=wire)
    s2, m2 = _run(2, gossip_wire=gossip_wire, wire=wire)
    for field in ("params", "opt_state", "batch_stats"):
        for a, b in zip(jax.tree.leaves(getattr(s1, field)),
                        jax.tree.leaves(getattr(s2, field))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for attr in ("thres", "last_sent_norm", "slopes", "num_events",
                 "num_deferred", "bufs"):
        for a, b in zip(jax.tree.leaves(getattr(s1.event, attr)),
                        jax.tree.leaves(getattr(s2.event, attr))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m1:  # every legacy metric unchanged; D=2 only ADDS keys
        np.testing.assert_array_equal(
            np.asarray(m1[k]), np.asarray(m2[k]), err_msg=k
        )
    assert set(m2) - set(m1) == {"edge_staleness", "late_commits"}
    # no late deliveries at the baseline lag
    assert int(np.asarray(m2["late_commits"]).sum()) == 0
    assert np.asarray(m2["edge_staleness"]).max() <= 1


# --- the composed overlap stack (ISSUE 20) -----------------------------


@pytest.mark.parametrize("bucketed,gossip_wire,wire,carrier", [
    # queue slots carried per-bucket, masked and compact wires
    (4, "dense", None, False),
    (4, "compact", None, False),
    # ... and carrier-resident: queue slots in the wire dtype with
    # per-slot dequant scales
    (4, "dense", "int8", True),
    (4, "compact", "int8", True),   # the full composed stack
    (None, "dense", "int8", True),  # monolithic carrier queue
    (None, "compact", "int8", True),
])
def test_composed_baseline_lag_reproduces_staleness1_bitwise(
        bucketed, gossip_wire, wire, carrier):
    """The D=2 ≡ D=1 contract survives FULL composition: bounded-async
    delivery queues x bucketed schedule x compact wire x int8
    carrier-resident buffers in one step. Params, optimizer, trigger
    state, receive buffers, and every shared metric bitwise."""
    kw = dict(gossip_wire=gossip_wire, wire=wire, bucketed=bucketed,
              carrier=carrier)
    s1, m1 = _run(1, **kw)
    s2, m2 = _run(2, **kw)
    for field in ("params", "opt_state", "batch_stats"):
        for a, b in zip(jax.tree.leaves(getattr(s1, field)),
                        jax.tree.leaves(getattr(s2, field))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for attr in ("thres", "last_sent_norm", "slopes", "num_events",
                 "num_deferred", "bufs", "buf_scales"):
        for a, b in zip(jax.tree.leaves(getattr(s1.event, attr)),
                        jax.tree.leaves(getattr(s2.event, attr))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m1:
        np.testing.assert_array_equal(
            np.asarray(m1[k]), np.asarray(m2[k]), err_msg=k
        )
    assert set(m2) - set(m1) == {"edge_staleness", "late_commits"}
    assert int(np.asarray(m2["late_commits"]).sum()) == 0
    if carrier:
        # the queue carry stayed carrier-resident: receive buffers in
        # the wire dtype on BOTH legs
        assert all(
            np.asarray(leaf).dtype == np.int8
            for leaf in jax.tree.leaves(s2.event.bufs)
        )


def test_composed_deep_queue_baseline_lag_matches_staleness1():
    """D=4 at the baseline lag on the full composed stack: the three
    extra runway slots are pure padding — still bitwise the
    staleness=1 model."""
    kw = dict(gossip_wire="compact", wire="int8", bucketed=4,
              carrier=True)
    s1, _ = _run(1, **kw)
    s4, m4 = _run(4, **kw)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s4.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.asarray(m4["late_commits"]).sum()) == 0


def test_composed_straggler_stack_replays_bitwise():
    """The full stack under a REAL straggler (slow=1@7 beyond the
    bound): gauges clamp at D, late commits accrue, and the whole
    composed story replays bitwise from its seed."""
    sched = ChaosSchedule(seed=5, slow=((1, 7),))
    kw = dict(chaos=sched, gossip_wire="compact", wire="int8",
              bucketed=4, carrier=True, steps=8)
    s_a, m_a = _run(4, **kw)
    es = np.asarray(m_a["edge_staleness"])
    assert es.max() == 4
    assert int(np.asarray(m_a["late_commits"]).sum()) > 0
    assert set(np.argwhere(es == 4)[:, 0].tolist()) == {0, 2}
    s_b, m_b = _run(4, **kw)
    for a, b in zip(jax.tree.leaves(s_a), jax.tree.leaves(s_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m_a:
        np.testing.assert_array_equal(
            np.asarray(m_a[k]), np.asarray(m_b[k]), err_msg=k
        )


def _run_sp(staleness, steps=5, bucketed=None, wire=None):
    """sp_eventgrad runner: payload queues live in SparseState.pending
    (sp's trigger EventState stays depth 0)."""
    topo = Ring(N_RANKS)
    model = MLP(**MODEL)
    tx = optax.sgd(0.05)
    state = init_train_state(
        model, IN_SHAPE, tx, topo, "sp_eventgrad", CFG, seed=0,
        staleness=staleness,
    )
    step = make_train_step(
        model, tx, topo, "sp_eventgrad", event_cfg=CFG,
        staleness=staleness, wire=wire, bucketed=bucketed,
    )
    lifted = jax.jit(spmd(step, topo))
    m = None
    for b in _batches(steps):
        state, m = lifted(state, b)
    return state, m


@pytest.mark.parametrize("bucketed,wire", [
    (None, None), (4, "int8"),
])
def test_sp_payload_queue_baseline_matches_staleness1(bucketed, wire):
    """sp_eventgrad at D=2 through its payload queues ≡ staleness=1
    bitwise (sp x chaos stays refused, so every payload enqueues at
    slot 0 — commit-on-arrival IS the one-pass-stale replica mix),
    monolithic and bucketed-int8 alike."""
    s1, m1 = _run_sp(1, bucketed=bucketed, wire=wire)
    s2, m2 = _run_sp(2, bucketed=bucketed, wire=wire)
    # params/opt_state bitwise — the MIX consumed identical replicas.
    # SparseState.replicas themselves legitimately differ by one pass:
    # D=2's resident replicas hold payloads <= p-1 (this pass's sits in
    # the queue), staleness=1's hold pass p (it mixes a pre-exchange
    # stale copy instead) — same mix input, different carrier.
    for field in ("params", "opt_state"):
        for a, b in zip(jax.tree.leaves(getattr(s1, field)),
                        jax.tree.leaves(getattr(s2, field))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m1:
        np.testing.assert_array_equal(
            np.asarray(m1[k]), np.asarray(m2[k]), err_msg=k
        )


@requires_shard_map
def test_composed_stack_vmap_shard_map_parity():
    """The composed production config (bounded-async D=4 x compact
    int8 x bucketed K=4 x carrier-resident) is bitwise identical
    across the vmap simulator and the real shard_map mesh."""
    if len(jax.devices()) < N_RANKS:
        pytest.skip(f"needs {N_RANKS} devices")
    x, y = synthetic_dataset(128, IN_SHAPE, seed=3)
    kw = dict(
        algo="eventgrad", epochs=2, batch_size=8, event_cfg=CFG, seed=0,
        log_every_epoch=False, staleness=4, gossip_wire="compact",
        compact_frac=0.5, wire="int8", bucketed=4, carrier_resident=True,
        chaos="slow=1@3,seed=5",
    )
    s_v, h_v = train(MLP(**MODEL), Ring(N_RANKS), x, y, backend="vmap",
                     **kw)
    s_s, h_s = train(MLP(**MODEL), Ring(N_RANKS), x, y,
                     backend="shard_map", **kw)
    for a, b in zip(jax.tree.leaves(s_v.params), jax.tree.leaves(s_s.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(s_v.event), jax.tree.leaves(s_s.event)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert h_v[-1]["late_commits"] == h_s[-1]["late_commits"] > 0


def test_straggler_staleness_clamps_at_bound():
    """A slow=R@f straggler with f beyond the bound: the affected
    edges' staleness gauge plateaus at D (the clamp IS the bound), the
    late-commit counter grows, and training stays finite."""
    sched = ChaosSchedule(seed=5, slow=((1, 7),))
    for D in (2, 4):
        state, m = _run(D, chaos=sched, steps=8)
        es = np.asarray(m["edge_staleness"])  # [n_ranks, n_nb]
        assert es.max() == D  # f=7 clamped to the bound
        assert int(np.asarray(m["late_commits"]).sum()) > 0
        assert np.isfinite(np.asarray(m["loss"])).all()
        # only the straggler's two ring neighbors see stale edges
        stale_rows = sorted(np.argwhere(es == D)[:, 0].tolist())
        assert set(stale_rows) == {0, 2}  # ranks adjacent to rank 1


def test_chaos_drop_composes_with_lag_queue():
    """Drops AND lags on the same run: a dropped message never commits
    (its edge's gauge keeps growing past the lag), and the run stays
    deterministic — the same seed replays bitwise."""
    sched = ChaosSchedule(seed=9, drop_p=0.3, slow=((2, 3),))
    s_a, m_a = _run(4, chaos=sched, steps=6)
    s_b, m_b = _run(4, chaos=sched, steps=6)
    for a, b in zip(jax.tree.leaves(s_a), jax.tree.leaves(s_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m_a:
        np.testing.assert_array_equal(
            np.asarray(m_a[k]), np.asarray(m_b[k]), err_msg=k
        )


def test_integrity_rejects_compose_with_lag_queue():
    """Integrity verdicts fold into the queue like drops: a rejected
    payload enqueues not-fired (reject ≡ not delivered — the clock
    does not advance on it), the defenses and the bounded engine run
    in one step, and the composed run replays bitwise."""
    x, y = synthetic_dataset(256, IN_SHAPE, seed=3)
    kw = dict(
        algo="eventgrad", epochs=2, batch_size=8, event_cfg=CFG, seed=0,
        log_every_epoch=False, staleness=2,
        chaos="slow=1@3,bitflip=5-10@1.0,seed=5", integrity="on",
    )
    s_a, h_a = train(MLP(**MODEL), Ring(N_RANKS), x, y, **kw)
    r = h_a[-1]
    assert r["wire_rejects"] > 0 and r["late_commits"] > 0
    assert r["edge_staleness_max"] == 2
    assert np.isfinite(r["loss"])
    s_b, _ = train(MLP(**MODEL), Ring(N_RANKS), x, y, **kw)
    for a, b in zip(jax.tree.leaves(s_a.params), jax.tree.leaves(s_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- guards (satellite: the new validation story) ----------------------


def test_bounded_async_guards():
    topo = Ring(N_RANKS)
    tx = optax.sgd(0.1)
    with pytest.raises(ValueError, match="bounded-async"):
        make_train_step(MLP(**MODEL), tx, topo, "eventgrad", staleness=-1)
    with pytest.raises(ValueError, match="arena=True"):
        make_train_step(MLP(**MODEL), tx, topo, "eventgrad", staleness=2)
    with pytest.raises(ValueError, match="fused"):
        make_train_step(MLP(**MODEL), tx, topo, "eventgrad", staleness=2,
                        arena=True, fused_sgd=(0.05, 0.0))
    # the legacy guards keep their meaning
    with pytest.raises(ValueError, match="event"):
        make_train_step(MLP(**MODEL), tx, topo, "dpsgd", staleness=1)
    # loop-level: membership transitions don't compose (a newcomer
    # would inherit its bootstrap source's in-flight queues)
    x, y = synthetic_dataset(64, IN_SHAPE, seed=3)
    with pytest.raises(ValueError, match="membership"):
        train(MLP(**MODEL), Ring(N_RANKS), x, y, algo="eventgrad",
              epochs=2, batch_size=4, event_cfg=CFG, seed=0,
              log_every_epoch=False, staleness=2,
              membership="leave=1@1")


def test_resume_across_staleness_depth_fails_loudly(tmp_path):
    """The queue depth D is checkpoint layout, like the bucket count:
    resuming across a different D fails LOUDLY in BOTH directions
    (the shrink direction would otherwise restore silently, dropping
    in-flight messages)."""
    x, y = synthetic_dataset(64, IN_SHAPE, seed=3)
    common = dict(
        algo="eventgrad", epochs=1, batch_size=4, event_cfg=CFG, seed=0,
        log_every_epoch=False, save_every=1,
    )
    d1 = str(tmp_path / "stale2")
    train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d1,
          staleness=2, **common)
    # D=2 snapshot -> D=0 (the silent-shrink direction)
    with pytest.raises(RuntimeError, match="staleness"):
        train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d1,
              resume=True, **{**common, "epochs": 2})
    # D=2 snapshot -> D=4 (depth mismatch)
    with pytest.raises(RuntimeError, match="staleness"):
        train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d1,
              resume=True, staleness=4, **{**common, "epochs": 2})
    d2 = str(tmp_path / "mono")
    train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d2, **common)
    # legacy snapshot -> D=2 (the grow direction)
    with pytest.raises(RuntimeError, match="staleness"):
        train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d2,
              resume=True, staleness=2, **{**common, "epochs": 2})
    # same-D resume round-trips
    s2, h2 = train(MLP(**MODEL), Ring(N_RANKS), x, y, checkpoint_dir=d1,
                   resume=True, staleness=2, **{**common, "epochs": 2})
    assert [r["epoch"] for r in h2] == [2]


# --- the ablation tool's fast leg (tier-1 smoke) -----------------------


def test_straggler_ablation_fast_leg_schema_valid(tmp_path):
    """The proof instrument's --fast --measured leg runs end to end —
    composed config (compact int8 x bucketed x carrier-resident),
    modeled AND real-wall-clock legs — and its output validates
    against STRAGGLER_ABLATION_SCHEMA — the same gates the committed
    artifact is held to."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "straggler_ablation",
        os.path.join(root, "tools", "straggler_ablation.py"),
    )
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    va_spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(root, "tools", "validate_artifacts.py"),
    )
    va = importlib.util.module_from_spec(va_spec)
    va_spec.loader.exec_module(va)

    out = str(tmp_path / "straggler_fast.json")
    assert tool.main(["--fast", "--measured", "--out", out]) == 0
    with open(out) as f:
        rec = json.load(f)
    errs = va.validate(rec, va.STRAGGLER_ABLATION_SCHEMA)
    assert errs == [], errs
    assert rec["bounded_async_beats_lockstep"]
    assert any(leg["staleness"] >= 2 and leg["late_commits"] > 0
               for leg in rec["legs"])
    # the measured leg: real seconds, lockstep strictly slower, both
    # instruments agreeing on direction
    assert rec["measured"] is True
    assert rec["measured_ratio"] > 1.0
    assert rec["measured_lockstep_wall_s"] > rec["measured_bounded_wall_s"]
    assert rec["measured_agrees_with_modeled"] is True
    # the gates are IN the schema: breaking any measured field must be
    # a schema violation, not a judgment call
    for k, bad in [
        ("measured", False),
        ("measured_ratio", 0.9),
        ("measured_agrees_with_modeled", False),
        ("measured_bounded_staleness", 1),
    ]:
        assert va.validate(dict(rec, **{k: bad}),
                           va.STRAGGLER_ABLATION_SCHEMA), (
            f"schema must reject {k}={bad!r}"
        )
    # dropping the measured leg entirely must also be rejected — the
    # committed artifact carries BOTH instruments
    stripped = {k: v for k, v in rec.items()
                if not k.startswith("measured")}
    assert va.validate(stripped, va.STRAGGLER_ABLATION_SCHEMA)
