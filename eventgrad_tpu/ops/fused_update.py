"""Pallas TPU kernel: fused gossip-mix + momentum-SGD parameter update.

The tail of every gossip train step is a chain of elementwise passes over
the full parameter set (~17.4M floats for the flagship ResNet):

    mixed = (p + sum(neighbor_bufs)) * w          # mixing.py / event.cpp:469-471
    trace = momentum * trace + grad               # optax sgd trace
    p_new = mixed - lr * trace                    # optimizer.step()

Left to XLA this is usually fused well, but it sits on the HBM-bandwidth
critical path of every step; this kernel guarantees exactly one read of
(p, buf_sum, grad, trace) and one write of (p_new, trace_new) per element,
tiled through VMEM. Used opt-in from `train.steps.make_train_step(
fused_update=True)`; `mix_sgd_reference` is the jnp twin used for
correctness tests and as the non-TPU fallback.

Layout: each parameter leaf is flattened and viewed as (rows, 128) — a
free reshape when the leaf size divides the 128-lane tile, which covers
every conv/fc weight of the flagship ResNet except the 1,728-element
stem conv — and processed on a 1-D grid
of row-blocks whose trailing block may be partial (Mosaic masks the
out-of-bounds stores, so no pad/unpad copies ride the HBM critical path).
Ragged leaves (biases, BN scales: a few KB) fall back to a zero-padded
copy of the same kernel; their traffic is negligible.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

try:  # TPU memory spaces only exist on TPU builds; interpret mode elsewhere
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANES = 128
_SUBLANES = 8
_BLOCK_ROWS = 512  # 512x128 f32 = 256 KiB per ref; 6 refs well under VMEM


def _kernel(p_ref, b_ref, g_ref, t_ref, po_ref, to_ref, *, lr, momentum, w):
    # INVARIANT: strictly elementwise. The partial trailing block relies on
    # Mosaic masking out-of-bounds stores and tolerating garbage in
    # out-of-bounds *reads* — safe only because no element's output depends
    # on any other element. Any future cross-element op (a reduction, a
    # shift) would silently consume the OOB rows; pad instead.
    mixed = (p_ref[:] + b_ref[:]) * w
    trace = momentum * t_ref[:] + g_ref[:]
    po_ref[:] = mixed - lr * trace
    to_ref[:] = trace


@functools.partial(jax.jit, static_argnames=("lr", "momentum", "w", "interpret"))
def _fused_leaf(p, b, g, t, *, lr, momentum, w, interpret):
    orig_shape, orig_dtype = p.shape, p.dtype
    n = p.size
    ragged = n % _LANES != 0
    if ragged:  # small leaves only: pad to one lane-tile multiple (copies)
        padded = -(-n // _LANES) * _LANES
        prep = lambda x: jnp.pad(
            x.reshape(-1).astype(jnp.float32), (0, padded - n)
        ).reshape(-1, _LANES)
    else:  # free reshape: no data movement outside the kernel
        prep = lambda x: x.reshape(-1, _LANES).astype(jnp.float32)

    p2, b2, g2, t2 = prep(p), prep(b), prep(g), prep(t)
    rows = p2.shape[0]
    # trailing block may be partial: Mosaic masks out-of-bounds stores
    grid = (pl.cdiv(rows, _BLOCK_ROWS),)
    spec = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES),
        lambda i: (i, 0),
        **({"memory_space": _VMEM} if (_VMEM is not None and not interpret) else {}),
    )
    # row blocks are independent: marking the grid parallel lets Mosaic
    # split it across both megacore TensorCores — without this the sweep
    # runs on one core while the XLA twin uses both (round-2 grid: 0.79x)
    extra = {}
    if not interpret and pltpu is not None:
        extra["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        )
    po, to = pl.pallas_call(
        functools.partial(_kernel, lr=lr, momentum=momentum, w=w),
        out_shape=(
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
        ),
        grid=grid,
        in_specs=[spec, spec, spec, spec],
        out_specs=(spec, spec),
        interpret=interpret,
        **extra,
    )(p2, b2, g2, t2)

    if ragged:
        unpad = lambda x: x.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)
    else:
        unpad = lambda x: x.reshape(orig_shape).astype(orig_dtype)
    return unpad(po), unpad(to)


#: leaves below this ride the XLA tree-map path instead of a Pallas launch:
#: biases/BN scales are a few KB — launch overhead and ragged pad/unpad
#: copies swamp any single-pass benefit there, while the large conv/fc
#: leaves (99%+ of the traffic) keep the guaranteed-one-pass kernel.
#: This is the per-shape auto-fallback of VERDICT r2 item 4.
_MIN_PALLAS_ELEMS = 1 << 16


def fused_mix_sgd(
    params: Any,
    buf_sum: Any,
    grads: Any,
    trace: Any,
    lr: float,
    momentum: float,
    mix_weight: float,
    interpret: bool = False,
) -> Tuple[Any, Any]:
    """Apply the fused update across a whole pytree.

    `buf_sum` is the elementwise sum of neighbor buffers (zeros for a
    neighborless rank: mix_weight must then be 1.0). Returns
    (new_params, new_trace) with optax-sgd-trace semantics.

    Hybrid dispatch: leaves >= _MIN_PALLAS_ELEMS run the Pallas kernel;
    smaller leaves take the jnp twin (XLA fuses them into one loop with
    no launch or padding cost).
    """
    flat_p, treedef = jax.tree.flatten(params)
    flat_b = treedef.flatten_up_to(buf_sum)
    flat_g = treedef.flatten_up_to(grads)
    flat_t = treedef.flatten_up_to(trace)
    out_p, out_t = [], []
    for p, b, g, t in zip(flat_p, flat_b, flat_g, flat_t):
        if p.size >= _MIN_PALLAS_ELEMS:
            np_, nt_ = _fused_leaf(
                p, b, g, t, lr=float(lr), momentum=float(momentum),
                w=float(mix_weight), interpret=interpret,
            )
        else:  # XLA path: one fused elementwise chain, no launch/pad cost
            nt_ = momentum * t + g
            np_ = ((p + b) * mix_weight - lr * nt_).astype(p.dtype)
            nt_ = nt_.astype(t.dtype)
        out_p.append(np_)
        out_t.append(nt_)
    return treedef.unflatten(out_p), treedef.unflatten(out_t)


def mix_sgd_reference(
    params: Any, buf_sum: Any, grads: Any, trace: Any,
    lr: float, momentum: float, mix_weight: float,
) -> Tuple[Any, Any]:
    """jnp twin of the kernel (also the non-TPU fallback path)."""
    mixed = jax.tree.map(lambda p, b: (p + b) * mix_weight, params, buf_sum)
    new_trace = jax.tree.map(lambda t, g: momentum * t + g, trace, grads)
    new_p = jax.tree.map(lambda m, t: m - lr * t, mixed, new_trace)
    return new_p, new_trace
