"""Operating-point sweep for the adaptive threshold (VERDICT round-1 item 4).

Sweeps the horizon (and optionally warmup) at reduced CPU op-points of the
two headline configs and prints one JSON line per point:
msgs-saved-%, final loss, consensus test accuracy, and the D-PSGD accuracy
at the same op-point for the gap. Targets: >=60% CIFAR, >=70% MNIST
(/root/reference/README.md:4) with a small accuracy gap.

Usage: python tools/tune_horizon.py [cifar|mnist|both] [h1 h2 ...]
       [--warmup N]   (default 30, the reference's initial_comm_passes)
"""

from __future__ import annotations

import json
import sys
import time

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # runnable uninstalled

import jax

from eventgrad_tpu.utils import compile_cache

compile_cache.honor_cpu_pin()  # JAX_PLATFORMS=cpu must beat the axon plugin
# persistent XLA cache: repeated invocations must not re-pay the jit
# compile per process (no-op on the CPU backend)
compile_cache.enable()


def run_point(dataset: str, horizon: float, warmup: int = 30,
              epochs: int | None = None, dpsgd_leg: bool = True,
              trail_every: int = 0, topo=None,
              algo: str = "eventgrad", topk_percent: float | None = None):
    """One sweep point. `epochs=None` uses the default reduced op-point;
    `dpsgd_leg=False` skips the accuracy-comparison leg; `trail_every=N`
    adds every Nth epoch's msgs-saved-% as a `trail` list; `topo` swaps
    the 8-rank ring for another topology (tools/torus_savings.py);
    `algo`/`topk_percent` select the sparsified variant
    (tools/sparse_bytes.py). The single definition of the headline
    reduced op-points — savings_curve.py, torus_savings.py, and
    sparse_bytes.py all call this, so every artifact family measures
    one config."""
    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import CNN2, ResNet
    from eventgrad_tpu.models.resnet import BasicBlock
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.sparsify import SparseConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train
    from eventgrad_tpu.utils import trees

    topo = topo or Ring(8)
    cfg = EventConfig(adaptive=True, horizon=horizon, warmup_passes=warmup)
    if dataset == "cifar":
        x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
        xt, yt = load_or_synthesize("cifar10", None, "test", n_synth=256)
        model = ResNet(stage_sizes=(1, 1, 1, 1), block_cls=BasicBlock, num_filters=8)
        kw = dict(epochs=epochs or 16, batch_size=8, learning_rate=1e-2,
                  momentum=0.9, random_sampler=True, log_every_epoch=False)
    else:
        x, y = load_or_synthesize("mnist", None, "train", n_synth=2048)
        xt, yt = load_or_synthesize("mnist", None, "test", n_synth=256)
        model = CNN2()
        kw = dict(epochs=epochs or 60, batch_size=64, learning_rate=0.05,
                  random_sampler=False, log_every_epoch=False)

    t0 = time.perf_counter()
    state, hist = train(
        model, topo, x, y, algo=algo, event_cfg=cfg,
        sparse_cfg=SparseConfig(topk_percent) if topk_percent else None,
        **kw,
    )
    cons = consensus_params(state.params)
    stats0 = rank0_slice(state.batch_stats)
    acc = evaluate(model, cons, stats0, xt, yt)["accuracy"]
    n_params = trees.tree_count_params(state.params) // topo.n_ranks

    rec = {
        "dataset": dataset,
        "algo": algo,
        "topk_percent": topk_percent,
        "horizon": horizon,
        "warmup": warmup,
        "passes": sum(h["steps"] for h in hist),
        "msgs_saved_pct": round(hist[-1]["msgs_saved_pct"], 2),
        "sent_bytes_per_step_per_chip": round(
            hist[-1]["sent_bytes_per_step_per_chip"], 1
        ),
        "dense_bytes_per_step_per_chip": float(topo.n_neighbors * 4 * n_params),
        "test_acc": round(acc, 2),
        "loss": round(hist[-1]["loss"], 4),
    }
    if trail_every:
        rec["trail"] = [
            round(h["msgs_saved_pct"], 1) for h in hist[::trail_every]
        ]
    if dpsgd_leg:
        sd, hd = train(model, topo, x, y, algo="dpsgd", **kw)
        cons_d = consensus_params(sd.params)
        stats_d = rank0_slice(sd.batch_stats)
        acc_d = evaluate(model, cons_d, stats_d, xt, yt)["accuracy"]
        rec["test_acc_dpsgd"] = round(acc_d, 2)
        rec["acc_gap"] = round(acc - acc_d, 2)
    rec["wall_s"] = round(time.perf_counter() - t0, 1)
    print(json.dumps(rec), flush=True)
    return rec


if __name__ == "__main__":
    args = sys.argv[1:]
    warmup = 30
    if "--warmup" in args:
        i = args.index("--warmup")
        if i + 1 >= len(args):
            raise SystemExit("--warmup needs a value")
        warmup = int(args[i + 1])
        del args[i : i + 2]
    which = args[0] if args else "both"
    if which not in ("cifar", "mnist", "both"):
        raise SystemExit(f"unknown dataset {which!r}: cifar | mnist | both")
    horizons = [float(h) for h in args[1:]] or [0.95, 0.99, 1.0, 1.05]
    datasets = ["cifar", "mnist"] if which == "both" else [which]
    for ds in datasets:
        for h in horizons:
            run_point(ds, h, warmup)
