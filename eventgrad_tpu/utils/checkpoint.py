"""Checkpoint/resume — absent from the reference (no torch::save anywhere;
the consensus model is evaluated then dropped, event.cpp:517-586). Cheap win
on TPU: orbax snapshots of the full stacked TrainState (params, optimizer
moments, event thresholds/slopes/buffers, sparsifier replicas, PRNG keys),
so an interrupted decentralized run resumes with its exact gossip state.
"""

from __future__ import annotations

import atexit
import os
import shutil
import threading
import warnings
from typing import Any, Callable, ContextManager, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

# seeded kill sites bracketing every mutation below (chaos/crashpoint.py:
# no-ops unless EG_CRASHPOINT arms one) — tools/crash_matrix.py kills at
# each and proves the resume
from eventgrad_tpu.chaos import crashpoint


def _fsync_path(path: str) -> None:
    """fsync one file or directory (best-effort on filesystems that
    reject directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - e.g. directory fsync unsupported
        pass
    finally:
        os.close(fd)


def _fsync_tree(root: str) -> None:
    """fsync every file AND directory under `root`, bottom-up, then
    `root`'s parent. A rename-commit is only durable once the renamed
    tree's data and the directory entries referencing it have hit disk:
    without the directory fsyncs a host crash can leave the promoted
    name pointing at zero-length files — fatal for a rollback engine
    whose whole contract is 'the last-known-good snapshot survives'."""
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for f in filenames:
            _fsync_path(os.path.join(dirpath, f))
        _fsync_path(dirpath)


def save(path: str, state: Any) -> None:
    """Crash-safe snapshot: write to `<path>.tmp`, fsync the written tree
    (files and directories — rename-commit durability needs both), swap
    the old snapshot to `<path>.prev`, promote tmp, drop prev, fsync the
    parent directory so the renames themselves persist. A kill at any
    point leaves either `<path>` or `<path>.prev` complete — `latest()`
    finds whichever survived — and a HOST CRASH after return cannot lose
    the promoted snapshot (the rollback engine depends on this).

    Multi-process: EVERY process must call this (orbax coordinates the write
    internally and only the primary touches disk); `path` must be on a
    filesystem all processes can read for a later resume. Leaves must be
    host-replicated (numpy) — `multihost.to_host` the state first."""
    from eventgrad_tpu.parallel import multihost

    path = os.path.abspath(path)
    tmp, prev = path + ".tmp", path + ".prev"
    # force=True clears a stale tmp itself, primary-only with internal syncs
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp, state, force=True)
    crashpoint.hit("ckpt.tmp_written")
    if multihost.is_primary():
        # durability point: the tmp tree's bytes are on disk BEFORE any
        # rename makes them the snapshot of record
        _fsync_tree(tmp)
        if os.path.exists(path):
            # make room for the demotion; the current snapshot covers the gap
            if os.path.exists(prev):
                shutil.rmtree(prev)
            os.rename(path, prev)
            crashpoint.hit("ckpt.mid_swap")
        # the promoted snapshot may be absent (first save, or resumed from
        # .prev); never touch a surviving .prev until the new one is in place
        os.rename(tmp, path)
        crashpoint.hit("ckpt.post_promote")
        if os.path.exists(prev):
            shutil.rmtree(prev)
        # persist the rename-commit itself
        _fsync_path(os.path.dirname(path))
    multihost.barrier("eg-ckpt-promote")


def host_snapshot(tree: Any) -> Any:
    """Blocking device->host COPY of a pytree — the eager half of an async
    save. Every leaf becomes an owned numpy array (np.array copies even
    host-resident leaves), so the caller may keep mutating the originals
    (trace carries, counters) while `AsyncWriter` serializes the frozen
    snapshot on its thread."""
    return jax.tree.map(lambda x: np.array(x), tree)


class AsyncWriter:
    """One background writer thread for checkpoint serialization.

    The dispatch pipeline (train/loop.py, docs/ARCHITECTURE.md "The
    dispatch pipeline") snapshots device state to host eagerly
    (`host_snapshot`) and hands the frozen copy here; `save()` runs
    `checkpoint.save`'s write-tmp/atomic-swap on the thread, so the
    orbax serialization overlaps the next dispatch block's compute.
    Crash safety is unchanged: the swap in `save` is the same atomic
    promote, so a kill mid-serialization still leaves `<path>` or
    `<path>.prev` complete for `latest()`.

    Join barriers: `save()` joins any in-flight write first (two writers
    must never race the tmp/prev swap), `wait()`/`close()` join on
    exit, and an `atexit` hook joins on INTERPRETER exit — a
    KeyboardInterrupt or SIGTERM-turned-exception that unwinds past
    every `finally` still cannot abandon a half-serialized tmp tree to
    the daemon-thread kill (the atomic swap keeps even that case safe
    on disk; the hook keeps it from being the normal path). A failed
    background save re-raises at the next barrier — never silently."""

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self._atexit_hook: Optional[Callable[[], None]] = None

    def save(
        self,
        path: str,
        payload: Any,
        span: Optional[Callable[[], ContextManager]] = None,
    ) -> None:
        """Serialize `payload` (host numpy — see `host_snapshot`) to
        `path` on the writer thread; joins the previous save first.
        `span` (zero-arg context-manager factory) wraps the write for
        observability (obs.Registry spans are thread-safe)."""
        self.wait()
        if self._atexit_hook is None:
            # interrupt barrier: interpreter teardown joins the in-flight
            # write (logged, not raised — close() unregisters on the
            # orderly paths, so this only fires on an unwind that skipped
            # them)
            self._atexit_hook = lambda: self.close(raise_errors=False)
            atexit.register(self._atexit_hook)

        def work() -> None:
            try:
                import contextlib

                crashpoint.hit("writer.bg_save")
                with (span() if span is not None else contextlib.nullcontext()):
                    save(path, payload)
            except BaseException as e:  # re-raised at the next barrier
                self._exc = e

        self._thread = threading.Thread(
            target=work, daemon=True, name="eg-ckpt-writer"
        )
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight save (if any) and re-raise its error."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise RuntimeError("async checkpoint save failed") from exc

    def close(self, raise_errors: bool = True) -> None:
        """Exit barrier. `raise_errors=False` is for exception-unwind
        paths: join without masking the primary exception — but a
        discarded save failure is still LOGGED (the snapshot on disk is
        the stale previous one; a resume would replay extra epochs)."""
        if self._atexit_hook is not None:
            atexit.unregister(self._atexit_hook)
            self._atexit_hook = None
        if raise_errors:
            self.wait()
            return
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._exc is not None:
            import logging

            logging.getLogger(__name__).warning(
                "async checkpoint save failed during unwind (snapshot on "
                "disk is the previous one): %r", self._exc,
            )
        self._exc = None


class RollingRetention:
    """Validated rolling retention of last-known-good snapshots.

    The integrity engine's rollback source (chaos/integrity.py): after
    every dispatch block the divergence sentinel judged healthy, the
    loop retains that state as `<directory>/good-<epoch>` — each written
    through `save`'s fsynced atomic swap, so every retained snapshot is
    individually crash-safe AND durable. Retention keeps the newest
    `keep` VALIDATED snapshots; pruning runs BEFORE a new save is
    dispatched (never after), so the invariant "at least one complete
    validated snapshot exists on disk" holds at every instant — even if
    the in-flight save dies mid-write, the newest retained snapshot
    survives untouched. With `keep=1` that means the only validated
    snapshot is never deleted until its successor has fully committed.

    Writes go through the optional `writer` (an `AsyncWriter` — the
    dispatch pipeline's background serialization) or synchronously via
    `save` when none is given.
    """

    PREFIX = "good-"

    def __init__(
        self, directory: str, keep: int = 2,
        writer: "Optional[AsyncWriter]" = None,
    ):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self.writer = writer

    def path_for(self, epoch: int) -> str:
        return os.path.join(self.directory, f"{self.PREFIX}{epoch:06d}")

    def snapshots(self):
        """Committed (promoted-name) snapshots as sorted (epoch, path)
        tuples — in-flight `.tmp` and demoted `.prev` trees excluded."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            if not name.startswith(self.PREFIX):
                continue
            if name.endswith(".tmp") or name.endswith(".prev"):
                continue
            try:
                epoch = int(name[len(self.PREFIX):])
            except ValueError:
                continue
            out.append((epoch, os.path.join(self.directory, name)))
        return sorted(out)

    def latest_good(self):
        """Newest retained (epoch, path), or None."""
        snaps = self.snapshots()
        return snaps[-1] if snaps else None

    def prune(self) -> int:
        """Delete the oldest snapshots beyond `keep`; returns how many.
        Never touches the newest `keep` — in particular never the only
        one."""
        snaps = self.snapshots()
        drop = snaps[: max(0, len(snaps) - self.keep)]
        for _, p in drop:
            shutil.rmtree(p, ignore_errors=True)
            for suffix in (".tmp", ".prev"):  # any stale swap leftovers
                if os.path.exists(p + suffix):
                    shutil.rmtree(p + suffix, ignore_errors=True)
        return len(drop)

    def save_good(self, epoch: int, payload: Any) -> str:
        """Retain one validated snapshot. Prunes FIRST (committed
        snapshots only, keeping `keep`), then writes `payload` to
        `path_for(epoch)` — async when a writer was given. The sync
        path prunes once more after the commit (safe: the new snapshot
        is already promoted); under a writer the extra snapshot rides
        until the next call — pruning concurrently with the in-flight
        promote could delete the only committed snapshot."""
        os.makedirs(self.directory, exist_ok=True)
        self.prune()
        path = self.path_for(epoch)
        if self.writer is not None:
            self.writer.save(path, payload)
        else:
            save(path, payload)
            self.prune()
        return path


def latest(path: str) -> Optional[str]:
    """The newest complete snapshot for `path` (the primary, or the .prev
    left by a save interrupted mid-swap); None if neither exists."""
    path = os.path.abspath(path)
    for cand in (path, path + ".prev"):
        if os.path.exists(cand):
            return cand
    return None


def peek(path: str) -> Any:
    """Template-free raw restore -> host numpy pytree. Restores the WHOLE
    snapshot (orbax has no partial read here), so use it only where the
    shape of the snapshot is itself unknown — e.g. a membership-elastic
    resume must read the saved epoch before it can size the state
    template (the rank count at that epoch follows from the membership
    schedule; train/loop.py); the generic resume path (train/loop.py)
    also routes through it so the fallback below covers every load.

    A truncated or corrupted PRIMARY with a complete demoted `.prev`
    twin (a kill between the swap's renames, torn metadata on a
    non-fsynced filesystem) auto-recovers from the twin — LOUDLY, via a
    RuntimeWarning naming both paths: the service keeps running at the
    cost of one save interval instead of paging a human to type the
    `.prev` path by hand. Anything less recoverable (no twin, or both
    sides corrupt) fails loudly with the offending path(s) and the
    remaining options — never half-restores: a resume that silently
    proceeded from garbage would train on it."""
    path = os.path.abspath(path)

    def _read(p: str) -> Any:
        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(p)

    try:
        return _read(path)
    except Exception as exc:
        prev = path + ".prev"
        if path.endswith(".prev") or not os.path.exists(prev):
            raise RuntimeError(
                f"checkpoint at {path} is unreadable (truncated or "
                f"corrupted): {type(exc).__name__}: {exc}. No .prev twin "
                "exists; restore from a retained last-known-good "
                "snapshot (RollingRetention) or an earlier backup"
            ) from exc
        try:
            out = _read(prev)
        except Exception as prev_exc:
            raise RuntimeError(
                f"checkpoint at {path} AND its demoted twin {prev} are "
                f"both unreadable (primary: {type(exc).__name__}: {exc}; "
                f"twin: {type(prev_exc).__name__}: {prev_exc}); restore "
                "from a retained last-known-good snapshot "
                "(RollingRetention) or an earlier backup"
            ) from exc
        # sideline the corrupt primary BEFORE anyone saves again: the
        # swap demotes an existing primary over .prev, so leaving the
        # corrupt tree in place would destroy the only good snapshot
        # the moment the recovered run checkpoints (and a kill inside
        # that swap would strand the run unresumable). Rename, never
        # delete — forensics keep the bytes; latest() ignores .corrupt.
        corrupt = path + ".corrupt"
        try:
            if os.path.exists(corrupt):
                shutil.rmtree(corrupt, ignore_errors=True)
            os.rename(path, corrupt)
        except OSError:  # multi-process peek race: another rank won
            corrupt = "(already sidelined)"
        warnings.warn(
            f"checkpoint at {path} is unreadable (truncated or "
            f"corrupted): {type(exc).__name__}: {exc} — RECOVERED from "
            f"its demoted twin {prev}; up to one save interval of work "
            f"replays, and the corrupt primary was sidelined to "
            f"{corrupt} so the next save cannot demote it over the "
            "good twin",
            RuntimeWarning,
        )
        return out


def restore(path: str, template: Any, raw: Any = None) -> Any:
    """Restore into the structure of `template` (an abstract or concrete
    TrainState with the same shapes/dtypes). `raw` (a `peek` of the same
    snapshot) grafts from the already-deserialized pytree instead of
    re-reading disk — exact-structure like the orbax item restore: a
    template leaf the snapshot lacks raises."""
    if raw is not None:
        restored, missing = _graft(raw, template)
        if missing:
            raise ValueError(f"snapshot lacks leaves {missing}")
        return restored
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        target = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return ckptr.restore(path, item=target)


def _path_name(keypath) -> str:
    """'/'-joined leaf path that is stable across container kinds: flax
    struct fields (GetAttrKey), dicts (DictKey), and tuples vs the lists
    orbax restores them as (SequenceKey) all reduce to their name/index."""
    return "/".join(
        str(getattr(k, "name", getattr(k, "key", getattr(k, "idx", k))))
        for k in keypath
    )


def restore_with_fill(path: str, template: Any, raw: Any = None):
    """Forward-compatible restore: snapshot leaves graft onto `template`
    BY PATH, and any leaf the snapshot lacks keeps its template (init)
    value — so a state field added after the snapshot was taken (e.g. a
    new counter) resumes from its initial value instead of failing the
    exact-structure match `restore` enforces. Returns (restored,
    missing_path_names); the caller decides how loud to be about the
    fills. A snapshot leaf with no template counterpart is ignored.
    `raw` (a `peek` of the same snapshot) skips the disk read."""
    if raw is None:
        path = os.path.abspath(path)
        with ocp.PyTreeCheckpointer() as ckptr:
            raw = ckptr.restore(path)
    return _graft(raw, template)


def _graft(raw: Any, template: Any):
    """Path-keyed graft of a template-free restore onto `template`:
    (leaves filled in template order, missing template path names)."""
    raw_map = {
        _path_name(kp): v
        for kp, v in jax.tree_util.tree_flatten_with_path(raw)[0]
    }
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    filled, missing = [], []
    for kp, tmpl_leaf in flat:
        name = _path_name(kp)
        if name in raw_map:
            # host numpy, like the exact-structure restore returns (the
            # trace carry is MUTATED by the trace writer; device arrays
            # would break it)
            raw_leaf = np.asarray(raw_map[name])
            tmpl_np = np.asarray(tmpl_leaf)
            if raw_leaf.shape != tmpl_np.shape:
                # a path that still exists but changed shape (different
                # rank count, history depth, ...) is NOT an added-field
                # migration — grafting it would corrupt state silently
                raise ValueError(
                    f"snapshot leaf {name} has shape {raw_leaf.shape}, "
                    f"template wants {tmpl_np.shape}"
                )
            filled.append(raw_leaf.astype(tmpl_np.dtype))
        else:
            missing.append(name)
            filled.append(tmpl_leaf)
    return jax.tree_util.tree_unflatten(treedef, filled), missing
